"""Bucketed, hierarchy-aware gradient reduction — the TPU-native
re-expression of PyTorch DDP's C++ `Reducer` (Li et al., VLDB 2020;
reference `Readme.md:145-157`).

The reference documents the Reducer's machinery: gradients are packed
into ~25 MB flat-buffer buckets in REVERSE registration order (late
layers' grads are produced first by backprop, so their bucket fills and
launches first), each full bucket fires a ring all-reduce from an
autograd hook, and the rings overlap the still-running backward. Our
`DDPEngine` instead lowers gradient reduction as one `lax.pmean` of the
whole pytree — a single fused collective GSPMD-style (Xu et al., 2021)
that cannot start until the LAST gradient exists and gives the
scheduler one monolithic op to (maybe) overlap.

This module rebuilds the Reducer's structure as explicit JAX
collectives under `shard_map`:

* `plan_buckets(leaves, bucket_mb)` — the bucket assignment: flatten
  the gradient pytree, walk the leaves in reverse registration order,
  group by dtype (mixed bf16/f32 pytrees never share a flat buffer),
  and cut a new bucket when the running byte count would pass
  `bucket_mb` (default 25, the Reducer's `bucket_cap_mb`). Pure
  shape-level planning — usable on avals, tested directly.

* `ring_reduce_scatter` / `ring_all_gather` — the per-bucket
  collectives, decomposed into chunked `lax.ppermute` rings exactly
  like `ops/collective_matmul.py` (same `_split`/`_perms`/`_ring_fold`
  machinery, Wang et al., ASPLOS 2023): S-1 collective-permutes each,
  bidirectional when S is even, so each bucket's reduction is a chain
  of small hops the scheduler interleaves with the remaining backward
  instead of one blocking fused op.

* `bucketed_psum` / `bucketed_pmean` — the hierarchy. On a hybrid
  ('dcn', 'ici') mesh (`runtime/mesh.py`, `MeshSpec(dcn=K)`) each
  bucket is reduced fabric-by-fabric:

      ring reduce-scatter over 'ici'   (fast intra-slice ring; each
                                        device ends with a 1/S shard)
      all-reduce over 'dcn'            (ONE cross-slice op, on 1/S of
                                        the bytes — the slow fabric
                                        never sees the full bucket)
      ring all-gather over 'ici'       (fan the reduced shard back out)

  On a plain ('data',) mesh the same path runs with `dcn_axis=None` —
  bucketed rings over the single fabric. Uneven bucket tails are
  zero-padded to the ring size and dropped on unpack; integer leaves
  are rejected (gradients are floating point).

Consumed by `DDPEngine(grad_reduction="bucketed")`, the explicit
bucketed-FSDP step (`parallel/fsdp.py`) and
`CausalLMSequenceParallelEngine(grad_reduction="bucketed")`; pinned
structurally in tests/test_collectives_hlo.py (per-bucket S-1 permute
chains, no monolithic grad-sized all-reduce) and numerically in
tests/test_grad_reduction.py (parity with `lax.pmean` at rtol 1e-5).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from distributed_model_parallel_tpu.ops.collective_matmul import (
    _axis_size,
    _perms,
    _ring_fold,
    _split,
)
from distributed_model_parallel_tpu.ops.wire_codec import (
    coded_ppermute,
    require_dcn_axis,
)

# The "one flat bucket per dtype" cap: `bucketed_psum` with this
# bucket_mb lowers the whole pytree through a single bucket — the shape
# the engines use for grad_reduction="monolithic" + dcn_compression
# (the monolithic lowering has no explicit dcn site to compress, so it
# borrows the bucket machinery without the bucket SPLITTING).
MONOLITHIC_BUCKET_MB = math.inf


def bucket_pad_multiple(
    ici_size: int, dcn_size: int, dcn_compression: str = "none"
) -> int:
    """Element multiple a bucket's flat buffer is zero-padded to. The
    uncompressed path needs divisibility by the 'ici' ring alone (the
    cross-slice psum takes the shard whole); the compressed path
    re-chunks the 1/ici shard across the K 'dcn' peers, so the buffer
    must also divide by K. Shared with `analysis/lint.py`'s expectation
    builder so the pin and the runtime can never desynchronize."""
    if dcn_compression != "none" and dcn_size > 1:
        return ici_size * dcn_size
    return ici_size


@dataclasses.dataclass(frozen=True)
class BucketSlot:
    """One gradient leaf's slice of a flat bucket buffer."""

    index: int  # position in the flattened-pytree leaf list
    offset: int  # start element inside the bucket's flat buffer
    size: int  # element count
    shape: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class Bucket:
    """A dtype-homogeneous flat-buffer bucket (the Reducer's unit of
    reduction). `size` is the unpadded element count; the runtime pads
    the flat buffer up to the ring size before reduce-scattering."""

    dtype: Any
    slots: Tuple[BucketSlot, ...]
    size: int


def plan_buckets(leaves: Sequence[Any], bucket_mb: float = 25.0):
    """Assign flattened gradient leaves to flat-buffer buckets.

    Reverse registration order (the Reducer's trick: backprop produces
    late-layer gradients first, so the buckets holding them are cut
    first and their reductions can launch while early layers are still
    differentiating) and dtype-grouped (a bf16 leaf never shares a flat
    buffer with an f32 one). A leaf larger than `bucket_mb` gets a
    bucket of its own. Works on anything with .shape/.dtype — arrays or
    avals — so tests and HLO pins can plan without materializing
    gradients."""
    if bucket_mb <= 0:
        raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
    cap_bytes = bucket_mb * (1 << 20)
    buckets: list[Bucket] = []
    open_slots: dict[Any, list[BucketSlot]] = {}
    open_elems: dict[Any, int] = {}

    def close(dt):
        slots = open_slots.pop(dt, [])
        if slots:
            buckets.append(Bucket(dt, tuple(slots), open_elems.pop(dt)))

    for index in reversed(range(len(leaves))):
        leaf = leaves[index]
        dt = jnp.dtype(leaf.dtype)
        if not jnp.issubdtype(dt, jnp.floating):
            raise TypeError(
                f"plan_buckets: leaf {index} has non-floating dtype "
                f"{dt}; gradient pytrees are floating point"
            )
        size = int(math.prod(leaf.shape)) if leaf.shape else 1
        have = open_elems.get(dt, 0)
        if have and (have + size) * dt.itemsize > cap_bytes:
            close(dt)
            have = 0
        open_slots.setdefault(dt, []).append(
            BucketSlot(index, have, size, tuple(leaf.shape))
        )
        open_elems[dt] = have + size
    for dt in list(open_slots):
        close(dt)
    return buckets


# ------------------------------------------------- ring collectives
# The flat-vector twins of collective_matmul's chunked kernels: the
# same bidirectional-ring hop schedule with an identity "dot", so a
# bucket reduction is S-1 collective-permutes in each direction of the
# hierarchy instead of one monolithic fused op.


def ring_reduce_scatter(x, axis_name):
    """Reduce-scatter a flat (n,) vector over `axis_name` as chunked
    ppermutes: partial-sum accumulators ring toward their destination
    shard (S-1 hops total, bidirectional when S is even). Returns this
    shard's (n/S,) summed chunk. n must divide by the axis size."""
    size = _axis_size(axis_name)
    if size == 1:
        return x
    n = x.shape[0]
    if n % size:
        raise ValueError(
            f"ring_reduce_scatter: length {n} not divisible by axis "
            f"{axis_name!r} size {size}"
        )
    nl = n // size
    i = lax.axis_index(axis_name)

    def chunk(c):
        return lax.dynamic_slice_in_dim(x, (c % size) * nl, nl, axis=0)

    n_up, n_dn = _split(size)
    up, dn = _perms(size)
    out = chunk(i)
    if n_up:
        acc = chunk(i + n_up)
        for r in range(n_up - 1, 0, -1):
            acc = lax.ppermute(acc, axis_name, up) + chunk(i + r)
        out = out + lax.ppermute(acc, axis_name, up)
    if n_dn:
        acc = chunk(i - n_dn)
        for r in range(n_dn - 1, 0, -1):
            acc = lax.ppermute(acc, axis_name, dn) + chunk(i - r)
        out = out + lax.ppermute(acc, axis_name, dn)
    return out


def ring_all_gather(x, axis_name):
    """All-gather a flat (m,) shard over `axis_name` as chunked
    ppermutes (S-1 hops, bidirectional when S is even). Returns the
    (S*m,) concatenation in ring order — the inverse of
    `ring_reduce_scatter`'s chunk layout."""
    size = _axis_size(axis_name)
    if size == 1:
        return x
    i = lax.axis_index(axis_name)
    nl = x.shape[0]
    out = jnp.zeros((size * nl,), x.dtype)

    def fold(buf, chunk, off):
        return lax.dynamic_update_slice_in_dim(
            buf, chunk, ((i + off) % size) * nl, axis=0
        )

    return _ring_fold(x, axis_name, out, fold)


# ------------------------------------------------- bucketed reduction


def compressed_dcn_psum(shard, dcn_axis, wire: str):
    """All-reduce a 1/ici bucket shard across the K 'dcn' slices with
    the PAYLOAD compressed (`ops/wire_codec.py`) — and the accumulate
    NOT: int8 never sums in int8. The monolithic `lax.psum` is replaced
    by its reduce-scatter/all-gather decomposition in the wire dtype:

        exchange  K-1 `coded_ppermute` hops deliver every peer's
                  encoded copy of THIS slice's 1/K sub-chunk; each is
                  decoded on arrival and summed in the bucket dtype
        gather    the reduced sub-chunk re-encodes once and rides K-1
                  more hops back out to every peer

    Total 'dcn' bytes: 2(K-1)/K of the shard at the wire itemsize
    (plus one f32 scale sidecar per int8 hop) — the same 2(K-1)/K
    volume the fused psum moves, at 1/2 resp. 1/4 the bytes. Error per
    element: one codec rounding per received chunk plus one on the
    gather re-encode, <= (K+1)·absmax/254 for int8 (INTERNALS §12).
    The shard length must divide by K (`bucket_pad_multiple`)."""
    k = _axis_size(dcn_axis)
    if k == 1:
        return shard
    n = shard.shape[0]
    if n % k:
        raise ValueError(
            f"compressed_dcn_psum: shard length {n} not divisible by "
            f"axis {dcn_axis!r} size {k} (pad the bucket to "
            "bucket_pad_multiple elements)"
        )
    nl = n // k
    i = lax.axis_index(dcn_axis)

    def chunk(c):
        return lax.dynamic_slice_in_dim(shard, (c % k) * nl, nl, axis=0)

    # Exchange: hop r moves every device's encoded chunk for the peer
    # r steps around; decode + accumulate in the bucket dtype.
    acc = chunk(i)
    for r in range(1, k):
        perm = tuple((j, (j + r) % k) for j in range(k))
        acc = acc + coded_ppermute(chunk(i + r), dcn_axis, perm, wire)
    # Gather: the reduced sub-chunk back out, one fresh encode per hop
    # (re-forwarding a decoded copy would re-quantize hop by hop and
    # compound the error with the ring distance).
    out = jnp.zeros_like(shard)
    out = lax.dynamic_update_slice_in_dim(out, acc, i * nl, axis=0)
    for r in range(1, k):
        perm = tuple((j, (j + r) % k) for j in range(k))
        recv = coded_ppermute(acc, dcn_axis, perm, wire)
        out = lax.dynamic_update_slice_in_dim(
            out, recv, ((i - r) % k) * nl, axis=0
        )
    return out


def reduce_bucket_flat(flat, ici_axis, dcn_axis=None,
                       dcn_compression: str = "none"):
    """Hierarchically all-reduce one flat bucket buffer (already padded
    to `bucket_pad_multiple` elements): ring reduce-scatter over the
    intra-slice fabric, one cross-slice all-reduce on the 1/S shard —
    compressed to the wire dtype when `dcn_compression` says so
    (`compressed_dcn_psum`) — ring all-gather back out. With
    `dcn_axis=None` the same rings run over the single fabric."""
    shard = ring_reduce_scatter(flat, ici_axis)
    if dcn_axis is not None:
        if dcn_compression != "none":
            shard = compressed_dcn_psum(shard, dcn_axis, dcn_compression)
        else:
            shard = lax.psum(shard, dcn_axis)
    return ring_all_gather(shard, ici_axis)


def bucketed_psum(
    grads,
    ici_axis: str,
    dcn_axis: Optional[str] = None,
    *,
    bucket_mb: float = 25.0,
    mean: bool = False,
    dcn_compression: str = "none",
):
    """Sum (or mean) a gradient pytree over the data fabric(s) through
    dtype-grouped flat-buffer buckets, each reduced hierarchically
    (`reduce_bucket_flat`). Must run inside `shard_map` with `ici_axis`
    (and `dcn_axis`, when given) bound. Numerically equal to
    `lax.psum(grads, axes)` up to reduction order — exactly with
    `dcn_compression="none"`, within the documented codec budget (bf16
    one-rounding-per-hop / int8 per-bucket absmax bound, module
    docstring of `ops/wire_codec.py`) when the cross-slice hop is
    compressed. Compression touches ONLY the 'dcn' wire: the intra-
    slice rings and the accumulate stay in the bucket dtype."""
    require_dcn_axis(dcn_compression, dcn_axis)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    denom = _axis_size(ici_axis) * (
        _axis_size(dcn_axis) if dcn_axis is not None else 1
    )
    ici_size = _axis_size(ici_axis)
    pad_mult = bucket_pad_multiple(
        ici_size,
        _axis_size(dcn_axis) if dcn_axis is not None else 1,
        dcn_compression,
    )
    out: list = [None] * len(leaves)
    for bucket in plan_buckets(leaves, bucket_mb):
        flat = jnp.concatenate(
            [leaves[s.index].reshape(-1) for s in bucket.slots]
        )
        pad = -flat.shape[0] % pad_mult
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), flat.dtype)]
            )
        reduced = reduce_bucket_flat(
            flat, ici_axis, dcn_axis, dcn_compression
        )
        if mean:
            reduced = reduced * (1.0 / denom)
        for s in bucket.slots:
            piece = lax.dynamic_slice_in_dim(
                reduced, s.offset, s.size, axis=0
            )
            out[s.index] = piece.reshape(s.shape)
    return jax.tree_util.tree_unflatten(treedef, out)


def bucketed_pmean(
    grads,
    ici_axis: str,
    dcn_axis: Optional[str] = None,
    *,
    bucket_mb: float = 25.0,
    dcn_compression: str = "none",
):
    """`lax.pmean` of a gradient pytree, bucketed and hierarchy-aware —
    the drop-in for `DDPEngine`'s monolithic grad pmean."""
    return bucketed_psum(
        grads, ici_axis, dcn_axis, bucket_mb=bucket_mb, mean=True,
        dcn_compression=dcn_compression,
    )


def data_replica_index(axes: Sequence[str]):
    """This shard's linear index over the (possibly factored) data
    axes, major-to-minor in `axes` order — the hybrid-mesh spelling of
    `lax.axis_index('data')` (per-replica RNG folding)."""
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * _axis_size(a) + lax.axis_index(a)
    return idx


__all__ = [
    "Bucket",
    "BucketSlot",
    "MONOLITHIC_BUCKET_MB",
    "bucket_pad_multiple",
    "bucketed_pmean",
    "bucketed_psum",
    "compressed_dcn_psum",
    "data_replica_index",
    "plan_buckets",
    "reduce_bucket_flat",
    "ring_all_gather",
    "ring_reduce_scatter",
]
