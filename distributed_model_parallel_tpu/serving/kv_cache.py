"""Block-paged, preallocated KV cache for autoregressive serving.

Two granularities share this module:

* **Contiguous slots** (`KVCacheSpec`, the PR 7 layout): one dense
  pytree `{k, v: (layers, slots, max_len, heads, head_dim), lengths}`
  — every admitted sequence owns a `max_len` stripe whether it uses 3
  positions or 300. Kept as the parity/bench twin the paged layout is
  measured against.

* **Paged pool** (`PagedKVCacheSpec`, PagedAttention — Kwon SOSP'23,
  PAPERS.md): one device-resident page pool
  `{k, v: (layers, num_pages, page_size, heads, head_dim)}` plus a
  HOST-side block table per slot mapping slot-local page index ->
  pool page id. Allocation is page-granular (`PagePool`): an admitted
  sequence takes ceil(tokens / page_size) pages and a recycled slot
  returns PAGES, not a `max_len` stripe — allocated HBM scales with
  live tokens, which is the whole PagedAttention claim
  (`kv_cache_bytes` / `pages_in_use` are the accounting seam the
  structural tests and bench.py assert against). Pages are refcounted
  so the prefix cache (`PrefixCache`) can share immutable prompt pages
  between slots; a write into a shared page copies it first
  (copy-on-write, engine-side).

A SLOT remains the unit of admission (Orca's iteration-level
scheduling): each active request owns one slot for its lifetime and
eviction is a host-side free-list operation (`SlotAllocator`) — the
device buffers are never resized or compacted, so the compiled shapes
never churn.

Within a slot, axes follow the repo's (B, T, H, Dh) attention
convention (`ops/attention.py`) so the cache feeds
`dot_product_attention` / the SP online-softmax without transposes.

Three mesh layouts, chosen to match the TRAINING engine whose params
are being served (`cache_pspecs` / `paged_pspecs`):

  replicated — every device holds the full cache (single-chip or pure
               data-parallel serving).
  tp         — heads sharded over 'model', the Megatron axis: the
               head-sharded q/k/v a column-parallel qkv projection
               produces attend against their local head shard
               (`parallel/tensor_parallel.py` layouts).
  sp         — positions sharded over 'seq': each shard owns a
               contiguous slice of every position range (the max_len
               axis for contiguous slots, the page_size axis for the
               paged pool), decode combines per-shard partial
               attention with the same online-softmax recurrence
               `ops/ring_attention.py` uses.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

LAYOUTS = ("replicated", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    """Static shape of the preallocated cache (one per ServingEngine)."""

    num_layers: int
    num_slots: int
    max_len: int
    num_heads: int
    head_dim: int
    dtype: Any = jnp.float32

    def validate(self, layout: str, mesh: Optional[Mesh]) -> None:
        """Fail at construction (not at trace time) when the cache
        cannot be laid out on the mesh."""
        if layout not in LAYOUTS:
            raise ValueError(
                f"layout must be one of {LAYOUTS}, got {layout!r}"
            )
        if layout == "replicated":
            return
        if mesh is None:
            raise ValueError(f"layout {layout!r} needs a mesh")
        if layout == "tp":
            s = mesh.shape["model"]
            if self.num_heads % s:
                raise ValueError(
                    f"tp cache shards heads over 'model': num_heads "
                    f"{self.num_heads} not divisible by {s} shards"
                )
        if layout == "sp":
            s = mesh.shape["seq"]
            if self.max_len % s:
                raise ValueError(
                    f"sp cache shards positions over 'seq': max_len "
                    f"{self.max_len} not divisible by {s} shards"
                )


def cache_pspecs(layout: str) -> dict:
    """PartitionSpec pytree for one cache (see module docstring)."""
    if layout == "tp":
        kv = P(None, None, None, "model", None)
    elif layout == "sp":
        kv = P(None, None, "seq", None, None)
    else:
        kv = P()
    return {"k": kv, "v": kv, "lengths": P()}


def cache_shardings(mesh: Mesh, layout: str) -> dict:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        cache_pspecs(layout),
        is_leaf=lambda x: isinstance(x, P),
    )


def init_cache(spec: KVCacheSpec) -> dict:
    """Zero-filled cache pytree; place with `cache_shardings`."""
    kv_shape = (
        spec.num_layers, spec.num_slots, spec.max_len,
        spec.num_heads, spec.head_dim,
    )
    return {
        "k": jnp.zeros(kv_shape, spec.dtype),
        "v": jnp.zeros(kv_shape, spec.dtype),
        "lengths": jnp.zeros((spec.num_slots,), jnp.int32),
    }


class SlotAllocator:
    """Host-side free-list over the cache's slot axis.

    Admission takes the lowest free slot (deterministic traces),
    eviction returns it; the device-side buffers are untouched — a
    recycled slot's stale K/V beyond the new request's positions stays
    masked by the per-slot length until overwritten.

    `bytes_per_slot` is the accounting seam: for the CONTIGUOUS layout
    every live slot pins a full `max_len` stripe of K/V whether the
    sequence uses 3 positions or 300, so `kv_cache_bytes` here is
    `live_slots * bytes_per_slot` — the number the paged pool's
    token-proportional `PagePool.kv_cache_bytes` is measured against
    (the PagedAttention waste claim, asserted from the bookkeeping in
    tests/test_serving_paged.py and reported by bench.py)."""

    def __init__(self, num_slots: int, *, bytes_per_slot: int = 0):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = num_slots
        self.bytes_per_slot = int(bytes_per_slot)
        self._free: List[int] = list(range(num_slots))
        self._live: set = set()

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def live_slots(self) -> int:
        return len(self._live)

    @property
    def kv_cache_bytes(self) -> int:
        """Bytes the LIVE slots pin: the contiguous layout charges a
        whole `max_len` stripe per admission, independent of how many
        positions actually hold K/V."""
        return len(self._live) * self.bytes_per_slot

    def alloc(self) -> int:
        if not self._free:
            raise RuntimeError(
                f"all {self.num_slots} cache slots are live; evict "
                "(finish) a sequence before admitting another"
            )
        slot = min(self._free)
        self._free.remove(slot)
        self._live.add(slot)
        return slot

    def free(self, slot: int) -> None:
        if slot not in self._live:
            raise ValueError(f"slot {slot} is not live")
        self._live.remove(slot)
        self._free.append(slot)


# ----------------------------------------------------------- paged pool


@dataclasses.dataclass(frozen=True)
class PagedKVCacheSpec:
    """Static shape of the preallocated PAGE POOL (one per paged
    ServingEngine). `num_pages` bounds total live tokens at
    `num_pages * page_size` across ALL slots — the pool may be sized
    well under `num_slots * max_len` because allocation is
    page-granular and ragged batches only pin what they use."""

    num_layers: int
    num_slots: int
    max_len: int
    page_size: int
    num_pages: int
    num_heads: int
    head_dim: int
    dtype: Any = jnp.float32

    @property
    def pages_per_slot(self) -> int:
        """Block-table width: pages covering one slot's max_len."""
        return -(-self.max_len // self.page_size)

    @property
    def page_bytes(self) -> int:
        """K AND V bytes one pool page pins across all layers."""
        return (
            2 * self.num_layers * self.page_size * self.num_heads
            * self.head_dim * jnp.dtype(self.dtype).itemsize
        )

    def validate(self, layout: str, mesh: Optional[Mesh]) -> None:
        if layout not in LAYOUTS:
            raise ValueError(
                f"layout must be one of {LAYOUTS}, got {layout!r}"
            )
        if self.page_size < 1:
            raise ValueError(
                f"page_size must be >= 1, got {self.page_size}"
            )
        if self.max_len % self.page_size:
            raise ValueError(
                f"page_size {self.page_size} must divide max_len "
                f"{self.max_len} (the block table covers whole pages)"
            )
        if self.num_pages < self.pages_per_slot:
            raise ValueError(
                f"num_pages {self.num_pages} cannot hold even one "
                f"full-length sequence ({self.pages_per_slot} pages "
                f"of {self.page_size})"
            )
        if layout == "replicated":
            return
        if mesh is None:
            raise ValueError(f"layout {layout!r} needs a mesh")
        if layout == "tp":
            s = mesh.shape["model"]
            if self.num_heads % s:
                raise ValueError(
                    f"tp cache shards heads over 'model': num_heads "
                    f"{self.num_heads} not divisible by {s} shards"
                )
        if layout == "sp":
            s = mesh.shape["seq"]
            if self.page_size % s:
                raise ValueError(
                    f"sp shards each page's positions over 'seq': "
                    f"page_size {self.page_size} not divisible by "
                    f"{s} shards"
                )


def paged_pspecs(layout: str) -> dict:
    """PartitionSpec pytree for the page pool
    (L, num_pages, page_size, H, Dh): heads over 'model' for tp, the
    WITHIN-page position axis over 'seq' for sp (every shard owns a
    contiguous slice of every page, so block-table gathers stay
    local)."""
    if layout == "tp":
        kv = P(None, None, None, "model", None)
    elif layout == "sp":
        kv = P(None, None, "seq", None, None)
    else:
        kv = P()
    return {"k": kv, "v": kv}


def paged_shardings(mesh: Mesh, layout: str) -> dict:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        paged_pspecs(layout),
        is_leaf=lambda x: isinstance(x, P),
    )


def init_paged_cache(spec: PagedKVCacheSpec) -> dict:
    """Zero-filled page pool; place with `paged_shardings`. Unlike the
    contiguous cache, `lengths` is NOT device state — the host loop
    owns every slot's position (it owns the block table anyway), so
    positions ride in as a step argument."""
    kv_shape = (
        spec.num_layers, spec.num_pages, spec.page_size,
        spec.num_heads, spec.head_dim,
    )
    return {
        "k": jnp.zeros(kv_shape, spec.dtype),
        "v": jnp.zeros(kv_shape, spec.dtype),
    }


class PagePool:
    """Host-side page allocator with refcounts.

    Allocation takes the lowest free page (deterministic traces);
    `incref`/`decref` support prefix sharing — a page frees only when
    its LAST reference drops. `pages_in_use`/`kv_cache_bytes` are the
    accounting seam: paged allocation must scale with live tokens
    (ceil per live sequence), never with `slots * max_len`."""

    def __init__(self, num_pages: int, page_bytes: int = 0):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        self.num_pages = num_pages
        self.page_bytes = int(page_bytes)
        self._free: List[int] = list(range(num_pages))
        self._refs: Dict[int, int] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def kv_cache_bytes(self) -> int:
        return self.pages_in_use * self.page_bytes

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def alloc(self) -> int:
        if not self._free:
            raise RuntimeError(
                f"page pool exhausted: all {self.num_pages} KV pages "
                "are live — size the pool larger (--kv-pages) or admit "
                "fewer concurrent sequences"
            )
        page = min(self._free)
        self._free.remove(page)
        self._refs[page] = 1
        return page

    def incref(self, page: int) -> None:
        if page not in self._refs:
            raise ValueError(f"page {page} is not live")
        self._refs[page] += 1

    def decref(self, page: int) -> bool:
        """Drop one reference; True when the page was freed."""
        n = self._refs.get(page)
        if n is None:
            raise ValueError(f"page {page} is not live")
        if n > 1:
            self._refs[page] = n - 1
            return False
        del self._refs[page]
        self._free.append(page)
        return True


class PrefixCache:
    """Host-side map from token prefixes to immutable shared pool
    pages (prompt/prefix caching — the millions-of-users shape where
    a repeated system prompt dominates prefill).

    Keys are CHAINED digests over the full token prefix (page j's key
    = blake2b(key_{j-1} || page j's int32 bytes) — the page content
    depends on every earlier token, so reuse requires an exact
    whole-prefix match, and the rolling chain prices a lookup at O(n)
    total instead of re-serializing O(n^2/page) prefix bytes per
    request). A prompt whose length is not page-aligned additionally
    registers a whole-prompt entry for its last PARTIAL page; a
    borrower of that page copies it before writing (copy-on-write,
    engine-side — the cache itself never mutates device state).

    Every cached entry holds one pool reference of its own, so pages
    outlive the slot that produced them; `release_unused` drops
    cache-only entries (refcount 1) in LRU order when the pool runs
    dry."""

    def __init__(self, pool: PagePool, page_size: int):
        self.pool = pool
        self.page_size = page_size
        # key -> page id, in LRU order (move_to_end on every match).
        self._map: "OrderedDict[bytes, int]" = OrderedDict()
        # key -> the keys chained directly off it: match() breaks at
        # the first missing key, so an entry whose PARENT is evicted
        # can never match again — eviction cascades down this map so
        # orphans neither pin pool references nor inflate `evictable`.
        self._children: Dict[bytes, List[bytes]] = {}
        self.hits = 0       # requests that reused >= 1 cached page
        self.misses = 0     # requests that matched nothing
        self.tokens_reused = 0

    def __len__(self) -> int:
        return len(self._map)

    @staticmethod
    def _chain(prev: bytes, tokens: np.ndarray) -> bytes:
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
        return h.digest()

    def _keys(self, prompt: np.ndarray):
        """(key, tokens-covered) per cacheable span of `prompt`, in
        prefix order: one per full page, then the whole-prompt partial
        entry when the length is not page-aligned. THE one place the
        chain rule lives — match and register can never disagree on a
        key."""
        ps = self.page_size
        key = b""
        out: List[Tuple[bytes, int]] = []
        for j in range(len(prompt) // ps):
            key = self._chain(key, prompt[j * ps:(j + 1) * ps])
            out.append((key, (j + 1) * ps))
        if len(prompt) % ps:
            out.append((
                self._chain(key, prompt[len(prompt) // ps * ps:]),
                len(prompt),
            ))
        return out

    def match(self, prompt: np.ndarray) -> Tuple[List[int], int]:
        """Longest cached prefix of `prompt`: ([page ids], tokens
        covered). Matched pages are incref'd FOR THE CALLER (the slot
        now shares them); spans match greedily from page 0 — the
        partial whole-prompt entry can only extend a fully matched
        run of full pages (its key chains through theirs)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        pages: List[int] = []
        covered = 0
        for key, n_tokens in self._keys(prompt):
            pid = self._map.get(key)
            if pid is None:
                break
            self._map.move_to_end(key)
            pages.append(pid)
            covered = n_tokens
        for pid in pages:
            self.pool.incref(pid)
        if pages:
            self.hits += 1
            self.tokens_reused += covered
        else:
            self.misses += 1
        return pages, covered

    def register(self, prompt: np.ndarray, page_ids: List[int]) -> None:
        """Publish a freshly ingested prompt's pages: one entry per
        full page plus the whole-prompt partial entry when the length
        is not page-aligned. Existing entries win (first writer keeps
        ownership); each NEW entry takes its own pool reference."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        parent = b""
        for page_idx, (key, _n) in enumerate(self._keys(prompt)):
            if page_idx >= len(page_ids):
                break
            if key not in self._map:
                pid = page_ids[page_idx]
                self.pool.incref(pid)
                self._map[key] = pid
                self._map.move_to_end(key)
                self._children.setdefault(parent, set()).add(key)
            parent = key

    def _evict(self, key: bytes) -> int:
        """Drop one entry AND its whole extension subtree (module
        docstring: a child is unmatchable once its parent is gone);
        returns pages actually freed (a page a live slot still borrows
        loses only the cache's reference)."""
        pid = self._map.pop(key, None)
        if pid is None:
            return 0
        freed = 1 if self.pool.decref(pid) else 0
        for child in self._children.pop(key, ()):
            freed += self._evict(child)
        return freed

    def release_unused(self, want: int) -> int:
        """Free up to `want` pages by dropping cache entries whose page
        no slot references (pool refcount 1 — the cache's own ref), in
        LRU order, each with its extension subtree. Returns how many
        pages were actually freed."""
        freed = 0
        for key in list(self._map):
            if freed >= want:
                break
            if key not in self._map:
                continue  # already gone with an evicted ancestor
            if self.pool.refcount(self._map[key]) == 1:
                freed += self._evict(key)
        return freed

    @property
    def evictable(self) -> int:
        """Pages only the cache still references (admission headroom)."""
        return sum(
            1 for pid in self._map.values()
            if self.pool.refcount(pid) == 1
        )


def copy_page(cache: dict, src, dst) -> dict:
    """Device-side page copy (the copy-on-write kernel): duplicate pool
    page `src` into `dst` across every layer of both K and V. The
    engine jits this once with the cache donated, so a COW costs one
    tiny in-place scatter, not a pool copy."""
    return {
        name: buf.at[:, dst].set(buf[:, src])
        for name, buf in cache.items()
    }


class PagedCacheHost:
    """Host half of the paged cache: the block tables, page-granular
    alloc/free, prefix sharing, and copy-on-write. Owns every invariant
    the compiled steps assume:

    * a slot's write position is always backed by an allocated page
      (`ensure_writable` before each decode/pseudo-decode write);
    * a write page is always PRIVATE — a shared page (prefix cache, or
      a borrowed partial page) is copied first, so distinct live slots
      never scatter into the same pool page;
    * a freed slot returns pages, not a max_len stripe (`release`),
      and shared pages survive via their remaining references.
    """

    def __init__(self, spec: PagedKVCacheSpec, *,
                 prefix_cache: bool = False, copy_fn=None):
        self.spec = spec
        self.pool = PagePool(spec.num_pages, spec.page_bytes)
        self.block_tables = np.full(
            (spec.num_slots, spec.pages_per_slot), -1, np.int32
        )
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(self.pool, spec.page_size)
            if prefix_cache else None
        )
        self._copy = copy_fn
        self.cow_copies = 0
        self.pages_in_use_peak = 0
        # Worst-case page commitment per admitted slot (`reserve`):
        # admission headroom is judged against every admitted-but-not-
        # yet-allocated page, so two concurrently ingesting slots can
        # never be promised the same free pages and a sequence, once
        # admitted, always completes (its decode growth and potential
        # COW swaps are inside its commitment).
        self._commit: Dict[int, int] = {}
        # Device mirror of block_tables, rebuilt lazily: steady-state
        # decode mutates the table only at page boundaries / COW /
        # admission, so most iterations reuse the cached upload
        # (every block_tables write below invalidates it).
        self._dev_table = None

    # ------------------------------------------------------ bookkeeping

    def device_table(self):
        if self._dev_table is None:
            self._dev_table = jnp.asarray(self.block_tables)
        return self._dev_table

    def device_row(self, slot: int):
        """One slot's block-table row — the per-slot steps (prefill,
        chunk ingest) take only their own row, sliced from the cached
        device mirror."""
        return self.device_table()[slot]

    def _note_peak(self) -> None:
        self.pages_in_use_peak = max(
            self.pages_in_use_peak, self.pool.pages_in_use
        )

    def _pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.spec.page_size)

    def _outstanding(self) -> int:
        """Pages promised to admitted slots but not yet allocated:
        each slot's commitment minus the PRIVATE pages it already
        holds (a shared entry — prefix-matched or cache-registered —
        still counts as owed, because a write into it copy-on-writes
        into a fresh page)."""
        total = 0
        for slot, commit in self._commit.items():
            private = sum(
                1 for pid in self.block_tables[slot]
                if pid >= 0 and self.pool.refcount(int(pid)) == 1
            )
            total += max(0, commit - private)
        return total

    def can_hold(self, n_tokens: int) -> bool:
        """Admission headroom: enough free (or cache-evictable) pages
        for a whole `n_tokens` sequence — prompt AND its generation
        budget, assuming none of it prefix-matches — AFTER honoring
        every already-admitted slot's outstanding commitment. A
        request admitted under this check (and reserved via `reserve`)
        can always allocate its way to completion; the alternative is
        overcommit, where mid-ingest exhaustion would abort work the
        scheduler already accepted."""
        headroom = self.pool.free_pages + (
            self.prefix.evictable if self.prefix else 0
        ) - self._outstanding()
        return headroom >= self._pages_for(n_tokens)

    def reserve(self, slot: int, n_tokens: int) -> None:
        """Commit the slot's worst-case page need (call at admission,
        with the same token count `can_hold` approved)."""
        self._commit[slot] = self._pages_for(n_tokens)

    def _alloc_page(self) -> int:
        try:
            page = self.pool.alloc()
        except RuntimeError:
            if self.prefix is None or not self.prefix.release_unused(1):
                raise
            page = self.pool.alloc()
        self._note_peak()
        return page

    # ------------------------------------------------------- lifecycle

    def ensure_pages(self, slot: int, n_tokens: int) -> None:
        """Allocate so the slot's pages cover positions [0, n_tokens)
        (prefix-matched entries are already in place and kept)."""
        for j in range(self._pages_for(n_tokens)):
            if self.block_tables[slot, j] < 0:
                self.block_tables[slot, j] = self._alloc_page()
                self._dev_table = None

    def ensure_writable(self, cache: dict, slot: int,
                        position: int) -> dict:
        """Back `position` with a PRIVATE page before a device write:
        allocate if unmapped, copy-on-write if shared. Returns the
        (possibly updated) device cache."""
        j = position // self.spec.page_size
        pid = int(self.block_tables[slot, j])
        if pid < 0:
            self.block_tables[slot, j] = self._alloc_page()
            self._dev_table = None
            return cache
        if self.pool.refcount(pid) > 1:
            fresh = self._alloc_page()
            cache = self._copy(cache, jnp.int32(pid), jnp.int32(fresh))
            self.pool.decref(pid)
            self.block_tables[slot, j] = fresh
            self._dev_table = None
            self.cow_copies += 1
        return cache

    def attach_prefix(self, slot: int, prompt) -> int:
        """Install the longest cached prefix into the slot's block
        table; returns tokens covered (0 when the cache is off or
        missed)."""
        if self.prefix is None:
            return 0
        pages, covered = self.prefix.match(prompt)
        for j, pid in enumerate(pages):
            self.block_tables[slot, j] = pid
        if pages:
            self._dev_table = None
        self._note_peak()
        return covered

    def register_prefix(self, slot: int, prompt) -> None:
        if self.prefix is None:
            return
        n = self._pages_for(len(np.asarray(prompt).reshape(-1)))
        ids = [int(p) for p in self.block_tables[slot, :n]]
        if all(p >= 0 for p in ids):
            self.prefix.register(prompt, ids)

    def truncate(self, slot: int, n_tokens: int) -> None:
        """Roll a slot back so it holds only its first `n_tokens`
        positions: pages wholly beyond the kept span return to the pool
        (shared pages just drop this slot's reference). THE speculative
        rollback primitive — a rejected draft suffix is a block-table
        edit plus refcount decrements, never a KV copy. Stale K/V
        inside the kept final page's tail stays masked by the slot's
        position until overwritten, the same discipline recycled slots
        rely on."""
        keep = self._pages_for(n_tokens)
        for j in range(keep, self.spec.pages_per_slot):
            pid = int(self.block_tables[slot, j])
            if pid >= 0:
                self.pool.decref(pid)
                self.block_tables[slot, j] = -1
                self._dev_table = None

    def release(self, slot: int) -> None:
        """Recycle a slot: PAGES return to the pool (minus surviving
        shared references) — never a max_len stripe — and its
        commitment clears."""
        for j, pid in enumerate(self.block_tables[slot]):
            if pid >= 0:
                self.pool.decref(int(pid))
        self.block_tables[slot] = -1
        self._dev_table = None
        self._commit.pop(slot, None)


__all__ = [
    "KVCacheSpec",
    "LAYOUTS",
    "PagePool",
    "PagedCacheHost",
    "PagedKVCacheSpec",
    "PrefixCache",
    "SlotAllocator",
    "copy_page",
    "cache_pspecs",
    "cache_shardings",
    "init_cache",
    "init_paged_cache",
    "paged_pspecs",
    "paged_shardings",
]
