"""Composable mesh-axis plans (`parallel/plan.py`, ISSUE 19).

Correctness bar: every factorization of the SAME GPT config is an exact
rearrangement of the dense computation, not an approximation — so each
plan's per-token loss, metrics, and multi-step trajectory are pinned
against the one-device dense `gpt_lm` step at rtol 1e-5, and the
degenerate-plan map (`build_plan_engine` routing a single-axis plan to
the existing single-axis engine) is pinned as a type contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.models.gpt import (
    GPTConfig,
    gpt_lm,
    lm_loss,
)
from distributed_model_parallel_tpu.parallel.plan import (
    ComposedPlanEngine,
    ParallelPlan,
    build_plan_engine,
    parse_plan,
)
from distributed_model_parallel_tpu.training.optim import SGD

TINY = GPTConfig(
    vocab_size=61, dim=32, num_layers=4, num_heads=4, ffn_dim=64,
    max_position=16, dropout_rate=0.0,
)
B, T = 8, 16
LR = 0.1


def _ids(seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(1, TINY.vocab_size, size=(B, T)).astype(np.int32)


def _dense_step_fn(cfg, ids):
    """One jitted dense train step over the full batch — the ground
    truth every factorization must reproduce."""
    model = gpt_lm(cfg)
    params, state = model.init(jax.random.PRNGKey(0))
    opt = SGD()
    opt_state = opt.init(params)
    idsj = jnp.asarray(ids)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits, _ = model.apply(
                p, state, idsj, L.Context(train=True)
            )
            return lm_loss(logits, idsj)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.update(
            params, opt_state, grads, jnp.float32(LR)
        )
        return params, opt_state, loss

    return step, params, opt_state, model, state, idsj


def _run_parity(spec, n_steps=3, rtol_params=2e-4):
    """Train `n_steps` under `spec` and densely; assert the loss
    trajectory matches at rtol 1e-5 and final params at rtol_params."""
    eng = build_plan_engine(TINY, SGD(), spec, donate=False)
    ts = eng.init_state(jax.random.PRNGKey(0))
    ids = _ids(seed=7)
    ids_s, tg_s = eng.shard_batch(ids)
    step, params, opt_state, model, state, idsj = _dense_step_fn(
        TINY, ids
    )
    for i in range(n_steps):
        ts, m = eng.train_step(ts, ids_s, tg_s, jnp.float32(LR))
        params, opt_state, dense_loss = step(params, opt_state)
        np.testing.assert_allclose(
            float(m["loss_sum"]) / float(m["count"]),
            float(dense_loss), rtol=1e-5,
            err_msg=f"{spec} diverged from dense at step {i}",
        )
        assert float(m["count"]) == B * (T - 1)
    got = eng.to_canonical(ts).params
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves(got),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=rtol_params, atol=2e-5,
            err_msg=f"{spec}: {jax.tree_util.keystr(path)}",
        )
    # eval path agrees with the dense eval loss on the trained params
    ev = eng.eval_step(ts, ids_s, tg_s)
    logits, _ = model.apply(params, state, idsj, L.Context(train=False))
    np.testing.assert_allclose(
        float(ev["loss_sum"]) / float(ev["count"]),
        float(lm_loss(logits, idsj)), rtol=1e-5,
    )


# ------------------------------------------------------------ the spec


def test_parse_plan_fields_and_spec_roundtrip():
    p = parse_plan("pp2xsp2xdp2")
    assert (p.pp, p.tp_or_sp, p.dp, p.ep, p.fsdp) == (2, 2, 2, 1, False)
    assert p.num_devices == 8
    assert parse_plan(p.spec) == p
    q = parse_plan("pp2xfsdp4")
    assert q.fsdp and q.dp == 4 and q.num_devices == 8
    assert parse_plan(q.spec) == q
    # tp is an alias for the within-'ici' model axis
    assert parse_plan("tp4").tp_or_sp == 4
    assert parse_plan("dp1") == ParallelPlan()


@pytest.mark.parametrize("bad", [
    "", "pp2x", "xx4", "pp2xpp2", "sp2xtp2", "dp3x2", "pp0",
])
def test_parse_plan_rejects_bad_specs(bad):
    with pytest.raises(ValueError):
        parse_plan(bad)


# ------------------------------------------- the degenerate-plan map


def test_degenerate_plans_route_to_single_axis_engines():
    """The INTERNALS §19 map as a type contract: each existing
    single-axis engine IS the degenerate form of its plan."""
    from distributed_model_parallel_tpu.parallel.pipeline import (
        LMPipelineEngine,
    )
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        CausalLMSequenceParallelEngine,
    )

    assert isinstance(
        build_plan_engine(TINY, SGD(), "pp2", donate=False),
        LMPipelineEngine,
    )
    assert isinstance(
        build_plan_engine(TINY, SGD(), "sp2", donate=False),
        CausalLMSequenceParallelEngine,
    )
    for spec in ("dp8", "fsdp4", "pp2xdp2", "sp2xdp2"):
        assert isinstance(
            build_plan_engine(TINY, SGD(), spec, donate=False),
            ComposedPlanEngine,
        ), spec


def test_build_plan_engine_refusals():
    import dataclasses

    with pytest.raises(ValueError, match="devices"):
        build_plan_engine(TINY, SGD(), "dp64")
    with pytest.raises(ValueError, match="no experts"):
        build_plan_engine(TINY, SGD(), "ep2")
    moe_cfg = dataclasses.replace(TINY, num_experts=4)
    # The refusal names the offending ParallelPlan FIELD and the flag
    # that sets it (ISSUE 20's guard convention), not a roadmap item.
    with pytest.raises(NotImplementedError, match="ParallelPlan.ep"):
        build_plan_engine(moe_cfg, SGD(), "pp2xep2")
    with pytest.raises(NotImplementedError, match="--plan"):
        build_plan_engine(moe_cfg, SGD(), "sp2xep2")
    # uniform stage slices: pp must divide the layer stack
    with pytest.raises(ValueError, match="num_layers"):
        build_plan_engine(
            TINY, SGD(), "pp8", force_composed=True,
        )
    # the tick loop cannot fill a pipeline with fewer microbatches
    # than stages
    with pytest.raises(ValueError, match="num_microbatches"):
        build_plan_engine(
            TINY, SGD(), "pp2xdp2", num_microbatches=1,
        )


# --------------------------------------------------- parity vs dense


def test_composed_2x2x2_matches_dense_trajectory():
    """THE acceptance pin (ISSUE 19): the pp2 x sp2 x dp2 composed
    plan on the 8-device mesh follows the dense 3-step trajectory —
    losses, token counts, final params, eval — at rtol 1e-5."""
    _run_parity("pp2xsp2xdp2")


@pytest.mark.slow
def test_composed_dp_only_matches_dense_trajectory():
    """The pure-data composed program (no stage wire, no seq ring —
    the degenerate tick loop) is still exactly dense. `slow` (one more
    composed compile); tier-1 twin:
    test_composed_2x2x2_matches_dense_trajectory — the same tick
    program with all three axes live."""
    _run_parity("dp8")


@pytest.mark.slow
def test_composed_fsdp_matches_dense_trajectory():
    """ZeRO-3 on the plan's data axis: 1/dp params + moments with the
    plan_fsdp_gather materialization, same trajectory as dense. `slow`
    (tier-1 budget); tier-1 twins:
    test_composed_2x2x2_matches_dense_trajectory (the same tick
    program) + test_checkpoint_sharded's cross-plan reshard test,
    which restores onto fsdp4 and runs a finite composed-fsdp
    train_step in tier-1."""
    _run_parity("pp2xfsdp4")


@pytest.mark.slow
def test_degenerate_composed_matches_forced_composed():
    """Both sides of the degenerate map agree: the single-axis SP
    engine and the force_composed ComposedPlanEngine produce the same
    loss for the same plan, params, and batch. `slow` (two extra
    engine compiles); tier-1 twins:
    test_degenerate_plans_route_to_single_axis_engines (the routing
    contract) + test_composed_2x2x2_matches_dense_trajectory (both
    sides are separately pinned against the SAME dense baseline)."""
    ids = _ids(seed=3)
    losses = []
    for force in (False, True):
        eng = build_plan_engine(
            TINY, SGD(), "sp2", donate=False, force_composed=force,
        )
        ts = eng.init_state(jax.random.PRNGKey(0))
        ids_s, tg_s = eng.shard_batch(ids)
        _, m = eng.train_step(ts, ids_s, tg_s, jnp.float32(LR))
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("spec", [
    "fsdp8", "pp2xdp4", "sp2xdp4", "pp4xdp2", "sp4xdp2",
    "pp2xfsdp2", "sp2xfsdp4", "pp2xsp2xfsdp2", "pp2xsp4",
])
def test_plan_parity_sweep(spec):
    """Full composed-plan parity sweep: every remaining factorization
    of the 8-device world follows the dense trajectory. `slow`
    (tier-1 budget: ~9 composed compiles); tier-1 twin:
    test_composed_2x2x2_matches_dense_trajectory — the 3-axis case of
    the same _run_parity assertion (the fsdp and degenerate cases ride
    this sweep and test_composed_fsdp_matches_dense_trajectory in the
    slow lane)."""
    _run_parity(spec)


@pytest.mark.slow
def test_composed_plan_num_microbatches_above_pp():
    """M > S: extra microbatches drain through the same tick program
    (M + S - 1 ticks) without changing the math. `slow` (one more
    composed compile); tier-1 twin:
    test_composed_2x2x2_matches_dense_trajectory — the M == S case of
    the same tick loop."""
    eng = build_plan_engine(
        TINY, SGD(), "pp2xdp2", num_microbatches=4, donate=False,
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    ids = _ids(seed=5)
    ids_s, tg_s = eng.shard_batch(ids)
    step, params, opt_state, *_ = _dense_step_fn(TINY, ids)
    ts, m = eng.train_step(ts, ids_s, tg_s, jnp.float32(LR))
    _, _, dense_loss = step(params, opt_state)
    np.testing.assert_allclose(
        float(m["loss_sum"]) / float(m["count"]), float(dense_loss),
        rtol=1e-5,
    )


# ------------------------------------------- scheduled plans (ISSUE 20)


def test_parse_plan_schedule_suffix_roundtrip():
    """`-1f1b` / `-int<V>` on the pp token are ParallelPlan.schedule /
    .virtual_stages; the spec string round-trips, including the dashed
    `pp2-1f1b-xsp2` form the checkpoint satellite saves under."""
    p = parse_plan("pp2-1f1bxsp2xdp2")
    assert (p.pp, p.tp_or_sp, p.dp) == (2, 2, 2)
    assert p.schedule == "1f1b" and p.virtual_stages == 1
    assert parse_plan(p.spec) == p
    q = parse_plan("pp4-int2xdp2")
    assert q.schedule == "interleaved" and q.virtual_stages == 2
    assert parse_plan(q.spec) == q
    # dashed-separator tolerance: `pp2-1f1b-xsp2` == `pp2-1f1bxsp2`
    assert parse_plan("pp2-1f1b-xsp2") == parse_plan("pp2-1f1bxsp2")
    # default stays gpipe and prints without a suffix
    assert parse_plan("pp2xdp2").schedule == "gpipe"
    assert "-" not in parse_plan("pp2xdp2").spec


@pytest.mark.parametrize("bad", [
    "pp2-int1",     # V=1 interleaving is spelled 1f1b
    "sp2-1f1b",     # schedule suffix only composes with the pp token
    "dp4-int2",
    "pp1-1f1b",     # a schedule needs a pipeline (pp >= 2)
    "pp2-gpipe",    # gpipe is the default, not a suffix
])
def test_parse_plan_rejects_bad_schedule_specs(bad):
    with pytest.raises(ValueError):
        parse_plan(bad)


def test_scheduled_plan_guards_name_field_and_flag():
    """ISSUE 20 guard convention: refusals name the ParallelPlan field
    AND the flag that sets it, fail-fast at build time."""
    # interleaved needs M >= pp * V to fill every virtual stage
    with pytest.raises(ValueError, match="num_microbatches"):
        build_plan_engine(
            TINY, SGD(), "pp2-int2xdp2", num_microbatches=2,
        )
    # V * pp must divide the block count (TINY has 4 layers)
    with pytest.raises(ValueError, match="num_layers"):
        build_plan_engine(TINY, SGD(), "pp2-int4xdp2")
    with pytest.raises(ValueError, match="virtual_stages"):
        ParallelPlan(pp=2, schedule="interleaved", virtual_stages=1)
    with pytest.raises(ValueError, match="schedule"):
        ParallelPlan(pp=1, schedule="1f1b")


def test_fsdp_per_parameter_layout():
    """The plan's fsdp bit uses the single-axis FSDPEngine's
    per-parameter layout (ISSUE 20), not whole-leaf 1/dp: leaves under
    `min_shard_elems` stay replicated P(), big leaves shard 1/dp on
    'data', and AdamW moments sit alongside their parameter with the
    SAME per-leaf spec."""
    from jax.sharding import PartitionSpec as P

    from distributed_model_parallel_tpu.training.optim import AdamW

    eng = build_plan_engine(TINY, AdamW(), "fsdp8", donate=False)
    specs = eng.state_partition_specs()
    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    leaves = jax.tree_util.tree_leaves(specs.params, is_leaf=is_spec)
    sharded = [s for s in leaves if s != P()]
    repl = [s for s in leaves if s == P()]
    # per-parameter means BOTH kinds coexist in one params tree
    assert sharded, "no leaf sharded — not an fsdp layout"
    assert repl, "every leaf sharded — min_shard_elems ignored"
    assert all(
        any(part == "data" for part in s if part is not None)
        for s in sharded
    )
    # moments mirror the per-leaf layout exactly
    assert jax.tree_util.tree_leaves(
        specs.opt_state.mu, is_leaf=is_spec
    ) == leaves
    assert jax.tree_util.tree_leaves(
        specs.opt_state.nu, is_leaf=is_spec
    ) == leaves


def test_composed_1f1b_matches_dense_trajectory():
    """THE acceptance pin (ISSUE 20): the pp2-1f1b x sp2 x dp2
    scheduled plan on the 8-device mesh follows the dense 3-step
    trajectory — losses, token counts, final params, eval — at
    rtol 1e-5."""
    _run_parity("pp2-1f1bxsp2xdp2")


@pytest.mark.slow
def test_composed_interleaved_matches_dense_trajectory():
    """Interleaved V=2 (two virtual stages per device, M=4 default)
    follows the dense trajectory. `slow` (one more composed compile);
    tier-1 twin: test_composed_1f1b_matches_dense_trajectory — the
    same table-driven tick program with V=1 tables."""
    _run_parity("pp2-int2xdp2")


@pytest.mark.slow
def test_composed_1f1b_fsdp_matches_dense_trajectory():
    """1F1B over the per-parameter fsdp layout: scheduled per-block
    gathers compose with ZeRO-3 sharding and stay exactly dense.
    `slow` (tier-1 budget); tier-1 twins:
    test_composed_1f1b_matches_dense_trajectory (the schedule) +
    test_fsdp_per_parameter_layout (the layout)."""
    _run_parity("pp2-1f1bxfsdp4")


def test_1f1b_bit_identical_to_gpipe_twin():
    """At M == S the 1F1B table IS the gpipe fill-drain order (all
    forwards, then all backwards, same microbatch order), so the final
    params after 3 steps must be BIT-identical to the gpipe twin —
    the 'math-preserving schedule' half of the ISSUE 20 parity bar."""
    finals = []
    for spec in ("pp2xdp4", "pp2-1f1bxdp4"):
        eng = build_plan_engine(TINY, SGD(), spec, donate=False)
        ts = eng.init_state(jax.random.PRNGKey(0))
        ids = _ids(seed=11)
        ids_s, tg_s = eng.shard_batch(ids)
        for _ in range(3):
            ts, _ = eng.train_step(ts, ids_s, tg_s, jnp.float32(LR))
        finals.append(eng.to_canonical(ts).params)
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(finals[0]),
        jax.tree_util.tree_leaves(finals[1]),
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            f"1f1b twin diverged bitwise: {jax.tree_util.keystr(path)}"
        )


def _payload_leading_dims(lowered_text, min_payload=2048):
    """Leading dims of every f32 buffer in the lowered StableHLO
    (`tensor<AxB..xf32>`) whose per-row payload is at least
    `min_payload` elements — the activation stacks; tiny control
    tensors are noise."""
    import re as _re

    dims = set()
    for m in _re.finditer(r"tensor<(\d+(?:x\d+)+)xf32>", lowered_text):
        shape = [int(x) for x in m.group(1).split("x")]
        payload = 1
        for d in shape[1:]:
            payload *= d
        if payload >= min_payload:
            dims.add(shape[0])
    return dims


def test_1f1b_activation_memory_structurally_o_s_not_o_m():
    """The structural O(S)-vs-O(M) pin (ISSUE 20) from lowered HLO:
    at M=8 >> S=2 the gpipe program stacks per-microbatch residuals
    (an f32 buffer with leading dim >= M appears), while the 1F1B
    program's largest leading dim stays below M — its stash depth is
    min(S, M), independent of M."""
    rng = np.random.RandomState(2)
    ids = rng.randint(1, TINY.vocab_size, size=(16, T)).astype(np.int32)
    dims = {}
    for spec in ("pp2xdp2", "pp2-1f1bxdp2"):
        eng = build_plan_engine(
            TINY, SGD(), spec, num_microbatches=8, donate=False,
        )
        ts = eng.init_state(jax.random.PRNGKey(0))
        ids_s, tg_s = eng.shard_batch(ids)
        txt = eng.train_step.lower(
            ts, ids_s, tg_s, jnp.float32(LR)
        ).as_text()
        dims[spec] = _payload_leading_dims(txt)
    M, S = 8, 2
    assert max(dims["pp2xdp2"]) >= M, dims
    # 1f1b: stacked block params give leading dim num_layers=4; no
    # activation stack reaches M
    assert max(dims["pp2-1f1bxdp2"]) < M, dims
    # and the schedule table itself pins the tight O(S) bound
    eng = build_plan_engine(
        TINY, SGD(), "pp2-1f1bxdp2", num_microbatches=8, donate=False,
    )
    assert eng._sched.stash_depth <= min(S, M)


def test_scheduled_layouts_identical_to_gpipe_twin():
    """Schedule is execution-only: a scheduled plan declares the SAME
    state_partition_specs as its gpipe twin (checkpoints reshard
    across schedules through the canonical seam for free)."""
    for a, b in (
        ("pp2xsp2xdp2", "pp2-1f1bxsp2xdp2"),
        ("pp2xfsdp4", "pp2-int2xfsdp4"),
    ):
        sa = build_plan_engine(
            TINY, SGD(), a, donate=False
        ).state_partition_specs()
        sb = build_plan_engine(
            TINY, SGD(), b, donate=False
        ).state_partition_specs()
        assert jax.tree_util.tree_structure(sa) == \
            jax.tree_util.tree_structure(sb)
        assert jax.tree_util.tree_leaves(sa) == \
            jax.tree_util.tree_leaves(sb), (a, b)


def test_degenerate_scheduled_plan_routes_to_pipeline_engine():
    """A pp-only scheduled plan routes to the single-axis
    LMPipelineEngine with the schedule and V threaded through (the
    degenerate-plan map extends to schedules)."""
    from distributed_model_parallel_tpu.parallel.pipeline import (
        LMPipelineEngine,
    )

    eng = build_plan_engine(TINY, SGD(), "pp2-1f1b", donate=False)
    assert isinstance(eng, LMPipelineEngine)
    assert eng.schedule == "1f1b"
    eng = build_plan_engine(TINY, SGD(), "pp2-int2", donate=False)
    assert isinstance(eng, LMPipelineEngine)
    assert eng.schedule == "interleaved" and eng.virtual_stages == 2


# ------------------------------------------------- layout declarations


def test_state_partition_specs_shapes_match_state():
    """The manifest seam declares one spec per TrainState leaf for
    BOTH plan classes: all-P() for a replicated plan, 1/dp 'data'
    leaves for an fsdp plan."""
    from jax.sharding import PartitionSpec as P

    repl = build_plan_engine(TINY, SGD(), "pp2xsp2xdp2", donate=False)
    ts = repl.init_state(jax.random.PRNGKey(0))
    specs = repl.state_partition_specs()
    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    flat = jax.tree_util.tree_leaves(specs, is_leaf=is_spec)
    assert len(flat) == len(jax.tree_util.tree_leaves(ts))
    assert all(s == P() for s in flat)

    fs = build_plan_engine(TINY, SGD(), "fsdp8", donate=False)
    fs_specs = jax.tree_util.tree_leaves(
        fs.state_partition_specs().params, is_leaf=is_spec,
    )
    assert any("data" in (s[0] or ()) if len(s) else False
               for s in fs_specs if s != P())
