"""Model zoo — mirrors the reference's `model/__init__.py` re-export style
(`code/distributed_training/model/__init__.py:1`) plus the ResNet/BERT
families demanded by BASELINE.json's configs."""

from distributed_model_parallel_tpu.models import layers  # noqa: F401
from distributed_model_parallel_tpu.models.layers import Context, Layer  # noqa: F401
from distributed_model_parallel_tpu.models.mobilenetv2 import (  # noqa: F401
    mobilenet_v2,
    mobilenet_v2_nobn,
    split_stages,
)
from distributed_model_parallel_tpu.models.resnet import (  # noqa: F401
    resnet,
    resnet18,
    resnet50,
)
from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn  # noqa: F401
from distributed_model_parallel_tpu.models.bert import (  # noqa: F401
    BERT_BASE,
    BertConfig,
    bert_base,
    bert_for_classification,
)
from distributed_model_parallel_tpu.models.gpt import (  # noqa: F401
    GPTConfig,
    gpt_lm,
    lm_loss,
    lm_loss_fn,
)
from distributed_model_parallel_tpu.models.moe import (  # noqa: F401
    moe_encoder_layer,
    moe_feed_forward,
)
from distributed_model_parallel_tpu.models.vit import (  # noqa: F401
    VIT_B16,
    VIT_CIFAR,
    ViTConfig,
    vit,
    vit_b16,
    vit_cifar,
)
