"""Trainer + checkpoint tests — the epoch protocol of the reference
(`data_parallel.py:99-172`) exercised end-to-end on the 8-device CPU mesh
with a tiny model and synthetic data (no downloads, per SURVEY.md §4)."""

import os

import jax
import numpy as np
import pytest

from distributed_model_parallel_tpu.data.datasets import synthetic
from distributed_model_parallel_tpu.data.loader import Loader
from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.parallel.data_parallel import (
    DataParallelEngine,
)
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.checkpoint import (
    latest_exists,
    restore_checkpoint,
    save_checkpoint,
)
from distributed_model_parallel_tpu.training.optim import SGD
from distributed_model_parallel_tpu.training.trainer import (
    Trainer,
    TrainerConfig,
)


def tiny_model(num_classes=4):
    return L.named([
        ("conv", L.conv2d(3, 8, 3, stride=1, padding=1)),
        ("bn", L.batchnorm2d(8)),
        ("relu", L.relu()),
        ("pool", L.global_avg_pool()),
        ("linear", L.linear(8, num_classes)),
    ])


@pytest.fixture()
def engine():
    mesh = make_mesh(MeshSpec(data=8))
    return DataParallelEngine(model=tiny_model(), optimizer=SGD(), mesh=mesh)


def loaders(n=256, batch=32):
    ds = synthetic(num_examples=n, num_classes=4, image_size=8, seed=0)
    train = Loader(ds, batch_size=batch, shuffle=True, seed=0)
    val = Loader(ds, batch_size=batch, shuffle=False)
    return train, val


def test_trainer_learns_and_logs(engine, tmp_path):
    train, val = loaders()
    cfg = TrainerConfig(
        epochs=3,
        base_lr=0.1,
        t_max=3,
        warmup_period=1,
        print_freq=0,
        log_dir=str(tmp_path / "log"),
        log_file="test.txt",
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    trainer = Trainer(engine, train, val, cfg, rng=jax.random.PRNGKey(0))
    result = trainer.fit()

    hist = result["history"]
    assert len(hist) == 3
    # Convergence smoke: the reference's acceptance methodology (loss falls).
    assert hist[-1]["train"]["loss"] < hist[0]["train"]["loss"]
    assert result["best_acc"] > 30.0  # 4 classes, separable synthetic data

    # Epoch log artifacts (host-0 txt + JSONL, `data_parallel.py:167-171`).
    txt = tmp_path / "log" / "test.txt"
    jsonl = tmp_path / "log" / "test.jsonl"
    assert txt.exists() and len(txt.read_text().splitlines()) == 3
    assert jsonl.exists() and len(jsonl.read_text().splitlines()) == 3
    # Best-acc checkpoint was written.
    assert latest_exists(str(tmp_path / "ckpt"))


def test_checkpoint_roundtrip(engine, tmp_path):
    state = engine.init_state(jax.random.PRNGKey(1))
    save_checkpoint(str(tmp_path), state, acc=93.8, epoch=17)
    template = engine.init_state(jax.random.PRNGKey(2))
    restored, acc, epoch = restore_checkpoint(str(tmp_path), template)
    assert acc == pytest.approx(93.8) and epoch == 17
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=0, atol=0)


def test_checkpoint_missing_raises(engine, tmp_path):
    state = engine.init_state(jax.random.PRNGKey(0))
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path / "nope"), state)


def test_multi_step_dispatch_matches_per_step(tmp_path):
    """steps_per_dispatch folds k steps into one lax.scan program; the
    trajectory (losses, accs, final params) must match per-step dispatch
    to numerical tolerance — same math, only the dispatch count changes.
    7 batches with k=3 also exercises the short-tail fallback (3+3+1),
    and the val-loss assertions pin the FUSED EVAL path
    (compile_multi_eval drives validate() whenever k > 1)."""
    train, val = loaders(n=224, batch=32)  # 7 train batches/epoch
    mesh = make_mesh(MeshSpec(data=8))
    common = dict(
        epochs=2, base_lr=0.1, t_max=2, warmup_period=1, print_freq=0,
        log_dir=str(tmp_path / "log"), checkpoint_dir=str(tmp_path / "ck"),
        save_best=False,
    )
    results = {}
    for k in (1, 3):
        eng = DataParallelEngine(
            model=tiny_model(), optimizer=SGD(), mesh=mesh
        )
        t = Trainer(
            eng, train, val, TrainerConfig(steps_per_dispatch=k, **common),
            rng=jax.random.PRNGKey(0),
        )
        t.fit()
        results[k] = (t.history, t.state)
    for h1, h3 in zip(results[1][0], results[3][0]):
        assert h1["train"]["count"] == h3["train"]["count"] == 224
        np.testing.assert_allclose(
            h1["train"]["loss"], h3["train"]["loss"], rtol=1e-5
        )
        np.testing.assert_allclose(
            h1["train"]["acc1"], h3["train"]["acc1"], atol=1e-3
        )
        np.testing.assert_allclose(
            h1["val"]["loss"], h3["val"]["loss"], rtol=1e-5
        )
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(results[1][1].params),
        jax.tree_util.tree_leaves(results[3][1].params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6,
            err_msg=jax.tree_util.keystr(path),
        )


def test_compile_multi_eval_matches_per_batch(engine):
    """Direct pin of the fused-eval program: summed metrics over k
    stacked batches == accumulating k separate eval_step calls."""
    import jax.numpy as jnp

    from distributed_model_parallel_tpu.training.multistep import (
        compile_multi_eval,
    )

    ds = synthetic(num_examples=96, num_classes=4, image_size=8, seed=3)
    ts = engine.init_state(jax.random.PRNGKey(0))
    batches = [
        engine.shard_batch(ds.images[i * 32:(i + 1) * 32]
                           .astype(np.float32) / 255.0,
                           ds.labels[i * 32:(i + 1) * 32]
                           .astype(np.int32))
        for i in range(3)
    ]
    fused = compile_multi_eval(engine, 3)(ts, tuple(batches))
    want = None
    for b in batches:
        m = engine.eval_step(ts, *b)
        want = m if want is None else jax.tree_util.tree_map(
            jnp.add, want, m
        )
    for key in want:
        np.testing.assert_allclose(
            float(fused[key]), float(want[key]), rtol=1e-6,
            err_msg=key,
        )


def test_multi_step_dispatch_with_shard_map_engine(tmp_path):
    """The k-step scan must also trace shard_map-built steps (DDPEngine):
    explicit collectives inside a scan body, one dispatch per group."""
    from distributed_model_parallel_tpu.parallel.data_parallel import (
        DDPEngine,
    )

    train, val = loaders(n=128, batch=32)
    mesh = make_mesh(MeshSpec(data=8))
    eng = DDPEngine(model=tiny_model(), optimizer=SGD(), mesh=mesh)
    cfg = TrainerConfig(
        epochs=2, base_lr=0.1, t_max=2, warmup_period=1, print_freq=0,
        log_dir=str(tmp_path / "log"), checkpoint_dir=str(tmp_path / "ck"),
        save_best=False, steps_per_dispatch=2,
    )
    t = Trainer(eng, train, val, cfg, rng=jax.random.PRNGKey(0))
    result = t.fit()
    hist = result["history"]
    assert hist[0]["train"]["count"] == 128
    assert hist[-1]["train"]["loss"] < hist[0]["train"]["loss"]


def test_device_normalize_trainer_matches_host_normalize(tmp_path):
    """The uint8-transfer + on-device-normalize path must follow the same
    trajectory as host-side normalization: same augment draws (keyed RNG),
    same normalize math, only the placement of the arithmetic moves."""
    from distributed_model_parallel_tpu.data.datasets import (
        CIFAR10_MEAN,
        CIFAR10_STD,
    )
    from distributed_model_parallel_tpu.data.loader import device_normalizer

    ds = synthetic(num_examples=128, num_classes=4, image_size=8, seed=0)
    mesh = make_mesh(MeshSpec(data=8))
    common = dict(
        epochs=1, base_lr=0.1, t_max=1, warmup_period=1, print_freq=0,
        log_dir=str(tmp_path / "log"), checkpoint_dir=str(tmp_path / "ck"),
        save_best=False,
    )
    histories = {}
    for dev_norm in (False, True):
        loader_kw = dict(
            batch_size=32, shuffle=True, augment=True,
            mean=CIFAR10_MEAN, std=CIFAR10_STD, seed=0, use_native=False,
        )
        eng = DataParallelEngine(
            model=tiny_model(), optimizer=SGD(), mesh=mesh,
            input_transform=(
                device_normalizer(CIFAR10_MEAN, CIFAR10_STD)
                if dev_norm else None
            ),
        )
        train = Loader(ds, device_normalize=dev_norm, **loader_kw)
        val = Loader(
            ds, batch_size=32, shuffle=False, augment=False,
            mean=CIFAR10_MEAN, std=CIFAR10_STD,
            device_normalize=dev_norm, use_native=False,
        )
        t = Trainer(eng, train, val, TrainerConfig(**common),
                    rng=jax.random.PRNGKey(0))
        t.fit()
        histories[dev_norm] = t.history
    h_host, h_dev = histories[False][0], histories[True][0]
    np.testing.assert_allclose(
        h_host["train"]["loss"], h_dev["train"]["loss"], rtol=1e-5
    )
    np.testing.assert_allclose(
        h_host["val"]["loss"], h_dev["val"]["loss"], rtol=1e-5
    )
    np.testing.assert_allclose(
        h_host["val"]["acc1"], h_dev["val"]["acc1"], atol=1e-3
    )


def test_generator_loader_without_len(engine, capsys):
    """Regression: `train_epoch` must drive a plain generator loader (no
    `__len__`, no `set_epoch`) end-to-end, including the progress print —
    which once called `len(self.train_loader)` unconditionally and
    crashed on exactly this loader shape. The unknown total renders as
    '?'."""
    ds = synthetic(num_examples=128, num_classes=4, image_size=8, seed=0)

    def gen_loader():
        for i in range(4):
            lo = i * 32
            yield (
                ds.images[lo:lo + 32].astype(np.float32) / 255.0,
                ds.labels[lo:lo + 32].astype(np.int32),
            )

    cfg = TrainerConfig(
        epochs=1, base_lr=0.1, t_max=1, warmup_period=1, print_freq=2,
        save_best=False,
    )
    trainer = Trainer(engine, gen_loader(), None, cfg,
                      rng=jax.random.PRNGKey(0))
    stats = trainer.train_epoch(0)
    assert stats.count == 128
    out = capsys.readouterr().out
    assert "/?]" in out  # progress line printed with unknown total


def test_generator_loader_with_fused_dispatch(engine):
    """The same generator loader under steps_per_dispatch > 1: grouping
    pulls from a bare iterator, the short tail (4 batches, k=3) falls
    back to per-step dispatch, and the one-deep prefetch never double
    consumes."""
    ds = synthetic(num_examples=128, num_classes=4, image_size=8, seed=0)

    def gen_loader():
        for i in range(4):
            lo = i * 32
            yield (
                ds.images[lo:lo + 32].astype(np.float32) / 255.0,
                ds.labels[lo:lo + 32].astype(np.int32),
            )

    cfg = TrainerConfig(
        epochs=1, base_lr=0.1, t_max=1, warmup_period=1, print_freq=0,
        save_best=False, steps_per_dispatch=3,
    )
    trainer = Trainer(engine, gen_loader(), None, cfg,
                      rng=jax.random.PRNGKey(0))
    stats = trainer.train_epoch(0)
    assert stats.count == 128


def test_resume_continues_from_epoch(engine, tmp_path):
    train, val = loaders(n=128)
    common = dict(
        base_lr=0.05,
        t_max=4,
        warmup_period=1,
        print_freq=0,
        log_dir=str(tmp_path / "log"),
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    t1 = Trainer(engine, train, val, TrainerConfig(epochs=2, **common),
                 rng=jax.random.PRNGKey(0))
    t1.fit()
    assert latest_exists(str(tmp_path / "ckpt"))

    # Resume with a *fresh* engine instance: `--resume` semantics
    # (`data_parallel.py:80-87`): state, best_acc, start_epoch restored.
    mesh = make_mesh(MeshSpec(data=8))
    engine2 = DataParallelEngine(model=tiny_model(), optimizer=SGD(), mesh=mesh)
    t2 = Trainer(engine2, train, val,
                 TrainerConfig(epochs=4, resume=True, **common),
                 rng=jax.random.PRNGKey(9))
    assert t2.start_epoch >= 1
    assert t2.best_acc == pytest.approx(t1.best_acc)
    result = t2.fit()
    assert result["best_acc"] >= t1.best_acc


# ------------------------------------------ sharded / async checkpoints


def test_trainer_sharded_format_saves_and_resumes(engine, tmp_path):
    """checkpoint_format='sharded' writes manifests instead of .npz and
    resume restores through the unified reader (checkpointing/)."""
    from distributed_model_parallel_tpu.checkpointing import (
        manifest_exists,
    )

    train, val = loaders(n=128)
    common = dict(
        base_lr=0.05, t_max=3, warmup_period=1, print_freq=0,
        log_dir=str(tmp_path / "log"),
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_format="sharded",
        save_last=True,
    )
    t1 = Trainer(engine, train, val, TrainerConfig(epochs=2, **common),
                 rng=jax.random.PRNGKey(0))
    t1.fit()
    assert manifest_exists(str(tmp_path / "ckpt"), "last")
    assert not os.path.isfile(tmp_path / "ckpt" / "last.npz")
    final = jax.tree_util.tree_map(
        lambda x: np.asarray(x), jax.device_get(t1.state)
    )

    mesh = make_mesh(MeshSpec(data=8))
    engine2 = DataParallelEngine(
        model=tiny_model(), optimizer=SGD(), mesh=mesh
    )
    t2 = Trainer(engine2, train, val,
                 TrainerConfig(epochs=4, resume=True, **common),
                 rng=jax.random.PRNGKey(9))
    assert t2.start_epoch == 2
    assert t2.best_acc == pytest.approx(t1.best_acc)
    for a, b in zip(jax.tree_util.tree_leaves(final),
                    jax.tree_util.tree_leaves(jax.device_get(t2.state))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trainer_async_write_error_surfaces_at_fit_exit(
    engine, tmp_path, monkeypatch
):
    """A failed background write must fail fit(), not vanish."""
    from distributed_model_parallel_tpu.checkpointing import (
        writer as writer_mod,
    )

    def crashing(path, arrays):
        raise OSError("no space left on device")

    monkeypatch.setattr(writer_mod, "_write_shard", crashing)
    train, val = loaders(n=64)
    cfg = TrainerConfig(
        epochs=1, base_lr=0.05, t_max=1, warmup_period=1, print_freq=0,
        log_dir=str(tmp_path / "log"),
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_format="sharded", async_save=True,
        save_best=False, save_last=True,
    )
    t = Trainer(engine, train, val, cfg, rng=jax.random.PRNGKey(0))
    with pytest.raises(OSError, match="no space left"):
        t.fit()


def test_trainer_async_requires_sharded_format(engine, tmp_path):
    train, val = loaders(n=64)
    cfg = TrainerConfig(
        epochs=1, print_freq=0, checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_format="legacy", async_save=True,
    )
    with pytest.raises(ValueError, match="async_save"):
        Trainer(engine, train, val, cfg, rng=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="checkpoint_format"):
        Trainer(engine, train, val,
                TrainerConfig(checkpoint_format="zip"),
                rng=jax.random.PRNGKey(0))


def test_trainer_async_drains_writes_when_fit_aborts(
    engine, tmp_path, monkeypatch
):
    """fit() dying mid-epoch (the elastic restart path) must DRAIN
    in-flight background writes before the exception propagates — the
    supervisor reads the checkpoint directory immediately after, and a
    half-committed save would hand it yesterday's (or no) manifest."""
    import time as _time

    from distributed_model_parallel_tpu.checkpointing import (
        manifest_exists,
        writer as writer_mod,
    )
    from distributed_model_parallel_tpu.training.checkpoint import (
        checkpoint_epoch,
    )

    real = writer_mod._write_shard

    def slow(path, arrays):
        _time.sleep(0.4)  # force the abort to race the write
        real(path, arrays)

    monkeypatch.setattr(writer_mod, "_write_shard", slow)

    class DiesInEpoch1:
        def __init__(self, inner):
            self.inner = inner
            self.calls = 0

        def __getattr__(self, name):
            return getattr(self.inner, name)

        def train_step(self, *args):
            self.calls += 1
            if self.calls == 5:  # 4 steps/epoch: dies in epoch 1
                raise RuntimeError("preempted")
            return self.inner.train_step(*args)

    train, val = loaders(n=128)
    cfg = TrainerConfig(
        epochs=3, base_lr=0.05, t_max=3, warmup_period=1, print_freq=0,
        log_dir=str(tmp_path / "log"),
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_format="sharded", async_save=True,
        save_best=False, save_last=True,
    )
    t = Trainer(DiesInEpoch1(engine), train, val, cfg,
                rng=jax.random.PRNGKey(0))
    with pytest.raises(RuntimeError, match="preempted"):
        t.fit()
    # Epoch 0's save is fully committed despite the slow writer.
    assert manifest_exists(str(tmp_path / "ckpt"), "last")
    assert checkpoint_epoch(str(tmp_path / "ckpt"), "last") == 0
