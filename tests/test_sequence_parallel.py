"""Ring attention + Ulysses sequence-parallelism tests (8-device mesh).

Correctness bar: sequence-sharded attention must equal the unsharded
`dot_product_attention` — forward AND gradients — because both are exact
rearrangements, not approximations.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from distributed_model_parallel_tpu.runtime.compat import shard_map
from jax.sharding import PartitionSpec as P

from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.models.transformer import encoder_layer
from distributed_model_parallel_tpu.ops.attention import (
    dot_product_attention,
)
from distributed_model_parallel_tpu.ops.ring_attention import (
    ring_attention,
    ulysses_attention,
)
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh

B, T, H, DH = 2, 16, 4, 8
SP = 4  # 'seq' axis size


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(MeshSpec(data=2, seq=SP))


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(
        rng.randn(B, T, H, DH).astype(np.float32), dtype
    )
    q, k, v = mk(), mk(), mk()
    mask = jnp.asarray(rng.rand(B, T) > 0.2)
    mask = mask.at[:, 0].set(True)  # at least one valid key per row
    return q, k, v, mask


def _sharded_attn(attn_fn, mesh):
    spec = P(None, ("seq",))
    return jax.jit(
        shard_map(
            partial(attn_fn, axis_name="seq"),
            mesh=mesh,
            in_specs=(spec, spec, spec, P(None, ("seq",))),
            out_specs=spec,
            check_vma=False,
        )
    )


@pytest.mark.parametrize("attn_fn", [ring_attention, ulysses_attention])
def test_forward_matches_full_attention(sp_mesh, attn_fn):
    q, k, v, mask = _qkv()
    want = dot_product_attention(q, k, v, mask)
    got = _sharded_attn(attn_fn, sp_mesh)(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("attn_fn", [ring_attention, ulysses_attention])
def test_gradients_match_full_attention(sp_mesh, attn_fn, causal):
    """Cotangents cross shards through the reversed ppermutes /
    all-to-alls; the grads wrt q, k, v must match the dense reference —
    with and without the causal block predicate."""
    q, k, v, mask = _qkv(seed=3)
    spec = P(None, ("seq",))
    sharded = jax.jit(
        shard_map(
            partial(attn_fn, axis_name="seq", causal=causal),
            mesh=sp_mesh,
            in_specs=(spec, spec, spec, P(None, ("seq",))),
            out_specs=spec,
            check_vma=False,
        )
    )

    def loss_sharded(q, k, v):
        return jnp.sum(jnp.square(sharded(q, k, v, mask)))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.square(
            dot_product_attention(q, k, v, mask, causal=causal)
        ))

    got = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=2e-4, atol=2e-5,
            err_msg=f"grad wrt {name}",
        )


def test_ring_bf16_roundtrip(sp_mesh):
    """bf16 inputs: accumulate in f32, return bf16, close to the dense
    bf16 reference."""
    q, k, v, mask = _qkv(seed=5, dtype=jnp.bfloat16)
    want = dot_product_attention(q, k, v, mask)
    got = _sharded_attn(ring_attention, sp_mesh)(q, k, v, mask)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_encoder_stack_runs_sequence_parallel(sp_mesh):
    """A 2-layer transformer encoder stack running fully seq-sharded with
    ring attention == the same stack unsharded: sequence parallelism is a
    layout choice, invisible to the math. (LayerNorm/FFN are per-token,
    so only attention needs the ring.)"""
    dim, heads, ffn = 32, 4, 64
    stack_ring = L.sequential(
        encoder_layer(dim, heads, ffn, attention_fn=partial(
            ring_attention, axis_name="seq")),
        encoder_layer(dim, heads, ffn, attention_fn=partial(
            ring_attention, axis_name="seq")),
    )
    stack_dense = L.sequential(
        encoder_layer(dim, heads, ffn),
        encoder_layer(dim, heads, ffn),
    )
    params, _ = stack_dense.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(7)
    hseq = jnp.asarray(rng.randn(B, T, dim).astype(np.float32))
    mask = jnp.asarray(rng.rand(B, T) > 0.2).at[:, 0].set(True)

    (want, _), _ = stack_dense.apply(
        params, {"0": {}, "1": {}}, (hseq, mask), L.Context()
    )

    @jax.jit
    @partial(
        shard_map,
        mesh=sp_mesh,
        in_specs=(P(), (P(None, ("seq",)), P(None, ("seq",)))),
        out_specs=P(None, ("seq",)),
        check_vma=False,
    )
    def sp_forward(params, x):
        (h, _), _ = stack_ring.apply(
            params, {"0": {}, "1": {}}, x, L.Context()
        )
        return h

    got = sp_forward(params, (hseq, mask))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


# ---------------------------------------------------------------------------
# SequenceParallelEngine: full TRAINING with 'seq'-sharded activations.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
def test_sequence_parallel_engine_matches_dense_dp(sp_mesh, attention):
    """Training with activations sharded T/4 over 'seq' must follow the
    SAME trajectory as dense 8-way data parallelism: context parallelism
    is a memory layout, not a different optimizer."""
    from distributed_model_parallel_tpu.models.bert import (
        BertConfig,
        bert_for_classification,
    )
    from distributed_model_parallel_tpu.parallel.data_parallel import (
        DataParallelEngine,
    )
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        SequenceParallelEngine,
    )
    from distributed_model_parallel_tpu.training.optim import SGD

    # One encoder layer: halves the two CPU-mesh compiles; multi-layer
    # composition under 'seq' sharding is covered by the two-layer
    # stack forward test above.
    cfg = BertConfig(
        vocab_size=67, hidden_size=32, num_layers=1, num_heads=4,
        intermediate_size=64, max_position=T, dropout_rate=0.0,
    )
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 67, size=(8, T)).astype(np.int32)
    ids[:, -3:] = 0  # pad tail
    labels = rng.randint(0, 4, size=(8,)).astype(np.int32)

    sp = SequenceParallelEngine(
        cfg, 4, SGD(), sp_mesh, attention=attention, donate=False
    )
    ts_sp = sp.init_state(jax.random.PRNGKey(0))
    ids_sp, labels_sp = sp.shard_batch(ids, labels)

    dense_mesh = make_mesh(MeshSpec(data=8))
    dp = DataParallelEngine(
        bert_for_classification(4, cfg), SGD(), dense_mesh, donate=False
    )
    ts_dp = dp.init_state(jax.random.PRNGKey(0))
    ids_dp, labels_dp = dp.shard_batch(ids, labels)

    for step in range(3):
        ts_sp, m_sp = sp.train_step(
            ts_sp, ids_sp, labels_sp, jnp.float32(0.05)
        )
        ts_dp, m_dp = dp.train_step(
            ts_dp, ids_dp, labels_dp, jnp.float32(0.05)
        )
        np.testing.assert_allclose(
            float(m_sp["loss_sum"]), float(m_dp["loss_sum"]),
            rtol=1e-4, err_msg=f"step {step} loss",
        )
        np.testing.assert_allclose(
            float(m_sp["correct1"]), float(m_dp["correct1"]), atol=0.5,
        )
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(ts_dp.params),
        jax.tree_util.tree_leaves(ts_sp.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=jax.tree_util.keystr(path),
        )


def test_sequence_parallel_eval_and_checkpoint_interop(sp_mesh):
    """Eval path works, and the param pytree is structurally identical to
    the dense BERT's (checkpoints/transplants interoperate)."""
    from distributed_model_parallel_tpu.models.bert import (
        BertConfig,
        bert_for_classification,
    )
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        SequenceParallelEngine,
    )
    from distributed_model_parallel_tpu.training.optim import SGD

    cfg = BertConfig(
        vocab_size=67, hidden_size=32, num_layers=1, num_heads=4,
        intermediate_size=64, max_position=T, dropout_rate=0.0,
    )
    sp = SequenceParallelEngine(cfg, 4, SGD(), sp_mesh, donate=False)
    ts = sp.init_state(jax.random.PRNGKey(1))
    dense_params, _ = bert_for_classification(4, cfg).init(
        jax.random.PRNGKey(1)
    )
    assert (
        jax.tree_util.tree_structure(ts.params)
        == jax.tree_util.tree_structure(dense_params)
    )
    rng = np.random.RandomState(1)
    ids = rng.randint(1, 67, size=(8, T)).astype(np.int32)
    labels = rng.randint(0, 4, size=(8,)).astype(np.int32)
    m = sp.eval_step(ts, *sp.shard_batch(ids, labels))
    assert float(m["count"]) == 8
    assert np.isfinite(float(m["loss_sum"]))


def test_shard_batch_rejects_overlong_sequences(sp_mesh):
    """Both SP engines' forward passes slice the position table with
    dynamic_slice, which CLAMPS out-of-range starts — so a T beyond
    max_position would silently reuse the last position rows on later
    'seq' shards. shard_batch must refuse instead; T == max_position is
    the boundary and must pass."""
    from distributed_model_parallel_tpu.models.bert import BertConfig
    from distributed_model_parallel_tpu.models.gpt import GPTConfig
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        CausalLMSequenceParallelEngine,
        SequenceParallelEngine,
    )
    from distributed_model_parallel_tpu.training.optim import SGD

    bert_cfg = BertConfig(
        vocab_size=67, hidden_size=32, num_layers=1, num_heads=4,
        intermediate_size=64, max_position=T, dropout_rate=0.0,
    )
    sp = SequenceParallelEngine(bert_cfg, 4, SGD(), sp_mesh, donate=False)
    rng = np.random.RandomState(0)
    labels = rng.randint(0, 4, size=(8,)).astype(np.int32)
    ok = rng.randint(1, 67, size=(8, T)).astype(np.int32)
    sp.shard_batch(ok, labels)  # boundary length passes
    too_long = rng.randint(1, 67, size=(8, 2 * T)).astype(np.int32)
    with pytest.raises(ValueError, match="max_position"):
        sp.shard_batch(too_long, labels)

    gpt_cfg = GPTConfig(
        vocab_size=61, dim=32, num_layers=1, num_heads=4, ffn_dim=64,
        max_position=T, dropout_rate=0.0,
    )
    lm = CausalLMSequenceParallelEngine(gpt_cfg, SGD(), sp_mesh, donate=False)
    lm.shard_batch(rng.randint(1, 61, size=(8, T)).astype(np.int32))
    with pytest.raises(ValueError, match="max_position"):
        lm.shard_batch(rng.randint(1, 61, size=(8, 2 * T)).astype(np.int32))


# ---------------------------------------------------------------------------
# Causal attention (decoder-style) across all attention implementations.
# ---------------------------------------------------------------------------


def test_causal_dense_reference_is_triangular():
    """Numpy ground truth: each query only attends to keys <= its
    position."""
    q, k, v, _ = _qkv(seed=9)
    out = dot_product_attention(q, k, v, causal=True)
    # Query 0 can only see key 0: its output must equal v[:, 0] exactly.
    np.testing.assert_allclose(
        np.asarray(out[:, 0]), np.asarray(v[:, 0]), rtol=1e-6
    )
    # And changing a FUTURE key must not change past outputs.
    v2 = v.at[:, -1].set(0.0)
    out2 = dot_product_attention(q, k, v2, causal=True)
    np.testing.assert_allclose(
        np.asarray(out[:, :-1]), np.asarray(out2[:, :-1]), rtol=1e-6
    )


@pytest.mark.parametrize("attn_fn", [ring_attention, ulysses_attention])
def test_causal_sharded_matches_dense(sp_mesh, attn_fn):
    """Causality with global positions survives sequence sharding: the
    ring's block-index predicate == the dense triangle."""
    q, k, v, mask = _qkv(seed=10)
    want = dot_product_attention(q, k, v, mask, causal=True)
    spec = P(None, ("seq",))
    sharded = jax.jit(
        shard_map(
            partial(attn_fn, axis_name="seq", causal=True),
            mesh=sp_mesh,
            in_specs=(spec, spec, spec, P(None, ("seq",))),
            out_specs=spec,
            check_vma=False,
        )
    )
    got = sharded(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


def test_causal_flash_matches_dense():
    from distributed_model_parallel_tpu.ops.pallas_attention import (
        flash_attention,
    )

    rng = np.random.RandomState(11)
    t = 128
    mk = lambda: jnp.asarray(rng.randn(2, t, 4, 16).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    mask = jnp.asarray(rng.rand(2, t) > 0.2).at[:, 0].set(True)
    want = dot_product_attention(q, k, v, mask, causal=True)
    got = flash_attention(q, k, v, mask, causal=True, block_q=32, block_k=32)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )
    # grads through the causal custom_vjp
    g1 = jax.grad(lambda q: jnp.sum(jnp.square(
        flash_attention(q, k, v, mask, causal=True, block_q=32, block_k=32)
    )))(q)
    g2 = jax.grad(lambda q: jnp.sum(jnp.square(
        dot_product_attention(q, k, v, mask, causal=True)
    )))(q)
    np.testing.assert_allclose(
        np.asarray(g1), np.asarray(g2), rtol=2e-4, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_full_attention(sp_mesh, causal):
    """ring_flash_attention (per-hop flash kernels + LSE merge) must
    equal dense attention — forward and all three gradients, with a
    padding mask, causal and not. On the CI mesh the tiny blocks take
    the dense per-hop fallback; the merge/rotation logic is identical."""
    from distributed_model_parallel_tpu.ops.ring_attention import (
        ring_flash_attention,
    )

    q, k, v, mask = _qkv(seed=21)
    spec = P(None, ("seq",))
    sharded = jax.jit(
        shard_map(
            partial(ring_flash_attention, axis_name="seq", causal=causal),
            mesh=sp_mesh,
            in_specs=(spec, spec, spec, P(None, ("seq",))),
            out_specs=spec,
            check_vma=False,
        )
    )
    want = dot_product_attention(q, k, v, mask, causal=causal)
    got = sharded(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )

    def loss_sharded(q, k, v):
        return jnp.sum(jnp.square(sharded(q, k, v, mask)))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.square(
            dot_product_attention(q, k, v, mask, causal=causal)
        ))

    got_g = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    want_g = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gg, wg, name in zip(got_g, want_g, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(wg), rtol=2e-4, atol=2e-5,
            err_msg=f"grad wrt {name} (causal={causal})",
        )


def test_ring_flash_no_mask(sp_mesh):
    from distributed_model_parallel_tpu.ops.ring_attention import (
        ring_flash_attention,
    )

    q, k, v, _ = _qkv(seed=22)
    spec = P(None, ("seq",))
    sharded = jax.jit(
        shard_map(
            partial(ring_flash_attention, axis_name="seq"),
            mesh=sp_mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
    )
    want = dot_product_attention(q, k, v)
    got = sharded(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )
    g = jax.grad(
        lambda k: jnp.sum(jnp.square(sharded(q, k, v)))
    )(k)
    gw = jax.grad(
        lambda k: jnp.sum(jnp.square(dot_product_attention(q, k, v)))
    )(k)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(gw), rtol=2e-4, atol=2e-5
    )


@pytest.mark.slow
def test_ring_flash_kernel_path_multihop(sp_mesh):
    """Shapes large enough that every hop runs the PALLAS kernels
    (interpret mode here): the LSE merge and the rotating dk/dv
    delivery are exercised with the production per-hop core, not the
    dense fallback. `slow` (tier-1 budget); tier-1 twins:
    test_causal_flash_matches_dense + the ring_flash cases of the
    forward/gradient parity sweeps above (same merge math on the
    fallback core)."""
    from distributed_model_parallel_tpu.ops.ring_attention import (
        ring_flash_attention,
    )

    b, t, h, dh = 1, 512, 2, 16  # Tl = 128 per shard -> kernel path
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(b, t, h, dh).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    # A mask exercises the kernel path's per-hop mask rotation, the
    # whole-mask BlockSpec, and the +/-inf LSE sentinel conversion.
    mask = jnp.asarray(rng.rand(b, t) > 0.2).at[:, 0].set(True)
    spec = P(None, ("seq",))
    f = jax.jit(shard_map(
        partial(ring_flash_attention, axis_name="seq", causal=True),
        mesh=sp_mesh,
        in_specs=(spec, spec, spec, P(None, ("seq",))),
        out_specs=spec,
        check_vma=False,
    ))
    want = dot_product_attention(q, k, v, mask, causal=True)
    np.testing.assert_allclose(
        np.asarray(f(q, k, v, mask)), np.asarray(want),
        rtol=2e-5, atol=2e-5,
    )
    g = jax.grad(lambda k: jnp.sum(f(q, k, v, mask) ** 2))(k)
    gw = jax.grad(
        lambda k: jnp.sum(
            dot_product_attention(q, k, v, mask, causal=True) ** 2
        )
    )(k)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(gw), rtol=2e-4, atol=2e-5
    )


def test_lm_engine_ring_flash_trains():
    """attention='ring_flash' drops into the causal-LM engine."""
    from distributed_model_parallel_tpu.models.gpt import GPTConfig
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        CausalLMSequenceParallelEngine,
    )
    from distributed_model_parallel_tpu.training.optim import SGD

    cfg = GPTConfig(
        vocab_size=61, dim=32, num_layers=1, num_heads=4, ffn_dim=64,
        max_position=16, dropout_rate=0.0,
    )
    mesh = make_mesh(MeshSpec(data=2, seq=4))
    eng = CausalLMSequenceParallelEngine(
        cfg, SGD(), mesh, attention="ring_flash", donate=False
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    ids = rng.randint(1, 61, size=(8, 16)).astype(np.int32)
    i, t = eng.shard_batch(ids)
    losses = []
    for _ in range(4):
        ts, m = eng.train_step(ts, i, t, jnp.float32(0.3))
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    assert losses[-1] < losses[0]


def test_ulysses_flash_matches_dense(sp_mesh):
    """Ulysses with the Pallas kernel as its local core == dense
    attention, forward and gradients (kernel-viable local length)."""
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        ATTENTION,
    )

    b, t, h, dh = 1, 128, 4, 16
    rng = np.random.RandomState(3)
    mk = lambda: jnp.asarray(rng.randn(b, t, h, dh).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    mask = jnp.asarray(rng.rand(b, t) > 0.2).at[:, 0].set(True)
    spec = P(None, ("seq",))
    f = jax.jit(shard_map(
        partial(ATTENTION["ulysses_flash"], axis_name="seq", causal=True),
        mesh=sp_mesh,
        in_specs=(spec, spec, spec, P(None, ("seq",))),
        out_specs=spec,
        check_vma=False,
    ))
    want = dot_product_attention(q, k, v, mask, causal=True)
    np.testing.assert_allclose(
        np.asarray(f(q, k, v, mask)), np.asarray(want),
        rtol=2e-5, atol=2e-5,
    )
    g = jax.grad(lambda v: jnp.sum(f(q, k, v, mask) ** 2))(v)
    gw = jax.grad(
        lambda v: jnp.sum(
            dot_product_attention(q, k, v, mask, causal=True) ** 2
        )
    )(v)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(gw), rtol=2e-4, atol=2e-5
    )
