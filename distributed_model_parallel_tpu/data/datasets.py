"""Dataset collection — parity with the reference's `DatasetCollection`
(`code/distributed_training/dataset/dataset_collection.py:28-69`), which
dispatches on a string type: 'Imagenet' (ImageFolder), 'CUB200'
(pandas-joined custom set), 'CIFAR10', 'Place365'.

TPU-era redesign:
* Datasets yield NumPy arrays (NHWC uint8 + int labels); all device
  placement is the loader's job, so the input path never routes through a
  "device 0" (the reference's known DP bottleneck, `Readme.md:15`).
* A deterministic `'Synthetic'` type is first-class so tests and CI never
  download anything (the reference downloads CIFAR-10 on every rank —
  `model_parallel.py:89-97`).
* CIFAR-10 reads the standard binary batches from disk when present.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import tarfile
from typing import Optional, Tuple

import numpy as np

# Channel statistics used by the reference transforms
# (`data_parallel.py:31-41` for CIFAR, `utils.py:13-14` for ImageNet-style).
CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2023, 0.1994, 0.2010], np.float32)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


@dataclasses.dataclass
class ArrayDataset:
    """In-memory dataset: images NHWC uint8 (or, for `kind='text'`,
    int32 token ids (N, T) still under the `images` field — the Loader
    treats text batches as raw pass-through), labels int64."""

    images: np.ndarray
    labels: np.ndarray
    num_classes: int
    kind: str = "image"  # 'image' | 'text' — drives Loader defaults

    def __len__(self) -> int:
        return len(self.labels)

    def gather(self, idx) -> Tuple[np.ndarray, np.ndarray]:
        return self.images[idx], self.labels[idx]


@dataclasses.dataclass
class LazyImageFolder:
    """Disk-backed ImageFolder split: holds paths + labels, decodes only
    the indices a batch asks for (`gather`). This is what lets the input
    pipeline hold ImageNet-scale trees without decoding the world up
    front; combined with the Loader's prefetch thread the decode overlaps
    the device step."""

    paths: list
    labels: np.ndarray
    num_classes: int
    image_size: int = 224

    def __len__(self) -> int:
        return len(self.labels)

    def gather(self, idx) -> Tuple[np.ndarray, np.ndarray]:
        from PIL import Image  # lazy; PIL ships with the torch stack

        images = np.empty(
            (len(idx), self.image_size, self.image_size, 3), np.uint8
        )
        for row, i in enumerate(np.asarray(idx)):
            with Image.open(self.paths[i]) as im:
                images[row] = np.asarray(
                    im.convert("RGB").resize(
                        (self.image_size, self.image_size)
                    ),
                    np.uint8,
                )
        return images, self.labels[idx]


def synthetic(
    num_examples: int = 2048,
    image_size: int = 32,
    num_classes: int = 10,
    seed: int = 0,
) -> ArrayDataset:
    """Deterministic fake data with learnable class structure (each class
    has a distinct mean image) so convergence smoke tests are meaningful.

    The class means are drawn from a FIXED rng independent of `seed`, so
    train (seed=1) and val (seed=2) splits share one task and val accuracy
    is a real generalization signal."""
    class_rng = np.random.RandomState(1234)
    class_means = class_rng.randint(0, 256, size=(num_classes, 1, 1, 3))
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=(num_examples,))
    noise = rng.randint(-40, 40, size=(num_examples, image_size, image_size, 3))
    images = np.clip(class_means[labels] + noise, 0, 255).astype(np.uint8)
    return ArrayDataset(images, labels.astype(np.int64), num_classes)


def synthetic_textures(
    num_examples: int = 2048,
    image_size: int = 32,
    num_classes: int = 10,
    seed: int = 0,
) -> ArrayDataset:
    """Procedural-texture classification with GENUINE generalization
    structure: each class is a texture FAMILY (two sinusoidal gratings
    with class-specific orientations/frequencies), and every sample
    draws fresh phases, amplitudes, a random spatial shift and pixel
    noise. Unlike `synthetic` (fixed class-mean images, which a
    2.3M-param model simply memorizes — RESULTS §1c), no two samples
    share pixels, so val accuracy measures the learned texture
    statistics, not recall.

    Class parameters come from a FIXED rng independent of `seed`:
    train/val splits with different seeds share one task."""
    class_rng = np.random.RandomState(977)
    thetas = class_rng.uniform(0, np.pi, size=(num_classes, 2))
    freqs = class_rng.uniform(2.0, 6.0, size=(num_classes, 2))
    colors = class_rng.uniform(0.3, 1.0, size=(num_classes, 2, 3))
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=(num_examples,))
    yy, xx = np.meshgrid(
        np.linspace(0, 2 * np.pi, image_size),
        np.linspace(0, 2 * np.pi, image_size),
        indexing="ij",
    )
    images = np.empty(
        (num_examples, image_size, image_size, 3), np.float32
    )
    # Float64 temporaries (waves, noise) are built per CHUNK so peak RAM
    # stays ~tens of MB at the 50k size instead of multi-GB. NumPy fills
    # arrays in draw order, so chunked draws are bit-identical to the
    # full-size draws this replaced.
    chunk = 4096
    for g in range(2):  # two gratings per class, summed
        phase = rng.uniform(0, 2 * np.pi, size=(num_examples, 1, 1))
        amp = rng.uniform(0.6, 1.4, size=(num_examples, 1, 1))
        for s in range(0, num_examples, chunk):
            sl = slice(s, min(s + chunk, num_examples))
            lab = labels[sl]
            th = thetas[lab, g][:, None, None]
            fr = freqs[lab, g][:, None, None]
            wave = amp[sl] * np.sin(
                fr * (np.cos(th) * xx[None] + np.sin(th) * yy[None])
                + phase[sl]
            )
            contrib = wave[..., None] * colors[lab, g][:, None, None, :]
            # f64 sum, cast on assignment — the rounding the original
            # full-array formulation produced.
            images[sl] = contrib if g == 0 else images[sl] + contrib
    # Heavy pixel noise keeps the task in the discriminating mid-range
    # (tinycnn reaches ~80-90% in a few epochs, not an instant 100%).
    for s in range(0, num_examples, chunk):
        sl = slice(s, min(s + chunk, num_examples))
        images[sl] += rng.normal(0.0, 1.2, size=images[sl].shape)
    lo, hi = -3.0, 3.0
    # In-place, same op order as `(clip(x)-lo)/(hi-lo)*255` — no extra
    # full-size f32 temporaries.
    np.clip(images, lo, hi, out=images)
    images -= lo
    images /= hi - lo
    images *= 255.0
    return ArrayDataset(
        images.astype(np.uint8), labels.astype(np.int64), num_classes
    )


def synthetic_text(
    num_examples: int = 2048,
    seq_len: int = 64,
    num_classes: int = 4,
    vocab_size: int = 512,
    seed: int = 0,
) -> ArrayDataset:
    """Deterministic text-CLASSIFICATION dataset: each class is its own
    first-order Markov chain over tokens [1, vocab) (0 stays reserved
    for padding — BERT's attention mask is `ids != 0`), so a model can
    classify by transition statistics — a real, learnable signal for the
    transformer-family engines (the text twin of `synthetic`'s
    class-mean images).

    Like `synthetic`, the per-class chains come from a FIXED rng
    independent of `seed`, so train/val splits with different seeds
    share one task and val accuracy measures generalization."""
    v = vocab_size - 1  # usable tokens 1..vocab-1
    class_rng = np.random.RandomState(4321)
    # Per-class transition logits with strong structure (peaked rows).
    trans = class_rng.dirichlet(
        np.full(v, 0.05), size=(num_classes, v)
    )  # (C, v, v) rows sum to 1
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=(num_examples,))
    ids = np.empty((num_examples, seq_len), np.int32)
    ids[:, 0] = rng.randint(0, v, size=num_examples)
    # Vectorized walk: one step for ALL sequences at a time via inverse-
    # CDF sampling against each row's class-specific transition row.
    cdf = np.cumsum(trans, axis=-1)  # (C, v, v)
    for t in range(1, seq_len):
        u = rng.rand(num_examples, 1)
        row_cdf = cdf[labels, ids[:, t - 1]]  # (N, v)
        # Clip: a float cumsum row can top out at 1-eps rather than 1.0,
        # and a u above it would index one past the table.
        ids[:, t] = np.minimum((u > row_cdf).sum(axis=1), v - 1)
    return ArrayDataset(
        ids + 1, labels.astype(np.int64), num_classes, kind="text"
    )


def _load_cifar10_batches(root: str) -> Optional[Tuple[np.ndarray, ...]]:
    """Read the python-version CIFAR-10 batches (cifar-10-batches-py) if the
    archive or extracted dir exists under `root`. No network access."""
    d = os.path.join(root, "cifar-10-batches-py")
    tar = os.path.join(root, "cifar-10-python.tar.gz")
    if not os.path.isdir(d) and os.path.isfile(tar):
        with tarfile.open(tar) as tf:
            tf.extractall(root, filter="data")
    if not os.path.isdir(d):
        return None

    def read(name):
        with open(os.path.join(d, name), "rb") as f:
            entry = pickle.load(f, encoding="bytes")
        x = entry[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        y = np.asarray(entry[b"labels"], np.int64)
        return x, y

    xs, ys = zip(*(read(f"data_batch_{i}") for i in range(1, 6)))
    xt, yt = read("test_batch")
    return np.concatenate(xs), np.concatenate(ys), xt, yt


def cifar10(root: str = "./data", *, fallback_synthetic: bool = True):
    """CIFAR-10 train/val pair (`dataset_collection.py:62-65`). Falls back
    to class-structured synthetic data when the files are absent so every
    entry point runs hermetically."""
    loaded = _load_cifar10_batches(root)
    if loaded is None:
        if not fallback_synthetic:
            raise FileNotFoundError(f"CIFAR-10 not found under {root}")
        return (
            synthetic(50_000, 32, 10, seed=1),
            synthetic(10_000, 32, 10, seed=2),
        )
    xtr, ytr, xte, yte = loaded
    return ArrayDataset(xtr, ytr, 10), ArrayDataset(xte, yte, 10)


_IMG_EXTS = {
    ".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp", ".ppm", ".pgm",
    ".tif", ".tiff",
}


def image_folder(root: str, split_dirs=("train", "val"), image_size: int = 224,
                 *, lazy: bool = True):
    """ImageFolder-style tree ('Imagenet'/'Place365' types,
    `dataset_collection.py:36-47,66-69`). `lazy=True` (default) returns
    `LazyImageFolder` splits that decode per batch on demand — the
    chip-rate path for large trees; `lazy=False` eagerly decodes into an
    in-memory `ArrayDataset` (handy for small fixtures/tests). Decoding
    uses torch's bundled PIL; the batched crop/flip/normalize hot loop is
    the C++ `native/` module either way."""
    out = []
    for split in split_dirs:
        base = os.path.join(root, split)
        classes = sorted(
            d for d in os.listdir(base)
            if os.path.isdir(os.path.join(base, d))
        )
        idx = {c: i for i, c in enumerate(classes)}
        paths, labels = [], []
        for c in classes:
            cdir = os.path.join(base, c)
            for fname in sorted(os.listdir(cdir)):
                # Extension filter (torchvision ImageFolder semantics):
                # a stray .DS_Store / checksum file must not become a
                # mid-epoch decode error hours into a lazy run.
                if os.path.splitext(fname)[1].lower() not in _IMG_EXTS:
                    continue
                paths.append(os.path.join(cdir, fname))
                labels.append(idx[c])
        ds = LazyImageFolder(
            paths, np.asarray(labels, np.int64), len(classes), image_size
        )
        if not lazy:
            images, lab = ds.gather(np.arange(len(ds)))
            ds = ArrayDataset(images, lab, ds.num_classes)
        out.append(ds)
    return tuple(out)


def cub200(root: str, image_size: int = 224):
    """CUB-200-2011 via its images.txt / train_test_split.txt /
    image_class_labels.txt metadata — same join the reference does with
    pandas (`dataset_collection.py:8-27`), without the pandas dependency."""
    from PIL import Image

    def read_table(name):
        with open(os.path.join(root, name)) as f:
            return [line.split() for line in f.read().splitlines() if line]

    paths = {int(i): p for i, p in read_table("images.txt")}
    is_train = {int(i): v == "1" for i, v in read_table("train_test_split.txt")}
    label = {int(i): int(l) - 1 for i, l in read_table("image_class_labels.txt")}

    splits = {True: ([], []), False: ([], [])}
    for i, rel in sorted(paths.items()):
        with Image.open(os.path.join(root, "images", rel)) as im:
            arr = np.asarray(
                im.convert("RGB").resize((image_size, image_size)), np.uint8
            )
        imgs, labs = splits[is_train[i]]
        imgs.append(arr)
        labs.append(label[i])
    train = ArrayDataset(
        np.stack(splits[True][0]), np.asarray(splits[True][1], np.int64), 200
    )
    val = ArrayDataset(
        np.stack(splits[False][0]), np.asarray(splits[False][1], np.int64), 200
    )
    return train, val


class DatasetCollection:
    """String-keyed factory with the reference's exact API shape:
    `DatasetCollection(type, path, compose_train, compose_val).init() ->
    (train, val)` (`dataset_collection.py:28-35`). Types: 'CIFAR10',
    'Imagenet', 'CUB200', 'Place365', plus 'Synthetic' and
    'SyntheticText' (token-id classification for the transformer
    family).

    `compose_train` / `compose_val` mirror the reference's
    caller-supplied torchvision Compose arguments: per-batch callables
    `(images, labels) -> (images, labels)` applied by the Loader INSTEAD
    of its built-in augment/normalize path (`Loader.transform`). Leave
    them None for the reference's default CIFAR transforms."""

    def __init__(self, dataset_type: str, dataset_path: str = "./data",
                 compose_train=None, compose_val=None,
                 image_size: int = 224):
        self.dataset_type = dataset_type
        self.dataset_path = dataset_path
        self.compose_train = compose_train
        self.compose_val = compose_val
        self.image_size = image_size

    def init(self):
        t = self.dataset_type
        if t == "CIFAR10":
            return cifar10(self.dataset_path)
        if t == "Synthetic":
            return synthetic(2048, 32, 10, seed=1), synthetic(512, 32, 10, seed=2)
        if t == "SyntheticText":
            return (
                synthetic_text(4096, 64, 4, seed=1),
                synthetic_text(1024, 64, 4, seed=2),
            )
        if t == "SyntheticTextures":
            return (
                synthetic_textures(50_000, 32, 10, seed=1),
                synthetic_textures(10_000, 32, 10, seed=2),
            )
        if t in ("Imagenet", "Place365"):
            return image_folder(self.dataset_path, image_size=self.image_size)
        if t == "CUB200":
            return cub200(self.dataset_path, image_size=self.image_size)
        raise ValueError(f"unknown dataset type {t!r}")
