"""Elastic restart tests: fail-fast + resume-from-checkpoint loop
(SURVEY.md §5 failure-detection row; VERDICT r2 'what's weak' #8)."""

import jax
import numpy as np
import pytest

from distributed_model_parallel_tpu.data.datasets import synthetic
from distributed_model_parallel_tpu.data.loader import Loader
from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn
from distributed_model_parallel_tpu.parallel.data_parallel import (
    DataParallelEngine,
)
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.checkpoint import latest_exists
from distributed_model_parallel_tpu.training.elastic import elastic_fit
from distributed_model_parallel_tpu.training.optim import SGD
from distributed_model_parallel_tpu.training.trainer import (
    Trainer,
    TrainerConfig,
)


class FlakyEngine:
    """Engine wrapper that dies once at a chosen train step — the
    single-controller stand-in for a lost host (whose collective error
    surfaces exactly like this: an exception out of train_step)."""

    def __init__(self, inner, fail_at_call: int):
        self.inner = inner
        self.fail_at_call = fail_at_call
        self.calls = 0
        self.already_failed = False

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def train_step(self, *args):
        self.calls += 1
        if not self.already_failed and self.calls == self.fail_at_call:
            self.already_failed = True
            raise RuntimeError("injected host failure")
        return self.inner.train_step(*args)


def _factory(tmp_path, engine, epochs=4):
    ds = synthetic(num_examples=128, num_classes=4, image_size=8, seed=0)
    trainers = []

    def make_trainer(restart: bool) -> Trainer:
        cfg = TrainerConfig(
            epochs=epochs, base_lr=0.05, t_max=epochs, warmup_period=1,
            print_freq=0,
            log_dir=str(tmp_path / "log"),
            checkpoint_dir=str(tmp_path / "ckpt"),
            resume=restart and latest_exists(str(tmp_path / "ckpt"), "last"),
            save_last=True,
        )
        train = Loader(ds, batch_size=32, shuffle=True, seed=0)
        val = Loader(ds, batch_size=32, shuffle=False)
        t = Trainer(engine, train, val, cfg, rng=jax.random.PRNGKey(0))
        trainers.append(t)
        return t

    return make_trainer, trainers


def test_elastic_restarts_from_last_checkpoint(tmp_path):
    mesh = make_mesh(MeshSpec(data=8))
    engine = FlakyEngine(
        DataParallelEngine(tiny_cnn(4), SGD(), mesh, donate=False),
        fail_at_call=7,  # dies in epoch 1 (4 steps/epoch)
    )
    make_trainer, trainers = _factory(tmp_path, engine)
    result = elastic_fit(make_trainer, max_restarts=2)

    assert len(trainers) == 2                # one restart
    assert trainers[0].start_epoch == 0
    # Epoch 0 completed + save_last ran before the injected failure, so
    # the restart resumes at epoch 1 — at most the failed epoch is lost.
    assert trainers[1].start_epoch == 1
    total_epochs = {h["epoch"] for h in result["history"]}
    assert total_epochs == {1, 2, 3}         # final attempt's epochs
    assert latest_exists(str(tmp_path / "ckpt"), "last")


def test_elastic_gives_up_after_budget(tmp_path):
    class AlwaysDies:
        def __init__(self, inner):
            self.inner = inner

        def __getattr__(self, name):
            return getattr(self.inner, name)

        def train_step(self, *args):
            raise RuntimeError("permanent failure")

    mesh = make_mesh(MeshSpec(data=8))
    engine = AlwaysDies(
        DataParallelEngine(tiny_cnn(4), SGD(), mesh, donate=False)
    )
    make_trainer, trainers = _factory(tmp_path, engine)
    with pytest.raises(RuntimeError, match="permanent failure"):
        elastic_fit(make_trainer, max_restarts=2, backoff_seconds=0.01)
    assert len(trainers) == 3  # initial + 2 restarts, then fail-fast


# ------------------------------------------- backoff schedule + summary


def test_backoff_schedule_exponential_with_cap():
    from distributed_model_parallel_tpu.training.elastic import (
        backoff_schedule,
    )

    assert [
        backoff_schedule(k, 1.0, 60.0) for k in (1, 2, 3, 4)
    ] == [1.0, 2.0, 4.0, 8.0]
    # The cap clamps, never amplifies.
    assert backoff_schedule(10, 1.0, 60.0) == 60.0
    assert backoff_schedule(1, 5.0, 2.0) == 2.0
    with pytest.raises(ValueError):
        backoff_schedule(0, 1.0, 60.0)


class _DiesNTimes:
    """Trainer stand-in: fit() raises `exc` the first `n` calls, then
    returns a minimal summary — no engine/mesh needed to test the
    supervisor's schedule."""

    def __init__(self, n, exc=RuntimeError):
        self.n = n
        self.exc = exc

    def fit(self):
        if self.n > 0:
            self.n -= 1
            raise self.exc(f"boom ({self.n} left)")
        return {"best_acc": 0.0, "history": []}


def test_elastic_backoff_sleeps_and_summary(monkeypatch):
    from distributed_model_parallel_tpu.training import elastic

    sleeps = []
    monkeypatch.setattr(elastic.time, "sleep", sleeps.append)
    box = _DiesNTimes(3, ValueError)
    result = elastic.elastic_fit(
        lambda resume: box,
        max_restarts=3,
        backoff_seconds=0.5,
        max_backoff_seconds=1.5,
        jitter=lambda attempt: 0.01 * attempt,
    )
    # Exponential 0.5, 1.0, then capped at 1.5 — plus the jitter hook.
    assert sleeps == pytest.approx([0.51, 1.02, 1.53])
    el = result["elastic"]
    assert el["attempts"] == 4
    assert [r["error_type"] for r in el["restarts"]] == ["ValueError"] * 3
    assert [r["attempt"] for r in el["restarts"]] == [1, 2, 3]
    assert [r["backoff_s"] for r in el["restarts"]] == pytest.approx(
        [0.51, 1.02, 1.53]
    )


def test_elastic_retry_on_narrowing(monkeypatch):
    """retry_on=(TypeError,) must NOT absorb a ValueError — it
    propagates immediately, zero restarts."""
    from distributed_model_parallel_tpu.training import elastic

    sleeps = []
    monkeypatch.setattr(elastic.time, "sleep", sleeps.append)
    calls = []

    def make_trainer(resume):
        calls.append(resume)
        return _DiesNTimes(5, ValueError)

    with pytest.raises(ValueError, match="boom"):
        elastic.elastic_fit(
            make_trainer, max_restarts=3, retry_on=(TypeError,),
        )
    assert calls == [False] and sleeps == []
    # ... while a matching type does retry.
    calls.clear()
    box = _DiesNTimes(1, TypeError)
    result = elastic.elastic_fit(
        lambda resume: (calls.append(resume), box)[1],
        max_restarts=3, retry_on=(TypeError,), backoff_seconds=0.0,
    )
    assert calls == [False, True]
    assert result["elastic"]["restarts"][0]["error_type"] == "TypeError"


# ----------------------------------------------------- elastic resize


def test_elastic_resize_restores_sharded_checkpoint_onto_bigger_mesh(
    tmp_path,
):
    """Genuine elasticity: an S=4 FSDP run dies after its first epoch's
    sharded save; the restart's `make_trainer(resume, topology)`
    receives the manifest's saved topology (data=4) and rebuilds onto
    the FULL 8-device mesh — the resharding restore places the state
    bit-exact (acceptance: S=4 -> S=8 through elastic_fit's resize
    path)."""
    from distributed_model_parallel_tpu.checkpointing import (
        restore_checkpoint,
    )
    from distributed_model_parallel_tpu.parallel.fsdp import FSDPEngine

    ds = synthetic(num_examples=128, num_classes=4, image_size=8, seed=0)
    ckdir = str(tmp_path / "ckpt")
    devs = jax.devices()
    topologies = []
    trainers = []
    restored_canonicals = []

    def build_engine(n_data):
        mesh = make_mesh(MeshSpec(data=n_data), devices=devs[:n_data])
        inner = FSDPEngine(
            tiny_cnn(4), SGD(), mesh, donate=False, min_shard_elems=64
        )
        return inner

    def make_trainer(restart, topology):
        topologies.append(topology)
        if not restart:
            engine = FlakyEngine(
                build_engine(4), fail_at_call=7,  # dies in epoch 1
            )
        else:
            # The preempted slice came back bigger: resize to all 8
            # devices; the restore reshards the S=4 state to fit.
            assert topology is not None
            assert topology["mesh_axes"]["data"] == 4
            engine = build_engine(8)
        cfg = TrainerConfig(
            epochs=3, base_lr=0.05, t_max=3, warmup_period=1,
            print_freq=0,
            log_dir=str(tmp_path / "log"),
            checkpoint_dir=ckdir,
            resume=restart and latest_exists(ckdir, "last"),
            save_last=True,
            checkpoint_format="sharded",
        )
        train = Loader(ds, batch_size=32, shuffle=True, seed=0)
        val = Loader(ds, batch_size=32, shuffle=False)
        t = Trainer(engine, train, val, cfg, rng=jax.random.PRNGKey(0))
        trainers.append(t)
        if restart:
            # Bit-exact reshard through the elastic path, checked at
            # restart time (before this trainer overwrites 'last' with
            # later epochs): what the S=8 trainer starts from equals
            # the S=4 checkpoint on disk, reassembled independently.
            started_from = jax.tree_util.tree_map(
                lambda x: np.asarray(x),
                jax.device_get(t._to_canonical(t.state)),
            )
            expected, _, _ = restore_checkpoint(
                ckdir, started_from, name="last"
            )
            for a, b in zip(
                jax.tree_util.tree_leaves(expected),
                jax.tree_util.tree_leaves(started_from),
            ):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            restored_canonicals.append(started_from)
        return t

    result = elastic_fit(
        make_trainer, max_restarts=2, backoff_seconds=0.01,
        checkpoint_dir=ckdir,
    )
    assert len(trainers) == 2
    assert topologies[0] is None  # first attempt: nothing saved yet
    assert trainers[1].start_epoch == 1  # lost at most the failed epoch
    assert {h["epoch"] for h in result["history"]} == {1, 2}
    assert result["elastic"]["restarts"][0]["error_type"] == "RuntimeError"
    assert restored_canonicals, "restart never verified the reshard"
