"""Decoder-only causal language model (GPT-1-style, post-LN).

The reference has no attention model at all; BERT covers the encoder
side of this framework's transformer capability, and this module covers
the decoder side — the consumer of `causal=True` attention
(`ops/attention.py`, `ops/ring_attention.py`, `ops/pallas_attention.py`
all accept it, so the same model runs dense, sequence-parallel, or on
the flash kernel by swapping `attention_fn`).

Shapes: int32 ids (B, T) -> logits (B, T, vocab). Training uses
`lm_loss` (next-token shift, padding-aware). The decoder block IS the
encoder block with a causal attention_fn — post-LN, like GPT-1; the
blocks reuse `models/transformer.py` wholesale, so TP's MEGATRON_RULES,
the pipeline stage splitter, AND the collective-matmul hook
(`layers.project`; chunked ppermute rings under
`collective_matmul=True`, `ops/collective_matmul.py`) apply to the
block stack unchanged.
(The classification engines' train loops expect (B, C) logits + integer
labels; LM training drives this model with `lm_loss` under plain
jit/grad — see tests/test_gpt.py for the data-parallel recipe.)
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp

from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.models.transformer import (
    AttentionFn,
    encoder_layer,
)
from distributed_model_parallel_tpu.ops.attention import (
    dot_product_attention,
)
from distributed_model_parallel_tpu.training.metrics import cross_entropy


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_dim: int = 3072
    max_position: int = 1024
    dropout_rate: float = 0.1
    # id treated as padding in the ATTENTION mask; None = every position
    # is real (fixed-length LM batches). Loss exclusion is separate:
    # use `lm_loss_fn(cfg)` (or pass pad_token_id to `lm_loss`) so pad
    # targets are masked there too.
    pad_token_id: Optional[int] = None
    # Mixture-of-Experts: num_experts > 0 swaps the FFN of every
    # `moe_every`-th decoder block for a routed MoE (`models/moe.py`,
    # same alternating recipe as BertConfig). Train with the EP engines
    # (`parallel/expert_parallel.ExpertParallelLMEngine`; the
    # sequence-parallel LM engine computes its loss per shard and
    # refuses MoE configs).
    num_experts: int = 0
    moe_every: int = 2
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25


def stem_apply(params, ids, cfg: GPTConfig, drop: L.Layer, ctx, *,
               positions=None):
    """The LM stem math, shared by the dense `_lm_stem` Layer and the
    sequence-parallel engine (which passes its shard's `positions`
    slice) — one copy, no drift. Returns (hidden, mask)."""
    mask = (
        jnp.ones(ids.shape, jnp.bool_) if cfg.pad_token_id is None
        else ids != cfg.pad_token_id
    )
    pos = (
        params["position"][: ids.shape[1]] if positions is None
        else positions
    )
    h = jnp.take(params["word"], ids, axis=0) + pos[None]
    if ctx.dtype is not None:
        h = h.astype(ctx.dtype)
    h, _ = drop.apply({}, {}, h, ctx)
    return h, mask


def head_apply(params, h):
    """Untied vocabulary projection; logits in f32. Shared by the dense
    Layer and the sequence-parallel engine."""
    return h.astype(jnp.float32) @ params["w"]


def _lm_stem(cfg: GPTConfig) -> L.Layer:
    """token + position embeddings, dropout. Output (hidden, mask)."""
    drop = L.dropout(cfg.dropout_rate)

    def init(key):
        kw, kp = jax.random.split(key)
        return {
            "word": 0.02 * jax.random.normal(
                kw, (cfg.vocab_size, cfg.dim)
            ),
            "position": 0.02 * jax.random.normal(
                kp, (cfg.max_position, cfg.dim)
            ),
        }, {}

    def apply(params, state, ids, ctx):
        return stem_apply(params, ids, cfg, drop, ctx), state

    return L.Layer(init, apply)


def _lm_head(cfg: GPTConfig) -> L.Layer:
    """Untied projection to the vocabulary; logits in f32."""

    def init(key):
        return {
            "w": 0.02 * jax.random.normal(key, (cfg.dim, cfg.vocab_size))
        }, {}

    def apply(params, state, x, ctx):
        h, _ = x
        return head_apply(params, h), state

    return L.Layer(init, apply)


def decoder_blocks(
    cfg: GPTConfig, attention_fn: Optional[AttentionFn] = None
) -> List[L.Layer]:
    attn = attention_fn or partial(dot_product_attention, causal=True)
    if cfg.num_experts > 0 and cfg.moe_every < 1:
        raise ValueError(
            f"moe_every must be >= 1 when num_experts > 0, got "
            f"{cfg.moe_every} (1 = every layer, 2 = every other, ...)"
        )
    blocks = []
    for i in range(cfg.num_layers):
        if cfg.num_experts > 0 and (i + 1) % cfg.moe_every == 0:
            from distributed_model_parallel_tpu.models.moe import (
                moe_encoder_layer,
            )

            blocks.append(moe_encoder_layer(
                cfg.dim, cfg.num_heads, cfg.ffn_dim, cfg.num_experts,
                top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                dropout_rate=cfg.dropout_rate, eps=1e-5,
                attention_fn=attn,
            ))
        else:
            blocks.append(encoder_layer(
                cfg.dim, cfg.num_heads, cfg.ffn_dim,
                dropout_rate=cfg.dropout_rate, eps=1e-5,
                attention_fn=attn,
            ))
    return blocks


def gpt_lm(
    cfg: GPTConfig, *, attention_fn: Optional[AttentionFn] = None,
    remat: bool = False,
) -> L.Layer:
    """Full LM: ids (B, T) -> logits (B, T, vocab).

    Pass `attention_fn=partial(flash_attention, causal=True)` for the
    Pallas kernel. For sequence parallelism, shard the BLOCK stack
    (`decoder_blocks` with `partial(ring_attention, axis_name='seq',
    causal=True)`) under shard_map — the stem must stay unsharded (or
    shard-aware): it indexes position embeddings with LOCAL offsets, so
    running the full model seq-sharded would give shards 1..N-1 wrong
    positions (see tests/test_gpt.py for the working recipe; a fully
    seq-sharded stem needs the SequenceParallelEngine position-offset
    treatment)."""
    from distributed_model_parallel_tpu.models import staging

    blocks = decoder_blocks(cfg, attention_fn)
    if remat:
        blocks = [L.remat(b) for b in blocks]
    return staging.staged_model(_lm_stem(cfg), blocks, _lm_head(cfg))


def _lm_head_flat(cfg: GPTConfig) -> L.Layer:
    """The LM head for PIPELINE stages: same params as `_lm_head` (an
    untied `w` — checkpoints interoperate), but logits flattened
    (B, T, V) -> (B*T, V) to satisfy `PipelineEngine`'s (rows, classes)
    last-stage contract. Feed targets pre-flattened the same way:
    `lm_targets(ids).reshape(-1)` (row order matches — batch-major,
    token-minor on both sides)."""
    inner = _lm_head(cfg)

    def apply(params, state, x, ctx):
        logits, state = inner.apply(params, state, x, ctx)
        b, t, v = logits.shape
        return logits.reshape(b * t, v), state

    return L.Layer(inner.init, apply)


def split_stages(
    num_stages: int,
    cfg: GPTConfig,
    *,
    boundaries=None,
    attention_fn: Optional[AttentionFn] = None,
) -> List[L.Layer]:
    """Pipeline stages for the decoder LM: stem (token+position
    embeddings) on stage 0, decoder blocks distributed, flattening LM
    head on the last stage — the same staging convention as
    `models/bert.py::split_stages` (the wire carries the (hidden, mask)
    pair between stages). Drive with `PipelineEngine` and labels
    `lm_targets(ids).reshape(-1)`; the engine normalizes its loss by the
    VALID (label != -1) row count, so gradients match the dense
    per-token mean-loss convention of `lm_loss`."""
    from distributed_model_parallel_tpu.models import staging

    blocks = decoder_blocks(cfg, attention_fn)
    cuts = staging.split_points(num_stages, boundaries, len(blocks))
    return staging.assemble_stages(
        blocks, _lm_stem(cfg), _lm_head_flat(cfg), cuts
    )


def lm_loss_fn(cfg: GPTConfig):
    """`lm_loss` bound to the config's pad_token_id — use this instead
    of raw `lm_loss` so loss masking can't silently fall out of sync
    with the attention mask."""
    return partial(lm_loss, pad_token_id=cfg.pad_token_id)


def lm_targets(ids, pad_token_id: Optional[int] = None):
    """Per-position next-token targets: targets[t] = ids[t+1], with the
    final position (and padding) marked -1 (the exclusion label
    `training/metrics.cross_entropy` masks).

    Computed on the HOST so sequence-parallel training can shard targets
    alongside ids — every shard then scores its own positions locally,
    including the shard-boundary token, with no cross-shard fetch."""
    import numpy as np

    # int32 BEFORE the -1 fills: in an unsigned ids dtype the sentinel
    # would wrap to a huge valid-looking label and defeat the exclusion.
    ids = np.asarray(ids).astype(np.int32)
    targets = np.concatenate(
        [ids[:, 1:], np.full((ids.shape[0], 1), -1, np.int32)], axis=1
    )
    if pad_token_id is not None:
        targets = np.where(targets == pad_token_id, -1, targets)
    return targets.astype(np.int32)


def lm_loss(logits: jax.Array, ids: jax.Array,
            pad_token_id: Optional[int] = None) -> jax.Array:
    """Next-token cross-entropy: position t predicts ids[t+1]; padding
    targets (== pad_token_id) are excluded via the label -1 convention
    `training/metrics.cross_entropy` already masks."""
    targets = ids[:, 1:]
    if pad_token_id is not None:
        targets = jnp.where(targets == pad_token_id, -1, targets)
    logits = logits[:, :-1, :]
    b, t, v = logits.shape
    return cross_entropy(
        logits.reshape(b * t, v), targets.reshape(b * t)
    )
