"""Background checkpoint writer — file I/O off the step path.

The save path splits in two exactly once:

  main thread      snapshot: device->host copy of the process's owned
                   chunks into host buffers (`sharded.local_chunk_data`)
                   + the write plan. This is the only part the train
                   loop waits for; it scales with 1/N of the state.
  writer thread    file I/O: shard npz (tmp + rename), peer-shard wait
                   (multi-process shared FS), manifest commit, stale-
                   shard GC. Runs while steps N+1, N+2, ... dispatch.

Failure surface — NEVER silent: each job's exception is stored on its
`SaveHandle` and on the checkpointer; `AsyncCheckpointer.check()` (the
trainer calls it at the NEXT save) and `.wait()` (called at `fit()`
exit) re-raise it. A crash mid-write cannot clobber the previous good
checkpoint: shard files carry the new save-id in their names and the
manifest — the commit point — is written last (see manifest.py).

`_write_shard` is module-level so tests can monkeypatch it with an
artificially slow or crashing writer (the timed not-blocked assertion
and the mid-write-crash test in tests/test_checkpoint_sharded.py).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, List, Optional

import numpy as np


def _write_shard(path: str, arrays: dict) -> None:
    """One shard npz, atomically (tmp + rename). Monkeypatch target for
    the slow-writer / crash tests."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)


class SaveHandle:
    """Ticket for one in-flight (or completed) save."""

    def __init__(self, path: str):
        self.path = path            # the manifest path once committed
        self._done = threading.Event()
        self._error: Optional[BaseException] = None

    def _finish(self, error: Optional[BaseException]) -> None:
        self._error = error
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until the write lands; re-raise its failure."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"checkpoint write to {self.path} still in flight after "
                f"{timeout}s"
            )
        if self._error is not None:
            raise self._error

    def result(self, timeout: Optional[float] = None) -> str:
        self.wait(timeout)
        return self.path


class AsyncCheckpointer:
    """One background thread, jobs in submission order (a 'best' and a
    'last' save of the same epoch must not interleave their renames).
    The thread is a daemon and is also joined explicitly by `wait()` —
    the trainer calls that at `fit()` exit so no write is abandoned."""

    def __init__(self):
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._pending: List[SaveHandle] = []
        self._unraised: Optional[BaseException] = None
        self._reserved_ids: dict = {}  # (directory, name) -> last id

    # ------------------------------------------------------------ worker

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="ckpt-writer", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        # Local imports keep this module's surface numpy-only for the
        # monkeypatching tests; tracer and registry are stdlib-only
        # and thread-safe by contract.
        from distributed_model_parallel_tpu.observability.metrics import (
            get_metrics,
        )
        from distributed_model_parallel_tpu.observability.trace import (
            get_tracer,
        )

        while True:
            item = self._queue.get()
            if item is None:
                return
            job, handle = item
            tracer = get_tracer()
            mx = get_metrics()
            t0 = tracer.now() if mx.enabled else None
            try:
                # The I/O half of a save, on THIS thread — the span the
                # Chrome trace shows running beside the main loop's
                # steps (the step path only paid ckpt_snapshot).
                with tracer.span(
                    "ckpt_background_write", path=handle.path
                ):
                    job()
                if t0 is not None:
                    mx.observe(
                        "ckpt_background_write_s", tracer.now() - t0
                    )
                handle._finish(None)
            except BaseException as e:  # noqa: BLE001 — stored, re-raised
                # Store the checkpointer-level error BEFORE publishing
                # the handle's done event: a waiter unblocking on the
                # event and immediately calling check() must already
                # see the failure (never-silent contract).
                with self._lock:
                    if self._unraised is None:
                        self._unraised = e
                handle._finish(e)

    # ------------------------------------------------------------ public

    def reserve_save_id(
        self, directory: str, name: str, floor: int
    ) -> int:
        """Monotonic save-id reservation across IN-FLIGHT saves of the
        same (directory, name): the on-disk manifest only reflects
        COMMITTED saves, so a snapshot racing a still-writing
        predecessor would otherwise reuse its id — and with it the
        shard filenames whose per-save uniqueness the crash discipline
        rests on (manifest.py)."""
        key = (os.path.abspath(directory), name)
        with self._lock:
            last = self._reserved_ids.get(key)
            sid = floor if last is None else max(floor, last + 1)
            self._reserved_ids[key] = sid
        return sid

    def submit(self, job: Callable[[], None], path: str) -> SaveHandle:
        """Enqueue the I/O half of a save; returns immediately."""
        handle = SaveHandle(path)
        with self._lock:
            self._pending.append(handle)
        self._ensure_thread()
        self._queue.put((job, handle))
        return handle

    def check(self) -> None:
        """Surface (raise) the oldest unsurfaced write failure — the
        trainer calls this at the START of every save so an epoch-N
        failure cannot hide behind epoch N+1's success."""
        with self._lock:
            err, self._unraised = self._unraised, None
        if err is not None:
            raise err

    def wait(self) -> None:
        """Drain every pending write, then surface any failure (fit()
        exit). Idempotent."""
        with self._lock:
            pending, self._pending = self._pending, []
        for h in pending:
            h._done.wait()
        self.check()

    def pending(self) -> int:
        with self._lock:
            return sum(1 for h in self._pending if not h.done())


__all__ = ["AsyncCheckpointer", "SaveHandle", "_write_shard"]
