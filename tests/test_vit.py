"""ViT family: torchvision param-count parity, forward smoke, engine
integration (DP/FSDP/TP via the shared Megatron rule paths)."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_model_parallel_tpu.models.layers import Context
from distributed_model_parallel_tpu.models.vit import (
    VIT_CIFAR,
    vit,
    vit_b16,
    vit_cifar,
)
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.optim import SGD


def n_params(tree):
    return sum(np.prod(x.shape) for x in jax.tree_util.tree_leaves(tree))


def test_vit_b16_param_count_matches_torchvision():
    """torchvision vit_b_16(num_classes=1000) has 86,567,656 parameters
    (public reference value); shapes via eval_shape, no compute."""
    shapes, _ = jax.eval_shape(
        vit_b16(1000).init, jax.ShapeDtypeStruct((2,), jnp.uint32)
    )
    assert n_params(shapes) == 86_567_656


def test_vit_cifar_forward_shape(rng):
    model = vit_cifar(10)
    params, state = model.init(rng)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    logits, _ = model.apply(params, state, x, Context(train=False))
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_vit_trains_under_dp_and_fsdp():
    from distributed_model_parallel_tpu.parallel.data_parallel import (
        DataParallelEngine,
    )
    from distributed_model_parallel_tpu.parallel.fsdp import FSDPEngine

    import dataclasses as dc

    tiny = dc.replace(
        VIT_CIFAR, image_size=16, patch_size=4, dim=32, num_layers=1,
        num_heads=4, mlp_dim=64,
    )
    rng = np.random.RandomState(0)
    means = np.random.RandomState(9).randn(4, 16, 16, 3).astype(np.float32)
    y = rng.randint(0, 4, size=(16,)).astype(np.int32)
    x = rng.randn(16, 16, 16, 3).astype(np.float32) * 0.3 + means[y]
    mesh = make_mesh(MeshSpec(data=8))
    for eng_cls in (DataParallelEngine, FSDPEngine):
        kw = {"min_shard_elems": 64} if eng_cls is FSDPEngine else {}
        eng = eng_cls(vit(4, tiny), SGD(), mesh, donate=False, **kw)
        ts = eng.init_state(jax.random.PRNGKey(0))
        losses = []
        for _ in range(5):
            ts, m = eng.train_step(
                ts, *eng.shard_batch(x, y), jnp.float32(0.01)
            )
            losses.append(float(m["loss_sum"]) / float(m["count"]))
        assert losses[-1] < losses[0], eng_cls.__name__


def test_vit_tensor_parallel_megatron_paths():
    """The pre-LN blocks expose the same attn/qkv, attn/out, ffn/in,
    ffn/out param paths, so MEGATRON_RULES shard ViT unchanged."""
    import dataclasses as dc

    from distributed_model_parallel_tpu.parallel.tensor_parallel import (
        TensorParallelEngine,
    )

    tiny = dc.replace(
        VIT_CIFAR, image_size=16, patch_size=4, dim=32, num_layers=1,
        num_heads=4, mlp_dim=64,
    )
    mesh = make_mesh(MeshSpec(data=2, model=4))
    eng = TensorParallelEngine(vit(4, tiny), SGD(), mesh, donate=False)
    ts = eng.init_state(jax.random.PRNGKey(0))
    qkv = ts.params["blocks"]["0"]["attn"]["qkv"]["w"]
    assert qkv.addressable_shards[0].data.shape[1] == qkv.shape[1] // 4
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16, 16, 3).astype(np.float32)
    y = rng.randint(0, 4, size=(8,)).astype(np.int32)
    ts, m = eng.train_step(ts, *eng.shard_batch(x, y), jnp.float32(0.01))
    assert np.isfinite(float(m["loss_sum"]))


def test_vit_rejects_wrong_image_size(rng):
    import pytest

    model = vit_cifar(10)
    params, state = model.init(rng)
    with pytest.raises(ValueError, match="32x32"):
        model.apply(
            params, state, jnp.zeros((2, 224, 224, 3)), Context(train=False)
        )
