"""Torch-checkpoint transplant for MobileNetV2 — the finetune bridge.

The reference's headline accuracy table is FINETUNING from pretrained
weights (`Readme.md:200-205`, 96.3% @ bs128), and its training scripts
save torch `state_dict`s (`data_parallel.py:143-155`, wrapped as
`{'net': state_dict, 'acc', 'epoch'}` with `module.*` key prefixes from
the `nn.DataParallel` wrapper at `data_parallel.py:77`). This module maps
that weight format into our functional pytrees, so a reference user's
checkpoints — or any torch MobileNetV2 weights in the same layout — carry
over: `--finetune ckpt.pth` on the CLI.

Layout mapped (the reference model's `state_dict()` key schema):
    conv1/bn1                      -> stem
    layers.{i}.conv1/bn1/conv2/bn2/conv3/bn3 (+shortcut.0/.1)
                                   -> blocks.{i}(.body/.shortcut)
    conv2/bn2/linear               -> head

Weight-convention transforms (pinned op-by-op against torch in
tests/test_torch_import.py):
    conv  OIHW -> HWIO   (transpose 2,3,1,0; depthwise O1HW -> HW1O)
    linear (out,in) -> (in,out) (transpose)
    BN weight/bias -> scale/bias params; running_mean/var -> state
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from distributed_model_parallel_tpu.models.mobilenetv2 import CFG


def _as_numpy(value) -> np.ndarray:
    if hasattr(value, "detach"):  # torch tensor without importing torch
        value = value.detach().cpu().numpy()
    return np.asarray(value)


def normalize_state_dict(obj) -> Dict[str, np.ndarray]:
    """Unwrap the reference's checkpoint format: accepts a bare
    state_dict, the `{'net': state_dict, ...}` wrapper the reference
    saves (`data_parallel.py:146-151`), and `module.*`-prefixed keys from
    its `nn.DataParallel` wrap; values become NumPy."""
    if isinstance(obj, dict) and "net" in obj and isinstance(obj["net"], dict):
        obj = obj["net"]
    out = {}
    for k, v in obj.items():
        if k.startswith("module."):
            k = k[len("module."):]
        out[k] = _as_numpy(v)
    return out


def _conv_w(t: np.ndarray) -> np.ndarray:
    """torch OIHW -> our HWIO (depthwise O1HW -> HW1O is the same move)."""
    return np.transpose(t, (2, 3, 1, 0)).astype(np.float32)


class _Consumer:
    """Tracks which checkpoint keys were used so the transplant can fail
    loudly on schema drift instead of silently half-loading."""

    def __init__(self, sd: Dict[str, np.ndarray]):
        self.sd = sd
        self.used = set()

    def take(self, key: str) -> np.ndarray:
        if key not in self.sd:
            raise KeyError(f"checkpoint is missing expected key {key!r}")
        self.used.add(key)
        return self.sd[key]

    def leftovers(self):
        ignorable = {k for k in self.sd if k.endswith("num_batches_tracked")}
        return sorted(set(self.sd) - self.used - ignorable)


def _bn(c: _Consumer, prefix: str, params: dict, state: dict) -> None:
    params["scale"] = c.take(f"{prefix}.weight").astype(np.float32)
    params["bias"] = c.take(f"{prefix}.bias").astype(np.float32)
    state["mean"] = c.take(f"{prefix}.running_mean").astype(np.float32)
    state["var"] = c.take(f"{prefix}.running_var").astype(np.float32)


def mobilenetv2_from_torch_state_dict(
    params: Any,
    state: Any,
    state_dict: Dict[str, Any],
    *,
    allow_head_mismatch: bool = True,
) -> Tuple[Any, Any]:
    """Transplant a reference-format torch MobileNetV2 `state_dict` into
    (params, state) from `mobilenet_v2(...).init(...)`. Returns new
    pytrees (inputs are not mutated).

    `allow_head_mismatch=True` keeps the freshly-initialized classifier
    when the checkpoint's `linear` has a different class count — the
    finetune-to-a-new-task path (`Readme.md:200-205` finetunes ImageNet
    weights onto CIFAR's 10 classes)."""
    import jax

    c = _Consumer(normalize_state_dict(state_dict))
    params = jax.tree_util.tree_map(np.asarray, params)
    state = jax.tree_util.tree_map(np.asarray, state)

    # --- stem (`conv1`/`bn1`) ----------------------------------------
    params["stem"]["conv1"]["w"] = _conv_w(c.take("conv1.weight"))
    _bn(c, "bn1", params["stem"]["bn1"], state["stem"]["bn1"])

    # --- the 17 inverted-residual blocks ------------------------------
    in_planes = 32
    i = 0
    for expansion, out_planes, num_blocks, stride in CFG:
        for s in [stride] + [1] * (num_blocks - 1):
            src = f"layers.{i}"
            tgt_p = params["blocks"][str(i)]
            tgt_s = state["blocks"][str(i)]
            has_residual = s == 1
            body_p = tgt_p["body"] if has_residual else tgt_p
            body_s = tgt_s["body"] if has_residual else tgt_s
            for conv, bn in (("conv1", "bn1"), ("conv2", "bn2"),
                             ("conv3", "bn3")):
                body_p[conv]["w"] = _conv_w(c.take(f"{src}.{conv}.weight"))
                _bn(c, f"{src}.{bn}", body_p[bn], body_s[bn])
            if has_residual and in_planes != out_planes:
                # reference shortcut = nn.Sequential(conv, bn) -> keys .0/.1
                tgt_p["shortcut"]["conv"]["w"] = _conv_w(
                    c.take(f"{src}.shortcut.0.weight")
                )
                _bn(c, f"{src}.shortcut.1",
                    tgt_p["shortcut"]["bn"], tgt_s["shortcut"]["bn"])
            in_planes = out_planes
            i += 1

    # --- head (`conv2`/`bn2`/`linear`) --------------------------------
    params["head"]["conv2"]["w"] = _conv_w(c.take("conv2.weight"))
    _bn(c, "bn2", params["head"]["bn2"], state["head"]["bn2"])
    lin_w = c.take("linear.weight")
    lin_b = c.take("linear.bias")
    if lin_w.shape[0] == params["head"]["linear"]["w"].shape[1]:
        params["head"]["linear"]["w"] = lin_w.T.astype(np.float32)
        params["head"]["linear"]["b"] = lin_b.astype(np.float32)
    elif not allow_head_mismatch:
        raise ValueError(
            f"checkpoint head has {lin_w.shape[0]} classes, model has "
            f"{params['head']['linear']['w'].shape[1]}"
        )
    # else: keep the fresh classifier (finetune-to-new-task path)

    leftovers = c.leftovers()
    if leftovers:
        raise ValueError(
            "checkpoint keys not consumed by the MobileNetV2 schema "
            f"(wrong architecture?): {leftovers[:8]}"
            + ("..." if len(leftovers) > 8 else "")
        )
    return params, state


def mobilenetv2_to_torch_state_dict(
    params: Any, state: Any, *, module_prefix: bool = True
) -> Dict[str, np.ndarray]:
    """The INVERSE bridge: a `mobilenet_v2(...)` (params, state) pair ->
    the reference's torch `state_dict` schema (OIHW convs, `module.*`
    prefixes as `nn.DataParallel` saves them — `data_parallel.py:146-151`).
    Round-trips bit-exactly through `mobilenetv2_from_torch_state_dict`
    (tests/test_torch_import.py), so a model trained HERE can be handed
    back to the reference code (or to this framework's own `--finetune`
    flag, which expects the reference format)."""
    import jax

    params = jax.tree_util.tree_map(np.asarray, params)
    state = jax.tree_util.tree_map(np.asarray, state)
    sd: Dict[str, np.ndarray] = {}

    def conv_w(t):  # HWIO -> OIHW
        return np.ascontiguousarray(np.transpose(t, (3, 2, 0, 1)))

    def put_bn(prefix, p, s):
        sd[f"{prefix}.weight"] = p["scale"]
        sd[f"{prefix}.bias"] = p["bias"]
        sd[f"{prefix}.running_mean"] = s["mean"]
        sd[f"{prefix}.running_var"] = s["var"]
        sd[f"{prefix}.num_batches_tracked"] = np.asarray(0, np.int64)

    sd["conv1.weight"] = conv_w(params["stem"]["conv1"]["w"])
    put_bn("bn1", params["stem"]["bn1"], state["stem"]["bn1"])

    in_planes, i = 32, 0
    for expansion, out_planes, num_blocks, stride in CFG:
        for s_ in [stride] + [1] * (num_blocks - 1):
            dst = f"layers.{i}"
            src_p = params["blocks"][str(i)]
            src_s = state["blocks"][str(i)]
            has_residual = s_ == 1
            body_p = src_p["body"] if has_residual else src_p
            body_s = src_s["body"] if has_residual else src_s
            for conv, bn in (("conv1", "bn1"), ("conv2", "bn2"),
                             ("conv3", "bn3")):
                sd[f"{dst}.{conv}.weight"] = conv_w(body_p[conv]["w"])
                put_bn(f"{dst}.{bn}", body_p[bn], body_s[bn])
            if has_residual and in_planes != out_planes:
                sd[f"{dst}.shortcut.0.weight"] = conv_w(
                    src_p["shortcut"]["conv"]["w"]
                )
                put_bn(f"{dst}.shortcut.1", src_p["shortcut"]["bn"],
                       src_s["shortcut"]["bn"])
            in_planes = out_planes
            i += 1

    sd["conv2.weight"] = conv_w(params["head"]["conv2"]["w"])
    put_bn("bn2", params["head"]["bn2"], state["head"]["bn2"])
    sd["linear.weight"] = np.ascontiguousarray(
        params["head"]["linear"]["w"].T
    )
    sd["linear.bias"] = params["head"]["linear"]["b"]
    if module_prefix:
        sd = {f"module.{k}": v for k, v in sd.items()}
    return sd


def save_reference_checkpoint(
    path: str, params: Any, state: Any, *, acc: float = 0.0,
    epoch: int = 0,
) -> str:
    """Write the reference's exact checkpoint schema
    `{'net': module.* state_dict, 'acc': acc, 'epoch': epoch}`
    (`data_parallel.py:146-151`) as a torch `.pth`."""
    import torch

    sd = {
        k: torch.from_numpy(np.ascontiguousarray(v))
        for k, v in mobilenetv2_to_torch_state_dict(params, state).items()
    }
    torch.save({"net": sd, "acc": acc, "epoch": epoch}, path)
    return path


def load_torch_checkpoint(path: str) -> Dict[str, Any]:
    """Read a torch `.pth`/`.pt` (via torch, CPU) or `.npz` checkpoint
    into a plain dict ready for `mobilenetv2_from_torch_state_dict`."""
    if path.endswith(".npz"):
        return dict(np.load(path))
    import torch

    return torch.load(path, map_location="cpu", weights_only=True)
