"""Wire compression for cross-slice ('dcn') traffic — the codec layer
under the two hierarchical exchanges.

Both hierarchical exchanges in this repo — the bucketed/overlapped
gradient reducer (`ops/grad_reduction.py`) and the two-level MoE expert
dispatch (`ops/expert_dispatch.py`) — already isolate the slow
cross-slice fabric onto a 1/ici-regrouped shard: the 'dcn' hop is the
ONE place a payload is both large and riding a link an order of
magnitude slower than ICI (RESULTS §3b/§3c). That is exactly where
payload compression pays, and it is the seam PyTorch DDP exposes as
comm hooks on its bucketed Reducer (Li et al., VLDB 2020) and
DeepSpeed-MoE cheapens its expert exchange through (Rajbhandari et al.,
ICML 2022); 1-bit Adam (Tang et al., ICML 2021, PAPERS.md) is the
take-it-to-the-limit anchor for compressing exactly the gradient
exchange while master state stays full precision.

Two codecs, selected by name (`dcn_compression` on the engines,
`--dcn-compression` on the CLIs):

* `"bf16"` — cast-codec: encode = cast to bfloat16 (same exponent range
  as f32, 8 mantissa bits), decode = cast back. Halves the 'dcn' bytes;
  elementwise error <= 2^-8 relative (one rounding per hop).
* `"int8"` — absmax-scale codec: encode computes one f32 scale
  `max(|x|)/127` over the hop's chunk (a sub-range of a flat BUCKET on
  the gradient path, one regrouped message on the dispatch path),
  quantizes to int8, and ships the scale as a sidecar; decode multiplies
  back. Quarters the 'dcn' bytes (+4 B sidecar per hop); elementwise
  error <= chunk_absmax/254 per hop — the per-bucket bound the parity
  tests pin (INTERNALS §12 documents the accumulation: a K-slice
  reduction crosses the codec once per received chunk plus once on the
  gather, so the reduced value is within (K+1)·absmax/254 of the f32
  sum).

Everything INTRA-slice stays in the math dtype (f32 master weights and
f32 rings are untouched — compression is a property of the 'dcn' wire,
never of the accumulate), and int8 never sums in int8: the compressed
reduction (`ops/grad_reduction.compressed_dcn_psum`) exchanges encoded
chunks, decodes, and accumulates in the bucket dtype — a
reduce-scatter-then-all-gather over 'dcn' in the wire dtype rather
than a wire-dtype all-reduce.

`coded_ppermute` is the one primitive both consumers ride: encode →
`lax.ppermute` → decode, with the payload hop under the `dcn_wire`
named scope and the int8 scale sidecar under `dcn_scale` — the scopes
hlolint's byte-aware rule `dcn-compressed-payload` pins (every 'dcn'
hop of an opted-in step carries the wire dtype at the regrouped chunk
shape; zero f32 grad- or dispatch-sized payload crosses 'dcn'). Its
`jax.custom_vjp` sends the COTANGENT through the same codec over the
inverse permutation, so the backward rides the wire dtype too (the
straight-through convention: the quantizer's rounding is not
differentiated — its jacobian is zero a.e. — the wire is).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# The engine/CLI surface: "none" keeps the f32 wire byte-identical to
# the uncompressed lowering.
COMPRESSION_MODES: Tuple[str, ...] = ("none", "bf16", "int8")

# Named scopes the payload / scale-sidecar hops carry — what hlolint's
# `dcn-compressed-payload` rule reads out of the traced jaxpr (compiled
# CPU HLO float-normalizes bf16 collectives to f32, so the dtype
# contract lives at trace level, like `bf16-ring-upcast`).
WIRE_SCOPE = "dcn_wire"
SCALE_SCOPE = "dcn_scale"

# Zero-chunk guard: an all-zero chunk gets this scale instead of 0/127
# (decode is still exactly zero — every quantized value is 0). The
# floor is 127x f32's smallest NORMAL magnitude so the derived scale
# `floor/127` itself stays normal — a DENORMAL scale flushes to zero
# under the backend's FTZ and 0-divides the whole chunk. Chunks of
# denormal gradients quantize against the floored scale; the
# elementwise bound everywhere is max(absmax, ABSMAX_FLOOR)/254.
ABSMAX_FLOOR = 127 * 1.1754944e-38


def check_compression(name: str) -> str:
    """Validate a compression-mode name (engines and the bucketed
    reducer call this at construction so a typo fails loudly)."""
    if name not in COMPRESSION_MODES:
        raise ValueError(
            f"dcn_compression must be one of {COMPRESSION_MODES}, got "
            f"{name!r}"
        )
    return name


def require_dcn_axis(name: str, dcn_axis, what: str = "hop") -> str:
    """The one guard every consumer shares: a compressed wire needs a
    cross-slice fabric to cross. Validates the mode name too, so one
    call at engine construction covers both failure modes."""
    check_compression(name)
    if name != "none" and dcn_axis is None:
        raise ValueError(
            f"dcn_compression compresses the cross-slice {what}; this "
            "mesh carries no 'dcn' axis — factor the data axis with "
            "MeshSpec(dcn=K) (--dcn-slices on the CLIs)"
        )
    return name


def wire_itemsize(wire: str) -> int:
    """Bytes per element on the 'dcn' wire (the 1/2 resp. 1/4 of the
    f32 bytes the hlolint rule shape-pins; sidecars excluded)."""
    return {"none": 4, "bf16": 2, "int8": 1}[wire]


def wire_encode(wire: str, x):
    """x -> (payload, scale). `scale` is None for the cast codecs and
    a () f32 sidecar for int8 (absmax/127 over the whole chunk)."""
    if wire == "bf16":
        return x.astype(jnp.bfloat16), None
    if wire == "int8":
        xf = x.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(xf))
        scale = jnp.maximum(absmax, ABSMAX_FLOOR) / 127.0
        q = jnp.clip(jnp.round(xf / scale), -127.0, 127.0)
        return q.astype(jnp.int8), scale
    return x, None


def wire_decode(wire: str, payload, scale, dtype):
    """Inverse of `wire_encode`, back to the chunk's math dtype."""
    if wire == "bf16":
        return payload.astype(dtype)
    if wire == "int8":
        return (payload.astype(jnp.float32) * scale).astype(dtype)
    return payload


def _coded_ppermute_impl(x, axis_name, perm, wire, tag):
    """encode -> ppermute -> decode, scopes applied per hop. With
    `wire="none"` this is a plain (optionally `tag`-scoped) ppermute —
    byte-identical to the uncompressed lowering."""
    perm = list(perm)
    if wire == "none":
        if tag is None:
            return lax.ppermute(x, axis_name, perm)
        with jax.named_scope(tag):
            return lax.ppermute(x, axis_name, perm)

    payload, scale = wire_encode(wire, x)
    if tag is None:
        with jax.named_scope(WIRE_SCOPE):
            payload = lax.ppermute(payload, axis_name, perm)
    else:
        with jax.named_scope(tag):
            with jax.named_scope(WIRE_SCOPE):
                payload = lax.ppermute(payload, axis_name, perm)
    if scale is not None:
        # The sidecar rides the SAME permutation but its own scope: it
        # must not count toward payload-hop pins (e.g. the moe_ring
        # chain of `moe-hierarchical-a2a`), and the hlolint rule
        # accounts for it separately (one f32 scalar per int8 hop).
        with jax.named_scope(SCALE_SCOPE):
            scale = lax.ppermute(scale, axis_name, perm)
    return wire_decode(wire, payload, scale, x.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def coded_ppermute(x, axis_name, perm, wire: str = "none",
                   tag: Optional[str] = None):
    """A `lax.ppermute` whose payload crosses the wire compressed.

    `perm` must be a hashable tuple of (src, dst) pairs. The backward
    runs the cotangent through the SAME codec over the inverse
    permutation (module docstring) — which is what keeps the transposed
    MoE exchange and the overlapped FFN ring's backward on the
    compressed wire instead of silently falling back to f32."""
    return _coded_ppermute_impl(x, axis_name, perm, wire, tag)


def _coded_fwd(x, axis_name, perm, wire, tag):
    return _coded_ppermute_impl(x, axis_name, perm, wire, tag), None


def _coded_bwd(axis_name, perm, wire, tag, _, g):
    inv = tuple((dst, src) for src, dst in perm)
    return (_coded_ppermute_impl(g, axis_name, inv, wire, tag),)


coded_ppermute.defvjp(_coded_fwd, _coded_bwd)


__all__ = [
    "ABSMAX_FLOOR",
    "COMPRESSION_MODES",
    "SCALE_SCOPE",
    "WIRE_SCOPE",
    "check_compression",
    "coded_ppermute",
    "require_dcn_axis",
    "wire_decode",
    "wire_encode",
    "wire_itemsize",
]
