"""Per-host sharded input pipeline.

Fixes the two input-path defects SURVEY.md calls out:
* the reference has **no DistributedSampler** — every rank shuffles the
  whole dataset independently (`utils.py:21` `train_sampler=None`); here
  each host deterministically owns a disjoint shard per epoch.
* the reference funnels all data through device 0 (`Readme.md:15`); here
  each host feeds only its local shard, and the engine's `shard_batch`
  places it along the 'data' mesh axis.

Augmentations are the reference's CIFAR train transforms
(`data_parallel.py:32-37`): random crop 32 with padding 4, random
horizontal flip, normalize. Implemented vectorized over the batch in
NumPy; the C++ native module (native/) provides a drop-in accelerated
version of the same ops for high-rate input.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional, Tuple

import numpy as np

from distributed_model_parallel_tpu.data.datasets import ArrayDataset


def random_crop_flip(
    images: np.ndarray, rng: np.random.RandomState, padding: int = 4
) -> np.ndarray:
    """Batched RandomCrop(pad)+RandomHorizontalFlip on uint8 NHWC,
    vectorized: one sliding-window view + one fancy-index gather, no
    per-image Python loop."""
    n, h, w, c = images.shape
    padded = np.pad(
        images,
        ((0, 0), (padding, padding), (padding, padding), (0, 0)),
        mode="constant",
    )
    ys = rng.randint(0, 2 * padding + 1, size=n)
    xs = rng.randint(0, 2 * padding + 1, size=n)
    flips = rng.rand(n) < 0.5
    # (n, 2p+1, 2p+1, c, h, w) view; gather each image's window.
    windows = np.lib.stride_tricks.sliding_window_view(padded, (h, w), axis=(1, 2))
    out = windows[np.arange(n), ys, xs]          # (n, c, h, w)
    out = np.ascontiguousarray(out.transpose(0, 2, 3, 1))  # NHWC
    out[flips] = out[flips, :, ::-1]
    return out


def normalize(images: np.ndarray, mean: np.ndarray, std: np.ndarray) -> np.ndarray:
    return (images.astype(np.float32) / 255.0 - mean) / std


@dataclasses.dataclass
class Loader:
    """Deterministic, host-sharded batch iterator.

    `process_index/process_count` implement the missing DistributedSampler:
    after the global epoch shuffle (seeded by epoch, identical on all
    hosts), each host takes every `process_count`-th index. `drop_last` is
    forced on for training so batch shapes are static for XLA; with
    `drop_last=False` a ragged final batch is padded back to `batch_size`
    with label -1 rows (masked out by metrics) for the same reason.

    `batch_size` is this host's PER-HOST batch; `cli.common.build_loaders`
    divides the user-facing global batch by `jax.process_count()` before
    constructing Loaders.
    """

    dataset: ArrayDataset
    batch_size: int
    shuffle: bool = True
    augment: bool = False
    mean: Optional[np.ndarray] = None
    std: Optional[np.ndarray] = None
    seed: int = 0
    process_index: int = 0
    process_count: int = 1
    drop_last: bool = True

    def __post_init__(self):
        if self.batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {self.batch_size}")
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        # Every host sees the same padded shard size (ceil(n/P)), so batch
        # counts agree across hosts — without this, a host with a shorter
        # shard exits its epoch loop early and the remaining hosts hang in
        # the next collective (torch's DistributedSampler pads for the same
        # reason).
        per_host = -(-len(self.dataset) // self.process_count)
        if self.drop_last:
            return per_host // self.batch_size
        return -(-per_host // self.batch_size)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        n = len(self.dataset)
        rng = np.random.RandomState(self.seed + self._epoch)
        order = rng.permutation(n) if self.shuffle else np.arange(n)
        # Pad to a multiple of process_count by wrapping (DistributedSampler
        # semantics) so every host's strided shard has identical length.
        per_host = -(-n // self.process_count)
        pad = per_host * self.process_count - n
        if pad:
            # np.tile handles pad > n (tiny dataset, many hosts) — torch's
            # DistributedSampler repeats the index list the same way.
            order = np.concatenate([order, np.tile(order, -(-pad // n))[:pad]])
        mine = order[self.process_index::self.process_count]
        aug_rng = np.random.RandomState(
            (self.seed + self._epoch) * 1009 + self.process_index
        )
        nb = len(self)
        for b in range(nb):
            idx = mine[b * self.batch_size:(b + 1) * self.batch_size]
            if len(idx) == 0:
                return
            images = self.dataset.images[idx]
            labels = self.dataset.labels[idx]
            if self.augment:
                images = random_crop_flip(images, aug_rng)
            if self.mean is not None:
                images = normalize(images, self.mean, self.std)
            else:
                images = images.astype(np.float32) / 255.0
            if len(idx) < self.batch_size:
                # Ragged final batch (drop_last=False): pad to the static
                # batch shape so XLA never sees a second shape and the
                # 'data'-axis sharding stays divisible. Padding rows carry
                # label -1; metrics/losses mask them out (metrics.py
                # valid_count).
                pad_n = self.batch_size - len(idx)
                images = np.concatenate(
                    [images, np.zeros((pad_n,) + images.shape[1:],
                                      images.dtype)]
                )
                labels = np.concatenate(
                    [labels, np.full((pad_n,), -1, labels.dtype)]
                )
            yield images, labels
