"""obsreport — one unified run report from trace + metrics + ledger.

`tools/obsreport` is the CLI. It ingests up to four artifacts of one
run and renders a single deterministic text report (plus a JSON twin):

  * a Chrome trace (`trace.Tracer` export, or a `--profile-dir`
    xplane `trace.json.gz`) -> the per-phase attribution table with
    the unattributed residual called out (`attribution.py`),
  * a metrics JSON (`--metrics-out`) -> the SLO histogram section
    (per-request/per-token quantiles, goodput, counters),
  * the cost ledger -> measured-vs-predicted rows per requested combo,
  * a calibration file (`calibrate.py`) -> fitted-vs-committed drift.

Rendering is pure formatting over the ingested JSON — no jax, no
numpy, no wall clock — so the same inputs yield the same bytes
forever. That property is the pre-gate: `tools/obsreport --pregate`
renders the canned golden inputs (tests/golden/obsreport_*.json) and
byte-compares against the committed golden report, exit 5 naming the
first diverging line — wired into tools/tier1.sh after the costgate
pre-gate, so a change that breaks attribution/report semantics fails
in under a second with the drift visible.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from distributed_model_parallel_tpu.observability.attribution import (
    Attribution,
    attribute,
    load_trace,
    profile_dir_traces,
    reconcile,
)

EXIT_GOLDEN_MISMATCH = 5

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))
GOLDEN_DIR = os.path.join(_REPO_ROOT, "tests", "golden")
PREGATE_INPUTS = {
    "trace": os.path.join(GOLDEN_DIR, "obsreport_trace.json"),
    "metrics": os.path.join(GOLDEN_DIR, "obsreport_metrics.json"),
    "ledger": os.path.join(GOLDEN_DIR, "obsreport_ledger.json"),
    "calibration": os.path.join(
        GOLDEN_DIR, "obsreport_calibration.json"
    ),
    "golden": os.path.join(GOLDEN_DIR, "obsreport_report.txt"),
}
PREGATE_COMBOS = ["golden/S2"]


def _f(v: Optional[float], nd: int = 3) -> str:
    return "-" if v is None else f"{v:.{nd}f}"


def render_report(
    chrome: dict,
    metrics: Optional[dict] = None,
    ledger: Optional[dict] = None,
    combos: Optional[List[str]] = None,
    calibration: Optional[dict] = None,
) -> str:
    """The unified text report (module docstring). Deterministic: no
    paths, no timestamps, sorted sections."""
    attr = attribute(chrome)
    lines: List[str] = ["== obsreport =="]
    lines.append(
        f"trace: {attr.n_events} spans, main track {attr.main_tid}, "
        f"wall {_f(attr.wall_ms)} ms"
    )
    lines.append("")
    lines.append("-- attribution (per phase) --")
    lines.append(
        f"{'phase':<24}{'count':>7}{'total_ms':>12}{'mean_ms':>12}"
        f"{'share%':>9}"
    )
    for p in attr.phases:
        lines.append(
            f"{p.name:<24}{p.count:>7}{p.total_ms:>12.3f}"
            f"{p.mean_ms:>12.3f}{p.share * 100:>9.2f}"
        )
    lines.append(
        f"unattributed residual: {_f(attr.residual_ms)} ms "
        f"({attr.residual_share * 100:.2f}% of wall)"
    )
    if ledger is not None and combos:
        lines.append("")
        lines.append("-- measured vs predicted (per combo) --")
        lines.append(
            f"{'combo':<36}{'predicted_ms':>14}"
            f"{'sync_ms/step':>14}{'delta%':>9}"
        )
        for row in reconcile(attr, ledger, combos):
            delta = row["delta_pct"]
            lines.append(
                f"{row['combo']:<36}{_f(row['predicted_ms']):>14}"
                f"{_f(row['measured_sync_ms_per_step']):>14}"
                f"{('%+.1f' % delta) if delta is not None else '-':>9}"
            )
    if metrics:
        hists: Dict[str, dict] = metrics.get("histograms", {})
        if hists:
            lines.append("")
            lines.append("-- SLO histograms --")
            for name in sorted(hists):
                h = hists[name]
                q = h.get("quantiles", {})
                lines.append(
                    f"{name:<28}n={h.get('count', 0):<7}"
                    f"p50 {_f(q.get('p50'), 6)}  "
                    f"p90 {_f(q.get('p90'), 6)}  "
                    f"p99 {_f(q.get('p99'), 6)}  "
                    f"[{h.get('mode', '?')}]"
                )
        scalars = []
        for kind in ("counters", "gauges"):
            for name, v in sorted(metrics.get(kind, {}).items()):
                scalars.append(f"{name:<28}{v:g}  [{kind[:-1]}]")
        if scalars:
            lines.append("")
            lines.append("-- counters / gauges --")
            lines += scalars
    if calibration:
        lines.append("")
        lines.append("-- calibration drift (reported, not gated) --")
        committed = calibration.get("committed_constants", {})
        fitted = calibration.get("constants", {})
        drift = calibration.get("drift_pct", {})
        for key in sorted(fitted):
            lines.append(
                f"{key:<34}committed {committed.get(key, 0):g}  "
                f"fitted {fitted[key]:g}  "
                f"({drift.get(key, 0):+.2f}%)"
            )
        rms = calibration.get("residual_rms_s")
        if rms is not None:
            lines.append(
                f"fit residual rms: {rms:.9f} s over "
                f"{calibration.get('n_rows', 0)} rows"
            )
    return "\n".join(lines) + "\n"


def report_json(
    chrome: dict,
    metrics: Optional[dict] = None,
    ledger: Optional[dict] = None,
    combos: Optional[List[str]] = None,
    calibration: Optional[dict] = None,
) -> dict:
    """The machine twin of `render_report`."""
    attr: Attribution = attribute(chrome)
    out = {"attribution": attr.as_dict()}
    if ledger is not None and combos:
        out["measured_vs_predicted"] = reconcile(attr, ledger, combos)
    if metrics:
        out["metrics"] = metrics
    if calibration:
        out["calibration_drift"] = calibration.get("drift_pct", {})
    return out


def _load_json(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="obsreport",
        description=(
            "Unified run report: trace attribution + SLO histograms "
            "+ measured-vs-predicted + calibration drift "
            "(INTERNALS.md section 14)."
        ),
    )
    parser.add_argument("--trace", default=None,
                        help="Chrome trace JSON (.json or .json.gz)")
    parser.add_argument(
        "--profile-dir", default=None,
        help="scan a jax.profiler capture directory for its newest "
             "trace.json(.gz) instead of --trace",
    )
    parser.add_argument("--metrics", default=None,
                        help="metrics JSON (--metrics-out output)")
    parser.add_argument("--ledger", default=None,
                        help="cost ledger (experiments/cost_ledger"
                             ".json) for measured-vs-predicted rows")
    parser.add_argument(
        "--combo", action="append", default=[],
        help="ledger combo name to reconcile against; repeatable",
    )
    parser.add_argument("--calibration", default=None,
                        help="calibration.json for the drift section")
    parser.add_argument("--json", action="store_true",
                        help="emit the JSON twin instead of text")
    parser.add_argument("--out", default=None,
                        help="also write the report to this path")
    parser.add_argument(
        "--pregate", action="store_true",
        help="render the canned golden inputs and byte-compare "
             "against the committed golden report (exit 5 on "
             "mismatch) — the tier-1 smoke",
    )
    parser.add_argument(
        "--update-golden", action="store_true",
        help="with --pregate: rewrite the committed golden report "
             "from the canned inputs (commit the diff deliberately)",
    )
    args = parser.parse_args(argv)

    if args.pregate:
        chrome = load_trace(PREGATE_INPUTS["trace"])
        got = render_report(
            chrome,
            metrics=_load_json(PREGATE_INPUTS["metrics"]),
            ledger=_load_json(PREGATE_INPUTS["ledger"]),
            combos=PREGATE_COMBOS,
            calibration=_load_json(PREGATE_INPUTS["calibration"]),
        )
        if args.update_golden:
            with open(PREGATE_INPUTS["golden"], "w") as f:
                f.write(got)
            print(f"[obsreport] wrote {PREGATE_INPUTS['golden']}")
            return 0
        try:
            with open(PREGATE_INPUTS["golden"]) as f:
                want = f.read()
        except OSError as e:
            print(f"[obsreport] cannot read golden: {e}",
                  file=sys.stderr)
            return EXIT_GOLDEN_MISMATCH
        if got != want:
            got_l, want_l = got.splitlines(), want.splitlines()
            for i in range(max(len(got_l), len(want_l))):
                g = got_l[i] if i < len(got_l) else "<missing>"
                w = want_l[i] if i < len(want_l) else "<missing>"
                if g != w:
                    print(
                        f"[obsreport] FAIL golden mismatch at line "
                        f"{i + 1}:\n  want: {w}\n  got:  {g}"
                    )
                    break
            print(json.dumps({"obsreport": {
                "pregate": "fail",
                "golden": PREGATE_INPUTS["golden"],
            }}))
            return EXIT_GOLDEN_MISMATCH
        print(json.dumps({"obsreport": {
            "pregate": "ok", "bytes": len(got),
            "combos": PREGATE_COMBOS,
        }}))
        return 0

    trace_path = args.trace
    if trace_path is None and args.profile_dir:
        hits = profile_dir_traces(args.profile_dir)
        if not hits:
            print(
                f"[obsreport] no trace.json(.gz) under "
                f"{args.profile_dir}", file=sys.stderr,
            )
            return 2
        trace_path = hits[0]
    if trace_path is None:
        print("[obsreport] --trace or --profile-dir required "
              "(or --pregate)", file=sys.stderr)
        return 2
    try:
        chrome = load_trace(trace_path)
    except (OSError, ValueError) as e:
        print(f"[obsreport] cannot read trace: {e}", file=sys.stderr)
        return 2
    metrics = _load_json(args.metrics) if args.metrics else None
    ledger = _load_json(args.ledger) if args.ledger else None
    calibration = (
        _load_json(args.calibration) if args.calibration else None
    )
    if args.json:
        rendered = json.dumps(report_json(
            chrome, metrics, ledger, args.combo or None, calibration,
        ), indent=1) + "\n"
    else:
        rendered = render_report(
            chrome, metrics, ledger, args.combo or None, calibration,
        )
    sys.stdout.write(rendered)
    if args.out:
        with open(args.out, "w") as f:
            f.write(rendered)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())


__all__ = [
    "EXIT_GOLDEN_MISMATCH",
    "PREGATE_COMBOS",
    "PREGATE_INPUTS",
    "main",
    "render_report",
    "report_json",
]
