#!/usr/bin/env bash
# Tier-1 verify — THE canonical test command (ROADMAP.md "Tier-1
# verify"). Checked in so builder and reviewer run the same line instead
# of copy-pasting divergent variants.
#
#   bash tools/tier1.sh            # from the repo root
#
# Behavior, kept bit-identical to the ROADMAP line:
#   * CPU-only jax (the conftest also forces it; the env var keeps the
#     PJRT plugin from dialing the TPU relay at interpreter start),
#   * the default marker filter (-m 'not slow', see pytest.ini),
#   * survives collection errors so one broken module can't hide the
#     rest of the suite's result,
#   * 870 s budget with a hard kill 10 s later,
#   * DOTS_PASSED=<n> printed from the progress dots as a
#     tamper-resistant pass count (parsed from the tee'd log, not from
#     pytest's summary line),
#   * exits with pytest's status (PIPESTATUS survives the tee).

set -o pipefail
cd "$(dirname "$0")/.."

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
