"""Per-rule positive/negative tests for the collective-contract
registry (`analysis/rules.py`): every rule is exercised on canned HLO
(and canned LintTargets) with one case where the contract is VIOLATED
(the rule must fire) and one where it holds (the rule must stay
silent). The conftest meta-check walks the `hlo_rule(<id>, <polarity>)`
markers and fails collection if a registered rule is missing either
polarity — a rule nobody can trip is a rule nobody can trust.

No lowering here: synthetic modules keep these tier-1 fast. The live
negatives (real engines lint clean) are tests/test_hlolint.py."""

import pytest

from distributed_model_parallel_tpu.analysis.collectives import MeshModel
from distributed_model_parallel_tpu.analysis.rules import (
    LintContext,
    LintTarget,
    REGISTRY,
)

MESH8 = MeshModel(
    axis_names=("data",), shape=(8,), coords={d: (d,) for d in range(8)}
)
MESH_2x4 = MeshModel(
    axis_names=("dcn", "ici"), shape=(2, 4),
    coords={d: (d // 4, d % 4) for d in range(8)},
)
MESH_M4 = MeshModel(
    axis_names=("model",), shape=(4,), coords={d: (d,) for d in range(4)}
)

ICI_PAIRS = "{0,1},{1,2},{2,3},{3,0},{4,5},{5,6},{6,7},{7,4}"
DATA_PAIRS = "{0,1},{1,2},{2,3},{3,4},{4,5},{5,6},{6,7},{7,0}"
ICI_GROUPS = "{{0,1,2,3},{4,5,6,7}}"
DCN_GROUPS = "{{0,4},{1,5},{2,6},{3,7}}"
# permute pairs that stay WITHIN 'dcn' on MESH_2x4 (cross-slice hops)
DCN_PAIRS_2x4 = "{0,4},{4,0},{1,5},{5,1},{2,6},{6,2},{3,7},{7,3}"
M4_PAIRS = "{0,1},{1,2},{2,3},{3,0}"


def module(body_lines, header_extra="", params=("p: f32[64]",)):
    """Wrap instruction lines into a minimal parseable module."""
    plist = ", ".join(params)
    body = ["  %{} = {} parameter({})".format(
        p.split(":")[0], p.split(": ")[1] + "{0}", i
    ) for i, p in enumerate(params)]
    body += ["  " + ln.strip() for ln in body_lines]
    body.append("  ROOT %ret = f32[] constant(0)")
    return (
        "HloModule m" + header_extra + "\n\n"
        + "ENTRY %main (" + plist + ") -> f32[] {\n"
        + "\n".join(body) + "\n}\n"
    )


def perm(name, operand, pairs, shape="f32[16]", tag=None):
    meta = (
        ', metadata={op_name="jit(f)/%s/ppermute"}' % tag if tag else ""
    )
    return (
        "%{n} = {s}{{0}} collective-permute({s}{{0}} %{o}), "
        "source_target_pairs={{{p}}}{m}".format(
            n=name, s=shape, o=operand, p=pairs, m=meta
        )
    )


def allreduce(name, operand, groups, shape="f32[16]", tag=None):
    meta = (
        ', metadata={op_name="jit(f)/%s/psum"}' % tag if tag else ""
    )
    return (
        "%{n} = {s}{{0}} all-reduce({s}{{0}} %{o}), "
        "replica_groups={g}, use_global_device_ids=true{m}".format(
            n=name, s=shape, o=operand, g=groups, m=meta
        )
    )


def check(rule_id, target, hlo, mesh):
    rule = REGISTRY[rule_id]
    assert rule.applies(target), (
        f"{rule_id} should apply to this target"
    )
    return rule.check(LintContext.build(target, hlo, mesh))


def hybrid_reducer_target(**kw):
    base = dict(
        name="t", engine="ddp", grad_reduction="bucketed",
        data_axes=("dcn", "ici"), ici_axis="ici", dcn_axis="dcn",
        ici_size=4, dcn_size=2,
        bucket_plans=(((64, "f32"),),),  # one 64-elem padded bucket
    )
    base.update(kw)
    return LintTarget(**base)


def plain_reducer_target(**kw):
    base = dict(
        name="t", engine="ddp", grad_reduction="bucketed",
        data_axes=("data",), ici_axis="data", ici_size=8,
        bucket_plans=(((64, "f32"),),),
    )
    base.update(kw)
    return LintTarget(**base)


# ------------------------------------------------ dcn-grad-all-reduce


@pytest.mark.hlo_rule("dcn-grad-all-reduce", "positive")
def test_dcn_grad_all_reduce_fires_on_full_bucket_over_dcn():
    # 64-elem f32 over 'dcn' = 256 B > the 16-elem (64 B) 1/ici shard.
    hlo = module([allreduce("ar", "p", DCN_GROUPS, shape="f32[64]")])
    found = check(
        "dcn-grad-all-reduce", hybrid_reducer_target(), hlo, MESH_2x4
    )
    assert found and "crosses 'dcn'" in found[0].message


@pytest.mark.hlo_rule("dcn-grad-all-reduce", "negative")
def test_dcn_grad_all_reduce_allows_shard_sized_hop():
    hlo = module([allreduce("ar", "p", DCN_GROUPS, shape="f32[16]")])
    assert check(
        "dcn-grad-all-reduce", hybrid_reducer_target(), hlo, MESH_2x4
    ) == []


# ------------------------------------------------ bucket-ring-permutes


@pytest.mark.hlo_rule("bucket-ring-permutes", "positive")
def test_bucket_ring_permutes_fires_on_missing_hop():
    # expected 2*(4-1)*1 = 6 ici permutes; provide 5.
    lines = [perm(f"cp{i}", "p", ICI_PAIRS) for i in range(5)]
    found = check(
        "bucket-ring-permutes", hybrid_reducer_target(), module(lines),
        MESH_2x4,
    )
    assert found and "expected 2*(4-1)*1 = 6" in found[0].message


@pytest.mark.hlo_rule("bucket-ring-permutes", "negative")
def test_bucket_ring_permutes_exact_count_is_clean():
    lines = [perm(f"cp{i}", "p", ICI_PAIRS) for i in range(6)]
    # a 'dcn'-crossing permute must NOT count toward the ici rings
    lines.append(perm("cpx", "p", "{0,4},{4,0}"))
    assert check(
        "bucket-ring-permutes", hybrid_reducer_target(), module(lines),
        MESH_2x4,
    ) == []


# ---------------------------------------------- dcn-bucket-psum-shard


@pytest.mark.hlo_rule("dcn-bucket-psum-shard", "positive")
def test_dcn_bucket_psum_shard_fires_on_wrong_shape():
    hlo = module([allreduce("ar", "p", DCN_GROUPS, shape="f32[64]")])
    found = check(
        "dcn-bucket-psum-shard", hybrid_reducer_target(), hlo, MESH_2x4
    )
    assert found and "1/ici shards" in found[0].message


@pytest.mark.hlo_rule("dcn-bucket-psum-shard", "negative")
def test_dcn_bucket_psum_shard_pinned_shape_is_clean():
    hlo = module([allreduce("ar", "p", DCN_GROUPS, shape="f32[16]")])
    assert check(
        "dcn-bucket-psum-shard", hybrid_reducer_target(), hlo, MESH_2x4
    ) == []


# -------------------------------------------------- no-grad-all-reduce


@pytest.mark.hlo_rule("no-grad-all-reduce", "positive")
def test_no_grad_all_reduce_fires_on_fused_grad_reduction():
    hlo = module(
        [allreduce("ar", "p", "{{0,1,2,3,4,5,6,7}}", shape="f32[100]")]
    )
    found = check(
        "no-grad-all-reduce",
        plain_reducer_target(state_leaf_shapes=((16,),)), hlo, MESH8,
    )
    assert found and "grad-sized" in found[0].message


@pytest.mark.hlo_rule("no-grad-all-reduce", "negative")
def test_no_grad_all_reduce_allows_bn_stats_and_scalars():
    hlo = module([
        allreduce("bn", "p", "{{0,1,2,3,4,5,6,7}}", shape="f32[16]"),
        allreduce("m", "p", "{{0,1,2,3,4,5,6,7}}", shape="f32[]"),
    ])
    assert check(
        "no-grad-all-reduce",
        plain_reducer_target(state_leaf_shapes=((16,),)), hlo, MESH8,
    ) == []


def test_no_grad_all_reduce_fused_tuple_cannot_smuggle_over_dcn():
    """A combiner-fused tuple all-reduce whose FIRST buffer matches a
    pinned 1/ici bucket shard must still fire when any OTHER buffer is
    grad-sized — every buffer is checked against the allowlist."""
    hlo = module(
        [
            "%art = (f32[16]{0}, f32[100]{0}) all-reduce(f32[16]{0} %p, "
            "f32[100]{0} %p2), replica_groups=" + DCN_GROUPS
            + ", use_global_device_ids=true",
        ],
        params=("p: f32[16]", "p2: f32[100]"),
    )
    found = check(
        "no-grad-all-reduce", hybrid_reducer_target(), hlo, MESH_2x4
    )
    assert found and "grad-sized" in found[0].message


# -------------------------------------------------- cm-ring-permutes


def cm_op_target(**kw):
    base = dict(
        name="t", engine="cm_ag", data_axes=(), ici_axis=None,
        ici_size=1, cm_axis="model", cm_size=4, expected_permutes=3,
    )
    base.update(kw)
    return LintTarget(**base)


@pytest.mark.hlo_rule("cm-ring-permutes", "positive")
def test_cm_ring_permutes_fires_on_short_chain():
    lines = [perm(f"cp{i}", "p", M4_PAIRS) for i in range(2)]
    found = check("cm-ring-permutes", cm_op_target(), module(lines),
                  MESH_M4)
    assert found and "expected exactly 3" in found[0].message


@pytest.mark.hlo_rule("cm-ring-permutes", "negative")
def test_cm_ring_permutes_s_minus_1_is_clean():
    lines = [perm(f"cp{i}", "p", M4_PAIRS) for i in range(3)]
    assert check(
        "cm-ring-permutes", cm_op_target(), module(lines), MESH_M4
    ) == []


# ------------------------------------------- cm-monolithic-collective


@pytest.mark.hlo_rule("cm-monolithic-collective", "positive")
def test_cm_monolithic_fires_on_surviving_all_gather():
    hlo = module([
        perm("cp0", "p", M4_PAIRS),
        "%ag = f32[64]{0} all-gather(f32[64]{0} %p), "
        "replica_groups={{0,1,2,3}}, dimensions={0}, "
        "use_global_device_ids=true",
    ])
    found = check(
        "cm-monolithic-collective", cm_op_target(), hlo, MESH_M4
    )
    assert found and "monolithic all-gather" in found[0].message


@pytest.mark.hlo_rule("cm-monolithic-collective", "negative")
def test_cm_monolithic_permute_only_kernel_is_clean():
    lines = [perm(f"cp{i}", "p", M4_PAIRS) for i in range(3)]
    assert check(
        "cm-monolithic-collective", cm_op_target(), module(lines),
        MESH_M4,
    ) == []


# ------------------------------------------------- serve-decode-ring


def serve_target(**kw):
    base = dict(
        name="t", engine="serve", collective_matmul=True,
        data_axes=(), ici_axis=None, ici_size=1,
        cm_axis="model", cm_size=4, serve_decode_permutes=2,
    )
    base.update(kw)
    return LintTarget(**base)


@pytest.mark.hlo_rule("serve-decode-ring", "positive")
def test_serve_decode_ring_fires_on_short_chain_and_gather():
    # One tagged permute where two are pinned, plus a surviving
    # monolithic all-gather over the TP axis: both findings fire.
    hlo = module([
        perm("cp0", "p", M4_PAIRS, tag="serve_ring"),
        "%ag = f32[64]{0} all-gather(f32[64]{0} %p), "
        "replica_groups={{0,1,2,3}}, dimensions={0}, "
        "use_global_device_ids=true",
    ])
    found = check("serve-decode-ring", serve_target(), hlo, MESH_M4)
    msgs = "; ".join(f.message for f in found)
    assert "expected exactly 2" in msgs
    assert "monolithic all-gather" in msgs


@pytest.mark.hlo_rule("serve-decode-ring", "negative")
def test_serve_decode_ring_tagged_chain_is_clean():
    # The pinned tagged count, plus an UNTAGGED permute (GSPMD's own
    # resharding traffic) that must not be counted against the pin.
    hlo = module([
        perm("cp0", "p", M4_PAIRS, tag="serve_ring"),
        perm("cp1", "cp0", M4_PAIRS, tag="serve_ring"),
        perm("cp2", "cp1", M4_PAIRS),
    ])
    assert check(
        "serve-decode-ring", serve_target(), hlo, MESH_M4
    ) == []


def test_serve_decode_ring_missing_expectation_is_a_finding():
    """An opted-in serving combo whose builder forgot the permute
    expectation must surface, not silently pass."""
    hlo = module([perm("cp0", "p", M4_PAIRS, tag="serve_ring")])
    found = check(
        "serve-decode-ring",
        serve_target(serve_decode_permutes=None), hlo, MESH_M4,
    )
    assert found and "was not checked" in found[0].message


# --------------------------------------------------- spec-verify-step


def spec_target(**kw):
    base = dict(
        name="t", engine="serve", collective_matmul=True,
        data_axes=(), ici_axis=None, ici_size=1,
        cm_axis="model", cm_size=4, speculative_k=2,
        spec_verify_permutes=2,
    )
    base.update(kw)
    return LintTarget(**base)


@pytest.mark.hlo_rule("spec-verify-step", "positive")
def test_spec_verify_step_fires_on_k_scaled_rings_and_gather():
    # A verify step whose ring count scaled with the chunk (3 tagged
    # permutes where ONE decode step's 2 are pinned) plus a surviving
    # monolithic all-gather over the TP axis: both findings fire.
    hlo = module([
        perm("cp0", "p", M4_PAIRS, tag="serve_ring"),
        perm("cp1", "cp0", M4_PAIRS, tag="serve_ring"),
        perm("cp2", "cp1", M4_PAIRS, tag="serve_ring"),
        "%ag = f32[64]{0} all-gather(f32[64]{0} %p), "
        "replica_groups={{0,1,2,3}}, dimensions={0}, "
        "use_global_device_ids=true",
    ])
    found = check("spec-verify-step", spec_target(), hlo, MESH_M4)
    msgs = "; ".join(f.message for f in found)
    assert "expected exactly 2" in msgs
    assert "independent of k=2" in msgs
    assert "monolithic all-gather" in msgs


@pytest.mark.hlo_rule("spec-verify-step", "negative")
def test_spec_verify_step_decode_inventory_is_clean():
    # Exactly one decode step's tagged rings; an UNTAGGED permute
    # (GSPMD's own resharding traffic) must not count against the pin.
    hlo = module([
        perm("cp0", "p", M4_PAIRS, tag="serve_ring"),
        perm("cp1", "cp0", M4_PAIRS, tag="serve_ring"),
        perm("cp2", "cp1", M4_PAIRS),
    ])
    assert check(
        "spec-verify-step", spec_target(), hlo, MESH_M4
    ) == []


def test_spec_verify_step_missing_expectation_is_a_finding():
    """A speculative combo whose builder forgot the verify-ring
    expectation must surface, not silently pass."""
    hlo = module([perm("cp0", "p", M4_PAIRS, tag="serve_ring")])
    found = check(
        "spec-verify-step",
        spec_target(spec_verify_permutes=None), hlo, MESH_M4,
    )
    assert found and "was not checked" in found[0].message


def test_spec_verify_step_and_decode_ring_never_double_fire():
    """A speculative target is judged by spec-verify-step only: the
    decode-ring pin defers (its expectation describes the decode
    step's HLO, and a speculative combo lowers the verify step)."""
    assert REGISTRY["spec-verify-step"].applies(spec_target())
    assert not REGISTRY["serve-decode-ring"].applies(spec_target())
    assert REGISTRY["serve-decode-ring"].applies(serve_target())
    assert not REGISTRY["spec-verify-step"].applies(serve_target())


# --------------------------------------------------- fsdp-at-rest-sharded


def fsdp_target(**kw):
    base = dict(
        name="t", engine="fsdp", data_axes=("data",), ici_axis="data",
        ici_size=8, fsdp_full_leaf_shapes=((128, 128),),
    )
    base.update(kw)
    return LintTarget(**base)


@pytest.mark.hlo_rule("fsdp-at-rest-sharded", "positive")
def test_fsdp_at_rest_fires_on_full_leaf_at_rest():
    hlo = module([], params=("p: f32[128,128]",))
    found = check("fsdp-at-rest-sharded", fsdp_target(), hlo, MESH8)
    assert found and "materialized at rest" in found[0].message


@pytest.mark.hlo_rule("fsdp-at-rest-sharded", "negative")
def test_fsdp_at_rest_sharded_entry_is_clean():
    hlo = module([], params=("p: f32[16,128]",))
    assert check("fsdp-at-rest-sharded", fsdp_target(), hlo, MESH8) == []


def test_fsdp_at_rest_vacuous_policy_is_a_finding():
    """A model/mesh where the policy shards nothing must surface, not
    silently pass."""
    hlo = module([], params=("p: f32[16,128]",))
    found = check(
        "fsdp-at-rest-sharded", fsdp_target(fsdp_full_leaf_shapes=()),
        hlo, MESH8,
    )
    assert found and "vacuous" in found[0].message


# ---------------------------------------------- overlap-first-bucket-free


def overlap_target(**kw):
    base = dict(
        name="t", engine="ddp", grad_reduction="overlapped",
        data_axes=("data",), ici_axis="data", ici_size=8,
        overlap_segments=2, bucket_plans=(((64, "f32"),), ((64, "f32"),)),
    )
    base.update(kw)
    return LintTarget(**base)


def overlap_module(first_operand):
    """bwd_stage1 -> grad_reduce_stage1 permute (first-fired, operand
    configurable) and bwd_stage0 -> grad_reduce_stage0 permute (the
    positive control)."""
    return module([
        '%b1 = f32[16]{0} negate(f32[16]{0} %p), '
        'metadata={op_name="jit(f)/bwd_stage1/neg"}',
        perm("g1", first_operand, DATA_PAIRS, tag="grad_reduce_stage1"),
        '%b0 = f32[16]{0} negate(f32[16]{0} %b1), '
        'metadata={op_name="jit(f)/bwd_stage0/neg"}',
        perm("g0", "b0", DATA_PAIRS, tag="grad_reduce_stage0"),
    ])


@pytest.mark.hlo_rule("overlap-first-bucket-free", "positive")
def test_overlap_first_bucket_fires_on_serialized_firing():
    # the first-fired bucket's permute consumes stage-0 backward output
    found = check(
        "overlap-first-bucket-free", overlap_target(),
        overlap_module("b0"), MESH8,
    )
    assert found and "serialized" in found[0].message


@pytest.mark.hlo_rule("overlap-first-bucket-free", "negative")
def test_overlap_first_bucket_independent_is_clean():
    assert check(
        "overlap-first-bucket-free", overlap_target(),
        overlap_module("b1"), MESH8,
    ) == []


def test_overlap_missing_tags_is_a_finding():
    """Renamed scopes must fail loudly, not let the pin rot."""
    hlo = module([perm("cp", "p", DATA_PAIRS)])
    found = check(
        "overlap-first-bucket-free", overlap_target(), hlo, MESH8
    )
    assert found and any("tags moved" in f.message for f in found)


# ------------------------------------------------- prefetch-gather-free


def fsdp_overlap_target(**kw):
    base = dict(
        name="t", engine="fsdp", grad_reduction="overlapped",
        data_axes=("data",), ici_axis="data", ici_size=8,
        overlap_segments=2, bucket_plans=(((64, "f32"),), ((64, "f32"),)),
        fsdp_full_leaf_shapes=((128, 128),),
    )
    base.update(kw)
    return LintTarget(**base)


def prefetch_module(gather_operand):
    return module([
        perm("r1", "p", DATA_PAIRS, tag="grad_reduce_stage1"),
        perm("r0", "p", DATA_PAIRS, tag="grad_reduce_stage0"),
        "%pg = f32[128]{0} all-gather(f32[16]{0} %" + gather_operand
        + "), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}, "
        'use_global_device_ids=true, metadata={op_name='
        '"jit(f)/prefetch_gather_stage0/all_gather"}',
    ], params=("p: f32[16]",))


@pytest.mark.hlo_rule("prefetch-gather-free", "positive")
def test_prefetch_gather_fires_when_fed_by_reduction():
    found = check(
        "prefetch-gather-free", fsdp_overlap_target(),
        prefetch_module("r1"), MESH8,
    )
    assert found and "overlap serialized" in found[0].message


@pytest.mark.hlo_rule("prefetch-gather-free", "negative")
def test_prefetch_gather_from_shards_is_clean():
    assert check(
        "prefetch-gather-free", fsdp_overlap_target(),
        prefetch_module("p"), MESH8,
    ) == []


# --------------------------------------------------- bf16-ring-upcast


def bf16_target(**kw):
    base = dict(
        name="t", engine="tp", collective_matmul=True, bf16=True,
        cm_axis="model", cm_size=4, cm_min_ring_permutes=0,
        data_axes=("data",), ici_axis="data", ici_size=2,
        ring_dtypes=(
            (("model",), "bf16", "jvp(ag_matmul)"),
            (("data",), "f32", "jvp(bucket_ring)"),
        ),
    )
    base.update(kw)
    return LintTarget(**base)


@pytest.mark.hlo_rule("bf16-ring-upcast", "positive")
def test_bf16_ring_upcast_fires_on_f32_cm_ring():
    found = check(
        "bf16-ring-upcast",
        bf16_target(ring_dtypes=((("model",), "f32", "jvp(ag_matmul)"),)),
        module([]), MESH8,
    )
    assert found and "silent upcast" in found[0].message


@pytest.mark.hlo_rule("bf16-ring-upcast", "negative")
def test_bf16_ring_upcast_bf16_rings_clean_f32_buckets_allowed():
    # grad-bucket rings over the data axis legitimately stay f32
    # (f32 master params); only the cm axis is pinned.
    assert check(
        "bf16-ring-upcast", bf16_target(), module([]), MESH8
    ) == []


def test_bf16_ring_upcast_exempts_the_kv_ring_scope():
    """The deliberately-f32 KV wire (accumulate-in-f32 contract,
    ops/ring_attention.py) is a named-scope exemption, not a finding —
    forward AND its transposed backward permutes."""
    assert check(
        "bf16-ring-upcast",
        bf16_target(
            cm_axis="seq",
            ring_dtypes=(
                (("seq",), "f32", "jvp(kv_ring)"),
                (("seq",), "f32", "transpose(jvp(kv_ring))"),
                (("seq",), "bf16", "jvp(ag_matmul)"),
            ),
        ),
        module([]), MESH8,
    ) == []


def test_bf16_ring_upcast_exemption_is_whole_word_not_substring():
    """A scope merely CONTAINING an exempt name (qkv_ring,
    kv_ring_cache) must not inherit the exemption."""
    found = check(
        "bf16-ring-upcast",
        bf16_target(ring_dtypes=(
            (("model",), "f32", "jvp(qkv_ring)"),
            (("model",), "f32", "jvp(kv_ring_cache)"),
        )),
        module([]), MESH8,
    )
    assert len(found) == 2


def test_bf16_ring_upcast_requires_jaxpr_data():
    found = check(
        "bf16-ring-upcast", bf16_target(ring_dtypes=()), module([]),
        MESH8,
    )
    assert found and "not checked" in found[0].message


# ------------------------------------------------ moe-hierarchical-a2a


def alltoall(name, operand, groups, shape="f32[16]"):
    return (
        "%{n} = {s}{{0}} all-to-all({s}{{0}} %{o}), "
        "replica_groups={g}, use_global_device_ids=true".format(
            n=name, s=shape, o=operand, g=groups
        )
    )


def moe_perm(name, operand, pairs, tag="moe_ring"):
    return perm(name, operand, pairs, tag=tag)


def moe_target(**kw):
    base = dict(
        name="t", engine="ep", moe_dispatch="hierarchical",
        data_axes=("dcn", "ici"), ici_axis="ici", dcn_axis="dcn",
        ici_size=4, dcn_size=2,
        # 1 MoE layer on a 2x4 fabric: 2*(2*(4-1) + 2*(2-1)) = 16.
        moe_ring_permutes=16,
    )
    base.update(kw)
    return LintTarget(**base)


@pytest.mark.hlo_rule("moe-hierarchical-a2a", "positive")
def test_moe_hierarchical_fires_on_flat_a2a_and_short_chain():
    # A surviving all-to-all over 'dcn' plus only one tagged hop: both
    # halves of the contract violated.
    lines = [
        alltoall("a2a", "p", DCN_GROUPS),
        moe_perm("cp0", "p", ICI_PAIRS),
    ]
    found = check("moe-hierarchical-a2a", moe_target(), module(lines),
                  MESH_2x4)
    msgs = " | ".join(f.message for f in found)
    assert "expected exactly 16" in msgs
    assert "all-to-all touching the data fabric" in msgs


@pytest.mark.hlo_rule("moe-hierarchical-a2a", "negative")
def test_moe_hierarchical_tagged_chain_is_clean():
    # 12 ici hops + 4 dcn hops (2 exchanges' worth fwd+bwd on 2x4,
    # transpose-spelled scopes included), no all-to-all anywhere.
    lines = (
        [moe_perm(f"ci{i}", "p", ICI_PAIRS) for i in range(9)]
        + [perm(f"ct{i}", "p", ICI_PAIRS, tag="transpose(moe_ring)")
           for i in range(3)]
        + [moe_perm(f"cd{i}", "p", DCN_PAIRS_2x4) for i in range(4)]
    )
    assert check(
        "moe-hierarchical-a2a", moe_target(), module(lines), MESH_2x4
    ) == []


def test_moe_hierarchical_missing_expectation_is_a_finding():
    found = check(
        "moe-hierarchical-a2a", moe_target(moe_ring_permutes=None),
        module([]), MESH_2x4,
    )
    assert found and "not checked" in found[0].message


def test_moe_hierarchical_untagged_permutes_do_not_count():
    # The right hop count but none scoped moe_ring: the chain pin must
    # fire (GSPMD resharding permutes are not the exchange).
    lines = [perm(f"cp{i}", "p", ICI_PAIRS) for i in range(16)]
    found = check(
        "moe-hierarchical-a2a", moe_target(), module(lines), MESH_2x4
    )
    assert found and "0 moe_ring-scoped" in found[0].message


# ---------------------------------------------- dcn-compressed-payload


def compressed_target(**kw):
    """DDP bucketed+int8 on the 2x4 hybrid: one 64-elem padded bucket
    -> 1/ici shard 16 elems -> 2(K-1)=2 dcn hops of 8 int8 elems each,
    one f32 scalar sidecar per hop."""
    base = dict(
        name="t", engine="ddp", grad_reduction="bucketed",
        data_axes=("dcn", "ici"), ici_axis="ici", dcn_axis="dcn",
        ici_size=4, dcn_size=2,
        bucket_plans=(((64, "f32"),),),
        dcn_compression="int8",
        dcn_wire_chunks=((8, "s8"), (8, "s8")),
        dcn_ring_records=(
            (("dcn",), "s8", "jit(f)/dcn_wire", 8),
            (("dcn",), "f32", "jit(f)/dcn_scale", 1),
            (("dcn",), "s8", "jit(f)/dcn_wire", 8),
            (("dcn",), "f32", "jit(f)/dcn_scale", 1),
            # intra-slice ring traffic stays f32 and must be ignored
            (("ici",), "f32", "jit(f)/bucket_ring", 16),
        ),
    )
    base.update(kw)
    return LintTarget(**base)


@pytest.mark.hlo_rule("dcn-compressed-payload", "positive")
def test_dcn_compressed_fires_on_f32_hop_and_grad_all_reduce():
    # An UNCODED f32 ppermute crossing 'dcn' in the trace, a payload
    # hop in the wrong dtype, AND a grad-sized f32 all-reduce crossing
    # 'dcn' in the compiled HLO: every half of the contract fires.
    hlo = module([allreduce("ar", "p", DCN_GROUPS, shape="f32[100]")],
                 params=("p: f32[100]",))
    found = check(
        "dcn-compressed-payload",
        compressed_target(dcn_ring_records=(
            (("dcn",), "f32", "jit(f)/bwd", 64),
            (("dcn",), "f32", "jit(f)/dcn_wire", 8),
            (("dcn",), "f32", "jit(f)/dcn_wire", 8),
        )),
        hlo, MESH_2x4,
    )
    msgs = " | ".join(f.message for f in found)
    assert "uncoded ppermute crosses 'dcn'" in msgs
    assert "expected compressed chunks" in msgs
    assert "all-reduce crosses 'dcn'" in msgs


@pytest.mark.hlo_rule("dcn-compressed-payload", "negative")
def test_dcn_compressed_pinned_wire_is_clean():
    # The exact chunk multiset in int8 + one sidecar per hop + a
    # state-shaped BN psum (allowlisted) + scalar metrics: clean.
    hlo = module([
        allreduce("bn", "p", "{{0,1,2,3,4,5,6,7}}", shape="f32[16]"),
        allreduce("m", "p", "{{0,1,2,3,4,5,6,7}}", shape="f32[]"),
    ])
    assert check(
        "dcn-compressed-payload",
        compressed_target(state_leaf_shapes=((16,),)), hlo, MESH_2x4,
    ) == []


def test_dcn_compressed_missing_records_is_a_finding():
    """A compressed combo whose builder collected no trace records must
    surface, not silently pass."""
    found = check(
        "dcn-compressed-payload",
        compressed_target(dcn_ring_records=()), module([]), MESH_2x4,
    )
    assert found and "not checked" in found[0].message


def test_dcn_compressed_missing_expectation_is_a_finding():
    found = check(
        "dcn-compressed-payload",
        compressed_target(dcn_wire_chunks=(), dcn_wire_hops=None),
        module([]), MESH_2x4,
    )
    assert found and any(
        "payload pin was not checked" in f.message for f in found
    )


def test_dcn_compressed_sidecar_accounting():
    """int8 demands exactly one f32 scalar sidecar per payload hop; a
    bf16 combo must carry none."""
    found = check(
        "dcn-compressed-payload",
        compressed_target(dcn_ring_records=(
            (("dcn",), "s8", "jit(f)/dcn_wire", 8),
            (("dcn",), "s8", "jit(f)/dcn_wire", 8),
            (("dcn",), "f32", "jit(f)/dcn_scale", 1),
        )),
        module([]), MESH_2x4,
    )
    assert found and "1 dcn_scale sidecars for 2" in found[0].message
    found = check(
        "dcn-compressed-payload",
        compressed_target(
            dcn_compression="bf16",
            dcn_wire_chunks=((8, "bf16"), (8, "bf16")),
            dcn_ring_records=(
                (("dcn",), "bf16", "jit(f)/dcn_wire", 8),
                (("dcn",), "bf16", "jit(f)/dcn_wire", 8),
                (("dcn",), "f32", "jit(f)/dcn_scale", 1),
            ),
        ),
        module([]), MESH_2x4,
    )
    assert found and "cast codec has no scale" in found[0].message


def test_dcn_compressed_hop_count_pin_for_moe():
    """The EP form of the pin: hop COUNT + wire dtype (chunk shapes are
    model-dependent), plus the dispatch-sized all-to-all ban."""
    ep = compressed_target(
        engine="ep", grad_reduction="monolithic",
        moe_dispatch="hierarchical", bucket_plans=(),
        dcn_wire_chunks=(), dcn_wire_hops=4,
        dcn_ring_records=tuple(
            (("dcn",), "s8", "jit(f)/moe_ring/dcn_wire", 48)
            for _ in range(4)
        ) + tuple(
            (("dcn",), "f32", "jit(f)/dcn_scale", 1) for _ in range(4)
        ),
    )
    assert check("dcn-compressed-payload", ep, module([]), MESH_2x4) == []
    # short chain + a surviving flat all-to-all over 'dcn'
    import dataclasses

    bad = check(
        "dcn-compressed-payload",
        dataclasses.replace(ep, dcn_ring_records=(
            (("dcn",), "s8", "jit(f)/moe_ring/dcn_wire", 48),
            (("dcn",), "f32", "jit(f)/dcn_scale", 1),
        )),
        module([alltoall("a2a", "p", DCN_GROUPS)]), MESH_2x4,
    )
    msgs = " | ".join(f.message for f in bad)
    assert "expected exactly 4" in msgs
    assert "all-to-all crosses 'dcn'" in msgs


def test_dcn_compressed_fsdp_gather_pin():
    """The FSDP half of the pin (ISSUE 16 satellite): the weight
    gather's dcn leg must appear as fsdp_gather-scoped coded ring hops
    matching the builder's multiset, and a fused all-gather crossing
    'dcn' is contraband on the compressed step (a leaf that fell off
    `_coded_dcn_gather`)."""
    fsdp = compressed_target(
        engine="fsdp", grad_reduction="monolithic",
        dcn_gather_chunks=((32, "s8"), (32, "s8")),
        dcn_ring_records=compressed_target().dcn_ring_records + (
            (("dcn",), "s8", "jit(f)/fsdp_gather/dcn_wire", 32),
            (("dcn",), "f32", "jit(f)/fsdp_gather/dcn_scale", 1),
            (("dcn",), "s8", "jit(f)/fsdp_gather/dcn_wire", 32),
            (("dcn",), "f32", "jit(f)/fsdp_gather/dcn_scale", 1),
        ),
    )
    assert check(
        "dcn-compressed-payload", fsdp, module([]), MESH_2x4
    ) == []
    # Gather hops missing from the trace + a surviving fused gather
    # over 'dcn' in the compiled HLO: both halves fire.
    import dataclasses

    bad = check(
        "dcn-compressed-payload",
        dataclasses.replace(
            fsdp, dcn_ring_records=compressed_target().dcn_ring_records,
        ),
        module([
            "%ag = f32[128]{0} all-gather(f32[64]{0} %p), "
            "replica_groups=" + DCN_GROUPS + ", dimensions={0}, "
            "use_global_device_ids=true",
        ]),
        MESH_2x4,
    )
    msgs = " | ".join(f.message for f in bad)
    assert "expected compressed weight-gather chunks" in msgs
    assert "monolithic all-gather crosses 'dcn'" in msgs


# ------------------------------------------------ decode-quantized-matmul


_QUANT_DOTS = tuple(("s8", "s8", (16, 48)) for _ in range(8))


def quant_serve_target(**kw):
    """Quantized serve decode on a single-host trace: 8 int8 projection
    dots (4 per layer x 2 layers), the f32 head, and one batched
    attention dot (rank-4 rhs — never counted as a projection)."""
    base = dict(
        name="t", engine="serve",
        data_axes=(), ici_axis=None, ici_size=1,
        compute_dtype="int8", quant_dot_count=8,
        head_weight_shape=(16, 61),
        decode_dot_records=_QUANT_DOTS + (
            ("f32", "f32", (16, 61)),
            ("f32", "f32", (2, 4, 16, 4)),
        ),
    )
    base.update(kw)
    return LintTarget(**base)


@pytest.mark.hlo_rule("decode-quantized-matmul", "positive")
def test_decode_quantized_fires_on_f32_projection_and_quantized_head():
    # 6 of 8 projections quantized, one fell back to f32, and the head
    # got quantized: the count pin, the zero-f32-projection pin and the
    # head-stays-f32 pin all fire.
    found = check(
        "decode-quantized-matmul",
        quant_serve_target(decode_dot_records=_QUANT_DOTS[:6] + (
            ("f32", "f32", (16, 48)),
            ("s8", "s8", (16, 61)),
        )),
        module([]), MESH_M4,
    )
    msgs = " | ".join(f.message for f in found)
    assert "expected exactly 8" in msgs
    assert "fell back to f32 arithmetic" in msgs
    assert "head stays f32" in msgs


@pytest.mark.hlo_rule("decode-quantized-matmul", "negative")
def test_decode_quantized_pinned_trace_is_clean():
    assert check(
        "decode-quantized-matmul", quant_serve_target(), module([]),
        MESH_M4,
    ) == []


def test_decode_quantized_missing_records_is_a_finding():
    """A quantized combo whose builder collected no dot records must
    surface, not silently pass."""
    found = check(
        "decode-quantized-matmul",
        quant_serve_target(decode_dot_records=(), quant_dot_count=None),
        module([]), MESH_M4,
    )
    assert found and "was not checked" in found[0].message


def test_decode_quantized_missing_head_record_is_a_finding():
    found = check(
        "decode-quantized-matmul",
        quant_serve_target(decode_dot_records=_QUANT_DOTS),
        module([]), MESH_M4,
    )
    assert found and any(
        "head-matmul-stays-f32 pin was not checked" in f.message
        for f in found
    )


# ------------------------------------------------- donated-step-aliased


@pytest.mark.hlo_rule("donated-step-aliased", "positive")
def test_donated_step_fires_without_alias_table():
    t = LintTarget(name="t", engine="ddp", donate=True, n_param_leaves=3)
    found = check("donated-step-aliased", t, module([]), MESH8)
    assert found and "double-buffered" in found[0].message


@pytest.mark.hlo_rule("donated-step-aliased", "negative")
def test_donated_step_with_alias_table_is_clean():
    t = LintTarget(name="t", engine="ddp", donate=True, n_param_leaves=3)
    hlo = module(
        [],
        header_extra=(
            ", input_output_alias={ {0}: (0, {}, may-alias), "
            "{1}: (1, {}, may-alias), {2}: (2, {}, may-alias) }"
        ),
    )
    assert check("donated-step-aliased", t, hlo, MESH8) == []


# --------------------------------------------- collective-fabric-known


@pytest.mark.hlo_rule("collective-fabric-known", "positive")
def test_fabric_known_fires_on_unresolvable_ids():
    hlo = module([allreduce("ar", "p", "{{0,9}}", shape="f32[16]")])
    t = LintTarget(name="t", engine="ddp")
    found = check("collective-fabric-known", t, hlo, MESH8)
    assert found and "does not resolve" in found[0].message


@pytest.mark.hlo_rule("collective-fabric-known", "negative")
def test_fabric_known_resolvable_ids_clean():
    hlo = module([allreduce("ar", "p", ICI_GROUPS, shape="f32[16]")])
    t = LintTarget(name="t", engine="ddp")
    assert check("collective-fabric-known", t, hlo, MESH8) == []


# ------------------------------------------------------ registry meta


def test_registry_shape():
    """>= 8 severity-tagged rules, each with contract + source + a
    callable applicability predicate (the acceptance-criteria floor)."""
    assert len(REGISTRY) >= 8
    for r in REGISTRY.values():
        assert r.severity in ("error", "warn")
        assert r.contract and r.source
        assert callable(r.applies) and callable(r.check)


def test_exemptions_report_but_do_not_count():
    from distributed_model_parallel_tpu.analysis.rules import run_rules

    t = LintTarget(
        name="t", engine="ddp", donate=True, n_param_leaves=3,
        exemptions={
            "donated-step-aliased": "intentional: lowering-only probe"
        },
    )
    ctx = LintContext.build(t, module([]), MESH8)
    found = [f for f in run_rules(ctx) if f.rule == "donated-step-aliased"]
    assert found and found[0].exempted
    assert "lowering-only" in found[0].exemption_reason


# ------------------------------------------- plan-* fabric rules


def plan_target(**kw):
    """Canned composed-plan target: a 2x2x2 PP x SP x DP plan whose
    traced collective inventory is exactly the contract — TWO
    plan_wire ppermutes on ('stage',) (the table-driven tick
    program's static count, for every schedule: forward wire +
    autodiff transpose under gpipe, up + down wires scheduled), one
    kv_ring hop on ('seq',), one fused plan_grad psum over all three
    axes."""
    base = dict(
        name="t", engine="plan",
        data_axes=("data",), ici_axis="data", ici_size=2,
        plan_axes=(("stage", 2), ("data", 2), ("seq", 2)),
        plan_collective_records=(
            ("ppermute", ("stage",), "f32",
             "jit(f)/plan_wire/ppermute", 64),
            ("ppermute", ("stage",), "f32",
             "jit(f)/transpose(plan_wire)/ppermute", 64),
            ("ppermute", ("seq",), "f32",
             "jit(f)/kv_ring/ppermute", 64),
            ("psum", ("stage", "data", "seq"), "f32",
             "jit(f)/plan_grad/psum", 64),
        ),
    )
    base.update(kw)
    return LintTarget(**base)


@pytest.mark.hlo_rule("plan-wire-fabric", "positive")
def test_plan_wire_fires_off_stage_axis():
    # The activation wire riding 'data' instead of 'stage' — the
    # composition put pipeline traffic on the wrong fabric.
    t = plan_target(plan_collective_records=(
        ("ppermute", ("data",), "f32",
         "jit(f)/plan_wire/ppermute", 64),
        ("psum", ("stage", "data", "seq"), "f32",
         "jit(f)/plan_grad/psum", 64),
    ))
    found = check("plan-wire-fabric", t, module([]), MESH8)
    assert found and "('stage',)" in found[0].message
    # Vacuity guard: a pp>1 plan with NO wire records also fires.
    t2 = plan_target(plan_collective_records=(
        ("psum", ("stage", "data", "seq"), "f32",
         "jit(f)/plan_grad/psum", 64),
    ))
    found2 = check("plan-wire-fabric", t2, module([]), MESH8)
    assert found2 and "not checked" in found2[0].message


@pytest.mark.hlo_rule("plan-wire-fabric", "negative")
def test_plan_wire_stage_only_clean():
    assert check(
        "plan-wire-fabric", plan_target(), module([]), MESH8
    ) == []
    # The scheduled twins trace the SAME static wire count — the
    # schedule-symmetric inventory the ISSUE 20 tick tables pin.
    for sched, v in (("1f1b", 1), ("interleaved", 2)):
        assert check(
            "plan-wire-fabric",
            plan_target(plan_schedule=sched, plan_virtual=v),
            module([]), MESH8,
        ) == []


@pytest.mark.hlo_rule("plan-wire-fabric", "positive")
def test_plan_wire_count_pins_table_driven_replay():
    # An UNROLLED per-tick program would trace O(ticks) stage
    # ppermutes; the rule pins the per-schedule static count (2) so
    # a replay regression cannot land silently.
    t = plan_target(
        plan_schedule="1f1b",
        plan_collective_records=(
            ("ppermute", ("stage",), "f32",
             "jit(f)/plan_wire/ppermute", 64),
            ("ppermute", ("stage",), "f32",
             "jit(f)/plan_wire/ppermute", 64),
            ("ppermute", ("stage",), "f32",
             "jit(f)/plan_wire/ppermute", 64),
            ("psum", ("stage", "data", "seq"), "f32",
             "jit(f)/plan_grad/psum", 64),
        ),
    )
    found = check("plan-wire-fabric", t, module([]), MESH8)
    assert found and "table-driven replay" in found[0].message


@pytest.mark.hlo_rule("plan-seq-fabric", "positive")
def test_plan_seq_fires_on_ring_off_seq_axis():
    # A kv_ring hop crossing 'stage' — the ring attention rotation
    # left the ICI fabric.
    t = plan_target(plan_collective_records=(
        ("ppermute", ("stage",), "f32",
         "jit(f)/plan_wire/ppermute", 64),
        ("ppermute", ("stage",), "f32",
         "jit(f)/kv_ring/ppermute", 64),
        ("psum", ("stage", "data", "seq"), "f32",
         "jit(f)/plan_grad/psum", 64),
    ))
    found = check("plan-seq-fabric", t, module([]), MESH8)
    assert found and "('seq',)" in found[0].message


@pytest.mark.hlo_rule("plan-seq-fabric", "negative")
def test_plan_seq_rings_on_seq_clean():
    assert check(
        "plan-seq-fabric", plan_target(), module([]), MESH8
    ) == []


@pytest.mark.hlo_rule("plan-grad-fabric", "positive")
def test_plan_grad_fires_on_partial_axis_psum():
    # A per-axis cascade ('data'-only psum under plan_grad) instead
    # of the single fused three-axis rendezvous.
    t = plan_target(plan_collective_records=(
        ("ppermute", ("stage",), "f32",
         "jit(f)/plan_wire/ppermute", 64),
        ("psum", ("data",), "f32", "jit(f)/plan_grad/psum", 64),
    ))
    found = check("plan-grad-fabric", t, module([]), MESH8)
    assert found and "fused psum" in found[0].message
    # An FSDP weight gather off the 'data' axis fires too.
    t2 = plan_target(plan_collective_records=(
        ("psum", ("stage", "data", "seq"), "f32",
         "jit(f)/plan_grad/psum", 64),
        ("all_gather", ("seq",), "f32",
         "jit(f)/plan_fsdp_gather/all_gather", 64),
    ))
    found2 = check("plan-grad-fabric", t2, module([]), MESH8)
    assert found2 and "plan_fsdp_gather" in found2[0].message


@pytest.mark.hlo_rule("plan-grad-fabric", "negative")
def test_plan_grad_fused_psum_and_data_gather_clean():
    t = plan_target(plan_collective_records=(
        ("ppermute", ("stage",), "f32",
         "jit(f)/plan_wire/ppermute", 64),
        ("psum", ("stage", "data", "seq"), "f32",
         "jit(f)/plan_grad/psum", 64),
        ("all_gather", ("data",), "f32",
         "jit(f)/plan_fsdp_gather/all_gather", 64),
    ))
    assert check("plan-grad-fabric", t, module([]), MESH8) == []
