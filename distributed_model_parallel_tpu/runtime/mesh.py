"""Device-mesh construction — the TPU-native replacement for process groups.

The reference bootstraps parallelism with an NCCL process group over a TCP
rendezvous (`code/distributed_training/model_parallel.py:57-58`) and a
`--world-size` flag; device placement is rank-scripted. Here the world is a
named `jax.sharding.Mesh` over axes

    ('data', 'stage', 'model', 'seq', 'expert')

and every engine addresses devices by axis name:
  data   — batch sharding + gradient psum (DataParallelEngine/DDPEngine)
  stage  — pipeline stages, activations move by ppermute (PipelineEngine)
  model  — tensor parallelism, Megatron weight shardings
           (TensorParallelEngine)
  seq    — sequence/context parallelism, ring attention / Ulysses
           all-to-all (SequenceParallelEngine)
  expert — expert parallelism, MoE expert weights sharded E/N per device
           (ExpertParallelEngine; dispatch all-to-alls from GSPMD)

A `MeshSpec` replaces `--world-size N`: any axis left at -1 absorbs the
remaining devices, so `MeshSpec(stage=4)` on 8 chips gives a
(2, 4, 1, 1, 1) mesh the way `--world-size 4` gave a 4-rank pipeline.

`MeshSpec(dcn=K)` factors the data axis over the two TPU fabrics: the
mesh then carries ('dcn', 'ici', ...) in place of 'data', with 'dcn'
the cross-slice (data-center network) factor and 'ici' the intra-slice
ring. Collectives can address the fabrics separately — the bucketed
gradient reducer (`ops/grad_reduction.py`) reduce-scatters over 'ici'
and all-reduces only the 1/N shard over 'dcn', the hierarchy PyTorch's
DDP gets from NCCL topology detection. Engines that shard a batch use
`data_axis_names(mesh)` instead of the literal 'data' so both mesh
families work. On a multi-process runtime the hybrid mesh is built with
`mesh_utils.create_hybrid_device_mesh` (slices = process granules);
single-process it is a virtual split of one host's devices.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "stage", "model", "seq", "expert")
# The factored spelling of the data axis on a hybrid (dcn>1) mesh:
# 'dcn' is slice-major (matches process granularity), 'ici' minor.
DATA_AXES_HYBRID = ("dcn", "ici")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. -1 on exactly one axis means 'all remaining
    devices'. `dcn` is the cross-slice factor of the data axis (1 =
    single fabric, the 'data' axis stays whole); it must divide the
    resolved data size."""

    data: int = -1
    stage: int = 1
    model: int = 1
    seq: int = 1
    expert: int = 1
    dcn: int = 1

    def resolve(self, n_devices: int) -> tuple[int, ...]:
        dims = [self.data, self.stage, self.model, self.seq, self.expert]
        wild = [i for i, d in enumerate(dims) if d == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {self}")
        fixed = math.prod(d for d in dims if d != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            dims[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {dims} needs {fixed} devices but {n_devices} present"
            )
        if self.dcn < 1:
            raise ValueError(f"dcn must be >= 1, got {self.dcn}")
        if dims[0] % self.dcn:
            raise ValueError(
                f"dcn={self.dcn} must divide the data axis ({dims[0]})"
            )
        return tuple(dims)


def make_mesh(
    spec: MeshSpec | None = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Sequence[str] = AXES,
) -> Mesh:
    """Build a named mesh over all (or the given) devices.

    Replaces `dist.init_process_group(...)` + rank arithmetic: after this,
    "which device does what" is a sharding annotation, not a script branch.

    With `spec.dcn > 1` the data axis splits into ('dcn', 'ici'): on a
    multi-process runtime the device order comes from
    `mesh_utils.create_hybrid_device_mesh` (each process granule is one
    slice, so 'ici' neighbors really are ICI neighbors); single-process
    it is a virtual split — the two-fabric PROGRAM structure on one
    host's devices.
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    shape = spec.resolve(len(devices))
    if spec.dcn == 1:
        dev_array = np.asarray(devices).reshape(shape)
        return Mesh(dev_array, axis_names=tuple(axis_names))
    dcn = spec.dcn
    ici = shape[0] // dcn
    hybrid_shape = (dcn, ici) + shape[1:]
    if jax.process_count() > 1:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            (ici,) + shape[1:],
            (dcn,) + (1,) * (len(shape) - 1),
            devices=devices,
        ).reshape(hybrid_shape)
    else:
        dev_array = np.asarray(devices).reshape(hybrid_shape)
    names = DATA_AXES_HYBRID + tuple(axis_names[1:])
    return Mesh(dev_array, axis_names=names)


def data_axis_names(mesh: Mesh) -> Tuple[str, ...]:
    """The axis-name tuple the data-parallel world lives on: ('data',)
    on a plain mesh, ('dcn', 'ici') on a hybrid one. Engines shard
    batches with `P(data_axis_names(mesh))` and reduce gradients over
    the same tuple, so one code path serves both mesh families."""
    return (
        DATA_AXES_HYBRID
        if DATA_AXES_HYBRID[0] in mesh.axis_names
        else ("data",)
    )


def data_axis_size(mesh: Mesh) -> int:
    """Total data-parallel ways (the product over `data_axis_names`)."""
    return int(
        math.prod(mesh.shape[a] for a in data_axis_names(mesh))
    )


def data_hierarchy_axes(mesh: Mesh):
    """(data_axes, ici_axis, dcn_axis) for gradient-reduction wiring:
    the full tuple for batch shards / fused collectives, the intra-
    slice axis the bucket rings run over, and the cross-slice axis for
    the 1/S-shard all-reduce (None on a single-fabric mesh). The one
    place the hybrid-axis convention is decoded — engines must not
    re-derive it."""
    d_axes = data_axis_names(mesh)
    ici_axis = d_axes[-1]
    dcn_axis = d_axes[0] if len(d_axes) > 1 else None
    return d_axes, ici_axis, dcn_axis


def mesh_axes(mesh: Mesh) -> dict:
    """Ordered `{axis name: size}` — the factorization record a sharded
    checkpoint manifest stores (`checkpointing/`), later handed back to
    `elastic_fit`'s `make_trainer` so a restart can compare the saved
    topology with the devices it actually has and rebuild RESIZED."""
    return {name: int(mesh.shape[name]) for name in mesh.axis_names}


def spec_from_axes(axes: dict) -> MeshSpec:
    """Inverse of `mesh_axes` for the two axis spellings this module
    builds: a plain ('data', ...) record maps straight onto MeshSpec
    fields; a hybrid ('dcn', 'ici', ...) record folds back into
    data=dcn*ici with the dcn factor preserved. Unknown axis names are
    rejected — a manifest from a foreign mesh layout must not silently
    drop a parallelism axis."""
    known = set(AXES) | set(DATA_AXES_HYBRID)
    unknown = set(axes) - known
    if unknown:
        raise ValueError(
            f"unknown mesh axes {sorted(unknown)} in topology record "
            f"(understood: {sorted(known)})"
        )
    if "dcn" in axes or "ici" in axes:
        dcn = int(axes.get("dcn", 1))
        data = dcn * int(axes.get("ici", 1))
        if "data" in axes:
            raise ValueError(
                "topology record mixes 'data' with 'dcn'/'ici' — the "
                "two spellings are exclusive"
            )
    else:
        dcn = 1
        data = int(axes.get("data", 1))
    return MeshSpec(
        data=data,
        stage=int(axes.get("stage", 1)),
        model=int(axes.get("model", 1)),
        seq=int(axes.get("seq", 1)),
        expert=int(axes.get("expert", 1)),
        dcn=dcn,
    )


def make_plan_mesh(
    pp: int, dp: int, sp: int,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """The composed-`ParallelPlan` mesh (`parallel/plan.py`, ISSUE 19):
    axes ('stage', 'data', 'seq') with the STAGE axis outermost.

    Ordering is the axis->fabric contract: the slowest-varying axis maps
    to the slowest fabric, so pipeline stages land across slices (DCN —
    their only traffic is one activation ppermute per tick), the 'seq'
    axis is innermost (its ring-attention / collective-matmul rings and
    grad psums need ICI neighbors), and FSDP-DP rides the middle. This
    is the opposite ordering from `make_mesh` (data-major), which is why
    the composed engine does not reuse it; the axis NAMES are the
    existing vocabulary, so `mesh_axes`/`spec_from_axes` and the sharded
    checkpoint topology records keep working unchanged.

    On a multi-process runtime the stage-major reshape composes with
    `create_hybrid_device_mesh` the same way `make_mesh` does; single
    process it is the virtual two-fabric program structure."""
    for name, v in (("pp", pp), ("dp", dp), ("sp", sp)):
        if v < 1:
            raise ValueError(f"plan mesh axis {name} must be >= 1, got {v}")
    devices = list(devices if devices is not None else jax.devices())
    need = pp * dp * sp
    if len(devices) < need:
        raise ValueError(
            f"plan mesh pp={pp} x dp={dp} x sp={sp} needs {need} "
            f"devices, {len(devices)} present"
        )
    dev_array = np.asarray(devices[:need]).reshape(pp, dp, sp)
    return Mesh(dev_array, axis_names=("stage", "data", "seq"))


def local_mesh(**axes: int) -> Mesh:
    """Convenience: `local_mesh(stage=4)` on 8 devices → (2, 4, 1, 1) mesh
    (unspecified `data` absorbs the remaining devices)."""
    return make_mesh(MeshSpec(**axes))


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Input-batch sharding: the TPU equivalent of DataParallel's `scatter`
    (reference `Readme.md:19-29`) — no device-0 hop, each host feeds its shard."""
    return NamedSharding(mesh, P(data_axis_names(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    """Parameter replication: the equivalent of `comm.broadcast_coalesced`
    (reference `Readme.md:30,49-56`) — a sharding spec, not a copy loop."""
    return NamedSharding(mesh, P())
