from distributed_model_parallel_tpu.training.optim import (  # noqa: F401
    SGD,
    SGDState,
    AdamW,
    AdamWState,
    cosine_warmup_schedule,
)
from distributed_model_parallel_tpu.training.metrics import (  # noqa: F401
    Meter,
    accuracy,
    cross_entropy,
    topk_correct,
)
from distributed_model_parallel_tpu.training.checkpoint import (  # noqa: F401
    latest_exists,
    restore_checkpoint,
    save_checkpoint,
)
from distributed_model_parallel_tpu.training.trainer import (  # noqa: F401
    EpochStats,
    Trainer,
    TrainerConfig,
)
