"""Fit the alpha-beta fabric constants from MEASURED rows.

`cost.py`'s constants are hand-derived from public TPU numbers; every
prediction in the ledger inherits them. This module closes the loop
the other way: given measured rows (the bench.py reducer/cm/moe
microbench legs, or any caller-built rows — e.g. from an attributed
trace), it solves the linear system

    t_row = sum_f  alpha_f * hops_f(row)  +  wire_bytes_f(row) / bw_f

for (alpha_ici, bw_ici, alpha_dcn, bw_dcn) by least squares, emits a
versioned `experiments/calibration.json` that `cost.load_calibration`
can hand back in place of the hand constants, and reports drift vs
the committed values — `tools/costgate --calibration` surfaces that
drift (reported, never gated: measured physics informs the model, it
does not veto a structural regression check).

The per-leg FEATURES (hop counts and wire-byte totals per fabric) are
the exact linear decompositions of `cost.py`'s closed forms — pinned
in tests: `features · hand-constants == closed_form` to float
precision, so the fit target and the prose model can never drift.
Each bench table also contributes a per-source intercept column (the
constant compute share of its timed leg — the MoE rows time
exchange + FFN + return; the fit must not launder FFN time into
alpha).

numpy only (lstsq); no jax — importable beside the analysis layer.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

from distributed_model_parallel_tpu.observability.cost import (
    CONSTANTS,
    WIRE_ITEMSIZE,
)

CALIBRATION_VERSION = "dmpt.calibration.v1"

#: Fit-constant order: (fabric, kind) -> CONSTANTS key.
_PARAM_KEYS = (
    ("ici", "alpha", "alpha_hop_s"),
    ("ici", "bw", "bw_ici_effective_bytes_per_s"),
    ("dcn", "alpha", "alpha_dcn_hop_s"),
    ("dcn", "bw", "bw_dcn_effective_bytes_per_s"),
)


@dataclasses.dataclass
class CalibrationRow:
    """One measured leg: per-fabric hop counts + wire bytes and the
    measured seconds. `source` groups rows that share an additive
    compute intercept (one per bench table)."""

    name: str
    measured_s: float
    hops: Dict[str, float]        # fabric -> latency hops
    wire_bytes: Dict[str, float]  # fabric -> bytes traversing the wire
    source: str = "rows"


# ---------------------------------------------- closed-form features
#
# Linear decompositions of cost.py's composition helpers: seconds ==
# hops_f * alpha_f + wire_bytes_f / bw_f summed over fabrics (pinned
# against the closed forms in tests/test_obsreport.py).


def ring_all_reduce_features(nbytes: float, size: int,
                             n_ops: int = 1) -> CalibrationRow:
    """§3a flat ring all-reduce (cost.ring_all_reduce_s)."""
    if size <= 1:
        return CalibrationRow("ring", 0.0, {}, {})
    return CalibrationRow(
        name=f"ring/S{size}",
        measured_s=0.0,
        hops={"ici": n_ops * 2 * (size - 1)},
        wire_bytes={"ici": 2 * (size - 1) / size * nbytes},
    )


def two_level_features(nbytes: float, ici: int, dcn: int,
                       n_buckets: int = 1,
                       wire: str = "none") -> CalibrationRow:
    """§3b hierarchical bucketed reduction
    (cost.two_level_all_reduce_s)."""
    wb = WIRE_ITEMSIZE[wire]
    sidecar = 1 if wire == "int8" else 0
    hops = {"ici": n_buckets * 2 * (ici - 1),
            "dcn": n_buckets * (1 + sidecar) * 2 * (dcn - 1)}
    wire_bytes = {"ici": 2 * (ici - 1) / ici * nbytes}
    if dcn > 1:
        wire_bytes["dcn"] = (
            2 * (dcn - 1) / dcn * (nbytes / ici) * (wb / 4)
        )
    return CalibrationRow(
        name=f"two_level/{dcn}x{ici}/wire-{wire}",
        measured_s=0.0, hops=hops, wire_bytes=wire_bytes,
    )


def flat_all_to_all_features(elems: float, itemsize: int, ici: int,
                             dcn: int) -> CalibrationRow:
    """§3c flat token exchange (cost.flat_all_to_all_s)."""
    x = elems * itemsize
    n = ici * dcn
    return CalibrationRow(
        name=f"flat_a2a/{dcn}x{ici}",
        measured_s=0.0,
        hops={"ici": ici - 1, "dcn": (dcn - 1) * ici},
        wire_bytes={"ici": (ici - 1) / n * x,
                    "dcn": (dcn - 1) / dcn * x},
    )


def hierarchical_all_to_all_features(
    elems: float, itemsize: int, ici: int, dcn: int,
    wire: Optional[str] = None,
) -> CalibrationRow:
    """§3c' two-level token exchange
    (cost.hierarchical_all_to_all_s)."""
    x = elems * itemsize
    dcn_itemsize = itemsize if wire in (None, "none") \
        else WIRE_ITEMSIZE[wire]
    return CalibrationRow(
        name=f"hier_a2a/{dcn}x{ici}/wire-{wire or 'none'}",
        measured_s=0.0,
        hops={"ici": ici - 1, "dcn": dcn - 1},
        wire_bytes={"ici": (ici - 1) / ici * x,
                    "dcn": (dcn - 1) / dcn * elems * dcn_itemsize},
    )


def features_to_seconds(row: CalibrationRow,
                        constants: Dict[str, float]) -> float:
    """Evaluate a feature row under explicit constants — the quantity
    the tests pin equal to cost.py's closed forms."""
    alpha = {"ici": constants["alpha_hop_s"],
             "dcn": constants["alpha_dcn_hop_s"]}
    bw = {"ici": constants["bw_ici_effective_bytes_per_s"],
          "dcn": constants["bw_dcn_effective_bytes_per_s"]}
    t = 0.0
    for f, h in row.hops.items():
        t += h * alpha[f]
    for f, b in row.wire_bytes.items():
        t += b / bw[f]
    return t


# ------------------------------------------------- bench row builders


def rows_from_bench(bench: dict) -> List[CalibrationRow]:
    """Measured rows out of a bench.py JSON (the reducer / moe / cm
    microbench tables, whichever are present — also found nested under
    a BENCH_r*.json's 'parsed' key). Each table's rows share shapes
    recorded beside it, so the features are fully determined."""
    if "parsed" in bench and isinstance(bench["parsed"], dict):
        bench = bench["parsed"]
    rows: List[CalibrationRow] = []
    grad_mb = float(bench.get("grad_mb", 0.0))
    n_buckets = int(bench.get("n_buckets", 1))
    for leg in bench.get("reducer_microbench", []):
        if "hierarchical_ms" not in leg:
            continue
        size = int(leg["axis_size"])
        if size < 2:
            continue
        r = two_level_features(
            grad_mb * 1e6, ici=size // 2, dcn=2,
            n_buckets=n_buckets,
            wire=leg.get("wire", "f32"),  # "f32" == "none" on the wire
        )
        r.name = f"reducer/S{size}/wire-{leg.get('wire', 'f32')}"
        r.measured_s = float(leg["hierarchical_ms"]) / 1e3
        r.source = "reducer"
        rows.append(r)
    payload_mb = float(bench.get("dispatch_payload_mb", 0.0))
    for leg in bench.get("moe_microbench", []):
        if "hierarchical_ms" not in leg:
            continue
        size = int(leg["axis_size"])
        if size < 2:
            continue
        wire = leg.get("wire", "f32")
        one_way = hierarchical_all_to_all_features(
            payload_mb * 1e6 / 4, 4, ici=size // 2, dcn=2,
            wire=None if wire == "f32" else wire,
        )
        # The timed leg is exchange + FFN + return: double the one-way
        # features; the FFN share lands in the per-source intercept.
        r = CalibrationRow(
            name=f"moe/S{size}/wire-{wire}",
            measured_s=float(leg["hierarchical_ms"]) / 1e3,
            hops={f: 2 * h for f, h in one_way.hops.items()},
            wire_bytes={
                f: 2 * b for f, b in one_way.wire_bytes.items()
            },
            source="moe",
        )
        rows.append(r)
    shapes = bench.get("shapes", {})
    for leg in bench.get("collective_matmul_microbench", []):
        if "fwd_overlapped_ms" not in leg or not shapes:
            continue
        size = int(leg["axis_size"])
        bx = (shapes["batch"] * shapes["seq_per_shard"] * size
              * shapes["d_model"] * 4)
        bh = (shapes["batch"] * shapes["seq_per_shard"] * size
              * shapes["d_ff"] * 4)
        rows.append(CalibrationRow(
            name=f"cm/S{size}",
            measured_s=float(leg["fwd_overlapped_ms"]) / 1e3,
            hops={"ici": 2 * (size - 1)},  # ag ring + rs ring
            wire_bytes={"ici": (size - 1) / size * (bx + bh)},
            source="cm",
        ))
    return rows


# --------------------------------------------------------------- fit


def fit_constants(rows: Sequence[CalibrationRow]) -> dict:
    """Least-squares fit of the four fabric constants (+ one compute
    intercept per row source). Returns {"constants": {...},
    "intercepts_s": {...}, "residual_rms_s": ..., "n_rows": ...};
    raises ValueError when the rows cannot identify the parameters
    (fewer rows than unknowns, or a fabric no row touches)."""
    import numpy as np

    rows = list(rows)
    touched = {f for r in rows for f in (*r.hops, *r.wire_bytes)}
    params = [
        (f, kind, key) for f, kind, key in _PARAM_KEYS if f in touched
    ]
    sources = sorted({r.source for r in rows})
    n_cols = len(params) + len(sources)
    if len(rows) < n_cols:
        raise ValueError(
            f"{len(rows)} measured rows cannot identify {n_cols} "
            "parameters (4 fabric constants + one intercept per "
            "source) — add more microbench legs"
        )
    a = np.zeros((len(rows), n_cols))
    b = np.array([r.measured_s for r in rows])
    for i, r in enumerate(rows):
        for j, (f, kind, _key) in enumerate(params):
            a[i, j] = (
                r.hops.get(f, 0.0) if kind == "alpha"
                else r.wire_bytes.get(f, 0.0)
            )
        a[i, len(params) + sources.index(r.source)] = 1.0
    sol, *_ = np.linalg.lstsq(a, b, rcond=None)
    constants: Dict[str, float] = {}
    for j, (_f, kind, key) in enumerate(params):
        v = float(sol[j])
        if kind == "bw":
            # The design matrix carries 1/bw; a non-positive solve
            # means the rows cannot see that fabric's bandwidth —
            # report infinity-free by falling back to the committed
            # value and letting the drift report say "unidentified".
            constants[key] = (1.0 / v) if v > 0 else CONSTANTS[key]
        else:
            constants[key] = max(v, 0.0)
    for key, committed in CONSTANTS.items():
        constants.setdefault(key, committed)
    resid = a @ sol - b
    return {
        "constants": constants,
        "intercepts_s": {
            s: float(sol[len(params) + i])
            for i, s in enumerate(sources)
        },
        "residual_rms_s": float(np.sqrt(np.mean(resid ** 2))),
        "n_rows": len(rows),
    }


def drift_report(fitted: Dict[str, float],
                 committed: Optional[Dict[str, float]] = None
                 ) -> Dict[str, float]:
    """Percent drift of each fitted constant vs the committed one."""
    committed = committed if committed is not None else CONSTANTS
    return {
        k: round((fitted[k] - committed[k]) / committed[k] * 100.0, 2)
        for k in sorted(committed)
        if k in fitted and committed[k]
    }


def calibration_payload(fit: dict, note: str = "",
                        fitted_from: Optional[dict] = None) -> dict:
    """The versioned artifact `experiments/calibration.json` holds."""
    return {
        "version": CALIBRATION_VERSION,
        "constants": {
            k: fit["constants"][k] for k in sorted(fit["constants"])
        },
        "committed_constants": dict(CONSTANTS),
        "drift_pct": drift_report(fit["constants"]),
        "intercepts_s": fit["intercepts_s"],
        "residual_rms_s": fit["residual_rms_s"],
        "n_rows": fit["n_rows"],
        "fitted_from": fitted_from or {},
        "note": note,
    }


def write_calibration(path: str, payload: dict) -> str:
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    return path


# --------------------------------------------------------------- CLI


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="calibrate",
        description=(
            "Fit the alpha-beta fabric constants from measured bench "
            "rows and emit a versioned calibration.json "
            "(INTERNALS.md section 14)."
        ),
    )
    parser.add_argument(
        "--bench", action="append", default=[], metavar="JSON",
        help="bench.py output (or BENCH_r*.json) to pull reducer/moe/"
             "cm microbench legs from; repeatable",
    )
    parser.add_argument(
        "--out", default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))), "experiments", "calibration.json",
        ),
    )
    parser.add_argument("--note", default="")
    args = parser.parse_args(argv)
    rows: List[CalibrationRow] = []
    for path in args.bench:
        with open(path) as f:
            rows += rows_from_bench(json.load(f))
    if not rows:
        print("[calibrate] no measured rows found", file=sys.stderr)
        return 2
    try:
        fit = fit_constants(rows)
    except ValueError as e:
        print(f"[calibrate] {e}", file=sys.stderr)
        return 2
    payload = calibration_payload(
        fit, note=args.note,
        fitted_from={"bench": [os.path.basename(p)
                               for p in args.bench]},
    )
    write_calibration(args.out, payload)
    for k, pct in payload["drift_pct"].items():
        print(f"[calibrate] {k}: committed {CONSTANTS[k]:g} -> "
              f"fitted {payload['constants'][k]:g} ({pct:+.1f}%)")
    print(json.dumps({"calibrate": {
        "out": args.out, "n_rows": fit["n_rows"],
        "residual_rms_s": round(fit["residual_rms_s"], 9),
    }}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())


__all__ = [
    "CALIBRATION_VERSION",
    "CalibrationRow",
    "calibration_payload",
    "drift_report",
    "features_to_seconds",
    "fit_constants",
    "flat_all_to_all_features",
    "hierarchical_all_to_all_features",
    "main",
    "ring_all_reduce_features",
    "rows_from_bench",
    "two_level_features",
    "write_calibration",
]
