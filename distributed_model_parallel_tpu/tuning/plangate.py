"""plangate — the tuner's regression gate, in the costgate mold.

`tools/plangate` is the CLI. For a pinned grid of mesh x model cells
(`GRID` — every engine family the tuner searches, on plain and hybrid
fabrics), this module re-runs the deterministic search and compares the
result against the committed `experiments/tuned_plans.json`, failing —
with the cell NAMED — when:

  * the re-searched argmin picked DIFFERENT knobs than the committed
    plan (the cost landscape under this tree's lowering moved: either
    an engine regression changed what a configuration asks the network
    for, or a deliberate change needs `--update` to re-commit),
  * the argmin's predicted step time drifted past tolerance in EITHER
    direction (a stale baseline is as misleading as a regression),
  * a grid cell has no committed plan (a new cell shipped without its
    baseline),
  * the artifact was generated under different alpha/beta constants.

Exit codes: 0 clean; 6 gate failure (tools/tier1.sh's plangate
pre-gate keys on it; 2/3/4/5 belong to the collection, hlolint,
costgate and obsreport pre-gates); 2 usage errors.

Modes mirror costgate: `--pregate` re-searches only the tier-1 cut
(tinycnn DDP + the hierarchical-MoE cell, seconds-scale) and
name-checks EVERY grid cell against the artifact; `--update`
regenerates (full grid by default, subset merge under
`--filter`/`--pregate`).

`gate_check` is a pure function over (artifact, results) so tests pin
the drift / missing-row / tolerance semantics without compiling
anything.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from distributed_model_parallel_tpu.tuning.plan import Cell

DEFAULT_PLANS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    ))),
    "experiments", "tuned_plans.json",
)
PLANS_SCHEMA = "dmpt.tuned_plans.v1"
DEFAULT_TOLERANCE = 0.05

EXIT_GATE_FAILED = 6


def grid() -> List[Cell]:
    """The pinned mesh x model grid (acceptance: >= 8 cells): both
    image reducer families on hybrid fabrics at two scales and both
    proxy models, the CausalLM-SP reducer plain and hybrid, the
    hierarchical-MoE fabric at two scales, the tp ring cell, the
    paged-serving cell (page_size x prefill_chunk, ISSUE 15), and the
    composed-plan factorization cell over the full 8-device CI mesh
    (ISSUE 19: the argmin is a whole ParallelPlan spec), plus the
    SCHEDULED plan cell (ISSUE 20, model tag "sched"): the pp2
    gpipe/1f1b/int2 twins at M just above pp, pinning that the tuner
    prices and selects a scheduled plan that beats its gpipe twin."""
    return [
        Cell("ddp", 4, 2, "mlp"),
        Cell("ddp", 8, 2, "tinycnn"),
        Cell("fsdp", 4, 2, "mlp"),
        Cell("fsdp", 8, 2, "tinycnn"),
        Cell("sp_lm", 2, 1),
        Cell("sp_lm", 4, 2),
        Cell("ep", 4, 2),
        Cell("ep", 8, 2),
        Cell("tp", 4),
        Cell("serve", 2),
        Cell("plan", 8),
        Cell("plan", 8, model="sched"),
    ]


def pregate_cells() -> List[Cell]:
    """The tier-1 cut: the tinycnn DDP cell (the deepest reducer knob
    stack — buckets, overlap segments, wire — on the BN model) plus
    one hierarchical-MoE cell, so a drifted argmin fails in seconds
    with the cell named, mirroring the hlolint/costgate pre-gates."""
    return [
        Cell("ddp", 8, 2, "tinycnn"),
        Cell("ep", 4, 2),
    ]


def load_plans(path: str) -> dict:
    with open(path) as f:
        artifact = json.load(f)
    if artifact.get("schema") != PLANS_SCHEMA or "cells" not in artifact:
        raise ValueError(
            f"{path}: not a tuned-plans artifact (schema "
            f"{PLANS_SCHEMA!r} with a 'cells' object)"
        )
    return artifact


def make_artifact(rows: Dict[str, dict],
                  tolerance: float = DEFAULT_TOLERANCE) -> dict:
    from distributed_model_parallel_tpu.observability.cost import (
        CONSTANTS,
    )

    return {
        "schema": PLANS_SCHEMA,
        "constants": dict(CONSTANTS),
        "tolerance": tolerance,
        "cells": {k: rows[k] for k in sorted(rows)},
    }


def plan_row(plan: dict) -> dict:
    """The per-cell record the artifact commits (the plan minus its
    per-run search diagnostics)."""
    return {
        "knobs": plan["knobs"],
        "combo": plan["combo"],
        "predicted_step_s": plan["predicted"]["predicted_step_s"],
    }


def gate_check(
    artifact: dict,
    results: Dict[str, dict],
    tolerance: Optional[float] = None,
    require_rows_for: Optional[Sequence[str]] = None,
    known_cells: Optional[Sequence[str]] = None,
) -> List[str]:
    """Pure comparison: one failure string per violated contract.
    `results` maps cell name -> plan_row-shaped dict for the cells
    re-searched this run; `require_rows_for` name-checks the rest;
    `known_cells` (the current grid) additionally flags ORPHANED
    artifact rows — a committed baseline for a cell the tree no
    longer searches is as misleading as a missing one."""
    from distributed_model_parallel_tpu.observability.cost import (
        CONSTANTS,
    )

    failures: List[str] = []
    tol = tolerance if tolerance is not None \
        else float(artifact.get("tolerance", DEFAULT_TOLERANCE))
    recorded = artifact.get("constants", {})
    for key, want in CONSTANTS.items():
        got = recorded.get(key)
        if got != want:
            failures.append(
                f"constants drift: artifact has {key}={got!r}, the "
                f"cost engine uses {want!r} — regenerate "
                "(tools/plangate --update)"
            )
    cells = artifact["cells"]
    for name in sorted(results):
        row = cells.get(name)
        got = results[name]
        if row is None:
            failures.append(
                f"{name}: no committed plan — a new grid cell must "
                "commit its tuned baseline (tools/plangate --update)"
            )
            continue
        if got["knobs"] != row["knobs"]:
            drifted = sorted(
                k for k in set(got["knobs"]) | set(row["knobs"])
                if got["knobs"].get(k) != row["knobs"].get(k)
            )
            failures.append(
                f"{name}: re-searched argmin drifted — "
                + ", ".join(
                    f"{k} {row['knobs'].get(k)!r} -> "
                    f"{got['knobs'].get(k)!r}" for k in drifted
                )
                + " (an engine change moved the cost landscape; "
                "re-commit with tools/plangate --update if intended)"
            )
            continue
        base = float(row["predicted_step_s"])
        pred = float(got["predicted_step_s"])
        if base and abs(pred - base) > base * tol:
            failures.append(
                f"{name}: argmin predicted step time drifted "
                f"{base * 1e3:.4f} -> {pred * 1e3:.4f} ms "
                f"({(pred / base - 1.0) * 100:+.1f}%, tolerance "
                f"{tol * 100:.0f}%) — regenerate or investigate"
            )
    if require_rows_for:
        for name in sorted(set(require_rows_for) - set(results)):
            if name not in cells:
                failures.append(
                    f"{name}: no committed plan — a new grid cell "
                    "must commit its tuned baseline "
                    "(tools/plangate --update)"
                )
    if known_cells is not None:
        for name in sorted(set(cells) - set(known_cells)):
            failures.append(
                f"{name}: committed plan for a cell no longer in the "
                "grid — a stale baseline gates nothing; regenerate "
                "the artifact (full tools/plangate --update)"
            )
    return failures


def _search(cells: Sequence[Cell], emit) -> Dict[str, dict]:
    """Re-search each cell, streaming one partial-JSON line per
    finished cell (the repo's convention). A cell whose search fails —
    lowering crash or a lint-dirty argmin — records an 'error' row the
    caller gates on."""
    from distributed_model_parallel_tpu.tuning.search import search_cell

    rows: Dict[str, dict] = {}
    for cell in cells:
        try:
            plan = search_cell(cell, emit=emit)
        except Exception as e:  # noqa: BLE001 — a failure IS a finding
            emit(f"[plangate] {cell.name}: SEARCH FAILED: {e!r}")
            rows[cell.name] = {"error": repr(e)}
            emit(json.dumps({
                "leg": {"name": cell.name, "error": repr(e)},
                "partial": True,
            }))
            continue
        row = plan_row(plan)
        rows[cell.name] = row
        emit(f"[plangate] {cell.name}: argmin {row['combo']} "
             f"({row['predicted_step_s'] * 1e3:.4f} ms/step)")
        emit(json.dumps({
            "leg": {
                "name": cell.name,
                "combo": row["combo"],
                "predicted_step_s": row["predicted_step_s"],
            },
            "partial": True,
        }))
    return rows


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="plangate",
        description=(
            "Auto-tuner regression gate: re-run the deterministic "
            "knob search for a pinned mesh x model grid (tuning/, "
            "INTERNALS.md section 15) and compare argmin + predicted "
            "time against the committed "
            "experiments/tuned_plans.json."
        ),
    )
    parser.add_argument(
        "--pregate", action="store_true",
        help="tier-1 cut: re-search only the pregate cells (seconds) "
             "and name-check every grid cell against the artifact",
    )
    parser.add_argument(
        "--filter", default=None,
        help="regex over cell names (e.g. 'ddp.*dcn2')",
    )
    parser.add_argument(
        "--update", action="store_true",
        help="regenerate rows and write the artifact instead of "
             "gating (full rewrite; merges into the existing file "
             "under --filter/--pregate)",
    )
    parser.add_argument("--plans", default=DEFAULT_PLANS)
    parser.add_argument(
        "--tolerance", type=float, default=None,
        help=f"override the artifact's tolerance (default "
             f"{DEFAULT_TOLERANCE})",
    )
    parser.add_argument("--devices", type=int, default=8)
    args = parser.parse_args(argv)

    # Virtual CPU devices BEFORE any backend initializes (same guard
    # as tools/hlolint and tools/costgate).
    from distributed_model_parallel_tpu.runtime.platform import force_cpu

    force_cpu(args.devices)

    from distributed_model_parallel_tpu.observability.cost import (
        CONSTANTS,
    )

    full = grid()
    cells = pregate_cells() if args.pregate else full
    if args.filter:
        import re

        cells = [c for c in cells if re.search(args.filter, c.name)]
    if not cells:
        print("[plangate] no cells match", file=sys.stderr)
        return 2

    subset_update = args.update and (args.pregate or args.filter) \
        and os.path.exists(args.plans)
    old = load_plans(args.plans) if subset_update else None
    if old is not None:
        drifted = sorted(
            k for k, v in CONSTANTS.items()
            if old.get("constants", {}).get(k) != v
        )
        if drifted:
            # Same refusal as costgate's: merging would keep the
            # un-searched rows under the OLD physics while stamping
            # the artifact with the current constants.
            print(
                "[plangate] refusing subset --update: the existing "
                f"artifact was searched under different constants "
                f"({', '.join(drifted)}); run a FULL "
                "`tools/plangate --update`",
                file=sys.stderr,
            )
            return 2

    rows = _search(cells, print)
    errored = sorted(n for n, r in rows.items() if "error" in r)
    rows = {n: r for n, r in rows.items() if "error" not in r}

    if args.update:
        tol = args.tolerance
        if tol is None and old is not None:
            tol = float(old.get("tolerance", DEFAULT_TOLERANCE))
        if tol is None:
            tol = DEFAULT_TOLERANCE
        if old is not None:
            merged = old["cells"]
            merged.update(rows)
            rows = merged
        artifact = make_artifact(rows, tol)
        with open(args.plans, "w") as f:
            json.dump(artifact, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps({
            "plangate": {
                "updated": args.plans,
                "cells": len(artifact["cells"]),
                "errors": len(errored),
                "failed_targets": errored,
            }
        }))
        return EXIT_GATE_FAILED if errored else 0

    try:
        artifact = load_plans(args.plans)
    except (OSError, ValueError) as e:
        print(f"[plangate] cannot read plans: {e}", file=sys.stderr)
        return EXIT_GATE_FAILED
    failures = gate_check(
        artifact, rows, args.tolerance,
        require_rows_for=[c.name for c in full] if args.pregate
        else None,
        known_cells=[c.name for c in full],
    )
    failures += [
        f"{name}: SEARCH FAILED (see log above)" for name in errored
    ]
    for f in failures:
        print(f"[plangate] FAIL {f}")
    print(json.dumps({
        "plangate": {
            "plans": args.plans,
            "gated": len(rows),
            "name_checked": len(full) if args.pregate else len(rows),
            "failures": len(failures),
            "failed_targets": sorted(
                {f.split(":", 1)[0] for f in failures}
            ),
        }
    }))
    return EXIT_GATE_FAILED if failures else 0


if __name__ == "__main__":
    sys.exit(main())
