"""Test harness: 8 virtual CPU devices so every collective path runs in CI
without hardware — the test story the reference lacks entirely (SURVEY.md §4:
no tests/ directory in the reference; its acceptance test was empirical
convergence curves, `Readme.md:283-294`).

This environment preloads a TPU PJRT plugin at interpreter start, and
backend *initialization* (which dials a remote device, slowly) is lazy.
Tests must be hermetic and CPU-only, so we force the cpu platform and the
virtual device count before any JAX computation runs. XLA_FLAGS is read
when the CPU client first initializes, so setting it here is early enough.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import re  # noqa: E402

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")


# Tier-1 budget guard: experiment sweeps (experiments/) time whole training
# schedules and must only ever run under the `slow` marker. A test module
# that imports experiments/ without marking every one of its tests slow
# would silently blow the 870 s tier-1 window, so collection fails loudly.
_EXPERIMENTS_IMPORT = re.compile(
    r"^\s*(?:from|import)\s+experiments\b", re.MULTILINE
)


# Budget-rebalance convention (PR 4): a test demoted to `slow` must name
# its tier-1 twin in its docstring, so the default run's coverage story
# stays auditable. A parametrized sweep whose non-slow cases keep running
# in tier-1 is its own twin and needs no docstring note.
_TWIN_RE = re.compile(r"tier-?1|twin", re.IGNORECASE)


def pytest_collection_modifyitems(config, items):
    offenders = []
    checked = {}
    for item in items:
        path = str(getattr(item, "fspath", ""))
        if path not in checked:
            try:
                with open(path) as f:
                    checked[path] = bool(_EXPERIMENTS_IMPORT.search(f.read()))
            except OSError:
                checked[path] = False
        if checked[path] and item.get_closest_marker("slow") is None:
            offenders.append(item.nodeid)
    if offenders:
        raise pytest.UsageError(
            "tests importing experiments/ must be marked @pytest.mark.slow "
            "(tier-1 budget): " + ", ".join(sorted(offenders))
        )

    # slow-twin meta-check: group collected items by test function; a
    # function whose EVERY case is slow must document its tier-1 twin.
    # Only meaningful when whole files/dirs were collected: a direct
    # node-id invocation (re-running one CI failure) can select a lone
    # slow param of a mixed sweep, which would otherwise masquerade as
    # an undocumented all-slow function and abort collection.
    if any("::" in a for a in config.args):
        return
    by_fn = {}
    for item in items:
        key = (
            str(getattr(item, "fspath", "")),
            getattr(item, "originalname", item.name),
        )
        by_fn.setdefault(key, []).append(item)
    undocumented = []
    for (path, name), group in by_fn.items():
        if any(i.get_closest_marker("slow") is None for i in group):
            continue  # mixed sweep: the non-slow cases ARE the twin
        fn = getattr(group[0], "function", None)
        doc = getattr(fn, "__doc__", None) or ""
        if not _TWIN_RE.search(doc):
            undocumented.append(f"{path}::{name}")
    if undocumented:
        raise pytest.UsageError(
            "slow-demoted tests must name their tier-1 twin in their "
            "docstring (PR 4 budget-rebalance convention): "
            + ", ".join(sorted(undocumented))
        )


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)
