"""Mixed-precision (bf16) path tests, on the 8-virtual-device CPU mesh.

The reference is f32-only CUDA; bf16 compute is TPU-native table stakes
(the MXU's matmul dtype), so every engine grows a `compute_dtype` knob:
activations bf16, params/optimizer/loss f32. These tests pin

* numerical closeness of the bf16 step to the f32 step (bf16 has ~3
  decimal digits; tolerances sized to that),
* that the pipeline wire buffer actually carries bf16 (half the ppermute
  bytes), not silently up-cast f32,
* that integer-input models (BERT) pick up the compute dtype at the
  embedding (`Context.dtype`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.models import tinycnn
from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn
from distributed_model_parallel_tpu.parallel.data_parallel import (
    DataParallelEngine,
    DDPEngine,
)
from distributed_model_parallel_tpu.parallel.pipeline import (
    PipelineEngine,
    _wire_dtype,
)
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.optim import SGD

BATCH = 16


def _batch(key, size=BATCH):
    kx, ky = jax.random.split(key)
    images = jax.random.normal(kx, (size, 32, 32, 3))
    labels = jax.random.randint(ky, (size,), 0, 10)
    return images, labels


def _run_steps(engine, n=3, lr=0.05):
    ts = engine.init_state(jax.random.PRNGKey(0))
    images, labels = engine.shard_batch(*_batch(jax.random.PRNGKey(7)))
    losses = []
    for _ in range(n):
        ts, m = engine.train_step(ts, images, labels, lr)
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    return ts, losses


def test_dp_bf16_close_to_f32():
    """GSPMD engine: 3 bf16 steps track the f32 trajectory within bf16
    tolerance, and params stay f32 master copies."""
    mesh = make_mesh(MeshSpec(data=8))
    f32 = DataParallelEngine(tiny_cnn(10), SGD(), mesh, donate=False)
    bf16 = DataParallelEngine(
        tiny_cnn(10), SGD(), mesh, donate=False,
        compute_dtype=jnp.bfloat16,
    )
    _, losses_f32 = _run_steps(f32)
    ts_bf16, losses_bf16 = _run_steps(bf16)
    np.testing.assert_allclose(losses_bf16, losses_f32, rtol=5e-2)
    assert losses_bf16[-1] < losses_bf16[0]
    for leaf in jax.tree_util.tree_leaves(ts_bf16.params):
        assert leaf.dtype == jnp.float32


def test_ddp_bf16_close_to_f32():
    """shard_map engine (explicit pmean all-reduce), SyncBN, bf16."""
    mesh = make_mesh(MeshSpec(data=8))
    f32 = DDPEngine(tiny_cnn(10), SGD(), mesh, sync_bn=True, donate=False)
    bf16 = DDPEngine(
        tiny_cnn(10), SGD(), mesh, sync_bn=True, donate=False,
        compute_dtype=jnp.bfloat16,
    )
    _, losses_f32 = _run_steps(f32)
    _, losses_bf16 = _run_steps(bf16)
    np.testing.assert_allclose(losses_bf16, losses_f32, rtol=5e-2)


@pytest.mark.slow
def test_pipeline_bf16_close_to_f32():
    """4-stage pipeline, bf16 activations over the ppermute wire.
    `slow` (tier-1 budget): test_pipeline_bf16_stage_local_combo below
    keeps the pipeline+bf16 wire coverage in tier-1 (same engine, plus
    the stage-local layout)."""
    mesh = make_mesh(MeshSpec(data=2, stage=4))
    stages = tinycnn.split_stages(4, 10)
    f32 = PipelineEngine(
        stages, SGD(), mesh, num_microbatches=2, donate=False
    )
    bf16 = PipelineEngine(
        stages, SGD(), mesh, num_microbatches=2, donate=False,
        compute_dtype=jnp.bfloat16,
    )
    _, losses_f32 = _run_steps(f32)
    _, losses_bf16 = _run_steps(bf16)
    np.testing.assert_allclose(losses_bf16, losses_f32, rtol=8e-2)


def test_wire_dtype_follows_activations():
    """bf16 activations (+ bool masks riding along) give a bf16 wire;
    pure-f32 stage I/O keeps an f32 wire."""
    bf_h = jax.ShapeDtypeStruct((2, 8, 4), jnp.bfloat16)
    mask = jax.ShapeDtypeStruct((2, 8), jnp.bool_)
    f32_h = jax.ShapeDtypeStruct((2, 8, 4), jnp.float32)
    assert _wire_dtype([((bf_h, mask), (bf_h, mask))]) == jnp.bfloat16
    assert _wire_dtype([(f32_h, f32_h)]) == jnp.float32


def test_embedding_casts_to_ctx_dtype():
    """Integer-input models enter the compute dtype at the embedding —
    the `Context.dtype` hook the engines set."""
    emb = L.embedding(16, 8)
    params, state = emb.init(jax.random.PRNGKey(0))
    ids = jnp.array([[1, 2], [3, 4]])
    out_f32, _ = emb.apply(params, state, ids, L.Context())
    out_bf16, _ = emb.apply(
        params, state, ids, L.Context(dtype=jnp.bfloat16)
    )
    assert out_f32.dtype == jnp.float32
    assert out_bf16.dtype == jnp.bfloat16


def test_profiler_trace_captured(tmp_path):
    """`TrainerConfig.profile_dir` writes a jax.profiler trace (the
    SURVEY §5 tracing-subsystem row; VERDICT r2 item 7)."""
    from distributed_model_parallel_tpu.data.datasets import synthetic
    from distributed_model_parallel_tpu.data.loader import Loader
    from distributed_model_parallel_tpu.training.trainer import (
        Trainer,
        TrainerConfig,
    )

    mesh = make_mesh(MeshSpec(data=8))
    engine = DataParallelEngine(tiny_cnn(10), SGD(), mesh)
    ds = synthetic(num_examples=64, num_classes=10, image_size=8, seed=0)
    train = Loader(ds, batch_size=16, shuffle=True, seed=0)
    prof_dir = tmp_path / "trace"
    cfg = TrainerConfig(
        epochs=1, base_lr=0.05, print_freq=0,
        log_dir=str(tmp_path / "log"), checkpoint_dir=str(tmp_path / "ckpt"),
        profile_dir=str(prof_dir),
    )
    trainer = Trainer(engine, train, None, cfg, rng=jax.random.PRNGKey(0))
    trainer.fit()
    trace_files = list(prof_dir.rglob("*"))
    assert any(f.is_file() for f in trace_files), (
        "profile_dir produced no trace files"
    )


@pytest.mark.slow
def test_pipeline_bf16_stage_local_combo():
    """The two pipeline options compose: bf16 activations/wire WITH
    stage-local (1/S-sharded) parameter storage. `slow` (tier-1
    budget); tier-1 twins: test_pipeline_bf16_close_to_f32 (the bf16
    half) + test_pipeline's stage-local storage pins (the sharding
    half)."""
    mesh = make_mesh(MeshSpec(data=2, stage=4))
    stages = tinycnn.split_stages(4, 10)
    f32 = PipelineEngine(
        stages, SGD(), mesh, num_microbatches=2, donate=False,
        stage_local_params=True,
    )
    bf16 = PipelineEngine(
        stages, SGD(), mesh, num_microbatches=2, donate=False,
        stage_local_params=True, compute_dtype=jnp.bfloat16,
    )
    _, losses_f32 = _run_steps(f32)
    ts_bf16, losses_bf16 = _run_steps(bf16)
    np.testing.assert_allclose(losses_bf16, losses_f32, rtol=8e-2)
    # storage stays f32 master rows, sharded 1/S
    assert ts_bf16.params.dtype == jnp.float32
    assert {s.data.shape[0] for s in ts_bf16.params.addressable_shards} == {1}
