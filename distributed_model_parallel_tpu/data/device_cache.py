"""Device-resident dataset cache — the input pipeline for datasets that
fit in HBM.

The reference's DataLoader re-ships every batch host->GPU each step
(`utils.py:42-72`); on a co-located host that link is PCIe and free-ish.
On TPU the idiomatic move for CIFAR-sized data is to stop shipping pixels
at all: upload the whole uint8 dataset ONCE (CIFAR-10 train = 153 MB —
noise against a 16 GB HBM), then each step sends only the batch's INDEX
vector (~2 KB) and the compiled train step does the gather, the
crop/flip augmentation, and the normalize on device. Measured on this
host's relay-attached chip, that turns an input path that was
bandwidth-bound at ~97 ms/batch (uint8) into a dispatch-bound one at
the compiled step rate (RESULTS §1c).

Composition contract:
* `IndexLoader` (below) reproduces `Loader`'s sampling EXACTLY — same
  per-epoch seeded permutation, same per-host strided shard, same
  batching — but yields `(indices, labels)` instead of pixels.
* `DeviceDatasetCache.transform()` is an `Engine.input_transform` with
  `wants_ctx = True`: engines call it as `tf(indices, step=..,
  train=..)` inside the jitted step. The cache arrays are closed over
  as replicated device constants.
* Augmentation draws are keyed by (augment_seed, step) with
  `jax.random` ON DEVICE — the same crop/flip distribution as the host
  path but a different (equally valid) random stream; trajectories
  match the host loader's in distribution, not bit-for-bit.

Datasets that do NOT fit in HBM (ImageNet at full res) keep the host
`Loader` path; this cache refuses datasets above `max_bytes` loudly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from distributed_model_parallel_tpu.data.datasets import ArrayDataset
from distributed_model_parallel_tpu.data.loader import Loader


class DeviceDatasetCache:
    """Upload `dataset` once (uint8 NHWC images replicated over the
    mesh) and build the device-side gather+augment+normalize transform.
    """

    def __init__(
        self,
        dataset: ArrayDataset,
        mesh,
        *,
        augment: bool = False,
        mean: Optional[np.ndarray] = None,
        std: Optional[np.ndarray] = None,
        padding: int = 4,
        augment_seed: int = 0,
        max_bytes: int = 2 << 30,
    ):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if isinstance(dataset, np.ndarray):
            images = dataset
        elif hasattr(dataset, "images"):
            images = dataset.images
        else:
            raise ValueError(
                f"device cache needs an in-memory dataset (ArrayDataset "
                f"or ndarray); got {type(dataset).__name__} — lazy "
                f"disk-backed datasets (ImageFolder trees) keep the host "
                f"Loader path"
            )
        if images.nbytes > max_bytes:
            raise ValueError(
                f"dataset is {images.nbytes / 1e9:.1f} GB uint8 — beyond "
                f"the device-cache budget ({max_bytes / 1e9:.1f} GB "
                f"replicated per device). Use the host Loader path."
            )
        repl = NamedSharding(mesh, P())
        if jax.process_count() == 1:
            self.images = jax.device_put(images, repl)
        else:
            # Every host loads the identical full dataset (the Loader
            # shards INDICES, not storage), so the replicated global
            # array assembles from identical per-process data.
            self.images = jax.make_array_from_process_local_data(
                repl, images
            )
        self.augment = augment
        self.padding = padding
        self.augment_seed = augment_seed
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.std = None if std is None else np.asarray(std, np.float32)

    def transform(self):
        """The `Engine.input_transform`: indices -> normalized f32 batch,
        entirely on device. `wants_ctx = True` makes engines pass
        (step, train); augmentation applies only when train=True."""
        import jax
        import jax.numpy as jnp

        cache = self.images
        p = self.padding
        mean, std = self.mean, self.std
        augment = self.augment
        seed = self.augment_seed

        def tf(indices, *, step=None, train=False):
            imgs = jnp.take(cache, indices, axis=0)
            if augment and train:
                b = imgs.shape[0]
                h, w = imgs.shape[1], imgs.shape[2]
                # Fold the first index into the key: under a shard_map
                # engine (DDP) the transform runs once PER SHARD with the
                # same `step`, and a step-only key would hand every shard
                # identical (ys, xs, flips) vectors. The shards' index
                # slices are disjoint, so indices[0] distinguishes them
                # — and under plain GSPMD jit there is one global call,
                # where any fold is fine.
                key = jax.random.fold_in(
                    jax.random.fold_in(jax.random.PRNGKey(seed), step),
                    indices[0],
                )
                ky, kx, kf = jax.random.split(key, 3)
                ys = jax.random.randint(ky, (b,), 0, 2 * p + 1)
                xs = jax.random.randint(kx, (b,), 0, 2 * p + 1)
                flips = jax.random.bernoulli(kf, 0.5, (b,))
                padded = jnp.pad(
                    imgs, ((0, 0), (p, p), (p, p), (0, 0))
                )
                # Per-image crop as TWO single-axis gathers
                # (take_along_axis rows, then cols) — measured 7x faster
                # on a v5e than the vmap(dynamic_slice) formulation
                # (1.3 ms vs 9.4 ms at B=512), bit-identical, uint8
                # throughout.
                rows = ys[:, None] + jnp.arange(h)[None, :]
                cols = xs[:, None] + jnp.arange(w)[None, :]
                imgs = jnp.take_along_axis(
                    padded, rows[:, :, None, None], axis=1
                )
                imgs = jnp.take_along_axis(
                    imgs, cols[:, None, :, None], axis=2
                )
                imgs = jnp.where(
                    flips[:, None, None, None], imgs[:, :, ::-1, :], imgs
                )
            out = imgs.astype(jnp.float32) / 255.0
            if mean is not None:
                out = (out - jnp.asarray(mean)) / jnp.asarray(std)
            return out

        tf.wants_ctx = True
        return tf


def combined_cache(
    train_ds: ArrayDataset,
    val_ds: ArrayDataset,
    mesh,
    *,
    mean: Optional[np.ndarray] = None,
    std: Optional[np.ndarray] = None,
    augment: bool = True,
    padding: int = 4,
    augment_seed: int = 0,
):
    """One replicated cache holding train AND val images (engines have a
    single `input_transform` serving both steps; augmentation applies
    only under train=True). Returns `(transform, val_offset)` — build
    the val `IndexLoader` with `index_offset=val_offset` so its indices
    address the val block of the combined cache."""
    for which, ds in (("train", train_ds), ("val", val_ds)):
        if not hasattr(ds, "images"):
            raise ValueError(
                f"device cache needs in-memory datasets; the {which} "
                f"split is a {type(ds).__name__} (lazy disk-backed) — "
                f"use the host Loader path for it"
            )
    images = np.concatenate([train_ds.images, val_ds.images])
    cache = DeviceDatasetCache(
        images, mesh, augment=augment, mean=mean, std=std,
        padding=padding, augment_seed=augment_seed,
    )
    return cache.transform(), len(train_ds.images)


@dataclasses.dataclass
class IndexLoader(Loader):
    """`Loader` with the pixel work removed: yields
    `(int32 indices, labels)` per batch, identical sampling (per-epoch
    seeded permutation, per-host strided shard, static batch shapes).
    Ragged final batches pad indices with row 0 and labels with -1
    (metrics mask the padding rows; the gathered pixels are dead).

    The index vector is the ONLY per-step host->device traffic, which
    is the point: ~2 KB/step against 1.5-6.3 MB for pixel batches.

    `index_offset` shifts every yielded index — the val loader of a
    `combined_cache` addresses the val block of the shared array."""

    index_offset: int = 0

    def __post_init__(self):
        super().__post_init__()
        # The pixel-path Loader fields are DEAD here (augment/normalize
        # live in DeviceDatasetCache.transform, on device); accepting
        # them silently would let a caller believe host augmentation is
        # happening when it is not.
        if (
            self.augment or self.transform is not None
            or self.device_normalize or self.mean is not None
        ):
            raise ValueError(
                "IndexLoader yields indices, not pixels: augment/"
                "mean/std/transform/device_normalize have no effect "
                "here — configure augmentation and normalization on "
                "DeviceDatasetCache/combined_cache instead"
            )

    def _make_batch(self, b: int, idx, use_native: bool):
        ds = self.dataset
        if hasattr(ds, "labels"):
            labels = ds.labels[idx]  # skip the host-side pixel gather
        else:
            _, labels = self._gather(idx)
        indices = np.asarray(idx, np.int32) + self.index_offset
        if len(idx) < self.batch_size:
            # Pad indices with a valid row (its gathered pixels are dead
            # — label -1 masks the row out of loss and metrics).
            pad_n = self.batch_size - len(idx)
            indices = np.concatenate(
                [indices, np.zeros((pad_n,), np.int32)]
            )
            labels = np.concatenate(
                [labels, np.full((pad_n,), -1, labels.dtype)]
            )
        return indices, labels


__all__ = ["DeviceDatasetCache", "IndexLoader", "combined_cache"]
