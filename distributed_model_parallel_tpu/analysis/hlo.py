"""Instruction-graph model of compiled-HLO text.

Promoted and hardened from the private `_hlo_graph`/`_depends_on`
helpers that lived in tests/test_collectives_hlo.py (PR 5). The parser
is deliberately text-level — `lowered.compile().as_text()` is the one
artifact every backend produces and the same surface the HLO pins have
always matched against — and deliberately CONSERVATIVE: instruction
references include operands AND called computations (fusion bodies,
reduction regions, to_apply targets), so reachability over the graph is
an over-approximation of data dependence. That is the safe direction
for every rule that asserts the ABSENCE of a dependency (the overlap
pins): a false edge can only make such a rule harder to pass, never
let a real serialization slip through.

No jax import here: the module parses strings, so golden-file tests and
the rule registry stay importable without a backend.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Bytes per element for the HLO primitive types that can appear in a
# result shape. Token/opaque carry no payload.
DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "f8e3m4": 1, "f8e4m3b11fnuz": 1, "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "token": 0, "opaque": 0,
}

# A collective op's result type in HLO text: a plain shape token on sync
# backends (`= f32[8,16]{1,0} all-gather(`) or a parenthesized tuple on
# async ones (`= (f32[...], f32[...]) all-gather-start(`).
RESULT_RE = r"(?:\([^)\n]*\)|\S+)"

COLLECTIVE_OPS = (
    "collective-permute",
    "all-gather",
    "reduce-scatter",
    "all-reduce",
    "all-to-all",
)

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    rf"(?:ROOT\s+)?%([\w.\-]+)\s*=\s*({RESULT_RE})\s+([\w\-]+)\(")
_COMP_RE = re.compile(r"(?:ENTRY\s+)?%?([\w.\-]+)\s*\(")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
# replica_groups, both printed forms: explicit `{{0,1},{2,3}}` (ends at
# the first `}}` — group bodies never nest) or empty `{}`, or the iota
# (v2) form `[4,2]<=[2,4]T(1,0)`.
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{.*?\}\}|\{\}|"
    r"\[[0-9,]*\]<=\[[0-9,]*\](?:T\([0-9,]*\))?)"
)


@dataclasses.dataclass(frozen=True)
class Buffer:
    """One result buffer of an instruction: dtype token + static shape."""

    dtype: str
    shape: Tuple[int, ...]

    @property
    def elems(self) -> int:
        return int(math.prod(self.shape)) if self.shape else 1

    @property
    def nbytes(self) -> int:
        return self.elems * DTYPE_BYTES.get(self.dtype, 4)

    @property
    def is_scalar(self) -> bool:
        return not self.shape


def parse_result_buffers(result: str) -> Tuple[Buffer, ...]:
    """Buffers carried by an instruction's printed result type —
    `f32[2,4]{1,0}`, `pred[]`, or an async tuple
    `(f32[2,4]{1,0}, u32[], ...)`. Layout annotations and index
    comments are ignored."""
    out = []
    for dt, dims in _SHAPE_RE.findall(result):
        if dt not in DTYPE_BYTES:
            continue  # a stray word that merely looks shape-like
        shape = tuple(int(d) for d in dims.split(",")) if dims else ()
        out.append(Buffer(dt, shape))
    return tuple(out)


def parse_replica_groups(attr: str) -> Optional[Tuple[Tuple[int, ...], ...]]:
    """Replica groups from either printed form:

    * explicit lists — `{{0,1,2,3},{4,5,6,7}}` (or the empty `{}`),
    * iota (v2) — `[2,4]<=[8]` or `[4,2]<=[2,4]T(1,0)`: reshape
      arange(prod(dims)) to `dims`, transpose by the permutation,
      flatten, reshape to the group shape.

    Returns a tuple of id tuples, or None when the attribute is absent.
    """
    attr = attr.strip()
    if attr.startswith("{"):
        inner = attr[1:-1].strip()
        if not inner:
            return ()
        groups = re.findall(r"\{([0-9,\s]*)\}", attr)
        return tuple(
            tuple(int(x) for x in g.replace(" ", "").split(",") if x != "")
            for g in groups
        )
    m = re.match(
        r"\[([0-9,]*)\]<=\[([0-9,]*)\](?:T\(([0-9,]*)\))?", attr
    )
    if not m:
        return None
    gshape = [int(x) for x in m.group(1).split(",") if x]
    dims = [int(x) for x in m.group(2).split(",") if x]
    perm = (
        [int(x) for x in m.group(3).split(",") if x]
        if m.group(3) is not None else list(range(len(dims)))
    )
    n = int(math.prod(dims)) if dims else 0
    ids = list(range(n))
    # reshape->transpose->flatten without numpy: walk the transposed
    # index space and read through the original row-major strides.
    if dims and perm != list(range(len(dims))):
        strides = [0] * len(dims)
        acc = 1
        for i in reversed(range(len(dims))):
            strides[i] = acc
            acc *= dims[i]
        tdims = [dims[p] for p in perm]
        tstrides = [strides[p] for p in perm]
        flat = []
        idx = [0] * len(tdims)
        for _ in range(n):
            flat.append(sum(i * s for i, s in zip(idx, tstrides)))
            for d in reversed(range(len(tdims))):
                idx[d] += 1
                if idx[d] < tdims[d]:
                    break
                idx[d] = 0
        ids = flat
    if not gshape:
        return (tuple(ids),) if ids else ()
    per = gshape[-1]
    n_groups = int(math.prod(gshape[:-1]))
    return tuple(
        tuple(ids[g * per:(g + 1) * per]) for g in range(n_groups)
    )


def _parse_pairs(line: str) -> Optional[Tuple[Tuple[int, int], ...]]:
    m = re.search(r"source_target_pairs=\{\{(.*?)\}\}", line)
    if not m:
        return None
    # "0,1},{1,2},..." — each {a,b} pair yields one digit,digit match;
    # the "},{"" separators keep pairs from matching across groups.
    pairs = re.findall(r"(\d+)\s*,\s*(\d+)", m.group(1))
    return tuple((int(a), int(b)) for a, b in pairs)


@dataclasses.dataclass(frozen=True)
class Instruction:
    """One HLO instruction, as printed."""

    name: str
    op: str  # op token, including any -start/-done suffix
    buffers: Tuple[Buffer, ...]
    refs: frozenset  # every %name referenced on the line (operands +
    #                  called computations) — the conservative edge set
    op_name: str  # metadata op_name (named-scope path), "" if absent
    computation: str  # owning computation's name
    replica_groups: Optional[Tuple[Tuple[int, ...], ...]] = None
    source_target_pairs: Optional[Tuple[Tuple[int, int], ...]] = None
    channel_id: Optional[int] = None
    is_root: bool = False
    parameter_number: Optional[int] = None

    @property
    def base_op(self) -> str:
        """Op with the async `-start` suffix stripped (a `-done` keeps
        its suffix: the pair is counted once, on the start)."""
        return self.op[:-6] if self.op.endswith("-start") else self.op

    @property
    def nbytes(self) -> int:
        return sum(b.nbytes for b in self.buffers)

    @property
    def is_scalar(self) -> bool:
        """True when every result buffer is rank-0 (the metrics-psum
        shape every engine legitimately keeps). An instruction whose
        result failed the shape grammar (empty buffers) answers False —
        an unparseable collective must stay VISIBLE to the non-scalar
        rules, not vanish into the scalar allowance."""
        return bool(self.buffers) and all(b.is_scalar for b in self.buffers)


@dataclasses.dataclass
class HloModule:
    """Parsed module: computations (name -> ordered instruction names),
    instructions (name -> Instruction), entry computation name, and the
    input_output_alias table from the HloModule header line."""

    computations: Dict[str, List[str]]
    instructions: Dict[str, Instruction]
    entry: Optional[str]
    input_output_aliases: int  # number of aliased output indices
    text: str

    # ---------------------------------------------------- reachability

    def depends_on(self, start: str, targets: Iterable[str]) -> bool:
        """True when `start` transitively references any name in
        `targets`, through operands and called computations — the
        conservative over-approximation of data dependence (module
        docstring)."""
        targets = set(targets)
        seen, stack = set(), [start]
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            if n in targets and n != start:
                return True
            instr = self.instructions.get(n)
            refs = instr.refs if instr is not None else ()
            for r in refs:
                if r in self.computations:
                    stack.extend(self.computations[r])
                elif r in self.instructions:
                    stack.append(r)
        return False

    # --------------------------------------------------------- queries

    def tagged(self, tag: str, op_prefix: Optional[str] = None
               ) -> List[str]:
        """Instruction names whose op_name metadata carries `tag` (a
        named-scope segment, matched with its trailing '/' so stage1
        never matches stage10), optionally filtered by op prefix."""
        return [
            n for n, i in self.instructions.items()
            if f"{tag}/" in i.op_name
            and (op_prefix is None or i.op.startswith(op_prefix))
        ]

    def collectives(self) -> List[Instruction]:
        """Every collective instruction, async pairs counted once (the
        `-start` form carries the attributes; `-done` is skipped)."""
        out = []
        for i in self.instructions.values():
            base = i.op[:-6] if i.op.endswith("-start") else i.op
            if base in COLLECTIVE_OPS and not i.op.endswith("-done"):
                out.append(i)
        return out

    def entry_parameters(self) -> List[Instruction]:
        """The entry computation's `parameter` instructions — the
        per-device at-rest buffers of the compiled step (what the FSDP
        at-rest rule sizes)."""
        if self.entry is None:
            return []
        return [
            self.instructions[n]
            for n in self.computations.get(self.entry, [])
            if n in self.instructions
            and self.instructions[n].op == "parameter"
        ]


def parse_hlo(text: str) -> HloModule:
    """Parse compiled-HLO text into an `HloModule`.

    Tolerant by construction: unknown attributes are ignored, an
    instruction that fails the shape grammar still lands in the graph
    with empty buffers, and metadata-free lines get an empty op_name —
    parsing must never be the reason a lint run dies (missing pieces
    surface as rule findings instead)."""
    comps: Dict[str, List[str]] = {}
    instrs: Dict[str, Instruction] = {}
    entry = None
    current = None
    aliases = 0
    for raw in text.splitlines():
        s = raw.strip()
        if not s:
            continue
        if s.startswith("HloModule"):
            if "input_output_alias=" in s:
                # one `}: (` per alias entry: `{0}: (0, {}, may-alias)`
                aliases = len(re.findall(r"\}\s*:\s*\(", s))
            continue
        if s.endswith("{") and "= " not in s:
            m = _COMP_RE.match(s)
            if m:
                current = m.group(1)
                comps[current] = []
                if s.startswith("ENTRY"):
                    entry = current
                continue
        if s == "}":
            current = None
            continue
        m = _INSTR_RE.match(s)
        if m and current is not None:
            name, result, op = m.groups()
            meta = _OPNAME_RE.search(s)
            chan = _CHANNEL_RE.search(s)
            gm = _GROUPS_RE.search(s)
            groups = parse_replica_groups(gm.group(1)) if gm else None
            pairs = _parse_pairs(s)
            pnum = None
            if op == "parameter":
                pm = re.search(r"parameter\((\d+)\)", s)
                pnum = int(pm.group(1)) if pm else None
            refs = frozenset(re.findall(r"%([\w.\-]+)", s)) - {name}
            instrs[name] = Instruction(
                name=name,
                op=op,
                buffers=parse_result_buffers(result),
                refs=refs,
                op_name=meta.group(1) if meta else "",
                computation=current,
                replica_groups=groups,
                source_target_pairs=pairs,
                channel_id=int(chan.group(1)) if chan else None,
                is_root=s.startswith("ROOT"),
                parameter_number=pnum,
            )
            comps[current].append(name)
    return HloModule(
        computations=comps,
        instructions=instrs,
        entry=entry,
        input_output_aliases=aliases,
        text=text,
    )


# ------------------------------------------------- text-level helpers
# The original test-file pins matched raw text; these keep that exact
# behavior available (and the refactored tests byte-compatible) without
# a full parse.


def collective_counts(hlo: str) -> Dict[str, int]:
    """Occurrences of each collective OP (not operand mentions) in
    compiled HLO text; async backends emit `<op>-start`/`-done` pairs,
    counted once via the -start form."""

    def n(op):
        return len(re.findall(rf"= {RESULT_RE} {op}(?:-start)?\(", hlo))

    return {op: n(op) for op in COLLECTIVE_OPS}


def has_op_with_result(hlo: str, op: str, shape: str) -> bool:
    """True when an `op` whose RESULT carries `shape` exists — matched
    on the op's definition line (sync or async-start form), never on
    operand mentions."""
    pat = (
        rf"= (?:\([^)\n]*{re.escape(shape)}[^)\n]*\)|{re.escape(shape)}"
        rf"\S*) {op}(?:-start)?\("
    )
    return re.search(pat, hlo) is not None


def nonscalar_all_reduce_count(hlo: str) -> int:
    """all-reduce ops whose RESULT carries at least one non-scalar
    buffer — gradient-sized reductions, as opposed to the scalar
    metrics psums every engine legitimately keeps."""
    n = 0
    for m in re.finditer(rf"= ({RESULT_RE}) all-reduce(?:-start)?\(", hlo):
        if re.search(r"\[\d", m.group(1)):
            n += 1
    return n


__all__ = [
    "Buffer",
    "COLLECTIVE_OPS",
    "DTYPE_BYTES",
    "HloModule",
    "Instruction",
    "RESULT_RE",
    "collective_counts",
    "has_op_with_result",
    "nonscalar_all_reduce_count",
    "parse_hlo",
    "parse_replica_groups",
    "parse_result_buffers",
]
