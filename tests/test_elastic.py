"""Elastic restart tests: fail-fast + resume-from-checkpoint loop
(SURVEY.md §5 failure-detection row; VERDICT r2 'what's weak' #8)."""

import jax
import pytest

from distributed_model_parallel_tpu.data.datasets import synthetic
from distributed_model_parallel_tpu.data.loader import Loader
from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn
from distributed_model_parallel_tpu.parallel.data_parallel import (
    DataParallelEngine,
)
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.checkpoint import latest_exists
from distributed_model_parallel_tpu.training.elastic import elastic_fit
from distributed_model_parallel_tpu.training.optim import SGD
from distributed_model_parallel_tpu.training.trainer import (
    Trainer,
    TrainerConfig,
)


class FlakyEngine:
    """Engine wrapper that dies once at a chosen train step — the
    single-controller stand-in for a lost host (whose collective error
    surfaces exactly like this: an exception out of train_step)."""

    def __init__(self, inner, fail_at_call: int):
        self.inner = inner
        self.fail_at_call = fail_at_call
        self.calls = 0
        self.already_failed = False

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def train_step(self, *args):
        self.calls += 1
        if not self.already_failed and self.calls == self.fail_at_call:
            self.already_failed = True
            raise RuntimeError("injected host failure")
        return self.inner.train_step(*args)


def _factory(tmp_path, engine, epochs=4):
    ds = synthetic(num_examples=128, num_classes=4, image_size=8, seed=0)
    trainers = []

    def make_trainer(restart: bool) -> Trainer:
        cfg = TrainerConfig(
            epochs=epochs, base_lr=0.05, t_max=epochs, warmup_period=1,
            print_freq=0,
            log_dir=str(tmp_path / "log"),
            checkpoint_dir=str(tmp_path / "ckpt"),
            resume=restart and latest_exists(str(tmp_path / "ckpt"), "last"),
            save_last=True,
        )
        train = Loader(ds, batch_size=32, shuffle=True, seed=0)
        val = Loader(ds, batch_size=32, shuffle=False)
        t = Trainer(engine, train, val, cfg, rng=jax.random.PRNGKey(0))
        trainers.append(t)
        return t

    return make_trainer, trainers


def test_elastic_restarts_from_last_checkpoint(tmp_path):
    mesh = make_mesh(MeshSpec(data=8))
    engine = FlakyEngine(
        DataParallelEngine(tiny_cnn(4), SGD(), mesh, donate=False),
        fail_at_call=7,  # dies in epoch 1 (4 steps/epoch)
    )
    make_trainer, trainers = _factory(tmp_path, engine)
    result = elastic_fit(make_trainer, max_restarts=2)

    assert len(trainers) == 2                # one restart
    assert trainers[0].start_epoch == 0
    # Epoch 0 completed + save_last ran before the injected failure, so
    # the restart resumes at epoch 1 — at most the failed epoch is lost.
    assert trainers[1].start_epoch == 1
    total_epochs = {h["epoch"] for h in result["history"]}
    assert total_epochs == {1, 2, 3}         # final attempt's epochs
    assert latest_exists(str(tmp_path / "ckpt"), "last")


def test_elastic_gives_up_after_budget(tmp_path):
    class AlwaysDies:
        def __init__(self, inner):
            self.inner = inner

        def __getattr__(self, name):
            return getattr(self.inner, name)

        def train_step(self, *args):
            raise RuntimeError("permanent failure")

    mesh = make_mesh(MeshSpec(data=8))
    engine = AlwaysDies(
        DataParallelEngine(tiny_cnn(4), SGD(), mesh, donate=False)
    )
    make_trainer, trainers = _factory(tmp_path, engine)
    with pytest.raises(RuntimeError, match="permanent failure"):
        elastic_fit(make_trainer, max_restarts=2, backoff_seconds=0.01)
    assert len(trainers) == 3  # initial + 2 restarts, then fail-fast
