"""Pipeline microbatch sweep: measured time/batch vs the bubble math,
for BOTH schedules (GPipe fill-drain and 1F1B/PipeDream-flush).

The reference's headline pipeline finding is that one-batch-in-flight
model parallelism is ~4x slower than data parallelism
(`/root/reference/Readme.md:283-292`) — a pure schedule artifact: with S
stages and M microbatches the pipeline runs M+S-1 ticks for M microbatches
of work, so time/batch scales like (M+S-1)/M (=S at the reference's M=1,
->1 as M grows). Both schedules share that bubble curve; what separates
them is MEMORY. GPipe holds all M microbatch activations live through the
backward (the stash grows O(M), so the bubble can only be shrunk by
spending memory), while 1F1B caps the live window at min(S, M) — the
sweep records each engine's traced stash metadata next to its throughput
so the figure shows the schedule trade directly.

Run: python experiments/pipeline_microbatch_sweep.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_model_parallel_tpu.runtime.platform import force_cpu  # noqa: E402


def main() -> None:
    force_cpu(8)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_model_parallel_tpu.models import layers as L
    from distributed_model_parallel_tpu.parallel import PipelineEngine
    from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
    from distributed_model_parallel_tpu.training.optim import SGD

    S = 4
    mesh = make_mesh(MeshSpec(data=2, stage=S))
    stages = [
        L.sequential(L.conv2d(3, 32, 3, stride=1, padding=1), L.relu()),
        L.sequential(L.conv2d(32, 32, 3, stride=1, padding=1), L.relu()),
        L.sequential(L.conv2d(32, 32, 3, stride=1, padding=1), L.relu()),
        L.sequential(L.global_avg_pool(), L.linear(32, 10)),
    ]
    rng = np.random.RandomState(0)
    batch = 64
    images = rng.rand(batch, 8, 8, 3).astype(np.float32)
    labels = rng.randint(0, 10, size=(batch,)).astype(np.int32)

    schedules = ("gpipe", "1f1b")
    rows = {sched: [] for sched in schedules}
    for m in (1, 2, 4, 8, 16):
        for sched in schedules:
            engine = PipelineEngine(
                stages, SGD(), mesh, num_microbatches=m, donate=False,
                schedule=sched,
            )
            ts = engine.init_state(jax.random.PRNGKey(0))
            im, lb = engine.shard_batch(images, labels)
            lr = jnp.float32(0.05)
            for _ in range(2):  # compile + warm
                ts, _ = engine.train_step(ts, im, lb, lr)
            jax.block_until_ready(ts)
            iters = 4
            t0 = time.perf_counter()
            for _ in range(iters):
                ts, _ = engine.train_step(ts, im, lb, lr)
            jax.block_until_ready(ts)
            dt = (time.perf_counter() - t0) / iters
            # Live activation window per stage: GPipe's autodiff stash is
            # every in-flight microbatch; 1F1B's is its static ring.
            if sched == "1f1b":
                stash = engine._sched_1f1b.stash_depth
            else:
                stash = m
            rows[sched].append(
                {"M": m, "time_per_batch": dt, "live_activations": stash}
            )
            print(f"{sched:>5} M={m:>2}: {dt:.3f} s/batch, "
                  f"live acts/stage={stash}", flush=True)

    for sched in schedules:
        base = rows[sched][0]["time_per_batch"]  # M=1: reference schedule
        for r in rows[sched]:
            m = r["M"]
            r["speedup_vs_m1"] = round(base / r["time_per_batch"], 2)
            # ideal time ratio t(M)/t(1) = (M+S-1) / (M*S)
            r["ideal_speedup"] = round(m * S / (m + S - 1), 2)

    os.makedirs("pic", exist_ok=True)
    with open("experiments/pipeline_microbatch_sweep.json", "w") as f:
        json.dump({"S": S, "batch": batch, "rows": rows}, f, indent=2)

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    ms = [r["M"] for r in rows["gpipe"]]
    fig, (ax, ax2) = plt.subplots(1, 2, figsize=(11, 4))
    ax.plot(ms, [r["speedup_vs_m1"] for r in rows["gpipe"]], marker="o",
            label="gpipe measured")
    ax.plot(ms, [r["speedup_vs_m1"] for r in rows["1f1b"]], marker="^",
            label="1f1b measured")
    ax.plot(ms, [r["ideal_speedup"] for r in rows["gpipe"]], marker="s",
            linestyle="--", label="ideal  M·S/(M+S−1)")
    ax.set_xscale("log", base=2)
    ax.set_xticks(ms)
    ax.set_xticklabels(ms)
    ax.set_xlabel("microbatches M")
    ax.set_ylabel("speedup vs M=1 (reference schedule)")
    ax.set_title(f"bubble (S−1)/(M+S−1), S={S}: both schedules")
    ax.grid(alpha=0.3)
    ax.legend()
    ax2.plot(ms, [r["live_activations"] for r in rows["gpipe"]],
             marker="o", label="gpipe  (O(M))")
    ax2.plot(ms, [r["live_activations"] for r in rows["1f1b"]],
             marker="^", label="1f1b  (O(S): ring ≤ min(S, M))")
    ax2.set_xscale("log", base=2)
    ax2.set_xticks(ms)
    ax2.set_xticklabels(ms)
    ax2.set_xlabel("microbatches M")
    ax2.set_ylabel("live activations per stage")
    ax2.set_title("activation memory vs M")
    ax2.grid(alpha=0.3)
    ax2.legend()
    fig.tight_layout()
    fig.savefig("pic/pipeline_microbatch_sweep.png", dpi=120)
    print("wrote pic/pipeline_microbatch_sweep.png")


if __name__ == "__main__":
    main()
