"""Shared CLI plumbing: dataset/loader construction and model selection."""

from __future__ import annotations

import argparse
from typing import Tuple

import jax
import numpy as np

from distributed_model_parallel_tpu.data.datasets import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    IMAGENET_MEAN,
    IMAGENET_STD,
    DatasetCollection,
)
from distributed_model_parallel_tpu.data.loader import Loader
from distributed_model_parallel_tpu.models import (
    mobilenet_v2,
    mobilenet_v2_nobn,
    mobilenetv2,
    resnet,
    resnet18,
    resnet50,
    tiny_cnn,
    tinycnn,
    vit_cifar,
)

MODELS = {
    "mobilenetv2": mobilenet_v2,
    "mobilenetv2_nobn": mobilenet_v2_nobn,
    "resnet18": resnet18,
    "resnet50": resnet50,
    "tinycnn": tiny_cnn,
    "vit": vit_cifar,  # CIFAR-scale ViT (32^2 inputs, 4x4 patches)
}

# Pipeline stage builders, kept beside MODELS so both CLIs extend in one
# place: name -> fn(num_stages, num_classes, boundaries) -> [Layer].
STAGE_BUILDERS = {
    "mobilenetv2": lambda n, c, b: mobilenetv2.split_stages(
        n, c, boundaries=b
    ),
    "mobilenetv2_nobn": lambda n, c, b: mobilenetv2.split_stages(
        n, c, batchnorm=False, boundaries=b
    ),
    "resnet18": lambda n, c, b: resnet.split_stages(
        18, n, c, cifar=True, boundaries=b
    ),
    "resnet50": lambda n, c, b: resnet.split_stages(
        50, n, c, boundaries=b
    ),
    "tinycnn": lambda n, c, b: tinycnn.split_stages(n, c, boundaries=b),
}


def build_optimizer(args):
    """--optimizer flag -> optimizer instance. --wd keeps its surface
    meaning for both (decay strength); --momentum applies to sgd only."""
    from distributed_model_parallel_tpu.training.optim import SGD, AdamW

    if args.optimizer == "adamw":
        return AdamW(weight_decay=args.weight_decay)
    return SGD(momentum=args.momentum, weight_decay=args.weight_decay)


def build_model(name: str, num_classes: int, *, remat: bool = False):
    if name not in MODELS:
        raise SystemExit(f"unknown model {name!r}; choose from {sorted(MODELS)}")
    return MODELS[name](num_classes, remat=remat)


def stats_for(dataset_type: str) -> Tuple[np.ndarray, np.ndarray]:
    if dataset_type in ("CIFAR10", "Synthetic"):
        return CIFAR10_MEAN, CIFAR10_STD
    return IMAGENET_MEAN, IMAGENET_STD


def build_loaders(
    dataset_type: str,
    data_path: str,
    batch_size: int,
    *,
    val_batch_size: int | None = None,
    augment: bool = True,
    seed: int = 0,
    workers: int = 1,
):
    """(train_loader, val_loader, num_classes) with per-host sharding —
    the DistributedSampler the reference lacks (`utils.py:21`).

    `batch_size` / `val_batch_size` are GLOBAL batch sizes (the reference's
    `-b 512` means 512 total, and lr=0.4 is tuned to that); each host's
    Loader draws global/process_count samples per step."""
    procs = jax.process_count()
    if batch_size % procs:
        raise SystemExit(
            f"global batch size {batch_size} must be divisible by the "
            f"process count {procs}"
        )
    if val_batch_size is not None and val_batch_size % procs:
        raise SystemExit(
            f"global val batch size {val_batch_size} must be divisible by "
            f"the process count {procs}"
        )
    train_ds, val_ds = DatasetCollection(dataset_type, data_path).init()
    mean, std = stats_for(dataset_type)
    train = Loader(
        train_ds,
        batch_size=batch_size // procs,
        shuffle=True,
        augment=augment,
        mean=mean,
        std=std,
        seed=seed,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        workers=workers,
    )
    val = Loader(
        val_ds,
        batch_size=(val_batch_size or batch_size) // procs,
        shuffle=False,
        augment=False,
        mean=mean,
        std=std,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        drop_last=False,
        workers=workers,
    )
    return train, val, train_ds.num_classes


def check_batch_divisibility(
    global_batch: int, mesh, *, microbatches: int = 1, label: str = "batch"
) -> None:
    """Fail at startup (not at trace time, possibly an epoch in) when the
    batch cannot be laid out on the mesh: the global batch shards over the
    'data' axis, and each device's shard must split into `microbatches`
    equal microbatches for the pipeline schedule."""
    data_axis = mesh.shape["data"]
    if global_batch % data_axis:
        raise SystemExit(
            f"{label} size {global_batch} must be divisible by the 'data' "
            f"mesh axis ({data_axis} shards)"
        )
    local = global_batch // data_axis
    if local % microbatches:
        raise SystemExit(
            f"{label} size {global_batch} gives {local} samples per 'data' "
            f"shard, not divisible by --microbatches {microbatches}"
        )


def compute_dtype_from_flag(name: str):
    """--dtype flag value -> engine compute_dtype (None = pure f32)."""
    import jax.numpy as jnp

    return {"float32": None, "bfloat16": jnp.bfloat16}[name]


def add_common_tpu_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--model", default="mobilenetv2", choices=sorted(MODELS),
        help="model family (reference hard-codes MobileNetV2)",
    )
    parser.add_argument(
        "--dtype", default="float32", choices=("float32", "bfloat16"),
        help="activation/compute dtype (params stay f32); bfloat16 is the "
             "TPU MXU's native matmul precision",
    )
    parser.add_argument(
        "--remat", action="store_true",
        help="rematerialize activations during backward (jax.checkpoint) "
             "— trades compute for HBM on deep models",
    )
    parser.add_argument(
        "--optimizer", default="sgd", choices=("sgd", "adamw"),
        help="sgd = the reference's SGD(momentum, wd) surface; adamw = "
             "decoupled-decay AdamW (the transformer-family convention)",
    )
    parser.add_argument(
        "--profile-dir", default=None,
        help="capture a jax.profiler trace of a few steady-state steps "
             "into this directory",
    )
    parser.add_argument(
        "--steps-per-epoch", default=0, type=int,
        help="truncate each epoch to N batches (0 = full epoch); "
             "for smoke runs and benchmarking",
    )
    parser.add_argument(
        "--log-file", default=None,
        help="epoch log filename under ./log (reference: 512.txt)",
    )
