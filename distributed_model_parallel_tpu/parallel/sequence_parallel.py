"""Sequence/context-parallel training engine — activations sharded over
`'seq'`.

Long-context training for the transformer family: token activations
(B, T, D) are sharded T/N per device over the `'seq'` mesh axis, so the
per-device activation (and attention working-set) memory scales 1/N with
the ring — the reason context parallelism exists. Attention is the only
cross-token op; it runs through `ops.ring_attention.ring_attention`
(K/V rotating over ICI, exact online-softmax) or `ulysses_attention`
(all-to-all head scatter). Everything else (LayerNorm, FFN, dropout) is
per-token and needs no communication. Parameters stay replicated
(compose with the 'model' axis / TensorParallelEngine for weight
sharding).

Mirrors the pipeline engine's autodiff discipline (`parallel/pipeline.py`):
the loss is computed ONLY on the shard that owns the [CLS] token (global
position 0 lives on seq-shard 0) and kept local — no psum before
`jax.grad` — so under `check_vma=False` no differentiated cross-device
reduction exists; the reversed ring permutes / all-to-alls alone carry
cotangents between shards, and the complementary per-shard param grads
are psum'd over 'seq' after grad (+ pmean over 'data').

The reference has nothing in this category (SURVEY.md §5: long-context
"entirely absent"); this engine exists because the framework treats
long-sequence training as first-class.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from distributed_model_parallel_tpu.runtime.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.models.bert import (
    BertConfig,
    _cls_head,
    _embeddings,
    _encoder_blocks,
    embed_apply,
    head_apply,
)
from distributed_model_parallel_tpu.ops.ring_attention import (
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)
from distributed_model_parallel_tpu.ops.grad_reduction import (
    MONOLITHIC_BUCKET_MB,
    bucketed_psum,
    data_replica_index,
)
from distributed_model_parallel_tpu.ops.wire_codec import require_dcn_axis
from distributed_model_parallel_tpu.parallel.data_parallel import (
    TrainState,
    _metrics,
    _place_batch,
)
from distributed_model_parallel_tpu.runtime.mesh import (
    data_hierarchy_axes,
)
from distributed_model_parallel_tpu.training.metrics import cross_entropy
from distributed_model_parallel_tpu.training.optim import SGD

def _ulysses_flash(*args, **kw):
    from distributed_model_parallel_tpu.ops.pallas_attention import (
        flash_attention,
    )

    return ulysses_attention(*args, attention_impl=flash_attention, **kw)


ATTENTION = {
    "ring": ring_attention,
    "ring_flash": ring_flash_attention,  # Pallas kernels per hop
    "ulysses": ulysses_attention,
    "ulysses_flash": _ulysses_flash,     # Pallas kernel as the local core
}


def _check_seq_len(ids, max_position: int, cfg_name: str) -> None:
    """Refuse global sequence lengths past the position table.

    Both SP engines' forwards slice the table with `dynamic_slice`, which
    CLAMPS out-of-range starts — shards past the table end would silently
    reuse the last position rows instead of failing like the dense stem's
    broadcast does. Validate in shard_batch, where the first real batch's
    T is known."""
    if ids.shape[1] > max_position:
        raise ValueError(
            f"global sequence length {ids.shape[1]} exceeds the "
            f"position table (max_position={max_position}); later 'seq' "
            f"shards would silently reuse position rows. Raise "
            f"{cfg_name}.max_position to at least the sequence length."
        )


def _seq_matmul_policy(enabled: bool, ffn_dim: int, seq_shards: int):
    """Collective-matmul policy for the SP engines (or None when off):
    `LocalCollectiveMatmul` over 'seq', FFN pair only — validated here so
    a non-divisible FFN width fails at construction, not an epoch in."""
    if not enabled:
        return None
    if ffn_dim % seq_shards:
        raise ValueError(
            f"collective_matmul=True chunks the FFN width over the "
            f"'seq' axis: intermediate/ffn dim {ffn_dim} must be "
            f"divisible by the {seq_shards} sequence shards"
        )
    from distributed_model_parallel_tpu.ops.collective_matmul import (
        LocalCollectiveMatmul,
    )

    return LocalCollectiveMatmul(axis="seq")


@dataclasses.dataclass
class SequenceParallelEngine:
    """BERT-family classification training with 'seq'-sharded activations.

    Parameters are IDENTICAL in structure to
    `bert_for_classification(num_classes, cfg)` — checkpoints and the
    transformers-weight transplant (tests/test_bert.py) interoperate.
    The global sequence length must be divisible by the 'seq' axis size
    (and, for 'ulysses', heads by the axis size)."""

    cfg: BertConfig
    num_classes: int
    optimizer: Any  # SGD | AdamW (init/update/state_shardings protocol)
    mesh: Mesh
    attention: str = "ring"
    donate: bool = True
    compute_dtype: Any = None
    # Rematerialize each transformer block during backward (jax.checkpoint).
    remat: bool = False
    # Latency-hiding collective matmul (default off): the FFN pair of
    # every block runs as chunked ppermute rings over 'seq' — each shard
    # slices its column/row block of the (replicated-in-storage) FFN
    # weights, gathers tokens via ag_matmul and scatters partial sums
    # back via matmul_rs, overlapping every hop with the chunk dot
    # (`ops/collective_matmul.py::LocalCollectiveMatmul`). Attention
    # projections stay local (their outputs feed the K/V ring). Same
    # math — parity pinned in tests/test_collective_matmul.py.
    collective_matmul: bool = False

    def __post_init__(self):
        mesh = self.mesh
        if "seq" not in mesh.axis_names:
            raise ValueError("sequence-parallel mesh needs a 'seq' axis")
        if self.attention not in ATTENTION:
            raise ValueError(
                f"attention must be one of {sorted(ATTENTION)}, "
                f"got {self.attention!r}"
            )
        cfg = self.cfg
        if getattr(cfg, "num_experts", 0) > 0:
            # MoE routing is per-shard under 'seq' sharding and the loss
            # lives on the [CLS] shard only, so the moe_aux load-balance
            # leaves would be silently dropped (and per-shard capacity
            # semantics differ from the dense model). Refuse loudly —
            # the GSPMD engines (DP/DDP/TP/EP) are the MoE path.
            raise NotImplementedError(
                "BertConfig.num_experts > 0 is not supported by "
                "SequenceParallelEngine; train MoE models with the "
                "DP / DDP / TensorParallel / ExpertParallel engines."
            )
        attn_fn = partial(ATTENTION[self.attention], axis_name="seq")
        self._matmul = _seq_matmul_policy(
            self.collective_matmul, cfg.intermediate_size,
            mesh.shape["seq"],
        )
        mm = self._matmul
        self._repl = NamedSharding(mesh, P())
        self._batch = NamedSharding(mesh, P(("data",), ("seq",)))
        self._labels = NamedSharding(mesh, P(("data",)))
        block_list = _encoder_blocks(cfg, attn_fn)
        if self.remat:
            block_list = [L.remat(b) for b in block_list]
        self._blocks = L.sequential(*block_list)
        self._full = L.named([
            ("stem", _embeddings(cfg)),
            ("blocks", self._blocks),
            ("head", _cls_head(cfg, self.num_classes)),
        ])
        self._ln = L.layernorm(cfg.hidden_size, eps=cfg.layer_norm_eps)
        self._drop = L.dropout(cfg.dropout_rate)
        # Encoder layers are stateless; sequential still wants its keyed
        # (empty) state dict.
        blocks_state = {str(i): {} for i in range(cfg.num_layers)}
        cdt = self.compute_dtype

        def forward(params, ids, ctx):
            """Seq-sharded forward on ONE device: local ids (Bl, Tl).
            The SAME stem/head math as the dense model (shared
            `embed_apply`/`head_apply` from models/bert.py), with the two
            position-dependent pieces made shard-aware: the position
            embedding slice starts at this shard's global offset, and the
            [CLS] pooler reads shard 0's local token 0."""
            tl = ids.shape[1]
            s_idx = lax.axis_index("seq")
            pos = lax.dynamic_slice_in_dim(
                params["stem"]["position"], s_idx * tl, tl, axis=0
            )
            h, mask = embed_apply(
                params["stem"], ids, cfg, self._ln, self._drop,
                ctx.child(0), positions=pos,
            )
            (h, _), _ = self._blocks.apply(
                params["blocks"], blocks_state, (h, mask), ctx.child(1)
            )
            logits = head_apply(params["head"], h[:, 0, :])
            # Only seq-shard 0's position 0 is the global [CLS]; other
            # shards' logits are garbage and masked out of loss/metrics.
            is_cls_shard = (s_idx == 0).astype(logits.dtype)
            return logits, is_cls_shard

        def shard_step(ts: TrainState, ids, labels, lr):
            rng = jax.random.fold_in(
                jax.random.fold_in(
                    jax.random.fold_in(jax.random.PRNGKey(0), ts.step),
                    lax.axis_index("data"),
                ),
                lax.axis_index("seq"),
            )
            ctx = L.Context(train=True, rng=rng, dtype=cdt, matmul=mm)

            def loss_fn(params):
                logits, is_cls = forward(params, ids, ctx)
                # Local loss (pipeline discipline: no psum before grad).
                loss = cross_entropy(logits, labels) * is_cls
                return loss, (logits, is_cls)

            (loss, (logits, is_cls)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(ts.params)
            # Per-shard grads are complementary pieces of the total
            # (each shard's tokens feed the rings); sum over 'seq',
            # average over 'data' — one fused all-reduce.
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(lax.psum(g, "seq"), "data"), grads
            )
            params, opt_state = self.optimizer.update(
                ts.params, ts.opt_state, grads, lr
            )
            new_ts = TrainState(
                params, ts.model_state, opt_state, ts.step + 1
            )
            m = _metrics(loss, logits, labels)
            m = {
                k: lax.psum(v * is_cls, ("seq", "data"))
                for k, v in m.items()
            }
            return new_ts, m

        def shard_eval(ts: TrainState, ids, labels):
            logits, is_cls = forward(
                ts.params, ids,
                L.Context(train=False, dtype=cdt, matmul=mm),
            )
            loss = cross_entropy(logits, labels) * is_cls
            m = _metrics(loss, logits, labels)
            return {
                k: lax.psum(v * is_cls, ("seq", "data"))
                for k, v in m.items()
            }

        donate = (0,) if self.donate else ()
        self.train_step = jax.jit(
            shard_map(
                shard_step, mesh=mesh,
                in_specs=(P(), P(("data",), ("seq",)), P(("data",)), P()),
                out_specs=(P(), P()),
                check_vma=False,
            ),
            donate_argnums=donate,
        )
        self.eval_step = jax.jit(
            shard_map(
                shard_eval, mesh=mesh,
                in_specs=(P(), P(("data",), ("seq",)), P(("data",))),
                out_specs=P(),
                check_vma=False,
            )
        )

    def init_state(self, rng: jax.Array) -> TrainState:
        params, model_state = self._full.init(rng)
        opt_state = self.optimizer.init(params)
        ts = TrainState(
            params, model_state, opt_state, jnp.zeros((), jnp.int32)
        )
        return jax.device_put(ts, self._repl)

    def shard_batch(self, ids, labels):
        """ids shard over ('data', 'seq'); labels over 'data' only."""
        _check_seq_len(ids, self.cfg.max_position, "BertConfig")
        ids_arr = _place_batch((ids,), self._batch)[0]
        labels_arr = _place_batch((labels,), self._labels)[0]
        return ids_arr, labels_arr


@dataclasses.dataclass
class CausalLMSequenceParallelEngine:
    """Decoder-only (GPT-family) LANGUAGE-MODEL training with
    'seq'-sharded activations — the long-context path for `models/gpt`.

    Parameters are identical in structure to `gpt_lm(cfg)`, so dense
    checkpoints interoperate. Unlike the classification engine (whose
    loss lives on the [CLS] shard alone), the next-token loss decomposes
    per position: `shard_batch` builds targets on the HOST
    (`models.gpt.lm_targets` — shard-boundary tokens included) and
    shards them alongside the ids, so every shard scores its own tokens
    with NO differentiated cross-shard reduction. Per-shard gradients of
    the local loss SUM are complementary pieces of the total; one fused
    `psum('seq','data')` after `jax.grad`, divided by the global valid-
    token count, yields exactly the dense mean-loss gradient.

    The attention rings rotate K/V with `causal=True`: blocks arriving
    from later shards are fully hidden, the resident block is
    triangular (`ops/ring_attention.py`)."""

    cfg: Any  # models.gpt.GPTConfig
    optimizer: Any  # SGD | AdamW (init/update/state_shardings protocol)
    mesh: Mesh
    attention: str = "ring"
    donate: bool = True
    compute_dtype: Any = None
    remat: bool = False
    # FFN pair as chunked ppermute rings over 'seq' (default off) — see
    # SequenceParallelEngine.collective_matmul.
    collective_matmul: bool = False
    # Gradient reduction over the DATA axes (the 'seq' psum is separate:
    # per-shard grads are complementary pieces, summed first either
    # way). "monolithic": one fused psum over ('seq', data axes).
    # "bucketed": Reducer-style flat buckets over the data fabric(s) —
    # ring reduce-scatter over 'ici', cross-slice all-reduce over 'dcn',
    # ring all-gather (`ops/grad_reduction.py`); hierarchy-aware on a
    # `MeshSpec(dcn=K)` mesh. "overlapped": the bucketed path fired
    # EAGERLY from a stagewise backward — decoder blocks are cut into
    # `overlap_stages` segments (`models/staging.split_points`), per-
    # segment vjp closures run late-layers-first, and each completed
    # segment's 'seq' psum + data-bucket rings launch before the earlier
    # segments' backward exists (tests/test_collectives_hlo.py pins the
    # dependency structure; parity in tests/test_grad_reduction.py).
    grad_reduction: str = "monolithic"
    bucket_mb: float = 25.0
    # Backward segment count under "overlapped" (0 = auto: min(4,
    # cfg.num_layers)).
    overlap_stages: int = 0
    # Compress the cross-slice 'dcn' hop of the DATA-axis bucket
    # reduction to this wire dtype ("none" | "bf16" | "int8",
    # `ops/wire_codec.py`) — the 'seq' psum (complementary per-shard
    # pieces, intra-slice) stays in the math dtype. Requires a
    # MeshSpec(dcn=K) mesh; under grad_reduction="monolithic" the data
    # reduction lowers through one flat bucket per dtype so the 'dcn'
    # hop has a seam to compress (see DDPEngine.dcn_compression).
    dcn_compression: str = "none"

    def __post_init__(self):
        from distributed_model_parallel_tpu.models.gpt import (
            decoder_blocks,
            gpt_lm,
            head_apply as lm_head_apply,
            lm_targets,
            stem_apply as lm_stem_apply,
        )

        mesh = self.mesh
        if "seq" not in mesh.axis_names:
            raise ValueError("sequence-parallel mesh needs a 'seq' axis")
        if self.attention not in ATTENTION:
            raise ValueError(
                f"attention must be one of {sorted(ATTENTION)}, "
                f"got {self.attention!r}"
            )
        if self.grad_reduction not in (
            "monolithic", "bucketed", "overlapped"
        ):
            raise ValueError(
                "grad_reduction must be 'monolithic', 'bucketed' or "
                f"'overlapped', got {self.grad_reduction!r}"
            )
        d_axes, ici_axis, dcn_axis = data_hierarchy_axes(mesh)
        bucketed = self.grad_reduction == "bucketed"
        overlapped = self.grad_reduction == "overlapped"
        bucket_mb = self.bucket_mb
        wire = require_dcn_axis(self.dcn_compression, dcn_axis)
        # Monolithic + compression routes the data reduction through
        # one flat bucket per dtype (class docstring).
        use_buckets = bucketed or (wire != "none" and not overlapped)
        data_bucket_mb = (
            bucket_mb if self.grad_reduction != "monolithic"
            else MONOLITHIC_BUCKET_MB
        )
        cfg = self.cfg
        if getattr(cfg, "num_experts", 0) > 0:
            # Same objection as the BERT SP engine: per-shard routing
            # under 'seq' sharding breaks the dense capacity semantics
            # and the moe_aux leaves never reach the differentiated
            # loss. The MoE text path is ExpertParallelLMEngine.
            raise NotImplementedError(
                "GPTConfig.num_experts > 0 is not supported by "
                "CausalLMSequenceParallelEngine; train MoE LMs with "
                "parallel/expert_parallel.ExpertParallelLMEngine "
                "(cli/lm.py --moe-experts)."
            )
        if overlapped:
            if cfg.num_layers < 2:
                raise ValueError(
                    "CausalLMSequenceParallelEngine: grad_reduction="
                    "'overlapped' splits the decoder stack into >= 2 "
                    f"backward segments; cfg.num_layers={cfg.num_layers}"
                )
            from distributed_model_parallel_tpu.models.staging import (
                resolve_overlap_segments,
                split_points,
            )

            n_over = resolve_overlap_segments(
                cfg.num_layers, self.overlap_stages,
                "CausalLMSequenceParallelEngine", noun="decoder blocks",
            )
            over_cuts = split_points(n_over, None, cfg.num_layers)
        self._lm_targets = partial(
            lm_targets, pad_token_id=cfg.pad_token_id
        )
        attn_fn = partial(
            ATTENTION[self.attention], axis_name="seq", causal=True
        )
        self._matmul = _seq_matmul_policy(
            self.collective_matmul, cfg.ffn_dim, mesh.shape["seq"]
        )
        mm = self._matmul
        self._repl = NamedSharding(mesh, P())
        self._batch = NamedSharding(mesh, P(d_axes, ("seq",)))
        # Dense-parameter twin used ONLY for init (identical pytree).
        self._full = gpt_lm(cfg)
        block_list = decoder_blocks(cfg, attn_fn)
        if self.remat:
            block_list = [L.remat(b) for b in block_list]
        blocks = L.sequential(*block_list)
        blocks_state = {str(i): {} for i in range(cfg.num_layers)}
        drop = L.dropout(cfg.dropout_rate)
        cdt = self.compute_dtype

        def forward(params, ids, ctx):
            """Per-shard forward: local ids (Bl, Tl) -> local logits.
            The SAME stem/head math as the dense model (shared
            `stem_apply`/`head_apply` from models/gpt.py), with the
            position-embedding slice made shard-aware: it starts at this
            shard's global offset (the dense stem would give shards
            1..N-1 local-offset positions — `models/gpt.gpt_lm` doc)."""
            tl = ids.shape[1]
            s_idx = lax.axis_index("seq")
            pos = lax.dynamic_slice_in_dim(
                params["stem"]["position"], s_idx * tl, tl, axis=0
            )
            h, mask = lm_stem_apply(
                params["stem"], ids, cfg, drop, ctx.child(0),
                positions=pos,
            )
            (h, _), _ = blocks.apply(
                params["blocks"], blocks_state, (h, mask), ctx.child(1)
            )
            return lm_head_apply(params["head"], h)

        def local_sums(logits, targets):
            """Per-shard metric SUMS over this shard's tokens — the
            shared `_metrics` contract on the flattened token axis."""
            b, tl, v = logits.shape
            flat_logits = logits.reshape(b * tl, v)
            flat_t = targets.reshape(b * tl)
            return _metrics(
                cross_entropy(flat_logits, flat_t), flat_logits, flat_t
            )

        def overlap_stage_fns(ctx):
            """Per-segment closures for the stagewise backward: the SAME
            stem/blocks/head math as `forward` (identical Context.child
            folding: stem -> ctx.child(0), block j -> ctx.child(1)
            .child(j)), cut at `over_cuts` block boundaries. Stage 0
            takes the local ids; the (hidden, mask) pair rides between
            segments; the LM head closes the last one."""
            block_ctx = ctx.child(1)
            fns = []
            n_over = len(over_cuts) - 1
            for i in range(n_over):
                def fn(p, _state, x, i=i):
                    k = 0
                    if i == 0:
                        tl = x.shape[1]
                        s_idx = lax.axis_index("seq")
                        pos = lax.dynamic_slice_in_dim(
                            p["0"]["position"], s_idx * tl, tl, axis=0
                        )
                        y = lm_stem_apply(
                            p["0"], x, cfg, drop, ctx.child(0),
                            positions=pos,
                        )
                        k = 1
                    else:
                        y = x
                    for j in range(over_cuts[i], over_cuts[i + 1]):
                        y, _ = block_list[j].apply(
                            p[str(k)], {}, y, block_ctx.child(j)
                        )
                        k += 1
                    if i == n_over - 1:
                        h, _mask = y
                        y = lm_head_apply(p[str(k)], h)
                    return y, {}

                fns.append(fn)
            return fns

        reduce_axes = ("seq",) + d_axes

        def shard_step(ts: TrainState, ids, targets, lr):
            rng = jax.random.fold_in(
                jax.random.fold_in(
                    jax.random.fold_in(jax.random.PRNGKey(0), ts.step),
                    data_replica_index(d_axes),
                ),
                lax.axis_index("seq"),
            )
            ctx = L.Context(train=True, rng=rng, dtype=cdt, matmul=mm)

            if overlapped:
                from distributed_model_parallel_tpu.models.staging import (
                    partition_tree,
                    stagewise_value_and_grad,
                    unpartition_tree,
                )

                def loss_head(logits):
                    m = local_sums(logits, targets)
                    # LOCAL token-loss sum (pipeline discipline: no
                    # psum before grad).
                    return m["loss_sum"], m

                def reduce_stage(k, stage_grads):
                    # 'seq' first (complementary per-shard pieces),
                    # then the Reducer buckets over the data fabric(s)
                    # — fired while earlier segments still
                    # differentiate.
                    with jax.named_scope(f"grad_reduce_stage{k}"):
                        return bucketed_psum(
                            jax.tree_util.tree_map(
                                lambda g: lax.psum(g, "seq"),
                                stage_grads,
                            ),
                            ici_axis, dcn_axis, bucket_mb=bucket_mb,
                            dcn_compression=wire,
                        )

                stage_params = partition_tree(ts.params, over_cuts)
                _, m, stage_grads, _ = stagewise_value_and_grad(
                    overlap_stage_fns(ctx), loss_head, stage_params,
                    [None] * (len(over_cuts) - 1), ids,
                    on_stage_grads=reduce_stage,
                )
                n_global = lax.psum(m["count"], reduce_axes)
                grads = jax.tree_util.tree_map(
                    lambda g: g / jnp.maximum(n_global, 1.0),
                    unpartition_tree(stage_grads, over_cuts),
                )
            else:
                def loss_fn(params):
                    logits = forward(params, ids, ctx)
                    m = local_sums(logits, targets)
                    # LOCAL token-loss sum (pipeline discipline: no psum
                    # before grad).
                    return m["loss_sum"], m

                (_, m), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(ts.params)
                n_global = lax.psum(m["count"], reduce_axes)
                if use_buckets:
                    # 'seq' first (complementary per-shard pieces — one
                    # fused psum over the TP-style axis), then the
                    # Reducer-style buckets over the data fabric(s).
                    grads = bucketed_psum(
                        jax.tree_util.tree_map(
                            lambda g: lax.psum(g, "seq"), grads
                        ),
                        ici_axis, dcn_axis, bucket_mb=data_bucket_mb,
                        dcn_compression=wire,
                    )
                    grads = jax.tree_util.tree_map(
                        lambda g: g / jnp.maximum(n_global, 1.0), grads
                    )
                else:
                    grads = jax.tree_util.tree_map(
                        lambda g: lax.psum(g, reduce_axes)
                        / jnp.maximum(n_global, 1.0),
                        grads,
                    )
            params, opt_state = self.optimizer.update(
                ts.params, ts.opt_state, grads, lr
            )
            new_ts = TrainState(
                params, ts.model_state, opt_state, ts.step + 1
            )
            return new_ts, {
                k: lax.psum(v, reduce_axes) for k, v in m.items()
            }

        def shard_eval(ts: TrainState, ids, targets):
            logits = forward(
                ts.params, ids,
                L.Context(train=False, dtype=cdt, matmul=mm),
            )
            m = local_sums(logits, targets)
            return {k: lax.psum(v, reduce_axes) for k, v in m.items()}

        donate = (0,) if self.donate else ()
        self.train_step = jax.jit(
            shard_map(
                shard_step, mesh=mesh,
                in_specs=(
                    P(), P(d_axes, ("seq",)), P(d_axes, ("seq",)),
                    P(),
                ),
                out_specs=(P(), P()),
                check_vma=False,
            ),
            donate_argnums=donate,
        )
        self.eval_step = jax.jit(
            shard_map(
                shard_eval, mesh=mesh,
                in_specs=(
                    P(), P(d_axes, ("seq",)), P(d_axes, ("seq",)),
                ),
                out_specs=P(),
                check_vma=False,
            )
        )

    def init_state(self, rng: jax.Array) -> TrainState:
        params, model_state = self._full.init(rng)
        opt_state = self.optimizer.init(params)
        ts = TrainState(
            params, model_state, opt_state, jnp.zeros((), jnp.int32)
        )
        return jax.device_put(ts, self._repl)

    def shard_batch(self, ids, labels=None):
        """ids (B, T) -> (ids, next-token targets), both sharded over
        ('data', 'seq'). `labels` is ignored (the LM's targets are the
        shifted ids); the parameter keeps the engine signature-uniform
        with the classification engines."""
        _check_seq_len(ids, self.cfg.max_position, "GPTConfig")
        targets = self._lm_targets(ids)
        ids_arr = _place_batch((ids,), self._batch)[0]
        targets_arr = _place_batch((targets,), self._batch)[0]
        return ids_arr, targets_arr
