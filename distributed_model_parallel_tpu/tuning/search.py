"""Deterministic enumerate-and-score search over one tuning cell.

Two-tier scoring, as the cost engine's shape demands:

1. **Closed-form pruning** — every candidate in the cell's space is
   priced with the alpha-beta closed forms (`observability/cost.py`,
   the same formulas scaling64 §3 asserts against) over the cell's
   payload model (gradient bytes from a jax.eval_shape of the lint
   proxy — no compile). Cheap enough to score the whole cross-product.
2. **Real lowering for the argmin finalists** — the K best-ranked
   candidates are lowered through `analysis/lint.lower_combo` (the
   SAME builders, models and meshes the hlolint rules and the costgate
   ledger judge) and priced from their compiled HLO
   (`cost.predict_collectives`). The argmin over the finalists is the
   plan.

The winner is then VERIFIED, not trusted: hlolint's full rule registry
runs over the winning lowering, so a plan that picked
`dcn_compression=int8` must actually produce
`dcn-compressed-payload`-clean HLO — a violation raises
`PlanLintError` naming the rule instead of emitting the plan.

Determinism contract: candidates enumerate in `space.candidates`'s
sorted order, ties break on `(score, space.preference, canonical
key)`, predicted times come from the ledger-rounded `as_row()` form —
two searches of the same cell produce byte-identical plans
(`plan.dumps_plan`), which is what `tools/plangate` gates on.

Both tiers price under the hand constants by default or an explicit
CONSTANTS-shaped dict (a loaded calibration) — measured physics, same
search. Heavy imports are function-local (module must import without a
backend).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

from distributed_model_parallel_tpu.tuning import space as tspace
from distributed_model_parallel_tpu.tuning.plan import Cell, make_plan

#: How many closed-form-ranked candidates get REAL lowering. Generous
#: relative to the spaces' plateau structure (the closed forms share
#: the walker's constants and formulas, so the true argmin landing
#: outside the top 4 would mean the closed form mis-ranks by more than
#: the candidates differ — the brute-force pin in tests/test_tuning.py
#: guards exactly that).
DEFAULT_FINALISTS = 4


class PlanLintError(RuntimeError):
    """The searched argmin's lowering violates a collective contract —
    the plan is NOT emitted (a tuner that ships physics-optimal but
    contract-breaking configurations is worse than no tuner)."""


# ----------------------------------------------------- payload models


def cell_payload(cell: Cell) -> dict:
    """The closed-form scorer's inputs for one cell, from the SAME lint
    proxy models the finalists will really lower — gradient bytes and
    block count via jax.eval_shape (no compile, no devices) for the
    reducer families, the dispatch-buffer element count for ep. tp has
    no closed-form payload (its two candidates are both lowered)."""
    if cell.family == "tp":
        return {}
    if cell.family == "serve":
        # The lint serve proxy (`analysis/lint._build_serve`): GPT
        # dim 16 / 2 layers / 4 heads serving 2S slots of a 16-position
        # cache — K+V bytes per token across the stack, a half-full
        # batch of live tokens, one 8-token prompt, 8 generated
        # tokens. jax-free on purpose (closed-form-only family).
        token_bytes = 2 * 2 * 16 * 4  # 2 (k+v) * layers * dim * f32
        return {
            "live_tokens": 2 * cell.size * 8,
            "prompt_tokens": 8,
            "new_tokens": 8,
            "token_bytes": token_bytes,
            # For the compute-dtype roofline term (ISSUE 16): the
            # proxy's slot batch and 'model' shard count.
            "n_slots": 2 * cell.size,
            "shards": cell.size,
        }
    import jax
    import jax.numpy as jnp

    from distributed_model_parallel_tpu.analysis import lint as L

    if cell.family == "plan":
        # The lint plan proxy (`analysis/lint._build_plan`): the same
        # tiny GPT as sp_lm, plus the STATIC shape facts the composed
        # closed form (`cost.composed_plan_step_s`) prices the wire and
        # KV-ring legs from. mb is the per-microbatch row count —
        # `_build_plan` feeds ids of shape (4 * dp * pp, 16), so every
        # plan's microbatch carries 4 rows.
        from distributed_model_parallel_tpu.models.gpt import gpt_lm

        cfg = L._gpt_cfg()
        key_aval = jax.ShapeDtypeStruct((2,), jnp.uint32)
        p_aval, _ = jax.eval_shape(gpt_lm(cfg).init, key_aval)
        grad_bytes = sum(
            int(math.prod(leaf.shape) or 1)
            * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree_util.tree_leaves(p_aval)
        )
        return {
            "grad_bytes": grad_bytes,
            "mb": 4,
            "seq_len": cfg.max_position,
            "dim": cfg.dim,
            "vocab": cfg.vocab_size,
            "n_layers": cfg.num_layers,
            # For the compute/bubble fold (ISSUE 20): the proxy's f32
            # parameter count — what the schedule knob's smaller
            # bubble actually multiplies.
            "params": grad_bytes // 4,
        }
    if cell.family in ("ddp", "fsdp"):
        if cell.model == "tinycnn":
            from distributed_model_parallel_tpu.models.tinycnn import (
                tiny_cnn,
            )

            model = tiny_cnn(4)
        else:
            model = L.staged_mlp(
                width=128 if cell.family == "fsdp" else 32
            )
    elif cell.family == "sp_lm":
        from distributed_model_parallel_tpu.models.gpt import gpt_lm

        model = gpt_lm(L._gpt_cfg())
    else:  # ep: the moe_classifier dispatch buffer, per device
        ici = cell.size // cell.dcn
        n = max(8, ici * cell.dcn)
        seq, dim, top_k, cap = 8, 16, 2, 1.25
        return {
            "elems": int(
                top_k * cap * (n * seq / cell.size) * dim
            ),
            "itemsize": 4,  # the lint classifier runs f32
        }
    key_aval = jax.ShapeDtypeStruct((2,), jnp.uint32)
    p_aval, _ = jax.eval_shape(model.init, key_aval)
    grad_bytes = sum(
        int(math.prod(leaf.shape) or 1) * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(p_aval)
    )
    return {
        "grad_bytes": grad_bytes,
        "n_blocks": len(model.parts.blocks),
    }


# --------------------------------------------------- closed-form tier


def reducer_closed_form_s(knobs: dict, grad_bytes: int, ici: int,
                          dcn: int, n_blocks: int = 4,
                          constants: Optional[Dict[str, float]] = None,
                          ) -> float:
    """Predicted per-step gradient-reduction comm time for one reducer
    candidate — the §3a/§3b/§3b' formulas keyed off the knobs. The
    bucket count is the flat approximation ceil(bytes / bucket)
    (dtype-grouping adds a bucket or two; ranking is unaffected), with
    a floor of one bucket per stagewise segment under 'overlapped'."""
    from distributed_model_parallel_tpu.observability import cost

    gr = knobs["grad_reduction"]
    wire = knobs["dcn_compression"]
    size = ici * dcn
    if gr == "monolithic":
        if wire == "none":
            bw_ici, a_ici, bw_dcn, a_dcn = cost._resolve_constants(
                constants
            )
            if dcn > 1:
                # One fused all-reduce whose membership crosses the
                # slice boundary: the slow fabric gates the whole ring.
                return cost.ring_all_reduce_s(
                    grad_bytes, size, n_ops=1, bw=bw_dcn, alpha=a_dcn
                )
            return cost.ring_all_reduce_s(
                grad_bytes, size, n_ops=1, bw=bw_ici, alpha=a_ici
            )
        # Compressed monolithic routes through ONE flat bucket
        # (MONOLITHIC_BUCKET_MB) — hierarchical with n_buckets=1.
        return cost.two_level_all_reduce_s(
            grad_bytes, ici, dcn, n_buckets=1, wire=wire,
            constants=constants,
        )
    n_seg = 1
    if gr == "overlapped":
        n_seg = knobs["overlap_stages"] or min(4, n_blocks)
    n_buckets = max(
        n_seg,
        math.ceil(grad_bytes / (knobs["bucket_mb"] * 2 ** 20)),
    )
    return cost.two_level_all_reduce_s(
        grad_bytes, ici, dcn, n_buckets=n_buckets, wire=wire,
        constants=constants,
    )


def moe_closed_form_s(knobs: dict, elems: int, itemsize: int,
                      ici: int, dcn: int,
                      constants: Optional[Dict[str, float]] = None,
                      ) -> float:
    """Predicted dispatch+combine comm time for one ep candidate — the
    §3c/§3c' exchange pair. Overlap reshapes the schedule, not the
    asks, so it prices identically and wins only through the tie-break
    (`space.preference`) when the extra structure is free."""
    from distributed_model_parallel_tpu.observability import cost

    if knobs["dispatch"] == "gspmd":
        return 2 * cost.flat_all_to_all_s(
            elems, itemsize, ici, dcn, constants=constants
        )
    wire = knobs["dcn_compression"]
    return 2 * cost.hierarchical_all_to_all_s(
        elems, itemsize, ici, dcn,
        wire=None if wire == "none" else wire, constants=constants,
    )


def serve_closed_form_s(knobs: dict, payload: dict,
                        constants: Optional[Dict[str, float]] = None,
                        ) -> float:
    """Predicted per-request serving cost for one paged-cache
    candidate — `cost.serve_paged_request_s` over the lint serve
    proxy's payload (the page-overscan vs gather-launch and
    chunk-padding vs chunk-launch tradeoffs, ISSUE 15) plus the
    compute-dtype roofline term over the request's decode steps
    (ISSUE 16; priced under the hand MXU/HBM constants — the comm
    `constants` dict is the calibratable set, compute is not).
    Speculative candidates (ISSUE 18) dispatch to
    `cost.serve_speculative_request_s`: the decode loop collapses to
    new_tokens / E verify rounds plus the draft's amortized share."""
    from distributed_model_parallel_tpu.observability import cost

    mode = knobs.get("compute_dtype") or "f32"
    spec_k = knobs.get("speculative_k") or 0
    decode_c = cost.serve_decode_compute_s(
        layers=2, dim=16, ffn_dim=32, n_slots=payload["n_slots"],
        mode=mode, shards=payload.get("shards", 1),
    )
    if spec_k:
        return cost.serve_speculative_request_s(
            payload["prompt_tokens"], payload["new_tokens"],
            payload["token_bytes"], knobs["page_size"],
            knobs["prefill_chunk"], spec_k,
            decode_compute_s=decode_c,
            verify_compute_s=cost.serve_verify_compute_s(
                layers=2, dim=16, ffn_dim=32,
                n_slots=payload["n_slots"], speculative_k=spec_k,
                mode=mode, shards=payload.get("shards", 1),
            ),
            constants=constants,
        )
    comm = cost.serve_paged_request_s(
        payload["live_tokens"], payload["prompt_tokens"],
        payload["new_tokens"], payload["token_bytes"],
        knobs["page_size"], knobs["prefill_chunk"],
        constants=constants,
    )
    return comm + payload["new_tokens"] * decode_c


def plan_closed_form_s(knobs: dict, payload: dict, ici: int, dcn: int,
                       constants: Optional[Dict[str, float]] = None,
                       ) -> float:
    """Predicted step time for one composed-plan candidate (ISSUE
    19/20): `cost.composed_plan_step_s` over the spec's axis
    factorization — the schedule's wire tick program on its fabric,
    the ring-attention KV hops on ICI, the ONE fused gradient psum as
    the hierarchical two-level form at dcn > 1, plus the proxy's ideal
    compute under the schedule's bubble factor (the term the
    1f1b/int<V> suffixes and the num_microbatches knob trade against
    the extra wire ticks)."""
    from distributed_model_parallel_tpu.observability import cost

    ax = tspace.plan_spec_axes(knobs["plan"])
    m = knobs.get("num_microbatches") or 0
    mb = payload["mb"]
    if m:
        # The proxy batch is fixed (mb rows per default microbatch, M
        # = pp of them); a deeper fill splits the same rows thinner.
        mb = max(1, payload["mb"] * ax["pp"] // m)
    compute_s = cost.plan_step_compute_s(
        payload["params"],
        payload["mb"] * ax["dp"] * ax["pp"] * payload["seq_len"],
        ax["pp"] * ax["sp"] * ax["dp"],
        constants=constants,
    ) if "params" in payload else 0.0
    return cost.composed_plan_step_s(
        ax["pp"], ax["sp"], ax["dp"],
        payload["grad_bytes"], mb, payload["seq_len"],
        payload["dim"], payload["vocab"], payload["n_layers"],
        ici, dcn, fsdp=ax["fsdp"], constants=constants,
        schedule=ax["schedule"], virtual_stages=ax["virtual"],
        num_microbatches=m, compute_s=compute_s,
    )


def closed_form_step_s(family: str, knobs: dict, payload: dict,
                       ici: int, dcn: int,
                       constants: Optional[Dict[str, float]] = None,
                       ) -> float:
    if family in ("ddp", "fsdp", "sp_lm"):
        return reducer_closed_form_s(
            knobs, payload["grad_bytes"], ici, dcn,
            n_blocks=payload.get("n_blocks", 4), constants=constants,
        )
    if family == "ep":
        return moe_closed_form_s(
            knobs, payload["elems"], payload["itemsize"], ici, dcn,
            constants=constants,
        )
    if family == "serve":
        return serve_closed_form_s(knobs, payload, constants)
    if family == "plan":
        return plan_closed_form_s(knobs, payload, ici, dcn, constants)
    return 0.0  # tp: both candidates are finalists; lowering decides


def rank_candidates(family: str, cands: Sequence[dict], payload: dict,
                    ici: int, dcn: int,
                    constants: Optional[Dict[str, float]] = None,
                    ) -> List[Tuple[float, dict]]:
    """[(closed_form_s, knobs)] in the search's deterministic order:
    score, then `space.preference`, then the canonical key."""
    scored = [
        (closed_form_step_s(family, k, payload, ici, dcn, constants),
         tspace.preference(family, k), tspace.canonical_key(k), k)
        for k in cands
    ]
    scored.sort(key=lambda t: t[:3])
    return [(s, k) for s, _, _, k in scored]


def closed_form_argmin(family: str, payload: dict, ici: int, dcn: int,
                       constants: Optional[Dict[str, float]] = None,
                       allow_cm: bool = True) -> Tuple[dict, float]:
    """(argmin knobs, predicted seconds) under the closed forms alone —
    the jax-free entry `experiments/scaling64.py` uses to put the
    tuner's @64 answer next to its hand-derived rows."""
    ranked = rank_candidates(
        family,
        tspace.candidates(family, dcn, allow_cm=allow_cm,
                          size=ici * dcn),
        payload, ici, dcn, constants,
    )
    score, knobs = ranked[0]
    return knobs, score


# ------------------------------------------------------ lowering tier


def candidate_combo(cell: Cell, knobs: dict):
    """Map one candidate onto the lint matrix's Combo vocabulary — the
    shared lowering path (`lower_combo`) then prices and lints the SAME
    program the engines would run."""
    from distributed_model_parallel_tpu.analysis.lint import Combo

    if cell.family in ("ddp", "fsdp", "sp_lm"):
        return Combo(
            cell.family, cell.size,
            grad_reduction=knobs["grad_reduction"],
            dcn=cell.dcn, model=cell.model,
            dcn_compression=knobs["dcn_compression"],
            collective_matmul=bool(knobs.get("collective_matmul")),
            bucket_mb=knobs["bucket_mb"],
            overlap_stages=knobs["overlap_stages"] or 0,
        )
    if cell.family == "ep":
        return Combo(
            "ep", cell.size, dcn=cell.dcn,
            moe_dispatch=knobs["dispatch"],
            moe_overlap=knobs["overlap"],
            dcn_compression=knobs["dcn_compression"],
        )
    if cell.family == "tp":
        return Combo(
            "tp", cell.size,
            collective_matmul=knobs["collective_matmul"],
        )
    if cell.family == "plan":
        return Combo(
            "plan", cell.size, plan=knobs["plan"],
            num_microbatches=knobs.get("num_microbatches") or 0,
        )
    if cell.family == "serve":
        # The paged decode step lowers per page_size; prefill_chunk
        # shapes the HOST loop only (no compiled-step difference), so
        # it rides the combo name for plan identity and the closed
        # form decides it. compute_dtype "f32" maps to the Combo
        # sentinel None (pre-ISSUE-16 names stay byte-stable).
        mode = knobs.get("compute_dtype") or "f32"
        return Combo(
            "serve", cell.size,
            page_size=knobs["page_size"],
            prefill_chunk=knobs["prefill_chunk"],
            compute_dtype=None if mode == "f32" else mode,
            # k > 0 lowers (and prices) the VERIFY step; 0 keeps
            # pre-ISSUE-18 combo names byte-stable.
            speculative_k=knobs.get("speculative_k") or 0,
        )
    raise ValueError(f"no combo mapping for family {cell.family!r}")


def _lower_and_price(combo, devices, constants):
    """(target, hlo, mesh_model, breakdown): ONE lowering feeds both
    the pricing and (for the winner) the lint pass — the two can never
    judge different programs."""
    from distributed_model_parallel_tpu.analysis.collectives import (
        MeshModel,
        classify,
    )
    from distributed_model_parallel_tpu.analysis.hlo import parse_hlo
    from distributed_model_parallel_tpu.analysis.lint import lower_combo
    from distributed_model_parallel_tpu.observability.cost import (
        fabrics_from_constants,
        predict_collectives,
    )

    target, hlo, mesh = lower_combo(combo, devices)
    mesh_model = MeshModel.from_mesh(mesh)
    collectives = classify(parse_hlo(hlo), mesh_model)
    breakdown = predict_collectives(
        collectives, mesh_model, target.dcn_axis,
        fabrics=fabrics_from_constants(constants)
        if constants is not None else None,
    )
    return target, hlo, mesh_model, breakdown


def search_cell(cell: Cell,
                constants: Optional[Dict[str, float]] = None,
                constants_source: str = "hand",
                finalists: Optional[int] = DEFAULT_FINALISTS,
                space_knobs: Optional[Sequence[dict]] = None,
                allow_cm: bool = True,
                devices=None,
                emit=None) -> dict:
    """Search one cell and return its validated plan dict.

    `finalists=None` (or 0) lowers EVERY candidate — the brute-force
    mode the argmin tests pin the pruned search against. `space_knobs`
    overrides the family's full space (tests; scoped searches).
    `constants` = a CONSTANTS-shaped dict (e.g.
    `cost.load_calibration(path)`) with `constants_source` naming where
    it came from."""
    from distributed_model_parallel_tpu.analysis.rules import (
        REGISTRY,
        LintContext,
        run_rules,
    )
    from distributed_model_parallel_tpu.observability.cost import (
        CONSTANTS,
    )
    from distributed_model_parallel_tpu.tuning.plan import validate_plan

    say = emit if emit is not None else (lambda s: None)
    if space_knobs is not None:
        cands = list(space_knobs)
    elif cell.family == "plan" and cell.model == "sched":
        # The scheduled cell (ISSUE 20) is a SCOPED comparison, not
        # the full factorization space: gpipe vs 1f1b vs int2 at fixed
        # pp x M, so the committed argmin pins the schedule tradeoff
        # itself (plan/S8 already pins the factorization).
        cands = tspace.scheduled_plan_candidates(cell.size)
    else:
        cands = list(
            tspace.candidates(cell.family, cell.dcn,
                              allow_cm=allow_cm, size=cell.size)
        )
    if not cands:
        raise ValueError(f"{cell.name}: empty candidate space")
    ici = cell.size // cell.dcn
    payload = cell_payload(cell)
    ranked = rank_candidates(
        cell.family, cands, payload, ici, cell.dcn, constants
    )
    k = len(ranked) if not finalists else min(finalists, len(ranked))
    say(f"[tuning] {cell.name}: {len(ranked)} candidate(s), "
        f"lowering the top {k}")

    lowered = []
    for closed_s, knobs in ranked[:k]:
        combo = candidate_combo(cell, knobs)
        target, hlo, mesh_model, breakdown = _lower_and_price(
            combo, devices, constants
        )
        row = breakdown.as_row()
        if cell.family == "serve":
            # Same compute-roofline fold as the costgate ledger
            # (`cost.add_serve_compute`) — the plan's gated number and
            # the ledger's price the same form.
            from distributed_model_parallel_tpu.observability.cost \
                import add_serve_compute

            row = add_serve_compute(row, combo)
        elif cell.family == "plan":
            # The plan twin (ISSUE 20): lowered comm is schedule-
            # symmetric by construction, so the bubble-stretched
            # compute term is what decides the sched cell's argmin —
            # same fold as the costgate ledger (`add_plan_compute`).
            from distributed_model_parallel_tpu.observability.cost \
                import add_plan_compute

            row = add_plan_compute(row, combo, constants)
        say(f"[tuning]   {combo.name}: closed-form "
            f"{closed_s * 1e3:.4f} ms -> lowered "
            f"{row['predicted_step_s'] * 1e3:.4f} ms/step")
        lowered.append(
            (row["predicted_step_s"],
             tspace.preference(cell.family, knobs),
             tspace.canonical_key(knobs),
             knobs, combo, row, target, hlo, mesh_model)
        )
    lowered.sort(key=lambda t: t[:3])
    (_, _, _, best_knobs, best_combo, best_row, target, hlo,
     mesh_model) = lowered[0]

    # Verify, don't trust: the full rule registry over the winner's
    # OWN lowering (already in hand — no recompile).
    ctx = LintContext.build(target, hlo, mesh_model)
    findings = run_rules(ctx)
    violations = [f for f in findings if not f.exempted]
    errors = [f for f in violations if f.severity == "error"]
    if errors:
        raise PlanLintError(
            f"{cell.name}: the searched argmin {best_combo.name} "
            "violates collective contract(s) "
            f"{', '.join(sorted({f.rule for f in errors}))} — plan "
            "NOT emitted (tools/hlolint has the catalog)"
        )
    say(f"[tuning] {cell.name}: argmin {best_combo.name} "
        f"({best_row['predicted_step_s'] * 1e3:.4f} ms/step), "
        f"lint clean over {len(REGISTRY)} rules")

    plan = make_plan(
        cell, best_knobs, best_combo.name, best_row,
        constants_source,
        dict(CONSTANTS) if constants is None else dict(constants),
        search={
            "candidates": len(ranked),
            "lowered": k,
            "finalist_combos": [
                t[4].name for t in sorted(lowered, key=lambda t: t[:3])
            ],
            "lint_violations": len(violations),
            "lint_rules": len(REGISTRY),
        },
    )
    return validate_plan(plan)


__all__ = [
    "DEFAULT_FINALISTS",
    "PlanLintError",
    "candidate_combo",
    "cell_payload",
    "closed_form_argmin",
    "closed_form_step_s",
    "moe_closed_form_s",
    "plan_closed_form_s",
    "rank_candidates",
    "reducer_closed_form_s",
    "search_cell",
    "serve_closed_form_s",
]
