"""The measured half of observability (INTERNALS.md §14): trace
attribution (per-phase table, nested-span union, the explicit
unattributed residual), measured-vs-predicted reconciliation keyed on
ledger combos, calibration (features pinned equal to the cost
engine's closed forms; synthetic round-trip recovers known constants
within 1%), and the obsreport golden pipeline (canned inputs ->
byte-stable report; --pregate exit-5 semantics). All jax-free."""

import gzip
import json
import os

import pytest

from distributed_model_parallel_tpu.observability import (
    attribution,
    calibrate,
    cost,
    report,
    trace,
)
from distributed_model_parallel_tpu.observability.metrics import (
    TRACE_EVENT_NAMES,
)

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")
GOLDEN_TRACE = os.path.join(GOLDEN_DIR, "obsreport_trace.json")
GOLDEN_REPORT = os.path.join(GOLDEN_DIR, "obsreport_report.txt")
GOLDEN_LEDGER = os.path.join(GOLDEN_DIR, "obsreport_ledger.json")
GOLDEN_CALIBRATION = os.path.join(
    GOLDEN_DIR, "obsreport_calibration.json"
)
GOLDEN_METRICS = os.path.join(GOLDEN_DIR, "obsreport_metrics.json")

#: The residual bound the golden trace is pinned under (acceptance:
#: "unattributed residual <= a stated bound on the golden trace") —
#: the canned timeline leaves 2 ms of un-spanned host bookkeeping per
#: training iteration, 8 of 93 ms total.
GOLDEN_RESIDUAL_BOUND = 0.10


class ManualClock:
    """Advances only when told — the golden timeline's clock."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> None:
        self.t += dt


def build_golden_obs_trace() -> trace.Tracer:
    """One synthetic run emitting EVERY span PR 12 wires (the trainer
    fetch/step/sync/checkpoint trio, the checkpoint writer pair, the
    serving engine + scheduler set) on a deterministic clock — the
    obsreport pre-gate's canned input (the generator that wrote
    tests/golden/obsreport_trace.json invoked this builder)."""
    clock = ManualClock()
    t = trace.Tracer(clock=clock, enabled=True)
    for _i in range(4):
        with t.span("fetch", want=1):
            clock.tick(0.010)
        with t.span("step", n=1):
            clock.tick(0.005)
        clock.tick(0.002)  # host bookkeeping NO span covers (residual)
        with t.span("sync"):
            clock.tick(0.003)
    with t.span("checkpoint_blocked", snapshot="last", epoch=0):
        with t.span("ckpt_snapshot", snapshot="last", save_id=1):
            clock.tick(0.004)
        clock.tick(0.001)
    t.complete(
        "ckpt_background_write", clock.t, clock.t + 0.006, tid=1,
    )
    with t.span("prefill_chunk", slot=0, start=0):
        clock.tick(0.002)
    with t.span("decode_step", active=2):
        clock.tick(0.002)
    with t.span("draft_round", active=2, k=2):
        clock.tick(0.002)
    with t.span("verify_step", active=2):
        clock.tick(0.002)
    t.counter("batch_occupancy", 2)
    tid = t.track_id("request 'r0'")
    t.complete("queued", 0.0, 0.004, tid=tid)
    t.complete("prefill", 0.004, 0.012, tid=tid, prompt_len=4)
    t.complete("decode", 0.012, 0.030, tid=tid, tokens=3)
    return t


def build_golden_ledger() -> dict:
    """The canned ledger the pre-gate reconciles against: one combo
    whose predicted step time equals the golden trace's measured
    per-step sync (3 ms), under the CURRENT constants."""
    return {
        "constants": {**cost.CONSTANTS, **cost.COMPUTE_CONSTANTS},
        "tolerance": 0.05,
        "combos": {"golden/S2": {
            "predicted_step_s": 0.003,
            "alpha_s": 0.0002,
            "beta_s": 0.0028,
            "n_collectives": 4,
        }},
    }


# -------------------------------------------------------- attribution


def test_golden_trace_file_matches_builder():
    """The committed canned trace IS the builder's output — the
    pre-gate input can never silently drift from what the tracer
    would record."""
    with open(GOLDEN_TRACE) as f:
        assert build_golden_obs_trace().to_chrome() == json.load(f)


def test_attribution_covers_every_pr12_span_with_bounded_residual():
    """The acceptance pin: the attribution table covers every phase
    span PR 12 emits and the unattributed residual on the golden
    trace stays under the stated bound."""
    attr = attribution.attribute(
        build_golden_obs_trace().to_chrome()
    )
    span_names = set(TRACE_EVENT_NAMES) - {"batch_occupancy"}  # counter
    assert {p.name for p in attr.phases} == span_names
    assert 0 < attr.residual_share <= GOLDEN_RESIDUAL_BOUND
    assert attr.residual_ms == pytest.approx(8.0, abs=1e-3)
    assert attr.wall_ms == pytest.approx(93.0, abs=1e-3)
    assert attr.main_tid == 0


def test_attribution_union_does_not_double_count_nested_spans():
    """ckpt_snapshot nests inside checkpoint_blocked on the main
    track; the covered union must count that interval once."""
    attr = attribution.attribute(
        build_golden_obs_trace().to_chrome()
    )
    assert attr.covered_ms == pytest.approx(85.0, abs=1e-3)
    snap = attr.phase("ckpt_snapshot")
    blocked = attr.phase("checkpoint_blocked")
    assert snap.total_ms == pytest.approx(4.0, abs=1e-3)
    assert blocked.total_ms == pytest.approx(5.0, abs=1e-3)


def test_reconcile_measured_vs_predicted_rows():
    attr = attribution.attribute(
        build_golden_obs_trace().to_chrome()
    )
    rows = attribution.reconcile(
        attr, build_golden_ledger(), ["golden/S2", "absent/S8"]
    )
    hit, miss = rows
    assert hit["combo"] == "golden/S2"
    assert hit["predicted_ms"] == pytest.approx(3.0)
    assert hit["measured_sync_ms_per_step"] == pytest.approx(3.0)
    assert hit["delta_pct"] == pytest.approx(0.0)
    assert hit["steps"] == 4
    assert miss["predicted_ms"] is None and miss["delta_pct"] is None


def test_load_trace_gz_and_profile_dir_scan(tmp_path):
    """xplane-style traces load through the same path: gzipped, bare
    event-list container, found by the --profile-dir scan."""
    events = build_golden_obs_trace().to_chrome()["traceEvents"]
    prof = tmp_path / "plugins" / "profile" / "2026_08_04"
    prof.mkdir(parents=True)
    path = prof / "host.trace.json.gz"
    with gzip.open(path, "wt") as f:
        json.dump(events, f)  # bare list, as xplane writes it
    hits = attribution.profile_dir_traces(str(tmp_path))
    assert hits == [str(path)]
    chrome = attribution.load_trace(hits[0])
    assert attribution.attribute(chrome).n_events > 0
    with pytest.raises(ValueError):
        bad = tmp_path / "not_a_trace.json"
        bad.write_text("{}")
        attribution.load_trace(str(bad))


# -------------------------------------------------------- calibration


def test_calibration_features_match_closed_forms():
    """Each feature decomposition, evaluated under the hand
    constants, reproduces cost.py's closed form to float precision —
    the fit target and the prose model can never drift."""
    c = cost.CONSTANTS
    cases = [
        (calibrate.ring_all_reduce_features(100e6, 64, n_ops=161),
         cost.ring_all_reduce_s(100e6, 64, n_ops=161)),
        (calibrate.two_level_features(100e6, 32, 2, n_buckets=4),
         cost.two_level_all_reduce_s(100e6, 32, 2, n_buckets=4)),
        (calibrate.two_level_features(100e6, 32, 2, n_buckets=4,
                                      wire="int8"),
         cost.two_level_all_reduce_s(100e6, 32, 2, n_buckets=4,
                                     wire="int8")),
        (calibrate.flat_all_to_all_features(12_500_000, 2, 32, 2),
         cost.flat_all_to_all_s(12_500_000, 2, 32, 2)),
        (calibrate.hierarchical_all_to_all_features(
            12_500_000, 2, 32, 2, wire="int8"),
         cost.hierarchical_all_to_all_s(12_500_000, 2, 32, 2,
                                        wire="int8")),
    ]
    for row, want in cases:
        assert calibrate.features_to_seconds(row, c) == pytest.approx(
            want, rel=1e-12
        ), row.name


def test_calibration_roundtrip_recovers_constants_within_1pct():
    """The acceptance pin: rows synthesized from KNOWN constants (plus
    a constant compute intercept) fit back to those constants within
    1%."""
    true = {
        "alpha_hop_s": 2e-6,
        "bw_ici_effective_bytes_per_s": 8e10,
        "alpha_dcn_hop_s": 2.5e-5,
        "bw_dcn_effective_bytes_per_s": 2e10,
    }
    intercept = 1e-4
    rows = []
    for s in (2, 4, 8, 16):
        for wire in ("none", "bf16", "int8"):
            for nbytes in (1.5e6, 24e6):
                r = calibrate.two_level_features(
                    nbytes, ici=max(s // 2, 1), dcn=2,
                    n_buckets=2, wire=wire,
                )
                r.measured_s = (
                    calibrate.features_to_seconds(r, true) + intercept
                )
                rows.append(r)
        r = calibrate.hierarchical_all_to_all_features(
            1e6, 4, ici=max(s // 2, 1), dcn=2,
        )
        r.measured_s = calibrate.features_to_seconds(r, true) + intercept
        rows.append(r)
    fit = calibrate.fit_constants(rows)
    for key, want in true.items():
        got = fit["constants"][key]
        assert abs(got - want) / want < 0.01, (key, got, want)
    assert fit["intercepts_s"]["rows"] == pytest.approx(
        intercept, rel=0.01
    )
    assert fit["residual_rms_s"] < 1e-9


def test_calibration_underdetermined_rows_refused():
    r = calibrate.two_level_features(1e6, 4, 2)
    r.measured_s = 1e-3
    with pytest.raises(ValueError, match="cannot identify"):
        calibrate.fit_constants([r])


def test_rows_from_committed_bench_and_fit():
    """The committed CPU-mesh bench artifact yields fit-able rows
    (the loop the committed experiments/calibration.json closed)."""
    with open(os.path.join(
        os.path.dirname(GOLDEN_DIR), "..", "BENCH_r06.json"
    )) as f:
        rows = calibrate.rows_from_bench(json.load(f))
    assert len(rows) >= 12
    sources = {r.source for r in rows}
    assert {"reducer", "moe"} <= sources
    fit = calibrate.fit_constants(rows)
    assert set(fit["constants"]) == set(cost.CONSTANTS)
    drift = calibrate.drift_report(fit["constants"])
    assert set(drift) == set(cost.CONSTANTS)


def test_committed_calibration_loads_and_reports_drift():
    """experiments/calibration.json is a valid, loadable artifact;
    cost.load_calibration validates it and drift_report prices it
    against the committed constants."""
    path = os.path.join(
        os.path.dirname(GOLDEN_DIR), "..", "experiments",
        "calibration.json",
    )
    constants = cost.load_calibration(path)
    assert set(constants) == set(cost.CONSTANTS)
    with open(path) as f:
        payload = json.load(f)
    assert payload["version"] == calibrate.CALIBRATION_VERSION
    assert set(payload["drift_pct"]) == set(cost.CONSTANTS)


def test_load_calibration_refuses_partial_constants(tmp_path):
    path = tmp_path / "cal.json"
    path.write_text(json.dumps({
        "version": calibrate.CALIBRATION_VERSION,
        "constants": {"alpha_hop_s": 1e-6},
    }))
    with pytest.raises(ValueError, match="missing constants"):
        cost.load_calibration(str(path))
    path.write_text(json.dumps({"not": "a calibration"}))
    with pytest.raises(ValueError, match="calibration"):
        cost.load_calibration(str(path))


def test_costgate_calibration_flag_reports_never_gates(capsys):
    """`tools/costgate --calibration` prints the fitted-vs-committed
    drift and carries it in the summary JSON without affecting the
    exit code; an unreadable file is a usage error (2)."""
    from distributed_model_parallel_tpu.observability import costgate

    cal = os.path.join(
        os.path.dirname(GOLDEN_DIR), "..", "experiments",
        "calibration.json",
    )
    rc = costgate.main([
        "--filter", "cm_ag/S2", "--calibration", cal,
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "calibration drift (reported, not gated)" in out
    summary = json.loads(out.strip().splitlines()[-1])
    assert set(summary["costgate"]["calibration_drift_pct"]) == set(
        cost.CONSTANTS
    )
    assert costgate.main([
        "--filter", "cm_ag/S2",
        "--calibration", "/no/such/calibration.json",
    ]) == 2


# ---------------------------------------------------------- obsreport


def _golden_inputs():
    with open(GOLDEN_METRICS) as f:
        metrics_json = json.load(f)
    with open(GOLDEN_CALIBRATION) as f:
        calibration = json.load(f)
    return metrics_json, calibration


def test_render_report_golden_bytes():
    """The pre-gate's contract, run in-process: canned inputs render
    to the committed golden report byte-for-byte."""
    metrics_json, calibration = _golden_inputs()
    got = report.render_report(
        build_golden_obs_trace().to_chrome(),
        metrics=metrics_json,
        ledger=build_golden_ledger(),
        combos=report.PREGATE_COMBOS,
        calibration=calibration,
    )
    with open(GOLDEN_REPORT) as f:
        assert got == f.read()
    # Every PR 12 phase span appears in the rendered table.
    for name in set(TRACE_EVENT_NAMES) - {"batch_occupancy"}:
        assert f"\n{name}" in got
    assert "unattributed residual" in got
    assert "golden/S2" in got


def test_golden_ledger_file_matches_builder():
    with open(GOLDEN_LEDGER) as f:
        assert build_golden_ledger() == json.load(f)


def test_report_json_twin():
    metrics_json, calibration = _golden_inputs()
    out = report.report_json(
        build_golden_obs_trace().to_chrome(),
        metrics=metrics_json,
        ledger=build_golden_ledger(),
        combos=["golden/S2"],
        calibration=calibration,
    )
    assert out["attribution"]["residual_ms"] == pytest.approx(
        8.0, abs=1e-3
    )
    assert out["measured_vs_predicted"][0]["delta_pct"] == 0.0
    assert set(out["calibration_drift"])


def test_obsreport_pregate_ok(capsys):
    assert report.main(["--pregate"]) == 0
    out = capsys.readouterr().out
    assert '"pregate": "ok"' in out


def test_obsreport_pregate_mismatch_exits_5(tmp_path, monkeypatch,
                                            capsys):
    bad = tmp_path / "golden.txt"
    bad.write_text("definitely not the report\n")
    monkeypatch.setitem(
        report.PREGATE_INPUTS, "golden", str(bad)
    )
    assert report.main(["--pregate"]) == report.EXIT_GOLDEN_MISMATCH
    out = capsys.readouterr().out
    assert "golden mismatch at line 1" in out


def test_obsreport_cli_end_to_end(tmp_path, capsys):
    """The non-pregate surface: --trace/--metrics/--ledger/--combo/
    --calibration render the same report; --json emits the twin."""
    tpath = tmp_path / "t.json"
    build_golden_obs_trace().export(str(tpath))
    lpath = tmp_path / "l.json"
    lpath.write_text(json.dumps(build_golden_ledger()))
    rc = report.main([
        "--trace", str(tpath),
        "--metrics", GOLDEN_METRICS,
        "--ledger", str(lpath), "--combo", "golden/S2",
        "--calibration", GOLDEN_CALIBRATION,
        "--out", str(tmp_path / "rep.txt"),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    with open(GOLDEN_REPORT) as f:
        assert out == f.read()
    with open(tmp_path / "rep.txt") as f:
        assert f.read() == out
    assert report.main([
        "--trace", str(tpath), "--json",
    ]) == 0
    assert report.main([]) == 2  # no trace source
