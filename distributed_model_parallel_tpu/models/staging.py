"""Shared pipeline-stage partitioning for stem/blocks/head model families.

Generalizes the reference's hard-coded ws=4 rank split
(`code/distributed_training/model_parallel.py:102-104,129,143-144`:
rank 0 = stem+blocks[0:3], middle rank r = blocks[6r-3:6r+3], last =
blocks[15:]+head) to any block count and stage count. Every model family
(MobileNetV2, ResNet, ...) shares one cut-point algorithm and one stage /
pytree assembly convention, so a single-device checkpoint always loads
into the matching pipeline run and vice versa.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax

from distributed_model_parallel_tpu.models import layers as L


def chunk_owner(logical: int, num_stages: int) -> int:
    """Physical stage that owns logical pipeline chunk `logical` under
    the interleaved virtual-pipeline placement (Megatron SC'21): chunks
    are dealt round-robin, so device s owns logicals {s, s+S, s+2S, ...}
    — NON-contiguous slices of the model, which is what lets a
    microbatch revisit every device V times and divide the pipeline
    bubble by V. With V=1 this is the identity (chunk i on device i)."""
    return logical % num_stages


def row_of_logical(logical: int, num_stages: int,
                   virtual_stages: int) -> int:
    """Storage row of logical chunk `logical` in the stage-local packed
    (S·V, maxP) parameter array. Rows are DEVICE-MAJOR — row s·V + v
    holds device s's v-th chunk (logical v·S + s) — so sharding the
    leading axis P('stage') lands each device's V chunks on it in local
    rows 0..V-1, matching the in-step chunk index."""
    s = logical % num_stages
    v = logical // num_stages
    return s * virtual_stages + v


def logical_of_row(row: int, num_stages: int, virtual_stages: int) -> int:
    """Inverse of `row_of_logical`."""
    s = row // virtual_stages
    v = row % virtual_stages
    return v * num_stages + s


def split_points(num_stages: int, boundaries: Sequence[int] | None,
                 n_blocks: int) -> List[int]:
    """Cut points [0, ..., n_blocks] delimiting each stage's block range.

    Default: blocks distributed as evenly as possible (earlier stages get
    the remainder). Pass `boundaries` (len num_stages-1) to override —
    e.g. [3, 9, 15] reproduces the reference's ws=4 MobileNetV2 split.
    `num_stages` counts CHUNKS: an interleaved virtual pipeline over S
    devices with V chunks each passes S·V here (the assembly convention
    is unchanged — stem on chunk 0, head on the last chunk; the ENGINE
    deals chunks round-robin to devices, `chunk_owner`).
    """
    if num_stages < 1 or num_stages > n_blocks:
        raise ValueError(f"num_stages must be in [1,{n_blocks}]")
    if boundaries is None:
        base, rem = divmod(n_blocks, num_stages)
        counts = [base + (1 if i < rem else 0) for i in range(num_stages)]
        boundaries = []
        acc = 0
        for c in counts[:-1]:
            acc += c
            boundaries.append(acc)
    if len(boundaries) != num_stages - 1:
        raise ValueError("need num_stages-1 boundaries")
    return [0, *boundaries, n_blocks]


def assemble_stages(blocks: Sequence[L.Layer], stem: L.Layer, head: L.Layer,
                    cuts: Sequence[int]) -> List[L.Layer]:
    """Stage i = blocks[cuts[i]:cuts[i+1]], with the stem prepended on
    stage 0 and the head appended on the last (the reference's
    header/medium/last roles, `model_parallel.py:99-157`)."""
    num_stages = len(cuts) - 1
    stages = []
    for i in range(num_stages):
        parts = list(blocks[cuts[i]:cuts[i + 1]])
        if i == 0:
            parts.insert(0, stem)
        if i == num_stages - 1:
            parts.append(head)
        stages.append(L.sequential(*parts))
    return stages


def stage_io_avals(stages: Sequence[L.Layer], param_avals: Sequence[Any],
                   state_avals: Sequence[Any], x_aval: Any,
                   ctx: L.Context) -> List[Tuple[Any, Any]]:
    """(input_aval, output_aval) per stage from an abstract trace — the
    static replacement for the reference's runtime dim/size handshake
    (`distributed_layers.py:40-47`), and the metadata every pipeline
    schedule sizes its buffers from: the GPipe wire buffer is the max
    output size, and the 1F1B activation ring holds per-stage *inputs*,
    so ring sizing needs the input avals too (stage 0's input is the
    image microbatch, which never rides the wire). Stage I/O may be any
    pytree of arrays (e.g. BERT's (hidden, mask) pair)."""
    avals = []
    aval = x_aval
    for i, stage in enumerate(stages):
        out = jax.eval_shape(
            lambda p, s, x, stage=stage: stage.apply(p, s, x, ctx)[0],
            param_avals[i], state_avals[i], aval,
        )
        avals.append((aval, out))
        aval = out
    return avals


def partition_tree(tree: Any, cuts: Sequence[int]) -> List[dict]:
    """Map a full-model `{stem, blocks:{'0'..}, head}` params/state pytree
    onto the `assemble_stages` structure (sequential-keyed stage trees in
    the same part order)."""
    num_stages = len(cuts) - 1
    out = []
    for i in range(num_stages):
        parts = []
        if i == 0:
            parts.append(tree["stem"])
        parts.extend(tree["blocks"][str(b)] for b in range(cuts[i], cuts[i + 1]))
        if i == num_stages - 1:
            parts.append(tree["head"])
        out.append({str(j): p for j, p in enumerate(parts)})
    return out
