"""Observability: the span tracer (`trace.py` — host-side runtime
timeline, Chrome trace export), the metrics registry (`metrics.py` —
counters/gauges/histograms with streaming quantiles, Prometheus +
JSON export, the ONE percentile rule), the static cost engine
(`cost.py` — shared alpha-beta constants, closed-form composition
formulas, and the per-combo predictor `tools/costgate` gates against
`experiments/cost_ledger.json`), and the measured half that closes
the loop: trace attribution (`attribution.py`), constant calibration
from measured rows (`calibrate.py`), and the unified run report
(`report.py`, `tools/obsreport`). INTERNALS.md §13–§14."""

from distributed_model_parallel_tpu.observability.metrics import (  # noqa: F401,E501
    MetricsRegistry,
    exact_quantile,
    get_metrics,
    set_metrics,
)
from distributed_model_parallel_tpu.observability.trace import (  # noqa: F401
    Tracer,
    disable,
    enable,
    get_tracer,
    set_tracer,
)

__all__ = [
    "MetricsRegistry",
    "Tracer",
    "disable",
    "enable",
    "exact_quantile",
    "get_metrics",
    "get_tracer",
    "set_metrics",
    "set_tracer",
]
