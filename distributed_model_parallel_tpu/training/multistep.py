"""Multi-step compiled dispatch — k optimizer steps per device program.

The reference's hot loop (`utils.py:42-72`) is one CUDA launch sequence
per Python iteration; CUDA's stream queue hides the per-step launch
latency. On a JAX host whose accelerator sits behind a network relay the
analogous per-step `jit` dispatch is NOT hidden: RESULTS §1c measured
0.145-0.181 s/batch end-to-end against an AOT step rate of 0.0197 s —
a 7-9x gap that is pure dispatch round-trip, not compute.

`compile_multi_step(engine, k)` removes it structurally: ONE jitted
program stacks k already-sharded batches and runs k sequential train
steps under `lax.scan`, so the per-step trajectory (step counter,
dropout folding, optimizer updates) matches k separate
`engine.train_step` calls to numerical tolerance (same math; XLA may
fuse across step boundaries differently — pinned at rtol 1e-5 by
tests/test_trainer.py) while the host pays one dispatch per k steps. Batches still transfer
asynchronously one by one (`shard_batch`), so input staging overlaps
the previous group's compute.

Works with any engine exposing the uniform protocol
`train_step(state, x, y, lr) -> (state, metrics)`: the engine's own
jitted step (jit- or shard_map-built) is traced inline into the scan
body, keeping its sharding annotations as constraints. That includes
steps that are themselves scans — PipelineEngine's tick programs (both
the gpipe fill-drain and the hand-scheduled 1f1b forward+backward) nest
as inner scans, pinned by tests/test_pipeline_schedule.py.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def compile_multi_step(engine: Any, k: int) -> Callable:
    """Build `fn(state, batches, lr) -> (state, summed_metrics)` running
    `k` train steps in one compiled program.

    `batches` is a tuple of `k` batch tuples as returned by
    `engine.shard_batch` (already device-placed). The returned metrics
    dict holds the SUM over the k steps of the engine's per-step metric
    sums — the same value accumulating k per-step results would give.

    k=1 is a passthrough: a one-step scan whose state/metrics match a
    single `engine.train_step` call (pinned in tests/test_multistep.py)
    — callers can treat every dispatch uniformly instead of special-
    casing the last short group of an epoch.
    """
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")

    def k_steps(state, batches: Tuple, lr):
        # Leaf-wise stack of the k batch tuples -> scan operands with a
        # leading step axis. Device-side: the k inputs were placed by
        # shard_batch; the stack is a cheap on-device concatenation.
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *batches
        )

        def body(s, batch):
            s2, m = engine.train_step(s, *batch, lr)
            return s2, m

        state, per_step = lax.scan(body, state, stacked)
        return state, jax.tree_util.tree_map(
            lambda x: jnp.sum(x, axis=0), per_step
        )

    return jax.jit(k_steps, donate_argnums=(0,))


def compile_multi_eval(engine: Any, k: int) -> Callable:
    """Eval twin of `compile_multi_step`: `fn(state, batches) ->
    summed_metrics` evaluating k batches in one compiled program
    (state is read-only — no carry, a plain scan over the stack).
    k=1 is a passthrough, like `compile_multi_step`."""
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")

    def k_evals(state, batches: Tuple):
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *batches
        )

        def body(carry, batch):
            return carry, engine.eval_step(state, *batch)

        _, per_step = lax.scan(body, 0, stacked)
        return jax.tree_util.tree_map(
            lambda x: jnp.sum(x, axis=0), per_step
        )

    return jax.jit(k_evals)


def group_batches(iterator, k: int):
    """Pull up to `k` items from `iterator`; a short list means the
    iterator was exhausted (the caller's per-step fallback path)."""
    group = []
    while len(group) < k:
        try:
            group.append(next(iterator))
        except StopIteration:
            break
    return group


__all__ = ["compile_multi_eval", "compile_multi_step", "group_batches"]
