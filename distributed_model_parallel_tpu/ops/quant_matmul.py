"""Quantized matmul primitives for the decode hot floor — int8 with
absmax scales, plus the bf16 cast path, behind one seam.

PR 11 compressed the *wire* (`ops/wire_codec.py`); this module applies
the same absmax-scale machinery to the *compute*: the serving engine's
per-token latency floor is its f32 projection GEMMs, which the MXU runs
at 2-4x the throughput in bf16/int8 (Wang et al. ASPLOS'23 is the
decomposition anchor; Tang et al. 1-bit Adam the absmax-scale one —
both PAPERS.md).

Scale layout (the int8 contract):

* weights  — per-OUTPUT-CHANNEL absmax: `w (K, N)` quantizes against
  `wscale (N,) = max(|w|, axis=0) / 127` (floored at `ABSMAX_FLOOR`,
  the wire codec's denormal guard). Static per weight, so a real
  deployment quantizes once; here it folds into the traced step.
* activations — per-TOKEN dynamic absmax: `x (M, K)` quantizes against
  `xscale (M, 1) = max(|x|, axis=-1) / 127`, recomputed every call
  (decode activations change every token; a static scale would clip).
* accumulate in int32 on the MXU (`preferred_element_type`), dequantize
  on exit: `y = acc_i32 * xscale * wscale` in f32 — int8 values are
  never summed in int8, mirroring the wire codec's
  decode-then-accumulate rule. The elementwise bound per operand is
  absmax/254, same as the wire's.

Dual path, same shape as `pallas_attention.flash_attention`:

  mode   | TPU                      | CPU / other backends
  -------|--------------------------|------------------------------
  int8   | Pallas kernel (quantize  | dtype-pinned `lax.dot_general`
         | + s8xs8 MXU dot in VMEM) | (s8 x s8 -> i32), same math
  bf16   | XLA (the MXU's native    | XLA bf16 dot — same cast path
         | bf16 path; no kernel     | everywhere
         | needed)                  |
  f32    | plain `x @ w`            | plain `x @ w`

The availability probe is cached ONCE at module import (`_VMEM`), never
raised at call time: `path=None` auto-selects the Pallas kernel only on
a TPU backend with a healthy pltpu import, and the `lax.dot_general`
fallback otherwise — so a CPU trace of an opted-in decode step carries
real int8 `dot_general` equations, which is exactly what the hlolint
rule `decode-quantized-matmul` pins from the jaxpr (compiled CPU HLO
normalizes dtypes, so the contract lives at trace level, like
`bf16-ring-upcast`). Tests drive the kernel explicitly with
`path="pallas"` (interpret mode off-TPU).

`QuantMatmul` is the `Context.matmul` policy the serving engine threads
for non-ring int8 decode; the ring layouts inject `quant_dot(mode)`
into the collective-matmul fold bodies instead
(`ops/collective_matmul.py`) so the ppermute chain stays byte-identical
and only the per-chunk GEMM dtype changes.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover - exotic builds
    pltpu = None
    _VMEM = None

from distributed_model_parallel_tpu.ops.wire_codec import ABSMAX_FLOOR

# The engine/CLI surface (`compute_dtype` on ServingEngine,
# `--compute-dtype` on cli/serve.py). "f32" is the identity.
COMPUTE_DTYPES = ("f32", "bf16", "int8")


def check_compute_dtype(name: str) -> str:
    if name not in COMPUTE_DTYPES:
        raise ValueError(
            f"compute_dtype must be one of {COMPUTE_DTYPES}, got "
            f"{name!r}"
        )
    return name


def normalize_compute_dtype(value) -> str:
    """Engine-surface normalization: the ServingEngine historically
    accepted a dtype object (`compute_dtype=jnp.bfloat16`); the knob
    surface is the string triple. Both map onto COMPUTE_DTYPES."""
    if value is None:
        return "f32"
    if isinstance(value, str):
        return check_compute_dtype(value)
    try:
        dt = jnp.dtype(value)
    except TypeError:
        raise ValueError(
            f"compute_dtype must be one of {COMPUTE_DTYPES} or a "
            f"dtype, got {value!r}"
        )
    if dt == jnp.dtype(jnp.bfloat16):
        return "bf16"
    if dt == jnp.dtype(jnp.float32):
        return "f32"
    raise ValueError(
        f"compute_dtype dtype {dt} unsupported; use one of "
        f"{COMPUTE_DTYPES}"
    )


# ------------------------------------------------------------ quantize


def quantize_weight(w):
    """w (K, N) -> (wq int8 (K, N), wscale f32 (N,)): per-output-channel
    absmax scales (module docstring). Floored like the wire codec so an
    all-zero column still decodes to exact zeros."""
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=0)
    scale = jnp.maximum(absmax, ABSMAX_FLOOR) / 127.0
    q = jnp.clip(jnp.round(wf / scale[None, :]), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def quantize_rows(x):
    """x (M, K) -> (q int8 (M, K), xscale f32 (M, 1)): per-token dynamic
    absmax scales."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, ABSMAX_FLOOR) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127.0, 127.0)
    return q.astype(jnp.int8), scale


# ----------------------------------------------------------- int8 paths


def _int8_matmul_xla(x2, w):
    """The dtype-pinned fallback: quantize, one s8 x s8 -> i32
    `dot_general`, dequantize. The int8 operand dtypes in this trace are
    the hlolint `decode-quantized-matmul` contract."""
    q, xscale = quantize_rows(x2)
    wq, wscale = quantize_weight(w)
    acc = lax.dot_general(
        q, wq,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * xscale * wscale[None, :]


def _int8_kernel(x_ref, wq_ref, ws_ref, o_ref):
    """One (bm, K) row block: dynamic row quantization in VMEM, the
    s8 x s8 MXU dot accumulating in i32, dequantize on exit. The weight
    arrives pre-quantized (its scale is static; recomputing it per grid
    step would waste VPU work)."""
    x = x_ref[...].astype(jnp.float32)                  # (bm, K)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, ABSMAX_FLOOR) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127.0, 127.0).astype(jnp.int8)
    acc = lax.dot_general(                              # MXU, i32 acc
        q, wq_ref[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    o_ref[...] = acc.astype(jnp.float32) * scale * ws_ref[...]


def _pick_rows(m: int, want: int = 128) -> int:
    """Largest multiple-of-8 divisor of m that is <= want, else m itself
    (a whole-array block is always a legal Mosaic tiling)."""
    b = min(want, m)
    while b >= 8:
        if m % b == 0 and b % 8 == 0:
            return b
        b -= 1
    return m


def _int8_matmul_pallas(x2, w, interpret):
    m, k = x2.shape
    n = w.shape[-1]
    wq, wscale = quantize_weight(w)  # static per weight; stays in XLA
    bm = _pick_rows(m)
    grid = (m // bm,) if m % bm == 0 else (1,)
    if grid == (1,):
        bm = m
    return pl.pallas_call(
        _int8_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret,
    )(x2.astype(jnp.float32), wq, wscale[None, :])


# --------------------------------------------------------------- public


def quant_matmul(
    x,
    w,
    mode: str = "int8",
    *,
    path: Optional[str] = None,
    interpret: Optional[bool] = None,
):
    """x (..., K) @ w (K, N) in `mode` arithmetic.

    mode "f32" is the identity dot; "bf16" casts both operands and
    returns bf16 (the MXU's native half path — downstream layers follow
    x.dtype, the mixed-precision convention); "int8" quantizes per the
    module contract and returns f32.

    `path` selects the int8 implementation: None auto-picks the Pallas
    kernel on TPU and the `lax.dot_general` fallback elsewhere (module
    docstring); "pallas" / "xla" force one (tests drive the kernel in
    interpret mode off-TPU). `interpret=None` auto-selects like
    `flash_attention`."""
    check_compute_dtype(mode)
    if mode == "f32":
        return x @ w
    if mode == "bf16":
        return x.astype(jnp.bfloat16) @ w.astype(jnp.bfloat16)
    if path is None:
        path = (
            "pallas"
            if _VMEM is not None and jax.default_backend() == "tpu"
            else "xla"
        )
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    if path == "pallas":
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        y = _int8_matmul_pallas(x2, w, interpret)
    elif path == "xla":
        y = _int8_matmul_xla(x2, w)
    else:
        raise ValueError(
            f"path must be None, 'pallas' or 'xla', got {path!r}"
        )
    return y.reshape(*lead, w.shape[-1])


def quant_dot(mode: Optional[str]) -> Optional[Callable]:
    """The chunk-GEMM to inject into a collective-matmul ring fold
    (`ops/collective_matmul.py`): None for f32 (the fold keeps its
    plain `chunk @ w`, byte-identical lowering), else a 2-arg dot in
    `mode` arithmetic. Always the XLA-auto path — inside a shard_map
    fold the chunk dots are the lint rule's jaxpr anchor on CPU, and
    auto still picks the kernel on TPU."""
    if mode is None or mode == "f32":
        return None
    check_compute_dtype(mode)
    return lambda a, b: quant_matmul(a, b, mode)


@dataclasses.dataclass(frozen=True)
class QuantMatmul:
    """`Context.matmul` policy for NON-ring quantized decode
    (replicated / tp-without-rings layouts): every opted-in projection
    — column and row alike — runs through `quant_matmul`; under the tp
    layout GSPMD partitions the int8 dot and all-reduces the
    DEQUANTIZED f32 partials (decode-then-accumulate holds across
    shards: each shard's partial product is dequantized against its own
    weight-block scales before the sum)."""

    mode: str = "int8"
    attn: bool = True
    ffn: bool = True

    def _mm(self, h, w, b):
        y = quant_matmul(h, w, self.mode)
        return y + b.astype(y.dtype)

    def column(self, h, w, b):
        return self._mm(h, w, b)

    def row(self, h, w, b):
        return self._mm(h, w, b)


__all__ = [
    "COMPUTE_DTYPES",
    "QuantMatmul",
    "check_compute_dtype",
    "normalize_compute_dtype",
    "quant_dot",
    "quant_matmul",
    "quantize_rows",
    "quantize_weight",
]
