"""Classify every collective in a parsed HLO module against the mesh.

The piece the test-local pins never had: each collective op is mapped
back through the mesh's device array to the AXES it actually crosses,
so a rule can say "no grad-sized all-reduce over 'dcn'" instead of
counting ops and hoping. Group/pair ids in compiled HLO are global
device ids when `use_global_device_ids=true` (every lowering this repo
produces); the mesh model resolves an id to its mesh coordinates and a
collective's crossed axes are the axes on which any group's (or
permute pair's) members differ.

Ring-vs-monolithic is structural: `collective-permute` hops are ring
traffic (the chunked `ppermute` decompositions of
`ops/collective_matmul.py` / `ops/grad_reduction.py`);
all-gather / reduce-scatter / all-reduce / all-to-all are the
monolithic fused forms the rings exist to replace.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from distributed_model_parallel_tpu.analysis.hlo import (
    HloModule,
    Instruction,
)


@dataclasses.dataclass(frozen=True)
class MeshModel:
    """The linter's view of a device mesh: axis names/sizes and the
    device-id -> coordinates map. Built from a `jax.sharding.Mesh` via
    `from_mesh` (the only jax-touching entry point) or directly from a
    coordinate table (golden tests)."""

    axis_names: Tuple[str, ...]
    shape: Tuple[int, ...]
    coords: Dict[int, Tuple[int, ...]]  # device id -> mesh coordinates

    @classmethod
    def from_mesh(cls, mesh) -> "MeshModel":
        coords = {}
        import numpy as np

        for idx, dev in np.ndenumerate(mesh.devices):
            coords[int(dev.id)] = tuple(int(i) for i in idx)
        return cls(
            axis_names=tuple(mesh.axis_names),
            shape=tuple(int(mesh.shape[a]) for a in mesh.axis_names),
            coords=coords,
        )

    def size(self, axis: str) -> int:
        return self.shape[self.axis_names.index(axis)]

    def axes_of_ids(self, ids: Sequence[int]) -> Optional[FrozenSet[str]]:
        """Axes on which the given device ids differ — the fabric a
        group of that membership crosses. None when an id is unknown
        (the conservative 'cannot classify' answer)."""
        cs = []
        for i in ids:
            c = self.coords.get(int(i))
            if c is None:
                return None
            cs.append(c)
        crossed = set()
        first = cs[0]
        for c in cs[1:]:
            for d, (a, b) in enumerate(zip(first, c)):
                if a != b:
                    crossed.add(self.axis_names[d])
        return frozenset(crossed)


@dataclasses.dataclass(frozen=True)
class ClassifiedCollective:
    """One collective op with its contract-relevant facts attached."""

    instruction: Instruction
    kind: str  # base op: all-reduce / collective-permute / ...
    axes: Optional[FrozenSet[str]]  # mesh axes crossed; None = unknown
    payload_bytes: int
    is_ring_hop: bool  # collective-permute (chunked-ring traffic)

    @property
    def name(self) -> str:
        return self.instruction.name

    @property
    def is_scalar(self) -> bool:
        return self.instruction.is_scalar

    @property
    def dtypes(self) -> Tuple[str, ...]:
        return tuple(b.dtype for b in self.instruction.buffers)

    @property
    def shapes(self) -> Tuple[Tuple[int, ...], ...]:
        return tuple(b.shape for b in self.instruction.buffers)

    def crosses(self, axis: Optional[str]) -> bool:
        """True when this collective's membership spans `axis`. Unknown
        membership (axes=None) conservatively answers True — a rule
        forbidding traffic on a fabric must not be dodged by an
        unparseable group list."""
        if axis is None:
            return False
        if self.axes is None:
            return True
        return axis in self.axes


def classify_instruction(
    instr: Instruction, mesh: MeshModel
) -> ClassifiedCollective:
    base = instr.base_op
    axes: Optional[FrozenSet[str]] = None
    if instr.source_target_pairs is not None:
        crossed: set = set()
        ok = True
        for s, t in instr.source_target_pairs:
            a = mesh.axes_of_ids((s, t))
            if a is None:
                ok = False
                break
            crossed |= a
        axes = frozenset(crossed) if ok else None
    elif instr.replica_groups == ():
        # Empty replica_groups is XLA's printed form for ONE group of
        # ALL devices — a world-spanning collective. Classifying it as
        # crossing nothing would hide exactly the traffic the fabric
        # rules forbid, so it spans every non-trivial mesh axis.
        axes = frozenset(
            a for a, s in zip(mesh.axis_names, mesh.shape) if s > 1
        )
    elif instr.replica_groups is not None:
        crossed = set()
        ok = True
        for g in instr.replica_groups:
            if len(g) < 2:
                continue
            a = mesh.axes_of_ids(g)
            if a is None:
                ok = False
                break
            crossed |= a
        axes = frozenset(crossed) if ok else None
    # Payload: the async tuple form carries context buffers alongside
    # the data; count only real array buffers (all of them — context
    # u32/token buffers are tiny and harmless to include).
    payload = instr.nbytes
    return ClassifiedCollective(
        instruction=instr,
        kind=base,
        axes=axes,
        payload_bytes=payload,
        is_ring_hop=(base == "collective-permute"),
    )


def classify(module: HloModule, mesh: MeshModel
             ) -> List[ClassifiedCollective]:
    """Every collective in the module, classified. Async `-start`/`-done`
    pairs are counted once (on the start)."""
    return [classify_instruction(i, mesh) for i in module.collectives()]


def ring_permutes_over(
    collectives: Sequence[ClassifiedCollective], axis: str
) -> List[ClassifiedCollective]:
    """The ring traffic on one fabric: collective-permutes whose pairs
    stay WITHIN `axis` (axes == {axis}) — a permute that also crosses
    another axis belongs to a different wire (e.g. the pipeline's
    stage hops)."""
    return [
        c for c in collectives
        if c.is_ring_hop and c.axes is not None and c.axes == {axis}
    ]


def monolithic_over(
    collectives: Sequence[ClassifiedCollective], axis: str,
    kinds: Tuple[str, ...] = ("all-gather", "reduce-scatter"),
) -> List[ClassifiedCollective]:
    """Monolithic (fused) collectives of the given kinds crossing
    `axis` — what a latency-hiding ring must have replaced."""
    return [
        c for c in collectives if c.kind in kinds and c.crosses(axis)
    ]


def nonscalar_all_reduces(
    collectives: Sequence[ClassifiedCollective],
) -> List[ClassifiedCollective]:
    return [
        c for c in collectives
        if c.kind == "all-reduce" and not c.is_scalar
    ]


__all__ = [
    "ClassifiedCollective",
    "MeshModel",
    "classify",
    "classify_instruction",
    "monolithic_over",
    "nonscalar_all_reduces",
    "ring_permutes_over",
]
