"""Latency-hiding collective matmul (`ops/collective_matmul.py`) parity
tests on the 8-virtual-device CPU mesh.

The chunked ppermute rings must be semantically invisible: `ag_matmul` /
`matmul_rs` equal the monolithic all_gather/psum_scatter baselines
(values AND custom-vjp gradients), and an engine constructed with
`collective_matmul=True` must train bit-for-bit-close (rtol 1e-5) to its
declarative twin — grads, metrics, and the multi-step trajectory — for
every ring size the 8-device mesh can host: S in {2, 4, 8} (plus the
odd-size single-ring path at S=3 for the raw ops).

The structural side (S-1 collective-permutes, no monolithic
all-gather/reduce-scatter on opted-in matmuls) is pinned from lowered
HLO in tests/test_collectives_hlo.py.
"""

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_model_parallel_tpu.models.bert import (
    BertConfig,
    bert_for_classification,
)
from distributed_model_parallel_tpu.ops.collective_matmul import (
    ag_matmul,
    matmul_rs,
    naive_ag_matmul,
    naive_matmul_rs,
)
from distributed_model_parallel_tpu.runtime.compat import shard_map
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.optim import SGD

from jax.sharding import Mesh, PartitionSpec as P

# Heads divisible by every tested ring size (S=8 needs 8 heads); seq and
# FFN widths divisible likewise.
TINY = BertConfig(
    vocab_size=61,
    hidden_size=32,
    num_layers=2,
    num_heads=8,
    intermediate_size=64,
    max_position=16,
    dropout_rate=0.0,  # deterministic parity
)
BATCH, SEQ, CLASSES = 8, 8, 4


def _mesh_1d(size):
    return Mesh(np.array(jax.devices()[:size]), ("m",))


# ------------------------------------------------------------- raw ops


@pytest.mark.parametrize("size", [2, 3, 4, 8])
def test_ag_matmul_matches_monolithic_gather(size):
    """Chunked == monolithic, bidirectional (even S) and single-ring
    (odd S) alike; scale-realistic values keep fp32 reassociation noise
    well under the engine parity bar."""
    mesh = _mesh_1d(size)
    rng = np.random.RandomState(0)
    x = jnp.asarray(0.1 * rng.randn(2, 4 * size, 16), jnp.float32)
    w = jnp.asarray(0.1 * rng.randn(16, 8 * size), jnp.float32)
    specs = dict(
        in_specs=(P(None, "m", None), P(None, "m")),
        out_specs=P(None, None, "m"),
        check_vma=False,
    )
    ring = jax.jit(shard_map(
        partial(ag_matmul, axis_name="m"), mesh=mesh, **specs
    ))
    mono = jax.jit(shard_map(
        partial(naive_ag_matmul, axis_name="m"), mesh=mesh, **specs
    ))
    np.testing.assert_allclose(ring(x, w), mono(x, w), rtol=1e-5,
                               atol=1e-7)
    np.testing.assert_allclose(ring(x, w), x @ w, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("size", [2, 3, 4, 8])
def test_matmul_rs_matches_monolithic_scatter(size):
    mesh = _mesh_1d(size)
    rng = np.random.RandomState(1)
    x = jnp.asarray(0.1 * rng.randn(2, 4 * size, 8 * size), jnp.float32)
    w = jnp.asarray(0.1 * rng.randn(8 * size, 16), jnp.float32)
    specs = dict(
        in_specs=(P(None, None, "m"), P("m", None)),
        out_specs=P(None, "m", None),
        check_vma=False,
    )
    ring = jax.jit(shard_map(
        partial(matmul_rs, axis_name="m"), mesh=mesh, **specs
    ))
    mono = jax.jit(shard_map(
        partial(naive_matmul_rs, axis_name="m"), mesh=mesh, **specs
    ))
    np.testing.assert_allclose(ring(x, w), mono(x, w), rtol=1e-5,
                               atol=1e-7)
    np.testing.assert_allclose(ring(x, w), x @ w, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("size", [2, 3, 4, 8])
def test_custom_vjp_matches_dense_gradients(size):
    """Grads through the dual-kernel backward (ag_matmul <-> matmul_rs)
    == jax.grad of the dense composition, for the column->row pair the
    transformer blocks use."""
    mesh = _mesh_1d(size)
    rng = np.random.RandomState(2)
    x = jnp.asarray(0.1 * rng.randn(2, 4 * size, 16), jnp.float32)
    w1 = jnp.asarray(0.1 * rng.randn(16, 8 * size), jnp.float32)
    w2 = jnp.asarray(0.1 * rng.randn(8 * size, 16), jnp.float32)

    def ring_loss(x, w1, w2):
        def f(xl, w1l, w2l):
            h = jnp.tanh(ag_matmul(xl, w1l, "m"))
            y = matmul_rs(h, w2l, "m")
            # Per-shard partial sums, combined OUTSIDE shard_map (the
            # engines' no-differentiated-psum discipline).
            return jnp.sum(y * y)[None]

        per = shard_map(
            f, mesh=mesh,
            in_specs=(P(None, "m", None), P(None, "m"), P("m", None)),
            out_specs=P("m"), check_vma=False,
        )
        return jnp.sum(per(x, w1, w2))

    def dense_loss(x, w1, w2):
        y = jnp.tanh(x @ w1) @ w2
        return jnp.sum(y * y)

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(x, w1, w2)
    g_dense = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))(x, w1, w2)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


# ------------------------------------------------- TP engine parity


def _batch(seed=0, seq=SEQ):
    rng = np.random.RandomState(seed)
    ids = rng.randint(1, TINY.vocab_size, size=(BATCH, seq)).astype(
        np.int32
    )
    ids[:, -2:] = 0  # pad tail -> exercises the attention mask
    labels = rng.randint(0, CLASSES, size=(BATCH,)).astype(np.int32)
    return ids, labels


def _run(engine, ids, labels, n=3, lr=0.05):
    ts = engine.init_state(jax.random.PRNGKey(0))
    ids, labels = engine.shard_batch(ids, labels)
    losses, accs = [], []
    for _ in range(n):
        ts, m = engine.train_step(ts, ids, labels, jnp.float32(lr))
        losses.append(float(m["loss_sum"]) / float(m["count"]))
        accs.append(float(m["correct1"]) / float(m["count"]))
    return ts, losses, accs


def _assert_state_close(ts_a, ts_b, rtol=1e-5, atol=1e-6):
    flat_a = jax.tree_util.tree_leaves(ts_a.params)
    flat_b = jax.tree_util.tree_leaves(ts_b.params)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=rtol, atol=atol
        )


# S=4 and S=8 ride slow (tier-1 budget): S=2 pins the rotate/overlap
# algebra on the same code path, and the multi-hop cases (4, 8) run in
# the full suite. Tier-1 twin of both: the S=2 case.
@pytest.mark.parametrize(
    "tp", [2, pytest.param(4, marks=pytest.mark.slow),
           pytest.param(8, marks=pytest.mark.slow)]
)
def test_tp_collective_matmul_matches_declarative(tp):
    """TensorParallelEngine(collective_matmul=True) == the declarative
    engine: same per-step loss/acc metrics and the same parameters after
    a 3-step trajectory, at every ring size the mesh hosts."""
    from distributed_model_parallel_tpu.parallel.tensor_parallel import (
        TensorParallelEngine,
    )

    mesh = make_mesh(MeshSpec(data=8 // tp, model=tp))
    model = bert_for_classification(CLASSES, TINY)
    ids, labels = _batch()
    ts_d, loss_d, acc_d = _run(
        TensorParallelEngine(model, SGD(), mesh, donate=False),
        ids, labels,
    )
    ts_c, loss_c, acc_c = _run(
        TensorParallelEngine(
            model, SGD(), mesh, donate=False, collective_matmul=True
        ),
        ids, labels,
    )
    np.testing.assert_allclose(loss_c, loss_d, rtol=1e-5)
    np.testing.assert_allclose(acc_c, acc_d, rtol=1e-5)
    _assert_state_close(ts_c, ts_d)
    assert loss_c[-1] < loss_c[0]


def test_tp_collective_matmul_needs_model_axis():
    from distributed_model_parallel_tpu.parallel.tensor_parallel import (
        TensorParallelEngine,
    )
    from jax.sharding import Mesh

    flat = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    with pytest.raises(ValueError, match="model"):
        TensorParallelEngine(
            bert_for_classification(CLASSES, TINY), SGD(), flat,
            rules=(), collective_matmul=True,
        )


def test_tp_collective_matmul_rejects_indivisible_seq():
    """T not divisible by the ring size must fail loudly at trace time,
    not silently compute garbage chunks."""
    from distributed_model_parallel_tpu.parallel.tensor_parallel import (
        TensorParallelEngine,
    )

    mesh = make_mesh(MeshSpec(data=2, model=4))
    eng = TensorParallelEngine(
        bert_for_classification(CLASSES, TINY), SGD(), mesh,
        donate=False, collective_matmul=True,
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    ids, labels = _batch(seq=6)  # 6 % 4 != 0
    ids, labels = eng.shard_batch(ids, labels)
    with pytest.raises(ValueError, match="divisible"):
        eng.train_step(ts, ids, labels, jnp.float32(0.05))


# ------------------------------------------------- SP engine parity


# S=4 and S=8 ride slow (tier-1 budget), same rationale and twin as
# the TP sweep above.
@pytest.mark.parametrize(
    "sp", [2, pytest.param(4, marks=pytest.mark.slow),
           pytest.param(8, marks=pytest.mark.slow)]
)
def test_sp_collective_matmul_matches_ring_engine(sp):
    """SequenceParallelEngine(collective_matmul=True) == the plain ring
    engine (and therefore dense, by the existing SP parity pins):
    metrics and trajectory at rtol 1e-5 for S in {2, 4, 8}."""
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        SequenceParallelEngine,
    )

    mesh = make_mesh(MeshSpec(data=8 // sp, seq=sp))
    ids, labels = _batch(seed=3, seq=16)
    ts_r, loss_r, acc_r = _run(
        SequenceParallelEngine(TINY, CLASSES, SGD(), mesh, donate=False),
        ids, labels,
    )
    ts_c, loss_c, acc_c = _run(
        SequenceParallelEngine(
            TINY, CLASSES, SGD(), mesh, donate=False,
            collective_matmul=True,
        ),
        ids, labels,
    )
    np.testing.assert_allclose(loss_c, loss_r, rtol=1e-5)
    np.testing.assert_allclose(acc_c, acc_r, rtol=1e-5)
    _assert_state_close(ts_c, ts_r)


@pytest.mark.slow
def test_lm_sp_collective_matmul_matches_ring_engine():
    """The decoder-side twin: CausalLMSequenceParallelEngine with the
    FFN rings matches its plain-ring self step for step. `slow` (tier-1
    budget); tier-1 twins: test_sp_collective_matmul_matches_ring_engine
    (the encoder SP engine, same FFN ring path over 'seq') and the
    structural SP permute-chain pins in tests/test_collectives_hlo.py."""
    from distributed_model_parallel_tpu.models.gpt import GPTConfig
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        CausalLMSequenceParallelEngine,
    )

    cfg = GPTConfig(
        vocab_size=61, dim=32, num_layers=2, num_heads=4, ffn_dim=64,
        max_position=16, dropout_rate=0.0, pad_token_id=0,
    )
    mesh = make_mesh(MeshSpec(data=2, seq=4))
    rng = np.random.RandomState(5)
    ids = rng.randint(1, 61, size=(BATCH, 16)).astype(np.int32)

    def run(eng):
        ts = eng.init_state(jax.random.PRNGKey(0))
        a, b = eng.shard_batch(ids)
        losses = []
        for _ in range(3):
            ts, m = eng.train_step(ts, a, b, jnp.float32(0.05))
            losses.append(float(m["loss_sum"]) / float(m["count"]))
        return ts, losses

    ts_r, loss_r = run(CausalLMSequenceParallelEngine(
        cfg, SGD(), mesh, donate=False
    ))
    ts_c, loss_c = run(CausalLMSequenceParallelEngine(
        cfg, SGD(), mesh, donate=False, collective_matmul=True
    ))
    np.testing.assert_allclose(loss_c, loss_r, rtol=1e-5)
    _assert_state_close(ts_c, ts_r)
    assert loss_c[-1] < loss_c[0]


def test_sp_collective_matmul_rejects_indivisible_ffn():
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        SequenceParallelEngine,
    )

    mesh = make_mesh(MeshSpec(data=2, seq=4))
    cfg = dataclasses.replace(TINY, intermediate_size=66)  # 66 % 4 != 0
    with pytest.raises(ValueError, match="divisible"):
        SequenceParallelEngine(
            cfg, CLASSES, SGD(), mesh, collective_matmul=True
        )
