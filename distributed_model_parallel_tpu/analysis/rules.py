"""The collective-contract rule registry.

Each rule encodes ONE contract the repo claims in prose (INTERNALS §3c/
§3e/§3f/§5c, RESULTS §3b) as a check over a parsed+classified HLO
module. Rules are severity-tagged and declare their own applicability
over a `LintTarget` (the engine/mode/mesh description the lint driver
fills in when it lowers a combo), so the same registry runs over the
whole engine matrix and each combo is judged only against the contracts
it opted into.

Adding a rule (INTERNALS §8b has the walkthrough):

    @rule(
        id="my-rule", severity="error", source="PR N",
        contract="one sentence of what must hold",
        applies=lambda t: t.engine == "ddp",
    )
    def _my_rule(ctx: LintContext) -> list:
        ...return [ctx.finding("my-rule", "what went wrong")]

plus one positive (violation detected) and one negative (clean) test in
tests/test_hlo_rules.py — the conftest meta-check fails collection when
a registered rule is missing either polarity.

Intended deviations are EXEMPTIONS, not deleted rules: a `LintTarget`
carries `exemptions={rule_id: reason}`, the finding is still computed
and reported but does not count as a violation, and the reason string
is printed beside it — the contract stays visible where it is waived.

No jax at module level: the registry must be importable by conftest
(for the coverage meta-check) and by golden-file tests without a
backend.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Callable, Dict, List, Optional, Tuple

from distributed_model_parallel_tpu.analysis.collectives import (
    ClassifiedCollective,
    MeshModel,
    classify,
    monolithic_over,
    nonscalar_all_reduces,
    ring_permutes_over,
)
from distributed_model_parallel_tpu.analysis.hlo import (
    DTYPE_BYTES,
    HloModule,
    parse_hlo,
)


@dataclasses.dataclass(frozen=True)
class LintTarget:
    """What the lint driver lowered: engine, mode, mesh facts, and the
    expectations rules pin against (bucket plans, at-rest layouts).
    Everything beyond `name`/`engine` has a safe default so golden
    tests can construct minimal targets."""

    name: str
    # dp | ddp | fsdp | tp | sp | sp_lm | pipeline | serve | cm_ag |
    # cm_rs
    engine: str
    grad_reduction: str = "monolithic"
    collective_matmul: bool = False
    bf16: bool = False
    donate: bool = False

    # Mesh facts (filled from the mesh the combo was lowered on).
    data_axes: Tuple[str, ...] = ("data",)
    ici_axis: Optional[str] = "data"
    dcn_axis: Optional[str] = None
    ici_size: int = 1
    dcn_size: int = 1
    cm_axis: Optional[str] = None  # the axis opted-in rings run over
    cm_size: int = 0

    # Reducer expectations: per backward segment, a tuple of
    # (padded_elems, dtype_token) bucket descriptors — one segment for
    # "bucketed", `overlap_segments` of them for "overlapped".
    bucket_plans: Tuple[Tuple[Tuple[int, str], ...], ...] = ()
    overlap_segments: int = 0

    # Collective-matmul expectations.
    expected_permutes: Optional[int] = None  # op-level exact pin
    cm_min_ring_permutes: int = 0  # engine-level floor
    # Serving decode expectation (engine == "serve", opted-in rings):
    # the exact `serve_ring`-tagged permute count of one decode step,
    # 4 projection rings per block x (S-1) hops (PR 7).
    serve_decode_permutes: Optional[int] = None
    # Speculative-verify expectation (ISSUE 18, engine == "serve" with
    # speculative_k > 0): the verify step scores k+1 positions per slot
    # in one pass, and its ring inventory must be EXACTLY one decode
    # step's — the same 4*layers*(S-1) `serve_ring` permutes, zero
    # monolithic collectives over the TP axis (rule spec-verify-step).
    speculative_k: int = 0
    spec_verify_permutes: Optional[int] = None
    # jaxpr metadata: ((axis_names, dtype_token, scope), ...) for every
    # `ppermute` equation in the traced step. Compiled CPU HLO cannot
    # carry dtype contracts (the backend's float-normalization pass
    # legalizes bf16 collectives to f32 + converts), so the bf16 ring
    # rule reads the trace-level dtypes instead; `scope` is the
    # equation's name_stack string (see lint.jaxpr_ppermute_dtypes).
    ring_dtypes: Tuple[Tuple[Tuple[str, ...], str, str], ...] = ()

    # At-rest / donation expectations.
    fsdp_full_leaf_shapes: Tuple[Tuple[int, ...], ...] = ()
    n_param_leaves: int = 0
    # Non-scalar all-reduce allowlist: BN state / batch-stat shapes.
    state_leaf_shapes: Tuple[Tuple[int, ...], ...] = ()

    # MoE dispatch expectations (engine == "ep"): which exchange the
    # combo opted into, and — for "hierarchical" — the EXACT count of
    # `moe_ring`-scoped collective-permutes one train step must carry
    # (2 x exchange_permutes(ici, dcn) per MoE layer: forward pair +
    # its mirrored backward; `ops/expert_dispatch.py`).
    moe_dispatch: str = "gspmd"
    moe_ring_permutes: Optional[int] = None

    # Compressed-'dcn'-wire expectations (`ops/wire_codec.py`, rule
    # `dcn-compressed-payload`). `dcn_ring_records` is the traced-jaxpr
    # record of EVERY ppermute equation — ((axis_names, dtype_token,
    # scope, n_elems), ...) — because compiled CPU HLO float-normalizes
    # bf16 collectives to f32 (the bf16-ring-upcast precedent), so the
    # wire dtype/byte contract lives at trace level. One of the two
    # expectations pins the payload hops: `dcn_wire_chunks` is the
    # exact multiset of (n_elems, wire_dtype_token) per hop (the
    # reducer paths, computable from the bucket plans), and
    # `dcn_wire_hops` is the exact hop COUNT when per-hop shapes are
    # model-dependent (the MoE exchange: 4(K-1) per routed layer).
    dcn_compression: str = "none"
    dcn_wire_chunks: Tuple[Tuple[int, str], ...] = ()
    dcn_wire_hops: Optional[int] = None
    # ISSUE 16 satellite: the exact (n_elems, wire_dtype_token)
    # multiset of FSDP's compressed WEIGHT-gather ring hops (the
    # `fsdp_gather`-scoped dcn_wire records, kept separate from the
    # gradient-bucket hops above) — (K-1) hops of full_leaf/K elems per
    # dcn-crossing leaf per gather, x2 under "overlapped" (forward
    # gather + backward regather).
    dcn_gather_chunks: Tuple[Tuple[int, str], ...] = ()
    dcn_ring_records: Tuple[
        Tuple[Tuple[str, ...], str, str, int], ...
    ] = ()

    # Quantized-decode expectations (`ops/quant_matmul.py`, rule
    # `decode-quantized-matmul`). `decode_dot_records` is the
    # traced-jaxpr record of EVERY `dot_general` equation in the decode
    # step — ((lhs_dtype_token, rhs_dtype_token, rhs_shape), ...) —
    # because compiled CPU HLO normalizes the quantized dots back to
    # f32 (the bf16-ring-upcast precedent), so the compute-dtype
    # contract lives at trace level. `quant_dot_count` is the exact
    # quantized projection-dot count (4L per step declaratively, 4LS
    # with the opted-in rings: S chunk dots per ring). The head matmul
    # (`head_weight_shape`) deliberately stays f32 — logits feed
    # sampling.
    compute_dtype: Optional[str] = None
    decode_dot_records: Tuple[
        Tuple[str, str, Tuple[int, ...]], ...
    ] = ()
    quant_dot_count: Optional[int] = None
    head_weight_shape: Optional[Tuple[int, ...]] = None

    # Composed-plan expectations (ISSUE 19, engine == "plan"):
    # `plan_axes` is the ordered {axis: ways} assignment of the
    # lowered ParallelPlan's ('stage', 'data', 'seq') mesh;
    # `plan_collective_records` is the traced-jaxpr record of EVERY
    # named-axis collective equation in one train step —
    # ((primitive, axis_names, dtype_token, scope, n_elems), ...) —
    # trace-level like the other named-axis contracts because
    # compiled CPU HLO normalizes dtypes and flattens axis names to
    # replica groups (see lint.jaxpr_collective_records).
    plan_axes: Tuple[Tuple[str, int], ...] = ()
    plan_collective_records: Tuple[
        Tuple[str, Tuple[str, ...], str, str, int], ...
    ] = ()
    # The plan's pipeline schedule (ISSUE 20): keys the plan-wire-
    # fabric rule's static ppermute-count pin (gpipe traces forward +
    # transpose; a scheduled plan traces the tick program's up + down
    # wires — and NEVER more, because schedules replay TABLES inside
    # one scan rather than unrolling per-tick programs).
    plan_schedule: str = "gpipe"
    plan_virtual: int = 1

    # rule_id -> reason; the finding is reported but not counted
    # (module docstring).
    exemptions: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    message: str
    instruction: Optional[str] = None
    exempted: bool = False
    exemption_reason: str = ""


@dataclasses.dataclass
class LintContext:
    """One lowered combo, parsed and classified, handed to every
    applicable rule."""

    target: LintTarget
    module: HloModule
    mesh: MeshModel
    collectives: List[ClassifiedCollective]

    @classmethod
    def build(cls, target: LintTarget, hlo_text: str,
              mesh: MeshModel) -> "LintContext":
        module = parse_hlo(hlo_text)
        return cls(
            target=target,
            module=module,
            mesh=mesh,
            collectives=classify(module, mesh),
        )

    def finding(self, rule_id: str, message: str,
                instruction: Optional[str] = None) -> Finding:
        sev = REGISTRY[rule_id].severity
        return Finding(rule_id, sev, message, instruction)

    # Shared helpers -------------------------------------------------

    def data_ring_permutes(self) -> List[ClassifiedCollective]:
        return ring_permutes_over(self.collectives, self.target.ici_axis)

    def total_buckets(self) -> int:
        return sum(len(p) for p in self.target.bucket_plans)

    def dcn_shard_shapes(self) -> Counter:
        """Expected multiset of (shape, dtype) for the per-bucket
        cross-slice all-reduce: each bucket's 1/ici shard of its padded
        flat buffer."""
        t = self.target
        c: Counter = Counter()
        for plan in t.bucket_plans:
            for padded, dt in plan:
                c[((padded // t.ici_size,), dt)] += 1
        return c


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    severity: str  # "error" | "warn"
    contract: str
    source: str  # the PR whose claim this encodes
    applies: Callable[[LintTarget], bool]
    check: Callable[[LintContext], List[Finding]]


REGISTRY: Dict[str, Rule] = {}


def rule(*, id: str, severity: str, contract: str, source: str,
         applies: Callable[[LintTarget], bool]):
    def deco(fn):
        if id in REGISTRY:
            raise ValueError(f"duplicate rule id {id!r}")
        REGISTRY[id] = Rule(id, severity, contract, source, applies, fn)
        return fn
    return deco


def run_rules(ctx: LintContext) -> List[Finding]:
    """Run every applicable rule; exempted findings come back flagged
    (reported, not counted — the driver's summary distinguishes)."""
    out: List[Finding] = []
    for r in REGISTRY.values():
        if not r.applies(ctx.target):
            continue
        for f in r.check(ctx):
            reason = ctx.target.exemptions.get(r.id)
            if reason is not None:
                f = dataclasses.replace(
                    f, exempted=True, exemption_reason=reason
                )
            out.append(f)
    return out


def _is_reducer(t: LintTarget) -> bool:
    # Compressed-monolithic counts too: dcn_compression on a
    # "monolithic" step routes the reduction through ONE flat bucket
    # per dtype (the engines' single-bucket path), so the bucket-ring
    # and no-grad-all-reduce contracts apply to it unchanged.
    return (
        (t.grad_reduction in ("bucketed", "overlapped")
         or t.dcn_compression != "none")
        and t.engine in ("ddp", "fsdp", "sp_lm")
    )


# ------------------------------------------------------------------ rules


@rule(
    id="dcn-grad-all-reduce", severity="error", source="PR 4",
    contract=(
        "On bucketed/overlapped paths over a hybrid dcn x ici mesh, no "
        "all-reduce crossing 'dcn' may carry more than one bucket's "
        "1/ici shard — the slow fabric never sees a full gradient."
    ),
    applies=lambda t: _is_reducer(t) and t.dcn_size > 1,
)
def _dcn_grad_all_reduce(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    limit = 0
    for plan in t.bucket_plans:
        for padded, dt in plan:
            itemsize = DTYPE_BYTES.get(dt, 4)
            limit = max(limit, (padded // t.ici_size) * itemsize)
    out = []
    for c in nonscalar_all_reduces(ctx.collectives):
        if c.crosses(t.dcn_axis) and c.payload_bytes > limit:
            out.append(ctx.finding(
                "dcn-grad-all-reduce",
                f"{c.name}: {c.payload_bytes} B all-reduce crosses "
                f"'{t.dcn_axis}' (largest allowed bucket shard: "
                f"{limit} B)",
                c.name,
            ))
    return out


@rule(
    id="bucket-ring-permutes", severity="error", source="PR 4",
    contract=(
        "Each bucket reduces as chunked ppermute rings: exactly "
        "2(S-1) collective-permutes per bucket over the intra-slice "
        "fabric (ring reduce-scatter + ring all-gather), summed over "
        "the per-segment bucket plans."
    ),
    applies=_is_reducer,
)
def _bucket_ring_permutes(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    expected = 2 * (t.ici_size - 1) * ctx.total_buckets()
    actual = len(ctx.data_ring_permutes())
    if actual != expected:
        return [ctx.finding(
            "bucket-ring-permutes",
            f"{actual} ring permutes over '{t.ici_axis}', expected "
            f"2*({t.ici_size}-1)*{ctx.total_buckets()} = {expected}",
        )]
    return []


@rule(
    id="dcn-bucket-psum-shard", severity="error", source="PR 4",
    contract=(
        "On a hybrid mesh, each bucket crosses 'dcn' exactly once, as "
        "an all-reduce shape-pinned at the bucket's 1/ici shard of its "
        "padded flat buffer. (Compressed combos carry NO dcn "
        "all-reduce at all — their hop contract is "
        "dcn-compressed-payload's.)"
    ),
    applies=lambda t: _is_reducer(t) and t.dcn_size > 1
    and t.dcn_compression == "none",
)
def _dcn_bucket_psum_shard(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    expected = ctx.dcn_shard_shapes()
    actual: Counter = Counter()
    for c in nonscalar_all_reduces(ctx.collectives):
        if c.axes is not None and c.axes == {t.dcn_axis}:
            for b in c.instruction.buffers:
                actual[(b.shape, b.dtype)] += 1
    if actual != expected:
        return [ctx.finding(
            "dcn-bucket-psum-shard",
            f"dcn-only all-reduce shapes {dict(actual)} != expected "
            f"per-bucket 1/ici shards {dict(expected)}",
        )]
    return []


@rule(
    id="no-grad-all-reduce", severity="error", source="PR 4",
    contract=(
        "Bucketed/overlapped steps keep ZERO grad-sized all-reduces "
        "over the data fabric: every non-scalar all-reduce touching "
        "the data axes must be either a pinned per-bucket dcn shard or "
        "a BatchNorm statistics reduction (state-leaf shaped)."
    ),
    applies=_is_reducer,
)
def _no_grad_all_reduce(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    allowed_state = set(t.state_leaf_shapes)
    dcn_shards = ctx.dcn_shard_shapes()
    out = []
    for c in nonscalar_all_reduces(ctx.collectives):
        if c.axes is None:
            out.append(ctx.finding(
                "no-grad-all-reduce",
                f"{c.name}: unclassifiable replica groups on a "
                "non-scalar all-reduce",
                c.name,
            ))
            continue
        if not (c.axes & set(t.data_axes)):
            continue  # another fabric's reduction ('seq', 'stage', ...)
        if c.axes == {t.dcn_axis}:
            # EVERY buffer must match a pinned shard: a combiner-fused
            # tuple all-reduce must not smuggle a grad-sized buffer
            # over 'dcn' behind one legitimate bucket shard.
            if c.instruction.buffers and all(
                (b.shape, b.dtype) in dcn_shards
                for b in c.instruction.buffers
            ):
                continue  # the pinned cross-slice bucket hop
        if all(b.shape in allowed_state for b in c.instruction.buffers):
            continue  # BN running-stat / batch-stat psum
        out.append(ctx.finding(
            "no-grad-all-reduce",
            f"{c.name}: non-scalar all-reduce over {sorted(c.axes)} "
            f"carrying {c.shapes} — grad-sized traffic outside the "
            "bucket rings",
            c.name,
        ))
    return out


@rule(
    id="cm-ring-permutes", severity="error", source="PR 2",
    contract=(
        "A collective-matmul ring is exactly S-1 collective-permutes "
        "per kernel (op-level pin); an opted-in engine step carries at "
        "least its projection sites' worth of ring permutes over the "
        "cm axis."
    ),
    applies=lambda t: t.engine in ("cm_ag", "cm_rs")
    or (t.collective_matmul and t.cm_axis is not None),
)
def _cm_ring_permutes(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    rings = ring_permutes_over(ctx.collectives, t.cm_axis)
    out = []
    if t.expected_permutes is not None:
        if len(rings) != t.expected_permutes:
            out.append(ctx.finding(
                "cm-ring-permutes",
                f"{len(rings)} ring permutes over '{t.cm_axis}', "
                f"expected exactly {t.expected_permutes}",
            ))
    elif len(rings) < t.cm_min_ring_permutes:
        out.append(ctx.finding(
            "cm-ring-permutes",
            f"{len(rings)} ring permutes over '{t.cm_axis}', expected "
            f">= {t.cm_min_ring_permutes} (the opted-in projection "
            "sites' rings)",
        ))
    return out


@rule(
    id="cm-monolithic-collective", severity="error", source="PR 2",
    contract=(
        "An opted-in collective-matmul site leaves NO monolithic "
        "all-gather/reduce-scatter on its axis: op-level kernels must "
        "be permute-only; SP engine steps (whose only cm-axis gathers "
        "would be the rings' replacements) must keep zero. The TP "
        "engine is judged only at op level — its embedding/head keep "
        "legitimate partitioner gathers."
    ),
    applies=lambda t: t.engine in ("cm_ag", "cm_rs")
    or (t.collective_matmul and t.engine in ("sp", "sp_lm")),
)
def _cm_monolithic(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    out = []
    if t.engine in ("cm_ag", "cm_rs"):
        bad = [
            c for c in ctx.collectives
            if c.kind in ("all-gather", "reduce-scatter", "all-reduce")
        ]
    else:
        bad = monolithic_over(ctx.collectives, t.cm_axis)
    for c in bad:
        out.append(ctx.finding(
            "cm-monolithic-collective",
            f"{c.name}: monolithic {c.kind} on the opted-in "
            f"'{t.cm_axis}' ring path",
            c.name,
        ))
    return out


@rule(
    id="fsdp-at-rest-sharded", severity="error", source="PR 2/PR 4",
    contract=(
        "FSDP parameters are never fully materialized at rest: no "
        "entry parameter of the compiled step may carry the FULL shape "
        "of a shardable leaf (every leaf >= min_shard_elems with a "
        "divisible dim lives 1/N on device)."
    ),
    applies=lambda t: t.engine == "fsdp",
)
def _fsdp_at_rest(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    out = []
    if not t.fsdp_full_leaf_shapes:
        return [ctx.finding(
            "fsdp-at-rest-sharded",
            "the at-rest policy shards nothing (no shardable leaves) — "
            "the contract is vacuous for this model/mesh",
        )]
    full = set(t.fsdp_full_leaf_shapes)
    for p in ctx.module.entry_parameters():
        for b in p.buffers:
            if b.shape in full:
                out.append(ctx.finding(
                    "fsdp-at-rest-sharded",
                    f"entry parameter {p.name} carries full shape "
                    f"{b.shape} of a shardable leaf — materialized at "
                    "rest",
                    p.name,
                ))
    return out


@rule(
    id="overlap-first-bucket-free", severity="error", source="PR 5",
    contract=(
        "Under grad_reduction='overlapped', the FIRST-fired bucket's "
        "ring permutes (last segment's — late layers differentiate "
        "first) carry no transitive dependency on segment 0's backward "
        "ops; segment 0's own bucket MUST depend on them (the control "
        "that keeps the analysis non-vacuous)."
    ),
    applies=lambda t: t.grad_reduction == "overlapped"
    and t.engine in ("ddp", "fsdp", "sp_lm") and t.ici_size > 1,
)
def _overlap_first_bucket(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    m = ctx.module
    s = t.overlap_segments
    first = m.tagged(f"grad_reduce_stage{s - 1}", "collective-permute")
    bwd0 = set(m.tagged("bwd_stage0"))
    out = []
    if not first:
        out.append(ctx.finding(
            "overlap-first-bucket-free",
            f"no ring permutes tagged grad_reduce_stage{s - 1} — the "
            "first-fired bucket left no trace (tags moved?)",
        ))
    if not bwd0:
        out.append(ctx.finding(
            "overlap-first-bucket-free",
            "no ops tagged bwd_stage0 — segment-0 backward left no "
            "trace (tags moved?)",
        ))
    if out:
        return out
    for p in first:
        if m.depends_on(p, bwd0):
            out.append(ctx.finding(
                "overlap-first-bucket-free",
                f"first-fired bucket permute {p} depends on segment-0 "
                "backward — the eager firing serialized",
                p,
            ))
    last = m.tagged("grad_reduce_stage0", "collective-permute")
    if not last or not all(m.depends_on(p, bwd0) for p in last):
        out.append(ctx.finding(
            "overlap-first-bucket-free",
            "positive control failed: segment 0's own bucket does not "
            "depend on segment-0 backward — the dependency analysis "
            "is vacuous",
        ))
    return out


@rule(
    id="prefetch-gather-free", severity="error", source="PR 5",
    contract=(
        "FSDP overlapped: the prefetched all-gather of segment k-1's "
        "weights depends only on the parameter shards — never on ANY "
        "segment's bucket-ring ops — so the scheduler may hoist it "
        "behind the in-flight reduction."
    ),
    applies=lambda t: t.engine == "fsdp"
    and t.grad_reduction == "overlapped",
)
def _prefetch_gather_free(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    m = ctx.module
    reduce_ops: set = set()
    for k in range(t.overlap_segments):
        reduce_ops |= set(m.tagged(f"grad_reduce_stage{k}"))
    out = []
    if not reduce_ops:
        return [ctx.finding(
            "prefetch-gather-free",
            "no grad_reduce_stage* tagged ops — the reduction left no "
            "trace (tags moved?)",
        )]
    for k in range(t.overlap_segments - 1):
        gathers = m.tagged(f"prefetch_gather_stage{k}", "all-gather")
        if not gathers:
            out.append(ctx.finding(
                "prefetch-gather-free",
                f"no prefetched all-gather tagged "
                f"prefetch_gather_stage{k}",
            ))
            continue
        for g in gathers:
            if m.depends_on(g, reduce_ops):
                out.append(ctx.finding(
                    "prefetch-gather-free",
                    f"prefetch gather {g} (segment {k}) depends on a "
                    "bucket reduction — the ZeRO overlap serialized",
                    g,
                ))
    return out


@rule(
    id="serve-decode-ring", severity="error", source="PR 7",
    contract=(
        "An opted-in serving decode step rides the chunked rings: "
        "exactly 4*layers*(S-1) `serve_ring`-tagged collective-"
        "permutes (one ag_matmul/matmul_rs ring per qkv / attn-out / "
        "ffn-in / ffn-out projection, no backward) and ZERO monolithic "
        "all-gather/reduce-scatter crossing the TP axis — the decode "
        "projections never fall back to the partitioner's fused "
        "collectives."
    ),
    applies=lambda t: t.engine == "serve" and t.collective_matmul
    and not t.speculative_k,
)
def _serve_decode_ring(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    out = []
    if t.serve_decode_permutes is None:
        return [ctx.finding(
            "serve-decode-ring",
            "no serve_decode_permutes expectation on an opted-in "
            "serving combo — the ring pin was not checked",
        )]
    tagged = ctx.module.tagged("serve_ring", "collective-permute")
    if len(tagged) != t.serve_decode_permutes:
        out.append(ctx.finding(
            "serve-decode-ring",
            f"{len(tagged)} serve_ring-tagged permutes, expected "
            f"exactly {t.serve_decode_permutes} (4 rings/block x "
            "(S-1) hops)",
        ))
    for c in monolithic_over(ctx.collectives, t.cm_axis):
        out.append(ctx.finding(
            "serve-decode-ring",
            f"{c.name}: monolithic {c.kind} crossing '{t.cm_axis}' on "
            "an opted-in decode step",
            c.name,
        ))
    return out


@rule(
    id="spec-verify-step", severity="error", source="ISSUE 18",
    contract=(
        "A speculative verify step on an opted-in serving combo "
        "amortizes k+1 scored positions over ONE decode step's wire "
        "traffic: exactly 4*layers*(S-1) `serve_ring`-tagged "
        "collective-permutes (the chunk axis rides the rings' local "
        "operand, never the fabric) and ZERO monolithic all-gather/"
        "reduce-scatter crossing the TP axis — if verify cost scaled "
        "with k on the wire, speculative decoding's win would vanish "
        "at exactly the batch sizes it targets."
    ),
    applies=lambda t: t.engine == "serve" and t.collective_matmul
    and t.speculative_k > 0,
)
def _spec_verify_step(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    out = []
    if t.spec_verify_permutes is None:
        return [ctx.finding(
            "spec-verify-step",
            "no spec_verify_permutes expectation on a speculative "
            "serving combo — the verify ring pin was not checked",
        )]
    tagged = ctx.module.tagged("serve_ring", "collective-permute")
    if len(tagged) != t.spec_verify_permutes:
        out.append(ctx.finding(
            "spec-verify-step",
            f"{len(tagged)} serve_ring-tagged permutes in the verify "
            f"step, expected exactly {t.spec_verify_permutes} — one "
            f"decode step's inventory (4 rings/block x (S-1) hops), "
            f"independent of k={t.speculative_k}",
        ))
    for c in monolithic_over(ctx.collectives, t.cm_axis):
        out.append(ctx.finding(
            "spec-verify-step",
            f"{c.name}: monolithic {c.kind} crossing '{t.cm_axis}' in "
            "a speculative verify step",
            c.name,
        ))
    return out


_QUANT_DOT_PAIR = {"int8": ("s8", "s8"), "bf16": ("bf16", "bf16")}


@rule(
    id="decode-quantized-matmul", severity="error", source="ISSUE 16",
    contract=(
        "An opted-in quantized decode step runs EVERY projection GEMM "
        "in the declared arithmetic: exactly 4*layers quantized "
        "dot_generals per step (4*layers*S with the opted-in rings — "
        "S chunk dots per ring), ZERO f32 dot_generals on projection "
        "shapes, and the head matmul still f32 (logits feed "
        "sampling). Pinned from the traced jaxpr "
        "(`lint.jaxpr_dot_records`): compiled CPU HLO normalizes "
        "int8/bf16 dots back to f32, the bf16-ring-upcast precedent."
    ),
    applies=lambda t: (
        t.engine == "serve" and t.compute_dtype is not None
    ),
)
def _decode_quantized_matmul(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    out = []
    pair = _QUANT_DOT_PAIR.get(t.compute_dtype)
    if pair is None:
        return [ctx.finding(
            "decode-quantized-matmul",
            f"unknown compute_dtype {t.compute_dtype!r} — the "
            "quantized-dot pin was not checked",
        )]
    if not t.decode_dot_records or t.quant_dot_count is None:
        return [ctx.finding(
            "decode-quantized-matmul",
            "no decode_dot_records/quant_dot_count expectation on a "
            "quantized serving combo — the compute-dtype pin was not "
            "checked",
        )]
    # Projection dots are the rank-2-rhs dot_generals that are not the
    # head matmul (attention's qk/av dots carry batched rank-3+ rhs).
    quantized = [
        r for r in t.decode_dot_records if (r[0], r[1]) == pair
    ]
    if len(quantized) != t.quant_dot_count:
        out.append(ctx.finding(
            "decode-quantized-matmul",
            f"{len(quantized)} {t.compute_dtype} dot_generals in the "
            f"decode trace, expected exactly {t.quant_dot_count} "
            "(4 projections/block"
            + (" x S chunk dots per ring" if t.collective_matmul
               else "") + ")",
        ))
    f32_proj = [
        r for r in t.decode_dot_records
        if (r[0], r[1]) == ("f32", "f32") and len(r[2]) == 2
        and r[2] != t.head_weight_shape
    ]
    for lhs, rhs, shape in f32_proj:
        out.append(ctx.finding(
            "decode-quantized-matmul",
            f"f32 dot_general on projection shape {shape} in an "
            f"opted-in {t.compute_dtype} decode step — the projection "
            "fell back to f32 arithmetic",
        ))
    if t.head_weight_shape is not None:
        head = [
            r for r in t.decode_dot_records
            if r[2] == t.head_weight_shape
        ]
        if not head:
            out.append(ctx.finding(
                "decode-quantized-matmul",
                f"no dot_general on the head shape "
                f"{t.head_weight_shape} — the head-matmul-stays-f32 "
                "pin was not checked",
            ))
        for lhs, rhs, shape in head:
            if (lhs, rhs) != ("f32", "f32"):
                out.append(ctx.finding(
                    "decode-quantized-matmul",
                    f"head matmul {shape} traced {lhs}x{rhs}; the "
                    "head stays f32 — logits feed sampling",
                ))
    return out


# Named-scope exemption for bf16-ring-upcast: permutes whose trace
# scope carries one of these names ride f32 ON PURPOSE and are not
# upcast findings. `kv_ring` is ring attention's K/V rotation
# (ops/ring_attention.py): its dk/dv cotangents retrace the reversed
# ring in the wire dtype, so a bf16 wire would accumulate each block's
# gradient through n-1 bf16 roundings — the module's contract is
# "accumulate in f32 end to end", and the wire pays 2x bytes for it.
# Matched as a whole scope-name WORD (\b-delimited), never a substring:
# a future `qkv_ring` or `kv_ring_cache` scope must not inherit the
# exemption silently.
BF16_RING_EXEMPT_SCOPES = ("kv_ring",)


def _scope_exempt(scope: str) -> bool:
    import re as _re

    return any(
        _re.search(rf"\b{_re.escape(s)}\b", scope)
        for s in BF16_RING_EXEMPT_SCOPES
    )


@rule(
    id="bf16-ring-upcast", severity="error", source="PR 2/PR 6",
    contract=(
        "Inside an opted-in bf16 region (compute_dtype=bfloat16 with "
        "collective-matmul rings), every ppermute over the cm axis "
        "carries a bf16 payload — an f32 permute is a silent upcast "
        "doubling the ring bytes. Checked from the traced jaxpr (the "
        "CPU backend's float-normalization pass rewrites compiled-HLO "
        "collectives to f32, so only trace-level dtypes carry this "
        "contract). Scopes in BF16_RING_EXEMPT_SCOPES (the KV ring's "
        "deliberate f32 wire) are exempt."
    ),
    applies=lambda t: t.bf16 and (
        t.engine in ("cm_ag", "cm_rs") or t.collective_matmul
    ),
)
def _bf16_ring_upcast(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    if not t.ring_dtypes:
        return [ctx.finding(
            "bf16-ring-upcast",
            "no jaxpr ppermute dtypes collected for a bf16 ring combo "
            "— the dtype contract was not checked",
        )]
    out = []
    for axes, dt, scope in t.ring_dtypes:
        if _scope_exempt(scope):
            continue
        if t.cm_axis in axes and dt == "f32":
            out.append(ctx.finding(
                "bf16-ring-upcast",
                f"f32 ppermute over '{t.cm_axis}' in the traced step "
                f"(scope {scope!r}) — silent upcast on an opted-in "
                "bf16 ring",
            ))
    return out


@rule(
    id="moe-hierarchical-a2a", severity="error", source="PR 10",
    contract=(
        "An opted-in hierarchical MoE step keeps the token exchange on "
        "the explicit two-level path: ZERO token-sized all-to-all "
        "touching the data fabric (the flat exchange the partitioner "
        "would insert — on a hybrid mesh it would drag the full "
        "payload across 'dcn'), and EXACTLY the expected moe_ring-"
        "scoped collective-permute chain (2(I-1)+2(K-1) per exchange "
        "pair, doubled by the mirrored backward — "
        "ops/expert_dispatch.exchange_permutes)."
    ),
    applies=lambda t: t.engine == "ep"
    and t.moe_dispatch == "hierarchical",
)
def _moe_hierarchical_a2a(ctx: LintContext) -> List[Finding]:
    import re as _re

    t = ctx.target
    if t.moe_ring_permutes is None:
        return [ctx.finding(
            "moe-hierarchical-a2a",
            "no moe_ring_permutes expectation on an opted-in MoE combo "
            "— the exchange chain was not checked",
        )]
    out = []
    # Word-matched, not tagged(): the backward hops surface as
    # `transpose(moe_ring)` in op_name, which the trailing-slash form
    # would miss; \b keeps a future moe_ring2 scope from inheriting.
    tagged = [
        i for i in ctx.module.collectives()
        if i.base_op == "collective-permute"
        and _re.search(r"\bmoe_ring\b", i.op_name)
    ]
    if len(tagged) != t.moe_ring_permutes:
        out.append(ctx.finding(
            "moe-hierarchical-a2a",
            f"{len(tagged)} moe_ring-scoped permutes, expected exactly "
            f"{t.moe_ring_permutes} (2(I-1)+2(K-1) per exchange pair, "
            "forward + mirrored backward)",
        ))
    for c in ctx.collectives:
        if c.kind == "all-to-all" and any(
            c.crosses(a) for a in t.data_axes
        ):
            out.append(ctx.finding(
                "moe-hierarchical-a2a",
                f"{c.name}: {c.payload_bytes} B all-to-all touching the "
                f"data fabric {tuple(t.data_axes)} — the flat token "
                "exchange survived on an opted-in step",
                c.name,
            ))
    return out


# Wire-dtype tokens per compression mode (`ops/wire_codec.py`): the
# dtype every payload hop of an opted-in step must carry — bf16 halves
# the f32 bytes, int8 quarters them (+ one f32 scalar sidecar per hop).
DCN_WIRE_TOKEN = {"bf16": "bf16", "int8": "s8"}


def _scope_word(word: str, scope: str) -> bool:
    import re as _re

    return bool(_re.search(rf"\b{_re.escape(word)}\b", scope))


@rule(
    id="dcn-compressed-payload", severity="error", source="PR 11",
    contract=(
        "An opted-in compressed step keeps EVERY cross-'dcn' hop on "
        "the wire codec: each traced dcn-crossing ppermute is either a "
        "dcn_wire-scoped payload in the wire dtype (shape-pinned at "
        "1/2 resp. 1/4 the f32 bytes — the regrouped chunk's element "
        "count at the wire itemsize; FSDP's weight-gather ring hops "
        "pin their own fsdp_gather multiset) or, under int8, its "
        "one-scalar f32 dcn_scale sidecar; and ZERO f32 grad-, "
        "weight- or dispatch-sized "
        "payload crosses 'dcn' in the compiled HLO (no non-scalar "
        "all-reduce outside the BN-state allowlist, no all-to-all, no "
        "all-gather/reduce-scatter). Checked from the traced jaxpr "
        "like bf16-ring-upcast — the CPU backend float-normalizes "
        "bf16 collectives in compiled HLO."
    ),
    applies=lambda t: t.dcn_compression != "none" and t.dcn_size > 1,
)
def _dcn_compressed_payload(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    out: List[Finding] = []
    wire = DCN_WIRE_TOKEN[t.dcn_compression]

    if not t.dcn_ring_records:
        out.append(ctx.finding(
            "dcn-compressed-payload",
            "no traced ppermute records collected for a compressed "
            "combo — the wire dtype/byte contract was not checked",
        ))
        return out

    payload: List[Tuple[int, str]] = []
    gather_payload: List[Tuple[int, str]] = []
    sidecars: List[Tuple[str, int]] = []
    for axes, dt, scope, elems in t.dcn_ring_records:
        if t.dcn_axis not in axes:
            continue  # intra-slice / other-fabric traffic
        if _scope_word("dcn_wire", scope):
            # FSDP's compressed weight-gather hops carry their own
            # scope word so they pin against `dcn_gather_chunks`, not
            # the gradient-bucket multiset (ISSUE 16 satellite).
            if _scope_word("fsdp_gather", scope):
                gather_payload.append((elems, dt))
            else:
                payload.append((elems, dt))
        elif _scope_word("dcn_scale", scope):
            sidecars.append((dt, elems))
        else:
            out.append(ctx.finding(
                "dcn-compressed-payload",
                f"uncoded ppermute crosses '{t.dcn_axis}' on an "
                f"opted-in step ({elems} x {dt}, scope {scope!r}) — "
                "traffic outside the wire codec",
            ))

    # Payload pin: exact multiset of (elems, wire dtype) when the
    # builder can compute it (bucket plans), exact hop count otherwise.
    if t.dcn_wire_chunks:
        expected = Counter(t.dcn_wire_chunks)
        actual = Counter(payload)
        if actual != expected:
            out.append(ctx.finding(
                "dcn-compressed-payload",
                f"dcn_wire payload hops {dict(actual)} != expected "
                f"compressed chunks {dict(expected)} (elems x "
                "wire-dtype per hop)",
            ))
    elif t.dcn_wire_hops is not None:
        if len(payload) != t.dcn_wire_hops:
            out.append(ctx.finding(
                "dcn-compressed-payload",
                f"{len(payload)} dcn_wire payload hops, expected "
                f"exactly {t.dcn_wire_hops}",
            ))
        for elems, dt in payload:
            if dt != wire:
                out.append(ctx.finding(
                    "dcn-compressed-payload",
                    f"dcn_wire payload hop carries {dt} ({elems} "
                    f"elems), expected the {wire} wire dtype",
                ))
    else:
        out.append(ctx.finding(
            "dcn-compressed-payload",
            "no dcn_wire_chunks/dcn_wire_hops expectation on a "
            "compressed combo — the payload pin was not checked",
        ))

    # Weight-gather pin (ISSUE 16 satellite): FSDP's dcn gather leg
    # rides the codec too — the fsdp_gather-scoped hops must match the
    # builder's per-leaf ring-gather multiset exactly (both directions:
    # an uncompressed fused gather shows up as a missing hop here AND
    # as a monolithic dcn all-gather in the compiled-HLO half below).
    expected_g = Counter(t.dcn_gather_chunks)
    actual_g = Counter(gather_payload)
    if actual_g != expected_g:
        out.append(ctx.finding(
            "dcn-compressed-payload",
            f"fsdp_gather dcn_wire hops {dict(actual_g)} != expected "
            f"compressed weight-gather chunks {dict(expected_g)} "
            "(elems x wire-dtype per ring hop)",
        ))

    # Sidecar accounting: one f32 scalar per int8 payload hop (bucket
    # AND gather hops), none otherwise.
    n_coded = len(payload) + len(gather_payload)
    if t.dcn_compression == "int8":
        bad = [s for s in sidecars if s != ("f32", 1)]
        for dt, elems in bad:
            out.append(ctx.finding(
                "dcn-compressed-payload",
                f"dcn_scale sidecar is {elems} x {dt}, expected one "
                "f32 scalar per hop",
            ))
        if not bad and len(sidecars) != n_coded:
            out.append(ctx.finding(
                "dcn-compressed-payload",
                f"{len(sidecars)} dcn_scale sidecars for "
                f"{n_coded} int8 payload hops — expected one per "
                "hop",
            ))
    elif sidecars:
        out.append(ctx.finding(
            "dcn-compressed-payload",
            f"{len(sidecars)} dcn_scale sidecar(s) on a "
            f"{t.dcn_compression} combo — the cast codec has no scale",
        ))

    # Compiled-HLO half: zero f32 grad-/dispatch-sized payload crosses
    # 'dcn' in any monolithic form. On the reducer engines EVERY
    # non-state dcn all-reduce / gather is contraband; the EP engine's
    # gradient reduction legitimately stays on the partitioner's fused
    # collectives (only the DISPATCH is compressed there), so for it
    # only the token-sized all-to-all is forbidden — the shape the
    # flat exchange would take across the slice boundary.
    if t.engine in ("ddp", "fsdp", "sp_lm"):
        allowed_state = set(t.state_leaf_shapes)
        for c in nonscalar_all_reduces(ctx.collectives):
            if not c.crosses(t.dcn_axis):
                continue
            if all(
                b.shape in allowed_state
                for b in c.instruction.buffers
            ):
                continue  # BN running-stat / batch-stat psum
            out.append(ctx.finding(
                "dcn-compressed-payload",
                f"{c.name}: {c.payload_bytes} B all-reduce crosses "
                f"'{t.dcn_axis}' on a compressed step — uncompressed "
                "payload on the slow fabric",
                c.name,
            ))
        # The gather ban covers all three reducer engines: ddp/sp_lm
        # never legitimately gather across 'dcn', and FSDP's per-leaf
        # weight all-gathers — which DO cross the joint fabric — ride
        # the codec on an opted-in step since ISSUE 16
        # (`parallel/fsdp._coded_dcn_gather`: ici-only all-gather +
        # coded dcn ring), so a fused gather crossing 'dcn' here means
        # a leaf fell off the compressed path.
        for c in ctx.collectives:
            if c.kind in ("all-gather", "reduce-scatter") \
                    and c.crosses(t.dcn_axis):
                out.append(ctx.finding(
                    "dcn-compressed-payload",
                    f"{c.name}: monolithic {c.kind} crosses "
                    f"'{t.dcn_axis}' on a compressed step",
                    c.name,
                ))
    for c in ctx.collectives:
        if c.kind == "all-to-all" and c.crosses(t.dcn_axis):
            out.append(ctx.finding(
                "dcn-compressed-payload",
                f"{c.name}: {c.payload_bytes} B all-to-all crosses "
                f"'{t.dcn_axis}' on a compressed step — the flat "
                "dispatch payload on the slow fabric",
                c.name,
            ))
    return out


@rule(
    id="donated-step-aliased", severity="warn", source="PR 1/PR 6",
    contract=(
        "A train step built with donate=True must alias its state "
        "buffers input->output (one alias entry per parameter/optimizer "
        "leaf); a missing alias table double-buffers the whole state "
        "every step."
    ),
    applies=lambda t: t.donate and t.n_param_leaves > 0,
)
def _donated_step_aliased(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    n = ctx.module.input_output_aliases
    if n < t.n_param_leaves:
        return [ctx.finding(
            "donated-step-aliased",
            f"input_output_alias covers {n} buffers, expected at least "
            f"{t.n_param_leaves} (the parameter/optimizer leaves) — "
            "the donated state is double-buffered",
        )]
    return []


@rule(
    id="collective-fabric-known", severity="warn", source="PR 6",
    contract=(
        "Every collective's replica groups / permute pairs resolve to "
        "mesh coordinates — an unclassifiable collective means the "
        "fabric rules above ran blind on it."
    ),
    applies=lambda t: True,
)
def _collective_fabric_known(ctx: LintContext) -> List[Finding]:
    out = []
    for c in ctx.collectives:
        has_membership = (
            c.instruction.replica_groups is not None
            or c.instruction.source_target_pairs is not None
        )
        if has_membership and c.axes is None:
            out.append(ctx.finding(
                "collective-fabric-known",
                f"{c.name}: {c.kind} membership does not resolve to "
                "mesh coordinates",
                c.name,
            ))
    return out


# The 'seq'-ring scope words a composed plan may carry: ring
# attention's K/V rotation plus the two collective-matmul rings
# (`ops/ring_attention.py`, `ops/collective_matmul.py`). Word-matched
# (\b), same discipline as BF16_RING_EXEMPT_SCOPES.
PLAN_SEQ_SCOPE_WORDS = ("kv_ring", "ag_matmul", "matmul_rs")


# Static plan_wire ppermute count per pipeline schedule (ISSUE 20).
# Both tick programs trace exactly TWO stage ppermutes: gpipe's
# forward hop + its autodiff transpose, a scheduled plan's up + down
# wires inside the one table-replayed tick body. The pin is the
# table-driven-replay contract itself — an unrolled schedule (or a
# per-tick lax.switch lowering) would multiply this count by the tick
# count.
PLAN_WIRE_PPERMUTES = {"gpipe": 2, "1f1b": 2, "interleaved": 2}


@rule(
    id="plan-wire-fabric", severity="error", source="ISSUE 19",
    contract=(
        "A composed plan's pipeline wire rides the stage fabric (the "
        "plan mesh's DCN contract) and nothing else: every "
        "`plan_wire`-scoped collective in the traced step is a "
        "ppermute over exactly ('stage',), and a pp>1 plan traces "
        "the schedule's exact static count (PLAN_WIRE_PPERMUTES: "
        "gpipe = forward + transpose; 1f1b/interleaved = the tick "
        "table's up + down wires) — more means the schedule unrolled "
        "instead of replaying its table."
    ),
    applies=lambda t: t.engine == "plan",
)
def _plan_wire_fabric(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    axes_of = dict(t.plan_axes)
    wire = [
        r for r in t.plan_collective_records
        if _scope_word("plan_wire", r[3])
    ]
    if axes_of.get("stage", 1) > 1 and not wire:
        return [ctx.finding(
            "plan-wire-fabric",
            "no plan_wire-scoped collectives traced on a pp>1 plan — "
            "the wire pin was not checked",
        )]
    out = []
    for prim, axes, dt, scope, elems in wire:
        if prim != "ppermute" or tuple(axes) != ("stage",):
            out.append(ctx.finding(
                "plan-wire-fabric",
                f"plan_wire {prim} over {tuple(axes)} ({elems} x "
                f"{dt}, scope {scope!r}) — the activation wire is a "
                "ppermute over ('stage',) only",
            ))
    expected = PLAN_WIRE_PPERMUTES.get(t.plan_schedule)
    if (axes_of.get("stage", 1) > 1 and expected is not None
            and len(wire) != expected):
        out.append(ctx.finding(
            "plan-wire-fabric",
            f"{len(wire)} plan_wire ppermute(s) traced under the "
            f"{t.plan_schedule!r} schedule — the tick program pins "
            f"exactly {expected} (table-driven replay, not an "
            "unrolled per-tick program)",
        ))
    return out


@rule(
    id="plan-seq-fabric", severity="error", source="ISSUE 19",
    contract=(
        "A composed plan keeps its sequence-axis rings on the ICI "
        "fabric: every kv_ring / ag_matmul / matmul_rs-scoped "
        "collective rides exactly ('seq',) — never 'stage' or "
        "'data' — and an sp>1 ring-attention plan must trace at "
        "least one kv_ring hop."
    ),
    applies=lambda t: t.engine == "plan",
)
def _plan_seq_fabric(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    axes_of = dict(t.plan_axes)
    rings = [
        r for r in t.plan_collective_records
        if any(_scope_word(w, r[3]) for w in PLAN_SEQ_SCOPE_WORDS)
    ]
    if axes_of.get("seq", 1) > 1 and not rings:
        return [ctx.finding(
            "plan-seq-fabric",
            "no seq-ring collectives traced on an sp>1 plan — the "
            "ring pin was not checked",
        )]
    out = []
    for prim, axes, dt, scope, elems in rings:
        if tuple(axes) != ("seq",):
            out.append(ctx.finding(
                "plan-seq-fabric",
                f"seq-ring {prim} (scope {scope!r}) over "
                f"{tuple(axes)} — the rings ride ('seq',) only",
            ))
    return out


@rule(
    id="plan-grad-fabric", severity="error", source="ISSUE 19",
    contract=(
        "A composed plan reduces gradients as ONE fused psum over "
        "the full ('stage', 'data', 'seq') tuple under the "
        "`plan_grad` scope (complementary stage pieces + seq "
        "partials + data replicas in a single rendezvous — never a "
        "per-axis cascade), and the FSDP weight materialization — "
        "when the plan shards — is `plan_fsdp_gather`-scoped "
        "all-gathers over ('data',) only."
    ),
    applies=lambda t: t.engine == "plan",
)
def _plan_grad_fabric(ctx: LintContext) -> List[Finding]:
    t = ctx.target
    grads = [
        r for r in t.plan_collective_records
        if _scope_word("plan_grad", r[3])
    ]
    if not grads:
        return [ctx.finding(
            "plan-grad-fabric",
            "no plan_grad-scoped collectives traced — the "
            "fused-reduction pin was not checked",
        )]
    out = []
    for prim, axes, dt, scope, elems in grads:
        if prim != "psum" or tuple(sorted(axes)) != (
            "data", "seq", "stage"
        ):
            out.append(ctx.finding(
                "plan-grad-fabric",
                f"plan_grad {prim} over {tuple(axes)} — the gradient "
                "reduction is one fused psum over "
                "('stage', 'data', 'seq')",
            ))
    for prim, axes, dt, scope, elems in t.plan_collective_records:
        if not _scope_word("plan_fsdp_gather", scope):
            continue
        if prim != "all_gather" or tuple(axes) != ("data",):
            out.append(ctx.finding(
                "plan-grad-fabric",
                f"plan_fsdp_gather {prim} over {tuple(axes)} — the "
                "ZeRO-3 weight gather rides ('data',) only",
            ))
    return out


__all__ = [
    "Finding",
    "LintContext",
    "LintTarget",
    "REGISTRY",
    "Rule",
    "rule",
    "run_rules",
]
