"""64-way structural scaling evidence (BASELINE.json north star:
>=90% weak-scaling efficiency at 64 chips).

This host has ONE real chip, so the evidence is structural + modeled:

1. Lower the ResNet-50 DDP train step on a 64-device virtual mesh and
   read the collective structure out of the StableHLO: every gradient
   leaf's all-reduce, with its byte count (static truth about what the
   program asks the network for).
2. Compile (XLA optimization pipeline, 64-way) the SAME ResNet-50 step
   and capture ITS OWN post-optimization all-reduce op count and bytes
   (VERDICT r5 weak #2: previously only tinycnn's optimized HLO was
   inspected and the fused-schedule shape was extrapolated from it —
   the flagship model's own compile is what the cost model must eat).
   The tinycnn compile+run stays as a cheap liveness check of the
   64-way program.
3. Feed ResNet-50's own post-optimization all-reduce bytes (and op
   count, via an alpha-beta ring model) plus the measured single-chip
   step time (BENCH_r*) and the public v5e ICI bandwidth into the
   standard ring all-reduce cost model to predict weak-scaling
   efficiency at 64 chips — both for this backend's unfused lowering
   and for a bucketed one.

Writes experiments/scaling64.json; summarized in RESULTS.md §3.

Run: python experiments/scaling64.py   (CPU-only, no TPU dial)
"""

from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_model_parallel_tpu.runtime.platform import force_cpu  # noqa: E402

force_cpu(64)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from distributed_model_parallel_tpu.models.resnet import resnet50  # noqa: E402
from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn  # noqa: E402
from distributed_model_parallel_tpu.observability import cost  # noqa: E402
from distributed_model_parallel_tpu.parallel.data_parallel import (  # noqa: E402
    DDPEngine,
)
from distributed_model_parallel_tpu.runtime.mesh import (  # noqa: E402
    MeshSpec,
    make_mesh,
)
from distributed_model_parallel_tpu.training.optim import SGD  # noqa: E402

N = 64
PER_CHIP_BATCH = 256

# Measured on the one real chip (BENCH_r04 / RESULTS.md §1): ResNet-50
# bs256 bf16, 2489 img/s/chip -> 0.1029 s/step, MFU 0.30.
MEASURED_STEP_S = 256 / 2489.0
# Per-fabric alpha/beta constants: ONE home, shared with the static
# cost engine (`observability/cost.py` — provenance documented there),
# so this script's hand-derived rows and the checked `tools/costgate`
# ledger can never drift apart. Each §3 row below is ASSERTED against
# the cost engine's closed-form prediction within 1%
# (`_assert_cost_engine_agrees`).
BW_ICI_EFFECTIVE = cost.BW_ICI_EFFECTIVE
ALPHA_HOP_S = cost.ALPHA_HOP_S
BW_DCN_EFFECTIVE = cost.BW_DCN_EFFECTIVE
ALPHA_DCN_HOP_S = cost.ALPHA_DCN_HOP_S
# Two-level (dcn × ici) hierarchy for the bucketed reducer
# (`ops/grad_reduction.py`): a 64-chip job as 2 slices × 32 chips.
DCN_SLICES = 2
BUCKET_MB = 25.0  # the reducer's default bucket_cap_mb
# MoE dispatch (step 3c): one routed layer's token exchange, sized for
# a GPT-MoE block — per-chip token load, model dim, top-k routing with
# the Switch capacity factor. The dispatch buffer each device must
# exchange is ~top_k * capacity_factor * tokens * dim bytes.
MOE_TOKENS_PER_CHIP = 4096   # B*T tokens resident per chip
MOE_DIM = 1024
MOE_TOP_K = 2
MOE_CAPACITY_FACTOR = 1.25
MOE_FFN_HIDDEN = 4 * MOE_DIM
# Per-chip dense-equivalent MXU throughput for hiding the exchange
# (peak bf16 ~197 TF/s on v5e at a conservative 0.3 MFU).
MOE_EFFECTIVE_FLOPS = 197e12 * 0.3


def optimized_all_reduce_bytes(text):
    """(op count, total reduced bytes) from POST-OPTIMIZATION HLO text.
    The op's OUTPUT shape(s) lead its definition — `%all-reduce.N =
    f32[1,1,256,1024]{3,2,1,0} all-reduce(...)`, or a parenthesized
    tuple for fused/async variants — so parse the text between '=' and
    the op name. `-done` ops are excluded (they'd double-count their
    `-start`), and an async `-start` op's tuple shape is (aliased
    operands, results), so only HALF its listed buffers are reduced
    bytes — counting both would double the beta term."""
    dt_bytes = {"f32": 4, "bf16": 2, "f16": 2, "f64": 8, "s32": 4,
                "u32": 4, "pred": 1}
    n_ops = 0
    total_bytes = 0
    for m in re.finditer(
        r"=\s*((?:\([^)]*\))|(?:\S+))\s+all-reduce(-start)?\(", text
    ):
        n_ops += 1
        op_bytes = 0
        for dt, dims in re.findall(r"([a-z0-9]+)\[([0-9,]*)\]",
                                   m.group(1)):
            nelems = 1
            for d in dims.split(","):
                if d:
                    nelems *= int(d)
            op_bytes += nelems * dt_bytes.get(dt, 4)
        if m.group(2) and m.group(1).startswith("("):
            op_bytes //= 2  # (operands, results) alias tuple
        total_bytes += op_bytes
    return n_ops, total_bytes


def stablehlo_all_reduce_bytes(text):
    """(op count, total reduced bytes) from StableHLO text. The op's
    operand signature `: (tensor<...>) -> ...` trails the (multi-line)
    reducer region, so scan from each op start to its signature."""
    dt_bytes = {"f32": 4, "bf16": 2, "f16": 2, "f64": 8, "i32": 4}
    n_ops = 0
    total_bytes = 0
    sig = re.compile(r":\s*\(tensor<([^>]+)>\)")
    for m in re.finditer(r'"?stablehlo\.all_reduce"?', text):
        s = sig.search(text, m.end())
        if not s:
            continue
        n_ops += 1
        dims = s.group(1).split("x")
        nelems = 1
        for d in dims[:-1]:
            if d.isdigit():
                nelems *= int(d)
        total_bytes += nelems * dt_bytes.get(dims[-1], 4)
    return n_ops, total_bytes


def _assert_cost_engine_agrees(label, hand_s, engine_s):
    """The drift tripwire: a §3 row's hand arithmetic and the cost
    engine's closed-form prediction must agree within 1% — edit one
    without the other and this script fails, not the prose."""
    if abs(hand_s - engine_s) > 0.01 * max(abs(hand_s), 1e-12):
        raise AssertionError(
            f"{label}: hand-derived {hand_s:.6e}s disagrees with the "
            f"cost engine's {engine_s:.6e}s by more than 1% — "
            "observability/cost.py and scaling64.py drifted"
        )


def main():
    mesh = make_mesh(MeshSpec(data=N))
    assert mesh.shape["data"] == N

    # ---- 1. ResNet-50 DDP: lower (SPMD trace) and read the asks ------
    eng = DDPEngine(
        resnet50(1000), SGD(momentum=0.9), mesh,
        compute_dtype=jnp.bfloat16, donate=False,
    )
    state_aval = jax.eval_shape(eng.init_state, jax.ShapeDtypeStruct(
        (2,), jnp.uint32))
    n_params = sum(
        int(np.prod(l.shape))
        for l in jax.tree_util.tree_leaves(state_aval.params)
    )
    imgs = jax.ShapeDtypeStruct((N * PER_CHIP_BATCH, 224, 224, 3),
                                jnp.float32)
    lbls = jax.ShapeDtypeStruct((N * PER_CHIP_BATCH,), jnp.int32)
    lowered = eng.train_step.lower(
        state_aval, imgs, lbls, jax.ShapeDtypeStruct((), jnp.float32)
    )
    text = lowered.as_text()
    n_ar, ar_bytes = stablehlo_all_reduce_bytes(text)
    grad_bytes_f32 = n_params * 4
    print(f"ResNet-50 params: {n_params/1e6:.1f} M "
          f"({grad_bytes_f32/1e6:.1f} MB f32 grads)")
    print(f"StableHLO all_reduce ops: {n_ar}, reduced bytes: "
          f"{ar_bytes/1e6:.1f} MB")

    # ---- 2. ResNet-50's OWN 64-way post-optimization collectives -----
    # (compile-only: ~20 s on this host; nothing executes). The op
    # count/bytes feeding the cost model now come from the flagship
    # model's own optimized program instead of a tinycnn extrapolation.
    rn_compiled = lowered.compile()
    n_opt_ar, opt_ar_bytes = optimized_all_reduce_bytes(
        rn_compiled.as_text()
    )
    print(f"ResNet-50 64-way optimized HLO: {n_opt_ar} all-reduce ops, "
          f"{opt_ar_bytes/1e6:.1f} MB reduced "
          f"(combiner {'ran' if n_opt_ar < n_ar else 'did NOT run'} on "
          f"this backend)")

    # ---- 2b. tinycnn 64-way compile + ONE real step: liveness check --
    small = DDPEngine(tiny_cnn(10), SGD(), mesh, donate=False)
    ts = small.init_state(jax.random.PRNGKey(0))
    x = np.random.RandomState(0).rand(N * 4, 8, 8, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, N * 4).astype(np.int32)
    xs, ys = small.shard_batch(x, y)
    compiled = small.train_step.lower(
        ts, xs, ys, jnp.float32(0.1)
    ).compile()
    n_small_ar, _ = optimized_all_reduce_bytes(compiled.as_text())
    small_leaves = len(jax.tree_util.tree_leaves(ts.params))
    ts2, m = compiled(ts, xs, ys, jnp.float32(0.1))
    loss0 = float(m["loss_sum"]) / float(m["count"])
    print(f"tinycnn 64-way liveness: {small_leaves} grad leaves -> "
          f"{n_small_ar} optimized all-reduce ops; one step ran, "
          f"loss {loss0:.3f}")

    # ---- 3. ring all-reduce cost model on the MEASURED lowering ------
    # Ring all-reduce moves 2*(N-1)/N * bytes per chip (beta term) and
    # pays 2*(N-1) latency hops PER OP (alpha term) — the alpha term
    # only matters because step 2 shows this backend keeps ResNet-50's
    # per-leaf reduces unfused. XLA overlaps comm with the remaining
    # backward, so bound both ends: zero overlap (worst) and full
    # overlap (best ~= max(compute, comm)). The bucketed-bound row is
    # the same bytes in ONE fused op — the TPU pipeline's all-reduce
    # combiner / the DDP Reducer's bucketing — since this CPU backend's
    # unfused lowering is a backend artifact, not a program property
    # (the StableHLO asks are identical).
    beta_s = 2 * (N - 1) / N * opt_ar_bytes / BW_ICI_EFFECTIVE
    alpha_s = n_opt_ar * 2 * (N - 1) * ALPHA_HOP_S
    alpha_bucketed_s = 1 * 2 * (N - 1) * ALPHA_HOP_S
    comm_s = beta_s + alpha_s
    comm_bucketed_s = beta_s + alpha_bucketed_s
    eff_no_overlap = MEASURED_STEP_S / (MEASURED_STEP_S + comm_s)
    eff_overlap = MEASURED_STEP_S / max(MEASURED_STEP_S, comm_s)
    eff_bucketed = MEASURED_STEP_S / (MEASURED_STEP_S + comm_bucketed_s)
    print(f"ring all-reduce (as lowered, {n_opt_ar} ops): "
          f"{beta_s*1e3:.2f} ms bandwidth + {alpha_s*1e3:.2f} ms "
          f"latency vs step {MEASURED_STEP_S*1e3:.1f} ms")
    print(f"predicted weak-scaling efficiency @64: "
          f"{eff_no_overlap:.3f} (no overlap, as lowered) .. "
          f"{eff_overlap:.3f} (full overlap); "
          f"{eff_bucketed:.3f} (no overlap, bucketed)")
    _assert_cost_engine_agrees(
        "ring all-reduce (as lowered)", comm_s,
        cost.ring_all_reduce_s(opt_ar_bytes, N, n_ops=n_opt_ar),
    )
    _assert_cost_engine_agrees(
        "ring all-reduce (bucketed)", comm_bucketed_s,
        cost.ring_all_reduce_s(opt_ar_bytes, N, n_ops=1),
    )

    # ---- 3b. two-level alpha-beta: the hierarchical bucketed reducer -
    # 64 chips as DCN_SLICES slices × ici chips. A FLAT 64-ring would
    # push the full gradient through the slice boundary at DCN
    # bandwidth (its slowest link gates the ring); the hierarchical
    # reducer (`ops/grad_reduction.py` — reduce-scatter over 'ici',
    # all-reduce of the 1/ici shard over 'dcn', all-gather back) keeps
    # the DCN bytes at 1/ici of the payload. Alpha counts per-bucket
    # hops (the Reducer's ~25 MB buckets), each fabric at its own hop
    # cost.
    ici = N // DCN_SLICES
    n_buckets = max(1, -(-opt_ar_bytes // int(BUCKET_MB * 2**20)))
    beta_flat_dcn_s = 2 * (N - 1) / N * opt_ar_bytes / BW_DCN_EFFECTIVE
    comm_flat_dcn_s = beta_flat_dcn_s + alpha_bucketed_s
    beta_two_level_s = (
        2 * (ici - 1) / ici * opt_ar_bytes / BW_ICI_EFFECTIVE
        + 2 * (DCN_SLICES - 1) / DCN_SLICES
        * (opt_ar_bytes / ici) / BW_DCN_EFFECTIVE
    )
    alpha_two_level_s = n_buckets * (
        2 * (ici - 1) * ALPHA_HOP_S
        + 2 * (DCN_SLICES - 1) * ALPHA_DCN_HOP_S
    )
    comm_two_level_s = beta_two_level_s + alpha_two_level_s
    eff_flat_dcn = MEASURED_STEP_S / (MEASURED_STEP_S + comm_flat_dcn_s)
    eff_two_level = MEASURED_STEP_S / (
        MEASURED_STEP_S + comm_two_level_s
    )
    eff_two_level_overlap = MEASURED_STEP_S / max(
        MEASURED_STEP_S, comm_two_level_s
    )
    print(f"two-level ({DCN_SLICES}x{ici} dcn*ici, {n_buckets} buckets "
          f"of {BUCKET_MB:.0f} MB): {beta_two_level_s*1e3:.2f} ms "
          f"bandwidth + {alpha_two_level_s*1e3:.2f} ms latency "
          f"(flat ring gated by DCN: {beta_flat_dcn_s*1e3:.2f} ms)")
    print(f"predicted weak-scaling efficiency @64 across 2 slices: "
          f"{eff_flat_dcn:.3f} (flat ring over DCN) -> "
          f"{eff_two_level:.3f} (hierarchical bucketed, no overlap) .. "
          f"{eff_two_level_overlap:.3f} (full overlap)")
    _assert_cost_engine_agrees(
        "flat ring over dcn", comm_flat_dcn_s,
        cost.ring_all_reduce_s(
            opt_ar_bytes, N, n_ops=1, bw=BW_DCN_EFFECTIVE
        ),
    )
    _assert_cost_engine_agrees(
        "two-level bucketed reducer", comm_two_level_s,
        cost.two_level_all_reduce_s(
            opt_ar_bytes, ici, DCN_SLICES, n_buckets=n_buckets
        ),
    )

    # ---- 3b'. compressed 'dcn' wire on the bucketed reducer ----------
    # (`ops/wire_codec.py`, PR 11). The intra-slice legs stay f32; only
    # the cross-slice term scales with the wire itemsize. int8 adds one
    # f32 scale sidecar per hop (4 B x 2(K-1) x n_buckets — noise) and
    # one extra tiny ppermute per payload hop (counted in alpha).
    dcn_beta_f32_s = (
        2 * (DCN_SLICES - 1) / DCN_SLICES
        * (opt_ar_bytes / ici) / BW_DCN_EFFECTIVE
    )
    wire_rows = {}
    for wire, wbytes, sidecar_hops in (
        ("bf16", 2, 0), ("int8", 1, 1)
    ):
        dcn_beta_wire_s = dcn_beta_f32_s * wbytes / 4
        beta_wire_s = (
            2 * (ici - 1) / ici * opt_ar_bytes / BW_ICI_EFFECTIVE
            + dcn_beta_wire_s
        )
        alpha_wire_s = n_buckets * (
            2 * (ici - 1) * ALPHA_HOP_S
            + (1 + sidecar_hops) * 2 * (DCN_SLICES - 1)
            * ALPHA_DCN_HOP_S
        )
        comm_wire_s = beta_wire_s + alpha_wire_s
        eff_wire = MEASURED_STEP_S / (MEASURED_STEP_S + comm_wire_s)
        wire_rows[wire] = dict(
            dcn_beta_s=round(dcn_beta_wire_s, 6),
            comm_s=round(comm_wire_s, 6),
            eff=round(eff_wire, 4),
        )
        print(f"compressed grad wire ({wire}): dcn leg "
              f"{dcn_beta_f32_s*1e3:.2f} -> {dcn_beta_wire_s*1e3:.2f} "
              f"ms, total comm {comm_wire_s*1e3:.2f} ms, "
              f"efficiency {eff_wire:.3f} (f32 hierarchical: "
              f"{eff_two_level:.3f})")
        _assert_cost_engine_agrees(
            f"compressed grad wire ({wire})", comm_wire_s,
            cost.two_level_all_reduce_s(
                opt_ar_bytes, ici, DCN_SLICES, n_buckets=n_buckets,
                wire=wire,
            ),
        )

    # ---- 3c. two-level a2a: the hierarchical MoE token exchange ------
    # One routed layer's dispatch+combine at 64 chips as DCN_SLICES x
    # ici (`ops/expert_dispatch.py`). The FLAT all-to-all sends each of
    # the S-1 partners X/S bytes: (K-1)*ici of those messages cross the
    # slice boundary — the alpha term pays (K-1)*ici DCN hops and the
    # full (K-1)/K of the payload rides DCN. The HIERARCHICAL exchange
    # moves the same cross-slice bytes (tokens must cross) but as K-1
    # contiguous messages of the 1/ici-regrouped shard — ici x fewer
    # DCN hops — and the (ici-1)/ici intra-slice share rides ICI
    # exclusively. OVERLAPPED additionally hides the exchange behind
    # the per-chunk expert FFN (the chunked ppermute decomposition).
    moe_x_elems = int(
        MOE_TOP_K * MOE_CAPACITY_FACTOR * MOE_TOKENS_PER_CHIP * MOE_DIM
    )
    moe_x_bytes = moe_x_elems * 2  # bf16 activations (the §3c shape)
    # per-exchange (dispatch or combine), per device:
    a2a_flat_s = (
        (DCN_SLICES - 1) / DCN_SLICES * moe_x_bytes / BW_DCN_EFFECTIVE
        + (ici - 1) / N * moe_x_bytes / BW_ICI_EFFECTIVE
        + (DCN_SLICES - 1) * ici * ALPHA_DCN_HOP_S
        + (ici - 1) * ALPHA_HOP_S
    )
    a2a_two_level_s = (
        (DCN_SLICES - 1) / DCN_SLICES * moe_x_bytes / BW_DCN_EFFECTIVE
        + (ici - 1) / ici * moe_x_bytes / BW_ICI_EFFECTIVE
        + (DCN_SLICES - 1) * ALPHA_DCN_HOP_S
        + (ici - 1) * ALPHA_HOP_S
    )
    # Expert FFN compute available to hide behind (per device, all its
    # routed tokens through the two dense matmuls):
    moe_ffn_flops = (
        4 * MOE_TOP_K * MOE_CAPACITY_FACTOR * MOE_TOKENS_PER_CHIP
        * MOE_DIM * MOE_FFN_HIDDEN
    )
    moe_ffn_s = moe_ffn_flops / MOE_EFFECTIVE_FLOPS
    moe_layer_flat_s = 2 * a2a_flat_s + moe_ffn_s
    moe_layer_two_level_s = 2 * a2a_two_level_s + moe_ffn_s
    moe_layer_overlap_s = max(2 * a2a_two_level_s, moe_ffn_s)
    print(f"MoE a2a ({DCN_SLICES}x{ici} dcn*ici, "
          f"{moe_x_bytes/1e6:.1f} MB dispatch buffer/chip): "
          f"flat {a2a_flat_s*1e3:.2f} ms/exchange "
          f"({(DCN_SLICES-1)*ici} DCN hops) -> two-level "
          f"{a2a_two_level_s*1e3:.2f} ms ({DCN_SLICES-1} DCN hop)")
    _assert_cost_engine_agrees(
        "MoE flat a2a", a2a_flat_s,
        cost.flat_all_to_all_s(moe_x_elems, 2, ici, DCN_SLICES),
    )
    _assert_cost_engine_agrees(
        "MoE two-level a2a", a2a_two_level_s,
        cost.hierarchical_all_to_all_s(
            moe_x_elems, 2, ici, DCN_SLICES
        ),
    )
    print(f"per MoE layer (2 exchanges + FFN {moe_ffn_s*1e3:.2f} ms): "
          f"flat {moe_layer_flat_s*1e3:.2f} ms, hierarchical "
          f"{moe_layer_two_level_s*1e3:.2f} ms, overlapped "
          f"{moe_layer_overlap_s*1e3:.2f} ms "
          f"(exchange {'hidden' if moe_ffn_s >= 2*a2a_two_level_s else 'exposed'})")

    # ---- 3c'. compressed 'dcn' wire on the MoE dispatch --------------
    # The intra-slice regroup stays at the activation dtype (bf16
    # here); only the cross-slice messages scale with the wire
    # itemsize. f32 is the uncompressed worst case (f32 activations,
    # no codec); int8 quarters it.
    moe_wire_rows = {}
    for wire, wbytes in (("f32", 4), ("bf16", 2), ("int8", 1)):
        dcn_leg_s = (
            (DCN_SLICES - 1) / DCN_SLICES
            * (moe_x_elems * wbytes) / BW_DCN_EFFECTIVE
        )
        a2a_wire_s = (
            dcn_leg_s
            + (ici - 1) / ici * moe_x_bytes / BW_ICI_EFFECTIVE
            + (DCN_SLICES - 1) * ALPHA_DCN_HOP_S
            + (ici - 1) * ALPHA_HOP_S
        )
        layer_s = 2 * a2a_wire_s + moe_ffn_s
        layer_overlap_s = max(2 * a2a_wire_s, moe_ffn_s)
        moe_wire_rows[wire] = dict(
            a2a_s=round(a2a_wire_s, 6),
            layer_s=round(layer_s, 6),
            layer_overlapped_s=round(layer_overlap_s, 6),
        )
        print(f"compressed dispatch wire ({wire}): "
              f"{a2a_wire_s*1e3:.2f} ms/exchange, per layer "
              f"{layer_s*1e3:.2f} ms unfused / "
              f"{layer_overlap_s*1e3:.2f} ms overlapped")
        _assert_cost_engine_agrees(
            f"compressed dispatch wire ({wire})", a2a_wire_s,
            cost.hierarchical_all_to_all_s(
                moe_x_elems, 2, ici, DCN_SLICES, wire=wire
            ),
        )

    # ---- 3e. tuner argmin rows (tuning/search.py closed forms) -------
    # The auto-tuner's answer for this @64 2x32 cell, next to the
    # hand-picked §3a-§3d rows: enumerate each family's knob space and
    # score with the SAME closed forms the rows above assert against.
    # The hand configurations are points IN the searched space, so the
    # argmin can never predict WORSE than them — asserted, like the
    # cost-engine agreement tripwire.
    from distributed_model_parallel_tpu.tuning.search import (
        closed_form_argmin,
    )

    grad_knobs, grad_argmin_s = closed_form_argmin(
        "ddp",
        {"grad_bytes": opt_ar_bytes, "n_blocks": 16},
        ici, DCN_SLICES,
    )
    print(f"tuner argmin (grad reduction @{DCN_SLICES}x{ici}): "
          f"{json.dumps(grad_knobs, sort_keys=True)} -> "
          f"{grad_argmin_s*1e3:.2f} ms (hand §3b bucketed row: "
          f"{comm_two_level_s*1e3:.2f} ms)")
    assert grad_argmin_s <= comm_two_level_s * (1 + 1e-9), (
        f"tuner argmin {grad_argmin_s:.6e}s predicts WORSE than the "
        f"hand §3b configuration {comm_two_level_s:.6e}s — the hand "
        "config is in the search space, so the search is broken"
    )
    moe_knobs, moe_argmin_s = closed_form_argmin(
        "ep",
        {"elems": moe_x_elems, "itemsize": 2},
        ici, DCN_SLICES,
    )
    moe_hand_pair_s = 2 * a2a_two_level_s  # §3c dispatch+combine
    print(f"tuner argmin (MoE dispatch @{DCN_SLICES}x{ici}): "
          f"{json.dumps(moe_knobs, sort_keys=True)} -> "
          f"{moe_argmin_s*1e3:.2f} ms/exchange pair (hand §3c "
          f"hierarchical pair: {moe_hand_pair_s*1e3:.2f} ms)")
    assert moe_argmin_s <= moe_hand_pair_s * (1 + 1e-9), (
        f"tuner argmin {moe_argmin_s:.6e}s predicts WORSE than the "
        f"hand §3c configuration {moe_hand_pair_s:.6e}s — the hand "
        "config is in the search space, so the search is broken"
    )
    tuned_rows = {
        "grad_reduction": {
            "knobs": grad_knobs,
            "predicted_s": round(grad_argmin_s, 6),
            "hand_two_level_s": round(comm_two_level_s, 6),
        },
        "moe_dispatch": {
            "knobs": moe_knobs,
            "predicted_exchange_pair_s": round(moe_argmin_s, 6),
            "hand_exchange_pair_s": round(moe_hand_pair_s, 6),
        },
    }

    # ---- 3f. composed-plan rows (ISSUE 19, parallel/plan.py) ---------
    # The plan family searches WHOLE mesh factorizations of the same
    # 2x32 fabric: a GPT-XL-ish training step (dim 1024, 16 layers,
    # vocab 32k, seq 2048, 8-row microbatches) under
    # `cost.composed_plan_step_s` — gpipe wire ticks across 'dcn',
    # ring-attention KV hops on 'ici', ONE fused gradient psum priced
    # as §3b's two-level form. The single-axis degenerate specs (dp64,
    # fsdp64, pp64) are points IN the plan space, so the tuner's
    # argmin can never predict worse than the best of them — asserted
    # like the §3e rows. NOTE the closed forms price what the program
    # ASKS THE NETWORK for (no compute/memory term), so pure-dp
    # factorizations — whose only collective is the fused psum —
    # structurally dominate at this payload; the anatomy rows record
    # what each added axis COSTS in asked bytes, which is the real
    # content of the comparison (pp/sp buy memory headroom the model
    # doesn't price).
    from distributed_model_parallel_tpu.tuning.search import (
        closed_form_step_s,
    )

    PLAN_DIM = 1024
    PLAN_VOCAB = 32768
    PLAN_LAYERS = 16
    PLAN_SEQ = 2048
    PLAN_MB = 8
    # ~12 D^2 per decoder block (QKV+proj 4D^2, FFN pair 8D^2) plus
    # the tied embedding/head table.
    plan_grad_bytes = (
        PLAN_LAYERS * 12 * PLAN_DIM * PLAN_DIM
        + PLAN_VOCAB * PLAN_DIM
    ) * 4
    plan_payload = {
        "grad_bytes": plan_grad_bytes, "mb": PLAN_MB,
        "seq_len": PLAN_SEQ, "dim": PLAN_DIM, "vocab": PLAN_VOCAB,
        "n_layers": PLAN_LAYERS,
    }
    plan_knobs, plan_argmin_s = closed_form_argmin(
        "plan", plan_payload, ici, DCN_SLICES,
    )
    # Hand dp64 row: the dp-only composed plan's one collective is the
    # fused psum over all 64 devices — at 2 slices the hierarchical
    # decomposition IS §3b's two-level form at one bucket.
    hand_dp64_s = cost.two_level_all_reduce_s(
        plan_grad_bytes, ici, DCN_SLICES, n_buckets=1
    )
    _assert_cost_engine_agrees(
        "composed-plan dp64 fused psum", hand_dp64_s,
        closed_form_step_s(
            "plan", {"plan": "dp64"}, plan_payload, ici, DCN_SLICES
        ),
    )
    plan_single_axis = {}
    for spec in ("dp64", "fsdp64", "pp64"):
        s = closed_form_step_s(
            "plan", {"plan": spec}, plan_payload, ici, DCN_SLICES
        )
        plan_single_axis[spec] = round(s, 6)
        assert plan_argmin_s <= s * (1 + 1e-9), (
            f"plan-family argmin {plan_argmin_s:.6e}s predicts WORSE "
            f"than the single-axis plan {spec} at {s:.6e}s — "
            "single-axis specs are in the plan space, so the search "
            "is broken"
        )
    # Anatomy: what each composed axis ADDS on top of the fused psum.
    plan_anatomy = {
        spec: round(closed_form_step_s(
            "plan", {"plan": spec}, plan_payload, ici, DCN_SLICES
        ), 6)
        for spec in ("pp2xdp32", "sp2xdp32", "pp2xsp2xdp16",
                     "pp2xsp2xfsdp16")
    }
    print(f"tuner argmin (composed plan @{DCN_SLICES}x{ici}): "
          f"{json.dumps(plan_knobs, sort_keys=True)} -> "
          f"{plan_argmin_s*1e3:.2f} ms (best single-axis: "
          f"{min(plan_single_axis.values())*1e3:.2f} ms; composed "
          f"pp2xsp2xdp16: {plan_anatomy['pp2xsp2xdp16']*1e3:.2f} ms)")
    # Scheduled-plan rows (ISSUE 20): the gpipe/1f1b/int2 twins of
    # ONE pp2 factorization at fixed M=4, priced with the compute x
    # bubble fold ('params' in the payload turns it on) ON TOP of the
    # asked-bytes wire terms. The schedule changes only the tick
    # program, so the twins share layouts and collectives; the rows
    # record what each schedule's bubble costs (gpipe/1f1b (M+pp-1)/M,
    # interleaved (VM+pp-1)/VM) against its extra wire ticks. The
    # gpipe plan is a POINT in the scheduled space, so the argmin
    # over the grown space can never predict worse than it — the
    # never-worse-than-gpipe assertion, like §3e's.
    plan_sched_payload = dict(plan_payload, params=plan_grad_bytes // 4)
    plan_sched = {}
    for spec in ("pp2xdp32", "pp2-1f1bxdp32", "pp2-int2xdp32"):
        plan_sched[spec] = round(closed_form_step_s(
            "plan", {"plan": spec, "num_microbatches": 4},
            plan_sched_payload, ici, DCN_SLICES,
        ), 6)
    sched_knobs, sched_argmin_s = closed_form_argmin(
        "plan", plan_sched_payload, ici, DCN_SLICES,
    )
    assert sched_argmin_s <= plan_sched["pp2xdp32"] * (1 + 1e-9), (
        f"scheduled-plan argmin {sched_argmin_s:.6e}s predicts WORSE "
        f"than the gpipe pp2xdp32/M4 row "
        f"{plan_sched['pp2xdp32']:.6e}s — the gpipe plan is a point "
        "in the scheduled space, so the search is broken"
    )
    print(f"tuner argmin (scheduled plan @{DCN_SLICES}x{ici}, with "
          f"compute fold): {json.dumps(sched_knobs, sort_keys=True)} "
          f"-> {sched_argmin_s*1e3:.2f} ms (gpipe twin @M4: "
          f"{plan_sched['pp2xdp32']*1e3:.2f} ms, 1f1b: "
          f"{plan_sched['pp2-1f1bxdp32']*1e3:.2f} ms, int2: "
          f"{plan_sched['pp2-int2xdp32']*1e3:.2f} ms)")
    plan_rows = {
        "payload": plan_payload,
        "argmin": {
            "knobs": plan_knobs,
            "predicted_s": round(plan_argmin_s, 6),
        },
        "single_axis_s": plan_single_axis,
        "composed_anatomy_s": plan_anatomy,
        "scheduled_twins_s": plan_sched,
        "scheduled_argmin": {
            "knobs": sched_knobs,
            "predicted_s": round(sched_argmin_s, 6),
        },
    }

    out = {
        "n_devices": N,
        "per_chip_batch": PER_CHIP_BATCH,
        "model": "resnet50",
        "params_m": round(n_params / 1e6, 2),
        "grad_bytes_f32": grad_bytes_f32,
        "stablehlo_all_reduce_ops": n_ar,
        "stablehlo_all_reduce_bytes": ar_bytes,
        "resnet50_optimized_all_reduce_ops": n_opt_ar,
        "resnet50_optimized_all_reduce_bytes": opt_ar_bytes,
        "tinycnn_grad_leaves": small_leaves,
        "tinycnn_optimized_all_reduce_ops": n_small_ar,
        "tinycnn_64way_step_loss": loss0,
        "measured_step_s_1chip": round(MEASURED_STEP_S, 5),
        "ici_bw_effective_bytes_per_s": BW_ICI_EFFECTIVE,
        "alpha_hop_s": ALPHA_HOP_S,
        "ring_allreduce_beta_s": round(beta_s, 6),
        "ring_allreduce_alpha_s": round(alpha_s, 6),
        "ring_allreduce_s": round(comm_s, 6),
        "predicted_weak_scaling_eff_64_no_overlap": round(
            eff_no_overlap, 4),
        "predicted_weak_scaling_eff_64_full_overlap": round(
            eff_overlap, 4),
        "predicted_weak_scaling_eff_64_bucketed_no_overlap": round(
            eff_bucketed, 4),
        # two-level (dcn × ici) hierarchical bucketed reducer row
        "dcn_slices": DCN_SLICES,
        "dcn_bw_effective_bytes_per_s": BW_DCN_EFFECTIVE,
        "alpha_dcn_hop_s": ALPHA_DCN_HOP_S,
        "bucket_mb": BUCKET_MB,
        "n_buckets": int(n_buckets),
        "ring_allreduce_flat_over_dcn_s": round(comm_flat_dcn_s, 6),
        "two_level_beta_s": round(beta_two_level_s, 6),
        "two_level_alpha_s": round(alpha_two_level_s, 6),
        "two_level_s": round(comm_two_level_s, 6),
        "predicted_weak_scaling_eff_64_2slice_flat_ring": round(
            eff_flat_dcn, 4),
        "predicted_weak_scaling_eff_64_2slice_hierarchical": round(
            eff_two_level, 4),
        "predicted_weak_scaling_eff_64_2slice_hierarchical_overlap":
            round(eff_two_level_overlap, 4),
        # two-level MoE token-exchange row (ops/expert_dispatch.py)
        "moe_dispatch_bytes_per_chip": moe_x_bytes,
        "moe_a2a_flat_s": round(a2a_flat_s, 6),
        "moe_a2a_two_level_s": round(a2a_two_level_s, 6),
        "moe_ffn_s": round(moe_ffn_s, 6),
        "moe_layer_flat_s": round(moe_layer_flat_s, 6),
        "moe_layer_hierarchical_s": round(moe_layer_two_level_s, 6),
        "moe_layer_overlapped_s": round(moe_layer_overlap_s, 6),
        "moe_dcn_hops_flat": (DCN_SLICES - 1) * ici,
        "moe_dcn_hops_hierarchical": DCN_SLICES - 1,
        # compressed 'dcn' wire rows (PR 11, ops/wire_codec.py)
        "grad_wire_rows": wire_rows,
        "moe_wire_rows": moe_wire_rows,
        # tuner argmin rows (tuning/search.py closed forms) — asserted
        # never worse than the hand §3b/§3c configurations above
        "tuned_rows": tuned_rows,
        # composed-plan factorization rows (ISSUE 19) — argmin
        # asserted never worse than every single-axis degenerate spec
        "plan_rows": plan_rows,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "scaling64.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
