"""Command-line entry points, flag-for-flag with the reference scripts.

`python -m distributed_model_parallel_tpu.cli.data_parallel` replaces
`python code/distributed_training/data_parallel.py` (CIFAR-10 DP training,
`--lr --resume`); `python -m distributed_model_parallel_tpu.cli.model_parallel`
replaces `python code/distributed_training/model_parallel.py` (pipeline
training, `DATA --world-size N --dist-backend ...`). Every reference flag
name is kept; TPU-only flags are additive.
"""
