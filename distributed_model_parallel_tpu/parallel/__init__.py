from distributed_model_parallel_tpu.parallel.data_parallel import (  # noqa: F401
    DataParallelEngine,
    DDPEngine,
    TrainState,
)
from distributed_model_parallel_tpu.parallel.pipeline import (  # noqa: F401
    LMPipelineEngine,
    PipelineEngine,
    build_1f1b_schedule,
    build_interleaved_schedule,
)
from distributed_model_parallel_tpu.parallel.sequence_parallel import (  # noqa: F401
    CausalLMSequenceParallelEngine,
    SequenceParallelEngine,
)
from distributed_model_parallel_tpu.parallel.tensor_parallel import (  # noqa: F401
    MEGATRON_RULES,
    TensorParallelEngine,
)
from distributed_model_parallel_tpu.parallel.expert_parallel import (  # noqa: F401
    EXPERT_RULES,
    ExpertParallelEngine,
)
from distributed_model_parallel_tpu.parallel.fsdp import (  # noqa: F401
    FSDPEngine,
)
from distributed_model_parallel_tpu.parallel.plan import (  # noqa: F401
    ComposedPlanEngine,
    ParallelPlan,
    build_plan_engine,
    parse_plan,
)
