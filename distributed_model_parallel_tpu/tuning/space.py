"""The declarative search space: which knobs exist per engine family,
which values each may take, and which CLI flag / engine parameter each
one drives.

Every `Knob` names its real surfaces (`cli_flag`, `engine_param`) so
the conftest META-CHECK (`scan_knob_surface`) can fail collection when
the space enumerates a knob that no engine or CLI actually accepts —
a tuner that searches over a phantom knob would emit plans nobody can
apply.

`candidates(family, dcn)` expands the cross-product, filters the
combinations the engines themselves refuse (wire compression without a
'dcn' axis to cross, overlap chunking without the hierarchical
dispatch, ...), canonicalizes inapplicable knobs to None so equivalent
configurations dedupe, and returns the list in a deterministic order —
the enumeration order IS part of the search's byte-stability contract.

jax-free by module contract (imported at pytest collection time).
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import re
from typing import Dict, List, Optional, Tuple

# Engine families the tuner knows how to search. They are the lint
# matrix's combo vocabulary (`analysis/lint.py` builders), which is
# what makes "price a candidate" a one-liner: every candidate maps to
# a Combo the shared lowering path already understands.
FAMILIES = ("ddp", "fsdp", "sp_lm", "ep", "tp", "serve", "plan")


@dataclasses.dataclass(frozen=True)
class Knob:
    """One tunable dimension: its value grid and its REAL surfaces."""

    name: str            # canonical key in a plan's "knobs" object
    values: tuple        # the enumerable grid
    cli_flag: str        # the training-CLI flag that sets it
    engine_param: str    # the engine dataclass field it lands on


# Bucket grid: 25 is the DDP Reducer's default cap; the sub-MB values
# matter twice — small real models, and the tiny lint proxies whose
# whole gradient fits one 0.1 MB bucket (a grid that never splits the
# proxy would make bucket_mb a phantom knob on every searched cell).
_BUCKET_GRID = (0.02, 0.1, 1.0, 25.0)

_REDUCER_KNOBS = (
    Knob("grad_reduction", ("monolithic", "bucketed", "overlapped"),
         "--grad-reduction", "grad_reduction"),
    Knob("bucket_mb", _BUCKET_GRID, "--bucket-mb", "bucket_mb"),
    # 0 = the engines' auto default (min(4, blocks)).
    Knob("overlap_stages", (0, 2), "--overlap-stages",
         "overlap_stages"),
    Knob("dcn_compression", ("none", "bf16", "int8"),
         "--dcn-compression", "dcn_compression"),
)

_CM_KNOB = Knob("collective_matmul", (False, True),
                "--collective-matmul", "collective_matmul")


# --------------------------------------------- the composed-plan family
#
# The "plan" family searches WHOLE mesh factorizations, not per-engine
# schedule knobs: each grid value is a ParallelPlan spec string
# ("pp2xsp2xdp2", "fsdp8", ...) that `build_plan_engine` runs directly
# (ISSUE 19). This module stays jax-free, so it carries its own mirror
# of `parallel/plan.py`'s token grammar; the drift pin lives in
# tests/test_tuning.py (`plan_spec_axes` vs `parse_plan` on the whole
# grid).

_PLAN_TOKEN_RE = re.compile(
    r"^(pp|sp|tp|dp|fsdp|ep)(\d+)(?:-(1f1b|int(\d+)))?$"
)

_PLAN_TOKEN_AXIS = {
    "pp": "pp", "sp": "sp", "tp": "sp", "dp": "dp", "fsdp": "dp",
    "ep": "ep",
}


def plan_spec_axes(spec: str) -> dict:
    """jax-free parse of a plan spec string into
    {"pp", "sp", "dp", "ep", "fsdp", "schedule", "virtual"} — the same
    grammar as `parallel.plan.parse_plan` (tokens
    `(pp|sp|tp|dp|fsdp|ep)<n>` joined by 'x', duplicate axes rejected;
    ISSUE 20's `-1f1b` / `-int<V>` schedule suffix rides the pp token
    only)."""
    axes = {"pp": 1, "sp": 1, "dp": 1, "ep": 1, "fsdp": False,
            "schedule": "gpipe", "virtual": 1}
    seen = set()
    for tok in spec.split("x"):
        tok = tok.strip().rstrip("-")
        m = _PLAN_TOKEN_RE.match(tok)
        if not m:
            raise ValueError(
                f"bad plan token {tok!r} in {spec!r} (want "
                "(pp|sp|tp|dp|fsdp|ep)<n>[-1f1b|-int<V>] joined by "
                "'x')"
            )
        field = _PLAN_TOKEN_AXIS[m.group(1)]
        if field in seen:
            raise ValueError(f"duplicate axis {field!r} in {spec!r}")
        seen.add(field)
        axes[field] = int(m.group(2))
        if m.group(1) == "fsdp":
            axes["fsdp"] = True
        if m.group(3):
            if m.group(1) != "pp":
                raise ValueError(
                    f"schedule suffix on non-pp token {tok!r} in "
                    f"{spec!r} (ParallelPlan.schedule rides the pp "
                    "token)"
                )
            if m.group(3) == "1f1b":
                axes["schedule"] = "1f1b"
            else:
                v = int(m.group(4))
                if v < 2:
                    raise ValueError(
                        f"int{v} in {spec!r}: V=1 interleaving IS "
                        "1f1b — spell it pp<S>-1f1b"
                    )
                axes["schedule"] = "interleaved"
                axes["virtual"] = v
    return axes


def _plan_spec(pp: int, sp: int, dp: int, fsdp: bool,
               schedule: str = "gpipe", virtual: int = 1) -> str:
    """Spec-string builder matching `ParallelPlan.spec` byte-for-byte:
    only non-1 axes are emitted, in order pp, sp, dp-or-fsdp (the dp
    bit also appears when it is the ONLY axis); the schedule suffix
    rides the pp bit."""
    bits = []
    if pp > 1:
        sched = {"gpipe": "", "1f1b": "-1f1b",
                 "interleaved": f"-int{virtual}"}[schedule]
        bits.append(f"pp{pp}{sched}")
    if sp > 1:
        bits.append(f"sp{sp}")
    if dp > 1 or not bits:
        bits.append(("fsdp" if fsdp else "dp") + str(dp))
    return "x".join(bits)


def plan_specs(total: int) -> tuple:
    """All power-of-2 (pp, sp, dp) factorizations of `total` devices,
    each dp>1 point twinned with its fsdp variant, and each pp>1 point
    twinned with its 1f1b and int2 scheduled variants (ISSUE 20 — the
    gpipe plan stays a point in the scheduled space). Deterministic
    order (pp outer, sp inner, dense before fsdp, gpipe before 1f1b
    before int2) — the enumeration order is part of the byte-stability
    contract."""
    sizes = []
    w = 1
    while w <= total:
        sizes.append(w)
        w *= 2
    if sizes[-1] != total:
        raise ValueError(f"plan grid wants a power-of-2 size, got {total}")
    out = []
    for pp in sizes:
        for sp in sizes:
            if total % (pp * sp):
                continue
            dp = total // (pp * sp)
            schedules = [("gpipe", 1)]
            if pp > 1:
                schedules += [("1f1b", 1), ("interleaved", 2)]
            for sched, v in schedules:
                out.append(_plan_spec(pp, sp, dp, False, sched, v))
                if dp > 1:
                    out.append(_plan_spec(pp, sp, dp, True, sched, v))
    return tuple(out)


# The searched grid covers the two mesh sizes the gates pin: the
# 8-device CI mesh (plangate's plan/S8 cell) and the 64-way scaling
# study (experiments/scaling64.py §3f).
_PLAN_GRID = plan_specs(8) + plan_specs(64)


def scheduled_plan_candidates(total: int) -> List[dict]:
    """The plangate sched cell's scoped space (plan/S<n>/sched,
    ISSUE 20): the pp2 gpipe / 1f1b / int2 twins at num_microbatches=4
    — M just above pp, the first point where a scheduled plan's
    smaller bubble can beat its gpipe twin's shorter tick program.
    All three are lowered (3 <= DEFAULT_FINALISTS), so the pinned
    argmin is decided at the lowering tier, not the closed form."""
    if total % 2:
        raise ValueError(
            f"sched cell wants an even device count, got {total}"
        )
    dp = total // 2
    return [
        {"plan": _plan_spec(2, 1, dp, False, sched, v),
         "num_microbatches": 4}
        for sched, v in (
            ("gpipe", 1), ("1f1b", 1), ("interleaved", 2),
        )
    ]

SPACES: Dict[str, Tuple[Knob, ...]] = {
    "ddp": _REDUCER_KNOBS,
    "fsdp": _REDUCER_KNOBS,
    "sp_lm": _REDUCER_KNOBS + (_CM_KNOB,),
    "ep": (
        Knob("dispatch", ("gspmd", "hierarchical"), "--moe-dispatch",
             "dispatch"),
        Knob("overlap", (False, True), "--moe-overlap", "overlap"),
        Knob("dcn_compression", ("none", "bf16", "int8"),
             "--dcn-compression", "dcn_compression"),
    ),
    "tp": (_CM_KNOB,),
    # Serving (the paged KV cache, ISSUE 15 / ROADMAP 5c): page size
    # trades per-token page WRITE-BACK bytes (the decode step rewrites
    # one whole page per slot per step) against page-allocation
    # launches over the sequence lifetime; prefill chunk trades
    # per-chunk launches against padded prompt compute. Priced by the
    # cost engine's closed form (`search.serve_closed_form_s`); the
    # engine lives under serving/ (`scan_knob_surface` scans it
    # alongside parallel/). Values sized to divide the lint serve
    # proxy's 16-position cache — the same proxy-fits-the-grid
    # compromise as _BUCKET_GRID's sub-MB values.
    # compute_dtype (ISSUE 16): the decode projection GEMM arithmetic
    # (`ops/quant_matmul.py`), priced by the MXU/HBM roofline closed
    # form (`cost.serve_decode_compute_s`).
    # speculative_k (ISSUE 18, `serving/speculative.py`): draft
    # lookahead depth — 0 is plain decode; k >= 1 trades k draft steps
    # (at DRAFT_COST_RATIO of a target step) for one k+1-wide verify
    # step whose weight stream costs the same as ONE decode step,
    # amortized over the expected accepted tokens
    # (`cost.serve_speculative_request_s`). Every serve grid point is
    # paged, so the rollback-by-block-table requirement always holds.
    "serve": (
        Knob("page_size", (4, 8, 16), "--page-size", "page_size"),
        Knob("prefill_chunk", (4, 8, 16), "--prefill-chunk",
             "prefill_chunk"),
        Knob("compute_dtype", ("f32", "bf16", "int8"),
             "--compute-dtype", "compute_dtype"),
        Knob("speculative_k", (0, 2, 4), "--speculative-k",
             "speculative_k"),
    ),
    # Composed mesh-axis plans (ISSUE 19): one spec-string knob whose
    # grid IS the factorization space — including the ISSUE 20
    # schedule suffixes (pp<S>-1f1b / pp<S>-int<V>), so the tuner
    # trades bubble against wire hops inside ONE family. The engine
    # field is `ComposedPlanEngine.plan`; the CLI flag is the training
    # CLIs' `--plan`. num_microbatches sizes the pipeline fill (0 =
    # the engine default M = pp*V); M just above pp is where a
    # scheduled plan first beats its gpipe twin. Candidate filtering
    # (device count, DCN slice boundaries, M bounds) happens in
    # `_canonicalize` against the cell's mesh.
    "plan": (
        Knob("plan", _PLAN_GRID, "--plan", "plan"),
        Knob("num_microbatches", (0, 4), "--microbatches",
             "num_microbatches"),
    ),
}


def canonical_key(knobs: dict) -> str:
    """The deterministic identity of one candidate (sort/tie-break and
    dedupe key)."""
    return json.dumps(knobs, sort_keys=True)


def _canonicalize(family: str, knobs: dict, dcn: int,
                  size: Optional[int] = None) -> Optional[dict]:
    """Normalize one raw cross-product point: inapplicable knobs go to
    None so equivalent configurations collapse; invalid combinations
    (the ones the engines refuse at construction) return None. `size`
    (total device count) gates the plan family's grid to the cell's
    mesh."""
    k = dict(knobs)
    if family == "plan":
        ax = plan_spec_axes(k["plan"])
        ndev = ax["pp"] * ax["sp"] * ax["dp"]
        if size is not None and ndev != size:
            return None  # grid point for a different mesh size
        if dcn > 1:
            # On a factored ('dcn','ici') fabric the slice boundary
            # must fall BETWEEN pipeline stages (stage wire is the only
            # collective the plan sends across DCN; pp=1 plans keep the
            # data axis across slices — the DDP case).
            if ax["pp"] > 1 and ax["pp"] % dcn:
                return None
            if ax["sp"] > ndev // dcn:
                return None  # a ring-attention hop would cross DCN
        m = k.get("num_microbatches") or 0
        if ax["pp"] == 1 or not m:
            # No pipeline to fill (or the engine default M = pp*V):
            # not a knob — collapse so equivalent configs dedupe.
            k["num_microbatches"] = None
        else:
            # The engine's own fail-fast guards: M >= pp*V fills every
            # (virtual) stage; the interleaved builder round-robins
            # microbatch groups of S (M % pp == 0).
            if m < ax["pp"] * ax["virtual"] or m % ax["pp"]:
                return None
        return k
    if family in ("ddp", "fsdp", "sp_lm"):
        if k["dcn_compression"] != "none" and dcn < 2:
            return None  # no 'dcn' hop to compress (engine guard)
        if k["grad_reduction"] == "monolithic":
            # Monolithic has no bucket surface (the compressed variant
            # routes through ONE flat bucket, MONOLITHIC_BUCKET_MB —
            # still not a knob) and no backward to segment.
            k["bucket_mb"] = None
            k["overlap_stages"] = None
        elif k["grad_reduction"] == "bucketed":
            k["overlap_stages"] = None
    elif family == "ep":
        if k["dispatch"] == "gspmd":
            # The gspmd flat exchange has no explicit 'dcn' seam and no
            # chunk ring to overlap; on a factored (dcn > 1) fabric it
            # is exactly the lowering the hierarchical exchange
            # replaced, so it leaves the space entirely there.
            if dcn > 1 or k["overlap"] or k["dcn_compression"] != "none":
                return None
        elif k["dcn_compression"] != "none" and dcn < 2:
            return None
    return k


def preference(family: str, knobs: dict) -> tuple:
    """Deterministic tie-break among equal-cost candidates (the cost
    engine prices what the program ASKS the network for; two configs
    with identical asks differ only in schedule). Lower sorts first:
    prefer the more-overlapped config (overlap changes dependency
    structure at zero asked-bytes cost — the hlolint dependency pins
    prove the overlap is real), then the larger bucket (fewer
    launches), then the LESS exotic wire (a codec the bytes don't pay
    for is free complexity)."""
    if family in ("ddp", "fsdp", "sp_lm"):
        return (
            {"overlapped": 0, "bucketed": 1, "monolithic": 2}[
                knobs["grad_reduction"]],
            -(knobs["bucket_mb"] or float("inf")),
            ("none", "bf16", "int8").index(knobs["dcn_compression"]),
            knobs["overlap_stages"] or 0,
            0 if knobs.get("collective_matmul") else 1,
        )
    if family == "ep":
        return (
            0 if knobs["dispatch"] == "hierarchical" else 1,
            0 if knobs["overlap"] else 1,
            ("none", "bf16", "int8").index(knobs["dcn_compression"]),
        )
    if family == "serve":
        # Equal-cost ties break toward less HBM overscan (smaller
        # pages), then fewer ingest launches (larger chunks), then the
        # LESS exotic arithmetic (quantization the roofline doesn't
        # pay for is free numerics risk — mirrors the wire tie-break),
        # then the SHALLOWER lookahead (a draft model the amortization
        # doesn't pay for is free machinery).
        return (
            knobs["page_size"], -knobs["prefill_chunk"],
            ("f32", "bf16", "int8").index(
                knobs.get("compute_dtype") or "f32"
            ),
            knobs.get("speculative_k") or 0,
        )
    if family == "plan":
        # Equal-cost ties break toward the LEAST-restructured plan:
        # fewer pipeline stages, then fewer sequence shards, then dense
        # dp over fsdp (resharding machinery the cost model doesn't pay
        # for is free complexity), then the SIMPLER schedule (gpipe
        # before 1f1b before interleaved — a tick table the bubble
        # term doesn't pay for is free machinery), then fewer
        # microbatches (a deeper fill the bubble doesn't pay for is
        # free latency).
        ax = plan_spec_axes(knobs["plan"])
        return (
            ax["pp"], ax["sp"], int(ax["fsdp"]),
            {"gpipe": 0, "1f1b": 1, "interleaved": 2}[ax["schedule"]],
            ax["virtual"],
            knobs.get("num_microbatches") or 0,
        )
    # tp: prefer the ring decomposition on a tie (latency hiding).
    return (0 if knobs["collective_matmul"] else 1,)


def candidates(family: str, dcn: int = 1, allow_cm: bool = True,
               size: Optional[int] = None) -> List[dict]:
    """The deduped, deterministically ordered candidate list for one
    engine family on a mesh with `dcn` cross-slice factor. `allow_cm`
    drops the collective_matmul=True half when the run has no ring axis
    (lm CLI with --seq-shards 1). `size` (total devices) restricts the
    plan family's spec grid to factorizations of the cell's mesh."""
    if family not in SPACES:
        raise ValueError(
            f"no search space for engine family {family!r} "
            f"(tunable families: {', '.join(sorted(SPACES))})"
        )
    knob_list = SPACES[family]
    out: Dict[str, dict] = {}
    for values in itertools.product(*(k.values for k in knob_list)):
        raw = {k.name: v for k, v in zip(knob_list, values)}
        if not allow_cm and raw.get("collective_matmul"):
            continue
        k = _canonicalize(family, raw, dcn, size)
        if k is not None:
            out.setdefault(canonical_key(k), k)
    return [out[key] for key in sorted(out)]


# ------------------------------------------------- the knob META-CHECK


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read_sources(subdir: str) -> str:
    root = os.path.join(_package_root(), subdir)
    chunks = []
    for name in sorted(os.listdir(root)):
        if name.endswith(".py"):
            with open(os.path.join(root, name)) as f:
                chunks.append(f.read())
    return "\n".join(chunks)


def scan_knob_surface() -> Dict[str, List[str]]:
    """Literal source scan backing the conftest META-CHECK: every knob
    the space enumerates must exist as (a) a CLI flag literal somewhere
    under `cli/` and (b) an engine dataclass field (annotated
    attribute) somewhere under `parallel/` or `serving/` (the serve
    family's engine lives in `serving/engine.py`). Returns
    {knob_name: [what's missing, ...]} — empty means the space and the
    real surfaces agree."""
    cli_src = _read_sources("cli")
    engine_src = (
        _read_sources("parallel") + "\n" + _read_sources("serving")
    )
    strays: Dict[str, List[str]] = {}
    seen = set()
    for family, knob_list in sorted(SPACES.items()):
        for knob in knob_list:
            if (knob.name, knob.cli_flag, knob.engine_param) in seen:
                continue
            seen.add((knob.name, knob.cli_flag, knob.engine_param))
            missing = []
            if f'"{knob.cli_flag}"' not in cli_src:
                missing.append(
                    f"CLI flag {knob.cli_flag} not found under cli/"
                )
            if not re.search(
                rf"^\s*{re.escape(knob.engine_param)}\s*:",
                engine_src, re.MULTILINE,
            ):
                missing.append(
                    f"engine field {knob.engine_param!r} not found "
                    "under parallel/ or serving/"
                )
            if missing:
                strays.setdefault(
                    f"{family}.{knob.name}", []
                ).extend(missing)
    return strays


__all__ = [
    "FAMILIES",
    "Knob",
    "SPACES",
    "candidates",
    "canonical_key",
    "plan_spec_axes",
    "plan_specs",
    "preference",
    "scan_knob_surface",
    "scheduled_plan_candidates",
]
