"""Disk-backed dataset paths: CIFAR-10 binary batches, lazy ImageFolder
trees, and the CUB200 metadata join — exercised against tiny synthetic
fixtures written to tmp (no downloads, per SURVEY.md §4)."""

import pickle

import numpy as np
import pytest
from PIL import Image

from distributed_model_parallel_tpu.data.datasets import (
    ArrayDataset,
    DatasetCollection,
    LazyImageFolder,
    cifar10,
    cub200,
    image_folder,
)
from distributed_model_parallel_tpu.data.loader import Loader


def _write_cifar(root, n_per_batch=20):
    d = root / "cifar-10-batches-py"
    d.mkdir(parents=True)
    rng = np.random.RandomState(0)

    def write(name, n):
        entry = {
            b"data": rng.randint(
                0, 256, size=(n, 3 * 32 * 32)
            ).astype(np.uint8),
            b"labels": rng.randint(0, 10, size=(n,)).tolist(),
        }
        with open(d / name, "wb") as f:
            pickle.dump(entry, f)

    for i in range(1, 6):
        write(f"data_batch_{i}", n_per_batch)
    write("test_batch", n_per_batch // 2)


def test_cifar10_reads_binary_batches(tmp_path):
    _write_cifar(tmp_path)
    train, val = cifar10(str(tmp_path), fallback_synthetic=False)
    assert len(train) == 100 and len(val) == 10
    assert train.images.shape == (100, 32, 32, 3)
    assert train.images.dtype == np.uint8
    assert train.num_classes == 10
    # And the string-keyed factory takes the same path.
    train2, _ = DatasetCollection("CIFAR10", str(tmp_path)).init()
    np.testing.assert_array_equal(train.images, train2.images)


def test_cifar10_missing_raises_without_fallback(tmp_path):
    with pytest.raises(FileNotFoundError):
        cifar10(str(tmp_path), fallback_synthetic=False)


def _write_image_tree(root, split="train", classes=("cat", "dog"), n=3,
                      size=8):
    rng = np.random.RandomState(1)
    for c in classes:
        d = root / split / c
        d.mkdir(parents=True)
        for i in range(n):
            arr = rng.randint(0, 256, size=(size, size, 3)).astype(np.uint8)
            Image.fromarray(arr).save(d / f"{i}.png")
        # Stray non-image files must be filtered, not decoded mid-epoch.
        (d / ".DS_Store").write_bytes(b"junk")
        (d / "checksums.txt").write_text("abc")


def test_image_folder_lazy_gather_and_loader(tmp_path):
    _write_image_tree(tmp_path, "train")
    _write_image_tree(tmp_path, "val", n=2)
    train, val = image_folder(str(tmp_path), image_size=8)
    assert isinstance(train, LazyImageFolder)
    assert len(train) == 6 and len(val) == 4
    assert train.num_classes == 2
    images, labels = train.gather(np.array([0, 5]))
    assert images.shape == (2, 8, 8, 3) and images.dtype == np.uint8
    assert labels.tolist() == [0, 1]
    # The Loader drives the lazy dataset through gather() end-to-end.
    loader = Loader(train, batch_size=3, shuffle=True, seed=0)
    batches = list(loader)
    assert len(batches) == 2
    assert batches[0][0].shape == (3, 8, 8, 3)


def test_image_folder_eager_mode(tmp_path):
    _write_image_tree(tmp_path, "train")
    _write_image_tree(tmp_path, "val", n=1)
    train, _ = image_folder(str(tmp_path), image_size=8, lazy=False)
    assert isinstance(train, ArrayDataset)
    assert train.images.shape == (6, 8, 8, 3)


def test_cub200_metadata_join(tmp_path):
    """The images.txt / train_test_split.txt / image_class_labels.txt
    three-way join (`dataset_collection.py:8-27` does it with pandas;
    ours is plain dicts)."""
    rng = np.random.RandomState(2)
    (tmp_path / "images" / "001.Black_footed_Albatross").mkdir(parents=True)
    (tmp_path / "images" / "002.Laysan_Albatross").mkdir(parents=True)
    rows = []
    for i in range(1, 7):
        cls = 1 if i <= 3 else 2
        cdir = ("001.Black_footed_Albatross" if cls == 1
                else "002.Laysan_Albatross")
        rel = f"{cdir}/img_{i}.jpg"
        arr = rng.randint(0, 256, size=(10, 12, 3)).astype(np.uint8)
        Image.fromarray(arr).save(tmp_path / "images" / rel)
        rows.append((i, rel, cls, 1 if i % 2 else 0))
    with open(tmp_path / "images.txt", "w") as f:
        f.writelines(f"{i} {rel}\n" for i, rel, _, _ in rows)
    with open(tmp_path / "image_class_labels.txt", "w") as f:
        f.writelines(f"{i} {cls}\n" for i, _, cls, _ in rows)
    with open(tmp_path / "train_test_split.txt", "w") as f:
        f.writelines(f"{i} {tr}\n" for i, _, _, tr in rows)

    train, val = cub200(str(tmp_path), image_size=8)
    assert len(train) == 3 and len(val) == 3
    assert train.images.shape == (3, 8, 8, 3)
    assert train.num_classes == 200
    assert sorted(train.labels.tolist()) == [0, 0, 1]


def test_synthetic_textures_properties():
    """Texture dataset: uint8, deterministic, per-sample unique pixels
    (the anti-memorization property), task shared across seeds."""
    import numpy as np

    from distributed_model_parallel_tpu.data.datasets import (
        synthetic_textures,
    )

    a = synthetic_textures(256, 16, 4, seed=1)
    b = synthetic_textures(256, 16, 4, seed=1)
    np.testing.assert_array_equal(a.images, b.images)  # deterministic
    assert a.images.dtype == np.uint8 and a.kind == "image"
    flat = a.images.reshape(len(a.images), -1)
    assert len(np.unique(flat, axis=0)) == len(flat)  # no repeats
    # class structure is in the FIXED class rng: same class's samples
    # correlate more with their class mean than with other classes'
    means = np.stack([
        a.images[a.labels == c].mean(axis=0).ravel() for c in range(4)
    ])
    own = cross = 0.0
    for c in range(4):
        sams = a.images[a.labels == c].reshape(-1, flat.shape[1])[:20]
        sims = [
            float(np.corrcoef(s, means[k])[0, 1])
            for s in sams for k in range(4)
        ]
        arr = np.array(sims).reshape(-1, 4)
        own += arr[:, c].mean()
        cross += (arr.sum(axis=1) - arr[:, c]).mean() / 3
    assert own / 4 > cross / 4 + 0.05


def test_synthetic_text_properties():
    import numpy as np

    from distributed_model_parallel_tpu.data.datasets import (
        synthetic_text,
    )

    a = synthetic_text(128, 32, 4, vocab_size=64, seed=3)
    b = synthetic_text(128, 32, 4, vocab_size=64, seed=3)
    np.testing.assert_array_equal(a.images, b.images)
    assert a.kind == "text" and a.images.dtype == np.int32
    assert a.images.min() >= 1 and a.images.max() < 64  # 0 = pad, free
