"""Loss + accuracy metrics matching the reference trainer.

* cross-entropy from logits = `nn.CrossEntropyLoss` (`data_parallel.py:89`)
* `accuracy(output, target, topk=(1,5))` = `utils.py:215-229`, returning
  percentages.
* `Meter` = the running averages the reference accumulates by hand
  (`utils.py:36-76`: batch_time_avg / data_time_avg / acc1_avg / loss_avg).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


def valid_count(labels: jax.Array) -> jax.Array:
    """Number of real (non-padding) samples in the batch. Padding rows are
    marked with label -1 by the Loader when it pads a ragged final val
    batch to a static shape; full training batches have no padding, so
    this equals the batch size there."""
    return jnp.sum((labels >= 0).astype(jnp.float32))


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy over the *valid* rows of the batch,
    computed in f32. Padding rows (label -1, see `valid_count`) contribute
    zero loss and zero count."""
    logits = logits.astype(jnp.float32)
    valid = (labels >= 0).astype(jnp.float32)
    safe = jnp.maximum(labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
    per_example = (logz - true_logit) * valid
    return jnp.sum(per_example) / jnp.maximum(jnp.sum(valid), 1.0)


def topk_correct(logits: jax.Array, labels: jax.Array, k: int) -> jax.Array:
    """Count of valid samples whose label is in the top-k logits (sum, not
    %, so counts psum correctly across shards). `k` is clamped to the
    number of classes so acc5 is well-defined on few-class heads; padding
    rows (label -1) never count."""
    _, pred = jax.lax.top_k(logits, min(k, logits.shape[-1]))
    hit = jnp.any(pred == labels[:, None], axis=-1)
    return jnp.sum(hit.astype(jnp.float32) * (labels >= 0).astype(jnp.float32))


def accuracy(logits: jax.Array, labels: jax.Array, topk=(1,)) -> list[jax.Array]:
    """Percentage top-k accuracies — same contract as reference
    `accuracy` (`utils.py:215-229`)."""
    n = labels.shape[0]
    return [100.0 * topk_correct(logits, labels, k) / n for k in topk]


@dataclasses.dataclass
class Meter:
    """Streaming average (host-side)."""

    total: float = 0.0
    count: int = 0

    def update(self, value: float, n: int = 1) -> None:
        self.total += float(value) * n
        self.count += n

    @property
    def avg(self) -> float:
        return self.total / max(self.count, 1)
