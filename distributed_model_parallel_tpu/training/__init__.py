from distributed_model_parallel_tpu.training.optim import (  # noqa: F401
    SGD,
    SGDState,
    cosine_warmup_schedule,
)
from distributed_model_parallel_tpu.training.metrics import (  # noqa: F401
    Meter,
    accuracy,
    cross_entropy,
    topk_correct,
)
