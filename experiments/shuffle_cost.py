"""The reference's open question, answered: shuffle's per-batch cost.

The reference measured 0.89 s/batch with shuffle=True vs 0.32 without
on its torch DataLoader and left it a mystery (`Readme.md:296-301`).
Hypothesis encoded here: the cost was never the permutation (an O(N)
numpy shuffle is microseconds per batch) — it is MEMORY LOCALITY of the
per-sample gather. A shuffled epoch gathers 512 rows scattered across
the whole 150 MB array (one cache-missing random access per row), a
sequential epoch reads contiguously; torch pays it per SAMPLE in Python
`__getitem__` + collate, amplifying the miss cost.

This script measures, on this framework's batched loader:
  1. pure batch production (no device, no training): shuffle on/off,
     augment on/off, prefetch on/off;
  2. the same with a sorted-within-batch gather (locality restored
     while keeping the epoch-level permutation) — isolating the
     locality effect from everything else.

Writes experiments/shuffle_cost.json; summarized in RESULTS.md.

Run on a QUIET host: python experiments/shuffle_cost.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from distributed_model_parallel_tpu.data.datasets import (  # noqa: E402
    CIFAR10_MEAN,
    CIFAR10_STD,
    synthetic,
)
from distributed_model_parallel_tpu.data.loader import Loader  # noqa: E402

N = 50_000
BATCH = 512


def time_epoch(loader, epochs=2):
    """s/batch over `epochs` full iterations (first epoch warms page
    cache/native build; the SECOND is reported)."""
    per = []
    for ep in range(epochs):
        loader.set_epoch(ep)
        t0 = time.perf_counter()
        n = 0
        for _ in loader:
            n += 1
        per.append((time.perf_counter() - t0) / n)
    return per[-1], n


def main():
    ds = synthetic(N, 32, 10, seed=1)
    rows = []
    for shuffle in (False, True):
        for augment in (False, True):
            for prefetch in (0, 2):
                loader = Loader(
                    ds, batch_size=BATCH, shuffle=shuffle,
                    augment=augment, mean=CIFAR10_MEAN, std=CIFAR10_STD,
                    prefetch=prefetch,
                )
                s, n = time_epoch(loader)
                rows.append({
                    "shuffle": shuffle, "augment": augment,
                    "prefetch": prefetch,
                    "s_per_batch": round(s, 5), "batches": n,
                })
                print(rows[-1], flush=True)

    # Locality probe: same epoch permutation, but each BATCH's indices
    # sorted before the gather (permutation across batches preserved).
    class SortedGatherLoader(Loader):
        def _make_batch(self, b, idx, use_native):
            return super()._make_batch(b, np.sort(idx), use_native)

    loader = SortedGatherLoader(
        ds, batch_size=BATCH, shuffle=True, augment=True,
        mean=CIFAR10_MEAN, std=CIFAR10_STD, prefetch=0,
    )
    s, _ = time_epoch(loader)
    rows.append({
        "shuffle": True, "augment": True, "prefetch": 0,
        "sorted_within_batch": True, "s_per_batch": round(s, 5),
    })
    print(rows[-1], flush=True)

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "shuffle_cost.json")
    with open(path, "w") as f:
        json.dump({"n": N, "batch": BATCH, "rows": rows}, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
