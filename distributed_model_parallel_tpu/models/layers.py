"""Pure-functional neural-net layer library (NHWC, param/state pytrees).

This replaces `torch.nn` for the model zoo. Design rules, chosen for TPU:

* A layer is a `Layer(init, apply)` pair of pure functions.
    init(key)                              -> (params, state)
    apply(params, state, x, ctx)           -> (y, new_state)
  `params` are trained; `state` holds non-trained buffers (BN running
  stats). Both are plain dict pytrees, so engines can shard, split into
  pipeline stages, or donate them without any module-object machinery.
* NHWC activations / HWIO conv kernels — the layouts XLA tiles best onto
  the MXU (the reference is NCHW torch, e.g.
  `code/distributed_training/model/mobilenetv2.py:17-21`; layout is an
  implementation choice, capability is identical).
* BatchNorm takes an optional mesh axis name: when set, batch statistics
  are `lax.pmean`-ed across that axis — SyncBatchNorm as a one-liner
  (reference documents SyncBN prep inside DDP init, `Readme.md:151`).
  When the engine runs the model under plain `jit` over a sharded batch,
  statistics are global automatically; under `shard_map` without the axis
  name they are per-shard, which is exactly `nn.DataParallel`'s
  per-replica-BN semantics (`Readme.md:70-107`).
* Initializers match torch defaults numerically (kaiming-uniform with
  a=sqrt(5) for conv/linear ⇒ U(±1/sqrt(fan_in))) so convergence parity
  with the reference's published accuracies is apples-to-apples.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

Params = Any
State = Any


@dataclasses.dataclass(frozen=True)
class Context:
    """Per-call context threaded through `apply`."""

    train: bool = False
    # Mesh axis name(s) over which BN stats are synchronized (SyncBN).
    # None => local (per-shard under shard_map, global under plain jit).
    bn_axis: Optional[str] = None
    # PRNG key for stochastic layers (dropout); None in eval.
    rng: Optional[jax.Array] = None
    # Activation compute dtype (mixed precision). None => follow the input
    # dtype unchanged. When set (e.g. jnp.bfloat16), "source" layers whose
    # output dtype comes from params rather than activations (embedding)
    # cast their output to it; everything downstream follows x.dtype, and
    # params stay f32 master copies (cast per-use inside each layer).
    dtype: Optional[Any] = None
    # Latency-hiding collective-matmul policy
    # (`ops.collective_matmul.CollectiveMatmul` / `LocalCollectiveMatmul`)
    # threaded by the TP/SP engines when `collective_matmul=True`; the
    # transformer-family projection layers consume it via `project`.
    # None => every projection is a plain dot (the default everywhere).
    matmul: Optional[Any] = None
    # Hand-rolled MoE token-exchange policy
    # (`ops.expert_dispatch.ExpertDispatch` / `LocalExpertDispatch`)
    # threaded by the EP/DDP engines when `dispatch="hierarchical"`;
    # `models/moe.py` routes its expert FFN through it. None => the
    # dense-dispatch einsums run whole and the partitioner inserts
    # whatever flat exchange it likes (the GSPMD default).
    expert_dispatch: Optional[Any] = None

    def child(self, i: int) -> "Context":
        """Context for the i-th child of a combinator: folds the child
        index into the rng so sibling stochastic layers draw independent
        masks."""
        if self.rng is None:
            return self
        return dataclasses.replace(self, rng=jax.random.fold_in(self.rng, i))


@dataclasses.dataclass(frozen=True)
class Layer:
    init: Callable[[jax.Array], tuple[Params, State]]
    apply: Callable[[Params, State, jax.Array, Context], tuple[jax.Array, State]]
    # Optional stage anatomy (`models/staging.StageParts`) attached by
    # `staging.staged_model` on the stem/blocks/head families: the
    # component Layers the composed model was assembled from, which the
    # stagewise-backward engines (`grad_reduction="overlapped"`) cut
    # into per-stage vjp segments. None for models without the
    # convention; composition/apply never reads it.
    parts: Optional[Any] = None


def _uniform(key, shape, bound, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, -bound, bound)


# ---------------------------------------------------------------------------
# Conv / Linear / Norm primitives
# ---------------------------------------------------------------------------


def conv2d(
    in_ch: int,
    out_ch: int,
    kernel: int,
    *,
    stride: int = 1,
    padding: int = 0,
    groups: int = 1,
    bias: bool = False,
) -> Layer:
    """2-D convolution, NHWC/HWIO. `groups=channels` gives the depthwise conv
    of the MobileNetV2 block (`mobilenetv2.py:19`)."""
    kshape = (kernel, kernel, in_ch // groups, out_ch)
    fan_in = (in_ch // groups) * kernel * kernel
    bound = 1.0 / math.sqrt(fan_in)

    def init(key):
        wkey, bkey = jax.random.split(key)
        params = {"w": _uniform(wkey, kshape, bound)}
        if bias:
            params["b"] = _uniform(bkey, (out_ch,), bound)
        return params, {}

    dn = lax.conv_dimension_numbers(
        (1, 1, 1, in_ch), kshape, ("NHWC", "HWIO", "NHWC")
    )

    def apply(params, state, x, ctx):
        y = lax.conv_general_dilated(
            x,
            params["w"].astype(x.dtype),
            window_strides=(stride, stride),
            padding=[(padding, padding), (padding, padding)],
            dimension_numbers=dn,
            feature_group_count=groups,
        )
        if bias:
            y = y + params["b"].astype(y.dtype)
        return y, state

    return Layer(init, apply)


def linear(in_features: int, out_features: int, *, bias: bool = True) -> Layer:
    """Dense layer, torch-default init (`nn.Linear`, used at
    `mobilenetv2.py:56`)."""
    bound = 1.0 / math.sqrt(in_features)

    def init(key):
        wkey, bkey = jax.random.split(key)
        params = {"w": _uniform(wkey, (in_features, out_features), bound)}
        if bias:
            params["b"] = _uniform(bkey, (out_features,), bound)
        return params, {}

    def apply(params, state, x, ctx):
        y = x @ params["w"].astype(x.dtype)
        if bias:
            y = y + params["b"].astype(y.dtype)
        return y, state

    return Layer(init, apply)


def batchnorm2d(
    num_features: int, *, momentum: float = 0.1, eps: float = 1e-5
) -> Layer:
    """BatchNorm over (N, H, W) with explicit running-stat state.

    Matches `nn.BatchNorm2d` semantics (normalize with biased batch var,
    update running stats with unbiased var, momentum 0.1). Cross-replica
    synchronization — the SyncBatchNorm the reference only documents
    (`Readme.md:151`) — is `ctx.bn_axis`: batch mean/var are pmean-ed over
    that mesh axis before use.
    """

    def init(key):
        params = {
            "scale": jnp.ones((num_features,)),
            "bias": jnp.zeros((num_features,)),
        }
        state = {
            "mean": jnp.zeros((num_features,)),
            "var": jnp.ones((num_features,)),
        }
        return params, state

    def apply(params, state, x, ctx):
        reduce_axes = tuple(range(x.ndim - 1))  # all but channel
        if ctx.train:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
            if ctx.bn_axis is not None:
                mean = lax.pmean(mean, ctx.bn_axis)
                mean_sq = lax.pmean(mean_sq, ctx.bn_axis)
            var = mean_sq - jnp.square(mean)  # biased, used to normalize
            n = math.prod(x.shape[i] for i in reduce_axes)
            if ctx.bn_axis is not None:
                # Global element count, so the Bessel correction matches
                # torch SyncBatchNorm and the GSPMD (global-batch) engine.
                n = n * lax.psum(1, ctx.bn_axis)
                unbiased = var * (n / jnp.maximum(n - 1, 1))
            else:
                unbiased = var * (n / max(n - 1, 1))
            new_state = {
                "mean": (1 - momentum) * state["mean"] + momentum * mean,
                "var": (1 - momentum) * state["var"] + momentum * unbiased,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + eps) * params["scale"]
        y = (x.astype(jnp.float32) - mean) * inv + params["bias"]
        return y.astype(x.dtype), new_state

    return Layer(init, apply)


def layernorm(dim: int, *, eps: float = 1e-12) -> Layer:
    """LayerNorm over the last axis (BERT uses eps=1e-12)."""

    def init(key):
        return {"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))}, {}

    def apply(params, state, x, ctx):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + eps)
        y = y * params["scale"] + params["bias"]
        return y.astype(x.dtype), state

    return Layer(init, apply)


def embedding(vocab: int, dim: int, *, scale: float = 0.02) -> Layer:
    def init(key):
        return {"table": scale * jax.random.normal(key, (vocab, dim))}, {}

    def apply(params, state, ids, ctx):
        out = jnp.take(params["table"], ids, axis=0)
        if ctx.dtype is not None:
            out = out.astype(ctx.dtype)
        return out, state

    return Layer(init, apply)


def project(h, w, b, ctx: Context, *, role: str, scope: str):
    """Dense projection with the collective-matmul hook.

    The transformer-family attention/MLP layers route every weight
    matmul through here. When an engine threads a policy into
    `ctx.matmul` (TP/SP engines with `collective_matmul=True`) and the
    policy opts `scope` in ('attn' | 'ffn'), 'column'-role projections
    (qkv / ffn-in) run as chunked `ag_matmul` ppermute rings and
    'row'-role ones (attn-out / ffn-out) as `matmul_rs` rings
    (`ops/collective_matmul.py`); otherwise this is exactly `h @ w + b`.
    """
    # Params are f32 masters; compute follows the activation dtype (the
    # `linear` layer's convention). Without this cast a bf16 model
    # silently upcast to f32 at its FIRST projection — and the opted-in
    # rings carried f32 payloads (2x the bytes); the hlolint rule
    # `bf16-ring-upcast` pins the fixed behavior.
    w = w.astype(h.dtype)
    b = b.astype(h.dtype)
    mm = ctx.matmul
    if mm is not None and getattr(mm, scope):
        return (mm.column if role == "column" else mm.row)(h, w, b)
    return h @ w + b


# ---------------------------------------------------------------------------
# Stateless ops as layers
# ---------------------------------------------------------------------------


def _stateless(fn) -> Layer:
    return Layer(
        init=lambda key: ({}, {}),
        apply=lambda params, state, x, ctx: (fn(x), state),
    )


def relu() -> Layer:
    return _stateless(jax.nn.relu)


def gelu() -> Layer:
    return _stateless(partial(jax.nn.gelu, approximate=False))


def avg_pool2d(window: int, stride: Optional[int] = None) -> Layer:
    """`F.avg_pool2d` equivalent (used with window 4 for CIFAR at
    `mobilenetv2.py:72-73`)."""
    stride = stride or window

    def fn(x):
        y = lax.reduce_window(
            x,
            0.0,
            lax.add,
            (1, window, window, 1),
            (1, stride, stride, 1),
            "VALID",
        )
        return y / (window * window)

    return _stateless(fn)


def max_pool2d(window: int, stride: Optional[int] = None, padding: int = 0) -> Layer:
    stride = stride or window

    def fn(x):
        return lax.reduce_window(
            x,
            -jnp.inf,
            lax.max,
            (1, window, window, 1),
            (1, stride, stride, 1),
            [(0, 0), (padding, padding), (padding, padding), (0, 0)],
        )

    return _stateless(fn)


def global_avg_pool() -> Layer:
    return _stateless(lambda x: jnp.mean(x, axis=(1, 2)))


def flatten() -> Layer:
    """`out.view(out.size(0), -1)` (`mobilenetv2.py:74`)."""
    return _stateless(lambda x: x.reshape(x.shape[0], -1))


def reshape_head(pool_window: int = 4) -> Layer:
    """relu → avgpool(window) → flatten: the reference's `Reshape1` tail
    module (`mobilenetv2.py:150-158`), used as the pipeline last-stage head
    (`model_parallel.py:144`). Its unused near-twin `Reshape`
    (`distributed_layers.py:64-69`) is intentionally not reproduced."""
    return sequential(relu(), avg_pool2d(pool_window), flatten())


def dropout(rate: float) -> Layer:
    def apply(params, state, x, ctx):
        if not ctx.train or rate == 0.0 or ctx.rng is None:
            return x, state
        keep = jax.random.bernoulli(ctx.rng, 1.0 - rate, x.shape)
        return jnp.where(keep, x / (1.0 - rate), 0.0), state

    return Layer(init=lambda key: ({}, {}), apply=apply)


def identity() -> Layer:
    return _stateless(lambda x: x)


# ---------------------------------------------------------------------------
# Combinators
# ---------------------------------------------------------------------------


def sequential(*layers: Layer) -> Layer:
    """`nn.Sequential` equivalent — params/state are dicts keyed '0','1',…
    so pipeline stage splitting is a dict-key slice, not a module surgery
    (the reference splits with `net.layers[a:b]`, `model_parallel.py:102-144`)."""

    def init(key):
        keys = jax.random.split(key, max(len(layers), 1))
        params, state = {}, {}
        for i, (l, k) in enumerate(zip(layers, keys)):
            p, s = l.init(k)
            params[str(i)] = p
            state[str(i)] = s
        return params, state

    def apply(params, state, x, ctx):
        new_state = {}
        for i, l in enumerate(layers):
            x, s = l.apply(params[str(i)], state[str(i)], x, ctx.child(i))
            new_state[str(i)] = s
        return x, new_state

    return Layer(init, apply)


def named(pairs: Sequence[tuple[str, Layer]]) -> Layer:
    """Sequential with explicit child names (conv1/bn1/... like the torch
    modules), keeping checkpoints and stage splits readable."""

    def init(key):
        keys = jax.random.split(key, max(len(pairs), 1))
        params, state = {}, {}
        for (name, l), k in zip(pairs, keys):
            p, s = l.init(k)
            params[name] = p
            state[name] = s
        return params, state

    def apply(params, state, x, ctx):
        new_state = {}
        for i, (name, l) in enumerate(pairs):
            x, s = l.apply(params[name], state[name], x, ctx.child(i))
            new_state[name] = s
        return x, new_state

    return Layer(init, apply)


def remat(layer: Layer, *, policy=None) -> Layer:
    """Gradient rematerialization (`jax.checkpoint`): activations inside
    `layer` are recomputed during the backward pass instead of stored —
    the standard TPU trade of MXU FLOPs for HBM when deep stacks don't
    fit. Engines expose this as `remat=True` (wrapping the whole model /
    each pipeline stage / each transformer block); `policy` passes
    through to jax.checkpoint (e.g.
    jax.checkpoint_policies.dots_with_no_batch_dims_saveable).

    Static Context fields ride the closure; the rng (a traced array)
    is threaded as a real argument so the checkpointed function stays
    closure-clean for autodiff."""

    def apply(params, state, x, ctx):
        if ctx.rng is None:
            fn = jax.checkpoint(
                lambda p, s, xx: layer.apply(p, s, xx, ctx),
                policy=policy,
            )
            return fn(params, state, x)
        fn = jax.checkpoint(
            lambda p, s, xx, r: layer.apply(
                p, s, xx, dataclasses.replace(ctx, rng=r)
            ),
            policy=policy,
        )
        return fn(params, state, x, ctx.rng)

    return Layer(layer.init, apply)


def residual(body: Layer, shortcut: Optional[Layer] = None) -> Layer:
    """out = body(x) + shortcut(x); shortcut=None means identity."""

    def init(key):
        bkey, skey = jax.random.split(key)
        bp, bs = body.init(bkey)
        params, state = {"body": bp}, {"body": bs}
        if shortcut is not None:
            sp, ss = shortcut.init(skey)
            params["shortcut"] = sp
            state["shortcut"] = ss
        return params, state

    def apply(params, state, x, ctx):
        y, bs = body.apply(params["body"], state["body"], x, ctx.child(0))
        new_state = {"body": bs}
        if shortcut is not None:
            sc, ss = shortcut.apply(params["shortcut"], state["shortcut"], x, ctx.child(1))
            new_state["shortcut"] = ss
        else:
            sc = x
        return y + sc, new_state

    return Layer(init, apply)
