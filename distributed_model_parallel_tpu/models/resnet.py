"""ResNet family — the BASELINE.json benchmark models.

The reference repo itself ships only MobileNetV2, but its benchmark spec
(`BASELINE.json` configs) names ResNet-18 (CIFAR-10, single process) and
ResNet-50 (ImageNet, DataParallel / DDP up to 64 ranks) as the workloads,
and the north-star metric is ResNet-50 images/sec/chip. This module
provides both, in the same pure-functional `Layer` style as the rest of
the zoo so every parallel engine (DP / DDP / pipeline / TP) consumes them
unchanged.

Architecture follows the canonical torchvision definitions (BasicBlock for
18/34, Bottleneck with expansion 4 for 50/101/152), with the standard
CIFAR adaptation (3x3 stride-1 stem, no maxpool) available for the
"ResNet-18 CIFAR-10" config — the same adaptation the reference applies to
MobileNetV2 for CIFAR (`code/distributed_training/model/mobilenetv2.py:42,51,72`).

For pipeline parallelism, `split_stages` partitions the residual blocks
across stages exactly like the MobileNetV2 splitter (stem with stage 0,
classifier head with the last stage).
"""

from __future__ import annotations

from typing import List, Sequence

from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.models import staging


def _basic_block(in_planes: int, planes: int, stride: int) -> L.Layer:
    """conv3x3-BN-ReLU-conv3x3-BN (+projection shortcut), ReLU after add."""
    body = L.named([
        ("conv1", L.conv2d(in_planes, planes, 3, stride=stride, padding=1)),
        ("bn1", L.batchnorm2d(planes)),
        ("relu", L.relu()),
        ("conv2", L.conv2d(planes, planes, 3, stride=1, padding=1)),
        ("bn2", L.batchnorm2d(planes)),
    ])
    shortcut = None
    if stride != 1 or in_planes != planes:
        shortcut = L.named([
            ("conv", L.conv2d(in_planes, planes, 1, stride=stride)),
            ("bn", L.batchnorm2d(planes)),
        ])
    return L.sequential(L.residual(body, shortcut), L.relu())


def _bottleneck(in_planes: int, planes: int, stride: int) -> L.Layer:
    """1x1 reduce — 3x3 — 1x1 expand(×4), ReLU after the residual add."""
    out_planes = planes * 4
    body = L.named([
        ("conv1", L.conv2d(in_planes, planes, 1)),
        ("bn1", L.batchnorm2d(planes)),
        ("relu1", L.relu()),
        ("conv2", L.conv2d(planes, planes, 3, stride=stride, padding=1)),
        ("bn2", L.batchnorm2d(planes)),
        ("relu2", L.relu()),
        ("conv3", L.conv2d(planes, out_planes, 1)),
        ("bn3", L.batchnorm2d(out_planes)),
    ])
    shortcut = None
    if stride != 1 or in_planes != out_planes:
        shortcut = L.named([
            ("conv", L.conv2d(in_planes, out_planes, 1, stride=stride)),
            ("bn", L.batchnorm2d(out_planes)),
        ])
    return L.sequential(L.residual(body, shortcut), L.relu())


_SPECS = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def _make_blocks(depth: int) -> tuple[List[L.Layer], int]:
    kind, counts = _SPECS[depth]
    block = _basic_block if kind == "basic" else _bottleneck
    expansion = 1 if kind == "basic" else 4
    blocks: List[L.Layer] = []
    in_planes = 64
    for stage_i, (planes, n) in enumerate(zip([64, 128, 256, 512], counts)):
        for b in range(n):
            stride = 2 if (stage_i > 0 and b == 0) else 1
            blocks.append(block(in_planes, planes, stride))
            in_planes = planes * expansion
    return blocks, in_planes


def _stem(cifar: bool) -> L.Layer:
    if cifar:
        return L.named([
            ("conv1", L.conv2d(3, 64, 3, stride=1, padding=1)),
            ("bn1", L.batchnorm2d(64)),
            ("relu", L.relu()),
        ])
    return L.named([
        ("conv1", L.conv2d(3, 64, 7, stride=2, padding=3)),
        ("bn1", L.batchnorm2d(64)),
        ("relu", L.relu()),
        ("maxpool", L.max_pool2d(3, 2, padding=1)),
    ])


def _head(feat: int, num_classes: int) -> L.Layer:
    return L.named([
        ("avgpool", L.global_avg_pool()),
        ("fc", L.linear(feat, num_classes)),
    ])


def resnet(depth: int, num_classes: int = 1000, *, cifar: bool = False,
           remat: bool = False) -> L.Layer:
    """Build ResNet-{18,34,50,101,152}. `cifar=True` swaps in the 3x3
    stride-1 stem with no maxpool (the standard CIFAR adaptation)."""
    blocks, feat = _make_blocks(depth)
    if remat:
        blocks = [L.remat(b) for b in blocks]
    return staging.staged_model(
        _stem(cifar), blocks, _head(feat, num_classes)
    )


def resnet18(num_classes: int = 10, *, cifar: bool = True,
             remat: bool = False) -> L.Layer:
    """The 'ResNet-18 CIFAR-10 single-process' BASELINE config."""
    return resnet(18, num_classes, cifar=cifar, remat=remat)


def resnet50(num_classes: int = 1000, *, cifar: bool = False,
             remat: bool = False) -> L.Layer:
    """The north-star benchmark model (images/sec/chip)."""
    return resnet(50, num_classes, cifar=cifar, remat=remat)


def split_stages(depth: int, num_stages: int, num_classes: int = 1000, *,
                 cifar: bool = False,
                 boundaries: Sequence[int] | None = None) -> List[L.Layer]:
    """Partition a ResNet into pipeline stages (stem on stage 0, head on the
    last), via the shared `models/staging.py` convention."""
    blocks, feat = _make_blocks(depth)
    cuts = staging.split_points(num_stages, boundaries, len(blocks))
    return staging.assemble_stages(
        blocks, _stem(cifar), _head(feat, num_classes), cuts
    )


def partition_pytree(tree, depth: int, num_stages: int, *,
                     boundaries: Sequence[int] | None = None) -> List[dict]:
    """Map a full-model params/state pytree ({stem, blocks, head}) onto the
    `split_stages` structure (shared `staging.partition_tree` convention) —
    single-device checkpoints load into pipeline runs and vice versa."""
    _, counts = _SPECS[depth]
    cuts = staging.split_points(num_stages, boundaries, sum(counts))
    return staging.partition_tree(tree, cuts)
