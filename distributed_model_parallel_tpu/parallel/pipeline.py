"""Pipeline model parallelism — SPMD over the `'stage'` mesh axis.

The TPU-native re-design of the reference's hand-rolled cross-process
pipeline (`code/distributed_training/model_parallel.py` +
`code/distributed_training/distributed_layers.py` +
`code/distributed_training/utils.py:34-210`):

reference (rank-scripted, NCCL P2P)          here (mesh-declarative, XLA)
--------------------------------------------  --------------------------------
one OS process per rank, role picked by       one SPMD program; every device
`if rank == 0 / < ws-1 / == ws-1`             runs `lax.switch(axis_index
(`model_parallel.py:99-157`)                  ('stage'), branches)` on its own
                                              stage's weights
`dist.send`/`dist.recv` with a runtime        `lax.ppermute` of a fixed-size
dim/size handshake per transfer               activation buffer; shapes are
(`distributed_layers.py:11-13,40-47`)         static at trace time, handshake
                                              deleted (SURVEY.md §7 hard parts)
`ForwardSend_BackwardReceive` /               plain `jax.grad` through the
`ForwardReceive_BackwardSend` autograd        scan: the transpose of ppermute
pair + the dummy-gradient `output.            IS the reversed permute, so the
backward(recv_size)` hack                     backward schedule emerges from
(`distributed_layers.py:7-62`,                autodiff instead of a hand-built
`utils.py:61-62`)                             protocol
exactly ONE batch in flight => all stages     GPipe fill-drain over
but one idle (`Readme.md:283-292`: MP is      `num_microbatches` M: scan over
4x slower than DP)                            T = M + S - 1 ticks, stage s
                                              works on microbatch t - s;
                                              M=1 reproduces the reference's
                                              single-batch schedule exactly

Three schedules (INTERNALS.md §3b/§3d): `schedule="gpipe"` (above —
backward is autodiff through the tick scan, O(M) live activations per
stage), `schedule="1f1b"` (PipeDream-flush — a hand-scheduled
forward+backward tick program from `build_1f1b_schedule`, per-stage
activation stash bounded by a min(S, M)-deep ring, so M scales until
the bubble is negligible at O(S) memory), and
`schedule="interleaved"` (Megatron's interleaved virtual pipeline,
Narayanan et al. SC'21 — each device owns `virtual_stages=V`
NON-contiguous model chunks, activations ring-route S·V-1 logical hops
over S physical devices, and the bubble floor drops from
(S-1)/(M+S-1) to (S-1)/(V·M+S-1)). Gradients/trajectories are
identical across all three (tests/test_pipeline_schedule.py).

Combinable with data parallelism: a (data=D, stage=S) mesh runs D
independent pipelines, gradients pmean over 'data' and psum over 'stage'
in the same fused reduction.

Design notes:
* Stage parameter STORAGE is a mode: the default replicates the per-stage
  tuple on every device (each device *computes* only its own stage via
  the switch branch — fine at reference scale, MobileNetV2 ~2.3M params);
  `stage_local_params=True` stores params/momentum/BN state as (S, maxP)
  arrays sharded over 'stage' so each device holds ~1/S of the model —
  the memory scaling that makes pipeline MP a memory tool.
* Activations cross stages in one flat buffer padded to the largest
  inter-stage tensor, so every ppermute has one static shape. The buffer
  dtype is the common type of all stage-I/O leaves (bf16 under mixed
  precision — half the ICI bytes of f32). Stage I/O shapes come from a
  setup-time `jax.eval_shape` chain over the stages — the static
  replacement for the reference's per-transfer dim/size messages.
* Invalid ticks (pipeline bubble) still execute the branch on a zeros
  buffer (SPMD lockstep); their outputs and BN-state updates are masked.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from distributed_model_parallel_tpu.runtime.compat import shard_map

from distributed_model_parallel_tpu.models.layers import Context, Layer
from distributed_model_parallel_tpu.models.layers import remat as remat_layer
from distributed_model_parallel_tpu.models.staging import (
    chunk_owner,
    logical_of_row,
    row_of_logical,
    stage_io_avals,
)
from distributed_model_parallel_tpu.parallel.data_parallel import (
    TrainState,
    _cast_input,
    _place_batch,
)
from distributed_model_parallel_tpu.training.metrics import (
    cross_entropy,
    topk_correct,
    valid_count,
)
from distributed_model_parallel_tpu.training.optim import SGD


def _tree_size(aval_tree) -> int:
    """Total element count of a pytree of avals/arrays."""
    return sum(
        math.prod(leaf.shape)
        for leaf in jax.tree_util.tree_leaves(aval_tree)
    )


def _wire_dtype(avals) -> jnp.dtype:
    """Dtype of the inter-stage wire buffer: the common type of every
    stage-I/O leaf. bf16 activations give a bf16 wire (half the ppermute
    bytes of f32); bool masks riding alongside (BERT's (hidden, mask) pair)
    promote into it losslessly (0/1 exact in every float dtype)."""
    dtypes = {
        leaf.dtype
        for in_aval, out_aval in avals
        for leaf in jax.tree_util.tree_leaves((in_aval, out_aval))
    }
    return jnp.result_type(*dtypes) if dtypes else jnp.dtype(jnp.float32)


def _pack(tree, buf_size: int, dtype=jnp.float32) -> jax.Array:
    """Pytree of arrays -> one flat buffer of `dtype` padded to `buf_size`
    (the wire format between stages; one static ppermute shape for
    everything). Also the storage format for stage-local parameters."""
    flats = [
        leaf.astype(dtype).reshape(-1)
        for leaf in jax.tree_util.tree_leaves(tree)
    ]
    if not flats:
        return jnp.zeros((buf_size,), dtype)
    flat = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
    return jnp.zeros((buf_size,), dtype).at[: flat.shape[0]].set(flat)


def _to_host(x):
    """Global array -> host numpy, multi-host safe: a 'stage'-sharded
    array's rows may live on OTHER hosts (non-fully-addressable), where
    plain device_get raises — allgather across processes instead."""
    import numpy as np

    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(jax.device_get(x))


def _pack_np(tree, buf_size: int):
    """Host-side `_pack` (f32 numpy): used when staging per-stage rows
    through host memory must not create device buffers."""
    import numpy as np

    flats = [
        np.asarray(leaf, np.float32).ravel()
        for leaf in jax.tree_util.tree_leaves(tree)
    ]
    row = np.zeros((buf_size,), np.float32)
    if flats:
        flat = np.concatenate(flats) if len(flats) > 1 else flats[0]
        row[: flat.shape[0]] = flat
    return row


def _unpack(buf: jax.Array, aval_tree):
    """Inverse of `_pack` given the target aval pytree."""
    leaves, treedef = jax.tree_util.tree_flatten(aval_tree)
    out, offset = [], 0
    for leaf in leaves:
        n = math.prod(leaf.shape)
        out.append(
            buf[offset:offset + n].reshape(leaf.shape).astype(leaf.dtype)
        )
        offset += n
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# 1F1B (PipeDream-flush) tick schedule — built on the host at setup time.
# ---------------------------------------------------------------------------

# Per-(tick, stage) work kinds. IDLE ticks are pipeline bubble: the SPMD
# program still executes a (masked) forward branch in lockstep.
PIPE_IDLE, PIPE_FWD, PIPE_BWD = 0, 1, 2


class Schedule1F1B(NamedTuple):
    """Static tick tables for the 1F1B schedule, all shaped (T, S).

    `work[t, s]` / `micro[t, s]` say what stage s computes at tick t;
    `recv_fwd*` / `recv_bwd*` say whether the activation (up) / cotangent
    (down) wire buffer a stage holds at the START of tick t carries a
    valid payload, and for which microbatch — the receive side of the
    schedule, derived from the sender side one tick earlier. Ring depths
    are the peak number of simultaneously-live activations / cotangents
    at any stage: the O(S) memory bound that is the point of 1F1B."""

    work: np.ndarray
    micro: np.ndarray
    recv_fwd: np.ndarray
    recv_fwd_m: np.ndarray
    recv_bwd: np.ndarray
    recv_bwd_m: np.ndarray
    num_ticks: int
    stash_depth: int
    cot_depth: int


def _min_ring_depth(intervals_per_slotkey: dict, max_key: int) -> int:
    """Smallest ring depth R such that assigning key k to slot k % R never
    overlaps two live intervals [start, end] (inclusive; arrival happens
    BEFORE compute within a tick, so reuse must be strictly later)."""
    for depth in range(1, max_key + 2):
        ok = True
        for (s, m), (start, _end) in intervals_per_slotkey.items():
            prev = intervals_per_slotkey.get((s, m - depth))
            if prev is not None and start <= prev[1]:
                ok = False
                break
        if ok:
            return depth
    return max_key + 1


def build_1f1b_schedule(num_stages: int, num_microbatches: int) -> Schedule1F1B:
    """One-forward-one-backward (PipeDream-flush) tick program.

    Stage s warms up with min(S-1-s, M) forwards, then alternates
    (forward, backward) pairs, then drains the remaining backwards —
    Megatron's non-interleaved 1F1B work order. Ticks are assigned by a
    greedy lockstep simulation: at each tick a stage runs the head of its
    work queue iff its dependencies completed at an EARLIER tick (one
    ppermute hop separates producer and consumer), else it idles. The
    program length never exceeds 2M + 2(S-1) — the same fill+drain span
    as GPipe's forward+backward — while the number of microbatch
    activations any stage holds live stays <= min(S, M), independent of M
    (GPipe-through-autodiff holds all M)."""
    S, M = num_stages, num_microbatches
    if S < 1 or M < 1:
        raise ValueError(f"need S >= 1, M >= 1; got S={S}, M={M}")
    queues = []
    for s in range(S):
        warm = min(S - 1 - s, M)
        q = [(PIPE_FWD, m) for m in range(warm)]
        for i in range(M - warm):
            q.append((PIPE_FWD, warm + i))
            q.append((PIPE_BWD, i))
        q.extend((PIPE_BWD, m) for m in range(M - warm, M))
        queues.append(q)

    done_f = [[None] * M for _ in range(S)]  # tick stage s finished fwd m
    done_b = [[None] * M for _ in range(S)]
    heads = [0] * S
    work_rows, micro_rows = [], []
    t = 0
    while any(heads[s] < len(queues[s]) for s in range(S)):
        if t > 2 * M + 2 * S:  # greedy 1F1B provably fits well inside this
            raise RuntimeError(
                f"1F1B schedule deadlocked at tick {t} (S={S}, M={M})"
            )
        row_w, row_m = [PIPE_IDLE] * S, [0] * S
        for s in range(S):
            if heads[s] >= len(queues[s]):
                continue
            kind, m = queues[s][heads[s]]
            if kind == PIPE_FWD:
                ready = s == 0 or (
                    done_f[s - 1][m] is not None and done_f[s - 1][m] < t
                )
            else:
                ready = done_f[s][m] is not None and done_f[s][m] < t
                if s < S - 1:
                    ready = ready and (
                        done_b[s + 1][m] is not None and done_b[s + 1][m] < t
                    )
            if ready:
                row_w[s], row_m[s] = kind, m
        # Commit after scanning every stage: this tick's completions become
        # visible only from t+1 (the `< t` checks above), matching the
        # one-tick ppermute latency of the lockstep SPMD program.
        for s in range(S):
            if row_w[s] == PIPE_FWD:
                done_f[s][row_m[s]] = t
                heads[s] += 1
            elif row_w[s] == PIPE_BWD:
                done_b[s][row_m[s]] = t
                heads[s] += 1
        work_rows.append(row_w)
        micro_rows.append(row_m)
        t += 1

    T = t
    assert T <= 2 * M + 2 * (S - 1) or S == 1, (T, S, M)
    work = np.asarray(work_rows, np.int32)
    micro = np.asarray(micro_rows, np.int32)

    # Receive tables: what the wire buffers hold at the START of tick t is
    # whatever the neighbor put on them at tick t-1.
    recv_fwd = np.zeros((T, S), bool)
    recv_fwd_m = np.zeros((T, S), np.int32)
    recv_bwd = np.zeros((T, S), bool)
    recv_bwd_m = np.zeros((T, S), np.int32)
    for tt in range(1, T):
        for s in range(S):
            if s >= 1 and work[tt - 1, s - 1] == PIPE_FWD:
                recv_fwd[tt, s] = True
                recv_fwd_m[tt, s] = micro[tt - 1, s - 1]
            if s <= S - 2 and work[tt - 1, s + 1] == PIPE_BWD:
                recv_bwd[tt, s] = True
                recv_bwd_m[tt, s] = micro[tt - 1, s + 1]

    # Ring depths from the exact live intervals (inclusive ticks):
    # * activation stash at stage s>=1: arrival F(s-1,m)+1 .. consumption
    #   by the backward B(s,m) (stage 0 reads the resident input batch
    #   directly and never stashes);
    # * cotangent at stage s<=S-2: arrival B(s+1,m)+1 .. B(s,m).
    stash_iv = {
        (s, m): (done_f[s - 1][m] + 1, done_b[s][m])
        for s in range(1, S)
        for m in range(M)
    }
    cot_iv = {
        (s, m): (done_b[s + 1][m] + 1, done_b[s][m])
        for s in range(S - 1)
        for m in range(M)
    }
    stash_depth = _min_ring_depth(stash_iv, M - 1) if stash_iv else 1
    cot_depth = _min_ring_depth(cot_iv, M - 1) if cot_iv else 1
    if stash_depth > min(S, M):
        raise RuntimeError(  # the O(S) guarantee this schedule exists for
            f"1F1B stash depth {stash_depth} exceeds min(S, M)="
            f"{min(S, M)} at S={S}, M={M}"
        )
    return Schedule1F1B(
        work, micro, recv_fwd, recv_fwd_m, recv_bwd, recv_bwd_m,
        T, stash_depth, cot_depth,
    )


# ---------------------------------------------------------------------------
# Interleaved virtual-pipeline tick schedule (Megatron SC'21) — the (T, S, V)
# generalization of the 1F1B tables. V=1 reduces EXACTLY to
# `build_1f1b_schedule` (pinned by tests/test_pipeline_schedule.py).
# ---------------------------------------------------------------------------


class ScheduleTicks(NamedTuple):
    """Static tick tables generalized over `virtual_stages` V, all shaped
    (T, S). Each physical stage owns V model chunks; `chunk[t, s]` names
    which of device s's chunks runs at tick t (the logical pipeline stage
    is `chunk * S + s`, so device s owns logical stages {s, s+S, ...} —
    Megatron's round-robin chunk placement). The recv tables gain a
    chunk column: the activation (up-ring) / cotangent (down-ring) wire
    payload a device holds at the START of tick t belongs to ring slot
    `recv_*_c * depth + recv_*_m % depth`. Ring depths are PER-CHUNK:
    the stash array is (V * stash_depth, buf)."""

    work: np.ndarray
    micro: np.ndarray
    chunk: np.ndarray
    recv_fwd: np.ndarray
    recv_fwd_m: np.ndarray
    recv_fwd_c: np.ndarray
    recv_bwd: np.ndarray
    recv_bwd_m: np.ndarray
    recv_bwd_c: np.ndarray
    num_ticks: int
    stash_depth: int
    cot_depth: int
    num_virtual: int


def build_interleaved_schedule(
    num_stages: int, num_microbatches: int, virtual_stages: int = 1
) -> ScheduleTicks:
    """Interleaved 1F1B tick program over S devices × V chunks each.

    Work order per device is Megatron's (Narayanan et al., SC'21,
    `megatron/core/pipeline_parallel/schedules.py`): microbatches are
    processed in groups of S — forward k runs chunk (k//S) % V on
    microbatch (k//(S·V))·S + k%S, backwards mirror with the chunk
    order reversed — with warmup 2(S-1-s) + (V-1)·S forwards before the
    first backward (V=1 keeps the non-interleaved min(S-1-s, M), which
    makes the V=1 tables bit-identical to `build_1f1b_schedule`). Ticks
    are assigned by the same greedy lockstep simulation: dependencies
    are between LOGICAL stages l = v·S + s (one ring-ppermute hop, so a
    consumer runs strictly after its producer's tick).

    The payoff is the span: T = 2MV + 2(S-1) chunk-ticks for 2MV
    chunk-ticks of work per device, i.e. an idle fraction of
    (S-1)/(V·M+S-1) — the 1F1B bubble divided by V (each chunk-tick is
    1/V of a stage-tick of compute, so the fill/drain cost shrinks by V
    while total compute is unchanged). The price is stash memory: early
    chunks' activations live until their late backwards, so the
    per-chunk ring depth grows past min(S, M) (bounded below by the
    exact live intervals, asserted <= min(M, 2S) here) and there are V
    rings. Megatron requires M % S == 0 for V > 1; so do we.
    """
    S, M, V = num_stages, num_microbatches, virtual_stages
    if S < 1 or M < 1 or V < 1:
        raise ValueError(f"need S, M, V >= 1; got S={S}, M={M}, V={V}")
    if V > 1 and S < 2:
        raise ValueError(
            f"interleaving needs >= 2 physical stages, got S={S}"
        )
    if V > 1 and M % S:
        raise ValueError(
            f"interleaved schedule needs num_microbatches divisible by "
            f"num_stages (Megatron's round-robin microbatch groups); "
            f"got M={M}, S={S}"
        )
    C = S * V          # logical pipeline depth
    total = M * V      # forward (and backward) chunk-ticks per device

    def fwd_item(k):
        return (PIPE_FWD, (k // C) * S + k % S, (k // S) % V)

    def bwd_item(k):
        return (PIPE_BWD, (k // C) * S + k % S, V - 1 - (k // S) % V)

    queues = []
    for s in range(S):
        warm = (
            min(S - 1 - s, M) if V == 1
            else min(2 * (S - 1 - s) + (V - 1) * S, total)
        )
        q = [fwd_item(k) for k in range(warm)]
        for i in range(total - warm):
            q.append(fwd_item(warm + i))
            q.append(bwd_item(i))
        q.extend(bwd_item(i) for i in range(total - warm, total))
        queues.append(q)

    done_f = [[None] * M for _ in range(C)]  # tick logical l finished fwd m
    done_b = [[None] * M for _ in range(C)]
    heads = [0] * S
    work_rows, micro_rows, chunk_rows = [], [], []
    t = 0
    while any(heads[s] < len(queues[s]) for s in range(S)):
        if t > 2 * total + 4 * C:
            raise RuntimeError(
                f"interleaved schedule deadlocked at tick {t} "
                f"(S={S}, M={M}, V={V})"
            )
        row_w = [PIPE_IDLE] * S
        row_m = [0] * S
        row_c = [0] * S
        for s in range(S):
            if heads[s] >= len(queues[s]):
                continue
            kind, m, v = queues[s][heads[s]]
            l = v * S + s
            if kind == PIPE_FWD:
                ready = l == 0 or (
                    done_f[l - 1][m] is not None and done_f[l - 1][m] < t
                )
            else:
                ready = done_f[l][m] is not None and done_f[l][m] < t
                if l < C - 1:
                    ready = ready and (
                        done_b[l + 1][m] is not None and done_b[l + 1][m] < t
                    )
            if ready:
                row_w[s], row_m[s], row_c[s] = kind, m, v
        # Commit after scanning every stage (one-tick ppermute latency).
        for s in range(S):
            l = row_c[s] * S + s
            if row_w[s] == PIPE_FWD:
                done_f[l][row_m[s]] = t
                heads[s] += 1
            elif row_w[s] == PIPE_BWD:
                done_b[l][row_m[s]] = t
                heads[s] += 1
        work_rows.append(row_w)
        micro_rows.append(row_m)
        chunk_rows.append(row_c)
        t += 1

    T = t
    # The bubble guarantee the schedule exists for: fill+drain only ever
    # costs the FIRST/LAST chunk's pipeline, 2(S-1) chunk-ticks total.
    assert T <= 2 * total + 2 * (S - 1) or S == 1, (T, S, M, V)
    work = np.asarray(work_rows, np.int32)
    micro = np.asarray(micro_rows, np.int32)
    chunk = np.asarray(chunk_rows, np.int32)

    # Receive tables. The wire is a RING: up payloads come from device
    # (s-1) mod S, down payloads from (s+1) mod S — the wrap edge is how
    # an activation crosses a chunk boundary (logical v·S+S-1 -> (v+1)·S
    # lives on device S-1 -> device 0). For V == 1 the wrap edge never
    # carries a valid payload (its sender would be the last / first
    # logical stage), so these tables equal the 1F1B chain tables.
    recv_fwd = np.zeros((T, S), bool)
    recv_fwd_m = np.zeros((T, S), np.int32)
    recv_fwd_c = np.zeros((T, S), np.int32)
    recv_bwd = np.zeros((T, S), bool)
    recv_bwd_m = np.zeros((T, S), np.int32)
    recv_bwd_c = np.zeros((T, S), np.int32)
    if S > 1:
        for tt in range(1, T):
            for s in range(S):
                sp = (s - 1) % S
                if work[tt - 1, sp] == PIPE_FWD:
                    l = chunk[tt - 1, sp] * S + sp
                    if l < C - 1:
                        recv_fwd[tt, s] = True
                        recv_fwd_m[tt, s] = micro[tt - 1, sp]
                        recv_fwd_c[tt, s] = (l + 1) // S
                sn = (s + 1) % S
                if work[tt - 1, sn] == PIPE_BWD:
                    l = chunk[tt - 1, sn] * S + sn
                    if l > 0:
                        recv_bwd[tt, s] = True
                        recv_bwd_m[tt, s] = micro[tt - 1, sn]
                        recv_bwd_c[tt, s] = (l - 1) // S
    # Per-chunk ring depths from the exact live intervals, keyed by
    # ((device, chunk), m) so reuse conflicts are checked within each
    # chunk's own ring (slot = chunk * depth + m % depth).
    stash_iv = {}
    cot_iv = {}
    for s in range(S):
        for v in range(V):
            l = v * S + s
            for m in range(M):
                if l >= 1:
                    stash_iv[((s, v), m)] = (
                        done_f[l - 1][m] + 1, done_b[l][m]
                    )
                if l <= C - 2:
                    cot_iv[((s, v), m)] = (
                        done_b[l + 1][m] + 1, done_b[l][m]
                    )
    stash_depth = _min_ring_depth(stash_iv, M - 1) if stash_iv else 1
    cot_depth = _min_ring_depth(cot_iv, M - 1) if cot_iv else 1
    if stash_depth > min(M, 2 * S if V > 1 else S):
        raise RuntimeError(
            f"interleaved stash depth {stash_depth} exceeds the "
            f"documented bound min(M, 2S) at S={S}, M={M}, V={V}"
        )
    return ScheduleTicks(
        work, micro, chunk,
        recv_fwd, recv_fwd_m, recv_fwd_c,
        recv_bwd, recv_bwd_m, recv_bwd_c,
        T, stash_depth, cot_depth, V,
    )


@dataclasses.dataclass
class PipelineEngine:
    """GPipe-style pipeline engine over the `'stage'` mesh axis.

    `stages` is the output of a model family's `split_stages` (e.g.
    `mobilenetv2.split_stages(4, boundaries=[3, 9, 15])` for the
    reference's exact ws=4 partition). `num_microbatches=1` is the
    reference's schedule (one batch in flight); raise it to fill the
    pipeline (bubble fraction (S-1)/(M+S-1))."""

    stages: List[Layer]
    optimizer: Any  # SGD | AdamW (init/update/state_shardings protocol)
    mesh: Mesh
    num_microbatches: int = 1
    sync_bn: bool = False
    donate: bool = True
    compute_dtype: Any = None  # mixed precision; see DataParallelEngine
    # Rematerialize each stage's forward during backward (jax.checkpoint).
    remat: bool = False
    # Stage-local parameter storage: params / BN state / momentum live as
    # (S, maxP) f32 arrays sharded over 'stage', so each device STORES
    # ~1/S of the model instead of all of it — the memory scaling that is
    # the reason pipeline MP exists (the reference splits the model across
    # GPUs for exactly this, `model_parallel.py:99-157`). Each device
    # unpacks only its own stage's slice inside the step; gradients stay
    # local to their stage's devices (no psum over 'stage' needed).
    # False keeps the replicated representation (params as a per-stage
    # tuple of pytrees on every device).
    stage_local_params: bool = False
    # Pipeline schedule:
    # * "gpipe" — fill-drain: all M forwards, then all M backwards (the
    #   backward derived by autodiff through the tick scan). Live
    #   activation memory grows O(M) per stage: the memory the schedule
    #   needs grows exactly as fast as raising M shrinks the bubble.
    # * "1f1b"  — PipeDream-flush: warmup, then each stage alternates one
    #   forward and one backward tick (hand-scheduled vjp per stage, same
    #   2(M+S-1)-tick span). Live activations are capped by a
    #   min(S, M)-deep ring buffer, independent of M — so microbatch
    #   count can scale until the bubble is negligible. Gradients and BN
    #   state match "gpipe" exactly (same per-microbatch math, same
    #   fold order); only the schedule and its memory change.
    # * "interleaved" — Megatron's interleaved virtual pipeline
    #   (Narayanan et al. SC'21): `stages` holds S·V chunks, device s
    #   owns the NON-contiguous set {s, s+S, ...}, and the 1F1B tick
    #   program generalizes to (microbatch, chunk) pairs riding a RING
    #   ppermute (the wrap edge carries chunk-boundary hops). Each
    #   chunk-tick is 1/V of a stage-tick of compute, so the fill/drain
    #   bubble drops to (S-1)/(V·M+S-1) — the 1F1B floor divided by V —
    #   at the price of deeper activation rings (V rings of depth
    #   <= min(M, 2S) instead of one of depth min(S, M)) and one
    #   ppermute per chunk-tick. Needs M % S == 0 when V > 1.
    schedule: str = "gpipe"
    # Model chunks per device under schedule="interleaved" (V). 1 keeps
    # one chunk per device (the plain 1F1B tick tables).
    virtual_stages: int = 1

    def __post_init__(self):
        mesh = self.mesh
        if self.schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"schedule must be 'gpipe', '1f1b' or 'interleaved', "
                f"got {self.schedule!r}"
            )
        if self.virtual_stages < 1:
            raise ValueError(
                f"virtual_stages must be >= 1, got {self.virtual_stages}"
            )
        if self.virtual_stages > 1 and self.schedule != "interleaved":
            raise ValueError(
                "virtual_stages > 1 requires schedule='interleaved' "
                "(gpipe/1f1b run exactly one chunk per device)"
            )
        if "stage" not in mesh.axis_names:
            raise ValueError("pipeline mesh needs a 'stage' axis")
        self.num_stages = mesh.shape["stage"]
        # V chunks per device; C = S·V logical pipeline stages. For the
        # non-interleaved schedules V == 1 and chunks == stages.
        self._V = self.virtual_stages if self.schedule == "interleaved" \
            else 1
        self.num_chunks = self.num_stages * self._V
        if self.num_chunks != len(self.stages):
            raise ValueError(
                f"{len(self.stages)} stage chunks but mesh 'stage' axis "
                f"size {self.num_stages} x virtual_stages {self._V} "
                f"needs {self.num_chunks}"
            )
        self._repl = NamedSharding(mesh, P())
        self._batch = NamedSharding(mesh, P(("data",)))

        # Per-stage param/state avals from an abstract trace of init —
        # the static metadata both param representations are built from.
        key_aval = jax.ShapeDtypeStruct((2,), jnp.uint32)
        self._param_avals, self._state_avals = [], []
        for stage in self.stages:
            p_aval, s_aval = jax.eval_shape(stage.init, key_aval)
            self._param_avals.append(p_aval)
            self._state_avals.append(s_aval)
        # MoE aux losses ride the layer state ("moe_aux" leaves), and the
        # pipeline computes its loss on the LAST stage's devices only —
        # folding other stages' aux in would need a differentiated
        # psum('stage'), which this engine's autodiff discipline excludes
        # (see _make_step). Refuse loudly rather than silently training
        # an unbalanced router (only the GSPMD engines consume moe_aux).
        for s_aval in self._state_avals:
            for path, _ in jax.tree_util.tree_leaves_with_path(s_aval):
                if path and getattr(path[-1], "key", None) == "moe_aux":
                    raise NotImplementedError(
                        "MoE layers are not supported inside PipelineEngine "
                        "stages: the load-balance aux loss cannot reach the "
                        "last-stage loss without a differentiated 'stage' "
                        "collective. Train MoE models with the DP / DDP / "
                        "TensorParallel / ExpertParallel engines."
                    )
        self._psize = max(
            (_tree_size(a) for a in self._param_avals), default=1
        ) or 1
        self._ssize = max(
            (_tree_size(a) for a in self._state_avals), default=1
        ) or 1
        self._stage_sh = NamedSharding(mesh, P(("stage",)))
        if self.stage_local_params:
            # Validate the optimizer's state_shardings declaration NOW:
            # a field built from neither protocol argument would otherwise
            # surface as an opaque trace/spec error inside the first
            # checkpoint or step build (and legacy shard_map validates
            # specs eagerly). Construction is where a protocol violation
            # should be loud.
            self._opt_param_fields()
        # Hand-scheduled tick tables are static in (S, M, V): build once,
        # fail early. "1f1b" rides the generalized builder at V=1, whose
        # tables are bit-identical to `build_1f1b_schedule`'s
        # (tests/test_pipeline_schedule.py pins the reduction).
        self._sched = (
            build_interleaved_schedule(
                self.num_stages, self.num_microbatches, self._V
            )
            if self.schedule in ("1f1b", "interleaved") else None
        )

        donate = (0,) if self.donate else ()
        self.train_step = jax.jit(
            self._make_step(train=True), donate_argnums=donate
        )
        self.eval_step = jax.jit(self._make_step(train=False))

    # ------------------------------------------------------------ setup

    def init_state(self, rng: jax.Array) -> TrainState:
        if not self.stage_local_params:
            params, state = [], []
            for i, stage in enumerate(self.stages):
                p, s = stage.init(jax.random.fold_in(rng, i))
                params.append(p)
                state.append(s)
            params, state = tuple(params), tuple(state)
            opt_state = self.optimizer.init(params)
            ts = TrainState(
                params, state, opt_state, jnp.zeros((), jnp.int32)
            )
            return jax.device_put(ts, self._repl)
        # Stage-local: per-chunk flats become rows of (S·V, maxP) /
        # (S·V, maxS) arrays sharded over 'stage'. Rows are DEVICE-MAJOR
        # (`staging.row_of_logical`): row s·V + v holds logical chunk
        # v·S + s, so the P('stage') sharding lands each device's V
        # interleaved chunks on it as local rows 0..V-1 (identity when
        # V == 1). Each chunk is initialized, moved to HOST memory, and
        # packed there before the next chunk initializes (so at most ONE
        # chunk's params are device-resident at a time), then the stacked
        # array materializes shard-by-shard (make_array_from_callback) —
        # the point of this mode is that the whole model doesn't fit per
        # device, so init must never assemble it on one.
        p_rows, s_rows = [], []
        for r in range(self.num_chunks):
            i = logical_of_row(r, self.num_stages, self._V)
            p, s = self.stages[i].init(jax.random.fold_in(rng, i))
            p_rows.append(_pack_np(jax.device_get(p), self._psize))
            s_rows.append(_pack_np(jax.device_get(s), self._ssize))
            del p, s
        flat_p = self._stack_local(p_rows)
        flat_s = self._stack_local(s_rows)
        # zeros_like keeps the 'stage' sharding for param-shaped buffers;
        # scalar fields (AdamW's count) come back process-local and must
        # be placed on the mesh like `step` below — state_shardings says
        # which is which.
        opt_state = jax.device_put(
            self.optimizer.init(flat_p),
            self.optimizer.state_shardings(self._stage_sh, self._repl),
        )
        return TrainState(
            flat_p, flat_s, opt_state,
            jax.device_put(jnp.zeros((), jnp.int32), self._repl),
        )

    def _stack_local(self, np_rows) -> jax.Array:
        """[per-stage 1-D host rows] -> (S, width) array sharded
        P('stage'), materialized shard-by-shard so the full stack never
        exists on one device."""
        import numpy as np

        np_rows = np.stack(np_rows)
        return jax.make_array_from_callback(
            np_rows.shape, self._stage_sh, lambda idx: np_rows[idx]
        )

    def params_tree(self, ts: TrainState):
        """The per-stage tuple-of-pytrees view of `ts.params`, whichever
        representation the engine uses — for checkpoint interop, weight
        transplant, and tests."""
        if not self.stage_local_params:
            return ts.params
        flat = _to_host(ts.params)
        return tuple(
            _unpack(
                flat[row_of_logical(i, self.num_stages, self._V)],
                self._param_avals[i],
            )
            for i in range(self.num_chunks)
        )

    # ---------------------------------------------- checkpoint canonical

    def _unpack_stages(self, flat_host, avals):
        """Device-major packed rows -> LOGICAL-order per-chunk tuple (the
        canonical checkpoint order; identity permutation at V == 1)."""
        return tuple(
            _unpack(
                flat_host[row_of_logical(i, self.num_stages, self._V)],
                avals[i],
            )
            for i in range(self.num_chunks)
        )

    def _opt_param_fields(self) -> dict:
        """Which optimizer-state fields follow the params (and are
        therefore packed (S, maxP) in stage-local mode) versus stay
        replicated — read from the optimizer's own `state_shardings`
        DECLARATION via a sentinel probe, NOT from shape or tuple-length
        heuristics: a future field that merely *happens* to be shaped
        (num_stages, psize), or a length-S tuple, must not silently
        mis-serialize (ADVICE r3 #2)."""
        p_mark, r_mark = object(), object()
        decl = self.optimizer.state_shardings(p_mark, r_mark)
        fields = {}
        for k, v in decl._asdict().items():
            if v is p_mark:
                fields[k] = True
            elif v is r_mark:
                fields[k] = False
            else:
                raise ValueError(
                    f"optimizer.state_shardings built field {k!r} from "
                    f"neither the param-sharding pytree nor the "
                    f"replicated sharding; PipelineEngine cannot infer "
                    f"its checkpoint layout. Declare each field as one "
                    f"of the two protocol arguments."
                )
        return fields

    def to_canonical(self, ts: TrainState) -> TrainState:
        """TrainState in the layout-independent checkpoint form: params /
        BN state / optimizer buffers as per-stage tuples of pytrees with
        real layer paths and shapes. Checkpoints written this way are
        interchangeable between stage_local_params modes (and validate
        per-layer structure on restore, which a packed (S, maxP) leaf
        cannot).

        Optimizer-state protocol: a NamedTuple whose fields are either
        param-shaped buffers (packed (S, maxP) here — SGD momentum,
        AdamW moments) or replicated scalars (AdamW's count); which is
        which comes from the optimizer's `state_shardings` declaration
        (`_opt_param_fields`)."""
        if not self.stage_local_params:
            return ts
        follows = self._opt_param_fields()

        def canon_opt_field(k, v):
            if follows[k]:
                return self._unpack_stages(_to_host(v), self._param_avals)
            return v

        opt_c = type(ts.opt_state)(
            **{
                k: canon_opt_field(k, v)
                for k, v in ts.opt_state._asdict().items()
            }
        )
        state = self._unpack_stages(
            _to_host(ts.model_state), self._state_avals
        )
        return TrainState(self.params_tree(ts), state, opt_c, ts.step)

    def from_canonical(self, ts: TrainState) -> TrainState:
        """Inverse of `to_canonical`: re-pack a canonical TrainState into
        this engine's runtime layout and placement."""
        if not self.stage_local_params:
            return jax.device_put(ts, self._repl)

        def rows(tree_tuple, size):
            """Logical-order per-chunk tuple -> device-major packed rows
            (the storage layout `init_state` builds)."""
            return [
                _pack_np(
                    tree_tuple[logical_of_row(r, self.num_stages, self._V)],
                    size,
                )
                for r in range(self.num_chunks)
            ]

        flat_p = self._stack_local(rows(ts.params, self._psize))
        flat_s = self._stack_local(rows(ts.model_state, self._ssize))

        follows = self._opt_param_fields()

        def pack_opt_field(k, v):
            if follows[k]:
                return self._stack_local(rows(v, self._psize))
            return jax.device_put(jnp.asarray(v), self._repl)

        opt_p = type(ts.opt_state)(
            **{
                k: pack_opt_field(k, v)
                for k, v in ts.opt_state._asdict().items()
            }
        )
        return TrainState(
            flat_p, flat_s, opt_p,
            jax.device_put(jnp.asarray(ts.step), self._repl),
        )

    def shard_batch(self, images, labels):
        return _place_batch((images, labels), self._batch)

    def _stage_avals(self, x_aval, train: bool):
        """(input_avals, output_avals) per stage — `staging.stage_io_avals`
        on this engine's abstract params/state; everything crosses stages
        packed into one flat buffer of the common wire dtype."""
        return stage_io_avals(
            self.stages, self._param_avals, self._state_avals, x_aval,
            Context(train=train, dtype=self.compute_dtype),
        )

    # ------------------------------------------------------- the program

    def _make_step(self, train: bool):
        S = self.num_stages
        M = self.num_microbatches
        V = self._V
        C = self.num_chunks
        mesh = self.mesh
        bn_axis = "data" if self.sync_bn else None
        cdt = self.compute_dtype
        local = self.stage_local_params
        exec_stages = (
            [remat_layer(s) for s in self.stages] if self.remat
            else self.stages
        )

        def stage_params(params, i, row=0):
            """Logical chunk i's param pytree from either representation.
            In stage-local mode every device holds ONLY its own chunks'
            (V, maxP) slice, device-major, so local row `row` (= the
            chunk index v on the owning device) selects it; the unpack
            is differentiable, so the grad wrt the flat slice is the
            full chunk-i gradient."""
            return _unpack(params[row], self._param_avals[i]) if local \
                else params[i]

        def stage_state(state, i, row=0):
            return _unpack(state[row], self._state_avals[i]) if local \
                else state[i]

        def program_setup(images):
            """Static per-trace metadata shared by both schedules: cast
            input, microbatch split, the stage-I/O aval chain, the logits
            contract of the last stage, and the wire buffer format."""
            images = _cast_input(images, cdt)
            n_local = images.shape[0]
            if n_local % M:
                raise ValueError(
                    f"local batch {n_local} not divisible by "
                    f"num_microbatches {M}"
                )
            mb = n_local // M
            x_aval = jax.ShapeDtypeStruct(
                (mb,) + images.shape[1:], images.dtype
            )
            avals = self._stage_avals(x_aval, train)
            out_leaves = jax.tree_util.tree_leaves(avals[-1][1])
            if len(out_leaves) != 1 or len(out_leaves[0].shape) != 2:
                raise ValueError(
                    "last pipeline stage must output a single (rows, "
                    f"classes) logits array, got {avals[-1][1]} — "
                    "classification heads emit (microbatch, classes); "
                    "token-level (LM) heads flatten to (microbatch*T, "
                    "vocab) (models/gpt.py split_stages)"
                )
            # Logits rows per microbatch, from the traced aval — mb for
            # classification heads, mb*T for token-level LM heads (whose
            # labels arrive pre-flattened to (B*T,) so rows line up).
            rows, num_classes = out_leaves[0].shape
            buf_size = max(_tree_size(out) for _, out in avals)
            wire_dt = _wire_dtype(avals)
            return images, mb, avals, rows, num_classes, buf_size, wire_dt

        def pipeline_forward(params, model_state, images, labels, step):
            """Runs on ONE device (inside shard_map): the full fill-drain
            schedule for this device's stage. Returns (sum CE over local
            batch, logits for the local batch, updated state)."""
            images, mb, avals, rows, num_classes, buf_size, wire_dt = (
                program_setup(images)
            )
            s_idx = lax.axis_index("stage")

            def make_branch(i):
                in_aval = avals[i][0]

                def branch(operand):
                    state, buf, images_mb, rng = operand
                    ctx = Context(
                        train=train, bn_axis=bn_axis, rng=rng, dtype=cdt
                    )
                    if i == 0:
                        x = images_mb
                    else:
                        x = _unpack(buf, in_aval)
                    y, new_si = exec_stages[i].apply(
                        stage_params(params, i), stage_state(state, i),
                        x, ctx,
                    )
                    y_pad = _pack(y, buf_size, wire_dt)
                    if local:
                        new_state = _pack(new_si, self._ssize)[None, :]
                    else:
                        new_state = tuple(
                            new_si if j == i else state[j] for j in range(S)
                        )
                    return y_pad, new_state

                return branch

            branches = [make_branch(i) for i in range(S)]
            images_mbs = images.reshape((M, mb) + images.shape[1:])
            rng_base = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(0), step),
                lax.axis_index("data"),
            )

            def tick(carry, t):
                buf, state, out_stack = carry
                m = t - s_idx
                valid = (m >= 0) & (m < M)
                m_safe = jnp.clip(m, 0, M - 1)
                images_mb = lax.dynamic_index_in_dim(
                    images_mbs, m_safe, keepdims=False
                )
                # Per-(stage, microbatch) dropout key: every stage draws
                # independent masks for each microbatch of this step.
                rng = jax.random.fold_in(
                    jax.random.fold_in(rng_base, s_idx), m_safe
                )
                y_pad, new_state = lax.switch(
                    s_idx, branches, (state, buf, images_mb, rng)
                )
                # Mask bubble ticks: keep old BN stats, zero the output so
                # garbage never reaches the logits stack.
                state = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(valid, new, old),
                    new_state, state,
                )
                y_pad = jnp.where(valid, y_pad, jnp.zeros_like(y_pad))
                # Logits stack stays f32 regardless of the wire dtype so
                # the loss/metrics see the same precision on every path.
                logits_mb = (
                    y_pad[: rows * num_classes]
                    .reshape(rows, num_classes)
                    .astype(jnp.float32)
                )
                out_stack = lax.dynamic_update_index_in_dim(
                    out_stack,
                    jnp.where(
                        valid,
                        logits_mb,
                        lax.dynamic_index_in_dim(out_stack, m_safe, 0, False),
                    ),
                    m_safe,
                    axis=0,
                )
                if S > 1:
                    buf = lax.ppermute(
                        y_pad, "stage", [(i, i + 1) for i in range(S - 1)]
                    )
                return (buf, state, out_stack), None

            buf0 = jnp.zeros((buf_size,), wire_dt)
            out0 = jnp.zeros((M, rows, num_classes), jnp.float32)
            (buf, new_state, out_stack), _ = lax.scan(
                tick,
                (buf0, model_state, out0),
                jnp.arange(M + S - 1),
            )
            logits = out_stack.reshape(M * rows, num_classes)
            # CE only counts on the last stage (the only device whose
            # out_stack holds real logits). NO psum here: the loss must stay
            # local so autodiff never transposes a cross-device reduction
            # (under check_vma=False a differentiated psum mis-scales
            # cotangents); the reversed ppermutes alone carry the true
            # cotangents upstream, and callers psum the VALUE for
            # reporting after grad.
            is_last = (s_idx == S - 1).astype(logits.dtype)
            loss_sum = (
                cross_entropy(logits, labels) * valid_count(labels) * is_last
            )
            return loss_sum, (logits, new_state, is_last)

        sched = self._sched
        interleaved = self.schedule == "interleaved"

        def pipeline_ticks(params, model_state, images, labels, step,
                           run_backward: bool):
            """Hand-scheduled tick program on ONE device — 1F1B
            (PipeDream-flush) when V == 1, Megatron's interleaved
            virtual pipeline when V > 1. Unlike `pipeline_forward`
            (whose backward is autodiff through the whole tick scan,
            saving every tick's residuals — O(M) live activations), this
            runs the static `build_interleaved_schedule` tick tables:
            each tick names a (microbatch, chunk) pair; forward ticks
            stash only the chunk's in-flight input window into a
            per-chunk ring buffer (V·R rows, slot v·R + m mod R);
            backward ticks re-run the chunk under `jax.vjp` on the
            stashed input (recompute is exact: BN normalizes with batch
            statistics in train mode, and the (logical chunk,
            microbatch) dropout key is deterministic), seed it with the
            cotangent the down-wire delivered (or the loss gradient on
            the last logical chunk), accumulate the parameter gradient
            in place, and send the input-cotangent one hop upstream.
            Two wires run concurrently — activations ppermute up,
            cotangents ppermute down. Under 1F1B the wires are chains;
            under interleaving they are RINGS, whose wrap edge carries a
            chunk-boundary hop (logical v·S+S-1 -> (v+1)·S crosses from
            device S-1 back to device 0), so activations traverse all
            S·V-1 logical hops over S physical devices.

            Returns (loss_sum, logits, new_state, grads, is_last); grads
            are the UNNORMALIZED sum over microbatches (the caller
            divides by its loss normalizer — a linear pull-out of the
            same scaling `jax.grad` applies under "gpipe").

            `run_backward=False` replays only the forward ticks (the
            interleaved EVAL path: backward/bubble ticks skip the chunk
            apply via `lax.cond`, the cotangent wire/ring is elided,
            grads return None) — the forward-side receive tables and
            ring slots are valid on their own because a slot's forward
            consumption always precedes the backward consumption it was
            sized for."""
            images, mb, avals, rows, num_classes, buf_size, wire_dt = (
                program_setup(images)
            )
            T, R, Rc = sched.num_ticks, sched.stash_depth, sched.cot_depth
            # Trace-time record for the structural memory tests: the
            # activation stash traced into this step is (V*R, buf_size).
            self._last_1f1b_trace = {
                "num_ticks": T, "stash_depth": R, "cot_depth": Rc,
                "buf_size": buf_size, "num_virtual": V,
            }
            work_tab = jnp.asarray(sched.work)
            micro_tab = jnp.asarray(sched.micro)
            chunk_tab = jnp.asarray(sched.chunk)
            recv_f = jnp.asarray(sched.recv_fwd)
            recv_f_m = jnp.asarray(sched.recv_fwd_m)
            recv_f_c = jnp.asarray(sched.recv_fwd_c)
            recv_b = jnp.asarray(sched.recv_bwd)
            recv_b_m = jnp.asarray(sched.recv_bwd_m)
            recv_b_c = jnp.asarray(sched.recv_bwd_c)
            s_idx = lax.axis_index("stage")
            images_mbs = images.reshape((M, mb) + images.shape[1:])
            labels_mbs = labels.reshape((M, -1)) if run_backward else None
            rng_base = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(0), step),
                lax.axis_index("data"),
            )

            def make_chunk(i, vv):
                """Device i's chunk vv = logical pipeline stage vv*S+i
                (round-robin placement; V=1 keeps chunk i on device
                i)."""
                l = vv * S + i
                in_aval = avals[l][0]

                def run(operand):
                    state, stash, cots, grads, m, w, rng = operand
                    ctx = Context(
                        train=train, bn_axis=bn_axis, rng=rng, dtype=cdt
                    )
                    p_l = stage_params(params, l, vv)
                    s_l = stage_state(state, l, vv)
                    # Logical chunk 0's input batch is device-resident,
                    # so it is never stashed: both work kinds index
                    # images_mbs.
                    if l == 0:
                        x = lax.dynamic_index_in_dim(images_mbs, m, 0, False)
                    else:
                        x = _unpack(
                            lax.dynamic_index_in_dim(
                                stash, vv * R + m % R, 0, False
                            ),
                            in_aval,
                        )

                    def fwd(_):
                        y, new_si = exec_stages[l].apply(p_l, s_l, x, ctx)
                        y_pad = _pack(y, buf_size, wire_dt)
                        # Bubble (idle) ticks run this branch on garbage
                        # in SPMD lockstep: mask state and output.
                        valid = w == PIPE_FWD
                        if local:
                            packed = _pack(new_si, self._ssize)
                            new_state = state.at[vv].set(
                                jnp.where(valid, packed, state[vv])
                            )
                        else:
                            masked = jax.tree_util.tree_map(
                                lambda new, old: jnp.where(valid, new, old),
                                new_si, state[l],
                            )
                            new_state = tuple(
                                masked if j == l else state[j]
                                for j in range(C)
                            )
                        y_pad = jnp.where(
                            valid, y_pad, jnp.zeros_like(y_pad)
                        )
                        if not run_backward:
                            return y_pad, new_state
                        return (
                            y_pad, jnp.zeros((buf_size,), wire_dt),
                            new_state, grads,
                        )

                    if not run_backward:
                        # Eval replays the train tables, where half the
                        # ticks are backward work. Executing the masked
                        # forward there (the train path's SPMD-lockstep
                        # convention for bubble ticks) would double eval
                        # compute — the cond skips the chunk apply at
                        # runtime instead. Safe per-device: no
                        # collective lives inside the branch (the ring
                        # ppermute is outside, in `tick`).
                        return lax.cond(
                            w == PIPE_FWD,
                            fwd,
                            lambda _: (
                                jnp.zeros((buf_size,), wire_dt), state,
                            ),
                            0,
                        )

                    def bwd(_):
                        if l == C - 1:
                            lbl = lax.dynamic_index_in_dim(
                                labels_mbs, m, 0, False
                            )

                            def f(p, xx):
                                y, _ = exec_stages[l].apply(p, s_l, xx, ctx)
                                y_pad = _pack(y, buf_size, wire_dt)
                                logits_mb = (
                                    y_pad[: rows * num_classes]
                                    .reshape(rows, num_classes)
                                    .astype(jnp.float32)
                                )
                                return (
                                    cross_entropy(logits_mb, lbl)
                                    * valid_count(lbl)
                                )

                            _, vjp_fn = jax.vjp(f, p_l, x)
                            gp, gx = vjp_fn(jnp.ones((), jnp.float32))
                        else:

                            def f(p, xx):
                                y, _ = exec_stages[l].apply(p, s_l, xx, ctx)
                                return _pack(y, buf_size, wire_dt)

                            _, vjp_fn = jax.vjp(f, p_l, x)
                            gp, gx = vjp_fn(
                                lax.dynamic_index_in_dim(
                                    cots, vv * Rc + m % Rc, 0, False
                                )
                            )
                        # Logical chunk 0 has no upstream (and in LM
                        # mode an integer input whose cotangent is
                        # symbolic-zero).
                        down = (
                            jnp.zeros((buf_size,), wire_dt) if l == 0
                            else _pack(gx, buf_size, wire_dt)
                        )
                        if local:
                            new_grads = grads.at[vv].add(
                                _pack(gp, self._psize)
                            )
                        else:
                            g_l = jax.tree_util.tree_map(
                                jnp.add, grads[l], gp
                            )
                            new_grads = tuple(
                                g_l if j == l else grads[j]
                                for j in range(C)
                            )
                        return (
                            jnp.zeros((buf_size,), wire_dt), down, state,
                            new_grads,
                        )

                    return lax.cond(w == PIPE_BWD, bwd, fwd, 0)

                return run

            def make_branch(i):
                runs = [make_chunk(i, vv) for vv in range(V)]

                def branch(operand):
                    state, stash, cots, grads, m, v, w, rng = operand
                    inner = (state, stash, cots, grads, m, w, rng)
                    if V == 1:
                        return runs[0](inner)
                    return lax.switch(v, runs, inner)

                return branch

            branches = [make_branch(i) for i in range(S)]
            if interleaved:
                # Ring wires: the wrap edge is the chunk-boundary hop.
                up_pairs = [(i, (i + 1) % S) for i in range(S)]
                down_pairs = [((i + 1) % S, i) for i in range(S)]
            else:
                up_pairs = [(i, i + 1) for i in range(S - 1)]
                down_pairs = [(i + 1, i) for i in range(S - 1)]

            def tick(carry, t):
                if run_backward:
                    (up_buf, down_buf, stash, cots, state, out_stack,
                     grads) = carry
                else:
                    up_buf, stash, state, out_stack = carry
                    down_buf = None
                    cots = grads = jnp.zeros((), jnp.float32)
                w = work_tab[t, s_idx]
                m = micro_tab[t, s_idx]
                v = chunk_tab[t, s_idx]
                # Receive: the wire buffers hold tick t-1's permute
                # output; the static tables say whether that payload is
                # real and which (chunk, microbatch) ring slot it
                # belongs in (receive-before-compute, so a tick may
                # consume the activation/cotangent that just arrived).
                slot = recv_f_c[t, s_idx] * R + recv_f_m[t, s_idx] % R
                stash = lax.dynamic_update_index_in_dim(
                    stash,
                    jnp.where(
                        recv_f[t, s_idx], up_buf,
                        lax.dynamic_index_in_dim(stash, slot, 0, False),
                    ),
                    slot, 0,
                )
                if run_backward:
                    cslot = (
                        recv_b_c[t, s_idx] * Rc + recv_b_m[t, s_idx] % Rc
                    )
                    cots = lax.dynamic_update_index_in_dim(
                        cots,
                        jnp.where(
                            recv_b[t, s_idx], down_buf,
                            lax.dynamic_index_in_dim(cots, cslot, 0, False),
                        ),
                        cslot, 0,
                    )
                # Per-(logical chunk, microbatch) dropout key — identical
                # at the forward tick and its backward-tick recompute
                # (v*S + s_idx == s_idx when V == 1).
                rng = jax.random.fold_in(
                    jax.random.fold_in(rng_base, v * S + s_idx), m
                )
                operand = (state, stash, cots, grads, m, v, w, rng)
                if run_backward:
                    up_out, down_out, state, grads = lax.switch(
                        s_idx, branches, operand
                    )
                else:
                    up_out, state = lax.switch(s_idx, branches, operand)
                write = (
                    (w == PIPE_FWD) & (s_idx == S - 1) & (v == V - 1)
                )
                logits_mb = (
                    up_out[: rows * num_classes]
                    .reshape(rows, num_classes)
                    .astype(jnp.float32)
                )
                out_stack = lax.dynamic_update_index_in_dim(
                    out_stack,
                    jnp.where(
                        write, logits_mb,
                        lax.dynamic_index_in_dim(out_stack, m, 0, False),
                    ),
                    m, 0,
                )
                if S > 1:
                    up_buf = lax.ppermute(up_out, "stage", up_pairs)
                    if run_backward:
                        down_buf = lax.ppermute(
                            down_out, "stage", down_pairs
                        )
                else:
                    up_buf = up_out
                    if run_backward:
                        down_buf = down_out
                if run_backward:
                    return (
                        up_buf, down_buf, stash, cots, state, out_stack,
                        grads,
                    ), None
                return (up_buf, stash, state, out_stack), None

            if run_backward:
                if local:
                    grads0 = jnp.zeros((V, self._psize), jnp.float32)
                else:
                    grads0 = jax.tree_util.tree_map(jnp.zeros_like, params)
                carry0 = (
                    jnp.zeros((buf_size,), wire_dt),
                    jnp.zeros((buf_size,), wire_dt),
                    # per-chunk activation rings (row v*R + m%R)
                    jnp.zeros((V * R, buf_size), wire_dt),
                    # per-chunk cotangent rings
                    jnp.zeros((V * Rc, buf_size), wire_dt),
                    model_state,
                    jnp.zeros((M, rows, num_classes), jnp.float32),
                    grads0,
                )
                (_, _, _, _, new_state, out_stack, grads), _ = lax.scan(
                    tick, carry0, jnp.arange(T)
                )
            else:
                carry0 = (
                    jnp.zeros((buf_size,), wire_dt),
                    jnp.zeros((V * R, buf_size), wire_dt),
                    model_state,
                    jnp.zeros((M, rows, num_classes), jnp.float32),
                )
                (_, _, new_state, out_stack), _ = lax.scan(
                    tick, carry0, jnp.arange(T)
                )
                grads = None
            logits = out_stack.reshape(M * rows, num_classes)
            is_last = (s_idx == S - 1).astype(logits.dtype)
            loss_sum = (
                cross_entropy(logits, labels) * valid_count(labels) * is_last
            )
            return loss_sum, logits, new_state, grads, is_last

        def reassemble_state(new_state, s_idx):
            """Each device updated only its own chunks' BN state; rebuild
            the replicated tuple by masked psum over 'stage'."""
            out = []
            for i in range(C):
                mask = (s_idx == chunk_owner(i, S)).astype(jnp.float32)
                out.append(
                    jax.tree_util.tree_map(
                        lambda v: lax.psum(v * mask, "stage"), new_state[i]
                    )
                )
            return tuple(out)

        def metrics_from(logits, labels, loss_sum, is_last):
            m = {
                "loss_sum": lax.psum(loss_sum, "stage"),
                "correct1": lax.psum(
                    topk_correct(logits, labels, 1) * is_last, "stage"
                ),
                "correct5": lax.psum(
                    topk_correct(logits, labels, 5) * is_last, "stage"
                ),
                "count": valid_count(labels),
            }
            return {k: lax.psum(v, "data") for k, v in m.items()}

        # shard_map spec for the TrainState: stage-local params ride the
        # 'stage' axis (each device gets its (1, maxP) slice); the
        # replicated representation is a plain P() prefix. The optimizer
        # state's spec comes from the optimizer itself (state_shardings:
        # param-shaped buffers follow the packed params, scalars like
        # AdamW's step count stay replicated).
        if local:
            st = P(("stage",))
            ts_spec = TrainState(
                st, st, self.optimizer.state_shardings(st, P()), P()
            )
        else:
            ts_spec = P()

        if train:

            @partial(
                shard_map,
                mesh=mesh,
                in_specs=(ts_spec, P(("data",)), P(("data",)), P()),
                out_specs=(ts_spec, P()),
                check_vma=False,
            )
            def step(ts: TrainState, images, labels, lr):
                s_idx = lax.axis_index("stage")

                # Normalize by the VALID row count (labels != -1), like
                # the dense engines' cross_entropy mean: for LM heads
                # that's per valid token (each sequence's final position
                # and pad targets carry -1), for classification it is
                # the unpadded batch — so gradient scale matches the
                # dense convention for both head kinds and does not
                # drift with the pad fraction. Local (this shard's
                # labels), keeping the no-collectives-before-grad
                # discipline.
                loss_norm = jnp.maximum(valid_count(labels), 1.0)

                if sched is not None:  # "1f1b" or "interleaved"
                    # Hand-scheduled fwd+bwd: grads come back as the
                    # unnormalized microbatch sum; dividing by loss_norm
                    # is the same linear scaling jax.grad applies to the
                    # gpipe loss below.
                    loss_sum, logits, new_state, grads, is_last = (
                        pipeline_ticks(
                            ts.params, ts.model_state, images, labels,
                            ts.step, run_backward=True,
                        )
                    )
                    grads = jax.tree_util.tree_map(
                        lambda g: g / loss_norm, grads
                    )
                    loss = loss_sum / loss_norm
                else:

                    def loss_fn(params):
                        loss_sum, aux = pipeline_forward(
                            params, ts.model_state, images, labels, ts.step
                        )
                        return loss_sum / loss_norm, aux

                    (loss, (logits, new_state, is_last)), grads = (
                        jax.value_and_grad(loss_fn, has_aux=True)(ts.params)
                    )
                if local:
                    # Each device's flat grad IS its stage's full gradient
                    # (cotangents crossed stages through the reversed
                    # ppermutes); only the data-parallel mean remains.
                    grads = lax.pmean(grads, "data")
                else:
                    # Stage-i grads are nonzero only on stage-i devices;
                    # the psum over 'stage' + pmean over 'data' is the
                    # single fused all-reduce replacing per-rank
                    # optimizers (`model_parallel.py:105-149`) and the
                    # DDP Reducer.
                    grads = jax.tree_util.tree_map(
                        lambda g: lax.pmean(lax.psum(g, "stage"), "data"),
                        grads,
                    )
                    new_state = reassemble_state(new_state, s_idx)
                if not self.sync_bn:
                    new_state = lax.pmean(new_state, "data")
                params, opt_state = self.optimizer.update(
                    ts.params, ts.opt_state, grads, lr
                )
                new_ts = TrainState(
                    params, new_state, opt_state, ts.step + 1
                )
                loss_sum = loss * loss_norm
                return new_ts, metrics_from(logits, labels, loss_sum, is_last)

            return step

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(ts_spec, P(("data",)), P(("data",))),
            out_specs=P(),
            check_vma=False,
        )
        def evstep(ts: TrainState, images, labels):
            if interleaved:
                # The fill-drain forward assumes one chunk per device;
                # interleaved eval replays the tick tables' forward
                # entries instead (backward ticks are masked no-ops).
                loss_sum, logits, _, _, is_last = pipeline_ticks(
                    ts.params, ts.model_state, images, labels, ts.step,
                    run_backward=False,
                )
            else:
                loss_sum, (logits, _, is_last) = pipeline_forward(
                    ts.params, ts.model_state, images, labels, ts.step
                )
            return metrics_from(logits, labels, loss_sum, is_last)

        return evstep


@dataclasses.dataclass
class LMPipelineEngine(PipelineEngine):
    """PipelineEngine for decoder-LM stages (`models/gpt.py
    split_stages`): `shard_batch` derives the flattened next-token
    targets from the ids on the HOST (`gpt.lm_targets` — the final
    position and pad targets carry -1, masked by the loss), so the
    uniform `(inputs, labels)` loader contract — `data/lm.py LMLoader`
    yields `(ids, ids)` — drives LM training unchanged. The engine's
    (rows, vocab) last-stage contract and valid-count loss normalization
    make gradients match the dense `lm_loss` convention."""

    pad_token_id: Any = None

    def shard_batch(self, ids, labels=None):
        import numpy as np

        from distributed_model_parallel_tpu.models.gpt import lm_targets

        targets = lm_targets(ids, self.pad_token_id).reshape(-1)
        return _place_batch(
            (np.asarray(ids, np.int32), targets), self._batch
        )
