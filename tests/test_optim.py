"""Optimizer semantics: SGD/AdamW parity with their torch namesakes, and
engine interchangeability (the optimizer protocol is duck-typed)."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.optim import SGD, AdamW


def _torch_run(opt_name, steps, lr, params0, grads_fn, **kw):
    tparams = [
        torch.nn.Parameter(torch.tensor(np.asarray(p))) for p in params0
    ]
    if opt_name == "sgd":
        topt = torch.optim.SGD(tparams, lr=lr, **kw)
    else:
        topt = torch.optim.AdamW(tparams, lr=lr, **kw)
    for s in range(steps):
        topt.zero_grad()
        for p, g in zip(tparams, grads_fn(s)):
            p.grad = torch.tensor(np.asarray(g))
        topt.step()
    return [p.detach().numpy() for p in tparams]


def _jax_run(opt, steps, lr, params0, grads_fn):
    params = list(params0)
    state = opt.init(params)
    for s in range(steps):
        params, state = opt.update(params, state, list(grads_fn(s)), lr)
    return [np.asarray(p) for p in params]


def _setup(seed=0):
    rng = np.random.RandomState(seed)
    params0 = [
        jnp.asarray(rng.randn(4, 3).astype(np.float32)),
        jnp.asarray(rng.randn(5).astype(np.float32)),
    ]
    gs = [
        [jnp.asarray(rng.randn(*p.shape).astype(np.float32))
         for p in params0]
        for _ in range(5)
    ]
    return params0, lambda s: gs[s]


def test_sgd_matches_torch():
    params0, grads_fn = _setup()
    got = _jax_run(
        SGD(momentum=0.9, weight_decay=1e-4), 5, 0.1, params0, grads_fn
    )
    want = _torch_run(
        "sgd", 5, 0.1, params0, grads_fn, momentum=0.9, weight_decay=1e-4
    )
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=1e-5, atol=1e-6)


def test_adamw_matches_torch():
    params0, grads_fn = _setup(1)
    got = _jax_run(
        AdamW(weight_decay=0.01), 5, 0.01, params0, grads_fn
    )
    want = _torch_run(
        "adamw", 5, 0.01, params0, grads_fn,
        betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01,
    )
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=1e-5, atol=1e-6)


def test_adamw_drives_every_engine_family():
    """AdamW slots into a GSPMD engine and a sharded-state engine (TP)
    via the shared init/update/state_shardings protocol."""
    from distributed_model_parallel_tpu.models.bert import (
        BertConfig,
        bert_for_classification,
    )
    from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn
    from distributed_model_parallel_tpu.parallel.data_parallel import (
        DataParallelEngine,
    )
    from distributed_model_parallel_tpu.parallel.tensor_parallel import (
        TensorParallelEngine,
    )

    rng = np.random.RandomState(0)
    mesh = make_mesh(MeshSpec(data=8))
    eng = DataParallelEngine(tiny_cnn(10), AdamW(), mesh, donate=False)
    ts = eng.init_state(jax.random.PRNGKey(0))
    x = rng.rand(16, 8, 8, 3).astype(np.float32)
    y = rng.randint(0, 10, size=(16,)).astype(np.int32)
    losses = []
    for _ in range(4):
        ts, m = eng.train_step(
            ts, *eng.shard_batch(x, y), jnp.float32(1e-3)
        )
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    assert losses[-1] < losses[0]

    cfg = BertConfig(
        vocab_size=67, hidden_size=32, num_layers=1, num_heads=4,
        intermediate_size=64, max_position=8, dropout_rate=0.0,
    )
    tmesh = make_mesh(MeshSpec(data=2, model=4))
    teng = TensorParallelEngine(
        bert_for_classification(4, cfg), AdamW(), tmesh, donate=False
    )
    tts = teng.init_state(jax.random.PRNGKey(0))
    ids = rng.randint(1, 67, size=(8, 8)).astype(np.int32)
    labels = rng.randint(0, 4, size=(8,)).astype(np.int32)
    tts, m = teng.train_step(
        tts, *teng.shard_batch(ids, labels), jnp.float32(1e-3)
    )
    assert np.isfinite(float(m["loss_sum"]))
    # AdamW moments shard like their params (state_shardings protocol)
    qkv_mu = tts.opt_state.mu["blocks"]["0"]["attn"]["qkv"]["w"]
    assert qkv_mu.addressable_shards[0].data.shape[1] == qkv_mu.shape[1] // 4


def test_adamw_pipeline_stage_local_roundtrip():
    """AdamW + stage-local pipeline params: the packed-state machinery
    must shard param-shaped moments over 'stage', keep the scalar count
    replicated, and round-trip through the canonical checkpoint form
    (the combo the --optimizer flag makes reachable from the CLI)."""
    from distributed_model_parallel_tpu.models import layers as L
    from distributed_model_parallel_tpu.parallel.pipeline import (
        PipelineEngine,
    )

    rng = np.random.RandomState(0)
    stages = [
        L.sequential(L.conv2d(3, 8, 3, stride=1, padding=1), L.relu()),
        L.sequential(L.global_avg_pool(), L.linear(8, 10)),
    ]
    mesh = make_mesh(MeshSpec(data=4, stage=2))
    eng = PipelineEngine(
        stages, AdamW(), mesh, num_microbatches=2,
        stage_local_params=True, donate=False,
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    x = rng.rand(8, 8, 8, 3).astype(np.float32)
    y = rng.randint(0, 10, size=(8,)).astype(np.int32)
    losses = []
    for _ in range(3):
        ts, m = eng.train_step(
            ts, *eng.shard_batch(x, y), jnp.float32(1e-3)
        )
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    assert losses[-1] < losses[0]
    assert int(ts.opt_state.count) == 3  # replicated scalar survived

    canon = eng.to_canonical(ts)
    assert isinstance(canon.opt_state.mu, tuple) and len(canon.opt_state.mu) == 2
    back = eng.from_canonical(canon)
    ts2, m2 = eng.train_step(back, *eng.shard_batch(x, y), jnp.float32(1e-3))
    ts1, m1 = eng.train_step(ts, *eng.shard_batch(x, y), jnp.float32(1e-3))
    np.testing.assert_allclose(
        float(m2["loss_sum"]), float(m1["loss_sum"]), rtol=1e-6
    )
