"""MoE layer + expert-parallel engine tests (8-virtual-device CPU mesh).

EP is absent from the reference; the correctness bar mirrors the other
engines: sharding experts over 'expert' must be semantically invisible
(same losses/params as the fully-replicated run) while expert weights
are physically 1/E_mesh per device.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.models.moe import (
    moe_encoder_layer,
    moe_feed_forward,
)
from distributed_model_parallel_tpu.models.transformer import feed_forward
from distributed_model_parallel_tpu.parallel.data_parallel import (
    DataParallelEngine,
)
from distributed_model_parallel_tpu.parallel.expert_parallel import (
    EXPERT_RULES,
    ExpertParallelEngine,
)
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.optim import SGD

B, T, D = 4, 16, 32


def _tokens(seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(B, T, D).astype(np.float32))


def test_single_expert_full_capacity_equals_dense_ffn():
    """E=1, k=1, capacity >= T: routing is the identity, so the MoE must
    reproduce the plain FFN with the same weights exactly."""
    dense = feed_forward(D, 2 * D)
    moe = moe_feed_forward(D, 2 * D, 1, top_k=1, capacity_factor=1.0)
    dp, _ = dense.init(jax.random.PRNGKey(0))
    mp, ms = moe.init(jax.random.PRNGKey(1))
    # transplant the dense weights into expert 0
    mp = {
        "router": mp["router"],
        "experts": {
            "w_in": dp["in"]["w"][None],
            "b_in": dp["in"]["b"][None],
            "w_out": dp["out"]["w"][None],
            "b_out": dp["out"]["b"][None],
        },
    }
    h = _tokens()
    mask = jnp.asarray(np.random.RandomState(1).rand(B, T) > 0.3)
    (want, _), _ = dense.apply(dp, {}, (h, mask), L.Context())
    (got, _), st = moe.apply(mp, ms, (h, mask), L.Context())
    # dense FFN transforms every token; MoE only dispatches valid ones —
    # compare on the valid tokens, check masked rows are zero.
    np.testing.assert_allclose(
        np.asarray(got)[np.asarray(mask)],
        np.asarray(want)[np.asarray(mask)],
        rtol=2e-5, atol=2e-5,
    )
    np.testing.assert_array_equal(np.asarray(got)[~np.asarray(mask)], 0.0)
    assert np.isfinite(float(st["moe_aux"]))


def test_capacity_drops_overflow_tokens():
    """A tiny capacity factor forces drops: dropped tokens produce zero
    output (the residual stream carries them), kept tokens match the
    generous-capacity run."""
    tight = moe_feed_forward(D, 2 * D, 2, top_k=1, capacity_factor=0.25)
    roomy = moe_feed_forward(D, 2 * D, 2, top_k=1, capacity_factor=2.0)
    p, s = tight.init(jax.random.PRNGKey(0))
    h = _tokens(2)
    (yt, _), _ = tight.apply(p, s, (h, None), L.Context())
    (yr, _), _ = roomy.apply(p, s, (h, None), L.Context())
    zero_rows = ~np.any(np.asarray(yt) != 0, axis=-1)
    assert zero_rows.any(), "expected capacity overflow to drop tokens"
    np.testing.assert_allclose(
        np.asarray(yt)[~zero_rows], np.asarray(yr)[~zero_rows],
        rtol=2e-5, atol=2e-5,
    )


def test_masked_tokens_do_not_claim_buffer_slots():
    """Regression: a masked token's all-zero gate row argmaxes to expert
    0; if it claimed a cumsum rank, a round-2 token would collide into an
    occupied capacity slot and two embeddings would sum. With the fix,
    the masked run must equal the run where masked tokens are simply
    absent from routing."""
    moe = moe_feed_forward(D, 2 * D, 2, top_k=2, capacity_factor=4.0)
    p, s = moe.init(jax.random.PRNGKey(3))
    h = _tokens(5)
    mask = jnp.ones((B, T), bool).at[:, 3].set(False)
    (y_masked, _), _ = moe.apply(p, s, (h, mask), L.Context())
    # reference: physically remove the masked token column
    keep_idx = [i for i in range(T) if i != 3]
    (y_removed, _), _ = moe.apply(
        p, s, (h[:, keep_idx], None), L.Context()
    )
    np.testing.assert_allclose(
        np.asarray(y_masked)[:, keep_idx], np.asarray(y_removed),
        rtol=2e-5, atol=2e-5,
    )
    np.testing.assert_array_equal(np.asarray(y_masked)[:, 3], 0.0)


def test_overflow_token_falls_to_second_choice():
    """Regression: a token whose first-choice expert is full must fall
    to its next-preferred expert in the following round, not re-pick the
    full expert and be dropped.

    Deterministic setup (B=1, T=3, E=3, cap=1): tokens 0/1 prefer
    A then B; token 2 prefers A then C. Round 1 fills A with token 0;
    round 2: token 0 takes B, token 1 loses B (full), and token 2 —
    whose round-1 pick of A overflowed — must land in C, which is
    empty. The old `remaining` update made token 2 re-pick the full A
    and emit a zero row."""
    t, e = 3, 3
    moe = moe_feed_forward(D, 2 * D, e, top_k=2, capacity_factor=0.5)
    p, s = moe.init(jax.random.PRNGKey(4))
    w = np.zeros((D, e), np.float32)
    w[0] = [2.0, 1.0, 0.0]   # direction 0: A then B
    w[1] = [1.5, 0.0, 1.0]   # direction 1: A then C
    p = {"router": {"w": jnp.asarray(w)}, "experts": p["experts"]}
    h = np.zeros((1, t, D), np.float32)
    h[0, 0, 0] = 1.0  # token 0 -> A then B
    h[0, 1, 0] = 1.0  # token 1 -> A then B
    h[0, 2, 1] = 1.0  # token 2 -> A then C
    (y, _), _ = moe.apply(p, s, (jnp.asarray(h), None), L.Context())
    y = np.asarray(y)
    assert np.any(y[0, 0] != 0)  # kept in A (and B)
    assert np.any(y[0, 2] != 0), (
        "overflow token was dropped instead of falling to its free "
        "second-choice expert"
    )


def _moe_classifier(num_experts, num_classes=4, top_k=2):
    """Tokens (B, T, D) -> logits: one MoE encoder block + mean-pool head."""
    block = moe_encoder_layer(
        D, 4, 2 * D, num_experts, top_k=top_k, dropout_rate=0.0
    )
    head = L.linear(D, num_classes)

    def init(key):
        kb, kh = jax.random.split(key)
        bp, bs = block.init(kb)
        return {"block": bp, "head": head.init(kh)[0]}, {"block": bs}

    def apply(params, state, x, ctx):
        (h, _), bs = block.apply(
            params["block"], state.get("block", {}), (x, None), ctx
        )
        logits, _ = head.apply(params["head"], {}, h.mean(axis=1), ctx)
        return logits, {"block": bs}

    return L.Layer(init, apply)


def _batch(seed=0, n=8, ncls=4):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, ncls, size=(n,)).astype(np.int32)
    means = np.random.RandomState(99).randn(ncls, D).astype(np.float32)
    x = rng.randn(n, T, D).astype(np.float32) * 0.5 + means[labels][:, None]
    return x, labels


def _run(engine, n_steps=3, lr=0.05):
    ts = engine.init_state(jax.random.PRNGKey(0))
    x, y = engine.shard_batch(*_batch())
    losses = []
    for _ in range(n_steps):
        ts, m = engine.train_step(ts, x, y, jnp.float32(lr))
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    return ts, losses


def test_moe_trains_and_aux_loss_flows():
    """Top-2 MoE classifier learns under the DP engine, and the router
    receives gradient through the engines' aux_loss hook (router weights
    move even though the router only feeds gate values + aux)."""
    mesh = make_mesh(MeshSpec(data=8))
    eng = DataParallelEngine(
        _moe_classifier(4), SGD(), mesh, donate=False
    )
    ts0 = eng.init_state(jax.random.PRNGKey(0))
    ts, losses = _run(eng, n_steps=6)
    assert losses[-1] < losses[0]
    r0 = np.asarray(ts0.params["block"]["moe"]["router"]["w"])
    r1 = np.asarray(ts.params["block"]["moe"]["router"]["w"])
    assert np.abs(r1 - r0).max() > 0
    assert np.isfinite(float(ts.model_state["block"]["moe"]["moe_aux"]))


def test_ep_matches_replicated_trajectory():
    """(data=2, expert=4) mesh == plain 8-way DP on the same MoE model:
    the partitioner's token all-to-alls are numerically invisible."""
    ep_mesh = make_mesh(MeshSpec(data=2, expert=4))
    dp_mesh = make_mesh(MeshSpec(data=8))
    model = _moe_classifier(4)
    _, losses_ep = _run(
        ExpertParallelEngine(model, SGD(), ep_mesh, donate=False)
    )
    _, losses_dp = _run(
        DataParallelEngine(model, SGD(), dp_mesh, donate=False)
    )
    np.testing.assert_allclose(losses_ep, losses_dp, rtol=1e-4)


def test_ep_weights_physically_sharded():
    """Each device must hold E/4 experts' weights, not all E."""
    mesh = make_mesh(MeshSpec(data=2, expert=4))
    eng = ExpertParallelEngine(_moe_classifier(4), SGD(), mesh, donate=False)
    ts = eng.init_state(jax.random.PRNGKey(0))
    w_in = ts.params["block"]["moe"]["experts"]["w_in"]
    assert w_in.shape[0] == 4
    for shard in w_in.addressable_shards:
        assert shard.data.shape[0] == 1  # 4 experts / 4-way 'expert' axis


def test_moe_bert_trains_expert_parallel():
    """BertConfig(num_experts=4): every 2nd encoder layer is MoE; the
    whole model trains under the EP engine with experts 1/4 per device
    and the aux loss flowing from inside the `sequential` stack."""
    from distributed_model_parallel_tpu.models.bert import (
        BertConfig,
        bert_for_classification,
    )

    cfg = BertConfig(
        vocab_size=67, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_position=16, dropout_rate=0.0,
        num_experts=4, moe_every=2,
    )
    mesh = make_mesh(MeshSpec(data=2, expert=4))
    eng = ExpertParallelEngine(
        bert_for_classification(4, cfg), SGD(), mesh, donate=False
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 67, size=(8, 16)).astype(np.int32)
    ids[:, -3:] = 0  # padding exercises the masked-routing path
    labels = rng.randint(0, 4, size=(8,)).astype(np.int32)
    ids_s, labels_s = eng.shard_batch(ids, labels)
    losses = []
    for _ in range(3):
        ts, m = eng.train_step(ts, ids_s, labels_s, jnp.float32(0.05))
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    assert losses[-1] < losses[0]
    # layer "1" (the 2nd) is the MoE one; its experts are 'expert'-sharded
    w_in = ts.params["blocks"]["1"]["moe"]["experts"]["w_in"]
    assert w_in.addressable_shards[0].data.shape[0] == 1
    aux = ts.model_state["blocks"]["1"]["moe"]["moe_aux"]
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_moe_rejected_by_single_shard_loss_engines():
    """PipelineEngine and SequenceParallelEngine compute their loss on
    one stage/shard; MoE aux leaves would be silently dropped, so both
    must refuse at construction."""
    from distributed_model_parallel_tpu.models.bert import BertConfig
    from distributed_model_parallel_tpu.parallel.pipeline import (
        PipelineEngine,
    )
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        SequenceParallelEngine,
    )

    cfg = BertConfig(
        vocab_size=67, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_position=16, dropout_rate=0.0,
        num_experts=4, moe_every=1,
    )
    with pytest.raises(NotImplementedError, match="MoE"):
        SequenceParallelEngine(
            cfg, 4, SGD(), make_mesh(MeshSpec(data=2, seq=4))
        )
    moe_stage = moe_encoder_layer(D, 4, 2 * D, 2)
    with pytest.raises(NotImplementedError, match="MoE"):
        PipelineEngine(
            [moe_stage, moe_stage], SGD(),
            make_mesh(MeshSpec(data=4, stage=2)),
        )


def test_moe_every_zero_rejected():
    from distributed_model_parallel_tpu.models.bert import (
        BertConfig,
        bert_for_classification,
    )

    cfg = BertConfig(
        vocab_size=67, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_position=16,
        num_experts=4, moe_every=0,
    )
    with pytest.raises(ValueError, match="moe_every"):
        bert_for_classification(4, cfg)


def test_moe_dropout_draws_from_its_own_child_lane():
    """Regression (PR 10 satellite): the MoE output dropout reused the
    PARENT ctx rng — the lane the enclosing block had already handed
    out — correlating its mask with sibling layers'. It must draw from
    the dedicated child(1) lane: the mask equals a bernoulli from
    fold_in(rng, 1), and differs from one drawn on the raw parent
    rng."""
    rate = 0.5
    moe = moe_feed_forward(D, 2 * D, 2, top_k=1, capacity_factor=2.0,
                           dropout_rate=rate)
    p, s = moe.init(jax.random.PRNGKey(0))
    h = _tokens(7)
    rng = jax.random.PRNGKey(42)
    (y_clean, _), _ = moe.apply(
        p, s, (h, None), L.Context(train=False)
    )
    (y_drop, _), _ = moe.apply(
        p, s, (h, None), L.Context(train=True, rng=rng)
    )

    def masked(key):
        keep = jax.random.bernoulli(key, 1.0 - rate, y_clean.shape)
        return np.where(np.asarray(keep),
                        np.asarray(y_clean) / (1.0 - rate), 0.0)

    np.testing.assert_allclose(
        np.asarray(y_drop),
        masked(jax.random.fold_in(rng, 1)),
        rtol=1e-6, atol=1e-6,
    )
    assert np.abs(
        np.asarray(y_drop) - masked(rng)
    ).max() > 1e-3, "mask still drawn from the parent lane"


def test_staged_moe_dropout_matches_composed_apply():
    """The stage_apply_fns global-index contract survives the dropout
    lane fix: a staged MoE model's stagewise forward draws bit-identical
    masks to the composed model's (same Context.child chain)."""
    from distributed_model_parallel_tpu.models import staging

    stem_lin = L.linear(D, D)

    def stem_apply(params, state, x, ctx):
        h, _ = stem_lin.apply(params, state, x, ctx)
        return (h, None), {}

    head_lin = L.linear(D, 4)

    def head_apply(params, state, x, ctx):
        h, _ = x
        return head_lin.apply(params, state, h.mean(axis=1), ctx)

    blocks = [
        moe_encoder_layer(D, 4, 2 * D, 2, top_k=1, dropout_rate=0.3)
        for _ in range(2)
    ]
    model = staging.staged_model(
        L.Layer(stem_lin.init, stem_apply), blocks,
        L.Layer(head_lin.init, head_apply),
    )
    params, state = model.init(jax.random.PRNGKey(0))
    x = _tokens(9)
    ctx = L.Context(train=True, rng=jax.random.PRNGKey(7))
    composed, _ = model.apply(params, state, x, ctx)
    cuts = staging.split_points(2, None, len(blocks))
    fns = staging.stage_apply_fns(model.parts, cuts, ctx)
    y = x
    for fn, sp, ss in zip(
        fns,
        staging.partition_tree(params, cuts),
        staging.partition_tree(state, cuts),
    ):
        y, _ = fn(sp, ss, y)
    np.testing.assert_array_equal(np.asarray(composed), np.asarray(y))


def test_rules_require_expert_axis():
    mesh = make_mesh(MeshSpec(data=8))  # no expert axis sized > 1 is fine;
    # the axis exists in AXES, so construction succeeds and shards E over
    # a size-1 axis (degenerate but valid). A mesh genuinely missing the
    # axis name must be rejected:
    import numpy as _np
    from jax.sharding import Mesh

    flat = Mesh(
        _np.asarray(jax.devices()).reshape(8, 1), axis_names=("data", "model")
    )
    with pytest.raises(ValueError, match="expert"):
        ExpertParallelEngine(_moe_classifier(4), SGD(), flat, donate=False)


def test_tp_ep_dp_compose_on_one_mesh():
    """Tensor, expert, and data parallelism in ONE jit program: a
    (data=2, model=2, expert=2) mesh with MEGATRON_RULES + EXPERT_RULES
    concatenated. Attention/FFN weights shard over 'model', expert
    stacks over 'expert', batch over 'data' — and the trajectory still
    matches plain 8-way DP."""
    from distributed_model_parallel_tpu.models.bert import (
        BertConfig,
        bert_for_classification,
    )
    from distributed_model_parallel_tpu.parallel.tensor_parallel import (
        MEGATRON_RULES,
        TensorParallelEngine,
    )

    cfg = BertConfig(
        vocab_size=67, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_position=16, dropout_rate=0.0,
        num_experts=2, moe_every=2,
    )
    model = bert_for_classification(4, cfg)
    mesh3 = make_mesh(MeshSpec(data=2, model=2, expert=2))
    eng3 = ExpertParallelEngine(
        model, SGD(), mesh3, rules=EXPERT_RULES + MEGATRON_RULES,
        donate=False,
    )
    dp = DataParallelEngine(
        model, SGD(), make_mesh(MeshSpec(data=8)), donate=False
    )
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 67, size=(8, 16)).astype(np.int32)
    labels = rng.randint(0, 4, size=(8,)).astype(np.int32)

    def run(eng):
        ts = eng.init_state(jax.random.PRNGKey(0))
        i, l = eng.shard_batch(ids, labels)
        losses = []
        for _ in range(3):
            ts, m = eng.train_step(ts, i, l, jnp.float32(0.05))
            losses.append(float(m["loss_sum"]) / float(m["count"]))
        return ts, losses

    ts3, l3 = run(eng3)
    _, ldp = run(dp)
    np.testing.assert_allclose(l3, ldp, rtol=1e-4)
    # physically: qkv sharded over 'model', experts over 'expert'
    qkv = ts3.params["blocks"]["0"]["attn"]["qkv"]["w"]
    assert qkv.addressable_shards[0].data.shape[1] == qkv.shape[1] // 2
    w_in = ts3.params["blocks"]["1"]["moe"]["experts"]["w_in"]
    assert w_in.addressable_shards[0].data.shape[0] == w_in.shape[0] // 2
