"""Mixture-of-Experts feed-forward — the routed FFN behind expert
parallelism (`parallel/expert_parallel.py`).

Absent from the reference (SURVEY.md §2.3: "EP — absent, non-goal"); it
exists here because the framework treats every parallelism axis as
first-class. The design is the dense-dispatch GShard/Switch formulation,
chosen FOR the TPU: routing is expressed as einsums against one-hot
dispatch/combine tensors — static shapes, no gather/scatter, everything
on the MXU — so under GSPMD the expert dimension shards over the
`'expert'` mesh axis and the partitioner inserts the token all-to-alls
that GPU MoE stacks hand-write. When an engine threads a policy into
`Context.expert_dispatch` (`ExpertParallelEngine(dispatch=
"hierarchical")` / the DDP engines' `expert_dispatch` knob), the expert
FFN instead runs through the hand-rolled two-level exchange of
`ops/expert_dispatch.py` — routing math here is untouched either way.

Mechanics per token (top-k routing with capacity):
  * router logits -> softmax gates (f32), masked tokens zeroed;
  * k rounds of argmax pick distinct experts; each round assigns the
    token a position in that expert's buffer via a cumulative count,
    tokens past the capacity C = ceil(top_k * T * capacity_factor / E)
    are DROPPED (their combine weight is 0 — the residual stream
    carries them unchanged, the standard Switch behavior);
  * chosen gates renormalize over the kept experts;
  * dispatch einsum packs (B, T, D) -> (E, B, C, D), the per-expert
    FFN runs as batched matmuls over the leading E axis, and the
    combine einsum scatters back weighted by the gates.

The load-balance auxiliary loss (Switch eq. 4: E * Σ_e f_e · p_e,
pre-scaled by `aux_loss_weight`) is returned through the layer STATE
under the reserved key `"moe_aux"`; engines add every `moe_aux` leaf of
the post-forward state to the training loss (see
`parallel/data_parallel.py::aux_loss`), which keeps `Layer`'s
(params, state, x) contract intact — no side-channel plumbing through
the module tree.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.models.transformer import (
    AttentionFn,
    multi_head_attention,
)
from distributed_model_parallel_tpu.ops.attention import dot_product_attention

AUX_KEY = "moe_aux"


def expert_ffn(w, xin, dtype=None):
    """The per-expert FFN (dense -> gelu -> dense), batched over the
    leading expert axis: xin (E', rows, C, D) -> (E', rows, C, D) with
    weight leaves leading E'. E' is the FULL expert stack on the GSPMD
    path and a device's E/S block inside the hand-rolled exchange
    (`ops/expert_dispatch.py`) — one copy of the math, no drift.
    Params are f32 masters cast per-use to the compute dtype."""
    dt = dtype if dtype is not None else xin.dtype
    y = jnp.einsum("ebcd,edh->ebch", xin, w["w_in"].astype(dt))
    y = jax.nn.gelu(
        y + w["b_in"][:, None, None, :].astype(dt), approximate=False
    )
    y = jnp.einsum("ebch,ehd->ebcd", y, w["w_out"].astype(dt))
    return y + w["b_out"][:, None, None, :].astype(dt)


def moe_feed_forward(
    dim: int,
    hidden_dim: int,
    num_experts: int,
    *,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    aux_loss_weight: float = 1e-2,
    dropout_rate: float = 0.0,
) -> L.Layer:
    """Drop-in replacement for `transformer.feed_forward` on the
    (hidden, mask) pair: each token runs through its top-k of
    `num_experts` expert FFNs (dense -> gelu -> dense), gate-weighted.

    Expert weights are stacked on a leading E axis — the axis
    `parallel.expert_parallel.EXPERT_RULES` shards over 'expert'.
    """
    if not 1 <= top_k <= num_experts:
        raise ValueError(
            f"top_k {top_k} must be in [1, num_experts {num_experts}]"
        )
    e, k = num_experts, top_k
    drop = L.dropout(dropout_rate)

    def init(key):
        kr, ki, ko = jax.random.split(key, 3)
        params = {
            "router": {"w": 0.02 * jax.random.normal(kr, (dim, e))},
            "experts": {
                "w_in": 0.02 * jax.random.normal(ki, (e, dim, hidden_dim)),
                "b_in": jnp.zeros((e, hidden_dim)),
                "w_out": 0.02 * jax.random.normal(ko, (e, hidden_dim, dim)),
                "b_out": jnp.zeros((e, dim)),
            },
        }
        return params, {AUX_KEY: jnp.zeros((), jnp.float32)}

    def apply(params, state, x, ctx):
        h, mask = x
        b, t, _ = h.shape
        cap = max(1, math.ceil(k * t * capacity_factor / e))

        # Routing in f32 regardless of compute dtype: softmax + cumsum
        # position bookkeeping are precision-sensitive and tiny.
        gates = jax.nn.softmax(
            h.astype(jnp.float32) @ params["router"]["w"].astype(jnp.float32)
        )  # (B, T, E)
        if mask is not None:
            gates = gates * mask[..., None]

        remaining = gates
        counts = jnp.zeros((b, e), jnp.int32)  # tokens KEPT per expert
        chosen = []  # (gate (B,T), expert one-hot (B,T,E), position (B,T))
        top1_assign = None  # round-0 PRE-capacity picks, for the aux loss
        for _ in range(k):
            idx = jnp.argmax(remaining, axis=-1)               # (B, T)
            raw = jax.nn.one_hot(idx, e, dtype=jnp.int32)      # (B, T, E)
            gate = jnp.sum(remaining * raw, axis=-1)           # (B, T)
            # Only tokens with a live gate claim a buffer rank: a masked
            # token's all-zero row argmaxes to expert 0, and counting it
            # in the cumsum would let a later round reuse an occupied
            # slot (two tokens summed into one capacity row).
            eligible = raw * (gate > 0)[..., None].astype(jnp.int32)
            # Buffer slot: tokens earlier in the sequence fill first;
            # previous rounds' KEPT assignments (counts) offset this
            # round's. Kept ranks are consecutive (overflow ranks are
            # all >= cap), so counts is exactly the next free slot.
            pos_in_e = (
                jnp.cumsum(eligible, axis=1) - eligible + counts[:, None, :]
            )
            pos = jnp.sum(pos_in_e * eligible, axis=-1)        # (B, T)
            if top1_assign is None:
                top1_assign = eligible
            keep = (pos < cap) & (gate > 0)
            kept = eligible * keep[..., None].astype(jnp.int32)
            counts = counts + jnp.sum(kept, axis=1)
            chosen.append((gate * keep, kept, pos))
            # Retire this round's PICK (eligible, not just kept) so a
            # token whose first choice overflowed falls to its genuine
            # second choice next round instead of re-picking a full
            # expert and being dropped outright.
            remaining = remaining * (1 - eligible.astype(gates.dtype))

        denom = sum(g for g, _, _ in chosen) + 1e-9
        combine = sum(  # (B, T, E, C): gate weight at the token's slot
            (g / denom)[..., None, None]
            * oh[..., None]
            * jax.nn.one_hot(p, cap)[:, :, None, :]
            for g, oh, p in chosen
        )
        dispatch = (combine > 0).astype(h.dtype)

        w = params["experts"]
        if ctx.expert_dispatch is not None:
            # Hand-rolled hierarchical token exchange
            # (`ops/expert_dispatch.py`): the policy runs the same
            # pack -> FFN -> unpack math with the (E, B, C, D) buffers
            # physically moved over explicit moe_ring permutes instead
            # of a partitioner-inserted flat all-to-all. Routing above
            # is per-sample, so it stays on the GSPMD side untouched.
            out = ctx.expert_dispatch(
                h, dispatch, combine.astype(h.dtype), w
            )
        else:
            xin = jnp.einsum("btec,btd->ebcd", dispatch, h)
            y = expert_ffn(w, xin, dtype=h.dtype)
            out = jnp.einsum(
                "btec,ebcd->btd", combine.astype(h.dtype), y
            )
        # Dedicated child lane for the one stochastic site: drawing from
        # the parent ctx rng reused the lane the enclosing block already
        # handed out, correlating the MoE mask with sibling layers'
        # masks; child(1) mirrors the composed-model global-index
        # contract `stage_apply_fns` reproduces (pinned in
        # tests/test_expert_parallel.py).
        out, _ = drop.apply({}, {}, out, ctx.child(1))

        # Switch load-balance loss: E * Σ_e (assigned fraction f_e) ·
        # (mean router prob p_e), over VALID tokens. f_e counts the
        # router's PRE-capacity top-1 picks: post-drop counts saturate at
        # the capacity exactly when an expert is overloaded, which would
        # blind the penalty to the collapse it exists to prevent.
        n_valid = (
            jnp.sum(mask.astype(jnp.float32))
            if mask is not None
            else jnp.float32(b * t)
        ) + 1e-9
        f_e = (
            jnp.sum(top1_assign.astype(jnp.float32), axis=(0, 1)) / n_valid
        )
        p_e = jnp.sum(gates, axis=(0, 1)) / n_valid
        aux = aux_loss_weight * e * jnp.sum(f_e * p_e)
        return (out, mask), {AUX_KEY: aux}

    return L.Layer(init, apply)


def moe_encoder_layer(
    dim: int,
    num_heads: int,
    hidden_dim: int,
    num_experts: int,
    *,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    aux_loss_weight: float = 1e-2,
    dropout_rate: float = 0.0,
    eps: float = 1e-12,
    attention_fn: AttentionFn = dot_product_attention,
) -> L.Layer:
    """BERT post-LN block with the FFN replaced by a routed MoE:
    LN(h + Attn(h)); LN(h + MoE(h)). Shape-compatible with
    `transformer.encoder_layer`, so MoE and dense blocks interleave in
    one `sequential` stack (the usual every-other-layer MoE recipe)."""
    attn = multi_head_attention(
        dim, num_heads, dropout_rate=dropout_rate, attention_fn=attention_fn
    )
    moe = moe_feed_forward(
        dim, hidden_dim, num_experts, top_k=top_k,
        capacity_factor=capacity_factor, aux_loss_weight=aux_loss_weight,
        dropout_rate=dropout_rate,
    )
    ln1 = L.layernorm(dim, eps=eps)
    ln2 = L.layernorm(dim, eps=eps)

    def init(key):
        ka, km, k1, k2 = jax.random.split(key, 4)
        mp, ms = moe.init(km)
        return (
            {
                "attn": attn.init(ka)[0],
                "ln1": ln1.init(k1)[0],
                "moe": mp,
                "ln2": ln2.init(k2)[0],
            },
            {"moe": ms},
        )

    def apply(params, state, x, ctx):
        h, mask = x
        (a, _), _ = attn.apply(params["attn"], {}, (h, mask), ctx.child(0))
        h, _ = ln1.apply(params["ln1"], {}, h + a, ctx)
        (f, mask), moe_state = moe.apply(
            params["moe"], state.get("moe", {}), (h, mask), ctx.child(1)
        )
        h, _ = ln2.apply(params["ln2"], {}, h + f, ctx)
        return (h, mask), {"moe": moe_state}

    return L.Layer(init, apply)
