"""Expert parallelism over the `'expert'` mesh axis — GSPMD style.

Absent from the reference (SURVEY.md §2.3: "EP — absent"); first-class
here. Like the tensor-parallel engine (`parallel/tensor_parallel.py`),
this is NOT a hand-written dispatch/collective stack: the MoE layer
(`models/moe.py`) expresses routing as dense einsums against one-hot
dispatch/combine tensors, so placing

    experts/w_in  (E, D, H)  -> P('expert', None, None)
    experts/b_in  (E, H)     -> P('expert', None)
    experts/w_out (E, H, D)  -> P('expert', None, None)
    experts/b_out (E, D)     -> P('expert', None)

on the weight pytree is sufficient: the XLA SPMD partitioner sees a
token tensor sharded over 'data' meeting expert weights sharded over
'expert' and inserts the token all-to-all exchange that GPU MoE
frameworks (GShard, Switch, DeepSpeed-MoE) implement by hand — forward
AND the mirrored gradient exchanges from the einsum transposes. Router
weights and all non-expert parameters stay replicated.

`ExpertParallelEngine` is the tensor-parallel engine with the expert
rule set; concatenate `EXPERT_RULES + MEGATRON_RULES` on a
(data, model, expert) mesh to run EP and TP together in one program.
Per-device expert-weight bytes scale 1/E_mesh (tested in
tests/test_expert_parallel.py), which is why EP exists.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

from jax.sharding import PartitionSpec as P

from distributed_model_parallel_tpu.parallel.tensor_parallel import (
    MEGATRON_RULES,
    TensorParallelEngine,
)

# Sharding layout for the stacked expert weights (models/moe.py param
# paths: .../moe/experts/{w_in,b_in,w_out,b_out}).
EXPERT_RULES: Tuple[Tuple[str, P], ...] = (
    (r"experts/w_in$", P("expert", None, None)),
    (r"experts/b_in$", P("expert", None)),
    (r"experts/w_out$", P("expert", None, None)),
    (r"experts/b_out$", P("expert", None)),
)


@dataclasses.dataclass
class ExpertParallelEngine(TensorParallelEngine):
    """GSPMD expert(+data) parallelism: expert weights sharded over
    'expert' by path rules, batch over 'data', token all-to-alls from
    the partitioner. Same API as every other engine."""

    rules: Sequence[Tuple[str, P]] = EXPERT_RULES


__all__ = [
    "EXPERT_RULES",
    "MEGATRON_RULES",
    "ExpertParallelEngine",
]
