"""costgate — the perf-regression gate over the lint matrix.

`tools/costgate` is the CLI. For each engine x mode combo the hlolint
matrix defines (`analysis/lint.full_matrix`), the cost engine predicts
per-step comm time from the combo's OWN compiled HLO
(`observability/cost.combo_cost`); this module compares those
predictions against the committed ledger
(`experiments/cost_ledger.json`) and fails — like a lint violation,
with the combo NAMED — when:

  * a combo's predicted step time worsens beyond tolerance vs its
    ledger row (a perf regression in what the program asks the network
    for),
  * a combo in the matrix has NO ledger row (a new engine x mode combo
    shipped without committing its cost baseline),
  * the ledger was generated under different alpha/beta constants
    (comparisons across physics are meaningless — regenerate).

Exit codes: 0 clean; 4 gate failure (tools/tier1.sh's costgate
pre-gate keys on it; 2/3 are the collection and hlolint pre-gates'); 2
usage errors.

Modes:
  --pregate   lower only the tier-1 cut (`pregate_matrix`, seconds) and
              additionally name-check EVERY full-matrix combo against
              the ledger (no lowering needed for the name check).
  --update    regenerate rows and (re)write the ledger: the full matrix
              by default, or a merge of just the --filter/--pregate
              subset into the existing file.

Per finished combo one partial-JSON line streams out (the repo's
established convention), then a summary object.

The gate-check itself (`gate_check`) is a pure function over (ledger,
predictions) so tests pin the regression / missing-row / tolerance
semantics without compiling anything.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from distributed_model_parallel_tpu.observability.cost import (
    COMPUTE_CONSTANTS,
    CONSTANTS,
)

# Every ledger-recorded constant the drift guard compares: the comm
# alpha/beta set plus the decode-compute roofline set (ISSUE 16).
_ALL_CONSTANTS = {**CONSTANTS, **COMPUTE_CONSTANTS}

DEFAULT_LEDGER = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    ))),
    "experiments", "cost_ledger.json",
)
DEFAULT_TOLERANCE = 0.05  # 5% predicted-step-time headroom

EXIT_GATE_FAILED = 4


def load_ledger(path: str) -> dict:
    with open(path) as f:
        ledger = json.load(f)
    if "combos" not in ledger:
        raise ValueError(
            f"{path}: not a cost ledger (no 'combos' key)"
        )
    return ledger


def make_ledger(rows: Dict[str, dict],
                tolerance: float = DEFAULT_TOLERANCE) -> dict:
    return {
        "constants": dict(_ALL_CONSTANTS),
        "tolerance": tolerance,
        "combos": {k: rows[k] for k in sorted(rows)},
    }


def gate_check(
    ledger: dict,
    predictions: Dict[str, dict],
    tolerance: Optional[float] = None,
    require_rows_for: Optional[Sequence[str]] = None,
) -> List[str]:
    """Pure comparison: one failure string per violated contract.

    `predictions` maps combo name -> row (at least `predicted_step_s`);
    `require_rows_for` additionally name-checks combos that were NOT
    lowered this run (the pre-gate's full-matrix coverage check)."""
    failures: List[str] = []
    tol = tolerance if tolerance is not None \
        else float(ledger.get("tolerance", DEFAULT_TOLERANCE))
    recorded = ledger.get("constants", {})
    for key, want in _ALL_CONSTANTS.items():
        got = recorded.get(key)
        if got != want:
            failures.append(
                f"constants drift: ledger has {key}={got!r}, the cost "
                f"engine uses {want!r} — regenerate the ledger "
                "(tools/costgate --update)"
            )
    combos = ledger["combos"]
    for name in sorted(predictions):
        row = combos.get(name)
        pred = float(predictions[name]["predicted_step_s"])
        if row is None:
            failures.append(
                f"{name}: no ledger row — a new engine x mode combo "
                "must commit its cost baseline "
                "(tools/costgate --update)"
            )
            continue
        base = float(row["predicted_step_s"])
        if pred > base * (1.0 + tol):
            failures.append(
                f"{name}: predicted step time regressed "
                f"{base * 1e3:.4f} -> {pred * 1e3:.4f} ms "
                f"(+{(pred / base - 1.0) * 100:.1f}%, tolerance "
                f"{tol * 100:.0f}%)"
            )
    if require_rows_for:
        for name in sorted(set(require_rows_for) - set(predictions)):
            if name not in combos:
                failures.append(
                    f"{name}: no ledger row — a new engine x mode "
                    "combo must commit its cost baseline "
                    "(tools/costgate --update)"
                )
    return failures


def _predict(combos, emit) -> Dict[str, dict]:
    """Lower + price each combo, streaming one partial line per combo.
    A combo that fails to LOWER is itself a gate failure (recorded as a
    row with an 'error' key; the caller fails on it)."""
    from distributed_model_parallel_tpu.observability.cost import (
        combo_cost,
    )

    rows: Dict[str, dict] = {}
    for combo in combos:
        try:
            row = combo_cost(combo)
        except Exception as e:  # noqa: BLE001 — a failure IS a finding
            emit(f"[costgate] {combo.name}: LOWERING FAILED: {e!r}")
            rows[combo.name] = {"error": repr(e)}
            emit(json.dumps({
                "leg": {"name": combo.name, "error": repr(e)},
                "partial": True,
            }))
            continue
        rows[combo.name] = row
        emit(f"[costgate] {combo.name}: predicted "
             f"{row['predicted_step_s'] * 1e3:.4f} ms/step "
             f"({row['n_collectives']} collectives)")
        emit(json.dumps({
            "leg": {
                "name": combo.name,
                "predicted_step_s": row["predicted_step_s"],
                "n_collectives": row["n_collectives"],
            },
            "partial": True,
        }))
    return rows


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="costgate",
        description=(
            "Perf-regression gate: predict per-combo per-step comm "
            "time over the hlolint matrix (alpha-beta cost engine, "
            "INTERNALS.md section 13) and compare against the "
            "committed ledger."
        ),
    )
    parser.add_argument(
        "--pregate", action="store_true",
        help="tier-1 cut: lower only the pregate combos (seconds) and "
             "name-check every full-matrix combo against the ledger",
    )
    parser.add_argument(
        "--filter", default=None,
        help="regex over combo names (e.g. 'ddp.*dcn')",
    )
    parser.add_argument(
        "--update", action="store_true",
        help="regenerate rows and write the ledger instead of gating "
             "(full rewrite; merges into the existing file under "
             "--filter/--pregate)",
    )
    parser.add_argument("--ledger", default=DEFAULT_LEDGER)
    parser.add_argument(
        "--tolerance", type=float, default=None,
        help=f"override the ledger's tolerance (default "
             f"{DEFAULT_TOLERANCE})",
    )
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument(
        "--calibration", default=None, metavar="JSON",
        help="report the drift between the fitted constants in this "
             "calibration.json (observability/calibrate.py) and the "
             "committed hand constants — measured physics informs the "
             "model; by default the gate stays a structural check on "
             "the lowered program (see --calibration-tolerance)",
    )
    parser.add_argument(
        "--calibration-tolerance", type=float, default=None,
        metavar="PCT",
        help="upgrade calibration drift beyond this percentage (any "
             "constant, either direction) to the exit-4 gate path, so "
             "a stale committed calibration.json can fail CI once "
             "opted in; default keeps the report-only behavior",
    )
    args = parser.parse_args(argv)

    if args.calibration_tolerance is not None and not args.calibration:
        print(
            "[costgate] --calibration-tolerance gates the drift "
            "report; pass --calibration JSON with it",
            file=sys.stderr,
        )
        return 2

    calibration_drift = None
    if args.calibration:
        from distributed_model_parallel_tpu.observability.calibrate import (  # noqa: E501
            drift_report,
        )
        from distributed_model_parallel_tpu.observability.cost import (
            load_calibration,
        )

        try:
            fitted = load_calibration(args.calibration)
        except (OSError, ValueError) as e:
            print(f"[costgate] cannot read calibration: {e}",
                  file=sys.stderr)
            return 2
        calibration_drift = drift_report(fitted)
        gated_note = (
            "reported, not gated"
            if args.calibration_tolerance is None
            else f"gated at {args.calibration_tolerance:g}%"
        )
        for key, pct in calibration_drift.items():
            print(
                f"[costgate] calibration drift ({gated_note}): "
                f"{key} committed {CONSTANTS[key]:g} -> "
                f"fitted {fitted[key]:g} ({pct:+.1f}%)"
            )
        if args.calibration_tolerance is not None:
            drifted = sorted(
                key for key, pct in calibration_drift.items()
                if abs(pct) > args.calibration_tolerance
            )
            if drifted:
                # Fail BEFORE any lowering: a stale calibration is a
                # property of the committed artifact, not of this
                # tree's programs — no compile can change the verdict.
                for key in drifted:
                    print(
                        f"[costgate] FAIL calibration drift: {key} "
                        f"{calibration_drift[key]:+.1f}% exceeds "
                        f"--calibration-tolerance "
                        f"{args.calibration_tolerance:g}% — refit "
                        "(observability/calibrate.py) and re-commit "
                        "experiments/calibration.json"
                    )
                print(json.dumps({"costgate": {
                    "failures": len(drifted),
                    "failed_targets": [
                        f"calibration:{k}" for k in drifted
                    ],
                    "calibration_drift_pct": calibration_drift,
                }}))
                return EXIT_GATE_FAILED

    # Virtual CPU devices BEFORE any backend initializes (same guard as
    # tools/hlolint: this environment preloads a TPU PJRT plugin).
    from distributed_model_parallel_tpu.runtime.platform import force_cpu

    force_cpu(args.devices)

    from distributed_model_parallel_tpu.analysis.lint import (
        full_matrix,
        pregate_matrix,
    )

    matrix = full_matrix()
    combos = pregate_matrix() if args.pregate else matrix
    if args.filter:
        import re

        combos = [c for c in combos if re.search(args.filter, c.name)]
    if not combos:
        print("[costgate] no combos match", file=sys.stderr)
        return 2
    # full_matrix may repeat a name (the pre-gate twins); dedupe.
    seen = set()
    combos = [
        c for c in combos
        if not (c.name in seen or seen.add(c.name))
    ]

    subset_update = args.update and (args.pregate or args.filter) \
        and os.path.exists(args.ledger)
    old = load_ledger(args.ledger) if subset_update else None
    if old is not None:
        drifted = sorted(
            k for k, v in _ALL_CONSTANTS.items()
            if old.get("constants", {}).get(k) != v
        )
        if drifted:
            # Merging would keep the un-lowered rows at the OLD
            # physics while stamping the ledger with the current
            # constants — silently defeating the drift guard. A
            # constants change requires repricing every row. Checked
            # BEFORE any lowering so the refusal costs nothing.
            print(
                "[costgate] refusing subset --update: the existing "
                f"ledger was priced under different constants "
                f"({', '.join(drifted)}); run a FULL "
                "`tools/costgate --update` to reprice every combo",
                file=sys.stderr,
            )
            return 2

    rows = _predict(combos, print)
    errored = sorted(n for n, r in rows.items() if "error" in r)
    rows = {n: r for n, r in rows.items() if "error" not in r}

    if args.update:
        # Tolerance precedence: explicit flag > the merged-into
        # ledger's committed value > the default — a subset merge must
        # not silently reset a deliberately committed tolerance.
        tol = args.tolerance
        if tol is None and old is not None:
            tol = float(old.get("tolerance", DEFAULT_TOLERANCE))
        if tol is None:
            tol = DEFAULT_TOLERANCE
        if old is not None:
            merged = old["combos"]
            merged.update(rows)
            rows = merged
        ledger = make_ledger(rows, tol)
        with open(args.ledger, "w") as f:
            json.dump(ledger, f, indent=1)
            f.write("\n")
        print(json.dumps({
            "costgate": {
                "updated": args.ledger,
                "combos": len(ledger["combos"]),
                "errors": len(errored),
                "failed_targets": errored,
            }
        }))
        return EXIT_GATE_FAILED if errored else 0

    try:
        ledger = load_ledger(args.ledger)
    except (OSError, ValueError) as e:
        print(f"[costgate] cannot read ledger: {e}", file=sys.stderr)
        return EXIT_GATE_FAILED
    failures = gate_check(
        ledger, rows, args.tolerance,
        require_rows_for=[c.name for c in matrix] if args.pregate
        else None,
    )
    failures += [
        f"{name}: LOWERING FAILED (see log above)" for name in errored
    ]
    for f in failures:
        print(f"[costgate] FAIL {f}")
    summary = {
        "ledger": args.ledger,
        "gated": len(rows),
        "name_checked": len(matrix) if args.pregate else len(rows),
        "failures": len(failures),
        "failed_targets": sorted(
            {f.split(":", 1)[0] for f in failures}
        ),
    }
    if calibration_drift is not None:
        summary["calibration_drift_pct"] = calibration_drift
    print(json.dumps({"costgate": summary}))
    return EXIT_GATE_FAILED if failures else 0


if __name__ == "__main__":
    sys.exit(main())
