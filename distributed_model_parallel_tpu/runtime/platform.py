"""Platform forcing helpers shared by the bench/dryrun entry points.

This image's sitecustomize imports jax at interpreter start (registering a
remote TPU PJRT plugin), so jax's config captures JAX_PLATFORMS before any
user code runs; mutating os.environ afterwards does nothing. The only
reliable switch is `jax.config.update("jax_platforms", ...)` — and the
virtual-device XLA flag must be in the environment before the CPU client
first initializes or it is silently ignored.
"""

from __future__ import annotations

import os


def force_cpu(n_devices: int = 8):
    """Force the cpu platform with >= n_devices virtual devices; returns
    the device list. Safe to call before or after `import jax`, but only
    before the CPU backend's first initialization."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    if len(devices) < n_devices:
        devices = jax.devices("cpu")
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, found {len(devices)} "
            f"(platform {devices[0].platform}); was the CPU backend "
            "initialized before force_cpu()?"
        )
    return devices[:n_devices]
