"""Incremental (KV-cached) decode and prefill, threaded through the
existing `attention_fn(q, k, v, mask)` seam.

The decoder blocks are NOT rewritten for inference: `gpt.decoder_blocks`
builds the same `models/transformer.py` layers the training engines run,
and the cache plumbing rides the `attention_fn` parameter — per traced
step a fresh recorder object is constructed, the blocks are rebuilt
around it (Layer construction is just closures; params come from the
dense `gpt_lm` pytree, so checkpoints and the TP/SP training engines'
states serve unchanged), and each block's single `attention_fn` call
becomes one layer's cache update + incremental attention:

  * decode (`CacheAttention`): the block hands over the NEW token's
    q/k/v (B, 1, H, Dh); the recorder writes k/v into layer `i` of the
    cache at each slot's own position (a ragged batch — every slot sits
    at a different position), then attends q against the full cached
    prefix through `ops.attention.dot_product_attention` with a
    per-slot key-validity mask — the same core the dense model runs,
    so logits are pinned identical to full recompute
    (tests/test_serving.py).
  * sp decode (`SeqShardedCacheAttention`): the cache's position axis
    is sharded over 'seq'; each shard attends q over ITS positions and
    the partial results merge with the online-softmax recurrence
    (pmax of the running max, psum of the exp-sums and weighted
    values) — the same flash-style merge `ops/ring_attention.py` uses,
    exact, not approximate.
  * prefill (`PrefillRecorder`): wraps any causal attention core
    (dense `dot_product_attention` or, under the sp layout,
    `ring_attention` over 'seq' — long prefill reuses the training
    ring) and captures each layer's full-prompt K/V for the cache
    write.

Decode-time TP projections ride the latency-hiding rings
(`DecodeCollectiveMatmul`): at decode the sequence axis is one token,
so the chunked `ag_matmul`/`matmul_rs` rings run over the SLOT-BATCH
axis instead — the residual stream between blocks is slot-sharded over
'model' (the decode analog of the Megatron-SP layout), column
projections gather slots via S-1 ppermute hops, row projections
reduce-scatter partial sums back, and no monolithic all-gather touches
the opted-in path (pinned by the hlolint `serve-decode-ring` rule:
exactly 4·L·(S-1) permutes per decode step).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_model_parallel_tpu.ops.attention import (
    dot_product_attention,
)
from distributed_model_parallel_tpu.ops.collective_matmul import (
    ag_matmul,
    ag_matmul_quant,
    matmul_rs,
    matmul_rs_quant,
)
from distributed_model_parallel_tpu.ops.quant_matmul import quant_dot
from distributed_model_parallel_tpu.runtime.compat import shard_map


# --------------------------------------------------------------- stems


def decode_stem(stem_params, tokens, positions, dtype):
    """One-token stem: word embedding of each slot's incoming token plus
    ITS OWN position row — the dense `gpt.stem_apply` broadcasts one
    shared position slice over the batch, which cannot express a ragged
    (mixed-position) decode batch, so the gather is per-slot here.
    tokens/positions (slots,) -> h (slots, 1, dim)."""
    h = jnp.take(stem_params["word"], tokens, axis=0)[:, None, :]
    pos = jnp.take(stem_params["position"], positions, axis=0)[:, None, :]
    h = h + pos
    if dtype is not None:
        h = h.astype(dtype)
    return h


def chunk_stem(stem_params, ids, start, dtype):
    """Chunked-prefill stem: (1, T) ids embedded at global positions
    start + [0, T) with PER-TOKEN position gathers (clipped — padding
    rows past the chunk's valid length may index beyond the table;
    their outputs are discarded). `prefill_stem`'s dynamic_slice would
    CLAMP the whole slice when start + T overruns the table, silently
    shifting every position row — the per-token gather cannot."""
    t = ids.shape[1]
    pos_ids = jnp.clip(
        start + jnp.arange(t), 0, stem_params["position"].shape[0] - 1
    )
    h = jnp.take(stem_params["word"], ids, axis=0) \
        + jnp.take(stem_params["position"], pos_ids, axis=0)[None]
    if dtype is not None:
        h = h.astype(dtype)
    return h


def verify_stem(stem_params, tokens, positions, dtype):
    """Speculative verify stem: each slot's (T,) token span embedded at
    ITS OWN positions `positions[s] + [0, T)` — the batched cousin of
    `chunk_stem` (same clipped per-token position gathers; padding rows
    past the table are discarded by the verify masks) crossed with
    `decode_stem`'s per-slot raggedness. tokens (slots, T),
    positions (slots,) -> h (slots, T, dim)."""
    t = tokens.shape[1]
    pos_ids = jnp.clip(
        positions[:, None] + jnp.arange(t)[None, :],
        0, stem_params["position"].shape[0] - 1,
    )
    h = jnp.take(stem_params["word"], tokens, axis=0) \
        + jnp.take(stem_params["position"], pos_ids, axis=0)
    if dtype is not None:
        h = h.astype(dtype)
    return h


def prefill_stem(stem_params, ids, offset, dtype):
    """Prompt stem over (B, T) ids starting at global position `offset`
    (0 for the dense layouts; the shard's global offset under 'seq'
    sharding, mirroring the SP training engines)."""
    t = ids.shape[1]
    pos = lax.dynamic_slice_in_dim(
        stem_params["position"], offset, t, axis=0
    )
    h = jnp.take(stem_params["word"], ids, axis=0) + pos[None]
    if dtype is not None:
        h = h.astype(dtype)
    return h


# ----------------------------------------------------- cache utilities


def write_position(cache_layer, new, positions, active):
    """Write each slot's (1, H, Dh) update at its own position along
    the cache's position axis; inactive slots keep their old row
    (admission gaps must not smear garbage into recycled slots).
    cache_layer (slots, max_len, H, Dh), new (slots, 1, H, Dh)."""
    upd = jax.vmap(
        lambda c, u, p: lax.dynamic_update_slice_in_dim(
            c, u.astype(c.dtype), p, axis=0
        )
    )(cache_layer, new, positions)
    return jnp.where(active[:, None, None, None], upd, cache_layer)


# ------------------------------------------------- decode attention fns


class CacheAttention:
    """attention_fn for one traced decode step, replicated/TP layouts.

    Construct fresh per trace with the incoming cache; each block's
    call consumes the next layer index in order (the blocks apply
    sequentially, so call order IS layer order). After the blocks run,
    `.k`/`.v` hold the updated stacked caches."""

    def __init__(self, k, v, positions, active):
        self.k = k  # (layers, slots, max_len, H, Dh)
        self.v = v
        self.positions = positions  # (slots,) write/attend position
        self.active = active  # (slots,) bool
        self.layer = 0

    def __call__(self, q, k_new, v_new, mask):
        i = self.layer
        self.layer += 1
        kc = write_position(self.k[i], k_new, self.positions, self.active)
        vc = write_position(self.v[i], v_new, self.positions, self.active)
        self.k = self.k.at[i].set(kc)
        self.v = self.v.at[i].set(vc)
        # Keys at the slot's position or earlier are the live prefix
        # (the new token was just written AT the position); later
        # positions are zero padding or a recycled slot's stale tail.
        valid = (
            jnp.arange(kc.shape[1])[None, :] <= self.positions[:, None]
        )
        return dot_product_attention(q, kc, vc, mask=valid)


def _sp_online_softmax_attend(q, kc, vc, valid, axis):
    """The exact cross-shard attention merge both sp decode recorders
    share (contiguous AND paged — ONE copy, so the paged==contiguous
    logit-parity pin can never be broken by the two drifting apart):
    each shard scores q against ITS local keys under `valid`
    (slots, local_kv), then the partial softmaxes combine via the
    online recurrence — pmax of the running max, one psum each for the
    exp-sums and weighted values."""
    dh = q.shape[-1]
    scale = 1.0 / jnp.sqrt(dh).astype(jnp.float32)
    qf = q.astype(jnp.float32)
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32)
    ) * scale  # (slots, H, 1, local_kv)
    neg = jnp.finfo(jnp.float32).min
    logits = jnp.where(valid[:, None, None, :], logits, neg)
    m = lax.pmax(jnp.max(logits, axis=-1), axis)  # (slots, H, 1)
    p = jnp.exp(logits - m[..., None])
    p = jnp.where(valid[:, None, None, :], p, 0.0)
    denom = lax.psum(jnp.sum(p, axis=-1), axis)  # (slots, H, 1)
    num = lax.psum(
        jnp.einsum("bhqk,bkhd->bqhd", p, vc.astype(jnp.float32)),
        axis,
    )  # (slots, 1, H, Dh)
    out = num / jnp.swapaxes(denom, 1, 2)[..., None]
    return out.astype(q.dtype)


class SeqShardedCacheAttention:
    """attention_fn for one traced decode step under the sp layout —
    call INSIDE shard_map over `axis`, with the cache's position axis
    sharded: local cache (layers, slots, max_len/S, H, Dh).

    Each shard writes the new K/V only if it owns the slot's position,
    attends q over its own positions, and the partial softmaxes merge
    exactly via the online recurrence (pmax/psum over `axis`)."""

    def __init__(self, k, v, positions, active, *, axis: str = "seq"):
        self.k = k
        self.v = v
        self.positions = positions
        self.active = active
        self.axis = axis
        self.layer = 0

    def _write(self, cache_layer, new):
        chunk = cache_layer.shape[1]
        idx = lax.axis_index(self.axis)
        local_p = self.positions - idx * chunk
        owns = (local_p >= 0) & (local_p < chunk) & self.active
        upd = jax.vmap(
            lambda c, u, p: lax.dynamic_update_slice_in_dim(
                c, u.astype(c.dtype), p, axis=0
            )
        )(cache_layer, new, jnp.clip(local_p, 0, chunk - 1))
        return jnp.where(owns[:, None, None, None], upd, cache_layer)

    def __call__(self, q, k_new, v_new, mask):
        i = self.layer
        self.layer += 1
        kc = self._write(self.k[i], k_new)
        vc = self._write(self.v[i], v_new)
        self.k = self.k.at[i].set(kc)
        self.v = self.v.at[i].set(vc)
        chunk = kc.shape[1]
        idx = lax.axis_index(self.axis)
        # Global validity of THIS shard's positions: every global
        # position <= the slot's position lives on exactly one shard,
        # so the union over shards is the dense prefix mask.
        gpos = idx * chunk + jnp.arange(chunk)
        valid = gpos[None, :] <= self.positions[:, None]  # (slots, C)
        return _sp_online_softmax_attend(q, kc, vc, valid, self.axis)


class PrefillRecorder:
    """attention_fn wrapper for the prefill pass: runs `core` (causal
    dense attention, or `ring_attention` under the sp layout) unchanged
    and captures each layer's K/V for the cache write."""

    def __init__(self, core):
        self.core = core
        self.ks: List[jax.Array] = []
        self.vs: List[jax.Array] = []

    def __call__(self, q, k, v, mask):
        self.ks.append(k)
        self.vs.append(v)
        return self.core(q, k, v, mask)


# ------------------------------------------------- paged attention fns
#
# The paged twins of the recorders above: K/V live in a page POOL
# (L, num_pages, page_size, H, Dh) and each slot reaches its positions
# through a block table (slots, pages_per_slot) of pool page ids (-1 =
# unallocated). Every recorder gathers the slot's pages into the same
# position-ordered view the contiguous cache stores directly — so the
# attention math (and therefore the logits) is IDENTICAL, and only the
# storage granularity changes. Gathers/scatters are local indexing ops,
# never collectives, so the decode step's collective inventory (hlolint
# `serve-decode-ring`) is untouched by paging.


def _gather_pages(pool_layer, block_table):
    """(num_pages, page, H, Dh) x (slots, P) -> position-ordered view
    (slots, P*page, H, Dh). Unallocated entries (-1) clamp-gather page
    0; their positions sit beyond every slot's live length, so the
    validity masks keep them invisible."""
    pages = jnp.take(
        pool_layer, jnp.clip(block_table, 0, pool_layer.shape[0] - 1),
        axis=0,
    )  # (slots, P, page, H, Dh)
    s, p, page, h, dh = pages.shape
    return pages.reshape(s, p * page, h, dh)


def _scatter_written_page(pool_layer, view, block_table, positions,
                          active, page_size):
    """Write back ONLY the page each slot's decode write landed in.
    Inactive slots (and unallocated entries) scatter out of bounds and
    drop — the pool is untouched for them. Distinct live slots write
    distinct pool pages (the host's copy-on-write keeps write pages
    private), so the scatter has no duplicate indices."""
    s = view.shape[0]
    num_pages = pool_layer.shape[0]
    pages = view.reshape(
        s, -1, page_size, view.shape[-2], view.shape[-1]
    )
    wp = positions // page_size  # (slots,) slot-local page index
    written = jnp.take_along_axis(
        pages, wp[:, None, None, None, None], axis=1
    )[:, 0]  # (slots, page, H, Dh)
    dst = jnp.take_along_axis(block_table, wp[:, None], axis=1)[:, 0]
    dst = jnp.where(active & (dst >= 0), dst, num_pages)  # OOB -> drop
    return pool_layer.at[dst].set(written, mode="drop")


class PagedCacheAttention:
    """attention_fn for one traced PAGED decode step, replicated/TP
    layouts: gather the slot's pages through the block table, write the
    new token at its own position, attend over the gathered view with
    the same per-slot validity mask as `CacheAttention` (logit parity
    is pinned paged == contiguous == dense), then scatter back only the
    written page."""

    def __init__(self, k, v, block_table, positions, active,
                 page_size: int):
        self.k = k  # (layers, num_pages, page, H, Dh)
        self.v = v
        self.bt = block_table  # (slots, pages_per_slot) int32
        self.positions = positions  # (slots,) write/attend position
        self.active = active  # (slots,) bool
        self.page = page_size
        self.layer = 0

    def __call__(self, q, k_new, v_new, mask):
        i = self.layer
        self.layer += 1
        kview = _gather_pages(self.k[i], self.bt)
        vview = _gather_pages(self.v[i], self.bt)
        kc = write_position(kview, k_new, self.positions, self.active)
        vc = write_position(vview, v_new, self.positions, self.active)
        self.k = self.k.at[i].set(_scatter_written_page(
            self.k[i], kc, self.bt, self.positions, self.active,
            self.page,
        ))
        self.v = self.v.at[i].set(_scatter_written_page(
            self.v[i], vc, self.bt, self.positions, self.active,
            self.page,
        ))
        valid = (
            jnp.arange(kc.shape[1])[None, :] <= self.positions[:, None]
        )
        return dot_product_attention(q, kc, vc, mask=valid)


class PagedSeqShardedCacheAttention:
    """Paged attention_fn for one traced decode step under the sp
    layout — call INSIDE shard_map over `axis`, with each PAGE's
    position axis sharded: local pool (layers, num_pages, page/S, H,
    Dh). Each shard owns positions [idx*psub, (idx+1)*psub) of EVERY
    page, writes the new K/V only if it owns the slot's within-page
    offset, and the per-shard partial softmaxes merge exactly via the
    online recurrence (pmax/psum over `axis`) — the paged twin of
    `SeqShardedCacheAttention`."""

    def __init__(self, k, v, block_table, positions, active,
                 page_size: int, *, axis: str = "seq"):
        self.k = k
        self.v = v
        self.bt = block_table
        self.positions = positions
        self.active = active
        self.page = page_size
        self.axis = axis
        self.layer = 0

    def _local(self, view_len, psub):
        """Global position of each local view element."""
        f = jnp.arange(view_len)
        idx = lax.axis_index(self.axis)
        return (f // psub) * self.page + idx * psub + (f % psub)

    def __call__(self, q, k_new, v_new, mask):
        i = self.layer
        self.layer += 1
        psub = self.k.shape[2]  # page/S positions per shard
        idx = lax.axis_index(self.axis)
        kview = _gather_pages(self.k[i], self.bt)
        vview = _gather_pages(self.v[i], self.bt)
        # Write the new token if THIS shard owns its within-page
        # offset; the local flat index of global position p is
        # (p // page) * psub + (p % page) % psub.
        p = self.positions
        off = p % self.page
        owns = (off // psub == idx) & self.active
        local = (p // self.page) * psub + off % psub
        kw = write_position(kview, k_new, local, owns)
        vw = write_position(vview, v_new, local, owns)
        self.k = self.k.at[i].set(_scatter_written_page(
            self.k[i], kw, self.bt, local, owns, psub,
        ))
        self.v = self.v.at[i].set(_scatter_written_page(
            self.v[i], vw, self.bt, local, owns, psub,
        ))
        gpos = self._local(kw.shape[1], psub)
        valid = gpos[None, :] <= p[:, None]  # (slots, view)
        return _sp_online_softmax_attend(q, kw, vw, valid, self.axis)


class PagedChunkAttention:
    """attention_fn for ONE chunked-prefill step of ONE slot
    (replicated/TP layouts): the chunk's queries (positions
    [start, start+n) for n = chunk length) attend causally over the
    slot's already-cached prefix PLUS the chunk itself, and the
    chunk's K/V lands in the slot's pages.

    The write is a gather-from-chunk select over the whole view (no
    dynamic-slice clamping hazards near max_len): view element at
    global position g takes chunk element g - start when
    start <= g < start + chunk. Chunk PADDING beyond the valid length
    also lands in the view, but padding positions are either
    overwritten by the next chunk / the first decode write (which
    start exactly at start + n_valid) or sit beyond the slot's length
    and stay masked — the same stale-tail discipline the contiguous
    cache relies on. Scatter-back rewrites only the chunk//page + 1
    pages the chunk region can touch (a static count; pages past the
    block table or unallocated entries drop) — never the whole slot,
    and never a prefix-cache SHARED page, since ingestion always
    resumes at or after the matched boundary on freshly allocated
    pages."""

    def __init__(self, k, v, bt_row, start, page_size: int):
        self.k = k
        self.v = v
        self.bt = bt_row  # (pages_per_slot,) int32
        self.start = start  # int32 global position of chunk token 0
        self.page = page_size
        self.layer = 0

    def _write_chunk(self, view, new):
        """view (1, view_len, H, Dh) <- new (1, chunk, H, Dh) at
        [start, start+chunk)."""
        chunk = new.shape[1]
        g = jnp.arange(view.shape[1])
        c = jnp.clip(g - self.start, 0, chunk - 1)
        cand = jnp.take(new[0], c, axis=0)[None].astype(view.dtype)
        inside = (g >= self.start) & (g < self.start + chunk)
        return jnp.where(inside[None, :, None, None], cand, view)

    def _scatter_touched(self, pool_layer, view, chunk: int):
        """Write back the slot-local pages overlapping
        [start, start + chunk): the last touched page index is
        (start + chunk - 1) // page, so with start possibly one short
        of a boundary the span is at most (chunk-1)//page + 2 pages —
        NOT chunk//page + 1, which undercounts whenever the chunk sits
        unaligned (pinned by the logit-parity test at
        prefill_chunk=3 / page_size=4). A trailing index past the real
        span rewrites a just-gathered page with its own bytes."""
        num_pages = pool_layer.shape[0]
        pages = view.reshape(
            -1, self.page, view.shape[-2], view.shape[-1]
        )
        idx = self.start // self.page + jnp.arange(
            (chunk - 1) // self.page + 2
        )
        safe = jnp.clip(idx, 0, pages.shape[0] - 1)
        touched = jnp.take(pages, safe, axis=0)
        dst = jnp.take(self.bt, safe, axis=0)
        ok = (idx < pages.shape[0]) & (dst >= 0)
        dst = jnp.where(ok, dst, num_pages)  # OOB -> drop
        return pool_layer.at[dst].set(touched, mode="drop")

    def __call__(self, q, k_new, v_new, mask):
        i = self.layer
        self.layer += 1
        chunk = k_new.shape[1]
        kview = self._write_chunk(
            _gather_pages(self.k[i], self.bt[None])[0][None], k_new
        )
        vview = self._write_chunk(
            _gather_pages(self.v[i], self.bt[None])[0][None], v_new
        )
        self.k = self.k.at[i].set(
            self._scatter_touched(self.k[i], kview, chunk)
        )
        self.v = self.v.at[i].set(
            self._scatter_touched(self.v[i], vview, chunk)
        )
        # Causal across the prefix boundary: query at global position
        # start + t sees every cached position <= start + t.
        tq = q.shape[1]
        qpos = self.start + jnp.arange(tq)
        valid = (
            jnp.arange(kview.shape[1])[None, :] <= qpos[:, None]
        )  # (Tq, view)
        return dot_product_attention(
            q, kview, vview, mask=valid[None, None]
        )


class PagedVerifyAttention:
    """attention_fn for ONE speculative VERIFY step over the whole slot
    batch (replicated/TP layouts): every slot's (k+1)-token span — its
    current last token plus the k draft proposals — attends causally
    over the slot's cached prefix PLUS the span itself, exactly the
    `PagedChunkAttention` causal-over-cached-prefix machinery batched
    over slots (each slot at its OWN start position, like
    `PagedCacheAttention`'s ragged batch).

    Writes are the chunk recorder's gather-select over the gathered
    view (no dynamic-slice clamping near max_len), gated per slot on
    `active`; scatter-back rewrites only the (T-1)//page + 2 pages each
    slot's span can touch (a static count — unallocated entries and
    inactive slots drop). The span lands in the cache BEFORE acceptance
    is known: rejected suffix tokens are rolled back host-side by
    truncating the block table (`PagedCacheHost.truncate`) — pages are
    freed, never copied, and stale K/V inside the kept tail stays
    masked by the slot's position like any recycled slot's."""

    def __init__(self, k, v, block_table, positions, active,
                 page_size: int):
        self.k = k  # (layers, num_pages, page, H, Dh)
        self.v = v
        self.bt = block_table  # (slots, pages_per_slot) int32
        self.positions = positions  # (slots,) span START position
        self.active = active  # (slots,) bool
        self.page = page_size
        self.layer = 0

    def _write_span(self, view, new):
        """view (slots, view_len, H, Dh) <- new (slots, T, H, Dh) at
        [pos_s, pos_s + T) per slot; inactive slots keep their view."""
        t = new.shape[1]
        g = jnp.arange(view.shape[1])  # (view,)
        c = jnp.clip(g[None, :] - self.positions[:, None], 0, t - 1)
        cand = jnp.take_along_axis(
            new, c[:, :, None, None], axis=1
        ).astype(view.dtype)  # (slots, view, H, Dh)
        inside = (
            (g[None, :] >= self.positions[:, None])
            & (g[None, :] < self.positions[:, None] + t)
            & self.active[:, None]
        )
        return jnp.where(inside[:, :, None, None], cand, view)

    def _scatter_span(self, pool_layer, view, t: int):
        """Write back each slot's touched pages — the span [pos, pos+t)
        overlaps at most (t-1)//page + 2 slot-local pages (the
        `PagedChunkAttention._scatter_touched` count, batched). A
        trailing index past the real span rewrites a just-gathered page
        with its own bytes; OOB / unallocated / inactive drop. Distinct
        live slots write distinct pool pages (the host's copy-on-write
        keeps write pages private), so the flattened scatter has no
        duplicate indices."""
        num_pages = pool_layer.shape[0]
        s = view.shape[0]
        pages = view.reshape(
            s, -1, self.page, view.shape[-2], view.shape[-1]
        )
        n_touch = (t - 1) // self.page + 2
        idx = (
            self.positions[:, None] // self.page
            + jnp.arange(n_touch)[None, :]
        )  # (slots, n_touch) slot-local page indices
        safe = jnp.clip(idx, 0, pages.shape[1] - 1)
        touched = jnp.take_along_axis(
            pages, safe[:, :, None, None, None], axis=1
        )  # (slots, n_touch, page, H, Dh)
        dst = jnp.take_along_axis(self.bt, safe, axis=1)
        ok = (idx < pages.shape[1]) & (dst >= 0) \
            & self.active[:, None]
        dst = jnp.where(ok, dst, num_pages)  # OOB -> drop
        return pool_layer.at[dst.reshape(-1)].set(
            touched.reshape(-1, self.page, *view.shape[-2:]),
            mode="drop",
        )

    def __call__(self, q, k_new, v_new, mask):
        i = self.layer
        self.layer += 1
        t = k_new.shape[1]
        kview = self._write_span(_gather_pages(self.k[i], self.bt), k_new)
        vview = self._write_span(_gather_pages(self.v[i], self.bt), v_new)
        self.k = self.k.at[i].set(self._scatter_span(self.k[i], kview, t))
        self.v = self.v.at[i].set(self._scatter_span(self.v[i], vview, t))
        # Causal across the prefix boundary, per slot: query token j of
        # slot s sits at global position pos_s + j and sees every cached
        # position <= pos_s + j — row 0 conditions on exactly the real
        # prefix, row j on the prefix plus the first j span tokens, so
        # accepted rows reproduce plain decode's logits position for
        # position.
        qpos = self.positions[:, None] + jnp.arange(t)[None, :]
        valid = (
            jnp.arange(kview.shape[1])[None, None, :]
            <= qpos[:, :, None]
        )  # (slots, Tq, view)
        return dot_product_attention(
            q, kview, vview, mask=valid[:, None]
        )


# ---------------------------------------- decode-time collective matmul


@dataclasses.dataclass(frozen=True)
class DecodeCollectiveMatmul:
    """Latency-hiding policy for TP DECODE steps (`Context.matmul` ->
    `layers.project`, the same hook the training engines thread).

    At decode the token axis is 1, so the training policy's
    sequence-chunked rings have nothing to ring over; the slot-batch
    axis is the long one instead. Column projections (qkv / ffn-in)
    enter slot-sharded and gather the batch through the `ag_matmul`
    ring (S-1 ppermutes, each hop overlapping the chunk dot); row
    projections (attn-out / ffn-out) reduce-scatter partial sums back
    onto the slot shards via `matmul_rs`. Between the pairs,
    activations sit exactly where the declarative TP layout puts them
    (head/feature-sharded), so the cache attention is untouched; the
    residual stream between blocks rides slot-sharded over `axis` —
    the decode analog of the Megatron-SP layout.

    `compute_dtype` ("bf16" | "int8" | None) injects a quantized
    per-chunk GEMM into the fold bodies (`ops/quant_matmul.quant_dot`):
    the ring permute chain stays byte-identical — same hops, same
    payload dtype, `serve-decode-ring` still pins 4·L·(S-1) — and only
    the chunk dot arithmetic changes (`decode-quantized-matmul` pins
    the chunk-dot dtypes from the jaxpr)."""

    mesh: Mesh
    axis: str = "model"
    attn: bool = True
    ffn: bool = True
    compute_dtype: Optional[str] = None

    def _check(self, rows: int, features: int, fdim: str) -> None:
        size = self.mesh.shape[self.axis]
        if rows % size:
            raise ValueError(
                f"decode collective_matmul rings over the slot-token "
                f"batch: {rows} rows not divisible by the {size}-way "
                f"'{self.axis}' axis"
            )
        if features % size:
            raise ValueError(
                f"decode collective_matmul: {fdim} ({features}) not "
                f"divisible by the {size}-way '{self.axis}' axis"
            )

    def column(self, h, w, b):
        """(slots, T, D) -> (slots, T, F) F-sharded; the flattened
        slots*T row batch gathered via the ag_matmul ring. T is 1 for a
        decode step and k+1 for a speculative verify step — the SAME
        ring either way (hop count depends only on the axis size), which
        is the hlolint `spec-verify-step` contract: k extra tokens ride
        the one chain, they never cost k chains. num_slots % S == 0
        (the engine guard) keeps the flattened row count divisible for
        every T."""
        rows = h.shape[0] * h.shape[1]
        self._check(rows, w.shape[-1], "output features")
        fn = shard_map(
            partial(
                _decode_column, axis_name=self.axis,
                mode=self.compute_dtype,
            ),
            mesh=self.mesh,
            in_specs=(P(self.axis, None), P(None, self.axis),
                      P(self.axis)),
            out_specs=P(None, self.axis),
            check_vma=False,
        )
        # The named scope is the hlolint anchor: `serve-decode-ring` /
        # `spec-verify-step` count exactly these permutes (GSPMD's own
        # resharding permutes around the regions stay untagged).
        with jax.named_scope("serve_ring"):
            y = fn(h.reshape(rows, h.shape[-1]), w, b)
        return y.reshape(h.shape[0], h.shape[1], -1)

    def row(self, h, w, b):
        """(slots, T, F) F-sharded -> (slots, T, D); partial sums
        reduce-scattered onto the flattened slot-token row shards via
        the matmul_rs ring (same T generalization as `column`)."""
        rows = h.shape[0] * h.shape[1]
        self._check(rows, w.shape[0], "input features")
        fn = shard_map(
            partial(
                _decode_row, axis_name=self.axis,
                mode=self.compute_dtype,
            ),
            mesh=self.mesh,
            in_specs=(P(None, self.axis), P(self.axis, None), P()),
            out_specs=P(self.axis, None),
            check_vma=False,
        )
        with jax.named_scope("serve_ring"):
            y = fn(h.reshape(rows, h.shape[-1]), w, b)
        return y.reshape(h.shape[0], h.shape[1], -1)


def _decode_column(hl, wl, bl, *, axis_name, mode=None):
    dot = quant_dot(mode)
    if dot is None:
        return ag_matmul(hl, wl, axis_name) + bl
    y = ag_matmul_quant(hl, wl, axis_name, dot)
    return y + bl.astype(y.dtype)


def _decode_row(hl, wl, b, *, axis_name, mode=None):
    dot = quant_dot(mode)
    if dot is None:
        return matmul_rs(hl, wl, axis_name) + b
    y = matmul_rs_quant(hl, wl, axis_name, dot)
    return y + b.astype(y.dtype)


def decode_ring_permutes(num_layers: int, size: int) -> int:
    """The exact collective-permute count of one opted-in decode step:
    4 projection rings per block (qkv, attn-out, ffn-in, ffn-out),
    S-1 hops each, no backward — the hlolint `serve-decode-ring` pin."""
    return 4 * num_layers * (size - 1)


__all__ = [
    "CacheAttention",
    "DecodeCollectiveMatmul",
    "PagedCacheAttention",
    "PagedChunkAttention",
    "PagedSeqShardedCacheAttention",
    "PagedVerifyAttention",
    "PrefillRecorder",
    "SeqShardedCacheAttention",
    "chunk_stem",
    "decode_ring_permutes",
    "decode_stem",
    "prefill_stem",
    "verify_stem",
    "write_position",
]
