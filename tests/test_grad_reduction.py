"""Bucketed hierarchical gradient reduction (`ops/grad_reduction.py`):
bucket-plan unit tests, ring reduce-scatter/all-gather parity with the
monolithic collectives, `bucketed_pmean` == `lax.pmean` on mixed-dtype
and uneven-tail pytrees, and engine-level parity — DDP / FSDP / causal-
LM SP with `grad_reduction="bucketed"` must match their monolithic
twins at rtol 1e-5 (grads, metrics, 3-step trajectory) for S ∈ {2, 4,
8}, including the 2×(S/2) dcn×ici factored mesh."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn
from distributed_model_parallel_tpu.ops.grad_reduction import (
    bucketed_pmean,
    data_replica_index,
    plan_buckets,
    ring_all_gather,
    ring_reduce_scatter,
)
from distributed_model_parallel_tpu.parallel.data_parallel import DDPEngine
from distributed_model_parallel_tpu.runtime.compat import shard_map
from distributed_model_parallel_tpu.runtime.mesh import (
    MeshSpec,
    data_axis_names,
    data_axis_size,
    make_mesh,
)
from distributed_model_parallel_tpu.training.optim import SGD


# ------------------------------------------------------- bucket plan


def _avals(*shaped):
    return [jax.ShapeDtypeStruct(s, d) for s, d in shaped]


def test_plan_buckets_reverse_registration_order():
    """Late leaves land in the FIRST bucket (backprop produces them
    first — the Reducer's launch-order trick)."""
    leaves = _avals(
        ((256,), jnp.float32), ((256,), jnp.float32), ((256,), jnp.float32)
    )
    buckets = plan_buckets(leaves, bucket_mb=2 * 256 * 4 / (1 << 20))
    assert [s.index for b in buckets for s in b.slots] == [2, 1, 0]
    assert [len(b.slots) for b in buckets] == [2, 1]
    # offsets are contiguous within a bucket
    assert [(s.offset, s.size) for s in buckets[0].slots] == [
        (0, 256), (256, 256)
    ]


def test_plan_buckets_groups_by_dtype():
    """bf16 and f32 leaves never share a flat buffer."""
    leaves = _avals(
        ((64,), jnp.float32), ((64,), jnp.bfloat16), ((64,), jnp.float32)
    )
    buckets = plan_buckets(leaves, bucket_mb=25.0)
    assert len(buckets) == 2
    by_dtype = {b.dtype: [s.index for s in b.slots] for b in buckets}
    assert by_dtype[jnp.dtype(jnp.float32)] == [2, 0]
    assert by_dtype[jnp.dtype(jnp.bfloat16)] == [1]


def test_plan_buckets_oversized_leaf_gets_own_bucket():
    """A leaf past bucket_mb sits alone in its bucket; buckets stay
    CONTIGUOUS ranges of the reverse order (Reducer semantics — the
    tiny leaves flanking the giant one do not leapfrog it to share)."""
    leaves = _avals(
        ((8,), jnp.float32), ((1 << 20,), jnp.float32), ((8,), jnp.float32)
    )
    buckets = plan_buckets(leaves, bucket_mb=0.5)
    assert [[s.index for s in b.slots] for b in buckets] == [
        [2], [1], [0]
    ]
    assert buckets[1].size == 1 << 20


def test_plan_buckets_rejects_integer_leaves():
    with pytest.raises(TypeError, match="non-floating"):
        plan_buckets(_avals(((4,), jnp.int32)), bucket_mb=1.0)


def test_plan_buckets_rejects_nonpositive_cap():
    with pytest.raises(ValueError, match="bucket_mb"):
        plan_buckets(_avals(((4,), jnp.float32)), bucket_mb=0.0)


# ------------------------------------------------- ring collectives


@pytest.mark.parametrize("size", [2, 3, 4, 8])
def test_ring_reduce_scatter_matches_psum_scatter(size, devices):
    """Each shard holds a distinct flat (n,) vector; both lowerings
    must produce the same summed 1/S chunks."""
    mesh = Mesh(np.array(devices[:size]), ("d",))
    n = 6 * size
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(size * n), jnp.float32)

    def run(fn):
        m = shard_map(
            fn, mesh=mesh, in_specs=(P("d"),), out_specs=P("d"),
            check_vma=False,
        )
        return np.asarray(jax.jit(m)(x))

    ring = run(partial(ring_reduce_scatter, axis_name="d"))
    mono = run(
        lambda v: lax.psum_scatter(v, "d", scatter_dimension=0,
                                   tiled=True)
    )
    np.testing.assert_allclose(ring, mono, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("size", [2, 3, 4, 8])
def test_ring_all_gather_matches_all_gather(size, devices):
    mesh = Mesh(np.array(devices[:size]), ("d",))
    x = jnp.arange(size * 5, dtype=jnp.float32)

    def run(fn):
        m = shard_map(
            fn, mesh=mesh, in_specs=(P("d"),), out_specs=P(None),
            check_vma=False,
        )
        return np.asarray(jax.jit(m)(x))

    ring = run(partial(ring_all_gather, axis_name="d"))
    mono = run(lambda v: lax.all_gather(v, "d", axis=0, tiled=True))
    np.testing.assert_allclose(ring, mono)


# ------------------------------------------- bucketed_pmean (pytree)


def _grad_tree(rng):
    """Mixed-dtype pytree with awkward (prime) sizes so every bucket
    has an uneven tail against the ring size."""
    return {
        "w1": jnp.asarray(rng.randn(13, 7), jnp.float32),
        "b1": jnp.asarray(rng.randn(7), jnp.float32),
        "w2": jnp.asarray(rng.randn(31, 3), jnp.bfloat16),
        "scalar": jnp.asarray(rng.randn(), jnp.float32),
        "w3": jnp.asarray(rng.randn(97), jnp.float32),
    }


@pytest.mark.parametrize(
    "mesh_shape, axes",
    [
        ((2,), ("data",)),
        pytest.param((4,), ("data",), marks=pytest.mark.slow),
        ((8,), ("data",)),
        pytest.param((2, 2), ("dcn", "ici"), marks=pytest.mark.slow),
        ((2, 4), ("dcn", "ici")),
    ],
)
def test_bucketed_pmean_matches_lax_pmean(mesh_shape, axes, devices):
    size = int(np.prod(mesh_shape))
    mesh = Mesh(np.array(devices[:size]).reshape(mesh_shape), axes)
    ici = axes[-1]
    dcn = axes[0] if len(axes) > 1 else None
    rngs = [np.random.RandomState(i) for i in range(size)]
    # One distinct tree per shard, stacked on a leading device axis.
    trees = [_grad_tree(r) for r in rngs]
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs).reshape(
            mesh_shape + xs[0].shape
        ),
        *trees,
    )
    in_spec = jax.tree_util.tree_map(
        lambda _: P(*axes), stacked
    )

    def run(fn):
        def body(t):
            squeezed = jax.tree_util.tree_map(
                lambda x: x.reshape(x.shape[len(mesh_shape):]), t
            )
            out = fn(squeezed)
            return jax.tree_util.tree_map(
                lambda x: x.reshape((1,) * len(mesh_shape) + x.shape),
                out,
            )

        m = shard_map(
            body, mesh=mesh, in_specs=(in_spec,), out_specs=in_spec,
            check_vma=False,
        )
        # every shard's result is identical; read shard (0, ..., 0)
        return jax.tree_util.tree_map(
            lambda x: np.asarray(x)[(0,) * len(mesh_shape)],
            jax.jit(m)(stacked),
        )

    mono = run(lambda t: lax.pmean(t, axes))
    # Tiny bucket cap → multiple buckets, exercising tails and order.
    buck = run(
        lambda t: bucketed_pmean(t, ici, dcn, bucket_mb=0.0005)
    )
    for k in mono:
        a, b = np.asarray(mono[k], np.float32), np.asarray(
            buck[k], np.float32
        )
        if trees[0][k].dtype == jnp.bfloat16:
            # bf16 carries ~8 mantissa bits; S-term sums in a different
            # order can disagree by a few ULPs of the PARTIAL sums.
            np.testing.assert_allclose(a, b, rtol=5e-2, atol=2e-2)
        else:
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_data_replica_index_is_dcn_major(devices):
    mesh = Mesh(np.array(devices).reshape(2, 4), ("dcn", "ici"))
    fn = jax.jit(shard_map(
        lambda x: x * 0 + data_replica_index(("dcn", "ici")),
        mesh=mesh, in_specs=(P(("dcn", "ici")),),
        out_specs=P(("dcn", "ici")), check_vma=False,
    ))
    out = np.asarray(fn(jnp.zeros(8, jnp.int32)))
    np.testing.assert_array_equal(out, np.arange(8))


# ------------------------------------------------- hybrid mesh layer


def test_make_mesh_dcn_factors_the_data_axis(devices):
    mesh = make_mesh(MeshSpec(data=8, dcn=2))
    assert mesh.axis_names[:2] == ("dcn", "ici")
    assert "data" not in mesh.axis_names
    assert mesh.shape["dcn"] == 2 and mesh.shape["ici"] == 4
    assert data_axis_names(mesh) == ("dcn", "ici")
    assert data_axis_size(mesh) == 8
    plain = make_mesh(MeshSpec(data=8))
    assert data_axis_names(plain) == ("data",)
    assert data_axis_size(plain) == 8


def test_make_mesh_dcn_must_divide_data():
    with pytest.raises(ValueError, match="dcn"):
        make_mesh(MeshSpec(data=8, dcn=3))
    with pytest.raises(ValueError, match="dcn"):
        MeshSpec(data=8, dcn=0).resolve(8)


def test_make_mesh_dcn_resolves_wildcard_data(devices):
    mesh = make_mesh(MeshSpec(data=-1, dcn=4))
    assert mesh.shape["dcn"] == 4 and mesh.shape["ici"] == 2


# ------------------------------------------------ engine-level parity


def _batch(n=16):
    rng = np.random.RandomState(7)
    return (
        rng.rand(n, 8, 8, 3).astype(np.float32),
        rng.randint(0, 10, size=(n,)).astype(np.int32),
    )


def _run(eng, n_steps=3, lr=0.05):
    ts = eng.init_state(jax.random.PRNGKey(0))
    x, y = eng.shard_batch(*_batch())
    traj, metrics = [], None
    for _ in range(n_steps):
        ts, metrics = eng.train_step(ts, x, y, jnp.float32(lr))
        traj.append(float(metrics["loss_sum"]))
    return ts, traj, metrics


def _tree_close(a, b, rtol=1e-5, atol=1e-6):
    for x, y in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    ):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol
        )


def _spec_for(s, dcn):
    return MeshSpec(data=s, dcn=dcn)


# Engine-parity sweeps: the full S ∈ {2, 4, 8} matrix runs under the
# `slow` marker (each case compiles two engines on the 1-core host);
# tier-1 keeps the S=8 smoke of each family — the same assertions on
# the largest mesh (the repo's established sweep-vs-smoke pattern,
# tests/test_pipeline_schedule.py).
_S_SWEEP = [
    pytest.param(2, marks=pytest.mark.slow),
    pytest.param(4, marks=pytest.mark.slow),
    8,
]


@pytest.mark.parametrize("s", _S_SWEEP)
def test_ddp_bucketed_matches_monolithic(s, devices):
    """Grads (via the first-step update), metrics, and the 3-step
    trajectory — rtol 1e-5, plain ('data',) mesh."""
    mesh = make_mesh(MeshSpec(data=s), devices=devices[:s])
    results = {}
    for gr in ("monolithic", "bucketed"):
        eng = DDPEngine(
            tiny_cnn(10), SGD(), mesh, donate=False,
            grad_reduction=gr, bucket_mb=0.02,
        )
        results[gr] = _run(eng)
    ts_m, traj_m, met_m = results["monolithic"]
    ts_b, traj_b, met_b = results["bucketed"]
    np.testing.assert_allclose(traj_b, traj_m, rtol=1e-5)
    _tree_close(ts_b.params, ts_m.params)
    _tree_close(met_b, met_m, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("s", _S_SWEEP)
def test_ddp_bucketed_matches_monolithic_on_hybrid_mesh(s, devices):
    """Same parity on the 2×(S/2) dcn×ici factored mesh — the
    hierarchical path (ici rings + dcn all-reduce)."""
    mesh = make_mesh(
        MeshSpec(data=s, dcn=2), devices=devices[:s]
    )
    results = {}
    for gr in ("monolithic", "bucketed"):
        eng = DDPEngine(
            tiny_cnn(10), SGD(), mesh, donate=False,
            grad_reduction=gr, bucket_mb=0.02,
        )
        results[gr] = _run(eng)
    np.testing.assert_allclose(
        results["bucketed"][1], results["monolithic"][1], rtol=1e-5
    )
    _tree_close(results["bucketed"][0].params,
                results["monolithic"][0].params)
    _tree_close(results["bucketed"][2], results["monolithic"][2],
                rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_ddp_bucketed_first_step_grads_match(devices):
    """Isolate the GRADIENTS: with momentum/wd off, the first-step
    param delta is -lr * grads; pin it between the two reducers.
    (`slow`: the tier-1 S=8 smokes above pin grads implicitly through
    the 3-step trajectory at the same tolerance.)"""
    mesh = make_mesh(MeshSpec(data=8))
    deltas = {}
    for gr in ("monolithic", "bucketed"):
        eng = DDPEngine(
            tiny_cnn(10), SGD(momentum=0.0, weight_decay=0.0), mesh,
            donate=False, grad_reduction=gr, bucket_mb=0.02,
        )
        ts0 = eng.init_state(jax.random.PRNGKey(0))
        x, y = eng.shard_batch(*_batch())
        ts1, _ = eng.train_step(ts0, x, y, jnp.float32(1.0))
        deltas[gr] = jax.tree_util.tree_map(
            lambda a, b: np.asarray(b) - np.asarray(a),
            ts0.params, ts1.params,
        )
    _tree_close(deltas["bucketed"], deltas["monolithic"])


@pytest.mark.slow
def test_ddp_bucketed_hybrid_matches_plain_mesh_trajectory(devices):
    """The dcn×ici factoring is a LAYOUT, not math: the bucketed
    trajectory on the hybrid mesh equals the plain-mesh one. Tier-1
    twin: test_ddp_bucketed_matches_monolithic_on_hybrid_mesh's S=8
    case pins the hybrid path against monolithic at the same rtol."""
    plain = make_mesh(MeshSpec(data=8))
    hybrid = make_mesh(MeshSpec(data=8, dcn=2))
    trajs = {}
    for name, mesh in (("plain", plain), ("hybrid", hybrid)):
        eng = DDPEngine(
            tiny_cnn(10), SGD(), mesh, donate=False,
            grad_reduction="bucketed", bucket_mb=0.02,
        )
        trajs[name] = _run(eng)[1]
    np.testing.assert_allclose(trajs["hybrid"], trajs["plain"],
                               rtol=1e-5)


# The hybrid mesh (covering both fabrics) is the tier-1 smoke; the
# plain-mesh twin rides the slow sweep.
_MESH_SWEEP = [
    pytest.param(1, marks=pytest.mark.slow),
    2,
]


@pytest.mark.parametrize("dcn", _MESH_SWEEP)
def test_fsdp_bucketed_matches_monolithic_and_stays_sharded(
    dcn, devices
):
    """The explicit bucketed-reduce-scatter FSDP step: trajectory
    parity with the declarative engine AND 1/N physical sharding of
    params + moments preserved."""
    from distributed_model_parallel_tpu.parallel.fsdp import FSDPEngine
    from distributed_model_parallel_tpu.training.optim import AdamW

    for spec in (MeshSpec(data=8, dcn=dcn),):
        mesh = make_mesh(spec)
        res = {}
        for gr in ("monolithic", "bucketed"):
            eng = FSDPEngine(
                tiny_cnn(10), AdamW(), mesh, donate=False,
                min_shard_elems=64, grad_reduction=gr, bucket_mb=0.02,
            )
            res[gr] = _run(eng, lr=1e-3)
        np.testing.assert_allclose(
            res["bucketed"][1], res["monolithic"][1], rtol=1e-5
        )
        _tree_close(res["bucketed"][0].params,
                    res["monolithic"][0].params)
        big = max(
            jax.tree_util.tree_leaves(res["bucketed"][0].params),
            key=lambda l: l.size,
        )
        assert np.prod(big.addressable_shards[0].data.shape) == (
            big.size // 8
        )
        mu = max(
            jax.tree_util.tree_leaves(res["bucketed"][0].opt_state.mu),
            key=lambda l: l.size,
        )
        assert np.prod(mu.addressable_shards[0].data.shape) == (
            mu.size // 8
        )


@pytest.mark.parametrize("dcn", _MESH_SWEEP)
def test_causal_lm_sp_bucketed_matches_monolithic(dcn, devices):
    """The lm CLI's engine: bucketed data-axis reduction (after the
    'seq' psum) matches the fused psum path, plain and hybrid."""
    from distributed_model_parallel_tpu.models.gpt import GPTConfig
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        CausalLMSequenceParallelEngine,
    )
    from distributed_model_parallel_tpu.training.optim import AdamW

    cfg = GPTConfig(
        vocab_size=64, dim=32, num_layers=1, num_heads=4, ffn_dim=64,
        max_position=32, dropout_rate=0.0, pad_token_id=0,
    )
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 64, size=(8, 32)).astype(np.int32)
    for spec in (MeshSpec(data=4, seq=2, dcn=dcn),):
        mesh = make_mesh(spec)
        res = {}
        for gr in ("monolithic", "bucketed"):
            eng = CausalLMSequenceParallelEngine(
                cfg, AdamW(), mesh, donate=False,
                grad_reduction=gr, bucket_mb=0.02,
            )
            ts = eng.init_state(jax.random.PRNGKey(0))
            a, b = eng.shard_batch(ids)
            traj = []
            for _ in range(3):
                ts, m = eng.train_step(ts, a, b, jnp.float32(1e-3))
                traj.append(float(m["loss_sum"]))
            res[gr] = (ts, traj)
        np.testing.assert_allclose(
            res["bucketed"][1], res["monolithic"][1], rtol=1e-5
        )
        _tree_close(res["bucketed"][0].params,
                    res["monolithic"][0].params, rtol=1e-4)


def test_engine_rejects_unknown_grad_reduction(devices):
    mesh = make_mesh(MeshSpec(data=8))
    with pytest.raises(ValueError, match="grad_reduction"):
        DDPEngine(tiny_cnn(10), SGD(), mesh, grad_reduction="fused")


# ------------------------------------- stagewise backward (overlapped)
# The `grad_reduction="overlapped"` substrate
# (`models/staging.stagewise_value_and_grad`): chained per-stage vjp
# closures must equal the monolithic `jax.grad` BIT FOR BIT on a
# single-device no-collective model — so an engine-level parity failure
# localizes to the collectives, never to the chain itself.


def _stagewise_grads(model, cuts, params, state, x, ctx,
                     on_stage_grads=None):
    from distributed_model_parallel_tpu.models import staging
    from distributed_model_parallel_tpu.parallel.data_parallel import (
        aux_loss,
    )

    def loss_head(y):
        loss = jnp.sum(y.astype(jnp.float32) ** 2)
        return loss, y

    loss, _, grads, new_states = staging.stagewise_value_and_grad(
        staging.stage_apply_fns(model.parts, cuts, ctx),
        loss_head,
        staging.partition_tree(params, cuts),
        staging.partition_tree(state, cuts),
        x,
        aux_of_state=aux_loss,
        on_stage_grads=on_stage_grads,
    )
    return (
        loss,
        staging.unpartition_tree(grads, cuts),
        staging.unpartition_tree(new_states, cuts),
    )


@pytest.mark.parametrize("remat", [False, True])
def test_stagewise_vjp_matches_jax_grad_bitwise(remat):
    """Single device, no collectives: the chained per-stage vjp equals
    `jax.grad` bit for bit — including `remat=True` blocks (the
    checkpointed recompute happens inside each stage closure) and the
    BN batch-stat side outputs (tiny_cnn's model_state), which must
    reassemble to exactly the monolithic apply's new_state."""
    from distributed_model_parallel_tpu.models import layers as L
    from distributed_model_parallel_tpu.models import staging
    from distributed_model_parallel_tpu.parallel.data_parallel import (
        aux_loss,
    )

    model = tiny_cnn(10, remat=remat)
    params, state = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.rand(4, 8, 8, 3), jnp.float32)
    ctx = L.Context(train=True)
    cuts = staging.split_points(3, None, len(model.parts.blocks))

    loss_s, grads_s, state_s = jax.jit(
        lambda p: _stagewise_grads(model, cuts, p, state, x, ctx)
    )(params)

    def mono(p):
        y, new_state = model.apply(p, state, x, ctx)
        return (
            jnp.sum(y.astype(jnp.float32) ** 2) + aux_loss(new_state),
            new_state,
        )

    (loss_m, state_m), grads_m = jax.jit(
        jax.value_and_grad(mono, has_aux=True)
    )(params)

    assert np.asarray(loss_s) == np.asarray(loss_m)
    for a, b in zip(
        jax.tree_util.tree_leaves(grads_s),
        jax.tree_util.tree_leaves(grads_m),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree_util.tree_leaves(state_s),
        jax.tree_util.tree_leaves(state_m),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_stagewise_hook_sees_stages_in_reverse():
    """The Reducer contract: `on_stage_grads` fires late stages first,
    once per stage, with that stage's partition-layout grads."""
    from distributed_model_parallel_tpu.models import layers as L
    from distributed_model_parallel_tpu.models import staging

    model = tiny_cnn(10)
    params, state = model.init(jax.random.PRNGKey(0))
    x = jnp.ones((2, 8, 8, 3), jnp.float32)
    cuts = staging.split_points(4, None, 4)
    order = []

    def hook(k, g):
        order.append(k)
        return g

    _stagewise_grads(
        model, cuts, params, state, x, L.Context(train=True),
        on_stage_grads=hook,
    )
    assert order == [3, 2, 1, 0]


def test_unpartition_tree_roundtrips():
    from distributed_model_parallel_tpu.models import staging

    model = tiny_cnn(10)
    params, _ = model.init(jax.random.PRNGKey(0))
    for n_stages in (2, 3, 4):
        cuts = staging.split_points(n_stages, None, 4)
        back = staging.unpartition_tree(
            staging.partition_tree(params, cuts), cuts
        )
        assert jax.tree_util.tree_structure(
            back
        ) == jax.tree_util.tree_structure(params)
        for a, b in zip(
            jax.tree_util.tree_leaves(back),
            jax.tree_util.tree_leaves(params),
        ):
            assert a is b


def test_resolve_overlap_stages_validates():
    from distributed_model_parallel_tpu.models import staging

    parts = tiny_cnn(10).parts
    assert staging.resolve_overlap_stages(parts, 0, "t") == 4
    assert staging.resolve_overlap_stages(parts, 2, "t") == 2
    with pytest.raises(ValueError, match="overlap_stages"):
        staging.resolve_overlap_stages(parts, 1, "t")
    with pytest.raises(ValueError, match="overlap_stages"):
        staging.resolve_overlap_stages(parts, 5, "t")
    with pytest.raises(ValueError, match="parts"):
        staging.resolve_overlap_stages(None, 0, "t")


# --------------------------------------- overlapped engine parity
# Same sweep-vs-smoke pattern as the bucketed rows above: the hybrid
# 2×(S/2) mesh (covering the hierarchical path) is the tier-1 smoke;
# plain-mesh twins ride the slow sweep.


@pytest.mark.parametrize("dcn", _MESH_SWEEP)
def test_ddp_overlapped_matches_bucketed_and_monolithic(dcn, devices):
    """Grads (via the 3-step trajectory + final params), metrics — all
    three reducers agree at rtol 1e-5, plain and hybrid mesh."""
    mesh = make_mesh(MeshSpec(data=8, dcn=dcn))
    res = {}
    for gr in ("monolithic", "bucketed", "overlapped"):
        eng = DDPEngine(
            tiny_cnn(10), SGD(), mesh, donate=False,
            grad_reduction=gr, bucket_mb=0.02,
        )
        res[gr] = _run(eng)
    for gr in ("bucketed", "overlapped"):
        np.testing.assert_allclose(
            res[gr][1], res["monolithic"][1], rtol=1e-5
        )
        _tree_close(res[gr][0].params, res["monolithic"][0].params)
        _tree_close(res[gr][2], res["monolithic"][2],
                    rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("dcn", _MESH_SWEEP)
def test_fsdp_overlapped_matches_monolithic_and_stays_sharded(
    dcn, devices
):
    """The stagewise ZeRO step: trajectory parity with the declarative
    engine AND the 1/N at-rest sharding of params + moments preserved
    (the regather-in-backward must not widen the stored state)."""
    from distributed_model_parallel_tpu.parallel.fsdp import FSDPEngine
    from distributed_model_parallel_tpu.training.optim import AdamW

    mesh = make_mesh(MeshSpec(data=8, dcn=dcn))
    res = {}
    for gr in ("monolithic", "overlapped"):
        eng = FSDPEngine(
            tiny_cnn(10), AdamW(), mesh, donate=False,
            min_shard_elems=64, grad_reduction=gr, bucket_mb=0.02,
        )
        res[gr] = _run(eng, lr=1e-3)
    np.testing.assert_allclose(
        res["overlapped"][1], res["monolithic"][1], rtol=1e-5
    )
    _tree_close(res["overlapped"][0].params,
                res["monolithic"][0].params)
    big = max(
        jax.tree_util.tree_leaves(res["overlapped"][0].params),
        key=lambda l: l.size,
    )
    assert np.prod(big.addressable_shards[0].data.shape) == (
        big.size // 8
    )
    mu = max(
        jax.tree_util.tree_leaves(res["overlapped"][0].opt_state.mu),
        key=lambda l: l.size,
    )
    assert np.prod(mu.addressable_shards[0].data.shape) == (
        mu.size // 8
    )


@pytest.mark.parametrize("dcn", _MESH_SWEEP)
def test_causal_lm_sp_overlapped_matches_monolithic(dcn, devices):
    """The lm CLI's engine: stagewise 'seq' psum + eager data buckets
    match the fused psum path, plain and hybrid."""
    from distributed_model_parallel_tpu.models.gpt import GPTConfig
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        CausalLMSequenceParallelEngine,
    )
    from distributed_model_parallel_tpu.training.optim import AdamW

    cfg = GPTConfig(
        vocab_size=64, dim=32, num_layers=2, num_heads=4, ffn_dim=64,
        max_position=32, dropout_rate=0.0, pad_token_id=0,
    )
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 64, size=(8, 32)).astype(np.int32)
    mesh = make_mesh(MeshSpec(data=4, seq=2, dcn=dcn))
    res = {}
    for gr in ("monolithic", "overlapped"):
        eng = CausalLMSequenceParallelEngine(
            cfg, AdamW(), mesh, donate=False,
            grad_reduction=gr, bucket_mb=0.02,
        )
        ts = eng.init_state(jax.random.PRNGKey(0))
        a, b = eng.shard_batch(ids)
        traj = []
        for _ in range(3):
            ts, m = eng.train_step(ts, a, b, jnp.float32(1e-3))
            traj.append(float(m["loss_sum"]))
        res[gr] = (ts, traj)
    np.testing.assert_allclose(
        res["overlapped"][1], res["monolithic"][1], rtol=1e-5
    )
    _tree_close(res["overlapped"][0].params,
                res["monolithic"][0].params, rtol=1e-4)


def test_overlapped_engine_construction_guards(devices):
    """Misuse fails at construction, not an epoch in: a model without
    stage anatomy, a 1-segment cut, more segments than blocks, and a
    1-layer LM."""
    from distributed_model_parallel_tpu.models import layers as L
    from distributed_model_parallel_tpu.models.gpt import GPTConfig
    from distributed_model_parallel_tpu.parallel.fsdp import FSDPEngine
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        CausalLMSequenceParallelEngine,
    )

    mesh = make_mesh(MeshSpec(data=8))
    partless = L.sequential(
        L.flatten(), L.linear(192, 16), L.linear(16, 10)
    )
    with pytest.raises(ValueError, match="parts"):
        DDPEngine(partless, SGD(), mesh, grad_reduction="overlapped")
    with pytest.raises(ValueError, match="overlap_stages"):
        DDPEngine(tiny_cnn(10), SGD(), mesh,
                  grad_reduction="overlapped", overlap_stages=1)
    with pytest.raises(ValueError, match="overlap_stages"):
        FSDPEngine(tiny_cnn(10), SGD(), mesh,
                   grad_reduction="overlapped", overlap_stages=9)
    cfg1 = GPTConfig(
        vocab_size=64, dim=16, num_layers=1, num_heads=2, ffn_dim=32,
        max_position=16, dropout_rate=0.0, pad_token_id=0,
    )
    smesh = make_mesh(MeshSpec(data=4, seq=2))
    with pytest.raises(ValueError, match="num_layers"):
        CausalLMSequenceParallelEngine(
            cfg1, SGD(), smesh, grad_reduction="overlapped"
        )


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-v"]))
