"""Epoch-driver trainer loops — the TPU-native `utils.py`/`data_parallel.py`
trainer surface.

Reproduces the reference's observable training behavior (SURVEY.md §5):
* per-batch loop with `batch_time` / `data_time` running averages —
  the two metrics the reference hand-accumulates (`utils.py:36-76`) and
  reports in its tables (`Readme.md:283-292`);
* progress print every `print_freq` batches (30 in the reference —
  `data_parallel.py:116-117`, `utils.py:69-70`);
* acc1/acc5 via the `accuracy(topk=(1,5))` contract (`utils.py:215-229`);
* per-epoch log line appended to a txt file (`data_parallel.py:167-171`,
  `model_parallel.py:119-125`) — plus structured JSONL, host-0 only;
* best-val-acc checkpointing and `--resume` (`data_parallel.py:80-87,
  143-155`), via `training/checkpoint.py`;
* cosine LR (T_max=90) with 10-epoch linear-warmup dampening stepped once
  per epoch (`data_parallel.py:90-96,163-164`).

Timing is fence-correct: JAX dispatch is async, so per-epoch averages are
computed from a fenced epoch wall clock, not from unfenced per-step deltas
(which would measure dispatch latency, not execution). The fence is a
VALUE FETCH of the epoch's summed metrics, not `block_until_ready` —
on a tunneled/remote TPU backend the latter can return at dispatch time
(measured ~100x-optimistic; see bench.py `_sync`), while fetched bytes
cannot exist before the steps that produced them ran.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Iterable, Optional

import jax
import jax.numpy as jnp

from distributed_model_parallel_tpu.checkpointing import (
    AsyncCheckpointer,
    restore_checkpoint,
    save_sharded,
)
from distributed_model_parallel_tpu.observability.metrics import (
    get_metrics,
)
from distributed_model_parallel_tpu.observability.trace import get_tracer
from distributed_model_parallel_tpu.runtime.dist import is_primary
from distributed_model_parallel_tpu.training.checkpoint import (
    newest_checkpoint_name,
    save_checkpoint,
)
from distributed_model_parallel_tpu.training.multistep import (
    compile_multi_eval,
    compile_multi_step,
    group_batches,
)
from distributed_model_parallel_tpu.training.optim import (
    cosine_warmup_schedule,
)


@dataclasses.dataclass
class EpochStats:
    """What the reference logs per epoch (`model_parallel.py:119-125`)."""

    loss: float = 0.0
    acc1: float = 0.0
    acc5: float = 0.0
    batch_time: float = 0.0  # avg seconds per batch, data included
    data_time: float = 0.0   # avg seconds waiting on the input pipeline
    count: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class TrainerConfig:
    """Trainer hyperparameters, flag-for-flag with the reference parsers
    (`data_parallel.py:19-23`, `model_parallel.py:15-42`); hard-coded
    reference values (epochs=100, T_max=90, print-every-30) become
    defaults."""

    epochs: int = 100
    base_lr: float = 0.1
    t_max: int = 90
    warmup_period: int = 10
    print_freq: int = 30
    log_dir: str = "./log"
    log_file: Optional[str] = None      # txt epoch log (e.g. "512.txt")
    checkpoint_dir: str = "./checkpoint"
    save_best: bool = True
    resume: bool = False
    # Truncate each training epoch to N batches (0 = full epoch) — for
    # smoke runs and throughput benchmarking.
    steps_per_epoch: int = 0
    # Capture a jax.profiler trace of a few steady-state train steps
    # (compile and warmup excluded) into this directory; None disables.
    # The trace is the tool for attributing a bad MFU number (SURVEY.md §5
    # tracing row) — open with TensorBoard or xprof.
    profile_dir: Optional[str] = None
    # Also write a 'last' checkpoint at the END of every epoch (not just
    # on best val acc). This is what makes a run restartable after a
    # failure — the elastic driver loop (`training/elastic.py`) resumes
    # from it; `--resume` prefers it over the best-acc snapshot when it
    # is newer.
    save_last: bool = False
    # Checkpoint on-disk format: "legacy" = the reference-shaped single
    # .npz gathered to host 0 (`training/checkpoint.py`); "sharded" =
    # each process writes only its locally-addressable shards plus a
    # JSON manifest (`checkpointing/` — ZeRO-style parallel save, no
    # cross-process gather anywhere on the save path, and restore can
    # RESHARD onto a different mesh). Restore auto-detects either
    # format regardless of this setting.
    checkpoint_format: str = "legacy"
    # Move checkpoint file I/O off the step path (sharded format only):
    # the save snapshots device->host once, then a background thread
    # writes the files while training continues. Write errors are NEVER
    # silent — they surface at the next save or at fit() exit
    # (`checkpointing/writer.py`).
    async_save: bool = False
    # Extra JSON-able metadata stored in the checkpoint sidecar /
    # manifest (e.g. the lm CLI records its GPTConfig so `cli/serve.py
    # --checkpoint` can fail fast on a flag mismatch).
    checkpoint_extra: Optional[dict] = None
    # Fold this many optimizer steps into ONE compiled dispatch
    # (lax.scan over stacked batches — `training/multistep.py`). The
    # training trajectory matches per-step dispatch to numerical
    # tolerance (same math; XLA may fuse across step boundaries
    # differently — pinned at rtol 1e-5 in tests/test_trainer.py); what
    # changes is the host->device round-trip count, the measured 7-9x
    # end-to-end gap on a relay-attached accelerator (RESULTS §1c).
    # Epoch tails shorter than the group fall back to per-step dispatch
    # (one extra compile the first time a tail occurs). 1 = off.
    steps_per_dispatch: int = 1


class Trainer:
    """Drives an engine (DP / DDP / pipeline — anything exposing
    `train_step`, `eval_step`, `shard_batch`, `init_state`) through the
    reference's epoch protocol."""

    def __init__(
        self,
        engine: Any,
        train_loader: Iterable,
        val_loader: Optional[Iterable],
        config: TrainerConfig,
        rng: Optional[jax.Array] = None,
    ):
        self.engine = engine
        self.train_loader = train_loader
        self.val_loader = val_loader
        self.config = config
        if config.checkpoint_format not in ("legacy", "sharded"):
            raise ValueError(
                "checkpoint_format must be 'legacy' or 'sharded', got "
                f"{config.checkpoint_format!r}"
            )
        if config.async_save and config.checkpoint_format != "sharded":
            raise ValueError(
                "async_save moves the sharded writer off the step path; "
                "it requires checkpoint_format='sharded' (the legacy "
                "format gathers to host 0 synchronously by design)"
            )
        self._ckpt_writer = (
            AsyncCheckpointer() if config.async_save else None
        )
        self.lr_fn = cosine_warmup_schedule(
            config.base_lr, config.t_max, config.warmup_period
        )
        self.state = engine.init_state(
            rng if rng is not None else jax.random.PRNGKey(0)
        )
        self.best_acc = 0.0
        self.start_epoch = 0
        if config.resume:
            # Resume from whichever snapshot is NEWER by its recorded
            # epoch: the per-epoch 'last' (written under save_last) when
            # it is ahead of the best-acc 'ckpt', so an elastic restart
            # loses at most the failed epoch — but a stale 'last' from an
            # older run never rolls a newer 'ckpt' back. Only host 0's
            # files matter: restore_checkpoint broadcasts host-0's read.
            name = newest_checkpoint_name(config.checkpoint_dir)
            restored, self.best_acc, last_epoch = restore_checkpoint(
                config.checkpoint_dir, self._to_canonical(self.state),
                name=name,
            )
            self.state = self._from_canonical(restored)
            self.start_epoch = last_epoch + 1
            self._log_print(
                f"==> Resumed from checkpoint: epoch {last_epoch}, "
                f"best acc {self.best_acc:.3f}"
            )
            if self.start_epoch >= config.epochs:
                # Deliberate deviation from the reference, which always
                # trains `epochs` FURTHER epochs on resume
                # (`data_parallel.py:160`); here fit() runs
                # range(start_epoch, epochs), so resuming a finished run
                # is a no-op — say so instead of silently returning.
                self._log_print(
                    f"==> WARNING: checkpoint is at epoch {last_epoch} but "
                    f"--epochs is {config.epochs}; fit() will train 0 "
                    f"epochs. Raise --epochs to continue training."
                )
        self.history: list[dict] = []
        self._profiled = False
        self._multi = None       # lazily compiled k-step train dispatch
        self._multi_eval = None  # lazily compiled k-batch eval dispatch

    # ------------------------------------------------------------- loops

    def train_epoch(self, epoch: int) -> EpochStats:
        cfg = self.config
        # Host-phase spans (observability/trace.py; off by default —
        # one branch per site): fetch = host load + device placement,
        # step = the dispatch call (enqueue under async dispatch),
        # sync = the value-fetch fences where device time surfaces,
        # checkpoint_blocked = how long a save holds this loop
        # (_write_checkpoint). The metrics registry
        # (observability/metrics.py; same off-by-default discipline)
        # mirrors the phases as distributions: train_fetch_s /
        # train_step_s histograms, timestamps from the tracer's
        # injectable clock so tests stay deterministic.
        tracer = get_tracer()
        mx = get_metrics()
        lr = jnp.asarray(self.lr_fn(epoch), jnp.float32)
        if hasattr(self.train_loader, "set_epoch"):
            # Re-seed the per-epoch shuffle + augmentation RNG (the torch
            # DataLoader reshuffles per epoch; our Loader keys on epoch).
            self.train_loader.set_epoch(epoch)
        it = iter(self.train_loader)
        sums = None
        n_batches = 0
        data_time = 0.0
        # Profile steps 10-12 of the first profiled epoch (past compile and
        # cache warmup); short smoke epochs profile from the first step so
        # the capture is never silently empty.
        # Batches this epoch can actually yield: the loader length
        # bounded by the steps_per_epoch truncation (None = unknown).
        # One source of truth for the profiler window AND the dispatch
        # clamp below.
        n_avail = (
            len(self.train_loader)
            if hasattr(self.train_loader, "__len__") else None
        )
        if cfg.steps_per_epoch:
            n_avail = (
                min(n_avail, cfg.steps_per_epoch)
                if n_avail else cfg.steps_per_epoch
            )
        profiling = False
        k = max(1, cfg.steps_per_dispatch)
        if n_avail is not None and k > n_avail:
            # A group larger than the epoch would NEVER fill, silently
            # degrading every epoch to per-step dispatch (the gap this
            # feature exists to close) — clamp so at least one fused
            # dispatch runs per epoch.
            if not getattr(self, "_warned_k_clamp", False):
                self._log_print(
                    f"==> steps_per_dispatch {k} exceeds the "
                    f"{n_avail}-batch epoch; clamping to {n_avail}"
                )
                self._warned_k_clamp = True
            k = max(1, n_avail)
        profile_at = None
        if cfg.profile_dir and not self._profiled:
            profile_at = 10 if (n_avail is None or n_avail > 12) else 0
            if profile_at and k > 1:
                # Dispatches happen at group granularity: arm at the
                # first group START past the warmup threshold so the
                # capture excludes the fused program's trace+compile.
                # When no later group exists (the epoch fits in one),
                # fall back to profiling the first dispatch — capturing
                # compile beats an empty trace directory.
                ga = ((profile_at + k - 1) // k) * k
                profile_at = (
                    ga if (n_avail is None or ga < n_avail) else 0
                )
        def fetch_group(n_done: int):
            """Pull + device-place the next dispatch group (up to k host
            batches, bounded by the steps_per_epoch budget; [] when the
            epoch is exhausted). Host loading is what data_time measures;
            shard_batch transfers are enqueued asynchronously, so calling
            this right after a dispatch stages the NEXT group's arrays
            while the current device step is still in flight."""
            nonlocal data_time
            want = k
            if cfg.steps_per_epoch:
                want = min(k, cfg.steps_per_epoch - n_done)
                if want <= 0:
                    return []
            with tracer.span("fetch", want=want):
                t0 = time.perf_counter()
                tm0 = tracer.now() if mx.enabled else 0.0
                host_batches = group_batches(it, want)
                data_time += time.perf_counter() - t0
                if mx.enabled and host_batches:
                    # Metric clock = tracer clock (injectable), like
                    # train_step_s; data_time keeps the wall clock the
                    # reference's report fields are defined on.
                    mx.observe(
                        "train_fetch_s",
                        (tracer.now() - tm0) / len(host_batches),
                    )
                return [
                    self.engine.shard_batch(*b) for b in host_batches
                ]

        epoch_start = time.perf_counter()
        # Metrics state: the step-time boundary clock (tracer domain,
        # so tests inject it) and the one-deep progress-print snapshot
        # (n_batches, metrics) of the PREVIOUS dispatch group.
        t_boundary = tracer.now() if mx.enabled else None
        printable = None
        placed = fetch_group(0)
        while placed:
            if (
                profile_at is not None
                and not profiling
                and n_batches >= profile_at
            ):
                # Arm on the first dispatch whose START is past the
                # warmup threshold — a group that merely SPANS it would
                # capture the k-step program's trace+compile, the cost
                # the offset exists to exclude.
                jax.block_until_ready(self.state)  # trace excludes backlog
                jax.profiler.start_trace(cfg.profile_dir)
                profiling = True
            with tracer.span("step", n=len(placed)):
                if len(placed) == k and k > 1:
                    # One dispatch, k steps (trajectory matches the
                    # per-step path to numerical tolerance —
                    # tests/test_trainer.py).
                    if self._multi is None:
                        self._multi = compile_multi_step(self.engine, k)
                    self.state, metrics = self._multi(
                        self.state, tuple(placed), lr
                    )
                else:
                    metrics = None
                    for b in placed:
                        self.state, m_i = self.engine.train_step(
                            self.state, *b, lr
                        )
                        metrics = (
                            m_i
                            if metrics is None
                            else jax.tree_util.tree_map(
                                jnp.add, metrics, m_i
                            )
                        )
            prev = n_batches
            n_group = len(placed)
            n_batches += n_group
            # One-deep device prefetch: the dispatch above returned at
            # enqueue time, so the next group's host load + placement
            # overlaps the in-flight compute — and, crucially, runs
            # BEFORE the progress print's device_get below fences on
            # that compute. On the CPU test harness the effect is small
            # (RESULTS.md §1g); the reorder exists for relay-attached
            # accelerators, where the fence is a network round-trip and
            # anything sequenced after it is dead time.
            placed = fetch_group(n_batches)
            if profiling and n_batches >= profile_at + 3:
                jax.block_until_ready(self.state)
                jax.profiler.stop_trace()
                profiling = False
                self._profiled = True
                profile_at = None  # never re-arm within this epoch
            sums = (
                metrics
                if sums is None
                else jax.tree_util.tree_map(jnp.add, sums, metrics)
            )
            if mx.enabled:
                # Step-time sample at dispatch granularity (boundary
                # to boundary, prefetch included), CLOSED before the
                # progress-print fetch below so the histogram can
                # never measure its own readback stall.
                t_now = tracer.now()
                if t_boundary is not None:  # None: enabled mid-epoch
                    mx.observe(
                        "train_step_s", (t_now - t_boundary) / n_group
                    )
                mx.inc("train_batches_total", n_group)
                t_boundary = t_now
            if cfg.print_freq and (
                n_batches // cfg.print_freq > prev // cfg.print_freq
            ):
                # Fetch the PREVIOUS group's metrics (the one-deep
                # snapshot seam, same shape as the input prefetch): a
                # newer dispatch already runs behind them, so this
                # device_get returns without fencing the in-flight
                # compute — the progress print no longer injects a
                # readback stall into the loop it reports on
                # (RESULTS §2's fence note; regression-pinned with an
                # injected slow clock in tests/test_observability.py).
                # The first print of an epoch has no predecessor and
                # falls back to fencing the current group.
                snap_n, snap_metrics = (
                    printable if printable is not None
                    else (n_batches, metrics)
                )
                with tracer.span("sync"):
                    m = jax.device_get(snap_metrics)
                self._log_print(
                    f"Epoch: [{epoch}]"
                    f"[{snap_n}/{n_avail if n_avail is not None else '?'}]"
                    f"\tLoss {m['loss_sum'] / m['count']:.4e}"
                    f"\tAcc@1 {100.0 * m['correct1'] / m['count']:.3f}"
                    f"\tTime {(time.perf_counter() - epoch_start) / n_batches:.3f}"
                )
            printable = (n_batches, metrics)
        # Value-fetch barrier: on a tunneled/remote backend
        # block_until_ready can return at dispatch time (see
        # bench._sync), but fetching the summed metrics' bytes cannot
        # complete before every step that fed the sum has executed.
        if sums is not None:
            with tracer.span("sync", epoch=epoch):
                sums = jax.device_get(sums)
        if profiling:  # epoch ended inside the capture window
            jax.profiler.stop_trace()
            self._profiled = True
        wall = time.perf_counter() - epoch_start
        return self._finalize(sums, n_batches, wall, data_time)

    def validate(self, epoch: int) -> EpochStats:
        it = iter(self.val_loader)
        sums = None
        n_batches = 0
        data_time = 0.0
        k = max(1, self.config.steps_per_dispatch)
        if hasattr(self.val_loader, "__len__"):
            k = max(1, min(k, len(self.val_loader)))
        epoch_start = time.perf_counter()
        while True:
            t0 = time.perf_counter()
            host_batches = group_batches(it, k)
            data_time += time.perf_counter() - t0
            if not host_batches:
                break
            placed = [self.engine.shard_batch(*b) for b in host_batches]
            if len(placed) == k and k > 1:
                if self._multi_eval is None:
                    self._multi_eval = compile_multi_eval(self.engine, k)
                metrics = self._multi_eval(self.state, tuple(placed))
            else:
                metrics = None
                for b in placed:
                    m_i = self.engine.eval_step(self.state, *b)
                    metrics = (
                        m_i
                        if metrics is None
                        else jax.tree_util.tree_map(jnp.add, metrics, m_i)
                    )
            sums = (
                metrics
                if sums is None
                else jax.tree_util.tree_map(jnp.add, sums, metrics)
            )
            n_batches += len(placed)
        if sums is not None:
            sums = jax.device_get(sums)  # value-fetch barrier, as above
        wall = time.perf_counter() - epoch_start
        return self._finalize(sums, n_batches, wall, data_time)

    def fit(self) -> dict:
        """The 100-epoch driver loop (`data_parallel.py:160-172`): train,
        validate, checkpoint on best acc, append the epoch log line."""
        try:
            return self._fit()
        except BaseException:
            # The failure path (exactly where the elastic supervisor
            # restarts from) must still DRAIN in-flight background
            # writes: the restart reads this checkpoint directory
            # immediately, and racing a half-committed save would hand
            # it yesterday's (or no) manifest. A write failure here is
            # printed, not raised — masking the training exception
            # would hide the error the supervisor's retry_on keys on.
            if self._ckpt_writer is not None:
                try:
                    self._ckpt_writer.wait()
                except Exception as we:  # noqa: BLE001 — reported below
                    self._log_print(
                        "==> WARNING: background checkpoint write "
                        f"failed during abort: {we!r}"
                    )
            raise

    def _fit(self) -> dict:
        cfg = self.config
        for epoch in range(self.start_epoch, cfg.epochs):
            train_stats = self.train_epoch(epoch)
            val_stats = (
                self.validate(epoch)
                if self.val_loader is not None
                else EpochStats()
            )
            is_best = (
                cfg.save_best
                and self.val_loader is not None
                and val_stats.acc1 > self.best_acc
            )
            if is_best or cfg.save_last:
                payload = self._checkpoint_payload()  # once per epoch
            if is_best:
                self.best_acc = val_stats.acc1
                self._log_print("Saving..")
                self._write_checkpoint(payload, "ckpt", epoch)
            if cfg.save_last:
                # acc records the best-so-far (restored into best_acc on
                # resume) — storing this epoch's val acc here would let a
                # restart reset best_acc downward and a worse model later
                # overwrite the best snapshot.
                self._write_checkpoint(payload, "last", epoch)
            self._append_epoch_log(epoch, train_stats, val_stats)
        if self._ckpt_writer is not None:
            # fit() exit is the LAST surfacing point for async write
            # errors (checkpointing/writer.py: never silent) and the
            # join guaranteeing the final snapshot is durable on return.
            self._ckpt_writer.wait()
        return {
            "best_acc": self.best_acc,
            "epochs": cfg.epochs,
            "history": self.history,
        }

    # ----------------------------------------------------------- helpers

    def _checkpoint_payload(self):
        """The tree handed to the checkpoint writer: the host-gathered
        canonical form for the legacy format; for the sharded format,
        the engine's DEVICE-SHARDED state via the `to_canonical_sharded`
        seam (canonical tree structure, values still 1/N per process —
        each process then persists only its addressable chunks and no
        cross-process gather runs anywhere on the save path)."""
        if self.config.checkpoint_format == "legacy":
            return self._to_canonical(self.state)
        fn = getattr(self.engine, "to_canonical_sharded", None)
        if fn is not None:
            return fn(self.state)
        if getattr(self.engine, "to_canonical", None) is not None:
            raise ValueError(
                f"{type(self.engine).__name__} defines a RESTRUCTURING "
                "canonical form (to_canonical) without a "
                "to_canonical_sharded seam, so its runtime layout "
                "cannot be written shard-for-shard; use "
                "checkpoint_format='legacy' with this engine"
            )
        return self.state  # state IS canonical (DP/DDP/SP engines)

    def _write_checkpoint(self, payload, name: str, epoch: int) -> None:
        cfg = self.config
        # checkpoint_blocked spans the time this save holds the epoch
        # loop: the whole write for sync formats, only the device->host
        # snapshot under async_save (the writer thread records its own
        # ckpt_background_write span — checkpointing/writer.py).
        tracer = get_tracer()
        mx = get_metrics()
        t0 = tracer.now() if mx.enabled else None
        try:
            with tracer.span(
                "checkpoint_blocked", snapshot=name, epoch=epoch,
                format=cfg.checkpoint_format,
            ):
                if cfg.checkpoint_format == "legacy":
                    save_checkpoint(
                        cfg.checkpoint_dir, payload, acc=self.best_acc,
                        epoch=epoch, name=name,
                        extra=cfg.checkpoint_extra,
                    )
                    return
                if self._ckpt_writer is not None:
                    # Surface an earlier epoch's failed background
                    # write BEFORE starting a new one
                    # (checkpointing/writer.py contract).
                    self._ckpt_writer.check()
                save_sharded(
                    cfg.checkpoint_dir, payload, acc=self.best_acc,
                    epoch=epoch, name=name, extra=cfg.checkpoint_extra,
                    writer=self._ckpt_writer,
                )
        finally:
            if t0 is not None:
                mx.observe(
                    "train_checkpoint_blocked_s", tracer.now() - t0
                )

    def _to_canonical(self, state):
        """Checkpoints are written in the engine's layout-independent
        canonical form when it defines one (e.g. PipelineEngine's
        stage-local packed params -> per-stage pytrees with real layer
        paths), so checkpoints stay interchangeable across engine storage
        layouts and validate per-layer structure on restore."""
        fn = getattr(self.engine, "to_canonical", None)
        return fn(state) if fn is not None else state

    def _from_canonical(self, state):
        fn = getattr(self.engine, "from_canonical", None)
        return fn(state) if fn is not None else state

    def _finalize(
        self, sums, n_batches: int, wall: float, data_time: float
    ) -> EpochStats:
        if sums is None or n_batches == 0:
            return EpochStats()
        m = jax.device_get(sums)
        count = float(m["count"])
        return EpochStats(
            loss=float(m["loss_sum"]) / count,
            acc1=100.0 * float(m["correct1"]) / count,
            acc5=100.0 * float(m["correct5"]) / count,
            batch_time=wall / n_batches,
            data_time=data_time / n_batches,
            count=int(count),
        )

    def _append_epoch_log(
        self, epoch: int, train: EpochStats, val: EpochStats
    ) -> None:
        """One line per epoch, same fields as the reference's
        `file.write(...)` block (`model_parallel.py:119-125`), plus a JSONL
        twin for machines. Host-0 only (logs are rank-0 artifacts in the
        reference too)."""
        record = {
            "epoch": epoch,
            "train": train.as_dict(),
            "val": val.as_dict(),
            "best_acc": self.best_acc,
        }
        self.history.append(record)
        if not is_primary():
            return
        cfg = self.config
        line = (
            f"epoch {epoch} "
            f"train_loss {train.loss:.4f} train_acc1 {train.acc1:.3f} "
            f"val_loss {val.loss:.4f} val_acc1 {val.acc1:.3f} "
            f"time_per_batch {train.batch_time:.4f} "
            f"time_load_perbatch {train.data_time:.4f}"
        )
        self._log_print(line)
        if cfg.log_file:
            os.makedirs(cfg.log_dir, exist_ok=True)
            with open(os.path.join(cfg.log_dir, cfg.log_file), "a") as f:
                f.write(line + "\n")
            jsonl = os.path.splitext(cfg.log_file)[0] + ".jsonl"
            with open(os.path.join(cfg.log_dir, jsonl), "a") as f:
                f.write(json.dumps(record) + "\n")

    @staticmethod
    def _log_print(msg: str) -> None:
        if is_primary():
            print(msg, flush=True)
