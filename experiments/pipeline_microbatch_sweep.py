"""Pipeline microbatch sweep: measured time/batch vs the bubble math.

The reference's headline pipeline finding is that one-batch-in-flight
model parallelism is ~4x slower than data parallelism
(`/root/reference/Readme.md:283-292`) — a pure schedule artifact: with S
stages and M microbatches the pipeline runs M+S-1 ticks for M microbatches
of work, so time/batch scales like (M+S-1)/M (=S at the reference's M=1,
->1 as M grows). This sweep measures that curve on the 4-stage engine and
overlays the ideal, producing the schedule-analysis figure the
reference's report format calls for (pic/).

Run: python experiments/pipeline_microbatch_sweep.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_model_parallel_tpu.runtime.platform import force_cpu  # noqa: E402


def main() -> None:
    force_cpu(8)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_model_parallel_tpu.models import layers as L
    from distributed_model_parallel_tpu.parallel import PipelineEngine
    from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
    from distributed_model_parallel_tpu.training.optim import SGD

    S = 4
    mesh = make_mesh(MeshSpec(data=2, stage=S))
    stages = [
        L.sequential(L.conv2d(3, 32, 3, stride=1, padding=1), L.relu()),
        L.sequential(L.conv2d(32, 32, 3, stride=1, padding=1), L.relu()),
        L.sequential(L.conv2d(32, 32, 3, stride=1, padding=1), L.relu()),
        L.sequential(L.global_avg_pool(), L.linear(32, 10)),
    ]
    rng = np.random.RandomState(0)
    batch = 64
    images = rng.rand(batch, 8, 8, 3).astype(np.float32)
    labels = rng.randint(0, 10, size=(batch,)).astype(np.int32)

    rows = []
    for m in (1, 2, 4, 8, 16):
        engine = PipelineEngine(
            stages, SGD(), mesh, num_microbatches=m, donate=False
        )
        ts = engine.init_state(jax.random.PRNGKey(0))
        im, lb = engine.shard_batch(images, labels)
        lr = jnp.float32(0.05)
        for _ in range(2):  # compile + warm
            ts, _ = engine.train_step(ts, im, lb, lr)
        jax.block_until_ready(ts)
        iters = 4
        t0 = time.perf_counter()
        for _ in range(iters):
            ts, _ = engine.train_step(ts, im, lb, lr)
        jax.block_until_ready(ts)
        dt = (time.perf_counter() - t0) / iters
        rows.append({"M": m, "time_per_batch": dt})
        print(f"M={m:>2}: {dt:.3f} s/batch", flush=True)

    base = rows[0]["time_per_batch"]  # M=1: the reference's schedule
    for r in rows:
        m = r["M"]
        r["speedup_vs_m1"] = round(base / r["time_per_batch"], 2)
        # ideal time ratio t(M)/t(1) = (M+S-1) / (M*S)
        r["ideal_speedup"] = round(m * S / (m + S - 1), 2)

    os.makedirs("pic", exist_ok=True)
    with open("experiments/pipeline_microbatch_sweep.json", "w") as f:
        json.dump({"S": S, "batch": batch, "rows": rows}, f, indent=2)

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    ms = [r["M"] for r in rows]
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.plot(ms, [r["speedup_vs_m1"] for r in rows], marker="o",
            label="measured")
    ax.plot(ms, [r["ideal_speedup"] for r in rows], marker="s",
            linestyle="--", label="ideal  M·S/(M+S−1)")
    ax.set_xscale("log", base=2)
    ax.set_xticks(ms)
    ax.set_xticklabels(ms)
    ax.set_xlabel("microbatches M")
    ax.set_ylabel("speedup vs M=1 (reference schedule)")
    ax.set_title(f"GPipe fill-drain: bubble (S−1)/(M+S−1), S={S}")
    ax.grid(alpha=0.3)
    ax.legend()
    fig.tight_layout()
    fig.savefig("pic/pipeline_microbatch_sweep.png", dpi=120)
    print("wrote pic/pipeline_microbatch_sweep.png")


if __name__ == "__main__":
    main()
