"""Golden-file tests for the HLO parser/classifier
(`analysis/hlo.py`, `analysis/collectives.py`): canned HLO text, no
mesh construction, no lowering — fast tier-1 coverage of the parsing
edge cases the engine matrix exercises only incidentally (nested
computations, missing metadata, empty/iota replica groups, async
start/done pairs, tuple results, alias tables)."""

import pytest

from distributed_model_parallel_tpu.analysis.collectives import (
    MeshModel,
    classify,
    monolithic_over,
    nonscalar_all_reduces,
    ring_permutes_over,
)
from distributed_model_parallel_tpu.analysis.hlo import (
    Buffer,
    collective_counts,
    has_op_with_result,
    nonscalar_all_reduce_count,
    parse_hlo,
    parse_replica_groups,
    parse_result_buffers,
)

# A hand-written module exercising: header alias table, a nested
# reduction region, an ENTRY computation, explicit + iota replica
# groups, permute pairs, named-scope metadata, a metadata-free line,
# an async all-gather pair, and a tuple-result instruction.
GOLDEN = """\
HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias), {1}: (1, {}, may-alias), {2}: (2, {}, may-alias) }, entry_computation_layout={(f32[8]{0})->f32[8]{0}}

%region_0.4 (a.1: f32[], b.1: f32[]) -> f32[] {
  %a.1 = f32[] parameter(0)
  %b.1 = f32[] parameter(1)
  ROOT %add.9 = f32[] add(f32[] %a.1, f32[] %b.1)
}

%fused_computation (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %mul.3 = f32[8]{0} multiply(f32[8]{0} %p0, f32[8]{0} %p0)
}

ENTRY %main.9_spmd (param: f32[8], param.1: f32[2,4], param.2: s32[]) -> f32[8] {
  %param = f32[8]{0} parameter(0)
  %param.1 = f32[2,4]{1,0} parameter(1)
  %param.2 = s32[] parameter(2)
  %fusion = f32[8]{0} fusion(f32[8]{0} %param), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(step)/grad_reduce_stage1/mul"}
  %ar.0 = f32[8]{0} all-reduce(f32[8]{0} %fusion), channel_id=1, replica_groups={{0,1,2,3},{4,5,6,7}}, use_global_device_ids=true, to_apply=%region_0.4, metadata={op_name="jit(step)/grad_reduce_stage1/psum"}
  %ar.scalar = f32[] all-reduce(f32[] %param.2), channel_id=2, replica_groups={{0,4},{1,5},{2,6},{3,7}}, use_global_device_ids=true, to_apply=%region_0.4, metadata={op_name="jit(step)/metrics/psum"}
  %cp.0 = f32[8]{0} collective-permute(f32[8]{0} %ar.0), channel_id=3, source_target_pairs={{0,1},{1,2},{2,3},{3,0}}, metadata={op_name="jit(step)/bwd_stage0/ppermute"}
  %ag-start = (f32[8]{0}, f32[32]{0}) all-gather-start(f32[8]{0} %cp.0), channel_id=4, replica_groups=[2,4]<=[8], dimensions={0}, use_global_device_ids=true
  %ag-done = f32[32]{0} all-gather-done((f32[8]{0}, f32[32]{0}) %ag-start)
  %notag = f32[8]{0} slice(f32[32]{0} %ag-done), slice={[0:8]}
  ROOT %out = f32[8]{0} add(f32[8]{0} %notag, f32[8]{0} %param)
}
"""

# A 2x4 dcn x ici mesh: device d at coords (d // 4, d % 4).
MESH_2x4 = MeshModel(
    axis_names=("dcn", "ici"),
    shape=(2, 4),
    coords={d: (d // 4, d % 4) for d in range(8)},
)


def test_parse_module_structure():
    m = parse_hlo(GOLDEN)
    assert m.entry == "main.9_spmd"
    assert set(m.computations) == {
        "region_0.4", "fused_computation", "main.9_spmd",
    }
    assert m.input_output_aliases == 3
    params = m.entry_parameters()
    assert [p.name for p in params] == ["param", "param.1", "param.2"]
    assert params[1].buffers == (Buffer("f32", (2, 4)),)


def test_parse_replica_group_forms():
    assert parse_replica_groups("{{0,1},{2,3}}") == ((0, 1), (2, 3))
    assert parse_replica_groups("{}") == ()
    assert parse_replica_groups("[2,4]<=[8]") == (
        (0, 1, 2, 3), (4, 5, 6, 7),
    )
    # transposed iota: arange(8).reshape(2,4).T.reshape(4,2)
    assert parse_replica_groups("[4,2]<=[2,4]T(1,0)") == (
        (0, 4), (1, 5), (2, 6), (3, 7),
    )
    assert parse_replica_groups("bogus") is None


def test_parse_result_buffer_forms():
    assert parse_result_buffers("f32[2,4]{1,0}") == (
        Buffer("f32", (2, 4)),
    )
    assert parse_result_buffers("pred[]") == (Buffer("pred", ()),)
    assert parse_result_buffers("(f32[8]{0}, u32[])") == (
        Buffer("f32", (8,)), Buffer("u32", ()),
    )
    assert Buffer("bf16", (4, 4)).nbytes == 32
    assert Buffer("f32", ()).is_scalar


def test_async_pair_counted_once():
    m = parse_hlo(GOLDEN)
    names = [c.name for c in m.collectives()]
    assert "ag-start" in names and "ag-done" not in names
    # text-level twin agrees
    assert collective_counts(GOLDEN)["all-gather"] == 1


def test_missing_metadata_and_tagging():
    m = parse_hlo(GOLDEN)
    assert m.instructions["notag"].op_name == ""
    assert m.tagged("grad_reduce_stage1") == ["fusion", "ar.0"]
    # trailing-slash discipline: stage1 never matches a stage10 tag
    assert m.tagged("grad_reduce_stage") == []
    assert m.tagged("grad_reduce_stage1", "all-reduce") == ["ar.0"]


def test_reachability_through_called_computations():
    m = parse_hlo(GOLDEN)
    # ar.0 -> fusion -> (calls) fused_computation -> p0; and transitively
    # back to the entry parameter through the fusion operand.
    assert m.depends_on("ar.0", {"param"})
    assert m.depends_on("out", {"ar.0"})
    assert not m.depends_on("fusion", {"cp.0"})
    # a name that appears nowhere is unreachable, not an error
    assert not m.depends_on("fusion", {"nonexistent"})


def test_classify_fabrics_on_hybrid_mesh():
    m = parse_hlo(GOLDEN)
    cols = classify(m, MESH_2x4)
    by_name = {c.name: c for c in cols}
    assert by_name["ar.0"].axes == frozenset({"ici"})
    assert by_name["ar.scalar"].axes == frozenset({"dcn"})
    assert by_name["ar.scalar"].is_scalar
    assert by_name["cp.0"].axes == frozenset({"ici"})
    assert by_name["ag-start"].axes == frozenset({"ici"})
    assert by_name["ar.0"].crosses("ici")
    assert not by_name["ar.0"].crosses("dcn")
    assert len(ring_permutes_over(cols, "ici")) == 1
    assert ring_permutes_over(cols, "dcn") == []
    assert [c.name for c in monolithic_over(cols, "ici")] == ["ag-start"]
    assert [c.name for c in nonscalar_all_reduces(cols)] == ["ar.0"]


def test_unknown_device_ids_classify_as_unknown():
    tiny = MeshModel(
        axis_names=("data",), shape=(2,), coords={0: (0,), 1: (1,)},
    )
    m = parse_hlo(GOLDEN)
    by_name = {c.name: c for c in classify(m, tiny)}
    assert by_name["ar.0"].axes is None
    # unknown membership conservatively answers True to crosses()
    assert by_name["ar.0"].crosses("data")


def test_text_level_helpers_match_legacy_semantics():
    assert has_op_with_result(GOLDEN, "all-reduce", "f32[8]")
    assert not has_op_with_result(GOLDEN, "all-reduce", "f32[9]")
    # async tuple results match through the parenthesized form
    assert has_op_with_result(GOLDEN, "all-gather", "f32[32]")
    assert nonscalar_all_reduce_count(GOLDEN) == 1
    c = collective_counts(GOLDEN)
    assert c["all-reduce"] == 2 and c["collective-permute"] == 1
    assert c["reduce-scatter"] == 0 and c["all-to-all"] == 0


def test_empty_replica_groups_and_degenerate_modules():
    # empty groups: XLA's printed form for ONE group of ALL devices —
    # a world-spanning collective. It must classify as crossing every
    # non-trivial mesh axis (hiding it would blind the fabric rules to
    # exactly the traffic they forbid).
    text = """\
ENTRY %e (p: f32[4]) -> f32[4] {
  %p = f32[4]{0} parameter(0)
  ROOT %ar = f32[4]{0} all-reduce(f32[4]{0} %p), replica_groups={}, to_apply=%r
}
"""
    m = parse_hlo(text)
    assert m.instructions["ar"].replica_groups == ()
    [c] = classify(m, MESH_2x4)
    assert c.axes == frozenset({"dcn", "ici"})
    assert c.crosses("dcn") and c.crosses("ici")
    # the empty string parses to an empty module
    empty = parse_hlo("")
    assert empty.entry is None and empty.instructions == {}
    assert empty.collectives() == []


def test_unparseable_result_shape_stays_visible_to_nonscalar_rules():
    """A collective whose result fails the shape grammar (empty
    buffers) must NOT masquerade as scalar — it would vanish from every
    non-scalar all-reduce rule."""
    text = """\
ENTRY %e (p: f32[4]) -> f32[4] {
  %p = f32[4]{0} parameter(0)
  ROOT %ar = f32[<=1024] all-reduce(f32[4]{0} %p), replica_groups={{0,1}}, to_apply=%r
}
"""
    m = parse_hlo(text)
    ar = m.instructions["ar"]
    assert ar.buffers == ()  # the bounded-dynamic shape didn't parse
    assert not ar.is_scalar  # ...but it must not count as scalar
    [c] = classify(m, MESH_2x4)
    assert [x.name for x in nonscalar_all_reduces([c])] == ["ar"]


def test_parser_tolerates_unknown_attributes():
    text = """\
ENTRY %e (p: f32[4]) -> f32[4] {
  %p = f32[4]{0} parameter(0), sharding={replicated}, frontend_attributes={x="y"}
  ROOT %cp = f32[4]{0} collective-permute(f32[4]{0} %p), channel_id=9, source_target_pairs={{0,1},{1,0}}, unknown_attr={weird}
}
"""
    m = parse_hlo(text)
    assert m.instructions["cp"].source_target_pairs == ((0, 1), (1, 0))
    assert m.instructions["cp"].channel_id == 9
