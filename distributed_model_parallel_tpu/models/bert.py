"""BERT family — the 'BERT-base DDP' capability from BASELINE.json.

Architecture follows the standard BERT encoder (post-LN, learned position
embeddings, token-type embeddings, tanh pooler over [CLS]); numerical
conventions (LayerNorm eps 1e-12, gelu, 0.02 init) match the torch
`transformers.BertModel` so parity is testable weight-for-weight against
that implementation (tests/test_bert.py transplants weights and compares
logits).

Inputs are int32 token ids (B, T), pad id 0; the attention mask is derived
as `ids != 0` — so the whole model is a standard `Layer` and every engine
(DP jit, DDP shard_map, pipeline) drives it exactly like the CNN zoo.

Stage splitting for pipeline parallelism follows the shared staging
convention: embeddings = stem, encoder layers = blocks, pooler+classifier
= head.

The encoder blocks are `models/transformer.py` wholesale, so every
projection matmul rides the `layers.project` collective-matmul hook:
under `TensorParallelEngine(collective_matmul=True)` /
`SequenceParallelEngine(collective_matmul=True)` the qkv/out and ffn
in/out matmuls run as latency-hiding chunked ppermute rings
(`ops/collective_matmul.py`) with no model change.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import jax
import jax.numpy as jnp

from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.models import staging
from distributed_model_parallel_tpu.models.transformer import (
    AttentionFn,
    encoder_layer,
)
from distributed_model_parallel_tpu.ops.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    # Mixture-of-Experts: num_experts > 0 swaps the FFN of every
    # `moe_every`-th encoder layer (the 2nd, 4th, ... — the standard
    # alternating recipe) for a routed MoE (`models/moe.py`); train with
    # the GSPMD engines (`parallel/expert_parallel.py` shards experts
    # over the 'expert' mesh axis).
    num_experts: int = 0
    moe_every: int = 2
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25


BERT_BASE = BertConfig()


def embed_apply(params, ids, cfg: BertConfig, ln: L.Layer, drop: L.Layer,
                ctx, *, positions=None):
    """The embedding math, shared by the dense stem Layer and the
    sequence-parallel engine (`parallel/sequence_parallel.py` passes its
    shard's `positions` slice; one copy of the math, no drift).
    Returns (hidden, mask)."""
    mask = ids != cfg.pad_token_id
    if positions is None:
        positions = params["position"][: ids.shape[1]]
    h = (
        jnp.take(params["word"], ids, axis=0)
        + positions[None, :, :]
        + params["token_type"][0][None, None, :]
    )
    if ctx.dtype is not None:  # mixed precision enters here (int inputs)
        h = h.astype(ctx.dtype)
    h, _ = ln.apply(params["ln"], {}, h, ctx)
    h, _ = drop.apply({}, {}, h, ctx)
    return h, mask


def _embeddings(cfg: BertConfig) -> L.Layer:
    """word + position + token-type embeddings, LN, dropout. Input: int ids
    (B, T) (token-type ids all zero — single-segment; the classification
    surface this framework benchmarks). Output: (hidden, mask)."""
    ln = L.layernorm(cfg.hidden_size, eps=cfg.layer_norm_eps)
    drop = L.dropout(cfg.dropout_rate)

    def init(key):
        kw, kp, kt, kl = jax.random.split(key, 4)
        params = {
            "word": 0.02 * jax.random.normal(
                kw, (cfg.vocab_size, cfg.hidden_size)
            ),
            "position": 0.02 * jax.random.normal(
                kp, (cfg.max_position, cfg.hidden_size)
            ),
            "token_type": 0.02 * jax.random.normal(
                kt, (cfg.type_vocab_size, cfg.hidden_size)
            ),
            "ln": ln.init(kl)[0],
        }
        return params, {}

    def apply(params, state, ids, ctx):
        h, mask = embed_apply(params, ids, cfg, ln, drop, ctx)
        return (h, mask), state

    return L.Layer(init, apply)


def _encoder_blocks(
    cfg: BertConfig, attention_fn: AttentionFn
) -> List[L.Layer]:
    if cfg.num_experts > 0 and cfg.moe_every < 1:
        raise ValueError(
            f"moe_every must be >= 1 when num_experts > 0, got "
            f"{cfg.moe_every} (1 = every layer, 2 = every other, ...)"
        )
    blocks = []
    for i in range(cfg.num_layers):
        is_moe = cfg.num_experts > 0 and (i + 1) % cfg.moe_every == 0
        if is_moe:
            from distributed_model_parallel_tpu.models.moe import (
                moe_encoder_layer,
            )

            blocks.append(moe_encoder_layer(
                cfg.hidden_size,
                cfg.num_heads,
                cfg.intermediate_size,
                cfg.num_experts,
                top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                dropout_rate=cfg.dropout_rate,
                eps=cfg.layer_norm_eps,
                attention_fn=attention_fn,
            ))
        else:
            blocks.append(encoder_layer(
                cfg.hidden_size,
                cfg.num_heads,
                cfg.intermediate_size,
                dropout_rate=cfg.dropout_rate,
                eps=cfg.layer_norm_eps,
                attention_fn=attention_fn,
            ))
    return blocks


def _cls_head(cfg: BertConfig, num_classes: int) -> L.Layer:
    """tanh pooler over [CLS] + classifier; takes (hidden, mask)."""

    def init(key):
        kp, kc = jax.random.split(key)
        return {
            "pooler": {
                "w": 0.02 * jax.random.normal(
                    kp, (cfg.hidden_size, cfg.hidden_size)
                ),
                "b": jnp.zeros((cfg.hidden_size,)),
            },
            "classifier": {
                "w": 0.02 * jax.random.normal(
                    kc, (cfg.hidden_size, num_classes)
                ),
                "b": jnp.zeros((num_classes,)),
            },
        }, {}

    def apply(params, state, x, ctx):
        h, _ = x
        return head_apply(params, h[:, 0, :]), state

    return L.Layer(init, apply)


def head_apply(params, h_cls):
    """Pooler+classifier math on the [CLS] hidden state, shared with the
    sequence-parallel engine (which feeds its shard's local token 0).
    Computed in f32 (bf16-safe logits)."""
    pooled = jnp.tanh(
        h_cls.astype(jnp.float32) @ params["pooler"]["w"]
        + params["pooler"]["b"]
    )
    return pooled @ params["classifier"]["w"] + params["classifier"]["b"]


def bert_for_classification(
    num_classes: int = 2,
    cfg: BertConfig = BERT_BASE,
    *,
    attention_fn: AttentionFn = dot_product_attention,
    remat: bool = False,
) -> L.Layer:
    """Full classification model: int ids (B, T) -> logits (B, C).
    `remat=True` checkpoints each encoder layer."""
    blocks = _encoder_blocks(cfg, attention_fn)
    if remat:
        blocks = [L.remat(b) for b in blocks]
    return staging.staged_model(
        _embeddings(cfg), blocks, _cls_head(cfg, num_classes)
    )


def bert_base(num_classes: int = 2) -> L.Layer:
    return bert_for_classification(num_classes, BERT_BASE)


def split_stages(
    num_stages: int,
    num_classes: int = 2,
    cfg: BertConfig = BERT_BASE,
    *,
    boundaries: Sequence[int] | None = None,
    attention_fn: AttentionFn = dot_product_attention,
) -> List[L.Layer]:
    """Pipeline stages: embeddings on stage 0, encoder layers distributed,
    pooler+classifier on the last stage (shared staging convention)."""
    blocks = _encoder_blocks(cfg, attention_fn)
    cuts = staging.split_points(num_stages, boundaries, len(blocks))
    return staging.assemble_stages(
        blocks, _embeddings(cfg), _cls_head(cfg, num_classes), cuts
    )


def partition_pytree(
    tree,
    num_stages: int,
    cfg: BertConfig = BERT_BASE,
    *,
    boundaries: Sequence[int] | None = None,
) -> List[dict]:
    cuts = staging.split_points(num_stages, boundaries, cfg.num_layers)
    return staging.partition_tree(tree, cuts)
