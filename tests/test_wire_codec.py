"""Quantized 'dcn' wire (`ops/wire_codec.py` + the compressed hops of
`ops/grad_reduction.py` / `ops/expert_dispatch.py`): codec unit tests
with explicit error bounds, the compressed cross-slice reduction
pinned against `lax.psum` at its documented budget, jaxpr-level dtype
pins on every hop, and engine-level parity sweeps — compression ×
{monolithic, bucketed, overlapped} against the f32 control on BOTH the
plain and the 2×(S/2) hybrid mesh, plus a 5-step trajectory test
quantifying drift. The f32 ("none") wire stays bit-identical to the
uncompressed lowering everywhere (rtol 1e-5 paths untouched); the
LOOSENED budgets apply only to the compressed hop:

    bf16  one rounding per hop              -> grads/trajectories at
                                               rtol 1e-2 (observed
                                               ~1e-5 on these models)
    int8  per-chunk absmax/254 per crossing -> elementwise
                                               <= (K+1)*absmax/254 per
                                               bucket (op level), and
                                               trajectories at rtol
                                               5e-2 (observed ~1e-4)
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn
from distributed_model_parallel_tpu.ops.grad_reduction import (
    bucket_pad_multiple,
    bucketed_pmean,
    compressed_dcn_psum,
)
from distributed_model_parallel_tpu.ops.wire_codec import (
    COMPRESSION_MODES,
    check_compression,
    wire_decode,
    wire_encode,
    wire_itemsize,
)
from distributed_model_parallel_tpu.parallel.data_parallel import DDPEngine
from distributed_model_parallel_tpu.runtime.compat import shard_map
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.optim import SGD

# Documented parity budgets for the COMPRESSED hop (module docstring;
# INTERNALS §12 carries the same numbers). f32 paths stay at 1e-5.
BF16_TRAJ_RTOL = 1e-2
INT8_TRAJ_RTOL = 5e-2


# ---------------------------------------------------------- codec units


def test_codec_surface():
    assert COMPRESSION_MODES == ("none", "bf16", "int8")
    assert [wire_itemsize(w) for w in COMPRESSION_MODES] == [4, 2, 1]
    assert check_compression("bf16") == "bf16"
    with pytest.raises(ValueError, match="dcn_compression"):
        check_compression("fp8")


@pytest.mark.parametrize("seed", [0, 3])
def test_int8_roundtrip_error_bound(seed):
    """|decode(encode(x)) - x| <= absmax/254 elementwise (round-half of
    one scale step) — the per-chunk bound every downstream budget
    derives from."""
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(257).astype(np.float32) * 3.0)
    payload, scale = wire_encode("int8", x)
    assert payload.dtype == jnp.int8 and scale.shape == ()
    dec = wire_decode("int8", payload, scale, x.dtype)
    bound = float(jnp.max(jnp.abs(x))) / 254.0 + 1e-7
    assert float(jnp.max(jnp.abs(dec - x))) <= bound


def test_bf16_roundtrip_error_bound():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(64).astype(np.float32))
    payload, scale = wire_encode("bf16", x)
    assert payload.dtype == jnp.bfloat16 and scale is None
    dec = wire_decode("bf16", payload, None, x.dtype)
    # one bf16 rounding: 2^-8 relative
    np.testing.assert_allclose(
        np.asarray(dec), np.asarray(x), rtol=2 ** -8, atol=1e-30
    )


def test_int8_zero_and_denormal_chunks():
    """All-zero chunks decode to EXACT zeros (the absmax floor guards
    the 0/127 scale); denormal-magnitude chunks stay finite and keep
    the relative bound."""
    z = jnp.zeros((16,), jnp.float32)
    payload, scale = wire_encode("int8", z)
    assert bool(jnp.all(payload == 0)) and bool(jnp.isfinite(scale))
    np.testing.assert_array_equal(
        np.asarray(wire_decode("int8", payload, scale, z.dtype)),
        np.zeros(16, np.float32),
    )
    # Tiny-but-NORMAL magnitudes keep the relative bound (the
    # ABSMAX_FLOOR keeps the derived scale a normal f32, so nothing
    # 0-divides or flushes in the codec itself).
    small = jnp.asarray(
        np.array([1e-35, -3e-35, 5e-36, 0.0], np.float32)
    )
    p, s = wire_encode("int8", small)
    dec = np.asarray(wire_decode("int8", p, s, small.dtype))
    assert np.all(np.isfinite(dec))
    assert np.max(np.abs(dec - np.asarray(small))) <= 3e-35 / 254 * 1.01
    # DENORMAL inputs are flushed by the backend before the codec sees
    # them (FTZ); the codec must stay finite and the error can never
    # exceed the largest denormal — f32's normal-min.
    den = jnp.asarray(np.array([1e-38, -1e-39, 0.0], np.float32))
    p, s = wire_encode("int8", den)
    dec = np.asarray(wire_decode("int8", p, s, den.dtype))
    assert np.all(np.isfinite(dec))
    assert np.max(np.abs(dec - np.asarray(den))) <= float(
        np.finfo(np.float32).tiny
    )


def test_int8_encode_preserves_bf16_chunk_dtype_roundtrip():
    x = jnp.asarray(np.linspace(-2, 2, 32), jnp.bfloat16)
    p, s = wire_encode("int8", x)
    dec = wire_decode("int8", p, s, x.dtype)
    assert dec.dtype == jnp.bfloat16


def test_bucket_pad_multiple():
    assert bucket_pad_multiple(4, 2, "none") == 4
    assert bucket_pad_multiple(4, 2, "int8") == 8
    assert bucket_pad_multiple(4, 1, "int8") == 4  # no dcn factor
    assert bucket_pad_multiple(2, 4, "bf16") == 8


# ------------------------------------------------ compressed dcn psum


@pytest.mark.parametrize("k", [2, 4])
def test_compressed_dcn_psum_matches_psum_within_bound(k, devices):
    """The wire-dtype RS+AG decomposition vs `lax.psum` over 'dcn':
    int8 within (K+1)*absmax/254 elementwise (one codec crossing per
    received chunk + one on the gather re-encode), bf16 within one
    rounding of the summed magnitude, f32 exact."""
    mesh = Mesh(np.array(devices[:k]), ("dcn",))
    n = 8 * k
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(k * n).astype(np.float32))

    def run(fn):
        m = shard_map(
            fn, mesh=mesh, in_specs=(P("dcn"),), out_specs=P("dcn"),
            check_vma=False,
        )
        return np.asarray(jax.jit(m)(x))

    mono = run(lambda v: lax.psum(v, "dcn"))
    # wire="none" moves the same f32 bytes through the decomposition:
    # equal up to reduction order (the repo's 1e-5 convention).
    exact = run(partial(compressed_dcn_psum, dcn_axis="dcn",
                        wire="none"))
    np.testing.assert_allclose(exact, mono, rtol=1e-5, atol=1e-6)
    absmax = float(np.max(np.abs(np.asarray(x))))
    int8 = run(partial(compressed_dcn_psum, dcn_axis="dcn",
                       wire="int8"))
    assert np.max(np.abs(int8 - mono)) <= (k + 1) * absmax / 254 + 1e-6
    bf16 = run(partial(compressed_dcn_psum, dcn_axis="dcn",
                       wire="bf16"))
    np.testing.assert_allclose(bf16, mono, rtol=BF16_TRAJ_RTOL,
                               atol=(k + 1) * absmax * 2 ** -8)


def _grad_tree(rng):
    """Mixed-dtype pytree with awkward (prime) sizes so every bucket
    has an uneven tail against the padded ring*dcn size."""
    return {
        "w1": jnp.asarray(rng.randn(13, 7), jnp.float32),
        "b1": jnp.asarray(rng.randn(7), jnp.float32),
        "w2": jnp.asarray(rng.randn(31, 3), jnp.bfloat16),
        "scalar": jnp.asarray(rng.randn(), jnp.float32),
        "w3": jnp.asarray(rng.randn(97), jnp.float32),
    }


@pytest.mark.parametrize("wire", ["bf16", "int8"])
def test_bucketed_pmean_compressed_uneven_tails(wire, devices):
    """Compressed `bucketed_pmean` == `lax.pmean` within the codec
    budget on the 2x4 hybrid mesh, mixed bf16/f32 leaves and uneven
    tails included (the tail zero-padding crosses the codec as zeros
    and must come back exact)."""
    mesh = Mesh(np.array(devices).reshape(2, 4), ("dcn", "ici"))
    trees = [_grad_tree(np.random.RandomState(i)) for i in range(8)]
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs).reshape((2, 4) + xs[0].shape), *trees
    )
    spec = jax.tree_util.tree_map(lambda _: P("dcn", "ici"), stacked)

    def run(fn):
        def body(t):
            sq = jax.tree_util.tree_map(
                lambda v: v.reshape(v.shape[2:]), t
            )
            out = fn(sq)
            return jax.tree_util.tree_map(
                lambda v: v.reshape((1, 1) + v.shape), out
            )

        m = shard_map(
            body, mesh=mesh, in_specs=(spec,), out_specs=spec,
            check_vma=False,
        )
        return jax.tree_util.tree_map(
            lambda v: np.asarray(v)[0, 0], jax.jit(m)(stacked)
        )

    mono = run(lambda t: lax.pmean(t, ("dcn", "ici")))
    got = run(lambda t: bucketed_pmean(
        t, "ici", "dcn", bucket_mb=0.0005, dcn_compression=wire
    ))
    # One budget for every leaf: the bf16 LEAVES' own rounding noise is
    # dominated by the wire codec's (int8 worst case), so the int8
    # bound covers both dtypes.
    for k in mono:
        np.testing.assert_allclose(
            np.asarray(mono[k], np.float32),
            np.asarray(got[k], np.float32),
            rtol=5e-2, atol=2e-2, err_msg=k,
        )


# --------------------------------------------------- jaxpr dtype pins


def test_every_dcn_hop_dtype_pinned_from_jaxpr(devices):
    """The static truth the hlolint rule reads, checked directly: in a
    compressed bucketed reduction every traced 'dcn'-crossing ppermute
    is a `dcn_wire` payload in the wire dtype or (int8) a one-scalar
    f32 `dcn_scale` sidecar; the intra-slice ring permutes stay in the
    math dtype."""
    from distributed_model_parallel_tpu.analysis.lint import (
        jaxpr_ppermute_records,
    )

    mesh = Mesh(np.array(devices).reshape(2, 4), ("dcn", "ici"))
    tree = {"w": jnp.zeros((64, 3), jnp.float32)}
    spec = jax.tree_util.tree_map(lambda _: P(), tree)

    for wire, tok in (("bf16", "bf16"), ("int8", "int8")):
        fn = jax.jit(shard_map(
            partial(bucketed_pmean, ici_axis="ici", dcn_axis="dcn",
                    bucket_mb=0.001, dcn_compression=wire),
            mesh=mesh, in_specs=(spec,), out_specs=spec,
            check_vma=False,
        ))
        recs = jaxpr_ppermute_records(fn, tree)
        dcn = [r for r in recs if "dcn" in r[0]]
        ici = [r for r in recs if "ici" in r[0]]
        assert dcn and ici
        for axes, dt, scope, elems in dcn:
            if "dcn_scale" in scope:
                assert (dt, elems) == ("f32", 1)
            else:
                assert "dcn_wire" in scope
                assert dt == ("s8" if wire == "int8" else "bf16")
        assert all(dt == "f32" for _, dt, _, _ in ici)
        n_scale = sum("dcn_scale" in r[2] for r in dcn)
        n_wire = sum("dcn_scale" not in r[2] for r in dcn)
        assert n_scale == (n_wire if wire == "int8" else 0)


def test_moe_dcn_hops_dtype_pinned_from_jaxpr(devices):
    """Same pin on the MoE exchange, INCLUDING the mirrored backward:
    trace grad of the exchanged FFN and assert every dcn-crossing hop
    rides the wire (the custom_vjp keeps cotangents compressed too),
    while the ici regroup stays f32."""
    from distributed_model_parallel_tpu.analysis.lint import (
        jaxpr_ppermute_records,
    )
    from distributed_model_parallel_tpu.models.moe import expert_ffn
    from distributed_model_parallel_tpu.ops.expert_dispatch import (
        exchanged_expert_ffn,
    )

    mesh = Mesh(np.array(devices).reshape(2, 4), ("dcn", "ici"))
    E, D = 8, 8
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(E, 8, 2, D).astype(np.float32))
    w = {
        "w_in": jnp.asarray(rng.randn(E, D, D).astype(np.float32)),
        "b_in": jnp.zeros((E, D), jnp.float32),
        "w_out": jnp.asarray(rng.randn(E, D, D).astype(np.float32)),
        "b_out": jnp.zeros((E, D), jnp.float32),
    }
    dd = ("dcn", "ici")
    wspec = {k: P(dd, *([None] * (v.ndim - 1))) for k, v in w.items()}

    for overlap in (False, True):
        def loss(x, w, overlap=overlap):
            def local(xl, wl):
                return exchanged_expert_ffn(
                    xl, partial(expert_ffn, wl), "ici", "dcn",
                    overlap, "int8",
                )

            y = shard_map(
                local, mesh=mesh,
                in_specs=(P(None, dd, None, None), wspec),
                out_specs=P(None, dd, None, None), check_vma=False,
            )(x, w)
            return jnp.sum(y * y)

        recs = jaxpr_ppermute_records(jax.grad(loss), x, w)
        dcn = [r for r in recs if "dcn" in r[0]]
        assert dcn, "no dcn hops traced"
        for axes, dt, scope, elems in dcn:
            if "dcn_scale" in scope:
                assert (dt, elems) == ("f32", 1)
            else:
                assert "dcn_wire" in scope and "moe_ring" in scope
                assert dt == "s8"
        assert all(
            dt == "f32" for axes, dt, _, _ in recs if "ici" in axes
        )


# ---------------------------------------------- engine parity sweeps


def _batch():
    rng = np.random.RandomState(7)
    return (
        rng.rand(16, 8, 8, 3).astype(np.float32),
        rng.randint(0, 10, size=(16,)).astype(np.int32),
    )


def _run(eng, n_steps=3, lr=0.05):
    ts = eng.init_state(jax.random.PRNGKey(0))
    x, y = eng.shard_batch(*_batch())
    traj = []
    for _ in range(n_steps):
        ts, m = eng.train_step(ts, x, y, jnp.float32(lr))
        traj.append(float(m["loss_sum"]))
    return ts, traj


def _budget(wire):
    return BF16_TRAJ_RTOL if wire == "bf16" else INT8_TRAJ_RTOL


# Tier-1 keeps the int8 hybrid smoke of each (mode) — the deepest
# codec path (sidecars + re-chunked padding); the bf16 twin rides the
# slow sweep (same assertions, looser codec).
_WIRE_SWEEP = [
    pytest.param("bf16", marks=pytest.mark.slow),
    "int8",
]

# The sp-lm engine sweep rides slow entirely (tier-1 budget): the
# codec x {mode} matrix is already pinned in tier-1 by
# test_ddp_compressed_matches_f32_all_modes[int8] (same bucketing and
# wire machinery after the 'seq' psum), and the five-step drift test
# keeps an int8 e2e trajectory in tier-1.
_WIRE_SWEEP_SLOW = [
    pytest.param("bf16", marks=pytest.mark.slow),
    pytest.param("int8", marks=pytest.mark.slow),
]


@pytest.mark.parametrize("wire", _WIRE_SWEEP)
def test_ddp_compressed_matches_f32_all_modes(wire, devices):
    """compression x {monolithic, bucketed, overlapped} on the 2x4
    hybrid mesh vs the f32 control on BOTH the plain and hybrid mesh:
    trajectories within the documented wire budget, and the two f32
    controls agree at rtol 1e-5 (the compressed hop is the ONLY
    loosened link)."""
    plain = make_mesh(MeshSpec(data=8))
    hybrid = make_mesh(MeshSpec(data=8, dcn=2))
    _, base_plain = _run(DDPEngine(
        tiny_cnn(10), SGD(), plain, donate=False
    ))
    _, base_hybrid = _run(DDPEngine(
        tiny_cnn(10), SGD(), hybrid, donate=False
    ))
    np.testing.assert_allclose(base_hybrid, base_plain, rtol=1e-5)
    for gr in ("monolithic", "bucketed", "overlapped"):
        _, traj = _run(DDPEngine(
            tiny_cnn(10), SGD(), hybrid, donate=False,
            grad_reduction=gr, bucket_mb=0.02, dcn_compression=wire,
        ))
        np.testing.assert_allclose(
            traj, base_plain, rtol=_budget(wire),
            err_msg=f"{gr}/{wire}",
        )
        assert traj[-1] < traj[0], f"{gr}/{wire} did not descend"


def test_fsdp_coded_gather_layout_matches_fused(devices):
    """`parallel/fsdp._coded_dcn_gather` (ISSUE 16 satellite): the
    hierarchical weight gather — ici all-gather + K-1 coded dcn ring
    hops placed by source-slice index — reproduces the fused
    `all_gather(('dcn', 'ici'), tiled=True)` layout BIT-EXACTLY with
    the identity codec, and within one codec crossing per element for
    the real wires (multi-hop re-encoding is idempotent, fsdp.py
    docstring), so `slice_tree`'s replica-index arithmetic and the 1/N
    checkpoints see the same byte order either way."""
    from distributed_model_parallel_tpu.parallel.fsdp import (
        _coded_dcn_gather,
    )

    mesh = make_mesh(MeshSpec(data=8, dcn=2))
    x = np.random.RandomState(0).randn(16, 6).astype(np.float32)

    def fused(leaf):
        return lax.all_gather(leaf, ("dcn", "ici"), axis=0, tiled=True)

    ref = np.asarray(jax.jit(shard_map(
        fused, mesh=mesh, in_specs=P(("dcn", "ici")),
        out_specs=P(None), check_vma=False,
    ))(x))
    np.testing.assert_array_equal(ref, x)  # fused gather = the array
    for wire, tol in (("none", 0.0), ("bf16", 4e-3), ("int8", 1e-2)):
        def coded(leaf, wire=wire):
            return _coded_dcn_gather(leaf, 0, "ici", "dcn", 2, wire)

        got = np.asarray(jax.jit(shard_map(
            coded, mesh=mesh, in_specs=P(("dcn", "ici")),
            out_specs=P(None), check_vma=False,
        ))(x))
        if wire == "none":
            np.testing.assert_array_equal(got, ref)
        else:
            # absmax here is ~3 (unit normals): one absmax/254 crossing.
            assert np.abs(got - ref).max() <= tol, wire


def test_fsdp_compressed_gather_hops_ride_the_wire(devices):
    """Trace-level pin for the compressed WEIGHT gather: an opted-in
    FSDP step's dcn-crossing gather traffic is exactly the
    fsdp_gather-scoped coded ring hops — (K-1) hops of full_leaf/K
    elems in the wire dtype per dcn-crossing leaf — and no unscoped
    f32 ppermute or fused gather crosses 'dcn' (the full-matrix combos
    pin the same contract through hlolint's dcn-compressed-payload;
    this is the fast unit-level twin)."""
    from collections import Counter

    from distributed_model_parallel_tpu.analysis.lint import (
        jaxpr_ppermute_records,
    )
    from distributed_model_parallel_tpu.parallel.fsdp import FSDPEngine

    mesh = make_mesh(MeshSpec(data=8, dcn=2))
    eng = FSDPEngine(
        tiny_cnn(10), SGD(), mesh, donate=False, min_shard_elems=64,
        grad_reduction="monolithic", dcn_compression="int8",
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    x, y = eng.shard_batch(*_batch())
    records = jaxpr_ppermute_records(
        eng.train_step, ts, x, y, jnp.float32(0.05)
    )
    gather = Counter(
        (elems, dt) for axes, dt, scope, elems in records
        if "dcn" in axes and "fsdp_gather" in scope
        and "dcn_wire" in scope
    )
    # tiny_cnn(10) at min_shard_elems=64 on an 8-way data world: the
    # dcn-crossing leaves are the two conv kernels and the dense
    # weight; each contributes K-1 = 1 hop of full/K elems.
    expected = Counter()
    for leaf, spec in zip(
        jax.tree_util.tree_leaves(ts.params),
        jax.tree_util.tree_leaves(
            eng.param_specs(ts.params),
            is_leaf=lambda s: isinstance(s, P),
        ),
    ):
        if any(part is not None for part in spec):
            expected[(leaf.size // 2, "s8")] += 1
    assert sum(expected.values()) >= 2  # the pin is non-trivial
    assert gather == expected
    # Every dcn-crossing ppermute is coded: payload or sidecar scoped.
    for axes, dt, scope, elems in records:
        if "dcn" in axes:
            assert "dcn_wire" in scope or "dcn_scale" in scope, scope


@pytest.mark.parametrize("wire", _WIRE_SWEEP_SLOW)
def test_fsdp_compressed_matches_f32_and_stays_sharded(wire, devices):
    """FSDP: monolithic (single-flat-bucket explicit step) + bucketed +
    overlapped with a compressed wire — trajectory within budget AND
    the 1/N at-rest sharding of params + moments preserved. Since
    ISSUE 16 the WEIGHT gathers ride the codec too (every forward sees
    one codec crossing per cross-slice weight block), so this budget
    now covers both compressed legs. `slow` (tier-1 budget); tier-1
    twins: test_ddp_compressed_matches_f32_all_modes[int8] (same
    bucketing + wire machinery), test_fsdp_coded_gather_layout_matches_fused
    + test_fsdp_compressed_gather_hops_ride_the_wire (the fsdp-specific
    coded gather leg and its hop multiset)."""
    from distributed_model_parallel_tpu.parallel.fsdp import FSDPEngine
    from distributed_model_parallel_tpu.training.optim import AdamW

    hybrid = make_mesh(MeshSpec(data=8, dcn=2))
    _, base = _run(FSDPEngine(
        tiny_cnn(10), AdamW(), hybrid, donate=False,
        min_shard_elems=64,
    ), lr=1e-3)
    for gr in ("monolithic", "bucketed", "overlapped"):
        ts, traj = _run(FSDPEngine(
            tiny_cnn(10), AdamW(), hybrid, donate=False,
            min_shard_elems=64, grad_reduction=gr, bucket_mb=0.02,
            dcn_compression=wire,
        ), lr=1e-3)
        np.testing.assert_allclose(
            traj, base, rtol=_budget(wire), err_msg=f"{gr}/{wire}"
        )
        big = max(
            jax.tree_util.tree_leaves(ts.params), key=lambda l: l.size
        )
        assert np.prod(big.addressable_shards[0].data.shape) == (
            big.size // 8
        )
        mu = max(
            jax.tree_util.tree_leaves(ts.opt_state.mu),
            key=lambda l: l.size,
        )
        assert np.prod(mu.addressable_shards[0].data.shape) == (
            mu.size // 8
        )


@pytest.mark.parametrize("wire", _WIRE_SWEEP_SLOW)
def test_causal_lm_sp_compressed_matches_f32(wire, devices):
    """The lm CLI's engine: compressed data buckets (after the 'seq'
    psum) across all three reduction modes vs the f32 monolithic
    control, within budget. `slow` (tier-1 budget); tier-1 twin:
    test_ddp_compressed_matches_f32_all_modes[int8] (same codec and
    bucketing machinery on the ddp engine)."""
    from distributed_model_parallel_tpu.models.gpt import GPTConfig
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        CausalLMSequenceParallelEngine,
    )
    from distributed_model_parallel_tpu.training.optim import AdamW

    cfg = GPTConfig(
        vocab_size=64, dim=32, num_layers=2, num_heads=4, ffn_dim=64,
        max_position=32, dropout_rate=0.0, pad_token_id=0,
    )
    ids = np.random.RandomState(0).randint(
        1, 64, size=(8, 32)
    ).astype(np.int32)
    mesh = make_mesh(MeshSpec(data=4, seq=2, dcn=2))

    def run(eng):
        ts = eng.init_state(jax.random.PRNGKey(0))
        a, b = eng.shard_batch(ids)
        traj = []
        for _ in range(3):
            ts, m = eng.train_step(ts, a, b, jnp.float32(1e-3))
            traj.append(float(m["loss_sum"]))
        return traj

    base = run(CausalLMSequenceParallelEngine(
        cfg, AdamW(), mesh, donate=False
    ))
    for gr in ("monolithic", "bucketed", "overlapped"):
        traj = run(CausalLMSequenceParallelEngine(
            cfg, AdamW(), mesh, donate=False, grad_reduction=gr,
            bucket_mb=0.02, dcn_compression=wire,
        ))
        np.testing.assert_allclose(
            traj, base, rtol=_budget(wire), err_msg=f"{gr}/{wire}"
        )


@pytest.mark.parametrize("wire", _WIRE_SWEEP_SLOW)
def test_ep_compressed_dispatch_matches_f32(wire, devices):
    """Compressed hierarchical MoE dispatch (unfused + overlapped) vs
    the f32 hierarchical control on the 2x4 hybrid fabric: the
    activations cross the codec here, so the budget is the wire's, and
    unfused == overlapped EXACTLY (same codec applications). `slow`
    (tier-1 budget); tier-1 twins:
    test_moe_dcn_hops_dtype_pinned_from_jaxpr (every dispatch dcn hop's
    wire dtype) + test_ddp_compressed_matches_f32_all_modes[int8] (the
    codec numerics on the grad path)."""
    from distributed_model_parallel_tpu.analysis.lint import (
        moe_classifier,
    )
    from distributed_model_parallel_tpu.parallel.expert_parallel import (
        ExpertParallelEngine,
    )

    model = moe_classifier(8, dim=16)
    mesh = make_mesh(MeshSpec(data=8, dcn=2))

    def run(eng):
        rr = np.random.RandomState(0)
        labels = rr.randint(0, 4, size=(8,)).astype(np.int32)
        means = np.random.RandomState(99).randn(4, 16).astype(
            np.float32
        )
        x = rr.randn(8, 8, 16).astype(np.float32) * 0.5 \
            + means[labels][:, None]
        ts = eng.init_state(jax.random.PRNGKey(0))
        xs, lbs = eng.shard_batch(x, labels)
        traj = []
        for _ in range(3):
            ts, m = eng.train_step(ts, xs, lbs, jnp.float32(0.05))
            traj.append(float(m["loss_sum"]) / float(m["count"]))
        return traj

    base = run(ExpertParallelEngine(
        model, SGD(), mesh, donate=False, dispatch="hierarchical"
    ))
    trajs = {}
    for overlap in (False, True):
        trajs[overlap] = run(ExpertParallelEngine(
            model, SGD(), mesh, donate=False, dispatch="hierarchical",
            overlap=overlap, dcn_compression=wire,
        ))
        np.testing.assert_allclose(
            trajs[overlap], base, rtol=_budget(wire)
        )
        assert trajs[overlap][-1] < trajs[overlap][0]
    np.testing.assert_array_equal(trajs[False], trajs[True])


def test_ddp_compressed_five_step_trajectory_drift(devices):
    """The drift quantification the ISSUE asks for: 5 steps of DDP on
    the hybrid mesh, f32 vs bf16 vs int8 wires. Drift (max relative
    loss deviation from f32) must stay inside the documented budgets,
    both compressed runs must still descend, and bf16 must not drift
    MORE than the documented int8 ceiling (the codecs stay ordered by
    their bounds)."""
    hybrid = make_mesh(MeshSpec(data=8, dcn=2))

    def run(wire):
        eng = DDPEngine(
            tiny_cnn(10), SGD(), hybrid, donate=False,
            grad_reduction="bucketed", bucket_mb=0.02,
            dcn_compression=wire,
        )
        return _run(eng, n_steps=5)[1]

    base = run("none")
    drift = {}
    for wire in ("bf16", "int8"):
        traj = run(wire)
        drift[wire] = max(
            abs(a - b) / abs(b) for a, b in zip(traj, base)
        )
        assert traj[-1] < traj[0], f"{wire} run did not descend"
    assert drift["bf16"] <= BF16_TRAJ_RTOL, drift
    assert drift["int8"] <= INT8_TRAJ_RTOL, drift


# -------------------------------------------------------------- guards


def test_engine_guards(devices):
    """Misuse fails at construction, not an epoch in: compression on a
    mesh with no 'dcn' axis (every engine), on the gspmd EP dispatch,
    and unknown codec names."""
    from distributed_model_parallel_tpu.analysis.lint import (
        moe_classifier,
    )
    from distributed_model_parallel_tpu.models.gpt import GPTConfig
    from distributed_model_parallel_tpu.parallel.expert_parallel import (
        ExpertParallelEngine,
    )
    from distributed_model_parallel_tpu.parallel.fsdp import FSDPEngine
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        CausalLMSequenceParallelEngine,
    )

    plain = make_mesh(MeshSpec(data=8))
    with pytest.raises(ValueError, match="dcn"):
        DDPEngine(tiny_cnn(10), SGD(), plain, dcn_compression="int8")
    with pytest.raises(ValueError, match="dcn"):
        FSDPEngine(tiny_cnn(10), SGD(), plain, dcn_compression="bf16")
    cfg = GPTConfig(
        vocab_size=64, dim=16, num_layers=2, num_heads=2, ffn_dim=32,
        max_position=16, dropout_rate=0.0, pad_token_id=0,
    )
    with pytest.raises(ValueError, match="dcn"):
        CausalLMSequenceParallelEngine(
            cfg, SGD(), make_mesh(MeshSpec(data=4, seq=2)),
            dcn_compression="int8",
        )
    with pytest.raises(ValueError, match="dcn"):
        ExpertParallelEngine(
            moe_classifier(8, dim=16), SGD(), plain,
            dispatch="hierarchical", dcn_compression="int8",
        )
    with pytest.raises(ValueError, match="hierarchical"):
        ExpertParallelEngine(
            moe_classifier(8, dim=16), SGD(),
            make_mesh(MeshSpec(data=8, dcn=2)),
            dcn_compression="bf16",  # gspmd dispatch: no dcn seam
        )
    with pytest.raises(ValueError, match="dcn_compression"):
        DDPEngine(
            tiny_cnn(10), SGD(), make_mesh(MeshSpec(data=8, dcn=2)),
            dcn_compression="fp8",
        )


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-v"]))
