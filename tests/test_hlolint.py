"""Engine-matrix lint gate: every supported engine x mode combo lowers
on a virtual mesh and must pass the collective-contract registry
(`analysis/lint.py`) — a future engine change that breaks a contract
fails here with a NAMED rule, not as a silent perf regression.

Tier-1 runs a representative subset (one combo per rule family:
overlapped rings + BN allowlist, hybrid dcn pins, ZeRO overlap deps,
bf16 cm rings, op-level S-1 kernels); the full S in {2,4,8} x mode x
hybrid matrix — the `tools/hlolint` default — is the slow sweep."""

import json

import pytest

from distributed_model_parallel_tpu.analysis.lint import (
    Combo,
    full_matrix,
    lint_combo,
    pregate_matrix,
    run,
)

# One combo per rule family — the tier-1 cut of the matrix.
TIER1_COMBOS = [
    # rings + overlap deps + BatchNorm state allowlist (the pre-gate
    # twin: tools/tier1.sh lints this exact combo before the suite)
    Combo("ddp", 8, grad_reduction="overlapped", model="tinycnn"),
    # ZeRO overlap: prefetch-gather freedom + at-rest sharding
    Combo("fsdp", 4, grad_reduction="overlapped"),
    # hybrid fabric: per-bucket dcn shard pins
    Combo("ddp", 4, grad_reduction="bucketed", dcn=2),
    # opted-in rings under mixed precision (jaxpr dtype contract)
    Combo("tp", 4, collective_matmul=True, bf16=True),
    # op-level exact S-1 kernels
    Combo("cm_ag", 4),
    Combo("cm_rs", 4),
    # serving decode rings: exact tagged 4L(S-1) chain, no monolithic
    # all-gather on the opted-in step (serve-decode-ring)
    Combo("serve", 2, collective_matmul=True),
    # the PAGED decode step must carry the identical inventory —
    # block-table gathers are local ops, never collectives (ISSUE 15)
    Combo("serve", 2, page_size=8, collective_matmul=True),
    # hierarchical MoE exchange on a hybrid fabric: exact moe_ring
    # chain + zero flat all-to-all (moe-hierarchical-a2a); the pre-gate
    # twin (tools/tier1.sh lints this exact combo before the suite)
    Combo("ep", 4, dcn=2, moe_dispatch="hierarchical",
          moe_overlap=True),
    # quantized 'dcn' wire (dcn-compressed-payload): int8 grad buckets
    # with scale sidecars (the pre-gate twin) + the bf16 compressed
    # MoE dispatch
    Combo("ddp", 4, grad_reduction="bucketed", dcn=2,
          dcn_compression="int8", model="tinycnn"),
    Combo("ep", 4, dcn=2, moe_dispatch="hierarchical",
          dcn_compression="bf16"),
    # quantized decode floor (decode-quantized-matmul): every decode
    # projection dot is s8 x s8 inside the cm rings, head stays f32
    # (the pre-gate twin)
    Combo("serve", 2, collective_matmul=True, compute_dtype="int8"),
    # speculative verify step (spec-verify-step): the one-pass k+1
    # verify carries exactly ONE decode step's tagged ring inventory,
    # no monolithic gather (the pre-gate twin, ISSUE 18)
    Combo("serve", 2, page_size=8, collective_matmul=True,
          speculative_k=2),
]


def _assert_clean(rep):
    assert rep.errors == [], (
        f"{rep.combo.name}: "
        + "; ".join(f"{f.rule}: {f.message}" for f in rep.errors)
    )


@pytest.mark.parametrize(
    "combo", TIER1_COMBOS, ids=lambda c: c.name.replace("/", "-")
)
def test_tier1_matrix_combo_lints_clean(combo):
    _assert_clean(lint_combo(combo))


@pytest.mark.slow
@pytest.mark.parametrize(
    "combo",
    [c for c in full_matrix() if c not in TIER1_COMBOS],
    ids=lambda c: c.name.replace("/", "-"),
)
def test_full_matrix_combo_lints_clean(combo):
    """Full engine x mode x mesh sweep (S in {2,4,8} + 2x(S/2) hybrids
    + bf16 + cm on/off) — the `tools/hlolint` default matrix. Tier-1
    twin: test_tier1_matrix_combo_lints_clean keeps one combo per rule
    family in the default run."""
    _assert_clean(lint_combo(combo))


def test_pregate_matrix_is_a_subset_of_the_full_matrix():
    full = {c.name for c in full_matrix()}
    for c in pregate_matrix():
        assert c.name in full


def test_runner_emits_partial_json_per_combo():
    """The established partial-JSON convention: one {"leg": ...,
    "partial": true} line per finished combo, then a final summary
    object with the violation/rule counts (the bench dryrun's hlo_lint
    leg consumes the same API)."""
    lines = []
    summary = run([Combo("cm_ag", 2)], emit=lines.append)
    parsed = [json.loads(x) for x in lines if x.startswith("{")]
    legs = [p for p in parsed if p.get("partial")]
    assert len(legs) == 1
    assert legs[0]["leg"]["name"] == "cm_ag/S2"
    assert legs[0]["leg"]["violations"] == 0
    final = [p for p in parsed if "hlo_lint" in p]
    assert len(final) == 1
    assert final[0]["hlo_lint"] == summary["hlo_lint"]
    assert summary["hlo_lint"]["errors"] == 0
    assert summary["hlo_lint"]["rules"] >= 8


def test_lowering_failure_counts_as_an_error():
    """A combo that fails to LOWER must drive a nonzero error count
    (and thus the CLI's exit status) — an engine regression that
    crashes lowering may not sail through the gates as 'no findings'."""
    lines = []
    summary = run([Combo("no-such-engine", 2)], emit=lines.append)
    assert summary["hlo_lint"]["lowered"] == 0
    assert summary["hlo_lint"]["errors"] == 1
    assert summary["hlo_lint"]["failed_targets"] == ["no-such-engine/S2"]
    legs = [json.loads(x) for x in lines if x.startswith("{")]
    assert any("error" in p.get("leg", {}) for p in legs
               if isinstance(p.get("leg"), dict))


def test_cli_list_rules_runs_without_backend(capsys):
    from distributed_model_parallel_tpu.analysis.lint import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "bucket-ring-permutes" in out
    assert "error" in out
