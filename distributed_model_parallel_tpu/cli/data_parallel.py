"""Data-parallel CIFAR-10 training — the reference's `data_parallel.py`
entry point, TPU-native.

Reference surface (`code/distributed_training/data_parallel.py`):
  argparse `--lr` (default 0.4) and `--resume/-r` (`:19-23`); CIFAR-10
  batch 512 train / 1000 test (`:43-51`); MobileNetV2 wrapped in
  `torch.nn.DataParallel` (`:74-78`); SGD(momentum .9, wd 1e-4) +
  CosineAnnealingLR(T_max=90) + LinearWarmup(10) (`:90-96`); 100 epochs
  with best-acc checkpointing and a txt log (`:160-172`).

Here the DataParallel wrapper is a mesh: batch sharded over 'data', params
replicated, gradients all-reduced by XLA — no scatter/replicate/
parallel_apply/gather and no device-0 bottleneck. Run it:

  python -m distributed_model_parallel_tpu.cli.data_parallel --lr 0.4
  python -m distributed_model_parallel_tpu.cli.data_parallel --resume
  python -m distributed_model_parallel_tpu.cli.data_parallel \
      --dataset-type Synthetic --epochs 2 --engine ddp --sync-bn
"""

from __future__ import annotations

import argparse

import jax

from distributed_model_parallel_tpu.cli.common import (
    add_checkpoint_flags,
    add_common_tpu_flags,
    add_grad_reduction_flags,
    build_loaders,
    build_model,
    build_optimizer,
    check_batch_divisibility,
    check_checkpoint_args,
    check_grad_reduction_args,
    compute_dtype_from_flag,
)
from distributed_model_parallel_tpu.parallel.data_parallel import (
    DataParallelEngine,
    DDPEngine,
)
from distributed_model_parallel_tpu.runtime.dist import initialize_backend
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.trainer import (
    Trainer,
    TrainerConfig,
)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="TPU CIFAR10 Training")
    # -- the reference's exact flags (`data_parallel.py:19-23`) ----------
    parser.add_argument("--lr", default=0.4, type=float, help="learning rate")
    parser.add_argument("--resume", "-r", action="store_true",
                        help="resume from checkpoint")
    parser.add_argument("--finetune", default=None, metavar="CKPT",
                        help="transplant torch MobileNetV2 weights "
                             "(reference checkpoint format, .pth/.npz; "
                             "module.* prefixes and the {'net': ...} "
                             "wrapper handled) before training — the "
                             "reference's finetune path (Readme.md:200-205)")
    # -- reference hard-codes surfaced as flags --------------------------
    parser.add_argument("-b", "--batch-size", default=512, type=int,
                        help="global batch size (reference: 512)")
    parser.add_argument("--val-batch-size", default=1000, type=int)
    parser.add_argument("--epochs", default=100, type=int)
    parser.add_argument("-type", "--dataset-type", default="CIFAR10",
                        dest="dataset_type")
    parser.add_argument("--data", default="./data", help="dataset path")
    parser.add_argument("--wd", "--weight-decay", default=1e-4, type=float,
                        dest="weight_decay")
    parser.add_argument("--momentum", default=0.9, type=float)
    parser.add_argument("-j", "--workers", default=1, type=int,
                        help="native augmentation thread-pool size")
    # -- TPU-native additions --------------------------------------------
    parser.add_argument("--engine", default="gspmd",
                        choices=("gspmd", "ddp", "fsdp", "tp"),
                        help="gspmd: compiler-partitioned (nn.DataParallel "
                             "equivalent); ddp: explicit shard_map psum "
                             "(DistributedDataParallel equivalent); fsdp: "
                             "params+optimizer sharded 1/N over 'data' "
                             "(ZeRO-3 equivalent); tp: Megatron tensor "
                             "parallelism over a 'model' axis "
                             "(--model-shards; transformer-family models)")
    parser.add_argument("--model-shards", default=1, type=int,
                        help="'model' mesh axis size under --engine tp "
                             "(remaining devices become data-parallel "
                             "replicas)")
    parser.add_argument("--collective-matmul", action="store_true",
                        help="latency-hiding collective matmul under "
                             "--engine tp: run the Megatron projections "
                             "as chunked ppermute rings that overlap "
                             "each ICI hop with the partial dot instead "
                             "of the partitioner's monolithic "
                             "all-gather/reduce-scatter (same math; "
                             "transformer-family models)")
    parser.add_argument("--plan", default=None, metavar="SPEC",
                        help="degenerate ParallelPlan spec for the "
                             "image engines (dpN / fsdpN, ISSUE 19): "
                             "the declarative spelling of --engine "
                             "ddp/fsdp on an N-way data world; "
                             "pp/sp/ep tokens are the LM CLI's "
                             "surface (cli/lm.py --plan)")
    add_grad_reduction_flags(parser)
    add_checkpoint_flags(parser)
    from distributed_model_parallel_tpu.tuning.apply import (
        add_auto_tune_flags,
    )

    add_auto_tune_flags(parser)
    parser.add_argument("--max-restarts", default=0, type=int,
                        help="fail-fast elastic mode: restart from the "
                             "per-epoch checkpoint up to N times on "
                             "failure (0 = off)")
    parser.add_argument("--sync-bn", action="store_true",
                        help="SyncBatchNorm semantics under --engine ddp")
    parser.add_argument("--device-normalize", action="store_true",
                        help="ship uint8 batches and normalize on device "
                             "(4x fewer host->device bytes; same math)")
    parser.add_argument("--device-cache", action="store_true",
                        help="upload the whole dataset to HBM once and "
                             "ship only per-batch INDEX vectors (~2 KB); "
                             "gather+augment+normalize run inside the "
                             "compiled step. For HBM-sized datasets "
                             "(CIFAR); the end-to-end fast path on a "
                             "bandwidth-limited host link")
    add_common_tpu_flags(parser)
    return parser


def main(argv=None) -> dict:
    args = build_parser().parse_args(argv)
    if args.finetune:
        # Fail fast (before datasets/engine/trainer build): typo'd paths
        # or unsupported model families should not cost a download first.
        import os

        if args.resume:
            raise SystemExit(
                "--finetune conflicts with --resume: resume restores the "
                "full training state; drop one of the flags"
            )
        if args.model != "mobilenetv2":
            raise SystemExit(
                "--finetune supports the BN MobileNetV2 ('mobilenetv2'); "
                f"got --model {args.model}"
            )
        if not os.path.exists(args.finetune):
            raise SystemExit(f"--finetune: no such file {args.finetune!r}")
    if args.auto_tune:
        # BEFORE the knob guards below: the tuner writes the chosen
        # knobs onto args and an inconsistent plan must still hit
        # every existing fail-fast check. Needs the device world, so
        # the (idempotent) backend init moves up.
        from distributed_model_parallel_tpu.tuning.apply import (
            auto_tune_data_parallel,
        )

        initialize_backend()
        auto_tune_data_parallel(args)
    _plan = None
    if args.plan:
        from distributed_model_parallel_tpu.parallel.plan import (
            parse_plan,
        )

        try:
            _plan = parse_plan(args.plan)
        except ValueError as e:
            raise SystemExit(f"--plan: {e}") from e
        if _plan.pp > 1 or _plan.tp_or_sp > 1 or _plan.ep > 1:
            raise SystemExit(
                f"--plan {_plan.spec}: the image engines run the "
                "data axis only — the plan's pp/sp/ep fields are the "
                "LM CLI's surface (cli/lm.py --plan)"
            )
        want = "fsdp" if _plan.fsdp else "ddp"
        if args.engine not in ("gspmd", want):
            raise SystemExit(
                f"--plan {_plan.spec} spells --engine {want} (plan "
                f"field {'fsdp' if _plan.fsdp else 'dp'}); it "
                f"conflicts with --engine {args.engine} — drop one"
            )
        args.engine = want
    check_grad_reduction_args(args)
    check_checkpoint_args(args)
    from distributed_model_parallel_tpu.cli.common import (
        setup_metrics_out,
    )

    setup_metrics_out(args.metrics_out)  # fail fast on a bad directory
    if args.grad_reduction != "monolithic" and args.engine not in (
        "ddp", "fsdp"
    ):
        raise SystemExit(
            f"--grad-reduction {args.grad_reduction} replaces the "
            "explicit gradient collective of the shard_map engines "
            f"(ddp, fsdp); the declarative --engine {args.engine} step "
            "has no explicit reduction site to bucket or overlap"
        )
    if args.dcn_compression != "none" and args.engine not in (
        "ddp", "fsdp"
    ):
        raise SystemExit(
            "--dcn-compression compresses the explicit cross-slice "
            "gradient hop of the shard_map engines (ddp, fsdp); the "
            f"declarative --engine {args.engine} step has no explicit "
            "'dcn' hop to compress — switch to --engine ddp/fsdp or "
            "drop the flag"
        )
    if args.grad_reduction == "overlapped":
        from distributed_model_parallel_tpu.cli.common import (
            check_overlapped_model,
        )

        check_overlapped_model(args.model, args.overlap_stages)
    if args.engine == "tp" and args.dcn_slices != 1:
        raise SystemExit(
            "--dcn-slices factors the data axis for the hierarchical "
            "reducer; combine it with --engine gspmd/ddp/fsdp, not tp"
        )
    if args.engine != "tp":
        if args.model_shards != 1:
            raise SystemExit(
                "--model-shards sizes the 'model' mesh axis and only "
                "applies under --engine tp"
            )
        if args.collective_matmul:
            raise SystemExit(
                "--collective-matmul decomposes the Megatron TP "
                "projections; it only applies under --engine tp"
            )
    if args.engine == "tp":
        from distributed_model_parallel_tpu.cli.common import (
            TRANSFORMER_MODELS,
        )

        if args.model not in TRANSFORMER_MODELS:
            # MEGATRON_RULES match transformer projection paths only; a
            # CNN under --engine tp would replicate every weight and do
            # redundant compute on the 'model' axis without an error.
            raise SystemExit(
                "--engine tp shards the Megatron projection layers; "
                f"--model {args.model} has none, so every weight would "
                "silently replicate across the 'model' axis (redundant "
                f"compute). Choose one of {', '.join(TRANSFORMER_MODELS)}."
            )
        if args.model_shards < 1:
            raise SystemExit(
                f"--model-shards must be >= 1, got {args.model_shards}"
            )
        if args.collective_matmul and args.model_shards < 2:
            raise SystemExit(
                "--collective-matmul rings over the 'model' axis; a "
                "size-1 ring is a plain dot, so the flag would silently "
                "do nothing — set --model-shards >= 2"
            )
    initialize_backend()
    if _plan is not None and _plan.num_devices != jax.device_count():
        raise SystemExit(
            f"--plan {_plan.spec} factors {_plan.num_devices} "
            f"device(s); this world has {jax.device_count()} — "
            "respell the plan's data axis"
        )
    if args.engine == "tp":
        mesh = make_mesh(MeshSpec(data=-1, model=args.model_shards))
    else:
        mesh = make_mesh(MeshSpec(data=-1, dcn=args.dcn_slices))
    check_batch_divisibility(args.batch_size, mesh)
    check_batch_divisibility(args.val_batch_size, mesh, label="val batch")
    if args.dataset_type == "SyntheticText" and (
        args.device_cache or args.device_normalize
    ):
        raise SystemExit(
            "--device-cache/--device-normalize apply the image "
            "normalize pipeline; token-id datasets ship raw (and are "
            "small on the wire already)"
        )
    itf = None
    if args.device_cache:
        if args.device_normalize:
            raise SystemExit(
                "--device-cache already normalizes on device; "
                "drop --device-normalize"
            )
        from distributed_model_parallel_tpu.cli.common import (
            build_index_loaders,
        )

        train, val, num_classes, itf = build_index_loaders(
            args.dataset_type, args.data, args.batch_size, mesh,
            val_batch_size=args.val_batch_size,
        )
    else:
        train, val, num_classes = build_loaders(
            args.dataset_type, args.data, args.batch_size,
            val_batch_size=args.val_batch_size,
            workers=args.workers,
            device_normalize=args.device_normalize,
        )
    model = build_model(args.model, num_classes, remat=args.remat)
    opt = build_optimizer(args)
    cdt = compute_dtype_from_flag(args.dtype)
    if args.device_normalize:
        from distributed_model_parallel_tpu.cli.common import stats_for
        from distributed_model_parallel_tpu.data.loader import (
            device_normalizer,
        )

        itf = device_normalizer(*stats_for(args.dataset_type))
    if args.engine == "ddp":
        engine = DDPEngine(
            model, opt, mesh, sync_bn=args.sync_bn, compute_dtype=cdt,
            input_transform=itf,
            grad_reduction=args.grad_reduction,
            bucket_mb=args.bucket_mb,
            overlap_stages=args.overlap_stages,
            dcn_compression=args.dcn_compression,
        )
    elif args.engine == "fsdp":
        from distributed_model_parallel_tpu.parallel.fsdp import FSDPEngine

        engine = FSDPEngine(
            model, opt, mesh, compute_dtype=cdt, input_transform=itf,
            grad_reduction=args.grad_reduction,
            bucket_mb=args.bucket_mb,
            overlap_stages=args.overlap_stages,
            dcn_compression=args.dcn_compression,
        )
    elif args.engine == "tp":
        from distributed_model_parallel_tpu.parallel.tensor_parallel import (
            TensorParallelEngine,
        )

        engine = TensorParallelEngine(
            model, opt, mesh, compute_dtype=cdt, input_transform=itf,
            collective_matmul=args.collective_matmul,
        )
    else:
        engine = DataParallelEngine(
            model, opt, mesh, compute_dtype=cdt, input_transform=itf
        )
    checkpoint_dir = args.checkpoint_dir  # one source of truth (cfg + probes)

    def _restart_can_resume() -> bool:
        """Host-0-authoritative: checkpoints are written by host 0 only,
        so on per-host disks every process must adopt host 0's answer or
        the hosts disagree on resume and deadlock in the restore
        broadcast."""
        from distributed_model_parallel_tpu.training.checkpoint import (
            latest_exists,
        )

        exists = latest_exists(checkpoint_dir, "last") or latest_exists(
            checkpoint_dir
        )
        if jax.process_count() > 1:
            import numpy as np
            from jax.experimental import multihost_utils

            exists = bool(int(
                multihost_utils.broadcast_one_to_all(np.int32(exists))
            ))
        return exists

    def make_trainer(restart: bool) -> Trainer:
        resume = args.resume or (restart and _restart_can_resume())
        cfg = TrainerConfig(
            epochs=args.epochs,
            base_lr=args.lr,
            t_max=90,
            warmup_period=10,
            log_file=args.log_file or f"data_para_{args.batch_size}.txt",
            checkpoint_dir=checkpoint_dir,
            resume=resume,
            steps_per_epoch=args.steps_per_epoch,
            steps_per_dispatch=args.steps_per_dispatch,
            profile_dir=args.profile_dir,
            save_last=args.max_restarts > 0,
            checkpoint_format=args.checkpoint_format,
            async_save=args.async_save,
        )
        trainer = Trainer(engine, train, val, cfg, rng=jax.random.PRNGKey(0))
        if args.finetune and not resume:
            from distributed_model_parallel_tpu.models.torch_import import (
                load_torch_checkpoint,
                mobilenetv2_from_torch_state_dict,
            )

            p, s = mobilenetv2_from_torch_state_dict(
                trainer.state.params,
                trainer.state.model_state,
                load_torch_checkpoint(args.finetune),
            )
            # Re-place in the ENGINE'S state layout: _state_sh for the
            # sharded engines (FSDP keeps params/moments 1/N — a
            # replicated put here would materialize the full state on
            # every device, the OOM FSDP exists to avoid); replicated
            # for DP/DDP.
            placement = getattr(engine, "_state_sh", engine._repl)
            trainer.state = jax.device_put(
                trainer.state._replace(params=p, model_state=s),
                placement,
            )
            print(f"==> Transplanted torch weights from {args.finetune}")
        return trainer

    if args.max_restarts > 0:
        from distributed_model_parallel_tpu.training.elastic import (
            elastic_fit,
        )

        out = elastic_fit(
            make_trainer, max_restarts=args.max_restarts,
            checkpoint_dir=checkpoint_dir,
        )
    else:
        out = make_trainer(False).fit()
    from distributed_model_parallel_tpu.cli.common import (
        export_metrics_out,
    )

    export_metrics_out(args.metrics_out)
    return out


if __name__ == "__main__":
    main()
