"""Real-TPU attention micro-benchmark: Pallas flash kernels vs the XLA
dot-product path, forward and forward+backward, across sequence lengths,
head dims (64 AND 128), and causal masking.

Timing uses value-fetch synchronization (see RESULTS.md measurement
note / bench.py `_sync`): each measured window ends in a scalar fetch
that cannot complete before the chained work ran — `block_until_ready`
is not a reliable barrier on a tunneled backend.

Usage (on a host with a TPU):
    python experiments/flash_attention_bench.py \
        [--out experiments/flash_attention_bench.json]
    python experiments/flash_attention_bench.py --block-sweep
Prints one markdown row per (dh, T, path, causal); the XLA path skips
lengths whose (B, H, T, T) f32 logits would not fit HBM. `--block-sweep`
instead tunes (block_q, block_k) at T=8192 for both head dims.
"""

from __future__ import annotations

import argparse
import itertools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_model_parallel_tpu.ops.attention import (
    dot_product_attention,
)
from distributed_model_parallel_tpu.ops.pallas_attention import (
    flash_attention,
)

B, H = 2, 8


def _qkv(t, dh, dtype=jnp.bfloat16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(
        rng.randn(B, t, H, dh).astype(np.float32), dtype
    )
    return mk(), mk(), mk()


def _time(fn, *args, iters=20, warmup=3):
    """Simple timing with a value-fetch barrier."""
    out = None
    for _ in range(warmup):
        out = fn(*args)
    float(jnp.sum(out))  # sync warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(jnp.sum(out))  # the fetch IS the barrier
    return (time.perf_counter() - t0) / iters


def attention_tflops(t, dh, seconds, bwd=False, causal=False):
    """2 matmuls of 2*B*H*T^2*DH flops each forward; backward ~2.5x the
    forward matmul work (dq, dk, dv, plus the recomputed logits).
    Causal attention computes half the tiles, so half the flops."""
    fwd = 4 * B * H * t * t * dh * (0.5 if causal else 1.0)
    total = fwd * (1 + 2.5) if bwd else fwd
    return total / seconds / 1e12


def measure(fn, q, k, v, causal, t, dh, **kw):
    f = jax.jit(lambda q, k, v: fn(q, k, v, causal=causal, **kw))
    g = jax.jit(
        jax.grad(
            lambda q, k, v: jnp.sum(
                fn(q, k, v, causal=causal, **kw).astype(jnp.float32) ** 2
            ),
            argnums=(0, 1, 2),
        )
    )
    tf = _time(f, q, k, v)
    tg = _time(lambda *a: g(*a)[0], q, k, v)
    return {
        "fwd_ms": round(tf * 1e3, 2),
        "fwd_tflops": round(
            attention_tflops(t, dh, tf, causal=causal), 1
        ),
        "fwdbwd_ms": round(tg * 1e3, 2),
        "fwdbwd_tflops": round(
            attention_tflops(t, dh, tg, True, causal=causal), 1
        ),
    }


def main_sweep(args):
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})")
    rows = []
    print("| dh | T | path | causal | fwd ms | fwd TF/s "
          "| fwd+bwd ms | fwd+bwd TF/s |")
    print("|---|---|---|---|---|---|---|---|")
    for dh in (64, 128):
        for t in (1024, 2048, 4096, 8192, 16384, 32768):
            q, k, v = _qkv(t, dh)
            # XLA materializes (B, H, T, T) f32 logits (+ probs in the
            # backward): cap where that no longer fits the 16 GB HBM.
            xla_ok = B * H * t * t * 4 * 3 < 12e9
            paths = [("flash", flash_attention)] + (
                [("xla", dot_product_attention)] if xla_ok else []
            )
            for name, fn in paths:
                for causal in (False, True):
                    r = {"dh": dh, "T": t, "path": name,
                         "causal": causal}
                    r.update(measure(fn, q, k, v, causal, t, dh))
                    rows.append(r)
                    print(
                        f"| {dh} | {t} | {name} | {causal} "
                        f"| {r['fwd_ms']} | {r['fwd_tflops']} "
                        f"| {r['fwdbwd_ms']} | {r['fwdbwd_tflops']} |",
                        flush=True,
                    )
    # causal-skip speedup at long T (flash path): wall-clock ratio
    for dh in (64, 128):
        for t in (8192, 16384, 32768):
            pair = {
                r["causal"]: r for r in rows
                if r["dh"] == dh and r["T"] == t and r["path"] == "flash"
            }
            if len(pair) == 2:
                print(
                    f"causal-skip speedup dh={dh} T={t}: "
                    f"fwd {pair[False]['fwd_ms']/pair[True]['fwd_ms']:.2f}x "
                    f"fwd+bwd {pair[False]['fwdbwd_ms']/pair[True]['fwdbwd_ms']:.2f}x",
                    flush=True,
                )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(
                {"device": dev.device_kind, "B": B, "H": H, "rows": rows},
                f, indent=2,
            )


def main_block_sweep(args):
    """(block_q, block_k) tuning at T=8192 for both head dims — the
    retune the round-3 verdict asked for (one retune ever, dh=64)."""
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})")
    t = 8192
    results = []
    for dh in (64, 128):
        q, k, v = _qkv(t, dh)
        best = None
        for bq, bk in itertools.product(
            (256, 512, 1024), (256, 512, 1024, 2048)
        ):
            try:
                ms = _time(
                    jax.jit(
                        lambda q, k, v, bq=bq, bk=bk: flash_attention(
                            q, k, v, block_q=bq, block_k=bk
                        )
                    ),
                    q, k, v, iters=10,
                ) * 1e3
            except Exception as e:  # noqa: BLE001 — invalid tile combos
                print(f"dh={dh} bq={bq} bk={bk}: {type(e).__name__}")
                continue
            print(f"dh={dh} bq={bq} bk={bk}: {ms:.2f} ms", flush=True)
            results.append({"dh": dh, "block_q": bq, "block_k": bk,
                            "fwd_ms": round(ms, 2)})
            if best is None or ms < best[0]:
                best = (ms, bq, bk)
        if best is None:
            print(f"dh={dh}: NO tile config compiled on this backend",
                  flush=True)
        else:
            print(f"BEST dh={dh}: block_q={best[1]} block_k={best[2]} "
                  f"({best[0]:.2f} ms)", flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"device": dev.device_kind, "T": t,
                       "rows": results}, f, indent=2)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--block-sweep", action="store_true")
    args = ap.parse_args()
    if args.block_sweep:
        main_block_sweep(args)
    else:
        main_sweep(args)


if __name__ == "__main__":
    main()
