"""Compute ops: attention cores (reference-free — the reference has no
attention model; BERT-base is demanded by BASELINE.json's configs), their
sequence-parallel variants (ring attention over ppermute, Ulysses
all-to-all, and ring_flash_attention — the ring with the fused Pallas
kernels as its per-hop core), the Pallas flash-attention kernels
(forward + backward) for the single-chip hot path, and the
latency-hiding collective matmuls (chunked ppermute ag_matmul /
matmul_rs for the TP/SP projection layers), and the bucketed
hierarchy-aware gradient reducer (flat-buffer buckets over dcn×ici —
the DDP Reducer re-expressed, `grad_reduction.py`)."""

from distributed_model_parallel_tpu.ops.attention import (  # noqa: F401
    dot_product_attention,
)
from distributed_model_parallel_tpu.ops.collective_matmul import (  # noqa: F401
    CollectiveMatmul,
    LocalCollectiveMatmul,
    ag_matmul,
    matmul_rs,
)
from distributed_model_parallel_tpu.ops.grad_reduction import (  # noqa: F401
    bucketed_pmean,
    bucketed_psum,
    plan_buckets,
    ring_all_gather,
    ring_reduce_scatter,
)
from distributed_model_parallel_tpu.ops.pallas_attention import (  # noqa: F401
    flash_attention,
)
from distributed_model_parallel_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)
