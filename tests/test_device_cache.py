"""Device-resident dataset cache tests (8-device CPU mesh).

The cache moves the input pipeline (gather, crop/flip, normalize) inside
the compiled step so per-batch host->device traffic is an index vector.
Correctness bar: the non-augmented path must match the host Loader's
pixels bit-for-bit; the augmented path must be a valid crop/flip stream;
end-to-end training must follow the host path's convergence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_model_parallel_tpu.data.datasets import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    synthetic,
)
from distributed_model_parallel_tpu.data.device_cache import (
    DeviceDatasetCache,
    IndexLoader,
    combined_cache,
)
from distributed_model_parallel_tpu.data.loader import Loader, normalize
from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.parallel.data_parallel import (
    DataParallelEngine,
)
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.optim import SGD
from distributed_model_parallel_tpu.training.trainer import (
    Trainer,
    TrainerConfig,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshSpec(data=8))


def test_cache_gather_normalize_matches_host(mesh):
    ds = synthetic(num_examples=64, num_classes=4, image_size=8, seed=2)
    cache = DeviceDatasetCache(
        ds, mesh, augment=False, mean=CIFAR10_MEAN, std=CIFAR10_STD
    )
    tf = cache.transform()
    idx = np.array([3, 0, 63, 17, 17, 40, 8, 1], np.int32)
    got = np.asarray(tf(jnp.asarray(idx), step=jnp.int32(0), train=False))
    want = normalize(ds.images[idx], CIFAR10_MEAN, CIFAR10_STD)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_cache_augment_is_valid_crop_flip(mesh):
    """Every augmented image must be an exact crop (possibly flipped) of
    the padded source — checked by brute-force matching each output
    against all (y, x, flip) candidates."""
    ds = synthetic(num_examples=8, num_classes=2, image_size=8, seed=3)
    p = 2
    cache = DeviceDatasetCache(ds, mesh, augment=True, padding=p)
    tf = cache.transform()
    idx = np.arange(8, dtype=np.int32)
    out = np.asarray(tf(jnp.asarray(idx), step=jnp.int32(7), train=True))
    padded = np.pad(ds.images, ((0, 0), (p, p), (p, p), (0, 0)))
    for i in range(8):
        candidates = []
        for y in range(2 * p + 1):
            for x in range(2 * p + 1):
                w = padded[i, y:y + 8, x:x + 8].astype(np.float32) / 255.0
                candidates += [w, w[:, ::-1]]
        assert any(
            np.allclose(out[i], c, atol=1e-6) for c in candidates
        ), f"image {i} is not a crop/flip of its source"
    # train=False must bypass augmentation entirely.
    plain = np.asarray(tf(jnp.asarray(idx), step=jnp.int32(7), train=False))
    np.testing.assert_allclose(
        plain, ds.images.astype(np.float32) / 255.0, atol=1e-6
    )
    # Different steps draw different augmentations (overwhelmingly).
    out2 = np.asarray(tf(jnp.asarray(idx), step=jnp.int32(8), train=True))
    assert not np.allclose(out, out2)


def test_index_loader_matches_host_loader_sampling():
    """IndexLoader must walk the dataset in EXACTLY the host Loader's
    order: same permutation, same per-host shard, same labels stream."""
    ds = synthetic(num_examples=96, num_classes=4, image_size=8, seed=4)
    kw = dict(batch_size=16, shuffle=True, seed=9,
              process_index=1, process_count=2)
    host = Loader(ds, **kw)
    index = IndexLoader(ds, **kw)
    host.set_epoch(2)
    index.set_epoch(2)
    for (him, hl), (idx, il) in zip(host, index):
        assert idx.dtype == np.int32
        np.testing.assert_array_equal(hl, il)
        # indices address the very rows the host loader materialized
        np.testing.assert_array_equal(ds.labels[idx], il)
        np.testing.assert_array_equal(
            (ds.images[idx].astype(np.float32) / 255.0), him
        )


def test_index_loader_pads_ragged_final_batch():
    ds = synthetic(num_examples=20, num_classes=2, image_size=8, seed=5)
    loader = IndexLoader(ds, batch_size=8, shuffle=False, drop_last=False,
                         index_offset=100)
    batches = list(loader)
    assert len(batches) == 3
    idx, labels = batches[-1]
    assert idx.shape == (8,) and labels.shape == (8,)
    assert (labels[4:] == -1).all()
    assert (idx[:4] >= 100).all()  # offset applied to real rows


def tiny_model(num_classes=4):
    return L.named([
        ("conv", L.conv2d(3, 8, 3, stride=1, padding=1)),
        ("bn", L.batchnorm2d(8)),
        ("relu", L.relu()),
        ("pool", L.global_avg_pool()),
        ("linear", L.linear(8, num_classes)),
    ])


def test_device_cache_with_ddp_shard_map_engine(mesh):
    """--device-cache --engine ddp: the wants_ctx transform must trace
    inside shard_map (closed-over replicated cache array + per-shard
    indices), and the indices[0] key fold must DECORRELATE the augment
    draws across data shards."""
    from distributed_model_parallel_tpu.parallel.data_parallel import (
        DDPEngine,
    )

    ds = synthetic(num_examples=128, num_classes=4, image_size=8, seed=6)
    cache = DeviceDatasetCache(
        ds, mesh, augment=True, mean=CIFAR10_MEAN, std=CIFAR10_STD,
        padding=2,
    )
    tf = cache.transform()
    eng = DDPEngine(
        model=tiny_model(), optimizer=SGD(), mesh=mesh, donate=False,
        input_transform=tf,
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    idx = np.arange(64, dtype=np.int32)
    labels = ds.labels[:64].astype(np.int32)
    x, y = eng.shard_batch(idx, labels)
    losses = []
    for _ in range(3):
        ts, m = eng.train_step(ts, x, y, jnp.float32(0.1))
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]

    # Shard decorrelation: a dataset whose rows repeat (rows 8..15 ==
    # rows 0..7) lets us feed THE SAME images through two different
    # index windows — what two DDP shards see when the step matches. A
    # step-only key would produce identical crops/flips (the regression
    # this guards); the indices[0] fold must decorrelate them.
    dup = synthetic(num_examples=8, num_classes=4, image_size=8, seed=6)
    dup_images = np.concatenate([dup.images, dup.images])
    cache2 = DeviceDatasetCache(dup_images, mesh, augment=True, padding=2)
    tf2 = cache2.transform()
    a = np.asarray(tf2(jnp.arange(0, 8), step=jnp.int32(5), train=True))
    b = np.asarray(tf2(jnp.arange(8, 16), step=jnp.int32(5), train=True))
    assert not np.allclose(a, b), (
        "identical augment draws across shards: indices fold lost"
    )


def test_trainer_with_device_cache_learns(mesh, tmp_path):
    """End to end: IndexLoaders + combined cache + input_transform,
    through the Trainer (with multi-step dispatch on top) — loss falls
    and val acc beats chance, same as the host-path trainer."""
    train_ds = synthetic(num_examples=256, num_classes=4, image_size=8,
                         seed=0)
    val_ds = synthetic(num_examples=64, num_classes=4, image_size=8,
                       seed=1)
    tf, val_off = combined_cache(
        train_ds, val_ds, mesh,
        mean=CIFAR10_MEAN, std=CIFAR10_STD, augment=True,
    )
    assert val_off == 256
    engine = DataParallelEngine(
        model=tiny_model(), optimizer=SGD(), mesh=mesh, input_transform=tf
    )
    train = IndexLoader(train_ds, batch_size=32, shuffle=True, seed=0)
    val = IndexLoader(val_ds, batch_size=32, shuffle=False,
                      drop_last=False, index_offset=val_off)
    cfg = TrainerConfig(
        epochs=3, base_lr=0.1, t_max=3, warmup_period=1, print_freq=0,
        log_dir=str(tmp_path / "log"),
        checkpoint_dir=str(tmp_path / "ck"),
        steps_per_dispatch=4,
    )
    t = Trainer(engine, train, val, cfg, rng=jax.random.PRNGKey(0))
    result = t.fit()
    hist = result["history"]
    assert hist[-1]["train"]["loss"] < hist[0]["train"]["loss"]
    assert result["best_acc"] > 30.0
