"""Model-zoo shape and semantics tests.

Upgrades the reference's never-invoked smoke function `test()`
(`code/distributed_training/model/mobilenetv2.py:79-83`, runs a (2,3,32,32)
batch and prints the shape) into real assertions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_model_parallel_tpu.models import (
    Context,
    mobilenet_v2,
    mobilenet_v2_nobn,
    split_stages,
)
from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.models.mobilenetv2 import partition_pytree


def _param_count(tree):
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


@pytest.mark.slow
def test_mobilenetv2_shapes(rng):
    """Full BN-variant MobileNetV2 forward shape. `slow` (tier-1
    budget); tier-1 twins: test_mobilenetv2_param_count (the torch
    param-count pin, init only) and test_mobilenetv2_nobn_shapes (the
    forward shape on the BN-free variant)."""
    model = mobilenet_v2(num_classes=10)
    params, state = model.init(rng)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    y, new_state = model.apply(params, state, x, Context(train=False))
    assert y.shape == (2, 10)
    # torch MobileNetV2(num_classes=10) has 2,296,922 params; ours must match.
    assert _param_count(params) == 2_296_922


def test_mobilenetv2_param_count(rng):
    # torch MobileNetV2(num_classes=10) has 2,296,922 params; ours must
    # match (init only — the BN-variant forward compile rides the slow
    # test_mobilenetv2_shapes).
    params, _ = mobilenet_v2(num_classes=10).init(rng)
    assert _param_count(params) == 2_296_922


def test_mobilenetv2_nobn_shapes(rng):
    model = mobilenet_v2_nobn(num_classes=10)
    params, state = model.init(rng)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    y, _ = model.apply(params, state, x, Context(train=False))
    assert y.shape == (2, 10)
    # no-BN variant must have strictly fewer params (BN scale/bias removed).
    assert _param_count(params) < 2_296_922


def test_batchnorm_train_updates_state(rng):
    bn = L.batchnorm2d(4)
    params, state = bn.init(rng)
    x = 3.0 + 2.0 * jax.random.normal(jax.random.PRNGKey(2), (8, 5, 5, 4))
    y, new_state = bn.apply(params, state, x, Context(train=True))
    # Output is normalized.
    np.testing.assert_allclose(float(jnp.mean(y)), 0.0, atol=1e-4)
    np.testing.assert_allclose(float(jnp.std(y)), 1.0, atol=1e-2)
    # Running stats moved toward batch stats with momentum 0.1.
    assert float(jnp.max(jnp.abs(new_state["mean"]))) > 0.1
    # Eval mode leaves state untouched.
    _, eval_state = bn.apply(params, new_state, x, Context(train=False))
    assert jax.tree_util.tree_all(
        jax.tree_util.tree_map(
            lambda a, b: bool(jnp.all(a == b)), eval_state, new_state
        )
    )


def test_stage_split_composes_to_full_model(rng):
    """Composition of pipeline stages == full network (same params, same
    output). This is the static-shape replacement for the reference's
    runtime shape handshake (`distributed_layers.py:40-47`): stage I/O
    shapes are derived by tracing, so consistency is a provable property."""
    full = mobilenet_v2(num_classes=10)
    params, state = full.init(rng)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 32, 32, 3))
    y_full, _ = full.apply(params, state, x, Context(train=False))

    # [3,9,15] reproduces the reference ws=4 split (`model_parallel.py:102-144`).
    for num_stages, boundaries in [(4, [3, 9, 15]), (4, None), (2, None), (8, None)]:
        stages = split_stages(num_stages, 10, boundaries=boundaries)
        stage_params = partition_pytree(params, num_stages, boundaries=boundaries)
        stage_state = partition_pytree(state, num_stages, boundaries=boundaries)
        h = x
        for stage, p, s in zip(stages, stage_params, stage_state):
            h, _ = stage.apply(p, s, h, Context(train=False))
        np.testing.assert_allclose(
            np.asarray(h), np.asarray(y_full), atol=1e-5,
            err_msg=f"stages={num_stages} boundaries={boundaries}",
        )


def test_stage_split_rejects_bad_counts():
    with pytest.raises(ValueError):
        split_stages(0)
    with pytest.raises(ValueError):
        split_stages(18)
