"""Expert parallelism — GSPMD over the `'expert'` mesh axis, or the
hand-rolled hierarchical exchange over the factored data fabric.

Absent from the reference (SURVEY.md §2.3: "EP — absent"); first-class
here. Two dispatch modes on one engine:

* `dispatch="gspmd"` (default, the original path): like the
  tensor-parallel engine, this is NOT a hand-written collective stack —
  the MoE layer (`models/moe.py`) expresses routing as dense einsums
  against one-hot dispatch/combine tensors, so placing

      experts/w_in  (E, D, H)  -> P('expert', None, None)
      experts/b_in  (E, H)     -> P('expert', None)
      experts/w_out (E, H, D)  -> P('expert', None, None)
      experts/b_out (E, D)     -> P('expert', None)

  on the weight pytree is sufficient: the XLA SPMD partitioner sees a
  token tensor sharded over 'data' meeting expert weights sharded over
  'expert' and inserts the token exchange that GPU MoE frameworks
  (GShard, Switch, DeepSpeed-MoE) implement by hand — forward AND the
  mirrored gradient exchanges from the einsum transposes. On a factored
  `MeshSpec(dcn=K)` mesh that fused exchange drags the full token
  payload across the slow fabric in (K-1)*I fragments.

* `dispatch="hierarchical"` (+ `overlap=True`): the expert-parallel
  world becomes the (factored) DATA fabric itself — DeepSpeed-MoE's
  setting (Rajbhandari ICML'22, PAPERS.md). Expert weights shard 1/S on
  their leading E axis over `data_axis_names(mesh)` (the EP memory win
  kept; E % S == 0 required), the MoE FFN runs as a shard_map region
  around the layer (`ops/expert_dispatch.ExpertDispatch`, threaded via
  `Context.expert_dispatch`), and the token exchange is explicit:
  intra-slice all-to-all over 'ici' first, ONE cross-slice exchange
  over 'dcn' on the 1/ici-regrouped shard, every hop a tagged
  `moe_ring` ppermute, the backward mirrored via custom_vjp.
  `overlap=True` chunks the exchange so expert FFN compute on chunk k
  hides the communication of chunk k+1 (same decomposition as
  `ops/collective_matmul.py`). Pinned by hlolint rule
  `moe-hierarchical-a2a`: zero token-sized all-to-all on the data
  fabric, the exact tagged permute chain present.

`ExpertParallelEngine` is the tensor-parallel engine with the expert
rule set; concatenate `EXPERT_RULES + MEGATRON_RULES` on a
(data, model, expert) mesh to run EP and TP together in one program
(gspmd mode). Per-device expert-weight bytes scale 1/E_mesh — 1/S over
the data fabric in hierarchical mode — tested in
tests/test_expert_parallel.py / tests/test_expert_dispatch.py.

`ExpertParallelLMEngine` drives `models/gpt.gpt_lm` MoE stacks
(GPTConfig(num_experts>0)) with the token-level next-token loss — the
`--moe-experts`/`--moe-dispatch`/`--moe-overlap` surface of cli/lm.py.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

from distributed_model_parallel_tpu.parallel.data_parallel import (
    _metrics,
    _place_batch,
)
from distributed_model_parallel_tpu.parallel.tensor_parallel import (
    MEGATRON_RULES,
    TensorParallelEngine,
)
from distributed_model_parallel_tpu.runtime.mesh import data_axis_names
from distributed_model_parallel_tpu.training.metrics import cross_entropy

# Sharding layout for the stacked expert weights (models/moe.py param
# paths: .../moe/experts/{w_in,b_in,w_out,b_out}) — gspmd mode.
EXPERT_RULES: Tuple[Tuple[str, P], ...] = (
    (r"experts/w_in$", P("expert", None, None)),
    (r"experts/b_in$", P("expert", None)),
    (r"experts/w_out$", P("expert", None, None)),
    (r"experts/b_out$", P("expert", None)),
)


def hierarchical_expert_rules(mesh) -> Tuple[Tuple[str, P], ...]:
    """The hierarchical-dispatch at-rest layout: expert stacks sharded
    1/S on their leading E axis over the (factored) data axes — the
    same fabric the `moe_ring` exchange runs over, so the shard_map
    region's in_specs match at-rest placement and entry is free."""
    dd = tuple(data_axis_names(mesh))
    return (
        (r"experts/w_in$", P(dd, None, None)),
        (r"experts/b_in$", P(dd, None)),
        (r"experts/w_out$", P(dd, None, None)),
        (r"experts/b_out$", P(dd, None)),
    )


@dataclasses.dataclass
class ExpertParallelEngine(TensorParallelEngine):
    """Expert(+data) parallelism: GSPMD over 'expert' by path rules
    (default), or the hand-rolled hierarchical dcn x ici exchange over
    the data fabric (`dispatch="hierarchical"`, optionally
    `overlap=True`). Same API as every other engine."""

    rules: Sequence[Tuple[str, P]] = EXPERT_RULES
    # "gspmd": partitioner-inserted flat exchange over 'expert'.
    # "hierarchical": explicit two-level moe_ring exchange over the
    # (factored) data axes (`ops/expert_dispatch.py`); requires the
    # 'expert' mesh axis at size 1 (experts ride the data fabric) and
    # num_experts divisible by the data-fabric size.
    dispatch: str = "gspmd"
    # Chunk the hierarchical exchange so expert FFN compute on chunk k
    # overlaps communication of chunk k+1 (hierarchical mode only; same
    # math, same tagged hop count, different dependency structure).
    overlap: bool = False
    # Compress the cross-slice 'dcn' messages of the hierarchical
    # exchange (BOTH directions, backward mirrored through the
    # custom_vjp) to this wire dtype ("none" | "bf16" | "int8",
    # `ops/wire_codec.py`). Hierarchical dispatch on a MeshSpec(dcn=K)
    # mesh only — the gspmd flat exchange has no explicit dcn seam.
    dcn_compression: str = "none"

    def __post_init__(self):
        if self.dispatch not in ("gspmd", "hierarchical"):
            raise ValueError(
                "dispatch must be 'gspmd' or 'hierarchical', got "
                f"{self.dispatch!r}"
            )
        if self.overlap and self.dispatch != "hierarchical":
            raise ValueError(
                "overlap=True chunks the hierarchical exchange; it has "
                "no effect under dispatch='gspmd' — set "
                "dispatch='hierarchical' or drop overlap"
            )
        from distributed_model_parallel_tpu.ops.wire_codec import (
            check_compression,
        )

        check_compression(self.dcn_compression)
        if (
            self.dcn_compression != "none"
            and self.dispatch != "hierarchical"
        ):
            raise ValueError(
                "dcn_compression compresses the hierarchical "
                "exchange's cross-slice messages; the gspmd dispatch "
                "has no explicit 'dcn' hop — set "
                "dispatch='hierarchical' or drop dcn_compression"
            )
        if self.dispatch == "hierarchical":
            if (
                "expert" in self.mesh.axis_names
                and int(self.mesh.shape["expert"]) > 1
            ):
                raise ValueError(
                    "dispatch='hierarchical' rides the (factored) data "
                    "fabric: experts shard over data_axis_names(mesh), "
                    "not 'expert' — build the mesh with expert=1 (got "
                    f"expert={int(self.mesh.shape['expert'])})"
                )
            from distributed_model_parallel_tpu.ops.expert_dispatch import (
                ExpertDispatch,
            )

            if self.dcn_compression != "none":
                from distributed_model_parallel_tpu.ops.wire_codec import (
                    require_dcn_axis,
                )
                from distributed_model_parallel_tpu.runtime.mesh import (
                    data_hierarchy_axes,
                )

                require_dcn_axis(
                    self.dcn_compression,
                    data_hierarchy_axes(self.mesh)[2],
                    what="MoE exchange",
                )
            # Swap the default 'expert'-axis layout for the data-fabric
            # one (an explicit rules= override wins).
            if self.rules is EXPERT_RULES:
                self.rules = hierarchical_expert_rules(self.mesh)
            self._expert_dispatch = ExpertDispatch(
                self.mesh, overlap=self.overlap,
                dcn_compression=self.dcn_compression,
            )
        super().__post_init__()


@dataclasses.dataclass
class ExpertParallelLMEngine(ExpertParallelEngine):
    """Causal-LM pretraining under expert(+data) parallelism: the EP
    engine with the token-level next-token loss — `gpt_lm(cfg)` stacks
    with `GPTConfig(num_experts > 0)` MoE decoder blocks serve
    directly. `shard_batch` builds targets on the HOST
    (`models.gpt.lm_targets`) like the CausalLM-SP engine, so the two
    text engines share one data contract."""

    pad_token_id: Optional[int] = None

    def __post_init__(self):
        from distributed_model_parallel_tpu.models.gpt import lm_targets

        self._lm_targets = partial(
            lm_targets, pad_token_id=self.pad_token_id
        )
        super().__post_init__()

    def loss_and_metrics(self, logits, targets):
        """Next-token loss on the flattened token axis: logits
        (B, T, V) + targets (B, T) -> the shared `_metrics` contract
        (pad targets are -1, excluded everywhere)."""
        b, t, v = logits.shape
        flat_logits = logits.reshape(b * t, v)
        flat_targets = targets.reshape(b * t)
        ce = cross_entropy(flat_logits, flat_targets)
        return ce, _metrics(ce, flat_logits, flat_targets)

    def shard_batch(self, ids, labels=None):
        """ids (B, T) -> (ids, next-token targets), both sharded over
        the data axes. `labels` is ignored (the LM's targets are the
        shifted ids — the LMLoader yields (ids, ids))."""
        targets = self._lm_targets(ids)
        return _place_batch((ids, targets), self._batch)


__all__ = [
    "EXPERT_RULES",
    "MEGATRON_RULES",
    "ExpertParallelEngine",
    "ExpertParallelLMEngine",
    "hierarchical_expert_rules",
]
