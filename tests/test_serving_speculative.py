"""Speculative decoding pins (ISSUE 18, `serving/speculative.py` /
`ServingEngine.verify_step` / `cli/serve.py` flags).

The load-bearing pins:

* **Greedy losslessness** — speculative greedy through `eng.run` is
  BIT-IDENTICAL to the non-speculative greedy engine for the
  replicated/TP/TP+collective-matmul layouts, with a random (almost
  always wrong) draft, under admission pressure (requests > slots, so
  slots recycle mid-run). Speculation is a scheduling change, never a
  token change.
* **Rollback returns pages** — a rejected suffix rolls back by
  `PagedCacheHost.truncate`; at page_size=2 a verify round writes
  past a page boundary, so rejections free pool pages (asserted from
  the pool bookkeeping through wrapped hosts on BOTH caches), and
  parity still holds.
* **Full accept** — a draft that IS the target's prefix (trailing
  residual blocks zeroed; GPT has no final LN) pins accept_rate == 1.0
  and mean_accept_len == k+1: acceptance measures draft quality, not
  machinery luck.
* **Sampled losslessness** — `rejection_verify`'s emitted-token
  marginal equals the target's filtered distribution for ANY draft
  (statistical pin over Philox lanes), plus the p==q all-accept and
  zero-overlap always-reject corners.
* **Guards** — engine- and CLI-level misconfigurations (non-paged
  draft, sp layout, k without pages, lockstep mismatches, draft flags
  without k, negative arrival knobs) fail loudly before any compile.
* **Pricing units** — the cost closed forms (`
  speculative_expected_tokens`, `serve_verify_compute_s`,
  `serve_speculative_token_s`, `serve_speculative_request_s`) match
  hand-computed values and refuse out-of-domain inputs.

S=4 layout sweeps are `slow` (tier-1 budget) with named tier-1 twins,
per the budget-rebalance convention.
"""

import dataclasses

import numpy as np
import pytest

import jax

from distributed_model_parallel_tpu.models.gpt import GPTConfig
from distributed_model_parallel_tpu.observability import cost, metrics
from distributed_model_parallel_tpu.runtime.mesh import (
    MeshSpec,
    make_mesh,
)
from distributed_model_parallel_tpu.serving.engine import ServingEngine
from distributed_model_parallel_tpu.serving.sampling import (
    SamplingConfig,
    SlotSampler,
)
from distributed_model_parallel_tpu.serving.scheduler import Request
from distributed_model_parallel_tpu.serving.speculative import (
    check_draft_engine,
    greedy_verify,
    rejection_verify,
)

CFG = GPTConfig(
    vocab_size=61, dim=16, num_layers=2, num_heads=4, ffn_dim=32,
    max_position=16, dropout_rate=0.0,
)
# A fresh-init 1-layer draft: wrong about almost every token (random
# weights disagree), so greedy parity is exercised through REJECTED
# suffixes, not lucky accepts.
DRAFT_CFG = dataclasses.replace(CFG, num_layers=1)

# page_size=2 with k=2: a verify round writes up to 3 positions —
# past a page boundary — so the shared run exercises rollback page
# frees, not just truncation-in-place. num_slots=4 divides both tp
# shard counts below, letting the layout tests reuse the shared
# fixture's draft engine and baseline tokens.
ENGINE_KW = dict(
    num_slots=4, max_len=16, prefill_len=8, page_size=2,
    prefill_chunk=4,
)


def _requests(n=6, seed=0, max_new=5):
    """Ragged prompts, more requests than slots: slots recycle
    mid-run (the admission/evict path under speculation)."""
    rng = np.random.RandomState(seed)
    return [
        Request(
            rid=i,
            prompt=rng.randint(
                1, CFG.vocab_size, size=int(rng.randint(2, 7))
            ).astype(np.int32),
            max_new_tokens=max_new,
        )
        for i in range(n)
    ]


def _spec_engines(k=2, mesh=None, layout_kw=None, **overrides):
    """Target (speculative_k=k) + plain twin + draft, all sharing the
    lockstep fields. The draft always runs replicated — proposals are
    host-side token ids, so the draft's layout is independent of the
    target's."""
    kw = dict(ENGINE_KW, **overrides)
    layout_kw = layout_kw or {}
    args = (CFG, mesh) if mesh is not None else (CFG,)
    target = ServingEngine(*args, speculative_k=k, **layout_kw, **kw)
    plain = ServingEngine(*args, **layout_kw, **kw)
    # The draft never shares target-side features (prefix_cache is
    # rejected by check_draft_engine) — only the lockstep fields.
    dkw = {key: v for key, v in kw.items() if key != "prefix_cache"}
    draft = ServingEngine(DRAFT_CFG, **dkw)
    return target, plain, draft


def _run_pair(target, plain, draft, reqs, *, sampling=None):
    """Run the same request set speculatively and plainly; return
    (spec tokens by rid, plain tokens by rid, spec scheduler)."""
    params = target.init_params(jax.random.PRNGKey(0))
    dparams = draft.init_params(jax.random.PRNGKey(7))
    sspec = target.run(
        params, [dataclasses.replace(r) for r in reqs],
        sampling, draft=draft, draft_params=dparams,
    )
    splain = plain.run(
        params, [dataclasses.replace(r) for r in reqs], sampling
    )
    assert len(sspec.finished) == len(reqs)
    assert len(splain.finished) == len(reqs)
    return (
        {f.rid: f.tokens for f in sspec.finished},
        {f.rid: f.tokens for f in splain.finished},
        sspec,
    )


def _wrap_truncate(eng):
    """Instrument the engine's future host: record how many pool pages
    each `truncate` call returns."""
    freed = []
    orig_new_host = eng.new_host

    def new_host():
        host = orig_new_host()
        orig_truncate = host.truncate

        def truncate(slot, n_tokens):
            before = host.pool.pages_in_use
            orig_truncate(slot, n_tokens)
            freed.append(before - host.pool.pages_in_use)

        host.truncate = truncate
        return host

    eng.new_host = new_host
    return freed


# --------------------------------------------- greedy parity (layouts)


@pytest.fixture(scope="module")
def spec_run_k2():
    """ONE shared replicated k=2 speculative-vs-plain run (compiles
    are the tier-1 cost driver): engines + params + both token maps +
    the speculative scheduler + a metrics snapshot + truncate-wrapped
    page-free ledgers, reused by the parity / rollback / metrics /
    sampled / full-accept / tp-layout tests below."""
    target, plain, draft = _spec_engines(k=2)
    target_freed = _wrap_truncate(target)
    draft_freed = _wrap_truncate(draft)
    params = target.init_params(jax.random.PRNGKey(0))
    dparams = draft.init_params(jax.random.PRNGKey(7))
    reqs = _requests()
    mx = metrics.enable()
    try:
        sched = target.run(
            params, [dataclasses.replace(r) for r in reqs],
            draft=draft, draft_params=dparams,
        )
        hist = mx.histogram("serve_spec_accept_len")
        snapshot = {
            "counters": mx.to_json()["counters"],
            "accept_len_count": hist.count if hist else 0,
        }
    finally:
        metrics.set_metrics(None)
    splain = plain.run(params, [dataclasses.replace(r) for r in reqs])
    return {
        "target": target, "plain": plain, "draft": draft,
        "params": params, "dparams": dparams, "reqs": reqs,
        "spec": {f.rid: f.tokens for f in sched.finished},
        "base": {f.rid: f.tokens for f in splain.finished},
        "sched": sched, "metrics": snapshot,
        "target_freed": list(target_freed),
        "draft_freed": list(draft_freed),
    }


def test_spec_greedy_matches_plain_replicated(spec_run_k2):
    """The tentpole pin: speculative greedy == plain greedy,
    bit-identical, with slot recycling (5 requests over 2 slots) and a
    random draft (rejections dominate)."""
    r = spec_run_k2
    assert len(r["spec"]) == len(r["reqs"])
    assert r["spec"] == r["base"]
    rep = r["sched"].latency_report()
    assert rep["speculative"]["k"] == 2
    # Every token except each request's prefill-produced first one
    # came out of a verify round (no slot neared max_len, so the
    # degrade-to-plain-decode path never fired here).
    assert rep["speculative"]["spec_tokens"] == sum(
        len(t) for t in r["spec"].values()
    ) - len(r["spec"])


@pytest.mark.slow
def test_spec_greedy_matches_plain_replicated_k4():
    """k=4 parity: deeper lookahead, same acceptance rule. `slow`
    (tier-1 budget); tier-1 twin:
    test_spec_greedy_matches_plain_replicated (k=2 on the same
    propose/verify/accept path — only the compiled verify width
    changes)."""
    target, plain, draft = _spec_engines(k=4)
    spec, base, _ = _run_pair(target, plain, draft, _requests())
    assert spec == base


def _run_spec_tp(s, devices, spec_run_k2, *, cm=False):
    """TP speculative target reusing the shared fixture's compiled
    draft, dense params (via `place_params`, the documented drop-in
    path — init values are layout-independent) and replicated plain
    baseline (tp plain == replicated plain is pinned by
    test_serving_paged's layout parity)."""
    mesh = make_mesh(MeshSpec(data=1, model=s), devices=devices[:s])
    target = ServingEngine(
        CFG, mesh, layout="tp", collective_matmul=cm,
        speculative_k=2, **ENGINE_KW,
    )
    sched = target.run(
        target.place_params(spec_run_k2["params"]),
        [dataclasses.replace(r) for r in spec_run_k2["reqs"]],
        draft=spec_run_k2["draft"],
        draft_params=spec_run_k2["dparams"],
    )
    assert {f.rid: f.tokens for f in sched.finished} \
        == spec_run_k2["base"]


@pytest.mark.parametrize("s", [
    2, pytest.param(4, marks=pytest.mark.slow),
])
def test_spec_greedy_matches_plain_tp(s, devices, spec_run_k2):
    """TP target + replicated draft: verify rides the tp chunk-shaped
    paged path; proposals cross as host token ids. S=4 is `slow`;
    tier-1 twin: the S=2 case on the same code path."""
    _run_spec_tp(s, devices, spec_run_k2)


@pytest.mark.parametrize("s", [
    2, pytest.param(4, marks=pytest.mark.slow),
])
def test_spec_greedy_matches_plain_tp_collective_matmul(
    s, devices, spec_run_k2
):
    """Opted-in decode rings under the verify step (the
    serve/S2/pg8/cm/spec2 hlolint combo's runtime twin). S=4 is
    `slow`; tier-1 twin: the S=2 case."""
    _run_spec_tp(s, devices, spec_run_k2, cm=True)


# ------------------------------------------------ rollback frees pages


def test_rejected_suffix_rollback_returns_pages(spec_run_k2):
    """page_size=2 with k=2: a verify round writes up to 3 positions —
    past a page boundary — so a first-position rejection leaves a
    wholly-stale page that `truncate` must return to the pool. Pinned
    through the pool bookkeeping on BOTH hosts of the shared run
    (whose parity the tentpole test asserts)."""
    rep = spec_run_k2["sched"].latency_report()["speculative"]
    # The random draft must actually have been rejected somewhere…
    assert rep["accept_rate"] < 1.0
    # …and at least one rollback returned whole pages on each cache.
    freed_t = spec_run_k2["target_freed"]
    freed_d = spec_run_k2["draft_freed"]
    assert freed_t and max(freed_t) > 0
    assert freed_d and max(freed_d) > 0


# --------------------------------------------- exact-prefix full accept


def test_exact_prefix_draft_full_accept(spec_run_k2):
    """A 1-layer draft holding the target's stem + block 0 + head,
    against a 2-layer target whose block 1 is identity (residual
    branch outputs zeroed; GPT has no final LN): the draft's logits
    ARE the target's, so every proposal survives — accept_rate == 1.0,
    mean_accept_len == k+1, and the emitted tokens still match plain
    greedy. Reuses the shared trio's compiled engines with SURGICAL
    params."""
    k = 2
    target = spec_run_k2["target"]
    plain = spec_run_k2["plain"]
    draft = spec_run_k2["draft"]
    # tree.map rebuilds the dict containers, so the surgery below
    # never touches the fixture's own params.
    params = jax.tree.map(lambda x: x, spec_run_k2["params"])
    for branch in ("attn", "ffn"):
        out = params["blocks"]["1"][branch]["out"]
        out["w"] = out["w"] * 0
        out["b"] = out["b"] * 0
    dparams = jax.tree.map(lambda x: x, spec_run_k2["dparams"])
    dparams["stem"] = params["stem"]
    dparams["blocks"]["0"] = params["blocks"]["0"]
    dparams["head"] = params["head"]
    reqs = spec_run_k2["reqs"]
    sspec = target.run(
        params, [dataclasses.replace(r) for r in reqs],
        draft=draft, draft_params=dparams,
    )
    splain = plain.run(params, [dataclasses.replace(r) for r in reqs])
    assert {f.rid: f.tokens for f in sspec.finished} == {
        f.rid: f.tokens for f in splain.finished
    }
    rep = sspec.latency_report()["speculative"]
    assert rep["accept_rate"] == 1.0
    assert rep["mean_accept_len"] == k + 1


# -------------------------------------------------- sampled (lossless)


def test_spec_sampled_runs_lossless_smoke(spec_run_k2):
    """Sampled speculative decoding completes the request set and
    emits the right token COUNTS (per-token values are random but the
    budget/eviction bookkeeping must hold under rejection draws).
    Reuses the shared trio's compiled engines — sampling is host-side
    over already-fetched logits, so the compiled steps are the same."""
    target = spec_run_k2["target"]
    draft = spec_run_k2["draft"]
    reqs = _requests()
    sched = target.run(
        spec_run_k2["params"], reqs,
        SamplingConfig(temperature=1.0, top_k=8, seed=3),
        draft=draft, draft_params=spec_run_k2["dparams"],
    )
    assert len(sched.finished) == len(reqs)
    for f in sched.finished:
        want = next(r.max_new_tokens for r in reqs if r.rid == f.rid)
        assert len(f.tokens) == want
        assert all(0 <= t < CFG.vocab_size for t in f.tokens)


def test_rejection_verify_marginal_is_target_distribution():
    """The losslessness theorem, statistically: over many Philox
    lanes, the FIRST emitted token's empirical marginal equals the
    target's filtered distribution p — even though the proposals come
    from a very different (peaked) draft q. Any accept/reject
    bookkeeping error shows up as mass displaced toward q's mode."""
    vocab, k, trials = 5, 2, 4000
    rng = np.random.RandomState(0)
    rows = rng.randn(k + 1, vocab)  # target logits per position
    q = np.full(vocab, 0.02)
    q[3] = 1.0 - 0.02 * (vocab - 1)  # draft: peaked on token 3
    counts = np.zeros(vocab)
    for t in range(trials):
        sampler = SlotSampler(
            SamplingConfig(temperature=1.0, seed=t), 1
        )
        d = sampler.sample_dist(q, 0)  # proposal drawn FROM q
        emitted = rejection_verify(
            rows, np.asarray([d, d], np.int64), [q, q], sampler, 0
        )
        counts[emitted[0]] += 1
    p = SlotSampler(
        SamplingConfig(temperature=1.0, seed=0), 1
    ).dist(rows[0])
    np.testing.assert_allclose(counts / trials, p, atol=0.03)


def test_rejection_verify_identical_dists_accept_all():
    """q == p accepts every proposal with probability 1 (the coin is
    u*q[d] <= p[d]); the round ends with a bonus draw from p."""
    vocab, k = 7, 3
    rng = np.random.RandomState(1)
    rows = rng.randn(k + 1, vocab)
    sampler = SlotSampler(SamplingConfig(temperature=1.0, seed=5), 1)
    dists = [sampler.dist(rows[i]) for i in range(k)]
    proposals = np.asarray(
        [sampler.sample_dist(dists[i], 0) for i in range(k)], np.int64
    )
    emitted = rejection_verify(rows, proposals, dists, sampler, 0)
    assert emitted[:k] == list(proposals)
    assert len(emitted) == k + 1
    assert 0 <= emitted[k] < vocab


def test_rejection_verify_zero_overlap_always_corrects():
    """p puts ZERO mass on the proposal -> the coin cannot accept
    (u*q[d] <= 0 has probability 0 for u in (0,1)); the correction
    comes from the residual normalize(max(p-q, 0)), which also
    excludes the proposal."""
    vocab = 4
    p = np.asarray([0.5, 0.5, 0.0, 0.0])
    q = np.asarray([0.0, 0.0, 1.0, 0.0])
    rows = np.log(np.maximum(p, 1e-12))[None]  # dist(rows[0]) ~= p
    for seed in range(16):
        sampler = SlotSampler(
            SamplingConfig(temperature=1.0, seed=seed), 1
        )
        emitted = rejection_verify(
            rows, np.asarray([2], np.int64), [q], sampler, 0
        )
        assert len(emitted) == 1  # suffix rejected at position 0
        assert emitted[0] in (0, 1)  # drawn from the residual = p


def test_rejection_verify_deterministic_per_seed():
    """Same lane seed -> byte-identical emission (the reproducibility
    contract sampling.py pins, extended through the rejection rule)."""
    vocab, k = 6, 2
    rng = np.random.RandomState(2)
    rows = rng.randn(k + 1, vocab)
    q = np.full(vocab, 1.0 / vocab)
    runs = []
    for _ in range(2):
        sampler = SlotSampler(
            SamplingConfig(temperature=1.0, seed=11), 1
        )
        runs.append(rejection_verify(
            rows, np.asarray([1, 4], np.int64), [q, q], sampler, 0
        ))
    assert runs[0] == runs[1]


# ------------------------------------------------- greedy_verify units


def test_greedy_verify_emits_longest_matching_prefix():
    vocab = 8
    rows = np.zeros((3, vocab))
    rows[0, 2] = rows[1, 5] = rows[2, 1] = 1.0  # target argmaxes
    # Full match -> k accepts + bonus (the row-k argmax).
    assert greedy_verify(rows, np.asarray([2, 5])) == [2, 5, 1]
    # Mismatch at position 1 -> the target's own token corrects and
    # the suffix is dropped.
    assert greedy_verify(rows, np.asarray([2, 3])) == [2, 5]
    assert greedy_verify(rows, np.asarray([7, 5])) == [2]


# ---------------------------------------------- prefix-cache interplay


@pytest.mark.slow
def test_spec_with_target_prefix_cache_hits_and_parity():
    """The prefix cache stays a TARGET-side feature under speculation:
    a repeated prompt hits (counter increments), the draft ingests
    every prompt itself, and the emitted tokens still match plain
    greedy. `slow` (tier-1 budget); tier-1 twins:
    test_spec_greedy_matches_plain_replicated (speculative parity on
    the same engines) + test_serving_paged's prefix-cache hit pins
    (the cache itself, non-speculative)."""
    # 2 slots so the later identical prompts arrive AFTER the first
    # wave's prefill has populated the cache (4 slots would admit all
    # four at once and every lookup would miss).
    target, plain, draft = _spec_engines(
        k=2, prefix_cache=True, num_slots=2
    )
    prompt = np.arange(1, 7, dtype=np.int32)  # covers whole pages
    reqs = [
        Request(rid=i, prompt=prompt.copy(), max_new_tokens=4)
        for i in range(4)
    ]
    mx = metrics.enable()
    try:
        spec, base, _ = _run_pair(target, plain, draft, reqs)
        assert spec == base
        hits = mx.to_json()["counters"]["serve_prefix_hits_total"]
        assert hits > 0
    finally:
        metrics.set_metrics(None)


# -------------------------------------------------------- observability


def test_spec_metrics_histogram_and_counter(spec_run_k2):
    """serve_spec_accept_len observes once per verify round and
    serve_spec_tokens_total counts every speculative-round token —
    both must reconcile with the scheduler's own report (snapshot
    captured by the shared fixture's metered run)."""
    rep = spec_run_k2["sched"].latency_report()["speculative"]
    snap = spec_run_k2["metrics"]
    assert snap["accept_len_count"] == rep["verify_rounds"]
    assert snap["counters"]["serve_spec_tokens_total"] \
        == rep["spec_tokens"]


# --------------------------------------------------------------- guards


def test_check_draft_engine_guards():
    target = ServingEngine(CFG, speculative_k=2, **ENGINE_KW)
    with pytest.raises(ValueError, match="PAGED draft"):
        check_draft_engine(
            target,
            ServingEngine(DRAFT_CFG, num_slots=2, max_len=16,
                          prefill_len=8),
        )
    with pytest.raises(ValueError, match="non-speculative"):
        check_draft_engine(
            target,
            ServingEngine(DRAFT_CFG, speculative_k=2, **ENGINE_KW),
        )
    with pytest.raises(ValueError, match="target-side"):
        check_draft_engine(
            target,
            ServingEngine(DRAFT_CFG, prefix_cache=True, **ENGINE_KW),
        )
    with pytest.raises(ValueError, match="lockstep"):
        check_draft_engine(
            target,
            ServingEngine(DRAFT_CFG, **dict(ENGINE_KW, num_slots=2)),
        )


def test_engine_speculative_guards(devices):
    with pytest.raises(ValueError, match=r"\[1, 8\]"):
        ServingEngine(CFG, **dict(ENGINE_KW, speculative_k=9))
    with pytest.raises(ValueError, match="BLOCK TABLE"):
        ServingEngine(
            CFG, num_slots=2, max_len=16, prefill_len=8,
            speculative_k=2,
        )
    with pytest.raises(ValueError, match="sp "):
        ServingEngine(
            CFG,
            make_mesh(MeshSpec(data=1, seq=2), devices=devices[:2]),
            layout="sp", num_slots=2, max_len=16, prefill_len=8,
            page_size=4, speculative_k=2,
        )
    with pytest.raises(ValueError, match="leaves no"):
        ServingEngine(
            CFG, num_slots=2, max_len=8, prefill_len=4, page_size=4,
            speculative_k=8,
        )
    # run()-time pairing: k without a draft, and a draft without k.
    target, plain, draft = _spec_engines(k=2)
    params = target.init_params(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="needs a proposer"):
        target.run(params, _requests(n=1))
    with pytest.raises(ValueError, match="speculative_k > 0 on the"):
        plain.run(
            params, _requests(n=1), draft=draft,
            draft_params=draft.init_params(jax.random.PRNGKey(7)),
        )


def test_serve_cli_speculative_flag_guards():
    """The CLI backstop (cli/common.check_serving_args): speculative
    and arrival misconfigurations die with flag vocabulary BEFORE any
    mesh or engine exists."""
    from distributed_model_parallel_tpu.cli import serve

    with pytest.raises(SystemExit):  # k out of range
        serve.main(["--speculative-k", "9"])
    with pytest.raises(SystemExit):  # rollback needs pages
        serve.main(["--speculative-k", "2"])
    with pytest.raises(SystemExit):  # no verify path under sp
        serve.main(["--layout", "sp", "--seq-shards", "2",
                    "--page-size", "16", "--speculative-k", "2"])
    with pytest.raises(SystemExit):  # k+1 must fit under max-len
        serve.main(["--page-size", "4", "--max-len", "8",
                    "--speculative-k", "8"])
    with pytest.raises(SystemExit):  # draft flags need k >= 1
        serve.main(["--speculative-draft-layers", "2"])
    with pytest.raises(SystemExit):  # checkpoint XOR fresh-init layers
        serve.main(["--page-size", "16", "--speculative-k", "2",
                    "--speculative-draft", "/tmp/nowhere",
                    "--speculative-draft-layers", "2"])
    with pytest.raises(SystemExit):  # negative draft depth
        serve.main(["--page-size", "16", "--speculative-k", "2",
                    "--speculative-draft-layers", "-1"])
    with pytest.raises(SystemExit):  # offered load can't be negative
        serve.main(["--arrival-rate", "-1"])
    with pytest.raises(SystemExit):  # a burst is >= 1 requests
        serve.main(["--arrival-rate", "2", "--arrival-burst", "0"])
    with pytest.raises(SystemExit):  # burst needs a rate
        serve.main(["--arrival-burst", "4"])


def test_synthetic_arrivals_deterministic_and_bursty():
    from distributed_model_parallel_tpu.cli import serve

    args = serve.build_parser().parse_args(
        ["--arrival-rate", "10", "--arrival-burst", "3",
         "--num-requests", "8", "--seed", "5"]
    )
    a = serve.synthetic_arrivals(args)
    b = serve.synthetic_arrivals(args)
    np.testing.assert_array_equal(a, b)  # deterministic in --seed
    assert a.shape == (8,)
    assert np.all(np.diff(a) >= 0)  # submission order
    # Burst structure: requests 0-2 share an event time, 3-5 the next.
    assert a[0] == a[1] == a[2]
    assert a[3] == a[4] == a[5]
    assert a[3] > a[0]
    # Rate 0 is the legacy all-at-t=0 trace.
    args0 = serve.build_parser().parse_args(["--num-requests", "4"])
    np.testing.assert_array_equal(
        serve.synthetic_arrivals(args0), np.zeros(4)
    )


# --------------------------------------------------------- cost units


def test_cost_speculative_expected_tokens():
    assert cost.speculative_expected_tokens(0.7, 0) == 1.0
    assert cost.speculative_expected_tokens(1.0, 4) == 5.0
    # Hand-computed: acc 0.5, k 2 -> 1 + 0.5 + 0.25.
    assert cost.speculative_expected_tokens(0.5, 2) == pytest.approx(
        1.75
    )
    assert cost.speculative_expected_tokens(0.0, 3) == 1.0
    with pytest.raises(ValueError, match=r"\[0, 1\]"):
        cost.speculative_expected_tokens(1.2, 2)


def test_cost_verify_step_is_decode_at_widened_batch():
    """The verify roofline IS the decode roofline at m = slots*(k+1):
    one closed form, no second set of constants to drift."""
    assert cost.serve_verify_compute_s(
        2, 16, 32, 4, speculative_k=3
    ) == cost.serve_decode_compute_s(2, 16, 32, 16)


def test_cost_speculative_token_hand_computed():
    # (k * ratio * decode + verify) / E(acc, k)
    # = (2 * 0.5 * 1.0 + 1.1) / 1.75 = 1.2 at acc 0.5, ratio 0.5.
    got = cost.serve_speculative_token_s(
        1.0, 1.1, 2, accept_rate=0.5, draft_cost_ratio=0.5
    )
    assert got == pytest.approx(2.1 / 1.75)
    # Defaults come from COMPUTE_CONSTANTS (the ledger drift-checks
    # them): acc 0.7, ratio 0.5.
    e = cost.speculative_expected_tokens(
        cost.SPEC_MODEL_ACCEPT, 2
    )
    assert cost.serve_speculative_token_s(1.0, 1.1, 2) \
        == pytest.approx((2 * 0.5 * 1.0 + 1.1) / e)
    with pytest.raises(ValueError, match="k >= 1"):
        cost.serve_speculative_token_s(1.0, 1.1, 0)


def test_cost_speculative_request_validates_and_prices():
    with pytest.raises(ValueError, match="k >= 1"):
        cost.serve_speculative_request_s(8, 16, 64, 4, 4, 0)
    with pytest.raises(ValueError, match="paged"):
        cost.serve_speculative_request_s(8, 16, 64, 0, 4, 2)
    s = cost.serve_speculative_request_s(8, 16, 64, 4, 4, 2)
    assert s > 0
    # A perfect-accept override amortizes strictly better than the
    # model default (0.7) at the same shapes.
    tok_model = cost.serve_speculative_token_s(1e-6, 1.2e-6, 2)
    tok_perfect = cost.serve_speculative_token_s(
        1e-6, 1.2e-6, 2, accept_rate=1.0
    )
    assert tok_perfect < tok_model
