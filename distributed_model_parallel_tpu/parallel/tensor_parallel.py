"""Tensor parallelism over the `'model'` mesh axis — GSPMD style.

The reference has no tensor parallelism (SURVEY.md §2.3: absent); this
engine exists because the framework treats the `'model'` axis as
first-class (`runtime/mesh.py`). The design is deliberately NOT a
Megatron-style hand-written f/g collective pair: on TPU the idiomatic
mechanism is sharding ANNOTATIONS — place the Megatron layout on the
weight pytree and let XLA's SPMD partitioner insert the all-reduces the
f/g autograd functions hand-code on GPU:

    column-parallel (qkv / ffn-in):  W (D, kD)  -> P(None, 'model')
    row-parallel    (attn-out / ffn-out): W (kD, D) -> P('model', None)
    column-parallel bias (kD,)       -> P('model')
    everything else (LN, embeddings, head) replicated -> P()

The partitioner propagates: activations after a column-parallel matmul
are head/feature-sharded, the attention einsum runs head-sharded, and the
row-parallel matmul produces the partial sums whose psum over 'model' XLA
inserts exactly where Megatron's `g` function calls all_reduce. Gradient
collectives come out of the transpose automatically.

Composes with data parallelism on a (data, model) mesh: batch sharded
over 'data', weights over 'model', one jit program for both.

`MEGATRON_RULES` matches the transformer/BERT layer tree
(`models/transformer.py`, `models/bert.py`); `rules` accepts any
(path-regex, PartitionSpec) list for other model families.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_model_parallel_tpu.models.layers import Context, Layer
from distributed_model_parallel_tpu.parallel.data_parallel import (
    TrainState,
    _apply_input_transform,
    _cast_input,
    _metrics,
    _place_batch,
    aux_loss,
)
from distributed_model_parallel_tpu.runtime.mesh import data_axis_names
from distributed_model_parallel_tpu.training.checkpoint import _path_str
from distributed_model_parallel_tpu.training.metrics import cross_entropy
from distributed_model_parallel_tpu.training.optim import SGD

# Megatron sharding layout for the transformer block tree
# (models/transformer.py param paths: attn.qkv/attn.out, ffn.in/ffn.out).
MEGATRON_RULES: Tuple[Tuple[str, P], ...] = (
    (r"attn/qkv/w$", P(None, "model")),
    (r"attn/qkv/b$", P("model")),
    (r"attn/out/w$", P("model", None)),
    (r"ffn/in/w$", P(None, "model")),
    (r"ffn/in/b$", P("model")),
    (r"ffn/out/w$", P("model", None)),
)


def shard_specs(params, rules: Sequence[Tuple[str, P]]):
    """Pytree of PartitionSpecs for `params`: first rule whose regex
    matches the 'a/b/c' path wins; unmatched leaves are replicated."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_of(path, leaf):
        s = _path_str(path)
        for pat, spec in compiled:
            if pat.search(s):
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(spec_of, params)


@dataclasses.dataclass
class TensorParallelEngine:
    """GSPMD tensor(+data) parallelism: weights sharded over 'model' by
    path rules, batch sharded over 'data', XLA inserts the Megatron
    collectives. API-compatible with the other engines (train_step /
    eval_step / shard_batch / init_state)."""

    model: Layer
    optimizer: Any  # SGD | AdamW (init/update/state_shardings protocol)
    mesh: Mesh
    rules: Sequence[Tuple[str, P]] = MEGATRON_RULES
    donate: bool = True
    compute_dtype: Any = None  # see DataParallelEngine
    input_transform: Any = None  # see DataParallelEngine
    # Latency-hiding collective matmul (default off): run the opted-in
    # Megatron projections as chunked ppermute rings that overlap each
    # ICI hop with the partial dot already on hand, instead of leaving
    # the partitioner's monolithic collectives to the scheduler
    # (`ops/collective_matmul.py`). Same math (parity pinned at rtol
    # 1e-5 in tests/test_collective_matmul.py); between blocks the
    # residual stream rides sequence-sharded over 'model' (Megatron-SP).
    # Transformer-family models only: the policy reaches the qkv/out and
    # ffn in/out projections through `Context.matmul` -> layers.project.
    collective_matmul: bool = False
    # (remat lives at model construction — see DataParallelEngine note)

    def __post_init__(self):
        mesh = self.mesh
        # The mesh must carry every axis the rules shard over ('model'
        # for MEGATRON_RULES, 'expert' for EXPERT_RULES, both when the
        # rule sets are concatenated).
        needed = set()
        for _, spec in self.rules:
            for part in spec:
                if part is None:
                    continue
                parts = part if isinstance(part, tuple) else (part,)
                needed.update(parts)
        missing = needed - set(mesh.axis_names)
        if missing:
            raise ValueError(
                f"mesh is missing axes {sorted(missing)} required by the "
                f"sharding rules (mesh axes: {mesh.axis_names})"
            )
        self._repl = NamedSharding(mesh, P())
        self._batch = NamedSharding(mesh, P(data_axis_names(mesh)))
        self._matmul = None
        if self.collective_matmul:
            if "model" not in mesh.axis_names:
                raise ValueError(
                    "collective_matmul=True needs a 'model' mesh axis to "
                    "ring over (the Megatron projection axis); this mesh "
                    f"has {mesh.axis_names}"
                )
            from distributed_model_parallel_tpu.ops.collective_matmul import (
                CollectiveMatmul,
            )

            self._matmul = CollectiveMatmul(
                mesh=mesh, axis="model",
                batch_axes=tuple(
                    a for a in data_axis_names(mesh)
                    if a in mesh.axis_names
                ),
            )
        mm = self._matmul
        # Hand-rolled MoE exchange policy, set by ExpertParallelEngine
        # (dispatch="hierarchical") BEFORE delegating here; consumed by
        # models/moe.py via Context.expert_dispatch.
        ed = getattr(self, "_expert_dispatch", None)
        cdt = self.compute_dtype
        tf = self.input_transform
        model = self.model

        def train_step(ts: TrainState, inputs, labels, lr):
            rng = jax.random.fold_in(jax.random.PRNGKey(0), ts.step)
            inputs_c = _cast_input(
                _apply_input_transform(tf, inputs, ts.step, True), cdt
            )

            def loss_fn(params, model_state):
                logits, new_state = model.apply(
                    params, model_state, inputs_c,
                    Context(train=True, rng=rng, dtype=cdt, matmul=mm,
                            expert_dispatch=ed),
                )
                loss, m = self.loss_and_metrics(logits, labels)
                return loss + aux_loss(new_state), (new_state, m)

            (_, (new_state, m)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(ts.params, ts.model_state)
            params, opt_state = self.optimizer.update(
                ts.params, ts.opt_state, grads, lr
            )
            new_ts = TrainState(params, new_state, opt_state, ts.step + 1)
            return new_ts, m

        def eval_step(ts: TrainState, inputs, labels):
            inputs_c = _cast_input(
                _apply_input_transform(tf, inputs, ts.step, False), cdt
            )
            logits, _ = self.model.apply(
                ts.params, ts.model_state, inputs_c,
                Context(train=False, dtype=cdt, matmul=mm,
                        expert_dispatch=ed),
            )
            _, m = self.loss_and_metrics(logits, labels)
            return m

        # State shardings are fixed by the rules and the model structure
        # (known from an abstract trace of init); jit pins them in/out so
        # the partitioner keeps weights resident in their 'model' shards
        # across steps (no per-step resharding).
        key_aval = jax.ShapeDtypeStruct((2,), jnp.uint32)
        p_aval, s_aval = jax.eval_shape(self.model.init, key_aval)
        pspecs = self.param_specs(p_aval)
        # The spec seam: the PartitionSpec pytree for the whole
        # TrainState, exposed via `state_partition_specs` so checkpoint
        # tooling and tests can read the engine's layout without
        # reverse-engineering it from live arrays.
        self._state_pspecs = TrainState(
            pspecs,
            jax.tree_util.tree_map(lambda _: P(), s_aval),
            self.optimizer.state_shardings(pspecs, P()),
            P(),
        )
        param_sh = jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec),
            pspecs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self._state_sh = TrainState(
            param_sh,
            jax.tree_util.tree_map(lambda _: self._repl, s_aval),
            # Optimizer buffers shard exactly like their parameters
            # (each optimizer declares its own state layout).
            self.optimizer.state_shardings(param_sh, self._repl),
            self._repl,
        )
        sh = self._state_sh
        donate = (0,) if self.donate else ()
        self.train_step = jax.jit(
            train_step,
            in_shardings=(sh, self._batch, self._batch, None),
            out_shardings=(sh, self._repl),
            donate_argnums=donate,
        )
        self.eval_step = jax.jit(
            eval_step,
            in_shardings=(sh, self._batch, self._batch),
            out_shardings=self._repl,
        )

    def loss_and_metrics(self, logits, labels):
        """The differentiated loss + engine metrics for one batch —
        classification cross-entropy here; `ExpertParallelLMEngine`
        overrides with the token-level next-token loss. The scalar is
        what `train_step` differentiates (MoE aux penalties are added
        by the caller); metrics keep the `_metrics` psum contract."""
        ce = cross_entropy(logits, labels)
        return ce, _metrics(ce, logits, labels)

    def param_specs(self, p_aval):
        """PartitionSpec pytree for the parameters — rule-driven here;
        subclasses (FSDPEngine) override with shape-driven policies."""
        return shard_specs(p_aval, self.rules)

    def init_state(self, rng: jax.Array) -> TrainState:
        params, model_state = self.model.init(rng)
        opt_state = self.optimizer.init(params)
        ts = TrainState(
            params, model_state, opt_state, jnp.zeros((), jnp.int32)
        )
        return jax.device_put(ts, self._state_sh)

    # ---------------------------------------------- checkpoint canonical

    def to_canonical(self, ts: TrainState) -> TrainState:
        """Host-complete (numpy) TrainState for checkpointing. On a
        multi-host mesh this engine's params and optimizer moments are
        sharded across processes ('model' rules here, 'data' under
        FSDPEngine) and thus NOT fully addressable — a bare
        `jax.device_get` in `save_checkpoint` would crash exactly on the
        ZeRO-3/TP deployments that shard (VERDICT r4 weak #3). Leaves
        are all-gathered one at a time (`tree_to_host`), so the device
        transient is a single unsharded leaf. COLLECTIVE on a
        multi-process mesh: every process must call this together."""
        from distributed_model_parallel_tpu.training.checkpoint import (
            tree_to_host,
        )

        return tree_to_host(ts)

    def from_canonical(self, ts: TrainState) -> TrainState:
        """Place a canonical (host-complete) TrainState back into this
        engine's sharded runtime layout. All processes must pass the
        same values (restore_checkpoint broadcasts host-0's read).

        This is also the RESHARD seam (`checkpointing/restore.py`): the
        canonical form carries no mesh, so a checkpoint taken at one
        factorization (S=4 FSDP, a 2×2 dcn×ici hybrid, ...) lands here
        as full host arrays and this device_put re-slices them for the
        CURRENT mesh — elastic resize needs no format conversion."""
        return jax.device_put(ts, self._state_sh)

    def to_canonical_sharded(self, ts: TrainState) -> TrainState:
        """Sharded-checkpoint seam (`checkpointing/save.py`): this
        engine's runtime TrainState already has canonical TREE
        structure — `to_canonical` only gathers values to host — so the
        sharded save path persists the device-sharded leaves directly.
        Each process then writes only its addressable chunks and the
        per-leaf `process_allgather` of the legacy path is never
        reached (pinned in tests/test_checkpoint_sharded.py). Engines
        whose canonical form RESTRUCTURES state (pipeline stage-local
        packing) deliberately do not define this method; the trainer
        falls back with an actionable error."""
        return ts

    def state_partition_specs(self) -> TrainState:
        """The PartitionSpec pytree of the runtime TrainState layout —
        what a sharded checkpoint manifest records per leaf."""
        return self._state_pspecs

    def shard_batch(self, inputs, labels):
        return _place_batch((inputs, labels), self._batch)
