"""Serving-engine correctness pins (`distributed_model_parallel_tpu/serving/`).

The load-bearing pin: incremental KV-cache decode is LOGIT-IDENTICAL
(rtol 1e-5) to full-sequence dense recompute, for the replicated, TP
(declarative AND opted-in decode rings), and SP cache layouts, on
ragged batches whose slots sit at different positions, including a
recycled slot mid-run — the cache is an optimization, never an
approximation. The continuous-batching loop (admission, eviction, slot
recycling) is pinned end-to-end against dense greedy generation.

Full S=8 / slot-sweep cases are `slow` (tier-1 budget) with named
tier-1 twins, per the budget-rebalance convention.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_model_parallel_tpu.models.gpt import GPTConfig, gpt_lm
from distributed_model_parallel_tpu.models.layers import Context
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.serving.engine import ServingEngine
from distributed_model_parallel_tpu.serving.kv_cache import (
    KVCacheSpec,
    SlotAllocator,
    cache_pspecs,
    init_cache,
)
from distributed_model_parallel_tpu.serving.scheduler import (
    Request,
    Scheduler,
)

CFG = GPTConfig(
    vocab_size=61, dim=16, num_layers=2, num_heads=4, ffn_dim=32,
    max_position=16, dropout_rate=0.0,
)
# Ragged on purpose: three slots at three different positions.
PROMPT_LENS = (3, 5, 2)


@pytest.fixture(scope="module")
def dense():
    """Shared dense twin: params + a full-recompute next-token oracle."""
    model = gpt_lm(CFG)
    params, state = model.init(jax.random.PRNGKey(0))

    def next_logits(ids):
        ids = jnp.asarray(np.asarray(ids, np.int32))[None]
        logits, _ = model.apply(params, state, ids, Context(train=False))
        return np.asarray(logits[0, -1])

    return params, next_logits


def _prompts(seed=0):
    rng = np.random.RandomState(seed)
    return [
        rng.randint(1, CFG.vocab_size, size=n).astype(np.int32)
        for n in PROMPT_LENS
    ]


def _assert_decode_parity(eng, dense, *, steps=3, rtol=1e-5, atol=1e-6):
    """Prefill a ragged batch, decode `steps` mixed-position tokens,
    then RECYCLE slot 0 into a fresh prompt and keep decoding — every
    emitted logit row compared against dense full recompute."""
    params, next_logits = dense
    params = eng.place_params(params)
    prompts = _prompts()[: min(eng.num_slots, 3)]
    cache = eng.init_cache()
    tokens = np.zeros((eng.num_slots,), np.int32)
    active = np.zeros((eng.num_slots,), bool)
    seqs = {}

    def ingest(slot, prompt):
        nonlocal cache
        ids, length = eng.pad_prompt(prompt)
        cache, nl = eng.prefill(params, cache, ids, length,
                                jnp.int32(slot))
        np.testing.assert_allclose(
            np.asarray(nl), next_logits(prompt), rtol=rtol, atol=atol
        )
        tok = int(np.asarray(nl).argmax())
        seqs[slot] = list(prompt) + [tok]
        tokens[slot] = tok
        active[slot] = True

    def step_all(n):
        nonlocal cache
        for _ in range(n):
            cache, logits = eng.decode_step(
                params, cache, jnp.asarray(tokens), jnp.asarray(active)
            )
            logits = np.asarray(logits)
            for slot in seqs:
                np.testing.assert_allclose(
                    logits[slot], next_logits(seqs[slot]),
                    rtol=rtol, atol=atol,
                )
                tok = int(logits[slot].argmax())
                seqs[slot].append(tok)
                tokens[slot] = tok

    for slot, prompt in enumerate(prompts):
        ingest(slot, prompt)
    step_all(steps)
    # Recycle slot 0 mid-run: a fresh (shorter) prompt lands on a slot
    # whose cache tail still holds the evicted sequence's K/V — the
    # per-slot length must keep the stale tail invisible while the
    # OTHER slots decode on, positions untouched.
    ingest(0, _prompts(seed=9)[2])
    step_all(2)


# ------------------------------------------------------------- layouts


@pytest.mark.slow
def test_decode_matches_dense_replicated(dense):
    """`slow` (tier-1 budget); tier-1 twins:
    test_serving_paged.test_paged_decode_matches_dense_replicated (the
    same replicated decode-vs-dense parity through the paged pool —
    the serving hot path since ISSUE 15) + the tp/sp layout parities
    below."""
    eng = ServingEngine(CFG, num_slots=4, max_len=16, prefill_len=8)
    _assert_decode_parity(eng, dense)


@pytest.mark.parametrize("s", [2, 4])
def test_decode_matches_dense_tp(s, dense, devices):
    mesh = make_mesh(MeshSpec(data=1, model=s), devices=devices[:s])
    eng = ServingEngine(
        CFG, mesh, layout="tp", num_slots=4, max_len=16, prefill_len=8
    )
    _assert_decode_parity(eng, dense)


@pytest.mark.parametrize("s", [2, 4])
def test_decode_matches_dense_tp_collective_matmul(s, dense, devices):
    """Opted-in decode rings (DecodeCollectiveMatmul over the slot
    batch): same logits as the declarative TP lowering and the dense
    recompute. The HLO side of the claim (exact 4L(S-1) tagged permute
    chain, no monolithic all-gather) is pinned by the hlolint
    serve-decode-ring rule (tests/test_hlolint.py)."""
    mesh = make_mesh(MeshSpec(data=1, model=s), devices=devices[:s])
    eng = ServingEngine(
        CFG, mesh, layout="tp", num_slots=4, max_len=16, prefill_len=8,
        collective_matmul=True,
    )
    _assert_decode_parity(eng, dense)


# --------------------------------------- quantized decode (ISSUE 16)

# Documented parity budgets for the quantized decode projections
# (`ops/quant_matmul.py`; INTERNALS §17 carries the same numbers):
# bf16 = one rounding per operand, int8 = absmax/254 per operand with
# f32 accumulate. The atol floor covers near-zero logits (the head is
# untrained, logits sit in ~[-0.2, 0.2], so pure rtol is meaningless on
# the small ones). Prefill stays f32 either way.
QUANT_LOGIT_RTOL = {"bf16": 1e-2, "int8": 5e-2}
QUANT_LOGIT_ATOL = {"bf16": 2e-3, "int8": 1e-2}


def _quant_parity(eng, dense, mode):
    _assert_decode_parity(
        eng, dense,
        rtol=QUANT_LOGIT_RTOL[mode], atol=QUANT_LOGIT_ATOL[mode],
    )


@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_decode_matches_dense_quantized_replicated(mode, dense):
    """Opted-in quantized decode projections on the replicated layout:
    logits within the documented budget vs the f32 dense oracle,
    INCLUDING the mid-run recycled slot (`_assert_decode_parity`
    re-ingests slot 0 onto a cache tail the evicted sequence wrote —
    under int8 the fresh per-token scales must see only the live
    prefix)."""
    eng = ServingEngine(
        CFG, num_slots=4, max_len=16, prefill_len=8, compute_dtype=mode
    )
    _quant_parity(eng, dense, mode)


@pytest.mark.parametrize("s", [2, 4])
def test_decode_matches_dense_int8_tp_collective_matmul(
    s, dense, devices
):
    """int8 chunk GEMMs INSIDE the decode rings (`quant_dot` injected
    into the ag/rs fold bodies): the ppermute chain is byte-identical
    to f32 cm (pinned by serve-decode-ring + decode-quantized-matmul in
    the lint matrix); here the math — logits within budget across both
    ring sizes, recycled slot included."""
    mesh = make_mesh(MeshSpec(data=1, model=s), devices=devices[:s])
    eng = ServingEngine(
        CFG, mesh, layout="tp", num_slots=4, max_len=16, prefill_len=8,
        collective_matmul=True, compute_dtype="int8",
    )
    _quant_parity(eng, dense, "int8")


def test_decode_matches_dense_int8_tp_declarative(dense, devices):
    """int8 under declarative tp: GSPMD partitions the s8 x s8 dots and
    all-reduces DEQUANTIZED f32 partials (each shard dequantizes
    against its own weight-block scales before the sum)."""
    mesh = make_mesh(MeshSpec(data=1, model=2), devices=devices[:2])
    eng = ServingEngine(
        CFG, mesh, layout="tp", num_slots=4, max_len=16, prefill_len=8,
        compute_dtype="int8",
    )
    _quant_parity(eng, dense, "int8")


def test_int8_greedy_tokens_match_f32(dense):
    """Greedy decode under int8 picks the SAME tokens as the f32 dense
    oracle through the full continuous-batching loop (admission
    pressure + slot recycling): quantization may move logits within
    budget but must not flip the argmax on this config."""
    params, next_logits = dense
    prompts = _prompts() + _prompts(seed=3)[:2]
    requests = [
        Request(rid=i, prompt=p, max_new_tokens=4)
        for i, p in enumerate(prompts)
    ]
    eng = ServingEngine(
        CFG, num_slots=2, max_len=16, prefill_len=8,
        compute_dtype="int8",
    )
    sched = eng.run(eng.place_params(params), requests)
    assert len(sched.finished) == len(requests)
    by_rid = {f.rid: f for f in sched.finished}
    for i, prompt in enumerate(prompts):
        ids = list(prompt)
        expect = []
        for _ in range(4):
            tok = int(next_logits(ids).argmax())
            expect.append(tok)
            ids.append(tok)
        assert by_rid[i].tokens == expect, f"request {i} diverged"


def test_int8_sp_layout_rejected(devices):
    mesh = make_mesh(MeshSpec(data=1, seq=2), devices=devices[:2])
    with pytest.raises(ValueError, match="int8"):
        ServingEngine(
            CFG, mesh, layout="sp", num_slots=4, max_len=16,
            prefill_len=8, compute_dtype="int8",
        )


@pytest.mark.parametrize("s", [2, 4])
def test_decode_matches_dense_sp(s, dense, devices):
    """Sequence-sharded cache: ring-attention prefill over 'seq', the
    online-softmax partial-attention merge at decode."""
    mesh = make_mesh(MeshSpec(data=1, seq=s), devices=devices[:s])
    eng = ServingEngine(
        CFG, mesh, layout="sp", num_slots=4, max_len=16, prefill_len=8
    )
    _assert_decode_parity(eng, dense)


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["tp", "sp"])
def test_decode_matches_dense_s8(layout, devices):
    """Full-mesh S=8 sweep of both sharded layouts (an 8-head config —
    the tp layout needs heads % S == 0 — with its own dense oracle).
    `slow` (tier-1 budget); tier-1 twins:
    test_decode_matches_dense_tp[2|4] and
    test_decode_matches_dense_sp[2|4] pin the same parity on the same
    code path at S in {2,4}."""
    import dataclasses

    cfg8 = dataclasses.replace(CFG, num_heads=8)
    model = gpt_lm(cfg8)
    params, state = model.init(jax.random.PRNGKey(1))

    def next_logits(ids):
        ids = jnp.asarray(np.asarray(ids, np.int32))[None]
        logits, _ = model.apply(params, state, ids, Context(train=False))
        return np.asarray(logits[0, -1])

    mesh = make_mesh(
        MeshSpec(data=1, **{("model" if layout == "tp" else "seq"): 8}),
        devices=devices,
    )
    eng = ServingEngine(
        cfg8, mesh, layout=layout, num_slots=8, max_len=16,
        prefill_len=8,
        collective_matmul=(layout == "tp"),
    )
    _assert_decode_parity(eng, (params, next_logits))


@pytest.mark.slow
@pytest.mark.parametrize("num_slots", [2, 6, 8])
def test_decode_parity_slot_sweep(num_slots, dense):
    """Replicated-layout slot-count sweep (capacity edges: minimum,
    odd-ish, full). `slow` (tier-1 budget); tier-1 twins:
    test_decode_matches_dense_replicated (num_slots=4, same code path)
    and test_run_recycles_slots_and_matches_dense_greedy (num_slots=2
    under admission pressure)."""
    eng = ServingEngine(
        CFG, num_slots=num_slots, max_len=16, prefill_len=8
    )
    _assert_decode_parity(eng, dense)


# ------------------------------------------- continuous batching loop


def test_run_recycles_slots_and_matches_dense_greedy(dense):
    """5 requests through 2 slots: admission pressure forces slot
    recycling, and every finished sequence's greedy tokens must equal
    the dense model's greedy continuation of its own prompt."""
    params, next_logits = dense
    prompts = _prompts() + _prompts(seed=3)[:2]
    requests = [
        Request(rid=i, prompt=p, max_new_tokens=4)
        for i, p in enumerate(prompts)
    ]
    eng = ServingEngine(CFG, num_slots=2, max_len=16, prefill_len=8)
    sched = eng.run(eng.place_params(params), requests)
    assert len(sched.finished) == len(requests)
    assert sched.slots.free_slots == 2  # every slot recycled
    by_rid = {f.rid: f for f in sched.finished}
    for i, prompt in enumerate(prompts):
        ids = list(prompt)
        expect = []
        for _ in range(4):
            tok = int(next_logits(ids).argmax())
            expect.append(tok)
            ids.append(tok)
        assert by_rid[i].tokens == expect, f"request {i} diverged"
    report = sched.latency_report()
    assert report["requests"] == 5
    assert report["generated_tokens"] == 20
    assert report["decode_p50_ms"] is not None


def test_run_respects_eos_and_capacity(dense):
    params, _ = dense
    eng = ServingEngine(CFG, num_slots=2, max_len=16, prefill_len=8)
    placed = eng.place_params(params)
    # max_new_tokens=1 finishes at admission (prefill-only request).
    sched = eng.run(placed, [
        Request(rid="one", prompt=_prompts()[0], max_new_tokens=1)
    ])
    first_tok = sched.finished[0].tokens[0]
    assert len(sched.finished[0].tokens) == 1
    # eos stops generation before max_new_tokens: declare the token the
    # model greedily emits first as eos and ask for 5.
    sched = eng.run(placed, [
        Request(rid="eos", prompt=_prompts()[0], max_new_tokens=5,
                eos_id=first_tok)
    ])
    assert sched.finished[0].tokens == [first_tok]
    # A slot can never outgrow max_len: a long prompt stops early.
    long_prompt = _prompts()[0][:3]
    sched = eng.run(placed, [
        Request(rid="cap", prompt=long_prompt, max_new_tokens=99)
    ])
    f = sched.finished[0]
    assert len(f.tokens) + f.prompt_len == eng.max_len


# --------------------------------------------------- cache + scheduler


def test_slot_allocator_recycles_lowest_free():
    alloc = SlotAllocator(2)
    a, b = alloc.alloc(), alloc.alloc()
    assert (a, b) == (0, 1)
    with pytest.raises(RuntimeError, match="slots are live"):
        alloc.alloc()
    alloc.free(0)
    with pytest.raises(ValueError, match="not live"):
        alloc.free(0)  # double free
    assert alloc.alloc() == 0  # lowest free, deterministic traces


def test_scheduler_iteration_level_lifecycle():
    sched = Scheduler(num_slots=1, max_len=16)
    sched.submit(Request(rid="a", prompt=np.array([1, 2])))
    sched.submit(Request(rid="b", prompt=np.array([3])))
    assert sched.can_admit()
    seq = sched.admit()
    assert seq.slot == 0 and not sched.can_admit()  # full
    seq.t_first_token = seq.t_admit
    seq.generated.append(7)
    fin = sched.finish(0)
    assert fin.rid == "a" and fin.tokens == [7]
    assert sched.can_admit()  # slot recycled, "b" admissible
    assert sched.admit().request.rid == "b"
    with pytest.raises(ValueError, match="no room"):
        sched.submit(Request(rid="c", prompt=np.zeros(16)))


def test_cache_spec_and_layout_validation(devices):
    spec = KVCacheSpec(
        num_layers=2, num_slots=4, max_len=16, num_heads=3, head_dim=4
    )
    cache = init_cache(spec)
    assert cache["k"].shape == (2, 4, 16, 3, 4)
    assert cache["lengths"].dtype == jnp.int32
    mesh = make_mesh(MeshSpec(data=1, model=2), devices=devices[:2])
    with pytest.raises(ValueError, match="num_heads"):
        spec.validate("tp", mesh)  # 3 heads over 2 shards
    smesh = make_mesh(MeshSpec(data=1, seq=8), devices=devices)
    with pytest.raises(ValueError, match="max_len"):
        KVCacheSpec(
            num_layers=2, num_slots=4, max_len=12, num_heads=4,
            head_dim=4,
        ).validate("sp", smesh)
    with pytest.raises(ValueError, match="layout"):
        spec.validate("paged", None)
    assert cache_pspecs("tp")["k"] != cache_pspecs("sp")["k"]


def test_run_allows_duplicate_rids(dense):
    """rids are caller-owned labels, not keys: two requests sharing a
    rid must both run to completion with their own timing legs
    (regression: a rid-keyed submit-time dict crashed admission)."""
    params, _ = dense
    eng = ServingEngine(CFG, num_slots=1, max_len=16, prefill_len=8)
    sched = eng.run(eng.place_params(params), [
        Request(rid="dup", prompt=_prompts()[0], max_new_tokens=2),
        Request(rid="dup", prompt=_prompts()[1], max_new_tokens=2),
    ])
    assert [f.rid for f in sched.finished] == ["dup", "dup"]
    assert all(len(f.tokens) == 2 for f in sched.finished)
    assert all(f.prefill_s >= 0 for f in sched.finished)


def test_engine_construction_guards(devices):
    with pytest.raises(ValueError, match="requires layout='tp'"):
        ServingEngine(CFG, collective_matmul=True)
    # tp shards the slot batch (logits stay slot-sharded in the
    # compiled step) even WITHOUT the rings: fail at construction, not
    # with an opaque pjit error at trace time.
    dmesh = make_mesh(MeshSpec(data=1, model=2), devices=devices[:2])
    with pytest.raises(ValueError, match="num_slots"):
        ServingEngine(
            CFG, dmesh, layout="tp", num_slots=3, max_len=16,
            prefill_len=8,
        )
    with pytest.raises(ValueError, match="position table"):
        ServingEngine(CFG, max_len=32)
    with pytest.raises(ValueError, match="prefill_len"):
        ServingEngine(CFG, max_len=16, prefill_len=32)
    mesh = make_mesh(MeshSpec(data=1, seq=4), devices=devices[:4])
    with pytest.raises(ValueError, match="prefill_len"):
        ServingEngine(
            CFG, mesh, layout="sp", max_len=16, prefill_len=6
        )
    tmesh = make_mesh(MeshSpec(data=1, model=4), devices=devices[:4])
    with pytest.raises(ValueError, match="num_slots"):
        ServingEngine(
            CFG, tmesh, layout="tp", num_slots=3, max_len=16,
            prefill_len=8, collective_matmul=True,
        )
    eng = ServingEngine(CFG, num_slots=2, max_len=16, prefill_len=8)
    with pytest.raises(ValueError, match="prompt length"):
        eng.pad_prompt(np.arange(9))
    with pytest.raises(ValueError, match="prompt length"):
        eng.pad_prompt(np.zeros((0,)))


def test_bf16_decode_runs_finite():
    """Mixed-precision serving smoke: bf16 activations + bf16 cache,
    logits still f32 (head contract) and finite."""
    eng = ServingEngine(
        CFG, num_slots=2, max_len=16, prefill_len=8,
        compute_dtype=jnp.bfloat16,
    )
    params = eng.init_params(jax.random.PRNGKey(0))
    cache = eng.init_cache()
    assert cache["k"].dtype == jnp.bfloat16
    ids, length = eng.pad_prompt(_prompts()[0])
    cache, nl = eng.prefill(params, cache, ids, length, jnp.int32(0))
    assert nl.dtype == jnp.float32
    cache, logits = eng.decode_step(
        params, cache,
        jnp.asarray([int(np.asarray(nl).argmax()), 0], jnp.int32),
        jnp.asarray([True, False]),
    )
    assert bool(jnp.all(jnp.isfinite(logits)))
