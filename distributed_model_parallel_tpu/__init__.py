"""distributed_model_parallel_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA re-design of the capabilities of
`timmywanttolearn/distributed_model_parallel` (reference mounted at
/root/reference): data-parallel training (the scatter / replicate /
parallel_apply / gather path of `torch.nn.DataParallel` and the bucketed
DDP Reducer, re-expressed as XLA collectives over a named device mesh),
pipeline model parallelism (the reference's autograd-transparent
`dist.send/recv` stage transport, re-expressed as `lax.ppermute` under
`shard_map` with static shapes), tensor, sequence/context, and expert
(MoE) parallelism, the model zoo (MobileNetV2 and variants, ResNet,
BERT, a GPT-style causal LM, MoE transformer blocks), the dataset
collection, and the trainer surface (SGD / AdamW + cosine decay + warmup,
acc1/acc5 metrics, best-acc checkpointing with resume, elastic
restarts). Mechanics: INTERNALS.md; numbers: RESULTS.md.

Package layout:
  runtime/   mesh + multi-host bootstrap (replaces dist.init_process_group)
  models/    pure-functional model zoo (param/state pytrees, NHWC)
  ops/       attention cores: XLA, ring / Ulysses sequence-parallel,
             Pallas flash kernel
  parallel/  DP / DDP / FSDP / pipeline / tensor-parallel /
             sequence-parallel / expert-parallel engines
  serving/   autoregressive inference: slot-paged KV cache, continuous
             batching, decode-time TP rings (INTERNALS.md §9)
  data/      dataset collection + per-host sharded, prefetching input
             pipeline
  training/  trainer loops, optimizer/schedule, metrics, checkpointing,
             elastic restart driver
  native/    C++ runtime components (input-pipeline hot loop)
"""

__version__ = "0.1.0"

from distributed_model_parallel_tpu.runtime.mesh import (  # noqa: F401
    MeshSpec,
    make_mesh,
    local_mesh,
)
