"""ServingEngine: prefill/decode split with continuous batching over
the slot-paged KV cache.

One engine = one compiled prefill program + ONE compiled decode program
that advances EVERY cache slot a single token per call, whatever
position each slot sits at (the mixed-position batch is the point of
continuous batching — Orca, PAPERS.md). The host loop
(`ServingEngine.run`) does iteration-level scheduling: admit waiting
requests into free slots (prefill), one decode step for the active
set, evict finished sequences and recycle their slots.

Parameters are the dense `models/gpt.gpt_lm` pytree — the SAME tree the
TP and SP-LM training engines train (`TrainState.params` serves
directly), placed per layout:

  replicated — params + cache replicated; plain jit.
  tp         — params sharded by `MEGATRON_RULES` on the 'model' axis
               (the TensorParallelEngine layout), cache head-sharded;
               GSPMD inserts the decode collectives — or, with
               `collective_matmul=True`, the opted-in projections ride
               chunked ppermute rings over the slot batch
               (`serving/decode.DecodeCollectiveMatmul`): exactly
               4·L·(S-1) permutes per decode step and no monolithic
               all-gather on the opted-in path (hlolint
               `serve-decode-ring`).
  sp         — cache position-sharded over 'seq'; decode merges
               per-shard partial attention via the online-softmax
               recurrence, and long prefill reuses the training ring
               (`ops/ring_attention.py`) over the same axis.

All three are logit-identical to full-sequence recompute at rtol 1e-5
(tests/test_serving.py) — the cache is an optimization, never an
approximation.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.models.gpt import (
    GPTConfig,
    decoder_blocks,
    gpt_lm,
    head_apply,
)
from distributed_model_parallel_tpu.observability.metrics import (
    get_metrics,
)
from distributed_model_parallel_tpu.observability.trace import get_tracer
from distributed_model_parallel_tpu.ops.attention import (
    dot_product_attention,
)
from distributed_model_parallel_tpu.ops.ring_attention import (
    ring_attention,
)
from distributed_model_parallel_tpu.runtime.compat import shard_map
from distributed_model_parallel_tpu.serving.decode import (
    CacheAttention,
    DecodeCollectiveMatmul,
    PrefillRecorder,
    SeqShardedCacheAttention,
    decode_stem,
    prefill_stem,
)
from distributed_model_parallel_tpu.serving.kv_cache import (
    KVCacheSpec,
    cache_pspecs,
    cache_shardings,
    init_cache,
)
from distributed_model_parallel_tpu.serving.scheduler import (
    Request,
    Scheduler,
)


@dataclasses.dataclass
class ServingEngine:
    """Autoregressive serving over `models/gpt` configs (module doc)."""

    cfg: GPTConfig
    mesh: Optional[Mesh] = None
    layout: str = "replicated"  # replicated | tp | sp
    num_slots: int = 4
    max_len: Optional[int] = None  # cache positions; <= cfg.max_position
    prefill_len: Optional[int] = None  # padded prompt length; <= max_len
    # Latency-hiding decode rings over 'model' (tp layout only):
    # `serving/decode.DecodeCollectiveMatmul`. Default off, same math.
    collective_matmul: bool = False
    compute_dtype: Any = None  # activation dtype; None = f32
    donate: bool = True  # donate the cache buffers step-over-step

    def __post_init__(self):
        cfg = self.cfg
        self.max_len = self.max_len or cfg.max_position
        self.prefill_len = self.prefill_len or self.max_len
        if self.max_len > cfg.max_position:
            raise ValueError(
                f"max_len {self.max_len} exceeds the position table "
                f"(cfg.max_position={cfg.max_position})"
            )
        if not 1 <= self.prefill_len <= self.max_len:
            raise ValueError(
                f"prefill_len {self.prefill_len} must be in "
                f"[1, max_len={self.max_len}]"
            )
        if cfg.dim % cfg.num_heads:
            raise ValueError(
                f"dim {cfg.dim} not divisible by heads {cfg.num_heads}"
            )
        cache_dtype = self.compute_dtype or jnp.float32
        self.spec = KVCacheSpec(
            num_layers=cfg.num_layers, num_slots=self.num_slots,
            max_len=self.max_len, num_heads=cfg.num_heads,
            head_dim=cfg.dim // cfg.num_heads, dtype=cache_dtype,
        )
        self.spec.validate(self.layout, self.mesh)
        if self.collective_matmul and self.layout != "tp":
            raise ValueError(
                "collective_matmul=True rings decode projections over "
                "the 'model' axis; it requires layout='tp' "
                f"(got {self.layout!r})"
            )
        self._mm = None
        if self.layout == "tp":
            s = self.mesh.shape["model"]
            if self.num_slots % s:
                # The decode step keeps logits slot-sharded over
                # 'model' (no final gather inside the program), and the
                # opted-in rings chunk the slot batch — both need the
                # slot axis divisible. Fail here, not at trace time.
                raise ValueError(
                    f"tp layout shards the slot batch over 'model': "
                    f"num_slots {self.num_slots} not divisible by {s} "
                    "shards"
                )
            if self.collective_matmul:
                if s < 2:
                    raise ValueError(
                        "collective_matmul=True needs a 'model' axis "
                        ">= 2 to ring over (a 1-shard ring is a plain "
                        "dot)"
                    )
                for n, label in (
                    (self.num_slots, "num_slots"),
                    (3 * cfg.dim, "qkv width (3*dim)"),
                    (cfg.dim, "dim"),
                    (cfg.ffn_dim, "ffn_dim"),
                ):
                    if n % s:
                        raise ValueError(
                            f"decode collective_matmul: {label} ({n}) "
                            f"must be divisible by the {s}-way 'model' "
                            "axis"
                        )
                self._mm = DecodeCollectiveMatmul(
                    mesh=self.mesh, axis="model"
                )
        if self.layout == "sp":
            s = self.mesh.shape["seq"]
            if self.prefill_len % s:
                raise ValueError(
                    f"sp prefill shards the prompt over 'seq': "
                    f"prefill_len {self.prefill_len} not divisible by "
                    f"{s} shards"
                )
        # Dense-parameter twin: init + checkpoint interop with the
        # training engines (identical pytree).
        self._full = gpt_lm(cfg)
        self._blocks_state = {
            str(i): {} for i in range(cfg.num_layers)
        }
        self._build_shardings()
        self._build_steps()

    # ------------------------------------------------------- shardings

    def _build_shardings(self):
        mesh = self.mesh
        if mesh is None:
            self._param_sh = self._cache_sh = self._repl = None
            return
        self._repl = NamedSharding(mesh, P())
        if self.layout == "tp":
            from distributed_model_parallel_tpu.parallel.tensor_parallel import (  # noqa: E501
                MEGATRON_RULES,
                shard_specs,
            )

            key_aval = jax.ShapeDtypeStruct((2,), jnp.uint32)
            p_aval, _ = jax.eval_shape(self._full.init, key_aval)
            self._param_sh = jax.tree_util.tree_map(
                lambda spec: NamedSharding(mesh, spec),
                shard_specs(p_aval, MEGATRON_RULES),
                is_leaf=lambda x: isinstance(x, P),
            )
        else:
            self._param_sh = self._repl
        self._cache_sh = cache_shardings(mesh, self.layout)

    # ----------------------------------------------------------- steps

    def _build_steps(self):
        cfg = self.cfg
        cdt = self.compute_dtype
        num_slots = self.num_slots
        max_len = self.max_len
        p_len = self.prefill_len
        blocks_state = self._blocks_state
        mm = self._mm
        ctx = L.Context(train=False, dtype=cdt)

        def run_blocks(params, x, attention_fn, block_ctx):
            blocks = L.sequential(*decoder_blocks(cfg, attention_fn))
            (h, _), _ = blocks.apply(
                params["blocks"], blocks_state, x, block_ctx
            )
            return h

        # --- decode: one token for every slot, mixed positions -------
        def decode_step(params, cache, tokens, active):
            positions = cache["lengths"]
            rec = CacheAttention(
                cache["k"], cache["v"], positions, active
            )
            h = decode_stem(
                params["stem"], tokens,
                jnp.clip(positions, 0, cfg.max_position - 1), cdt,
            )
            mask = jnp.ones((num_slots, 1), jnp.bool_)
            h = run_blocks(
                params, (h, mask), rec,
                dataclasses.replace(ctx, matmul=mm),
            )
            logits = head_apply(params["head"], h)[:, 0, :]
            new_lengths = jnp.where(active, positions + 1, positions)
            new_cache = {
                "k": rec.k, "v": rec.v, "lengths": new_lengths,
            }
            return new_cache, logits

        def sp_decode_step(params, cache, tokens, active):
            positions = cache["lengths"]
            rec = SeqShardedCacheAttention(
                cache["k"], cache["v"], positions, active, axis="seq"
            )
            h = decode_stem(
                params["stem"], tokens,
                jnp.clip(positions, 0, cfg.max_position - 1), cdt,
            )
            mask = jnp.ones((num_slots, 1), jnp.bool_)
            h = run_blocks(params, (h, mask), rec, ctx)
            logits = head_apply(params["head"], h)[:, 0, :]
            new_lengths = jnp.where(active, positions + 1, positions)
            new_cache = {
                "k": rec.k, "v": rec.v, "lengths": new_lengths,
            }
            return new_cache, logits

        # --- prefill: one padded prompt into one slot ----------------
        def prefill_step(params, cache, ids, length, slot):
            mask = jnp.arange(p_len)[None, :] < length  # (1, P)
            h = prefill_stem(params["stem"], ids, 0, cdt)
            rec = PrefillRecorder(
                partial(dot_product_attention, causal=True)
            )
            h = run_blocks(params, (h, mask), rec, ctx)
            logits = head_apply(params["head"], h)  # (1, P, V) f32
            next_logits = lax.dynamic_index_in_dim(
                logits[0], length - 1, axis=0, keepdims=False
            )
            k_stack = jnp.stack([k[0] for k in rec.ks])  # (L,P,H,Dh)
            v_stack = jnp.stack([v[0] for v in rec.vs])
            pad = ((0, 0), (0, max_len - p_len), (0, 0), (0, 0))
            new_cache = {
                "k": lax.dynamic_update_slice(
                    cache["k"],
                    jnp.pad(k_stack, pad)[:, None].astype(
                        cache["k"].dtype
                    ),
                    (0, slot, 0, 0, 0),
                ),
                "v": lax.dynamic_update_slice(
                    cache["v"],
                    jnp.pad(v_stack, pad)[:, None].astype(
                        cache["v"].dtype
                    ),
                    (0, slot, 0, 0, 0),
                ),
                "lengths": cache["lengths"].at[slot].set(length),
            }
            return new_cache, next_logits

        def sp_prefill_step(params, cache, ids, length, slot):
            s = self.mesh.shape["seq"]
            tl = p_len // s
            chunk = max_len // s
            idx = lax.axis_index("seq")
            offset = idx * tl
            gmask = (offset + jnp.arange(tl))[None, :] < length
            h = prefill_stem(params["stem"], ids, offset, cdt)
            rec = PrefillRecorder(
                partial(ring_attention, axis_name="seq", causal=True)
            )
            h = run_blocks(params, (h, gmask), rec, ctx)
            logits = head_apply(params["head"], h)  # (1, tl, V)
            # The next-token logits live on the shard owning global
            # position length-1; psum broadcasts that one row.
            owner = (length - 1) // tl
            li = jnp.clip(length - 1 - offset, 0, tl - 1)
            row = jnp.where(
                idx == owner,
                lax.dynamic_index_in_dim(
                    logits[0], li, axis=0, keepdims=False
                ),
                jnp.zeros((cfg.vocab_size,), jnp.float32),
            )
            next_logits = lax.psum(row, "seq")
            # Each cache shard owns positions [idx*chunk, (idx+1)*chunk);
            # gather the prompt K/V once, pad to max_len, keep my chunk.
            k_stack = jnp.stack([k[0] for k in rec.ks])  # (L,tl,H,Dh)
            v_stack = jnp.stack([v[0] for v in rec.vs])
            pad = ((0, 0), (0, max_len - p_len), (0, 0), (0, 0))

            def my_chunk(stack):
                full = jnp.pad(
                    lax.all_gather(stack, "seq", axis=1, tiled=True),
                    pad,
                )
                return lax.dynamic_slice_in_dim(
                    full, idx * chunk, chunk, axis=1
                )

            new_cache = {
                "k": lax.dynamic_update_slice(
                    cache["k"],
                    my_chunk(k_stack)[:, None].astype(cache["k"].dtype),
                    (0, slot, 0, 0, 0),
                ),
                "v": lax.dynamic_update_slice(
                    cache["v"],
                    my_chunk(v_stack)[:, None].astype(cache["v"].dtype),
                    (0, slot, 0, 0, 0),
                ),
                "lengths": cache["lengths"].at[slot].set(length),
            }
            return new_cache, next_logits

        donate = (1,) if self.donate else ()  # the cache argument
        if self.layout == "sp":
            mesh = self.mesh
            cspec = cache_pspecs("sp")
            self.decode_step = jax.jit(
                shard_map(
                    sp_decode_step, mesh=mesh,
                    in_specs=(P(), cspec, P(), P()),
                    out_specs=(cspec, P()),
                    check_vma=False,
                ),
                donate_argnums=donate,
            )
            self.prefill = jax.jit(
                shard_map(
                    sp_prefill_step, mesh=mesh,
                    in_specs=(P(), cspec, P(None, "seq"), P(), P()),
                    out_specs=(cspec, P()),
                    check_vma=False,
                ),
                donate_argnums=donate,
            )
        elif self.mesh is not None:
            # replicated-with-mesh and tp: declarative placement; the
            # opted-in tp rings enter via ctx.matmul inside decode_step.
            logits_sh = (
                NamedSharding(self.mesh, P("model", None))
                if self.layout == "tp" else self._repl
            )
            self.decode_step = jax.jit(
                decode_step,
                in_shardings=(
                    self._param_sh, self._cache_sh, self._repl,
                    self._repl,
                ),
                out_shardings=(self._cache_sh, logits_sh),
                donate_argnums=donate,
            )
            self.prefill = jax.jit(
                prefill_step,
                in_shardings=(
                    self._param_sh, self._cache_sh, self._repl,
                    self._repl, self._repl,
                ),
                out_shardings=(self._cache_sh, self._repl),
                donate_argnums=donate,
            )
        else:
            self.decode_step = jax.jit(
                decode_step, donate_argnums=donate
            )
            self.prefill = jax.jit(
                prefill_step, donate_argnums=donate
            )

    # ------------------------------------------------------------ state

    def init_params(self, rng: jax.Array):
        """Fresh dense-twin parameters (`gpt_lm(cfg)` pytree — a trained
        TrainState.params from the TP / SP-LM engines drops in via
        `place_params`)."""
        params, _ = self._full.init(rng)
        return self.place_params(params)

    def place_params(self, params):
        """Place an existing dense-layout param pytree (a checkpoint or
        a training engine's canonical params) into this layout."""
        if self._param_sh is None:
            return params
        return jax.device_put(params, self._param_sh)

    def init_cache(self) -> dict:
        cache = init_cache(self.spec)
        if self._cache_sh is None:
            return cache
        return jax.device_put(cache, self._cache_sh)

    # ---------------------------------------------------------- serving

    def pad_prompt(self, prompt: np.ndarray):
        """(ids (1, prefill_len) int32, length int32) for one prompt."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if not 1 <= prompt.size <= self.prefill_len:
            raise ValueError(
                f"prompt length {prompt.size} must be in "
                f"[1, prefill_len={self.prefill_len}]"
            )
        ids = np.zeros((1, self.prefill_len), np.int32)
        ids[0, : prompt.size] = prompt
        return jnp.asarray(ids), jnp.int32(prompt.size)

    def run(self, params, requests: Sequence[Request]) -> Scheduler:
        """Offline continuous batching: drive the request set to
        completion (greedy decoding), returning the Scheduler with its
        per-request `finished` records and `latency_report()`."""
        tracer = get_tracer()
        mx = get_metrics()  # per-call histograms; one branch when off
        sched = Scheduler(self.num_slots, self.max_len)
        for r in requests:
            if r.prompt.size > self.prefill_len:
                raise ValueError(
                    f"request {r.rid!r}: prompt length {r.prompt.size} "
                    f"exceeds prefill_len {self.prefill_len}"
                )
            sched.submit(r)
        cache = self.init_cache()
        tokens = np.zeros((self.num_slots,), np.int32)
        active = np.zeros((self.num_slots,), bool)
        while sched.has_work():
            # Admission: prefill waiting requests into free slots.
            while sched.can_admit():
                seq = sched.admit()
                ids, length = self.pad_prompt(seq.request.prompt)
                t0 = tracer.now()
                with tracer.span("prefill", rid=repr(seq.request.rid),
                                 slot=seq.slot):
                    cache, next_logits = self.prefill(
                        params, cache, ids, length, jnp.int32(seq.slot)
                    )
                    tok = int(np.asarray(next_logits).argmax())
                seq.t_first_token = tracer.now()
                if mx.enabled:
                    mx.observe(
                        "serve_prefill_s", seq.t_first_token - t0
                    )
                    # The prefill produced this request's FIRST token;
                    # decode steps count theirs in record_decode_step,
                    # so the counter totals to the report's
                    # generated_tokens exactly.
                    mx.inc("serve_tokens_total", 1)
                seq.generated.append(tok)
                tokens[seq.slot] = tok
                active[seq.slot] = True
                if seq.done(self.max_len):
                    sched.finish(seq.slot)
                    active[seq.slot] = False
            if not active.any():
                continue
            # One decode step for the whole mixed-position batch.
            n_active = int(active.sum())
            t0 = tracer.now()
            with tracer.span("decode_step", active=n_active):
                cache, logits = self.decode_step(
                    params, cache, jnp.asarray(tokens),
                    jnp.asarray(active),
                )
                logits_np = np.asarray(logits)
            dt = tracer.now() - t0
            sched.record_decode_step(n_active)
            tracer.counter("batch_occupancy", n_active)
            if mx.enabled:
                mx.observe("serve_decode_step_s", dt)
            for slot, seq in list(sched.active.items()):
                tok = int(logits_np[slot].argmax())
                seq.generated.append(tok)
                seq.token_times.append(dt)
                tokens[slot] = tok
                if seq.done(self.max_len):
                    sched.finish(slot)
                    active[slot] = False
        return sched


__all__ = ["ServingEngine"]
