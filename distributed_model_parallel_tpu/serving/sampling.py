"""Token selection for the serving engine: greedy (the bit-stable
default) or temperature / top-k / top-p sampling with a per-slot PRNG
lane.

Sampling runs on the HOST over the logits row the decode step already
fetched (the engine reads every step's logits to feed the next token
back in, so there is no extra device round-trip), which keeps it
layout-independent — replicated, TP and SP serve the same math.

Determinism contract:

* **Greedy is bit-stable.** `temperature == 0` (the default) never
  touches an RNG and picks `argmax` exactly as the pre-sampling engine
  did — a greedy run's token ids are byte-identical before and after
  this module existed (pinned in tests/test_serving_paged.py).
* **Per-slot PRNG lane.** Each cache slot owns one counter-based
  Philox stream keyed `(seed, slot)`; a slot's draws depend only on
  how many tokens IT has sampled, never on the other slots' schedule,
  so a fixed (seed, admission order) trace reproduces its tokens
  exactly even as the continuous batch around it changes shape.

Filter order follows the common serving convention: logits / T, keep
the top-k, then the top-p nucleus (smallest prefix of the remaining
probability mass reaching `top_p`; the most-probable token always
survives), renormalize, draw.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Decode-time sampling surface (`cli/serve.py`
    --temperature/--top-k/--top-p). temperature 0 = greedy."""

    temperature: float = 0.0
    top_k: int = 0  # 0 = no top-k cut
    top_p: float = 1.0
    seed: int = 0

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}"
            )
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0 < self.top_p <= 1:
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p}"
            )
        if self.temperature == 0 and (
            self.top_k > 0 or self.top_p < 1
        ):
            raise ValueError(
                "top_k/top_p filter a SAMPLING distribution; with "
                "temperature 0 (greedy) they would silently do "
                "nothing — set temperature > 0"
            )

    @property
    def greedy(self) -> bool:
        return self.temperature == 0


class SlotSampler:
    """One Philox lane per cache slot (module docstring)."""

    def __init__(self, cfg: Optional[SamplingConfig], num_slots: int):
        self.cfg = cfg or SamplingConfig()
        self._lanes: List[np.random.Generator] = [
            np.random.Generator(
                np.random.Philox(key=[self.cfg.seed, slot])
            )
            for slot in range(num_slots)
        ]

    def pick(self, logits: np.ndarray, slot: int) -> int:
        """Next token id for `slot` from its logits row."""
        cfg = self.cfg
        if cfg.greedy:
            return int(np.argmax(logits))
        z = np.asarray(logits, np.float64) / cfg.temperature
        order = np.argsort(z)[::-1]  # descending
        if cfg.top_k:
            order = order[: cfg.top_k]
        z = z[order]
        probs = np.exp(z - z.max())
        probs /= probs.sum()
        if cfg.top_p < 1:
            keep = int(np.searchsorted(
                np.cumsum(probs), cfg.top_p, side="left"
            )) + 1  # the argmax always survives
            order = order[:keep]
            probs = probs[:keep] / probs[:keep].sum()
        draw = self._lanes[slot].random()
        idx = int(np.searchsorted(np.cumsum(probs), draw, side="right"))
        return int(order[min(idx, len(order) - 1)])

    # ------------------------------------------- speculative surface
    # The lossless rejection rule (serving/speculative.py, Leviathan
    # ICML'23) needs the FULL filtered distributions of both models and
    # raw lane uniforms, not just a draw — `dist` is `pick`'s filter
    # pipeline factored out (same temperature/top-k/top-p order), and
    # `uniform`/`sample_dist` consume the SAME per-slot Philox lane, so
    # a slot's draws still depend only on how many numbers IT drew.

    def dist(self, logits: np.ndarray) -> np.ndarray:
        """The filtered, renormalized distribution `pick` samples from,
        as a dense vocab-length float64 vector (zero outside the kept
        set). Pure — never touches a lane."""
        cfg = self.cfg
        if cfg.greedy:
            raise ValueError(
                "greedy decoding (temperature 0) has no sampling "
                "distribution — the speculative greedy path compares "
                "argmaxes instead"
            )
        z = np.asarray(logits, np.float64) / cfg.temperature
        order = np.argsort(z)[::-1]
        if cfg.top_k:
            order = order[: cfg.top_k]
        zk = z[order]
        probs = np.exp(zk - zk.max())
        probs /= probs.sum()
        if cfg.top_p < 1:
            keep = int(np.searchsorted(
                np.cumsum(probs), cfg.top_p, side="left"
            )) + 1
            order = order[:keep]
            probs = probs[:keep] / probs[:keep].sum()
        out = np.zeros(np.asarray(logits).shape[-1], np.float64)
        out[order] = probs
        return out

    def uniform(self, slot: int) -> float:
        """One U[0,1) draw from the slot's lane (the accept/reject
        coin)."""
        return float(self._lanes[slot].random())

    def sample_dist(self, dist: np.ndarray, slot: int) -> int:
        """Inverse-CDF draw from a dense distribution on the slot's
        lane (the residual-distribution draw after a rejection, and the
        bonus-token draw after a full accept)."""
        cdf = np.cumsum(np.asarray(dist, np.float64))
        u = self._lanes[slot].random() * cdf[-1]
        idx = int(np.searchsorted(cdf, u, side="right"))
        return int(min(idx, len(cdf) - 1))


__all__ = ["SamplingConfig", "SlotSampler"]
