"""Slot-paged, preallocated KV cache for autoregressive serving.

The cache is one fixed-size pytree allocated ONCE per engine — no
per-request allocation, no shape churn, no recompiles:

    {"k": (layers, slots, max_len, heads, head_dim),
     "v": (layers, slots, max_len, heads, head_dim),
     "lengths": (slots,) int32}

A SLOT is the unit of admission (Orca's iteration-level scheduling,
PAPERS.md): each active request owns one slot for its lifetime, its
per-slot `lengths` counter marks how many positions hold real K/V, and
eviction is a host-side free-list operation (`SlotAllocator`) — the
device buffers are never resized or compacted, a recycled slot is
simply overwritten from position 0 (stale tail positions stay masked
until each decode step overwrites its own position before attending).
This is PagedAttention's insight at page-size = max_len: preallocate,
never fragment the compiled shapes.

Within a slot, axes follow the repo's (B, T, H, Dh) attention
convention (`ops/attention.py`) so the cache feeds
`dot_product_attention` / the SP online-softmax without transposes.

Three mesh layouts, chosen to match the TRAINING engine whose params
are being served (`cache_pspecs`):

  replicated — every device holds the full cache (single-chip or pure
               data-parallel serving).
  tp         — heads sharded over 'model', the Megatron axis: the
               head-sharded q/k/v a column-parallel qkv projection
               produces attend against their local head shard
               (`parallel/tensor_parallel.py` layouts).
  sp         — max_len sharded over 'seq': each shard owns a
               contiguous range of global positions, decode combines
               per-shard partial attention with the same online-softmax
               recurrence `ops/ring_attention.py` uses.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

LAYOUTS = ("replicated", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    """Static shape of the preallocated cache (one per ServingEngine)."""

    num_layers: int
    num_slots: int
    max_len: int
    num_heads: int
    head_dim: int
    dtype: Any = jnp.float32

    def validate(self, layout: str, mesh: Optional[Mesh]) -> None:
        """Fail at construction (not at trace time) when the cache
        cannot be laid out on the mesh."""
        if layout not in LAYOUTS:
            raise ValueError(
                f"layout must be one of {LAYOUTS}, got {layout!r}"
            )
        if layout == "replicated":
            return
        if mesh is None:
            raise ValueError(f"layout {layout!r} needs a mesh")
        if layout == "tp":
            s = mesh.shape["model"]
            if self.num_heads % s:
                raise ValueError(
                    f"tp cache shards heads over 'model': num_heads "
                    f"{self.num_heads} not divisible by {s} shards"
                )
        if layout == "sp":
            s = mesh.shape["seq"]
            if self.max_len % s:
                raise ValueError(
                    f"sp cache shards positions over 'seq': max_len "
                    f"{self.max_len} not divisible by {s} shards"
                )


def cache_pspecs(layout: str) -> dict:
    """PartitionSpec pytree for one cache (see module docstring)."""
    if layout == "tp":
        kv = P(None, None, None, "model", None)
    elif layout == "sp":
        kv = P(None, None, "seq", None, None)
    else:
        kv = P()
    return {"k": kv, "v": kv, "lengths": P()}


def cache_shardings(mesh: Mesh, layout: str) -> dict:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        cache_pspecs(layout),
        is_leaf=lambda x: isinstance(x, P),
    )


def init_cache(spec: KVCacheSpec) -> dict:
    """Zero-filled cache pytree; place with `cache_shardings`."""
    kv_shape = (
        spec.num_layers, spec.num_slots, spec.max_len,
        spec.num_heads, spec.head_dim,
    )
    return {
        "k": jnp.zeros(kv_shape, spec.dtype),
        "v": jnp.zeros(kv_shape, spec.dtype),
        "lengths": jnp.zeros((spec.num_slots,), jnp.int32),
    }


class SlotAllocator:
    """Host-side free-list over the cache's slot axis.

    Admission takes the lowest free slot (deterministic traces),
    eviction returns it; the device-side buffers are untouched — a
    recycled slot's stale K/V beyond the new request's positions stays
    masked by the per-slot length until overwritten."""

    def __init__(self, num_slots: int):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = num_slots
        self._free: List[int] = list(range(num_slots))
        self._live: set = set()

    @property
    def free_slots(self) -> int:
        return len(self._free)

    def alloc(self) -> int:
        if not self._free:
            raise RuntimeError(
                f"all {self.num_slots} cache slots are live; evict "
                "(finish) a sequence before admitting another"
            )
        slot = min(self._free)
        self._free.remove(slot)
        self._live.add(slot)
        return slot

    def free(self, slot: int) -> None:
        if slot not in self._live:
            raise ValueError(f"slot {slot} is not live")
        self._live.remove(slot)
        self._free.append(slot)


__all__ = [
    "KVCacheSpec",
    "LAYOUTS",
    "SlotAllocator",
    "cache_pspecs",
    "cache_shardings",
    "init_cache",
]
