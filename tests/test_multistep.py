"""Direct unit tests for training/multistep.py — previously covered
only indirectly through the trainer/pipeline suites: `group_batches`
trailing-partial-group behavior and `compile_multi_step`'s k=1
passthrough parity with the engine's own step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn
from distributed_model_parallel_tpu.parallel.data_parallel import DDPEngine
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.multistep import (
    compile_multi_eval,
    compile_multi_step,
    group_batches,
)
from distributed_model_parallel_tpu.training.optim import SGD


# ------------------------------------------------------ group_batches


def test_group_batches_full_groups_then_trailing_partial():
    it = iter(range(10))
    assert group_batches(it, 4) == [0, 1, 2, 3]
    assert group_batches(it, 4) == [4, 5, 6, 7]
    # The exhausted iterator yields the SHORT trailing group (the
    # caller's per-step fallback path), then empties.
    assert group_batches(it, 4) == [8, 9]
    assert group_batches(it, 4) == []


def test_group_batches_exact_multiple_has_no_phantom_group():
    it = iter(range(8))
    assert group_batches(it, 4) == [0, 1, 2, 3]
    assert group_batches(it, 4) == [4, 5, 6, 7]
    assert group_batches(it, 4) == []


def test_group_batches_k_larger_than_stream():
    assert group_batches(iter([1, 2]), 5) == [1, 2]


# -------------------------------------------------- compile_multi_step


def _engine_and_batches(n_batches, batch=16):
    mesh = make_mesh(MeshSpec(data=8))
    eng = DDPEngine(tiny_cnn(10), SGD(), mesh, donate=False)
    rng = np.random.RandomState(0)
    batches = []
    for i in range(n_batches):
        x = rng.rand(batch, 8, 8, 3).astype(np.float32)
        y = rng.randint(0, 10, size=(batch,)).astype(np.int32)
        batches.append(eng.shard_batch(x, y))
    return eng, batches


def _tree_allclose(a, b, rtol=1e-5, atol=1e-6):
    for x, y in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    ):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol
        )


def test_compile_multi_step_k1_is_engine_step_passthrough():
    """One-batch dispatch == one engine.train_step call: same params,
    same step counter, same metrics (the trajectory-identity contract,
    at its smallest k)."""
    eng, batches = _engine_and_batches(1)
    ts0 = eng.init_state(jax.random.PRNGKey(0))
    lr = jnp.float32(0.05)

    ts_direct, m_direct = eng.train_step(ts0, *batches[0], lr)

    multi = compile_multi_step(eng, 1)
    ts_multi, m_multi = multi(
        eng.init_state(jax.random.PRNGKey(0)), tuple(batches), lr
    )
    assert int(ts_multi.step) == int(ts_direct.step) == 1
    _tree_allclose(ts_multi.params, ts_direct.params)
    _tree_allclose(m_multi, m_direct, rtol=1e-5, atol=1e-5)


def test_compile_multi_step_k2_matches_two_sequential_steps():
    eng, batches = _engine_and_batches(2)
    lr = jnp.float32(0.05)

    ts = eng.init_state(jax.random.PRNGKey(0))
    summed = None
    for b in batches:
        ts, m = eng.train_step(ts, *b, lr)
        summed = (
            m if summed is None
            else jax.tree_util.tree_map(jnp.add, summed, m)
        )

    multi = compile_multi_step(eng, 2)
    ts_multi, m_multi = multi(
        eng.init_state(jax.random.PRNGKey(0)), tuple(batches), lr
    )
    assert int(ts_multi.step) == 2
    _tree_allclose(ts_multi.params, ts.params)
    _tree_allclose(m_multi, summed, rtol=1e-5, atol=1e-5)


def test_compile_multi_eval_k1_matches_engine_eval():
    eng, batches = _engine_and_batches(1)
    ts = eng.init_state(jax.random.PRNGKey(0))
    m_direct = eng.eval_step(ts, *batches[0])
    m_multi = compile_multi_eval(eng, 1)(ts, tuple(batches))
    _tree_allclose(m_multi, m_direct, rtol=1e-5, atol=1e-5)


def test_compile_multi_step_rejects_k0():
    eng, _ = _engine_and_batches(1)
    with pytest.raises(ValueError, match=">= 1"):
        compile_multi_step(eng, 0)
    with pytest.raises(ValueError, match=">= 1"):
        compile_multi_eval(eng, 0)


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-v"]))
